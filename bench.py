"""Benchmark entry point: prints ONE JSON line with the headline metric.

Round-1 scope: decode throughput of a Llama-3.2-1B-architecture model (random bf16
weights) on one chip — the 8B flagship needs weight quantization to fit a single v5e
chip's 16 GB HBM and moves here once that lands. ``vs_baseline`` is measured against the
north-star target of 2000 decode tok/s/chip (BASELINE.md).
"""

import json
import sys
import time

import numpy as np


def main() -> None:
    import jax

    from neuronx_distributed_inference_tpu.config import (
        TpuConfig, load_pretrained_config)
    from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
        LlamaForCausalLM, LlamaInferenceConfig)
    from neuronx_distributed_inference_tpu.ops.sampling import prepare_sampling_params

    batch, prompt_len, decode_steps = 8, 128, 128
    hf_cfg = {
        "model_type": "llama",
        "vocab_size": 128256,
        "hidden_size": 2048,
        "intermediate_size": 8192,
        "num_hidden_layers": 16,
        "num_attention_heads": 32,
        "num_key_value_heads": 8,
        "head_dim": 64,
        "max_position_embeddings": 131072,
        "rms_norm_eps": 1e-5,
        "rope_theta": 500000.0,
        "rope_scaling": {"rope_type": "llama3", "factor": 32.0, "low_freq_factor": 1.0,
                         "high_freq_factor": 4.0,
                         "original_max_position_embeddings": 8192},
        "tie_word_embeddings": True,
    }
    tpu_cfg = TpuConfig(batch_size=batch, seq_len=512, max_context_length=256,
                        dtype="bfloat16", tp_degree=1,
                        context_encoding_buckets=[128, 256],
                        token_generation_buckets=[256, 512])
    config = LlamaInferenceConfig(tpu_cfg, load_config=load_pretrained_config(hf_cfg))
    app = LlamaForCausalLM(None, config)
    app.load_random(seed=0)

    rng = np.random.default_rng(0)
    input_ids = rng.integers(1, 128256, size=(batch, prompt_len)).astype(np.int32)
    sp = prepare_sampling_params(batch)

    # warm both graphs (compile), then measure
    app.generate(input_ids, max_new_tokens=decode_steps)
    out = app.generate(input_ids, max_new_tokens=decode_steps, collect_latency=True)
    chunk_s = np.array([s for s, _ in out.decode_latencies_s])
    chunk_toks = np.array([t for _, t in out.decode_latencies_s])
    total_decode_s = float(chunk_s.sum())
    n_decode_tokens = int(chunk_toks.sum())
    decode_tok_s = batch * n_decode_tokens / total_decode_s
    p50_step_ms = float(np.percentile(chunk_s / chunk_toks, 50) * 1e3)

    print(json.dumps({
        "metric": "llama3.2-1b-arch decode tokens/sec/chip (bs=8, bf16, tp=1)",
        "value": round(decode_tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(decode_tok_s / 2000.0, 3),
        "extra": {"p50_decode_step_ms": round(p50_step_ms, 2),
                  "ttft_s": round(out.ttft_s, 3)},
    }))


if __name__ == "__main__":
    sys.exit(main())
