"""Benchmark entry point: prints the headline-metric JSON line (re-emitted, with a
progressively richer ``extra``, after each enrichment phase — the driver parses the
last complete line).

Headline: Llama-3.1-8B-architecture decode throughput on ONE chip — int8 weight-only
quantization (the 8B bf16 weights alone exceed a single v5e's HBM) + int8 KV cache
with static per-head scales (measured faster than fp8-direct, and the serving
kernels are MXU-native on int8), measured through the full serving path (bucketed
prefill, chunked greedy decode).
``vs_baseline`` is against the BASELINE.md north star of 2000 decode tok/s/chip.

Structure (the round-3 bench timed out under the driver's budget and lost every
number — VERDICT r3 #1): the headline JSON line is printed and flushed THE MOMENT
the dense measurement finishes; enrichment phases (device-timed decode/TTFT,
bandwidth utilization, paged serving) then run one by one, each gated on the
remaining time budget (``BENCH_TIME_BUDGET_S``, default 1500 s), and the enriched
JSON line is re-printed at the end. A timeout at any point still leaves a complete,
parseable headline on stdout. All progress chatter goes to stderr.

``--small`` runs the 1B-architecture bf16 variant (fast sanity check).

Weights are synthesized DIRECTLY in the quantized int8 layout host-side (a float 8B
intermediate would need ~32 GB of host RAM); random weights measure system throughput
exactly like the reference's random-weight integration benchmarks (SURVEY §4).
"""

import json
import os
import sys
import time

import numpy as np

T0 = time.time()
BUDGET_S = float(os.environ.get("BENCH_TIME_BUDGET_S", "1500"))

# The HBM-bandwidth roofline number (VERDICT r3 #10) now derives from the ONE
# device-spec table in analysis/perf_model.py (DEVICE_SPECS); decode at
# bs<=64 is weight-streaming-bound, so bytes-read/step ÷ device-step-time ÷
# peak-BW is the MFU-analog that matters. On an UNVERIFIED spec (this CPU
# container) the hardware-claim keys publish as ``*_unverified``
# (utils/provenance.py — the r5 honesty pattern, structural since ISSUE-14).


def _remaining() -> float:
    return BUDGET_S - (time.time() - T0)


def _tok_per_s(out, bs: int) -> float:
    """Decode tokens/s from a collect_latency generate output (the shared
    utils/benchmark definition; import deferred — jax config happens in main)."""
    from neuronx_distributed_inference_tpu.utils.benchmark import decode_tok_per_s

    return decode_tok_per_s(out, bs)


def _p_ms(values_s, key: str) -> float:
    """One percentile (ms) of second-valued samples through THE shared
    percentile definition (utils/benchmark.percentiles) — bench keys and
    runner.stats() cannot drift apart."""
    from neuronx_distributed_inference_tpu.utils.benchmark import percentiles

    return percentiles(list(values_s))[key]


def _note(msg: str) -> None:
    print(f"[bench +{time.time() - T0:.0f}s] {msg}", file=sys.stderr, flush=True)


def _random_quantized_llama_params(cfg, seed: int = 0, weight_dtype: str = "int8"):
    """Host quantized param tree for the llama arch described by ``cfg`` (HF
    dict): born int8; for weight_dtype="int4" the big streaming projections are
    repacked to the q4 layout (ops/w4.repack_int8_to_int4 — same path a real
    pre-quantized int8 checkpoint takes)."""
    rng = np.random.default_rng(seed)
    L = cfg["num_hidden_layers"]
    H = cfg["hidden_size"]
    I = cfg["intermediate_size"]
    d = cfg["head_dim"]
    q_size = cfg["num_attention_heads"] * d
    kv_size = cfg["num_key_value_heads"] * d
    V = cfg["vocab_size"]

    def qw(*shape):
        # layer-stacked weights tile ONE random layer across L: decode streams
        # identical bytes regardless of values (this is a perf bench on
        # synthetic weights either way) and synthesis drops from ~20 min to
        # seconds — the r5b full-budget run lost every enrichment phase to
        # param synthesis under CPU contention
        if len(shape) == 3:
            one = rng.integers(-127, 128, size=shape[1:], dtype=np.int8)
            q = np.broadcast_to(one, shape)
        else:
            q = rng.integers(-127, 128, size=shape, dtype=np.int8)
        return {"q": q,
                "s": np.full(shape[:-2] + (1, shape[-1]), 2e-4, dtype=np.float32)}

    import ml_dtypes

    layers = {
        "ln1": np.ones((L, H), dtype=ml_dtypes.bfloat16),
        "wq": qw(L, H, q_size),
        "wk": qw(L, H, kv_size),
        "wv": qw(L, H, kv_size),
        "wo": qw(L, q_size, H),
        "ln2": np.ones((L, H), dtype=ml_dtypes.bfloat16),
        "wg": qw(L, H, I),
        "wu": qw(L, H, I),
        "wd": qw(L, I, H),
    }
    from neuronx_distributed_inference_tpu.ops import rope as rope_ops

    params = {
        "embed": (rng.standard_normal((V, H)) * 0.02).astype(ml_dtypes.bfloat16),
        "layers": layers,
        "final_norm": np.ones((H,), dtype=ml_dtypes.bfloat16),
        "rope_inv_freq": rope_ops.inv_freq_from_hf_config(
            d, cfg["rope_theta"], cfg["rope_scaling"]),
        "lm_head": qw(H, V),
    }
    if weight_dtype == "int4":
        from neuronx_distributed_inference_tpu.ops.quantization import (
            W4_DEFAULT_PARAMS)
        from neuronx_distributed_inference_tpu.ops.w4 import repack_int8_to_int4

        def to4(v):
            # repack ONE layer and re-broadcast: repacking the L-broadcast view
            # would materialize multi-GB float32 temporaries per leaf
            if v["q"].ndim == 3:
                one = repack_int8_to_int4({"q": v["q"][0], "s": v["s"][0]})
                L = v["q"].shape[0]
                return {"q4": np.broadcast_to(one["q4"], (L,) + one["q4"].shape),
                        "s": np.broadcast_to(one["s"], (L,) + one["s"].shape)}
            return repack_int8_to_int4(v)

        params["layers"] = {
            k: (to4(v) if k in W4_DEFAULT_PARAMS else v)
            for k, v in params["layers"].items()}
    return params


def _streamed_bytes_per_decode_step(hf_cfg, quant, batch, avg_ctx) -> int:
    """Bytes read from HBM per decode step: every layer weight + lm_head (streamed
    once per step regardless of batch) + the KV prefix each sequence attends over."""
    L = hf_cfg["num_hidden_layers"]
    H = hf_cfg["hidden_size"]
    I = hf_cfg["intermediate_size"]
    d = hf_cfg["head_dim"]
    q_size = hf_cfg["num_attention_heads"] * d
    kv_size = hf_cfg["num_key_value_heads"] * d
    V = hf_cfg["vocab_size"]
    wq = quant is not None and quant.quantize_weights
    wbytes = 1 if wq else 2
    # int4 halves the big streaming projections (ops/w4.py W4_DEFAULT_PARAMS:
    # wq/wo/wg/wu/wd); wk/wv and lm_head stay int8
    w4bytes = 0.5 if (wq and quant.weight_dtype == "int4") else wbytes
    per_layer = ((H * q_size + q_size * H + 3 * H * I) * w4bytes
                 + 2 * H * kv_size * wbytes)
    lm_head = H * V * wbytes
    kvbytes = 1 if (quant is not None and quant.kv_cache_dtype) else 2
    kv_read = batch * L * 2 * kv_size * int(avg_ctx) * kvbytes
    return L * per_layer + lm_head + kv_read


def _arg_int(name: str, default: int) -> int:
    """Tiny flag parser (the bench predates argparse here and the driver
    invokes it positionally; keep the surface minimal)."""
    if name in sys.argv:
        return int(sys.argv[sys.argv.index(name) + 1])
    return default


def main() -> None:
    small = "--small" in sys.argv
    # ONE tp flag threaded through every phase (headline, paged serving,
    # spec draft): no phase may silently bench a different world size than
    # the headline claims. tp > 1 also turns on the sequence-parallel
    # residual path + overlap-scheduled collective matmuls (parallel/overlap)
    # — the serving configuration the multichip keys describe.
    tp_degree = _arg_int("--tp-degree", 1)

    import jax

    # Persistent compile cache: repeated phases (and repeated bench runs on the
    # same machine) skip recompilation — the r3 timeout was compile-dominated.
    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/tpu_bench_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as e:  # cache is an optimization, never a failure
        _note(f"compile cache unavailable: {e}")

    from neuronx_distributed_inference_tpu.analysis import perf_model
    from neuronx_distributed_inference_tpu.config import (
        QuantizationConfig, TpuConfig, load_pretrained_config)
    from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
        LlamaForCausalLM, LlamaInferenceConfig)
    from neuronx_distributed_inference_tpu.utils import provenance

    # provenance fingerprint ONCE (device probe + git subprocess, cached):
    # stamped into every emitted line so even a timed-out run's surviving
    # headline says what hardware produced it
    fp = provenance.fingerprint()
    dev_spec = perf_model.resolve_device_spec()
    _note(f"provenance: {fp['key']} (verified={fp['verified']}, "
          f"device_kind={fp['device_kind']!r})")

    if small:
        hf_cfg = {
            "model_type": "llama", "vocab_size": 128256, "hidden_size": 2048,
            "intermediate_size": 8192, "num_hidden_layers": 16,
            "num_attention_heads": 32, "num_key_value_heads": 8, "head_dim": 64,
            "max_position_embeddings": 131072, "rms_norm_eps": 1e-5,
            "rope_theta": 500000.0,
            "rope_scaling": {"rope_type": "llama3", "factor": 32.0,
                             "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                             "original_max_position_embeddings": 8192},
            "tie_word_embeddings": True,
        }
        batch, quant = 8, None
        name = (f"llama3.2-1b-arch decode tokens/sec/chip "
                f"(bs=8, bf16, tp={tp_degree})")
    else:
        hf_cfg = {
            "model_type": "llama", "vocab_size": 128256, "hidden_size": 4096,
            "intermediate_size": 14336, "num_hidden_layers": 32,
            "num_attention_heads": 32, "num_key_value_heads": 8, "head_dim": 128,
            "max_position_embeddings": 131072, "rms_norm_eps": 1e-5,
            "rope_theta": 500000.0,
            "rope_scaling": {"rope_type": "llama3", "factor": 8.0,
                             "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                             "original_max_position_embeddings": 8192},
            "tie_word_embeddings": False,
        }
        batch = 128
        # int4 weights (Pallas W4A8 streaming matmul, ops/w4.py — measured
        # r5: 13.48 ms/step vs 18.23 int8 same-session at bs=64) + int8 KV
        # with static per-head scales. bs=128 amortizes the (now-halved)
        # weight stream over 2x the tokens: measured 7433 tok/s sync vs 4656
        # at bs=64 (bs=256 exceeds HBM). The batch-bucket ladder keeps a
        # bs=64 dense measurement on the SAME app so paged_vs_dense stays a
        # same-config ratio (the paged phase serves 64 slots at seq 1024).
        quant = QuantizationConfig.for_kv_dtype(
            "int8", quantize_weights=True, weight_dtype="int4")
        name = ("llama3.1-8b-arch decode tokens/sec/chip "
                f"(bs={batch}, int4 weights, int8 KV, tp={tp_degree})")

    prompt_len, decode_steps = 128, 128
    tpu_cfg = TpuConfig(batch_size=batch, seq_len=512, max_context_length=256,
                        dtype="bfloat16", tp_degree=tp_degree,
                        sequence_parallel_enabled=tp_degree > 1,
                        context_encoding_buckets=[128, 256],
                        token_generation_buckets=[256, 512],
                        batch_buckets=([1, 64, batch] if batch > 64
                                       else [1, batch] if batch > 1 else None),
                        quantization_config=quant)
    config = LlamaInferenceConfig(tpu_cfg, load_config=load_pretrained_config(hf_cfg))
    app = LlamaForCausalLM(None, config)
    _note("loading params")
    if small:
        app.load_random(seed=0)
    else:
        app.load_host_params(_random_quantized_llama_params(
            hf_cfg, seed=0, weight_dtype=quant.weight_dtype))

    rng = np.random.default_rng(0)
    input_ids = rng.integers(1, hf_cfg["vocab_size"],
                             size=(batch, prompt_len)).astype(np.int32)

    # ---- headline: warm both graphs (compile), then measure -------------------
    _note("dense warmup (compiles prefill+decode)")
    app.generate(input_ids, max_new_tokens=decode_steps)
    _note("dense measure")
    out = app.generate(input_ids, max_new_tokens=decode_steps, collect_latency=True)
    chunk_s = np.array([s for s, _ in out.decode_latencies_s])
    chunk_toks = np.array([t for _, t in out.decode_latencies_s])
    total_decode_s = float(chunk_s.sum())
    total_toks = int(chunk_toks.sum()) * batch
    tok_per_s = total_toks / total_decode_s
    per_step_ms = 1000.0 * chunk_s / chunk_toks

    extra = {
        # no real checkpoints exist in this environment: weights are synthetic
        # random in the exact serving layout (the reference's own integration
        # benchmarks use truncated random-weight models, SURVEY §4); real-weight
        # token parity is covered by the HF-CPU parity suite at tiny scale
        "weights": "synthetic-random (env has no real checkpoints)",
        "p50_decode_step_ms": round(_p_ms(per_step_ms / 1000.0,
                                          "latency_ms_p50"), 2),
        "ttft_bulk_bs%d_s" % batch: round(out.ttft_s, 3),
    }
    provenance.apply_to_extra(extra, fp)
    if tp_degree > 1:
        # multichip keys (PR 5): the timed decode above ran ON the tp mesh
        # through the sequence-parallel residual path; the scaling-efficiency
        # phase below adds the tp=1 denominator when the budget allows.
        # HONESTY MARKER: the overlap collective matmuls serve PLAIN dense
        # weights only (parallel/overlap._plain) — the quantized 8B headline's
        # int4/int8 dict payloads keep their fused qapply kernels and GSPMD
        # collective placement, so only the --small (bf16) variant actually
        # rides the ring-overlap path. The key records which one ran.
        from neuronx_distributed_inference_tpu.parallel import overlap as _ov

        extra[f"multichip_tp{tp_degree}_tok_per_s"] = round(tok_per_s, 1)
        extra["tp_overlap_active"] = bool(quant is None
                                          and _ov.overlap_enabled())
        extra["ici_bytes_per_step"] = _ov.estimated_ici_bytes_per_step(
            app.arch_args, tp_degree, batch, dtype_bytes=2)
    result = {
        "metric": name,
        "value": round(tok_per_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tok_per_s / 2000.0, 3),
        "extra": extra,
    }
    # EARLY EMIT: the driver keeps whatever is on stdout at timeout — this line
    # makes the headline survivable no matter what the enrichment phases cost.
    print(json.dumps(result), flush=True)

    if tp_degree > 1 and _remaining() > 420:
        # tp=1 same-config reference for tp_scaling_efficiency: the SAME
        # model/batch/quant on one chip (fresh app — a tp=1 mesh cannot share
        # the sharded weights). Ideal tp scaling on a bandwidth-bound decode
        # is N chips streaming 1/N of the weights each: eff = tokN/(N*tok1).
        _note(f"phase: tp=1 reference for tp_scaling_efficiency")
        try:
            import dataclasses as _dc

            cfg1 = _dc.replace(tpu_cfg, tp_degree=1,
                               sequence_parallel_enabled=False)
            config1 = LlamaInferenceConfig(
                cfg1, load_config=load_pretrained_config(hf_cfg))
            app1 = LlamaForCausalLM(None, config1)
            if small:
                app1.load_random(seed=0)
            else:
                app1.load_host_params(_random_quantized_llama_params(
                    hf_cfg, seed=0, weight_dtype=quant.weight_dtype))
            app1.generate(input_ids, max_new_tokens=decode_steps)   # warm
            out1 = app1.generate(input_ids, max_new_tokens=decode_steps,
                                 collect_latency=True)
            tok1 = _tok_per_s(out1, batch)
            extra["tp1_tok_per_s"] = round(tok1, 1)
            extra["tp_scaling_efficiency"] = round(
                tok_per_s / (tp_degree * tok1), 3) if tok1 else None
            app1.params = None
            app1.kv_cache = None
            del app1
            import gc

            gc.collect()
        except Exception as e:
            _note(f"tp=1 reference failed: {e}")
        print(json.dumps(result), flush=True)

    if _remaining() > 90:
        # async dispatch-ahead (VERDICT r3 #4): chunk N+1 is dispatched from
        # chunk N's device-resident last token before N is synced — the SAME
        # decode executable, so enabling it on the warm app compiles nothing.
        # The headline takes the better mode; both numbers are reported.
        _note("phase: async dispatch-ahead probe")
        try:
            app.tpu_config.async_mode = True
            out_a = app.generate(input_ids, max_new_tokens=decode_steps,
                                 collect_latency=True)
            async_tok_per_s = _tok_per_s(out_a, batch)
            extra["sync_tok_per_s"] = round(tok_per_s, 1)
            extra["async_tok_per_s"] = round(async_tok_per_s, 1)
            if async_tok_per_s > tok_per_s:
                result["value"] = round(async_tok_per_s, 1)
                result["vs_baseline"] = round(async_tok_per_s / 2000.0, 3)
            else:                      # keep serving in the faster mode
                app.tpu_config.async_mode = False
        except Exception as e:
            _note(f"async probe failed: {e}")
            app.tpu_config.async_mode = False
        print(json.dumps(result), flush=True)

    if not small and batch > 64 and _remaining() > 90:
        # bs=64 dense on the SAME app (batch bucket 64): the paged serving
        # phase runs 64 slots, so this is the same-config denominator for
        # paged_vs_dense — and an apples-to-apples point against the r5
        # bs=64 headline
        _note("phase: dense bs=64 (batch bucket)")
        was_async = app.tpu_config.async_mode
        try:
            ids64 = input_ids[:64]
            b64 = ids64.shape[0]
            app.tpu_config.async_mode = False
            app.generate(ids64, max_new_tokens=decode_steps)     # warm bucket
            o64 = app.generate(ids64, max_new_tokens=decode_steps,
                               collect_latency=True)
            extra["dense_bs64_sync_tok_per_s"] = round(_tok_per_s(o64, b64), 1)
            app.tpu_config.async_mode = True
            o64a = app.generate(ids64, max_new_tokens=decode_steps,
                                collect_latency=True)
            extra["dense_bs64_async_tok_per_s"] = round(_tok_per_s(o64a, b64), 1)
        except Exception as e:
            _note(f"bs=64 phase failed: {e}")
        finally:
            # later phases must run in the mode the headline probe chose
            app.tpu_config.async_mode = was_async
        print(json.dumps(result), flush=True)

    # ---- enrichment phases, each budget-gated ---------------------------------
    import jax.numpy as jnp

    from neuronx_distributed_inference_tpu.utils import profiling as prof

    import shutil

    decode_step_device_ms = None
    if _remaining() > 120:
        _note("phase: device-timed decode step")
        try:
            dec_steps = 64
            dec_trace = "/tmp/bench_decode_trace"
            shutil.rmtree(dec_trace, ignore_errors=True)
            app.generate(input_ids, max_new_tokens=1)  # fresh prefill outside trace
            with prof.trace(dec_trace):
                app.generate(input_ids, max_new_tokens=dec_steps)
            ddev = prof.device_time_ms(dec_trace, "decode")
            if ddev is not None:
                decode_step_device_ms = round(ddev / dec_steps, 2)
            extra["decode_step_device_ms"] = decode_step_device_ms
            # prefill MFU (VERDICT r4 #10): matmul+attention flops of the bulk
            # bs prefill vs device time, against the 197 TFLOPs bf16 peak
            pdev = prof.device_time_ms(dec_trace, "prefill")
            if pdev:
                L = hf_cfg["num_hidden_layers"]
                H = hf_cfg["hidden_size"]
                I = hf_cfg["intermediate_size"]
                d = hf_cfg["head_dim"]
                q_size = hf_cfg["num_attention_heads"] * d
                kv_size = hf_cfg["num_key_value_heads"] * d
                per_layer = (H * q_size + 2 * H * kv_size + q_size * H
                             + 3 * H * I)
                flops = (2 * batch * prompt_len * L * per_layer
                         + 2 * batch * H * hf_cfg["vocab_size"]      # last tok
                         + 2 * batch * hf_cfg["num_attention_heads"]
                         * prompt_len * prompt_len * d)              # causal QK+PV
                extra["prefill_device_ms"] = round(pdev, 2)
                # MFU vs the resolved spec's bf16 peak; the v5e reference
                # peak is only a placeholder denominator on unverified
                # hardware, where the key name itself says so
                extra[provenance.claim_key("prefill_mfu_bf16", fp)] = round(
                    flops / (pdev * 1e-3) / (dev_spec.peak_flops or 197e12),
                    3)
        except Exception as e:
            _note(f"decode trace failed: {e}")
        print(json.dumps(result), flush=True)

    # Bandwidth utilization (roofline): free arithmetic once we have a device
    # time; falls back to wall p50 when the trace phase was skipped. The peak
    # comes from the resolved device spec (analysis/perf_model.DEVICE_SPECS);
    # an unverified spec (CPU container) keeps the v5e reference denominator
    # but the key publishes as *_unverified — the number stays visible, the
    # hardware claim does not.
    step_ms = decode_step_device_ms or extra["p50_decode_step_ms"]
    bytes_step = _streamed_bytes_per_decode_step(
        hf_cfg, quant, batch, prompt_len + decode_steps / 2)
    util = perf_model.hbm_utilization(bytes_step, step_ms, dev_spec)
    if util is None:
        util = bytes_step / (step_ms * 1e-3) / 819e9
    extra[provenance.claim_key("hbm_bw_utilization", fp)] = round(util, 3)
    # int4 keeps decode HBM-bound but the ratio is vs the REDUCED bytes
    extra["streamed_bytes_per_step_gb"] = round(bytes_step / 1e9, 2)
    print(json.dumps(result), flush=True)

    if _remaining() > 150:
        # serving TTFT: a single request prefilled at batch bucket 1 (first-class
        # metric, ≈ reference TTFT reporting `utils/benchmark.py:479-494`); the
        # bulk ttft above amortizes a full batch-64 prefill and is NOT
        # time-to-first-token for one user. Three numbers, so the wall figure is
        # attributable:
        #  - ttft_p50_ms        : wall time of the bs=1 prefill dispatch (what a
        #                         client sees THROUGH THIS ENVIRONMENT'S TUNNEL)
        #  - dispatch_floor_noop_ms : p50 wall time of a no-op jitted dispatch —
        #                         the tunnel's irreducible blocking round trip
        #                         (the MEASURED serving-path floor now lives in
        #                         the bs=1 megastep phase's dispatch_floor_ms:
        #                         host wall per decode dispatch minus attributed
        #                         device time, ISSUE-10)
        #  - ttft_device_ms     : event-timed on-device duration of the same bs=1
        #                         prefill (the number BASELINE.md's <50 ms north
        #                         star bounds)
        _note("phase: single-request TTFT")
        try:
            single = input_ids[:1]
            f_noop = jax.jit(lambda x: x + 1)
            xs = jnp.zeros((8, 128), jnp.float32)
            np.asarray(f_noop(xs))
            floor = []
            for i in range(10):
                # vary the input and FETCH the result: the tunnel client
                # elides repeated identical unfetched executions (a r5b run
                # reported floor 0.0 from block_until_ready on elided calls)
                t0 = time.perf_counter()
                np.asarray(f_noop(xs + i))
                floor.append(time.perf_counter() - t0)
            extra["dispatch_floor_noop_ms"] = round(
                _p_ms(floor, "latency_ms_p50"), 1)

            ttfts = []
            for i in range(8):
                o1 = app.generate(single, max_new_tokens=1)
                if i:  # first call pays the bs=1-bucket compilation
                    ttfts.append(o1.ttft_s)
            extra["ttft_p50_ms"] = round(_p_ms(ttfts, "latency_ms_p50"), 1)

            trace_dir = "/tmp/bench_ttft_trace"
            shutil.rmtree(trace_dir, ignore_errors=True)
            with prof.trace(trace_dir):
                app.generate(single, max_new_tokens=1)
            dev = prof.device_time_ms(trace_dir, "prefill")
            extra["ttft_device_ms"] = round(dev, 2) if dev is not None else None
        except Exception as e:
            _note(f"ttft phase failed: {e}")
        print(json.dumps(result), flush=True)

    if _remaining() > 120:
        # ISSUE-10 bs=1 closed-loop decode latency: the device-resident
        # megastep (ONE lax.while_loop dispatch per K tokens) vs the
        # step-wise path at decode_chunk=1 (one dispatch per token), plus the
        # MEASURED dispatch floor — host wall per decode dispatch minus
        # PR 7-attributed device time — on a dispatch-floor probe model.
        _note("phase: bs=1 closed-loop decode latency (megastep vs step-wise)")
        try:
            extra.update(_bs1_megastep_decode())
        except Exception as e:
            _note(f"bs=1 megastep phase failed: {e}")
        print(json.dumps(result), flush=True)

    if _remaining() > 120:
        # ISSUE-19 kernel-floor legs: the in-path KV-length split on a
        # long-context bs=1 probe (lenpar_stats engagement witness), and the
        # spec/mixed megastep speedups vs their step-wise twins — each key
        # refused with an *_invalid marker if its leg never actually served.
        _note("phase: kernel-floor bs=1 (lenpar split, spec/mixed megastep)")
        try:
            extra.update(_kernel_floor_bs1())
        except Exception as e:
            _note(f"kernel-floor phase failed: {e}")
        print(json.dumps(result), flush=True)

    if _remaining() > 150:
        # ISSUE-16 MoE serving: a Mixtral-arch probe through the paged CB
        # runner — fused grouped decode kernel vs the dense all-experts
        # fallback on the same geometry, with the trace-stat honesty gate
        # (moe_invalid if the dense path silently served the measured leg).
        _note("phase: MoE paged decode (grouped kernel vs dense fallback)")
        try:
            extra.update(_moe_paged_decode(_arg_int("--ep-degree", 1)))
        except Exception as e:
            _note(f"MoE phase failed: {e}")
        print(json.dumps(result), flush=True)

    if not small and _remaining() > 360:
        _note("phase: paged continuous-batching serving (same config as headline)")
        # free the dense app's device buffers first: the paged serving app loads
        # its own 8 GB of int8 weights, and two copies exceed one chip's HBM
        app.params = None
        app.kv_cache = None
        del app
        import gc

        gc.collect()
        paged_app = None
        try:
            paged_sync, paged_async, paged_depth, paged_app, tel_extra = \
                _paged_serving_throughput(hf_cfg, min(batch, 64), tp_degree)
            extra["paged_sync_tok_per_s"] = paged_sync
            extra["paged_async_tok_per_s"] = paged_async
            extra["paged_async_depth"] = paged_depth
            # ISSUE-7: enabled+carry telemetry cost (1.0 = free) + the
            # profiled host-vs-device decomposition of the dispatch floor
            extra.update(tel_extra)
            pq = paged_app.tpu_config.quantization_config
            extra["paged_kv_dtype"] = f"{pq.kv_cache_dtype}-{pq.kv_cache_scale_mode}"
            paged = max(paged_sync, paged_async)
            extra["paged_serving_tok_per_s"] = paged
            # same-config ratio: best paged mode (64 slots) vs the bs=64 dense
            # measurement on the same weights — NEVER the bs=128 headline (a
            # denominator switch would masquerade as a paged regression)
            dense64 = max(extra.get("dense_bs64_async_tok_per_s", 0),
                          extra.get("dense_bs64_sync_tok_per_s", 0))
            if dense64:
                extra["paged_vs_dense"] = round(paged / dense64, 3)
            extra["paged_vs_headline"] = round(paged / result["value"], 3)
        except Exception as e:
            _note(f"paged phase failed: {e}")
        print(json.dumps(result), flush=True)

        if paged_app is not None and _remaining() > 240:
            # fused speculation THROUGH the paged serving path (VERDICT r4 #1/#10).
            # Random weights make greedy acceptance ~chance, so two honest
            # numbers: the measured FLOOR (overhead-only, ~1 token/iteration)
            # and the measured-iteration-time CEILING (all K tokens commit —
            # the fused iteration's cost does not depend on acceptance). Real
            # checkpoints land between the two by their acceptance rate.
            _note("phase: speculative decoding through paged serving")
            try:
                spec = _paged_spec_throughput(
                    paged_app, hf_cfg,
                    paged_app.tpu_config.max_batch_size)
                extra.update(spec)
                paged = extra.get("paged_serving_tok_per_s")
                if paged:
                    extra["paged_spec_ceiling_vs_paged"] = round(
                        spec["paged_spec_full_accept_tok_per_s"] / paged, 3)
                    if "paged_spec_floor_tok_per_s" in spec:
                        extra["paged_spec_floor_vs_paged"] = round(
                            spec["paged_spec_floor_tok_per_s"] / paged, 3)
            except Exception as e:
                _note(f"spec serving phase failed: {e}")
            print(json.dumps(result), flush=True)

        if paged_app is not None and _remaining() > 180:
            # self-draft variant (VERDICT r5 #5): draft = target drives the
            # REAL accept/commit/rollback path at (near-)full acceptance —
            # the ceiling stops being arithmetic and becomes a measurement
            _note("phase: self-draft speculative serving (accept-path check)")
            try:
                extra.update(_paged_spec_selfdraft(
                    paged_app, paged_app.tpu_config.max_batch_size))
            except Exception as e:
                _note(f"self-draft spec phase failed: {e}")
            print(json.dumps(result), flush=True)

        if paged_app is not None and _remaining() > 300:
            # open-loop Poisson-arrival serving (the mixed-step PR's headline
            # phase): requests ARRIVE while residents decode, so prefill
            # interference is measured instead of hidden by closed-loop
            # steady state. Two schedulers on the same app: the insert-window
            # baseline (capped bs=1 windows between decode chunks) vs the
            # MIXED token-budget scheduler (decode rows + prefill chunks in
            # one dispatch). prefill_interference_ratio = mixed / baseline
            # serving tok/s under the same arrival trace.
            _note("phase: open-loop arrival serving (mixed-step vs "
                  "insert-window)")
            try:
                extra.update(_paged_arrival_serving(
                    paged_app, paged_app.tpu_config.max_batch_size,
                    extra.get("paged_serving_tok_per_s")))
                base_t = extra.get("arrival_insert_window_tok_per_s")
                mixed_t = extra.get("arrival_paged_serving_tok_per_s")
                if base_t and mixed_t:
                    extra["prefill_interference_ratio"] = round(
                        mixed_t / base_t, 3)
            except Exception as e:
                _note(f"arrival phase failed: {e}")

        if paged_app is not None and _remaining() > 240:
            # ISSUE-9 scale-out phase: the engine/frontend split under an
            # open-loop arrival trace — a prefix-affinity router over 2
            # replicas (independent runners, shared weights) vs the SAME
            # trace under random placement, plus a host-RAM KV tier leg.
            # Affinity numbers refuse to publish if the prefix cache was off
            # for the run (same honesty pattern as the r5 spec-floor marker).
            _note("phase: multi-replica router serving (affinity vs random "
                  "placement, KV host tier)")
            try:
                extra.update(_router_arrival_serving(
                    paged_app, paged_app.tpu_config.max_batch_size,
                    extra.get("paged_serving_tok_per_s")))
            except Exception as e:
                _note(f"router phase failed: {e}")

        if paged_app is not None and _remaining() > 200:
            # ISSUE-11 fault-schedule phase: the router trace re-run under
            # injected hard replica death + host-tier corruption, against a
            # fault-free control of the SAME trace. Publishes goodput under
            # faults, recovery latency, zero-loss, and a bit-exactness
            # marker; REFUSES (faults_invalid) if no fault actually fired.
            _note("phase: fault-schedule serving (injected replica death + "
                  "corruption vs fault-free control)")
            try:
                extra.update(_router_fault_serving(
                    paged_app, paged_app.tpu_config.max_batch_size,
                    extra.get("paged_serving_tok_per_s")))
            except Exception as e:
                _note(f"fault phase failed: {e}")

        if paged_app is not None and _remaining() > 200:
            # ISSUE-13 multi-tenant overload phase: a bursty bulk tenant +
            # steady interactive tenant on the SAME trace, served by the SLA
            # control plane (weighted-fair budgets, priority preemption,
            # brown-out shed) vs a FIFO control. Publishes per-class
            # TTFT/TPOT percentiles, goodput under overload, shed-by-class,
            # and a preempt-resume bit-exactness marker; REFUSES
            # (multitenant_invalid) if no shed/preemption actually fired.
            _note("phase: multi-tenant overload serving (SLA classes vs "
                  "FIFO control)")
            try:
                extra.update(_multitenant_serving(
                    paged_app, paged_app.tpu_config.max_batch_size,
                    extra.get("paged_serving_tok_per_s")))
            except Exception as e:
                _note(f"multitenant phase failed: {e}")

        if paged_app is not None and _remaining() > 120:
            # ISSUE-15 memory-pressure phase: forced KV churn (spill /
            # readmit / preempt-resume) through the block-ledgered tiered
            # runner; publishes fragmentation, idle-age p50, host-tier
            # watermark, and the leak counter (MUST be 0 under the
            # conservation audit); REFUSES (memledger_invalid) if no churn
            # actually occurred.
            _note("phase: KV memory pressure (block-ledger churn + "
                  "conservation audit)")
            try:
                extra.update(_memledger_pressure(
                    paged_app, paged_app.tpu_config.max_batch_size))
            except Exception as e:
                _note(f"memledger phase failed: {e}")

        if paged_app is not None and _remaining() > 180:
            # ISSUE-17 disaggregated-pools phase: the open-loop interference
            # trace on a 1-prefill + 1-decode pooled fleet (remote_prefill +
            # live KV handoff) vs a 2-replica unified control. Publishes the
            # per-leg prefill-interference ratios, TTFT p99, handoff
            # latency/bytes/overlap; REFUSES (pools_invalid) if no handoff
            # fired or any stream diverged from the control.
            _note("phase: disaggregated prefill/decode pools (live KV "
                  "handoff vs unified control)")
            try:
                extra.update(_pooled_serving(
                    paged_app, paged_app.tpu_config.max_batch_size,
                    extra.get("paged_serving_tok_per_s")))
            except Exception as e:
                _note(f"pooled phase failed: {e}")

        if paged_app is not None and _remaining() > 150:
            # ISSUE-18 self-tuning phase: the COMMITTED multi-phase arrival
            # trace replayed tuned-vs-static through the deterministic
            # what-if replayer on a real probe fleet; the online controller
            # walks retrace-free knobs (megastep_k, async_depth) off real
            # fleet signals with every decision stamped into the journal /
            # timeline. Publishes tuned_vs_static_ratio; REFUSES
            # (tuner_invalid) if the controller never decided, never beat
            # static, broke bit-exactness, or failed reconciliation.
            _note("phase: self-tuning serving (deterministic replay, "
                  "tuned vs static)")
            try:
                extra.update(_selftuning_serving(
                    paged_app, paged_app.tpu_config.max_batch_size))
            except Exception as e:
                _note(f"selftuning phase failed: {e}")

        if paged_app is not None and _remaining() > 150:
            # ISSUE-20 fleet-wide content-addressed KV store phase: shared-
            # prefix Poisson trace on a COLD replica, cluster-store leg
            # (cross-replica pulls through the fleet rung) vs local-tier-only
            # control (re-prefill). Publishes cluster_kv_hit_ratio,
            # cluster_dedup_ratio (< 1.0 = bytes scale with unique content),
            # cluster_readmit_tok_per_s; REFUSES (cluster_kv_invalid) if no
            # cross-replica hit fired or any stream diverged.
            _note("phase: fleet content-addressed KV store (cluster pulls "
                  "vs local re-prefill)")
            try:
                extra.update(_cluster_kv_serving(
                    paged_app, paged_app.tpu_config.max_batch_size,
                    extra.get("paged_serving_tok_per_s")))
            except Exception as e:
                _note(f"cluster KV phase failed: {e}")

    # FINAL EMIT: same schema, enriched extra. The driver parses the last JSON
    # line; if the process was killed earlier, the early emit already landed.
    # apply_to_extra is the structural refusal net (idempotent): any
    # hardware-claim key a phase wrote under its verified name is renamed
    # *_unverified here when the spec is unverified, and the provenance
    # block rides in every snapshot.
    provenance.apply_to_extra(extra, fp)
    print(json.dumps(result), flush=True)


def _paged_serving_throughput(hf_cfg, batch, tp_degree=1):
    """Steady-state decode throughput of the PAGED continuous-batching serving
    path with the Pallas ragged kernels, at the SAME config as the dense
    headline — int8-static KV end-to-end since r5 (VERDICT r3 #2: the serving
    path must carry the headline; paged_vs_dense is a true same-config ratio).
    Returns (sync_tok_per_s, async_tok_per_s, async_depth, app) — async
    dispatch-ahead (depth-N pipeline, on-device stop tracking) reuses the same
    executables, so the second measurement costs only its runtime; the app
    (weights) is returned for the spec phase."""
    import time as _time

    from neuronx_distributed_inference_tpu.config import (
        TpuConfig, load_pretrained_config)
    from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
        LlamaForCausalLM, LlamaInferenceConfig)
    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)

    from neuronx_distributed_inference_tpu.config import QuantizationConfig

    # int8-static KV (same as the dense headline): the ragged Pallas kernels
    # run MXU-native int8 dots — measured r5: 182 us/layer attend vs 405 for
    # fp8 (whose in-kernel cast is VPU-bound). Accuracy is pinned by
    # tests/test_quantization.py::test_int8_kv_static_scales_close_and_paths_agree.
    pquant = QuantizationConfig.for_kv_dtype(
        "int8", quantize_weights=True, weight_dtype="int4")
    bs, seq, block = batch, 1024, 128
    cfg = TpuConfig(batch_size=bs, seq_len=seq, max_context_length=256,
                    dtype="bfloat16", tp_degree=tp_degree,
                    sequence_parallel_enabled=tp_degree > 1,
                    context_encoding_buckets=[256],
                    token_generation_buckets=[seq],
                    is_continuous_batching=True, paged_attention_enabled=True,
                    pa_num_blocks=bs * (seq // block) + 8, pa_block_size=block,
                    quantization_config=pquant)
    config = LlamaInferenceConfig(cfg, load_config=load_pretrained_config(hf_cfg))
    app = LlamaForCausalLM(None, config)
    app.load_host_params(_random_quantized_llama_params(
        hf_cfg, seed=0, weight_dtype=pquant.weight_dtype))
    rng = np.random.default_rng(0)
    # NO in-bench calibration: calibrate_kv_scales builds a transient DENSE
    # cache (~4.3 GB at this geometry) on top of weights + the paged pool and
    # OOMed the chip. sigma=1 scales are PERF-identical (same ops, same
    # bytes); int8 accuracy with calibrated scales is pinned on CPU by
    # tests/test_quantization.py::test_int8_kv_static_scales_close_and_paths_agree.
    #
    # decode_chunk 48 (was 32): the serving chunk amortizes the measured
    # ~109 ms dispatch floor over more iterations (~2.3 ms/step vs ~3.4) —
    # the r5 paged_vs_dense 0.694 sat right under the 0.70 bar and the sync
    # path's gap was dispatch-share. Prompt/max_new shift (100/920) keeps
    # every row alive through all measured chunks at the longer stride.
    runner = ContinuousBatchingRunner(app, decode_chunk=48)
    for _ in range(bs):
        runner.submit(rng.integers(1, 100000, size=(100,)).astype(np.int32),
                      max_new_tokens=920)
    for _ in range(3):                        # place + warm the compiled chunks
        runner.step()

    def measure(n_chunks=6):
        # count EMITTED tokens (not bs * chunk): rows that stop early would
        # otherwise be billed for tokens that were never produced. Async lag
        # washes out: the 2 fill steps prime the pipeline, so measured step 1
        # commits the fill window's chunk and the chunk left in flight at the
        # end is excluded — one in, one out, 6 chunks counted over 6 dispatched
        t0 = _time.time()
        n = 0
        for _ in range(n_chunks):
            n += sum(len(v) for v in runner.step().values())
        return round(n / (_time.time() - t0), 1)

    sync = measure()
    runner.async_mode = True
    for _ in range(1 + runner.async_depth):
        # fill steps: prime the depth-N pipeline (async_depth chunks in
        # flight) plus one to compile the device-resident-carry executable
        # variant (one-time)
        runner.step()
    async_ = measure()
    runner.async_mode = False
    # ISSUE-7 observability window on the same warm executables: the
    # enabled+carry telemetry overhead ratio and the profiled host/device
    # dispatch-gap decomposition. Never allowed to sink the headline.
    tel_extra = {}
    if _remaining() > 120:
        try:
            tel_extra = _telemetry_overhead_and_gap(runner, rng, bs)
        except Exception as e:
            _note(f"telemetry overhead/gap window failed: {e}")
    # release the runner's 4.4 GB block pools so the follow-on spec phase can
    # build its own (target + draft) without OOMing the chip; the APP (weights)
    # is returned for reuse — a second 8 GB host->device load costs ~7 min
    depth = runner.async_depth
    runner.cache = None
    del runner
    import gc

    gc.collect()
    return sync, async_, depth, app, tel_extra


def _telemetry_overhead_and_gap(runner, rng, bs, n_chunks=3, prompt_len=100,
                                max_new=480, tok_high=100000,
                                logdir="/tmp/tpu_bench_profile_serving",
                                plane="tpu"):
    """ISSUE-7 observability window on an ALREADY-WARM runner (no fresh
    compiles): (a) ``telemetry_overhead_ratio`` — steady-state decode tok/s
    with telemetry ENABLED (host hooks + the in-graph device-carry drain at
    each pipeline flush) over the same window with ``enabled=False`` (1.0 =
    telemetry is free; the carry's in-graph adds ride in BOTH numbers since
    they are threaded unconditionally); (b) ``dispatch_gap_ms`` — a short
    jax.profiler-traced window attributed per dispatch kind
    (runner.attribute_device_time): host step span minus on-device time per
    decode dispatch, the host share of the ~109 ms dispatch floor ROADMAP
    open item 2 targets. Returns bench ``extra`` keys; device attribution
    keys are None when the backend's xplane carries no matching events."""
    import shutil
    import time as _time

    from neuronx_distributed_inference_tpu.utils import profiling as prof

    runner.run_to_completion()            # drain the headline rows first
    tel = runner.telemetry
    tel.enabled = True
    tel.reset()
    runner.reset_device_telemetry()
    for _ in range(bs):
        runner.submit(rng.integers(1, tok_high,
                                   size=(prompt_len,)).astype(np.int32),
                      max_new_tokens=max_new)
    runner.step()                         # place + seed every row (warm graphs)

    def window(chunks):
        t0 = _time.time()
        n = 0
        for _ in range(chunks):
            n += sum(len(v) for v in runner.step().values())
        return n / (_time.time() - t0)

    # adjacent same-kind windows: every row stays alive through both (the
    # max_new budget covers all chunks below), so off-vs-on is apples-to-apples
    tel.enabled = False
    off = window(n_chunks)
    tel.enabled = True
    on = window(n_chunks)
    out = {"telemetry_overhead_ratio": round(on / off, 3)}

    # traced gap window: host spans of the MEASURED window only
    tel.reset()
    runner.reset_device_telemetry()
    shutil.rmtree(logdir, ignore_errors=True)
    with prof.trace(logdir):
        window(2)
    timing = runner.attribute_device_time(logdir, plane_substr=plane)
    dec = timing.get("decode", {})
    out["dispatch_gap_ms"] = dec.get("dispatch_gap_ms")
    out["decode_device_ms_per_dispatch"] = dec.get("device_ms_per_dispatch")
    # ISSUE-14 measured-vs-model join: per-kind roofline efficiency over the
    # SAME profiled window (attribute_device_time attached it). For a
    # memory-bound kind the efficiency IS its hbm_bw_utilization — derived
    # from the model per kind, not hand-derived once; the per-kind key uses
    # the provenance claim-key naming (``*_unverified`` off TPU).
    from neuronx_distributed_inference_tpu.utils import provenance

    roof = runner.telemetry.roofline or {}
    for kind, e in sorted((roof.get("by_kind") or {}).items()):
        if e.get("efficiency") is None:
            continue
        out[f"roofline_{kind}_efficiency"] = round(e["efficiency"], 4)
        out[f"roofline_{kind}_bound"] = e["bound"]
        if e["bound"] == "memory":
            out[provenance.claim_key(f"{kind}_hbm_bw_utilization")] = \
                round(e["efficiency"], 4)
    if roof.get("error"):
        out["roofline_error"] = roof["error"]
    tel.enabled = False
    return out


def _bs1_megastep_decode(k=16, warm_steps=6, measure_toks=64,
                         trace_steps=24,
                         logdir="/tmp/tpu_bench_bs1_trace"):
    """ISSUE-10 bs=1 closed-loop decode latency: ONE live request served

    (a) STEP-WISE at decode_chunk=1 — one jitted dispatch + one host sync per
        token, the regime where the ~109 ms dispatch floor IS the latency;
    (b) through the device-resident MEGASTEP — one ``lax.while_loop``
        dispatch + one sync per K tokens.

    Emits ``bs1_decode_tok_per_s`` (megastep), ``bs1_stepwise_tok_per_s``,
    ``megastep_speedup_vs_stepwise`` (the floor-amortization factor — ~K×
    when the floor dominates device time), and ``dispatch_floor_ms``:
    MEASURED, not folklore — the step-wise window is jax.profiler-traced and
    PR 7's ``runner.attribute_device_time`` subtracts attributed device time
    from the host span per decode dispatch (the old no-op probe survives as
    ``dispatch_floor_noop_ms``).

    Runs on a dedicated DISPATCH-FLOOR PROBE model (tiny llama, recorded in
    ``bs1_probe_arch``): the floor is a property of the dispatch path, not
    the model, and isolating it keeps the phase honest AND cheap on every
    backend — at 8B scale a CPU container's compute would swamp the floor
    and measure nothing. HONESTY GUARD (r5 spec-floor pattern): if the
    megastep runner silently served step-wise scan chunks instead of
    megasteps, the keys are REFUSED and ``megastep_invalid`` is emitted.
    """
    import shutil
    import time as _time

    import jax

    from neuronx_distributed_inference_tpu.config import (
        TpuConfig, load_pretrained_config)
    from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
        LlamaForCausalLM, LlamaInferenceConfig)
    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)
    from neuronx_distributed_inference_tpu.utils import profiling as prof

    probe_hf = {
        "model_type": "llama", "vocab_size": 256, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2,
        "max_position_embeddings": 1024, "rms_norm_eps": 1e-5,
        "rope_theta": 10000.0, "tie_word_embeddings": False,
    }
    seq, block = 512, 16
    cfg = TpuConfig(batch_size=2, seq_len=seq, max_context_length=64,
                    dtype="float32", context_encoding_buckets=[64],
                    token_generation_buckets=[seq],
                    is_continuous_batching=True, paged_attention_enabled=True,
                    pa_num_blocks=2 * (seq // block) + 8, pa_block_size=block)
    config = LlamaInferenceConfig(cfg, load_config=load_pretrained_config(probe_hf))
    app = LlamaForCausalLM(None, config)
    app.load_random(seed=0)
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, 250, size=(32,)).astype(np.int32)
    plane = "" if jax.devices()[0].platform == "cpu" else "tpu"

    def serve_window(runner, n_toks):
        t0 = _time.perf_counter()
        n = 0
        while n < n_toks and runner.has_work:
            n += sum(len(v) for v in runner.step().values())
        return n / (_time.perf_counter() - t0)

    # ---- step-wise: one dispatch (and one sync) per token -----------------
    stepwise = ContinuousBatchingRunner(app, decode_chunk=1, telemetry=True)
    stepwise.submit(prompt, max_new_tokens=seq - len(prompt) - 8)
    for _ in range(1 + warm_steps):           # place + warm the executables
        stepwise.step()
    stepwise.telemetry.reset()
    stepwise.reset_device_telemetry()
    step_tok_s = serve_window(stepwise, measure_toks)
    # traced window -> PR 7 attribution: the measured host-vs-device floor
    stepwise.telemetry.reset()
    stepwise.reset_device_telemetry()
    shutil.rmtree(logdir, ignore_errors=True)
    with prof.trace(logdir):
        serve_window(stepwise, trace_steps)
    timing = stepwise.attribute_device_time(logdir, plane_substr=plane)
    dec = timing.get("decode", {})
    out = {
        "bs1_stepwise_tok_per_s": round(step_tok_s, 1),
        "dispatch_floor_ms": dec.get("dispatch_gap_ms"),
        "bs1_decode_device_ms": dec.get("device_ms_per_dispatch"),
        "megastep_k": k,
        "bs1_probe_arch": "llama 2L/64H probe (floor isolation; the "
                          "dispatch floor is model-independent)",
    }
    stepwise.cache = None
    del stepwise

    # ---- megastep: one while_loop dispatch + one sync per K tokens --------
    runner = ContinuousBatchingRunner(app, decode_chunk=1, megastep_k=k,
                                      telemetry=True)
    runner.submit(prompt, max_new_tokens=seq - len(prompt) - 8)
    for _ in range(3):                        # place + compile the megastep
        runner.step()
    runner.telemetry.reset()
    runner.reset_device_telemetry()
    mega_tok_s = serve_window(runner, measure_toks)
    s = runner.stats()
    served = s["device"]["steps"] if s.get("device") else {}
    if not served.get("megastep"):
        # the loop silently fell back to step-wise scan chunks: refuse the
        # keys (r5 spec-floor honesty pattern — an invalid marker, never a
        # plausible-looking number)
        out["megastep_invalid"] = (
            f"no megastep dispatches in the measured window (served kinds: "
            f"{served or 'unknown'})")
        _note(f"bs=1 megastep INVALID: {out['megastep_invalid']}")
    else:
        out["bs1_decode_tok_per_s"] = round(mega_tok_s, 1)
        out["megastep_speedup_vs_stepwise"] = round(
            mega_tok_s / step_tok_s, 3) if step_tok_s else None
        out["bs1_megastep_exits"] = dict(s["megastep"]["exits"])
    runner.cache = None
    del runner
    import gc

    gc.collect()
    return out


def _kernel_floor_bs1(k=8, measure_toks=48, warm_steps=4):
    """ISSUE-19 kernel-floor bench: the three decode hot-loop legs, each on a
    probe model with an r5-pattern honesty refusal.

    (b) in-path KV-length split — long-context bs=1 decode with the auto
        split engaged (``lenpar_decode_tok_per_s``, ``lenpar_split_speedup``
        vs the TPUINF_LENPAR=0 control). REFUSED via ``lenpar_invalid`` if
        `lenpar_stats()` shows the auto split never traced in the measured
        runner — a silent fall-back to the unsplit walk must not publish a
        plausible-looking number. (On a CPU container the split runs the
        interpreter serially, so the speedup only means something on TPU —
        the witness guards engagement, the trajectory gate guards the ratio.)
    (c) megastep-everything — ``megastep_spec_speedup`` (the device-resident
        speculative megastep vs step-wise draft-verify chunks; REFUSED via
        ``megastep_spec_invalid`` without cb.spec.megastep dispatches) and
        ``megastep_mixed_speedup`` (the mixed insert+decode megastep scan vs
        step-wise chunked prefill; REFUSED via ``megastep_mixed_invalid``).

    Leg (a), AMLA, has no wall-clock phase on purpose: its win is in-kernel
    transcendental count, invisible to CPU wall time — the canary group
    (``amla``) pins its zero-extra-HBM contract instead."""
    import gc
    import os as _os
    import time as _time

    from neuronx_distributed_inference_tpu.config import (
        TpuConfig, load_pretrained_config)
    from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
        LlamaForCausalLM, LlamaInferenceConfig)
    from neuronx_distributed_inference_tpu.ops import paged_decode as _pd
    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)

    probe_hf = {
        "model_type": "llama", "vocab_size": 256, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2,
        "max_position_embeddings": 1024, "rms_norm_eps": 1e-5,
        "rope_theta": 10000.0, "tie_word_embeddings": False,
    }
    seq, block = 512, 16

    def build(batch, layers=2, seed=0):
        hf = dict(probe_hf, num_hidden_layers=layers)
        cfg = TpuConfig(batch_size=batch, seq_len=seq, max_context_length=256,
                        dtype="float32", context_encoding_buckets=[256],
                        token_generation_buckets=[seq],
                        is_continuous_batching=True,
                        paged_attention_enabled=True,
                        pa_num_blocks=(batch + 1) * (seq // block) + 8,
                        pa_block_size=block, decode_kernel_enabled=True)
        config = LlamaInferenceConfig(
            cfg, load_config=load_pretrained_config(hf))
        app = LlamaForCausalLM(None, config)
        app.load_random(seed=seed)
        return app

    def serve_window(runner, n_toks):
        t0 = _time.perf_counter()
        n = 0
        while n < n_toks and runner.has_work:
            n += sum(len(v) for v in runner.step().values())
        return n / (_time.perf_counter() - t0)

    rng = np.random.default_rng(11)
    out = {}

    # ---- leg b: in-path KV-length split, long-context bs=1 ----------------
    # bs=1 x 2 kv heads x a 32-wide table is the _auto_kv_splits regime (a
    # 4-way split); each env variant builds a FRESH runner so the trace-time
    # toggle retraces, and lenpar_stats() is the engagement witness.
    prompt = rng.integers(1, 250, size=(200,)).astype(np.int32)
    app1 = build(1)
    rates, split_stats = {}, {}
    saved_env = _os.environ.get("TPUINF_LENPAR")
    try:
        for tag, env in (("control", "0"), ("split", "1")):
            _os.environ["TPUINF_LENPAR"] = env
            _pd.reset_lenpar_stats()
            r = ContinuousBatchingRunner(app1, decode_chunk=1)
            r.submit(prompt, max_new_tokens=seq - len(prompt) - 24)
            for _ in range(1 + warm_steps):       # place + warm
                r.step()
            if tag == "split":
                split_stats = _pd.lenpar_stats()
            rates[tag] = serve_window(r, measure_toks)
            r.cache = None
            del r
    finally:
        if saved_env is None:
            _os.environ.pop("TPUINF_LENPAR", None)
        else:
            _os.environ["TPUINF_LENPAR"] = saved_env
    if not (split_stats.get("split_traces") and split_stats.get("auto_engaged")):
        out["lenpar_invalid"] = (
            f"auto length split never traced in the measured runner "
            f"(lenpar stats {split_stats})")
        _note(f"lenpar INVALID: {out['lenpar_invalid']}")
    else:
        out["lenpar_decode_tok_per_s"] = round(rates["split"], 1)
        out["lenpar_control_tok_per_s"] = round(rates["control"], 1)
        out["lenpar_split_speedup"] = round(
            rates["split"] / rates["control"], 3) if rates["control"] else None
        out["lenpar_splits"] = split_stats["last_splits"]
    app1.params = None
    del app1
    gc.collect()

    # ---- leg c: speculative megastep vs step-wise draft-verify chunks -----
    target, draft = build(2, seed=0), build(2, layers=1, seed=1)
    sp_prompt = rng.integers(1, 250, size=(32,)).astype(np.int32)

    def spec_runner(mega):
        kw = dict(megastep_k=k, megastep_ring=k) if mega else {}
        r = ContinuousBatchingRunner(target, draft=draft,
                                     speculation_length=4, spec_chunk=2,
                                     telemetry=True, **kw)
        r.submit(sp_prompt, max_new_tokens=seq - len(sp_prompt) - 24)
        for _ in range(3):                        # place + compile
            r.step()
        return r

    base = spec_runner(False)
    base_tok_s = serve_window(base, measure_toks)
    base.cache = None
    del base
    mega = spec_runner(True)
    mega_tok_s = serve_window(mega, measure_toks)
    s = mega.stats()
    served = s["device"]["steps"] if s.get("device") else {}
    if not served.get("spec_megastep"):
        out["megastep_spec_invalid"] = (
            f"no spec megastep dispatches in the measured window "
            f"(served kinds: {served or 'unknown'})")
        _note(f"spec megastep INVALID: {out['megastep_spec_invalid']}")
    else:
        out["spec_stepwise_tok_per_s"] = round(base_tok_s, 1)
        out["spec_megastep_tok_per_s"] = round(mega_tok_s, 1)
        out["megastep_spec_speedup"] = round(
            mega_tok_s / base_tok_s, 3) if base_tok_s else None
        out["spec_megastep_exits"] = dict(s["megastep"]["exits"])
    mega.cache = None
    del mega

    # ---- leg c: mixed insert+decode megastep vs step-wise chunked prefill -
    # a decoding short prompt + a 3-window long prompt is the smallest stream
    # where the mixed megastep scan batches whole insert windows; the runner
    # is warmed on one full workload, then the identical resubmission is the
    # measured window (same dispatch objects, so compiles are paid up front).
    mixed_prompts = [rng.integers(1, 250, size=(n,)).astype(np.int32)
                     for n in (12, 40)]

    def mixed_measure(mega_on):
        kw = dict(megastep_k=4, megastep_ring=4) if mega_on else {}
        r = ContinuousBatchingRunner(target, decode_chunk=4, prefill_chunk=16,
                                     telemetry=True, **kw)
        for p in mixed_prompts:
            r.submit(p, max_new_tokens=16)
        while r.has_work:                         # compile pass
            r.step()
        t0 = _time.perf_counter()
        n = 0
        for p in mixed_prompts:
            r.submit(p, max_new_tokens=16)
        while r.has_work:
            n += sum(len(v) for v in r.step().values())
        tok_s = n / (_time.perf_counter() - t0)
        st = r.stats()
        r.cache = None
        return tok_s, (st["device"]["steps"] if st.get("device") else {})

    base_tok_s, _ = mixed_measure(False)
    mega_tok_s, served = mixed_measure(True)
    if not served.get("mixed_megastep"):
        out["megastep_mixed_invalid"] = (
            f"no mixed megastep scans in the measured window "
            f"(served kinds: {served or 'unknown'})")
        _note(f"mixed megastep INVALID: {out['megastep_mixed_invalid']}")
    else:
        out["mixed_stepwise_tok_per_s"] = round(base_tok_s, 1)
        out["mixed_megastep_tok_per_s"] = round(mega_tok_s, 1)
        out["megastep_mixed_speedup"] = round(
            mega_tok_s / base_tok_s, 3) if base_tok_s else None
    target.params = None
    draft.params = None
    del target, draft
    gc.collect()
    return out


def _moe_paged_decode(ep_degree=1, bs=8, n_chunks=4, max_new=220):
    """ISSUE-16 MoE serving phase: a Mixtral-arch probe model (2L, 256H, 8
    experts top-2 — MoE cost structure without swamping the phase budget)
    served through the PAGED CB runner twice on identical geometry:

    - grouped leg: the fused grouped expert kernel (ops/moe.py), and at
      ep_degree > 1 the overlap-scheduled EP ring (parallel/overlap.py);
    - dense leg: TPUINF_MOE_GROUPED=0 / TPUINF_EP_OVERLAP=0 — the dense
      all-experts einsums with GSPMD combine (a fresh app per leg: the env
      flags are read at trace time, so reusing warm executables would
      silently measure the same graph twice).

    HONESTY GUARD (r5 spec-floor pattern): the trace counters — read as
    in-scope deltas via ``ops/moe.trace_stats_scope`` around the measured leg,
    so stale global state can't stand in for evidence — must show the fast
    path actually lowered into the measured leg's graphs. Any ``dense_decode``
    tick, or an all-zero delta (nothing traced: a warm executable silently
    reused), REFUSES the keys and emits ``moe_invalid`` instead of a
    plausible-looking number.
    ``ep_all_to_all_bytes_per_step`` is the ring schedule's analytic traffic
    for THIS config (0 at ep=1 — the single-chip truth — with an explicitly
    ``_projected``-suffixed ep=4 companion so the multichip estimate is
    visible without masquerading as a measurement)."""
    import gc
    import time as _time

    from neuronx_distributed_inference_tpu.config import (
        TpuConfig, load_pretrained_config)
    from neuronx_distributed_inference_tpu.models.mixtral import (
        MixtralForCausalLM)
    from neuronx_distributed_inference_tpu.ops import moe as moe_ops
    from neuronx_distributed_inference_tpu.parallel.overlap import (
        estimated_ep_bytes_per_step)
    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)

    moe_hf = {
        "model_type": "mixtral", "vocab_size": 1024, "hidden_size": 256,
        "intermediate_size": 512, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2,
        "num_local_experts": 8, "num_experts_per_tok": 2,
        "max_position_embeddings": 1024, "rms_norm_eps": 1e-5,
        "rope_theta": 10000.0, "sliding_window": None,
        "tie_word_embeddings": False,
    }
    seq, block = 512, 16
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, 1000, size=(48,)).astype(np.int32)
               for _ in range(bs)]

    def serve(env):
        old = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            cfg = TpuConfig(
                batch_size=bs, seq_len=seq, max_context_length=64,
                dtype="bfloat16", ep_degree=ep_degree,
                context_encoding_buckets=[64],
                token_generation_buckets=[seq],
                is_continuous_batching=True, paged_attention_enabled=True,
                pa_num_blocks=bs * (seq // block) + 8, pa_block_size=block)
            config = MixtralForCausalLM.get_config_cls()(
                cfg, load_config=load_pretrained_config(moe_hf))
            app = MixtralForCausalLM(None, config)
            app.load_random(seed=0)
            runner = ContinuousBatchingRunner(app, decode_chunk=16)
            for p in prompts:
                runner.submit(p, max_new_tokens=max_new)
            for _ in range(3):            # place + warm the compiled chunks
                runner.step()
            t0 = _time.perf_counter()
            n = 0
            for _ in range(n_chunks):
                n += sum(len(v) for v in runner.step().values())
            tok_s = n / (_time.perf_counter() - t0)
        finally:
            for k, v in old.items():
                os.environ.pop(k, None) if v is None else \
                    os.environ.__setitem__(k, v)
        runner.cache = None
        app.params = None
        app.kv_cache = None
        del runner, app
        gc.collect()
        return tok_s

    out = {"moe_probe_arch": "mixtral 2L/256H/8E top-2 probe",
           "moe_ep_degree": ep_degree}
    dense_tok_s = serve({"TPUINF_MOE_GROUPED": "0", "TPUINF_EP_OVERLAP": "0"})
    out["moe_dense_decode_tok_per_s"] = round(dense_tok_s, 1)

    with moe_ops.trace_stats_scope() as stats:
        tok_s = serve({})
    fast = stats["grouped"] + stats["ep_ring"]
    if stats["dense_decode"] or not fast:
        why = ("dense fallback served the measured grouped leg"
               if stats["dense_decode"] else
               "no MoE graph traced in the measured leg (warm executable "
               "reused?)")
        out["moe_invalid"] = f"{why} (trace stats {stats})"
        _note(f"MoE phase INVALID: {out['moe_invalid']}")
        return out
    out["moe_decode_tok_per_s"] = round(tok_s, 1)
    out["moe_grouped_vs_dense_ratio"] = (round(tok_s / dense_tok_s, 3)
                                         if dense_tok_s else None)
    out["moe_fast_path"] = "ep_ring" if stats["ep_ring"] else "grouped"
    L, H = moe_hf["num_hidden_layers"], moe_hf["hidden_size"]
    out["ep_all_to_all_bytes_per_step"] = estimated_ep_bytes_per_step(
        L, H, ep_degree, bs)
    if ep_degree == 1:
        out["ep_all_to_all_bytes_per_step_ep4_projected"] = \
            estimated_ep_bytes_per_step(L, H, 4, bs)
    return out


def _spec_runner_measure(runner, batch, k, n_chunks=4, max_new=760):
    """Warm + measure a spec CB runner; returns (tok_per_s, accept_mean,
    iter_ms, full_accept_tok_per_s)."""
    import time as _time

    rng = np.random.default_rng(0)
    for _ in range(batch):
        runner.submit(rng.integers(1, 100000, size=(200,)).astype(np.int32),
                      max_new_tokens=max_new)
    for _ in range(2):                         # place + warm the spec chunk
        runner.step()

    h0 = runner.acceptance_counts.copy()
    i0 = runner.spec_iters_run
    n_tokens = 0
    t0 = _time.time()
    for _ in range(n_chunks):
        em = runner.step()
        n_tokens += sum(len(v) for v in em.values())
    wall = _time.time() - t0
    # actually-dispatched iterations (step() clamps a chunk below spec_chunk
    # near request tails — assuming n_chunks * spec_chunk would bias iter_ms
    # and the ceiling low whenever the budget runs out mid-chunk)
    from neuronx_distributed_inference_tpu.utils.metrics import acceptance_mean

    iters = max(1, runner.spec_iters_run - i0)
    # acceptance from the runner's registry histogram through the ONE shared
    # mean definition (utils/metrics.acceptance_mean — same as runner.stats())
    hist = runner.acceptance_counts - h0       # measured window only
    accept_mean = acceptance_mean(hist)
    iter_ms = 1000.0 * wall / iters
    return (round(n_tokens / wall, 1), round(accept_mean, 2),
            round(iter_ms, 2), round(batch * k / (wall / iters), 1))


def _paged_spec_throughput(app, hf_cfg, batch):
    """Fused speculation through ContinuousBatchingRunner at the serving
    config: the 8B target serves with a small (8-layer, 2048-hidden) draft,
    both on the target app's quantization config (int4 weights through the
    W4A8 kernels, int8-KV paged pools for BOTH models).
    Returns the extra-dict entries (floor/ceiling/acceptance/iteration time).

    Three measurements:
    - raw spec chunks (adaptive OFF): iteration time + the acceptance-
      independent full-accept CEILING;
    - adaptive floor (spec_adaptive=True): with random weights acceptance is
      ~chance, so the runner detects the loss and serves PLAIN chunks — the
      measured floor is ~plain-paged throughput instead of ~plain/k;
    - self-draft (draft = target, see _paged_spec_selfdraft): full acceptance
      through the REAL accept/commit path, validating the ceiling arithmetic.
    """
    from neuronx_distributed_inference_tpu.config import (
        TpuConfig, load_pretrained_config)
    from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
        LlamaForCausalLM, LlamaInferenceConfig)
    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)

    k = 4
    tgt_cfg = app.tpu_config
    quant = tgt_cfg.quantization_config     # draft matches the serving config
    # standard head_dim (128) so the DRAFT also rides the paged Pallas kernels
    # (the r5 first run used head_dim=64, which the kernel gate declines — the
    # draft fell to the gather path and dominated the iteration at 140 ms)
    draft_hf = dict(hf_cfg, hidden_size=2048, intermediate_size=8192,
                    num_hidden_layers=8, num_attention_heads=16,
                    num_key_value_heads=4, head_dim=128)
    d_tpu = TpuConfig(batch_size=tgt_cfg.max_batch_size, seq_len=tgt_cfg.seq_len,
                      max_context_length=tgt_cfg.max_context_length,
                      dtype="bfloat16", tp_degree=tgt_cfg.tp_degree,
                      sequence_parallel_enabled=tgt_cfg.sequence_parallel_enabled,
                      context_encoding_buckets=list(
                          tgt_cfg.context_encoding_buckets),
                      token_generation_buckets=list(
                          tgt_cfg.token_generation_buckets),
                      is_continuous_batching=True, paged_attention_enabled=True,
                      pa_num_blocks=tgt_cfg.pa_num_blocks,
                      pa_block_size=tgt_cfg.pa_block_size,
                      quantization_config=quant)
    d_config = LlamaInferenceConfig(d_tpu,
                                    load_config=load_pretrained_config(draft_hf))
    draft = LlamaForCausalLM(None, d_config)
    draft.load_host_params(_random_quantized_llama_params(
        draft_hf, seed=1, weight_dtype=quant.weight_dtype))
    # no calibration (see _paged_serving_throughput): with RANDOM weights the
    # acceptance floor is ~chance regardless of draft cache fidelity, and the
    # full-accept ceiling is acceptance-independent — the numbers reported

    # spec_chunk default == decode_chunk (32): the per-ITERATION dispatch
    # amortization matches plain decode's per-step share (~3.4 ms at the
    # measured ~109 ms floor) instead of the old 8-iteration chunks (~13.6)
    runner = ContinuousBatchingRunner(app, draft=draft, speculation_length=k)
    tok_s, accept_mean, iter_ms, ceiling = _spec_runner_measure(
        runner, batch, k)
    out = {
        # measured committed-token throughput at random-weight acceptance
        "paged_spec_tok_per_s": tok_s,
        "paged_spec_accept_mean": accept_mean,
        "paged_spec_iter_ms": iter_ms,
        # the fused iteration costs the same regardless of acceptance: at full
        # acceptance every iteration commits K tokens per row
        "paged_spec_full_accept_tok_per_s": ceiling,
        "paged_spec_chunk_iters": runner.spec_chunk,
    }
    _drain_runner(runner)

    # --- adaptive floor: worst-case (chance-acceptance) serving rate -------
    # spec_adaptive falls back to plain decode chunks when measured
    # acceptance cannot pay for the spec iteration, so the serving FLOOR is
    # ~plain-paged throughput (minus the periodic re-probe chunk). The r5
    # anomaly — paged_spec_tok_per_s 938.2 at accept_mean 1.0 published as
    # the spec serving number — was this fallback NOT being exercised: the
    # raw (adaptive-OFF) chunks are an iteration-cost measurement, not a
    # serving configuration. The floor run now ASSERTS the guard engaged
    # (runner.stats() surfaces its state) so chance-level acceptance can
    # never again masquerade as the spec serving rate.
    try:
        _note("spec phase: adaptive floor (spec_adaptive=True)")
        runner = ContinuousBatchingRunner(app, draft=draft,
                                          speculation_length=k,
                                          spec_adaptive=True)
        tok_s, _, _, _ = _spec_runner_measure(runner, batch, k, n_chunks=6)
        guard = runner.stats()["spec"]["adaptive"]
        out["paged_spec_adaptive_fallback_active"] = bool(
            guard["fallback_active"])
        if accept_mean < runner.spec_min_accept \
                and not guard["fallback_active"]:
            # chance acceptance (measured by the raw phase above) but the
            # guard never tripped — the floor number would be the r5
            # masquerade again. Do NOT publish it: emit an explicit invalid
            # marker instead (the bench must keep emitting, so this cannot
            # be a raise — an exception here would be swallowed by this
            # phase's own failure guard and the number would land anyway).
            out["paged_spec_floor_invalid"] = (
                f"guard-not-engaged at accept_mean={accept_mean} < "
                f"min_accept={runner.spec_min_accept}")
            _note(f"adaptive floor INVALID: {out['paged_spec_floor_invalid']}")
        else:
            # at chance acceptance the floor serves plain chunks: the spec
            # serving number IS the floor, with the raw spec chunks kept as
            # the iteration-cost reference
            out["paged_spec_floor_tok_per_s"] = tok_s
            out["paged_spec_serving_tok_per_s"] = tok_s
    except Exception as e:  # the raw numbers above still stand
        _note(f"adaptive-floor measurement failed: {e}")
    finally:
        _drain_runner(runner)
    return out


def _drain_runner(runner) -> None:
    """Release a CB runner's device pools (target + draft) for the next phase."""
    import gc

    runner.cache = None
    runner.d_cache = None
    gc.collect()


def _drive_open_loop(runner, prompts, arrivals, max_new):
    """Drive a CB runner under an open-loop arrival trace.

    Requests are submitted at their (precomputed) arrival offsets while the
    serving loop steps. TTFT / token accounting is NOT recomputed here — the
    runner's telemetry records the events and the caller reads runner.stats()
    (the same numbers a production scrape would see). Each submit backdates
    ``arrival_ts`` to the SCHEDULED arrival: a request that arrives while
    step() is blocking is only submitted after the step returns, and that
    wait is exactly the interference this phase measures (it must count in
    TTFT, matching the pre-telemetry birth-time bookkeeping). Returns
    wall_s."""
    import time as _time

    t0 = _time.perf_counter()
    idx = 0
    while idx < len(arrivals) or runner.has_work:
        now = _time.perf_counter() - t0
        while idx < len(arrivals) and arrivals[idx] <= now:
            runner.submit(prompts[idx], max_new_tokens=max_new,
                          arrival_ts=t0 + arrivals[idx])
            idx += 1
        if not runner.has_work:
            _time.sleep(max(0.0, arrivals[idx] - (_time.perf_counter() - t0)))
            continue
        runner.step()
    return _time.perf_counter() - t0


def _paged_arrival_serving(app, batch, closed_loop_tok_s):
    """Open-loop Poisson-arrival serving: TTFT percentiles and committed-token
    throughput WITH concurrent inserts, for the insert-window baseline and the
    mixed-step token-budget scheduler — the same arrival trace for both.

    The arrival rate targets ~70% of the measured closed-loop serving rate
    (offered tokens / window = 0.7 x closed-loop tok/s), the standard loaded-
    but-stable operating point: slower and prefill never overlaps decode,
    faster and the queue (not the scheduler) dominates TTFT."""
    import gc

    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)

    n_req, max_new, prompt_len = 2 * batch, 256, 200
    rate = 0.7 * (closed_loop_tok_s or 2000.0) / max_new        # req/s
    rng = np.random.default_rng(11)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_req))
    prompts = [rng.integers(1, 100000, size=(prompt_len,)).astype(np.int32)
               for _ in range(n_req)]
    warm = [rng.integers(1, 100000, size=(prompt_len,)).astype(np.int32)
            for _ in range(2)]
    out = {"arrival_rate_req_s": round(rate, 2)}

    variants = [
        # insert-window baseline: capped bs=1 prefill windows between chunks
        ("arrival_insert_window", dict(decode_chunk=32,
                                       max_insert_tokens_per_step=256)),
        # mixed-step token-budget scheduler: decode rows + prefill chunk rows
        # in ONE dispatch while any insert is in flight
        ("arrival_mixed", dict(decode_chunk=32, prefill_chunk=256,
                               prefill_token_budget=256,
                               mixed_decode_steps=8)),
    ]
    events_jsonl = "/tmp/bench_arrival_events.jsonl"
    for name, kw in variants:
        # telemetry ON: the phase reads TTFT percentiles and token counts off
        # runner.stats() instead of hand-rolled birth/emit bookkeeping. The
        # serving (mixed) variant additionally spools its event log so the
        # phase ships an explain_request.py-ready artifact.
        if name == "arrival_mixed":
            from neuronx_distributed_inference_tpu.utils.metrics import (
                ServingTelemetry)

            telemetry = ServingTelemetry(jsonl_path=events_jsonl)
        else:
            telemetry = True
        runner = ContinuousBatchingRunner(app, telemetry=telemetry, **kw)
        # warm every executable this schedule touches (insert windows / mixed
        # dispatch / plain chunks) outside the measured trace
        for p in warm:
            runner.submit(p, max_new_tokens=max_new)
        guard = 0
        while runner.has_work and guard < 200:
            runner.step()
            guard += 1
        runner.telemetry.reset()     # drop the warmup from the measured stats
        wall = _drive_open_loop(runner, prompts, arrivals, max_new)
        s = runner.stats()
        out[f"{name}_tok_per_s"] = round(s["tokens_emitted"] / wall, 1)
        out[f"{name}_ttft_p50_ms"] = round(s["ttft_ms"]["latency_ms_p50"], 1)
        out[f"{name}_ttft_p99_ms"] = round(s["ttft_ms"]["latency_ms_p99"], 1)
        out[f"{name}_queue_wait_p99_ms"] = round(
            s["queue_wait_ms"]["latency_ms_p99"], 1)
        if name == "arrival_mixed":
            # TRACE HONESTY GUARD (r5 pattern): every request of the phase
            # must reconstruct into a complete causal span tree whose
            # latency waterfall reconciles to the recorded TTFT/E2E within
            # 5% — otherwise the phase's latency keys describe requests the
            # event stream cannot actually explain, and the trace keys
            # refuse to publish.
            from neuronx_distributed_inference_tpu.serving import tracing

            cov = tracing.validate_coverage(runner.telemetry, tolerance=0.05)
            runner.telemetry.close()
            if cov["ok"]:
                out["arrival_trace_coverage"] = 1.0
                out["arrival_trace_requests"] = cov["requests"]
                out["arrival_waterfall_max_residual_frac"] = \
                    cov["max_residual_frac"]
                out["arrival_events_jsonl"] = events_jsonl
            else:
                out["trace_coverage_invalid"] = cov["reason"]
                _note(f"arrival trace coverage INVALID: {cov['reason']}")
        _drain_runner(runner)
        del runner
        gc.collect()
    # the serving-mode numbers the acceptance bar reads: the MIXED scheduler
    # IS the serving configuration under arrival traffic
    out["arrival_paged_serving_tok_per_s"] = out["arrival_mixed_tok_per_s"]
    out["arrival_ttft_p50_ms"] = out["arrival_mixed_ttft_p50_ms"]
    out["arrival_ttft_p99_ms"] = out["arrival_mixed_ttft_p99_ms"]
    return out


def _drive_router_open_loop(router, prompts, arrivals, max_new):
    """Open-loop arrival driver for the multi-replica router (the router
    analog of _drive_open_loop): submit at the scheduled offsets while the
    router steps every replica. Samples per-replica load (queue + live rows)
    each step for the imbalance number. Returns (wall_s, depth_samples)."""
    import time as _time

    t0 = _time.perf_counter()
    idx = 0
    samples = []                     # per step: [replica load, ...]
    while idx < len(arrivals) or router.has_work:
        now = _time.perf_counter() - t0
        while idx < len(arrivals) and arrivals[idx] <= now:
            router.submit(prompts[idx], max_new_tokens=max_new,
                          arrival_ts=t0 + arrivals[idx])
            idx += 1
        if not router.has_work:
            _time.sleep(max(0.0, arrivals[idx] - (_time.perf_counter() - t0)))
            continue
        router.step()
        samples.append([a["queue_depth"] + a["active_requests"]
                        for a in router.stats()["replicas"].values()])
    return _time.perf_counter() - t0, samples


def _router_arrival_serving(app, batch, closed_loop_tok_s, n_replicas=2):
    """ISSUE-9 scale-out phase: an open-loop Poisson trace of PREFIX-SHARING
    prompts served by a PrefixAffinityRouter over ``n_replicas`` independent
    runners (one weights object, one paged pool each), twice: affinity
    placement vs random placement — same trace, so the prefix-hit delta is
    the router's doing. A third leg forces the host-RAM KV tier's
    evict→readmit path (spill every idle block, then re-offer the shared
    prefixes).

    HONESTY GUARD (same pattern as the r5 spec-floor marker): the affinity
    keys are refused — ``router_affinity_invalid`` is emitted instead — if
    the replicas' prefix caches were not actually enabled for the run, since
    a hit ratio over a disabled cache is vacuously 0 vs 0."""
    import gc

    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)
    from neuronx_distributed_inference_tpu.serving import (EngineReplica,
                                                           HostKVTier,
                                                           PrefixAffinityRouter)

    cfg = app.tpu_config
    slots = max(2, batch // (2 * n_replicas))
    n_req = 4 * n_replicas
    # geometry-adaptive so the phase also runs at toy scale: prompts take a
    # quarter of seq_len, half of that a BLOCK-ALIGNED shared prefix
    prompt_len = max(2 * cfg.pa_block_size, min(256, cfg.seq_len // 4))
    prefix_len = max(cfg.pa_block_size,
                     (prompt_len // 2 // cfg.pa_block_size)
                     * cfg.pa_block_size)
    max_new = min(192, cfg.seq_len - prompt_len - 8)
    if max_new < 4:
        raise ValueError(f"seq_len {cfg.seq_len} too small for the router "
                         f"arrival phase")
    rate = 0.5 * (closed_loop_tok_s or 2000.0) / max_new
    rng = np.random.default_rng(17)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_req))
    # two prefix FAMILIES: half the trace shares prefix A, half prefix B —
    # affinity should route each family to the replica holding its blocks
    prefixes = [rng.integers(1, 100000, size=(prefix_len,)).astype(np.int32)
                for _ in range(2)]
    prompts = [np.concatenate([
        prefixes[i % 2],
        rng.integers(1, 100000,
                     size=(prompt_len - prefix_len,)).astype(np.int32)])
        for i in range(n_req)]
    out = {"router_replicas": n_replicas,
           "router_arrival_rate_req_s": round(rate, 2)}

    def build(policy, tier):
        reps = [EngineReplica(
            str(i), lambda tel, t=tier: ContinuousBatchingRunner(
                app, decode_chunk=32, telemetry=tel, kv_tier=t))
            for i in range(n_replicas)]
        return PrefixAffinityRouter(reps, policy=policy), reps

    def prefix_hits(reps):
        return sum(
            (reps_i.registry.get("serving_prefix_hit_tokens_total").value
             if reps_i.registry.get("serving_prefix_hit_tokens_total")
             else 0) for reps_i in reps)

    total_prompt_toks = sum(len(p) for p in prompts)
    runs = {}
    for policy in ("affinity", "random"):
        tier = HostKVTier(capacity_blocks=4 * slots)
        router, reps = build(policy, tier)
        wall, samples = _drive_router_open_loop(router, prompts, arrivals,
                                                max_new)
        s = router.stats()
        mean_loads = np.asarray(samples, dtype=np.float64).mean(axis=0) \
            if samples else np.zeros(n_replicas)
        imbalance = (float(mean_loads.max() / mean_loads.mean())
                     if mean_loads.mean() > 0 else 1.0)
        runs[policy] = {
            "tok_per_s": round(s["tokens"] / wall, 1),
            "hit_ratio": round(prefix_hits(reps) / total_prompt_toks, 4),
            "imbalance": round(imbalance, 3),
            "prefix_caching": s["prefix_caching"],
            "spills": s["affinity_spills"],
        }
        if policy == "affinity":
            # tier leg: spill every committed prefix to host RAM, then
            # re-offer the two shared prefixes — the readmit path must fire
            for rep in reps:
                rep.runner.spill_idle_blocks()
            for pre in prefixes:
                router.submit(np.concatenate([
                    pre, rng.integers(1, 100000, size=(8,)).astype(np.int32)]),
                    max_new_tokens=16)
            router.run_to_completion()
            evict = sum(r.runner.kv_tier.evictions for r in reps)
            readmit = sum(r.runner.kv_tier.readmit_blocks for r in reps)
            out["kv_tier_evictions"] = evict
            out["kv_tier_readmit_blocks"] = readmit
            out["kv_tier_readmit_hit_ratio"] = round(
                readmit / max(1, evict), 3)
        for rep in reps:
            _drain_runner(rep.runner)
        del router, reps
        gc.collect()

    out["router_tok_per_s"] = runs["affinity"]["tok_per_s"]
    out["router_random_tok_per_s"] = runs["random"]["tok_per_s"]
    out["replica_load_imbalance"] = runs["affinity"]["imbalance"]
    if not runs["affinity"]["prefix_caching"]:
        # refuse to publish a hit ratio measured over a disabled cache
        out["router_affinity_invalid"] = (
            "prefix cache disabled during the affinity run — hit ratio "
            "would be vacuous")
        _note(f"router affinity INVALID: {out['router_affinity_invalid']}")
    else:
        out["prefix_affinity_hit_ratio"] = runs["affinity"]["hit_ratio"]
        out["prefix_random_hit_ratio"] = runs["random"]["hit_ratio"]
        out["router_affinity_spills"] = runs["affinity"]["spills"]
    return out


def _router_fault_serving(app, batch, closed_loop_tok_s, n_replicas=2):
    """ISSUE-11 fault-schedule phase: the PR 8 router trace re-run under
    injected faults — hard death of replica "0" mid-trace plus one host-tier
    entry corruption — with the supervisor auto-recovering, against a
    fault-free CONTROL of the same trace. Publishes:

    - ``goodput_under_faults_ratio``: fault-run tok/s over the control's
      (the cost of losing a replica and recovering its streams);
    - ``recovery_time_ms_p50/p99`` over recover_replica invocations;
    - ``requests_lost_total`` (MUST be 0 — the zero-loss guarantee);
    - ``fault_streams_bit_exact``: every greedy trace stream compared
      token-for-token against the fault-free control.

    HONESTY GUARD (r5 pattern): if no fault actually fired — a mis-aimed
    schedule, a refactored seam — the keys are REFUSED and
    ``faults_invalid`` says why; a fault-tolerance number measured on a
    fault-free run is vacuous."""
    import gc

    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)
    from neuronx_distributed_inference_tpu.serving import (EngineReplica,
                                                           FaultInjector,
                                                           HostKVTier,
                                                           PrefixAffinityRouter)

    cfg = app.tpu_config
    slots = max(2, batch // (2 * n_replicas))
    n_req = 4 * n_replicas
    prompt_len = max(2 * cfg.pa_block_size, min(256, cfg.seq_len // 4))
    prefix_len = max(cfg.pa_block_size,
                     (prompt_len // 2 // cfg.pa_block_size)
                     * cfg.pa_block_size)
    max_new = min(192, cfg.seq_len - prompt_len - 8)
    if max_new < 4:
        raise ValueError(f"seq_len {cfg.seq_len} too small for the fault "
                         f"phase")
    rate = 0.5 * (closed_loop_tok_s or 2000.0) / max_new
    rng = np.random.default_rng(23)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_req))
    prefixes = [rng.integers(1, 100000, size=(prefix_len,)).astype(np.int32)
                for _ in range(2)]
    prompts = [np.concatenate([
        prefixes[i % 2],
        rng.integers(1, 100000,
                     size=(prompt_len - prefix_len,)).astype(np.int32)])
        for i in range(n_req)]

    def build(injector):
        tier = HostKVTier(capacity_blocks=4 * slots)
        reps = [EngineReplica(
            str(i), lambda tel, t=tier: ContinuousBatchingRunner(
                app, decode_chunk=32, telemetry=tel, kv_tier=t))
            for i in range(n_replicas)]
        return PrefixAffinityRouter(reps, fault_injector=injector,
                                    auto_recover=True), reps, tier

    runs = {}
    for leg in ("control", "faults"):
        inj = (None if leg == "control" else FaultInjector(
            "death@0:at_step=3;corrupt@1:every_n=1,once=1", seed=11))
        router, reps, tier = build(inj)
        # seed the host tier BEFORE the trace so the corruption has bytes to
        # hit mid-run: serve both shared prefixes once and spill them
        for pre in prefixes:
            router.submit(np.concatenate([
                pre, rng.integers(1, 100000, size=(4,)).astype(np.int32)]),
                max_new_tokens=4)
        router.run_to_completion()
        for rep in reps:
            rep.runner.spill_idle_blocks()
        n_seed = len(router.requests)
        wall, _samples = _drive_router_open_loop(router, prompts, arrivals,
                                                 max_new)
        s = router.stats()
        runs[leg] = {
            "tok_per_s": s["tokens"] / wall,
            "streams": {i - n_seed: list(router.requests[i].generated)
                        for i in router.requests if i >= n_seed},
            "lost": s["requests"] - s["finished"],
            "recovery_ms": list(router.recovery_times_ms),
            "fired": inj.fired_total if inj is not None else 0,
            "integrity_failures": tier.integrity_failures,
            "failed_replicas": [r for r, st in s["replica_state"].items()
                                if st == "failed"],
        }
        for rep in reps:
            if runs[leg]["failed_replicas"] and \
                    rep.replica_id in runs[leg]["failed_replicas"]:
                continue                    # a dead runner cannot drain
            _drain_runner(rep.runner)
        del router, reps
        gc.collect()

    f, c = runs["faults"], runs["control"]
    out = {"fault_replicas": n_replicas,
           "faults_injected_total": f["fired"],
           "fault_control_tok_per_s": round(c["tok_per_s"], 1)}
    if f["fired"] == 0 or not f["failed_replicas"]:
        out["faults_invalid"] = (
            "no fault fired (or no replica failed) during the fault leg — "
            "fault-tolerance numbers over a fault-free run are vacuous")
        _note(f"fault phase INVALID: {out['faults_invalid']}")
        return out
    exact = all(f["streams"][i] == c["streams"][i]
                for i in range(len(prompts)))
    out.update({
        "goodput_under_faults_ratio": round(
            f["tok_per_s"] / max(c["tok_per_s"], 1e-9), 3),
        "recovery_time_ms_p50": round(_p_ms(
            [t / 1e3 for t in f["recovery_ms"]], "latency_ms_p50"), 3),
        "recovery_time_ms_p99": round(_p_ms(
            [t / 1e3 for t in f["recovery_ms"]], "latency_ms_p99"), 3),
        "requests_lost_total": f["lost"],
        "fault_streams_bit_exact": exact,
        "kv_tier_integrity_failures_total": f["integrity_failures"],
    })
    if f["lost"] or not exact:
        _note(f"FAULT PHASE REGRESSION: lost={f['lost']} bit_exact={exact}")
    return out


def _drive_router_open_loop_ttft(router, prompts, arrivals, max_new):
    """Open-loop router driver that also measures FRONTEND TTFT: wall time
    from each request's scheduled arrival to its first folded token (robust
    to migration/handoff — the fold is placement-agnostic). Returns
    (wall_s, rids, ttft_s_list)."""
    import time as _time

    t0 = _time.perf_counter()
    idx = 0
    rids = []
    first = {}
    while idx < len(arrivals) or router.has_work:
        now = _time.perf_counter() - t0
        while idx < len(arrivals) and arrivals[idx] <= now:
            rids.append(router.submit(prompts[idx], max_new_tokens=max_new,
                                      arrival_ts=t0 + arrivals[idx]))
            idx += 1
        if not router.has_work:
            _time.sleep(max(0.0, arrivals[idx] - (_time.perf_counter() - t0)))
            continue
        emitted = router.step()
        tnow = _time.perf_counter() - t0
        for rid, toks in emitted.items():
            if toks and rid not in first:
                first[rid] = tnow
    wall = _time.perf_counter() - t0
    ttft = [first[rid] - arrivals[i] for i, rid in enumerate(rids)
            if rid in first]
    return wall, rids, ttft


def _pooled_serving(app, batch, closed_loop_tok_s):
    """ISSUE-17 disaggregated-pools phase: the open-loop interference trace
    served twice by two-replica fleets on the same app —

    - **pooled**: 1 prefill-pool + 1 decode-pool replica under the
      ``remote_prefill`` policy, committed KV blocks handed off LIVE
      (serving/pools.py) with the transfer overlapped against the remaining
      prefill chunks;
    - **unified**: 2 unified replicas under affinity placement (the
      pre-pools fleet) — same trace, same geometry, so the interference
      delta is the topology's doing.

    ``pooled_prefill_interference_ratio`` is the share of decode-serving
    step time spent on prefill-family dispatches (``prefill_tokens > 0``;
    the ``kv_handoff`` transfer itself is excluded and priced separately by
    the handoff keys): on the pooled leg that is the DECODE replica's share
    (expected near zero — prefill landed on the other pool), on the unified
    control every replica's (prefill waves collide with resident decodes).

    HONESTY GUARD (r5 pattern): the keys REFUSE — ``pools_invalid`` — if no
    handoff actually completed, no bytes moved, any stream diverged from the
    unified control (both legs are greedy: the control IS the dedicated
    reference), or a request was lost."""
    import gc

    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)
    from neuronx_distributed_inference_tpu.serving import (EngineReplica,
                                                           HostKVTier,
                                                           PrefixAffinityRouter)

    cfg = app.tpu_config
    slots = max(2, batch // 4)
    n_req = 8
    prompt_len = max(2 * cfg.pa_block_size, min(256, cfg.seq_len // 4))
    prefix_len = max(cfg.pa_block_size,
                     (prompt_len // 2 // cfg.pa_block_size)
                     * cfg.pa_block_size)
    max_new = min(128, cfg.seq_len - prompt_len - 8)
    if max_new < 4:
        raise ValueError(f"seq_len {cfg.seq_len} too small for the pooled "
                         f"phase")
    rate = 0.5 * (closed_loop_tok_s or 2000.0) / max_new
    rng = np.random.default_rng(29)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_req))
    prefixes = [rng.integers(1, 100000, size=(prefix_len,)).astype(np.int32)
                for _ in range(2)]
    prompts = [np.concatenate([
        prefixes[i % 2],
        rng.integers(1, 100000,
                     size=(prompt_len - prefix_len,)).astype(np.int32)])
        for i in range(n_req)]
    # chunked prompt insertion (multiple windows per prompt) is what gives
    # the handoff chunks to overlap against — same cap on BOTH legs
    insert_cap = 2 * cfg.pa_block_size

    def build(leg):
        def mk(i, role):
            tier = HostKVTier(capacity_blocks=4 * slots)
            return EngineReplica(
                str(i), lambda tel, t=tier: ContinuousBatchingRunner(
                    app, decode_chunk=32, telemetry=tel, kv_tier=t,
                    max_insert_tokens_per_step=insert_cap),
                telemetry_enabled=True, pool_role=role)
        if leg == "pooled":
            reps = [mk(0, "prefill"), mk(1, "decode")]
            return PrefixAffinityRouter(reps, policy="remote_prefill"), reps
        reps = [mk(0, "unified"), mk(1, "unified")]
        return PrefixAffinityRouter(reps, policy="affinity"), reps

    def interference(reps, decode_only):
        t_pref = t_all = 0.0
        for rep in reps:
            if decode_only and rep.pool_role != "decode":
                continue
            for r in rep.runner.telemetry.steps:
                d = r.get("dur_s", 0.0)
                t_all += d
                if (r.get("kind") != "kv_handoff"
                        and r.get("prefill_tokens", 0) > 0):
                    t_pref += d
        return (t_pref / t_all) if t_all > 0 else None

    runs = {}
    for leg in ("pooled", "unified"):
        router, reps = build(leg)
        wall, rids, ttft = _drive_router_open_loop_ttft(router, prompts,
                                                        arrivals, max_new)
        s = router.stats()
        runs[leg] = {
            "tok_per_s": s["tokens"] / wall,
            "streams": {i: list(router.requests[rid].generated)
                        for i, rid in enumerate(rids)},
            "ttft": ttft,
            "interference": interference(reps,
                                         decode_only=(leg == "pooled")),
            "pools": s.get("pools"),
            "lost": s["requests"] - s["finished"],
        }
        for rep in reps:
            _drain_runner(rep.runner)
        del router, reps
        gc.collect()

    p, u = runs["pooled"], runs["unified"]
    ps = p["pools"] or {}
    out = {"pooled_handoff_channel": ps.get("channel"),
           "unified_prefill_interference_ratio": (
               round(u["interference"], 4)
               if u["interference"] is not None else None),
           "unified_decode_tok_per_s": round(u["tok_per_s"], 1)}
    exact = all(p["streams"][i] == u["streams"][i] for i in range(n_req))
    if (ps.get("completed", 0) == 0 or ps.get("bytes_total", 0) == 0
            or not exact or p["lost"] or p["interference"] is None
            or u["interference"] is None):
        out["pools_invalid"] = (
            f"pooled leg unusable: handoffs_completed={ps.get('completed')} "
            f"bytes={ps.get('bytes_total')} bit_exact={exact} "
            f"lost={p['lost']} — disaggregation numbers over a run where "
            f"no live handoff fired (or streams diverged) are vacuous")
        _note(f"pooled phase INVALID: {out['pools_invalid']}")
        return out
    out.update({
        "pooled_prefill_interference_ratio": round(p["interference"], 4),
        "pooled_decode_tok_per_s": round(p["tok_per_s"], 1),
        "pooled_ttft_p99_ms": round(_p_ms(p["ttft"], "latency_ms_p99"), 3),
        "unified_ttft_p99_ms": round(_p_ms(u["ttft"], "latency_ms_p99"), 3),
        "handoffs_completed_total": ps["completed"],
        "handoff_bytes_total": ps["bytes_total"],
        "handoff_overlap_ratio": round(ps["overlap_ratio"], 4),
        "handoff_latency_ms_p50": ps["latency_ms_p50"],
        "handoff_latency_ms_p99": ps["latency_ms_p99"],
        "pooled_streams_bit_exact": exact,
    })
    if p["interference"] >= (u["interference"] or 1.0):
        _note(f"POOLED PHASE: interference NOT below unified control "
              f"(pooled={p['interference']:.4f} "
              f"unified={u['interference']:.4f})")
    return out


def _cluster_kv_serving(app, batch, closed_loop_tok_s):
    """ISSUE-20 fleet-wide content-addressed KV store phase: a shared-prefix
    Poisson trace served by a COLD replica twice —

    - **cluster**: replica A computes the shared prefixes, spills them into
      the fleet's :class:`ClusterKVStore` (content-hash dedup), then the
      trace lands on cold replica B whose prefix walk PULLS the fleet-warm
      blocks over the cluster rung (no re-prefill of shared blocks);
    - **local**: identical choreography without a cluster store — B
      re-prefills every shared block (the pre-fleet baseline; greedy, so
      its streams are the dedicated reference).

    After the trace B's idle prefixes spill back: on the cluster leg those
    hashes are ALREADY stored, so the publish dedups — that measured
    ``cluster_dedup_ratio`` < 1.0 is the bytes-scale-with-unique-content
    claim. ``cluster_kv_hit_ratio`` is committed pull blocks over the
    fleet-warm opportunity (the shared-prefix blocks A published — exactly
    what cold B could avoid re-prefilling);
    ``cluster_readmit_tok_per_s`` prices the pull-side restore through the
    step-timeline's ``tier_readmit`` records.

    HONESTY GUARD (r5 pattern): REFUSES — ``cluster_kv_invalid`` — if no
    cross-replica pull actually committed, if any stream diverged from the
    local control, if a request was lost, or if nothing was ever published
    (a 0-vs-0 dedup ratio is vacuous)."""
    import gc

    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)
    from neuronx_distributed_inference_tpu.serving import (
        ClusterKVStore, EngineReplica, HostKVTier, PrefixAffinityRouter)

    cfg = app.tpu_config
    slots = max(2, batch // 4)
    bs = cfg.pa_block_size
    n_req = 8
    prompt_len = max(2 * bs, min(256, cfg.seq_len // 4))
    prefix_len = max(bs, (prompt_len // 2 // bs) * bs)
    max_new = min(128, cfg.seq_len - prompt_len - 8)
    if max_new < 4:
        raise ValueError(f"seq_len {cfg.seq_len} too small for the cluster "
                         f"KV phase")
    rate = 0.5 * (closed_loop_tok_s or 2000.0) / max_new
    rng = np.random.default_rng(41)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_req))
    prefixes = [rng.integers(1, 100000, size=(prefix_len,)).astype(np.int32)
                for _ in range(2)]
    warmups = [np.concatenate([
        pre, rng.integers(1, 100000, size=(4,)).astype(np.int32)])
        for pre in prefixes]
    prompts = [np.concatenate([
        prefixes[i % 2],
        rng.integers(1, 100000,
                     size=(prompt_len - prefix_len,)).astype(np.int32)])
        for i in range(n_req)]

    # the store must hold the full published working set (warm prefixes +
    # the post-trace spill-back) — a store that LRU-drops the prefixes
    # before the dedup republish would measure a vacuous 1.0
    store_cap = 2 * n_req * (prompt_len // bs) + 16

    def run_leg(leg):
        store = (ClusterKVStore(capacity_blocks=store_cap)
                 if leg == "cluster" else None)

        def mk(rid):
            tier = HostKVTier(capacity_blocks=store_cap, cluster=store,
                              owner=f"{leg}-rep{rid}")
            return EngineReplica(
                rid, lambda tel, t=tier: ContinuousBatchingRunner(
                    app, decode_chunk=32, telemetry=tel, kv_tier=t),
                telemetry_enabled=True)

        rep_a, rep_b = mk("A"), mk("B")
        router = PrefixAffinityRouter([rep_a, rep_b])
        # warm A with the shared prefixes, spill → publish (cluster leg)
        router.drain_replica("B")
        for w in warmups:
            router.submit(w, max_new_tokens=4)
        router.run_to_completion()
        rep_a.runner.spill_idle_blocks()
        # the trace lands on COLD B: its device pool and host tier are
        # empty — only the cluster rung (when present) avoids re-prefill
        router.drain_replica("A")
        router.reactivate_replica("B")
        wall, rids, _ttft = _drive_router_open_loop_ttft(
            router, prompts, arrivals, max_new)
        s = router.stats()
        # B's idle prefixes spill back: on the cluster leg those hashes are
        # already stored — the publish DEDUPS (the measured dedup < 1.0)
        rep_b.runner.spill_idle_blocks()
        readmit_toks = readmit_s = 0.0
        for r in rep_b.runner.telemetry.steps:
            n_cl = r.get("cluster_blocks", 0)
            if r.get("kind") == "tier_readmit" and n_cl:
                readmit_toks += n_cl * bs
                readmit_s += r.get("dur_s", 0.0)
        out = {
            "tok_per_s": s["tokens"] / wall,
            "streams": {i: list(router.requests[rid].generated)
                        for i, rid in enumerate(rids)},
            "lost": s["requests"] - s["finished"],
            "cluster_affinity_blocks": s.get("cluster_affinity_blocks", 0),
            "store": store.stats() if store is not None else None,
            "readmit_tok_per_s": (readmit_toks / readmit_s
                                  if readmit_s > 0 else None),
        }
        for rep in (rep_a, rep_b):
            _drain_runner(rep.runner)
        del router, rep_a, rep_b
        gc.collect()
        return out

    runs = {leg: run_leg(leg) for leg in ("cluster", "local")}
    c, l = runs["cluster"], runs["local"]
    st = c["store"] or {}
    exact = all(c["streams"][i] == l["streams"][i] for i in range(n_req))
    out = {"local_tier_decode_tok_per_s": round(l["tok_per_s"], 1)}
    dedup = st.get("dedup_ratio")
    if (st.get("cross_replica_pulls", 0) == 0
            or st.get("pull_blocks_committed", 0) == 0
            or not exact or c["lost"] or l["lost"]
            or dedup is None or not st.get("published_unique")):
        out["cluster_kv_invalid"] = (
            f"cluster leg unusable: cross_replica_pulls="
            f"{st.get('cross_replica_pulls')} committed="
            f"{st.get('pull_blocks_committed')} bit_exact={exact} "
            f"lost={c['lost']}+{l['lost']} dedup_ratio={dedup} — fleet-KV "
            f"numbers over a run where no cross-replica hit fired (or "
            f"streams diverged) are vacuous")
        _note(f"cluster KV phase INVALID: {out['cluster_kv_invalid']}")
        return out
    # hit ratio over the trace's fleet-warm OPPORTUNITY: the shared prefix
    # blocks replica A published are exactly what cold B could avoid
    # re-prefilling
    warm_blocks = len(prefixes) * (prefix_len // bs)
    out.update({
        "cluster_kv_hit_ratio": round(
            st["pull_blocks_committed"] / warm_blocks, 4),
        "cluster_dedup_ratio": round(dedup, 4),
        "cluster_kv_decode_tok_per_s": round(c["tok_per_s"], 1),
        "cluster_cross_replica_pulls": st["cross_replica_pulls"],
        "cluster_kv_bytes_pulled": st["bytes_pulled"],
        "cluster_kv_streams_bit_exact": exact,
    })
    if c["readmit_tok_per_s"] is not None:
        out["cluster_readmit_tok_per_s"] = round(c["readmit_tok_per_s"], 1)
    if dedup >= 1.0:
        _note("CLUSTER KV PHASE: no dedup measured (every publish stored a "
              "first copy) — the bytes-vs-traffic claim is untested here")
    return out


def _multitenant_serving(app, batch, closed_loop_tok_s, n_replicas=2):
    """ISSUE-13 multi-tenant overload phase: one trace — a BURSTY bulk
    tenant (clumped long prompts) beside a STEADY Poisson interactive
    tenant — served twice:

    - **sla**: the overload control plane ON — SLA classes with
      weighted-fair mixed-step prefill budgets, priority placement,
      preemptive priorities, and the brown-out ladder driven by a frontend
      backlog health signal;
    - **fifo**: the classless control — same replicas, same trace, plain
      FIFO everywhere.

    Runs on a dedicated OVERLOAD PROBE fleet (tiny llama, 2 replicas x 2
    slots, recorded in ``multitenant_probe_arch``): overload behavior is a
    property of the control plane, not the model, and the 64-slot bench app
    cannot be saturated within the phase budget — the same isolation
    argument as the bs=1 dispatch-floor probe. Latency is measured at the
    FRONTEND (submit wall time -> first/last folded token), identically for
    both legs and robust to migration/preemption. Publishes per-class
    TTFT/TPOT p50/p99 for both legs, ``goodput_under_overload_ratio``
    (interactive tokens from requests whose TTFT landed within 2x the
    unloaded p99, sla leg over FIFO control), ``requests_shed_by_class``,
    preemption counts, and ``preempted_resumed_bit_exact`` (every admitted
    stream token-compared against its dedicated single-request greedy
    reference — preempted/migrated streams included).

    HONESTY GUARD (r5 pattern): if the sla leg fired NO shed and NO
    preemption, the overload never actually engaged the control plane —
    the latency/goodput keys are REFUSED and ``multitenant_invalid`` says
    why."""
    import gc
    import time as _time

    from neuronx_distributed_inference_tpu.config import (
        TpuConfig, load_pretrained_config)
    from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
        LlamaForCausalLM, LlamaInferenceConfig)
    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)
    from neuronx_distributed_inference_tpu.serving import (
        EngineReplica, PrefixAffinityRouter, RouterOverloaded, SLAClass,
        SLAClassSet)

    del app, batch, closed_loop_tok_s          # probe fleet (see docstring)
    probe_hf = {
        "model_type": "llama", "vocab_size": 256, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2,
        "max_position_embeddings": 512, "rms_norm_eps": 1e-5,
        "rope_theta": 10000.0, "tie_word_embeddings": False,
    }
    seq, block, slots = 192, 8, 2
    cfg = TpuConfig(batch_size=slots, seq_len=seq, max_context_length=48,
                    dtype="float32", context_encoding_buckets=[16, 48],
                    token_generation_buckets=[seq],
                    is_continuous_batching=True, paged_attention_enabled=True,
                    pa_num_blocks=120, pa_block_size=block)
    config = LlamaInferenceConfig(cfg,
                                  load_config=load_pretrained_config(probe_hf))
    papp = LlamaForCausalLM(None, config)
    papp.load_random(seed=0)
    sla = SLAClassSet([
        SLAClass("interactive", priority=0, weight=4.0, sheddable=False),
        SLAClass("bulk", priority=1, weight=1.0)], default="bulk")

    rng = np.random.default_rng(29)
    inter_len, inter_new = 12, 10
    bulk_len, bulk_new = 80, 32
    # the trace, in ROUTER STEPS (deterministic across box speeds): bulk
    # arrives in two clumps (the bursty tenant), interactive arrivals are
    # Poisson-gapped throughout
    bulk_bursts = {0: 5, 8: 5, 11: 3}
    n_inter = 10
    inter_steps = np.cumsum(np.maximum(1, rng.poisson(3.0, size=n_inter)))
    inter_prompts = [rng.integers(1, 250, size=(inter_len,)).astype(np.int32)
                     for _ in range(n_inter)]
    bulk_prompts = [rng.integers(1, 250, size=(bulk_len,)).astype(np.int32)
                    for _ in range(sum(bulk_bursts.values()))]
    refs = {("i", i): papp.generate(p[None, :], max_new_tokens=inter_new
                                    ).tokens[0].tolist()
            for i, p in enumerate(inter_prompts)}
    refs.update({("b", i): papp.generate(p[None, :], max_new_tokens=bulk_new
                                         ).tokens[0].tolist()
                 for i, p in enumerate(bulk_prompts)})

    def build_router(with_sla):
        classes = sla if with_sla else None
        reps = [EngineReplica(
            str(i), lambda tel: ContinuousBatchingRunner(
                papp, decode_chunk=4, prefill_chunk=16,
                prefill_token_budget=32, mixed_decode_steps=2,
                telemetry=tel, sla_classes=classes),
            # a shallow replica queue keeps the backlog at the FRONTEND,
            # where the shed/brown-out machinery lives (a deep replica
            # queue would just hide the overload from the router)
            max_queue_depth=2)
            for i in range(n_replicas)]
        holder = {}
        router = PrefixAffinityRouter(
            reps, sla_classes=classes,
            # health = "the frontend backlog is small": sustained backlog
            # IS the overload the brown-out ladder exists for
            slo_signal=((lambda: len(holder["r"].queue) < 3) if with_sla
                        else None),
            brownout_up_after=1, brownout_down_after=3)
        holder["r"] = router
        # warm every executable this schedule touches (mixed dispatch,
        # insert windows, plain chunks) OUTSIDE the measured trace — each
        # leg builds fresh runners, so each leg pays its own compiles here
        warm_rng = np.random.default_rng(5)
        for n, mx in ((inter_len, inter_new), (bulk_len, bulk_new)):
            router.submit(warm_rng.integers(1, 250, size=(n,)).astype(
                np.int32), max_new_tokens=mx)
        router.run_to_completion()
        return router, reps

    def run_leg(with_sla):
        router, reps = build_router(with_sla)
        t0 = _time.perf_counter()
        placed = {}                      # (tenant, idx) -> frontend rid
        arrive, first, last, ntok = {}, {}, {}, {}
        shed = 0
        bursts = dict(bulk_bursts)
        bi = ii = step = 0

        def _submit(key, prompt, max_new, cls):
            nonlocal shed
            now = _time.perf_counter()
            try:
                rid = router.submit(
                    prompt, max_new_tokens=max_new, arrival_ts=now,
                    **({"sla_class": cls} if with_sla else {}))
            except RouterOverloaded:
                shed += 1
                return
            placed[key] = rid
            arrive[rid] = now

        while step < 500:
            for _ in range(bursts.pop(step, 0)):
                _submit(("b", bi), bulk_prompts[bi], bulk_new, "bulk")
                bi += 1
            while ii < n_inter and inter_steps[ii] <= step:
                _submit(("i", ii), inter_prompts[ii], inter_new,
                        "interactive")
                ii += 1
            em = router.step()
            now = _time.perf_counter()
            for rid, toks in em.items():
                if toks:
                    first.setdefault(rid, now)
                    last[rid] = now
                    ntok[rid] = ntok.get(rid, 0) + len(toks)
            step += 1
            if ii >= n_inter and not bursts and not router.has_work:
                break
        wall = _time.perf_counter() - t0
        # bit-exactness over every ADMITTED stream — preempted/migrated
        # included (shed requests were refused typed+counted at the door,
        # never silently lost). A stream cut short by the step cap is
        # TRUNCATION (its tokens must be a strict prefix of the reference),
        # not divergence — the refusal below handles it; only a non-prefix
        # mismatch is a real regression.
        exact, truncated = True, False
        for key, rid in placed.items():
            gen, ref = router.requests[rid].generated, refs[key]
            if gen == ref:
                continue
            if not router.requests[rid].done and ref[: len(gen)] == gen:
                truncated = True
            else:
                exact = False
        complete = (ii >= n_inter and not bursts and not router.has_work
                    and not truncated)
        finished = sum(1 for rid in placed.values()
                       if router.requests[rid].done)
        ttft = {"interactive": [], "bulk": []}
        tpot = {"interactive": [], "bulk": []}
        for (kind, _i), rid in placed.items():
            cls = "interactive" if kind == "i" else "bulk"
            if rid in first:
                ttft[cls].append(first[rid] - arrive[rid])
            if rid in first and ntok.get(rid, 0) > 1:
                tpot[cls].append((last[rid] - first[rid]) / (ntok[rid] - 1))
        s = router.stats()
        leg = {
            "wall": wall, "steps": step, "shed": shed, "exact": exact,
            "complete": complete,
            "finished": finished, "admitted": len(placed),
            "ttft": ttft, "tpot": tpot,
            "class_preemptions": sum(
                s.get("sla", {}).get("preempted_by_class", {}).values()),
            "shed_by_class": dict(
                s.get("sla", {}).get("shed_by_class", {})),
            "brownout_transitions": len(
                [e for e in router.trace_events if e["event"] == "brownout"]),
            "inter_tok_in_target": None,   # filled by the caller (needs bar)
            "placed": placed, "router_requests": router.requests,
            "first": first, "arrive": arrive,
        }
        for rep in reps:
            _drain_runner(rep.runner)
        del router, reps
        gc.collect()
        return leg

    # ---- unloaded interactive TTFT: the acceptance bar's denominator -------
    router0, reps0 = build_router(True)
    un_samples = []
    for p in inter_prompts[:4]:
        t = _time.perf_counter()
        rid = router0.submit(p, max_new_tokens=inter_new, arrival_ts=t,
                             sla_class="interactive")
        while not router0.requests[rid].generated:
            router0.step()
        un_samples.append(_time.perf_counter() - t)
        router0.run_to_completion()
    for rep in reps0:
        _drain_runner(rep.runner)
    del router0, reps0
    gc.collect()
    un_p99 = _p_ms(un_samples, "latency_ms_p99")

    legs = {name: run_leg(with_sla)
            for name, with_sla in (("sla", True), ("fifo", False))}

    out = {
        "multitenant_replicas": n_replicas,
        "multitenant_probe_arch": "llama 2L/64H probe, 2x2 slots (overload "
                                  "isolation; control-plane behavior is "
                                  "model-independent)",
        "multitenant_interactive_ttft_p99_unloaded_ms": round(un_p99, 1),
    }
    target_s = 2.0 * un_p99 / 1e3       # the acceptance bar: 2x unloaded p99
    for name, leg in legs.items():
        for cls in ("interactive", "bulk"):
            for metric, samples in (("ttft", leg["ttft"][cls]),
                                    ("tpot", leg["tpot"][cls])):
                for q in ("p50", "p99"):
                    out[f"multitenant_{name}_{cls}_{metric}_{q}_ms"] = (
                        round(_p_ms(samples, f"latency_ms_{q}"), 1)
                        if samples else None)
        # goodput: tokens of interactive requests whose TTFT met the bar
        good = sum(len(leg["router_requests"][rid].generated)
                   for (kind, _i), rid in leg["placed"].items()
                   if kind == "i" and rid in leg["first"]
                   and leg["first"][rid] - leg["arrive"][rid] <= target_s)
        leg["goodput_tok_s"] = good / leg["wall"]
        out[f"multitenant_{name}_interactive_goodput_tok_per_s"] = round(
            leg["goodput_tok_s"], 2)
    s_leg = legs["sla"]
    out["requests_shed_by_class"] = s_leg["shed_by_class"]
    out["multitenant_shed_total"] = s_leg["shed"]
    out["multitenant_class_preemptions"] = s_leg["class_preemptions"]
    out["multitenant_brownout_transitions"] = s_leg["brownout_transitions"]
    if not (s_leg["complete"] and legs["fifo"]["complete"]):
        # the step cap cut a leg short: its streams are prefixes, not
        # measurements — refuse rather than publish truncated latencies (or
        # a false bit-exactness regression)
        out["multitenant_invalid"] = (
            "a leg did not complete within the step cap — truncated streams "
            "measure the cap, not the control plane")
        _note(f"multitenant phase INVALID: {out['multitenant_invalid']}")
        return out
    if s_leg["shed"] == 0 and s_leg["class_preemptions"] == 0:
        out["multitenant_invalid"] = (
            "no shed and no preemption fired in the sla leg — the overload "
            "trace never engaged the control plane; its latency/goodput "
            "numbers would be vacuous")
        _note(f"multitenant phase INVALID: {out['multitenant_invalid']}")
        return out
    out["preempted_resumed_bit_exact"] = bool(
        s_leg["exact"] and legs["fifo"]["exact"])
    out["goodput_under_overload_ratio"] = round(
        s_leg["goodput_tok_s"] / max(legs["fifo"]["goodput_tok_s"], 1e-9), 3)
    p99_sla = out.get("multitenant_sla_interactive_ttft_p99_ms")
    if p99_sla is not None and un_p99 > 0:
        out["multitenant_interactive_ttft_p99_vs_unloaded"] = round(
            p99_sla / un_p99, 3)
    if not out["preempted_resumed_bit_exact"]:
        _note("MULTITENANT PHASE REGRESSION: a preempted/admitted stream "
              "diverged from its reference")
    return out


def _selftuning_serving(app, batch):
    """ISSUE-18 self-tuning phase: the COMMITTED multi-phase arrival trace
    (tests/data/selftune_journal.jsonl — bursty interactive, bulk
    decode-heavy, long-context; recorded by a prompt-journaling router)
    replayed twice on a real probe fleet through the deterministic what-if
    replayer (serving/replay.py):

    - **static**: the constructor configuration, untouched;
    - **tuned**: the SAME starting configuration driven live by the online
      controller (serving/tuner.py), whitelisted to the retrace-free knobs
      (``megastep_k`` — a dynamic operand of one executable — and
      ``async_depth``), reading REAL fleet signals (queue depth, occupancy,
      measured dispatch-gap fraction). The honest win mechanism is the
      megastep walk-up on the decode-heavy stretch: fewer host round trips
      per emitted token.

    Both legs build fresh fleets warmed on the same executables, and both
    are scored by the existing waterfall/coverage pipeline. Publishes
    ``tuned_vs_static_ratio`` (tuned tok/s over static tok/s on the wall
    clock of the replay loop), the decision count, and the bit-exactness
    marker (schedule-only knobs: the streams MUST match).

    HONESTY GUARD (r5 pattern): REFUSES — ``tuner_invalid`` — if the
    controller never made a decision, if either leg fails the ≤5% PR 11
    waterfall-reconciliation contract, if any stream differs between legs,
    or if tuned did not beat static (a controller that cannot beat the
    static config has no business publishing a tuning ratio)."""
    import gc

    from neuronx_distributed_inference_tpu.config import (
        TpuConfig, load_pretrained_config)
    from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
        LlamaForCausalLM, LlamaInferenceConfig)
    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)
    from neuronx_distributed_inference_tpu.serving import (
        EngineReplica, PrefixAffinityRouter, ServingTuner, reconstruct_trace,
        replay)

    del app, batch                  # probe fleet (see docstring)
    journal = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "tests", "data", "selftune_journal.jsonl")
    trace = reconstruct_trace(journal)
    probe_hf = {
        "model_type": "llama", "vocab_size": 256, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2,
        "max_position_embeddings": 512, "rms_norm_eps": 1e-5,
        "rope_theta": 10000.0, "tie_word_embeddings": False,
    }
    seq, slots = 192, 2
    cfg = TpuConfig(batch_size=slots, seq_len=seq, max_context_length=48,
                    dtype="float32", context_encoding_buckets=[16, 48],
                    token_generation_buckets=[seq],
                    is_continuous_batching=True, paged_attention_enabled=True,
                    pa_num_blocks=120, pa_block_size=8)
    config = LlamaInferenceConfig(cfg,
                                  load_config=load_pretrained_config(probe_hf))
    papp = LlamaForCausalLM(None, config)
    papp.load_random(seed=0)

    def fleet():
        reps = [EngineReplica(
            str(i), lambda tel: ContinuousBatchingRunner(
                papp, decode_chunk=4, megastep_k=2, megastep_ring=16,
                telemetry=tel), telemetry_enabled=True)
            for i in range(2)]
        router = PrefixAffinityRouter(reps)
        # warm every executable the trace touches OUTSIDE the measured
        # replay (each leg builds fresh runners, so each leg pays its own
        # compiles here — megastep_k is a dynamic operand of ONE warmed
        # executable, so the tuned leg's walks recompile nothing)
        warm_rng = np.random.default_rng(17)
        for n, mx in ((12, 20), (44, 8)):
            router.submit(warm_rng.integers(1, 250, size=(n,)).astype(
                np.int32), max_new_tokens=mx)
        router.run_to_completion()
        for rep in reps:
            rep.runner.telemetry.reset()       # score only the replayed trace
            rep.runner.knobs.refresh()         # re-export gauges post-reset
        return router

    def tuner_factory(rt):
        return ServingTuner(
            router=rt, knob_whitelist=["megastep_k", "async_depth"],
            up_after=2, down_after=2, eval_ticks=4)

    static = replay(trace, fleet)
    tuned = replay(trace, fleet, tuner_factory=tuner_factory)
    gc.collect()

    ratio = (tuned.tokens_per_s / static.tokens_per_s
             if static.tokens_per_s > 0 else 0.0)
    s_sum, t_sum = static.summary(), tuned.summary()
    out = {
        "selftune_replay_requests": len(trace),
        "selftune_probe_arch": "llama 2L/64H probe, 2x2 slots, megastep "
                               "ring 16 (committed multi-phase trace; "
                               "control-plane behavior is model-independent)",
        "selftune_static_tok_per_s": round(static.tokens_per_s, 2),
        "selftune_tuned_tok_per_s": round(tuned.tokens_per_s, 2),
        "selftune_tuner_decisions": len(tuned.tuner_decisions),
        "selftune_decisions": [
            {k: d[k] for k in ("knob", "from", "to", "direction", "phase")}
            for d in tuned.tuner_decisions[:12]],
        "selftune_streams_bit_exact": bool(static.tokens
                                           and static.tokens == tuned.tokens),
        "selftune_static_coverage_ok": static.coverage_ok,
        "selftune_tuned_coverage_ok": tuned.coverage_ok,
        "selftune_static_mean_ttft_ms": s_sum["mean_ttft_ms"],
        "selftune_tuned_mean_ttft_ms": t_sum["mean_ttft_ms"],
    }
    if not out["selftune_streams_bit_exact"]:
        # schedule-only means exactly this: any divergence is a regression,
        # never a trade
        out["tuner_invalid"] = ("a tuned stream diverged from the static "
                                "leg — the schedule-only knob invariant is "
                                "broken")
        _note(f"SELFTUNE PHASE REGRESSION: {out['tuner_invalid']}")
        return out
    if not (static.coverage_ok and tuned.coverage_ok):
        why = (static.coverage if not static.coverage_ok
               else tuned.coverage)
        out["tuner_invalid"] = (f"a leg failed the waterfall reconciliation "
                                f"contract: {why}")
        _note(f"selftune phase INVALID: {out['tuner_invalid']}")
        return out
    if not tuned.tuner_decisions:
        out["tuner_invalid"] = (
            "the controller never made a decision on the committed trace — "
            "a tuning ratio without tuning would be vacuous")
        _note(f"selftune phase INVALID: {out['tuner_invalid']}")
        return out
    if ratio < 1.0:
        out["tuner_invalid"] = (
            f"tuned did not beat static ({tuned.tokens_per_s:.2f} vs "
            f"{static.tokens_per_s:.2f} tok/s) — refusing to publish a "
            f"losing tuning ratio")
        _note(f"selftune phase INVALID: {out['tuner_invalid']}")
        return out
    out["tuned_vs_static_ratio"] = round(ratio, 3)
    _note(f"selftune: tuned {tuned.tokens_per_s:.1f} tok/s vs static "
          f"{static.tokens_per_s:.1f} ({ratio:.3f}x), "
          f"{len(tuned.tuner_decisions)} decision(s)")
    return out


def _memledger_pressure(app, batch):
    """ISSUE-15 memory-pressure phase: forced KV churn — spill, readmit,
    preempt/resume — through a block-ledgered tiered runner
    (serving/memledger.py), publishing the ledger's fragmentation /
    idle-age / host-tier-watermark telemetry and the leak counter, which
    MUST be 0 under the conservation audit.

    HONESTY GUARD (r5 pattern): if no churn actually occurred — nothing
    spilled, nothing re-admitted, nothing preempted — the keys are REFUSED
    and ``memledger_invalid`` says why; memory-accountability numbers over
    an idle pool are vacuous."""
    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)
    from neuronx_distributed_inference_tpu.serving import HostKVTier

    cfg = app.tpu_config
    bs = cfg.pa_block_size
    tier = HostKVTier(capacity_blocks=64)
    runner = ContinuousBatchingRunner(app, decode_chunk=8, kv_tier=tier)
    out = {}
    try:
        if runner.ledger is None:
            out["memledger_invalid"] = ("runner has no block ledger — the "
                                        "allocator lacks Python seams")
            _note(f"memledger phase INVALID: {out['memledger_invalid']}")
            return out
        rng = np.random.default_rng(31)
        prefixes = [rng.integers(1, 100000, size=(2 * bs,)).astype(np.int32)
                    for _ in range(4)]

        def prompt(i):
            return np.concatenate([
                prefixes[i % len(prefixes)],
                rng.integers(1, 100000, size=(bs,)).astype(np.int32)])

        # 1) commit the shared prefixes (park idle), then SPILL them to host
        for i in range(len(prefixes)):
            runner.submit(prompt(i), max_new_tokens=4)
        runner.run_to_completion()
        spilled = runner.spill_idle_blocks()
        # 2) a same-prefix wave pulls the bytes back: READMIT churn
        for i in range(len(prefixes)):
            runner.submit(prompt(i), max_new_tokens=4)
        runner.run_to_completion()
        # 3) preempt/resume churn: a wave drained mid-flight and resumed —
        # the migration hand-off the ledger must balance across
        n_wave = min(8, 2 * runner.num_slots)
        for i in range(n_wave):
            runner.submit(prompt(i), max_new_tokens=48)
        runner.step()
        runner.step()
        mem_mid = runner.stats()["memory"]     # fragmentation under load
        _, evicted = runner.drain_requests()   # audits the hand-off itself
        preempted = sum(1 for r in evicted if r.generated)
        for r in evicted:
            runner.submit(r.prompt, max_new_tokens=r.max_new_tokens,
                          resume_tokens=r.generated or None)
        runner.run_to_completion()
        mem = runner.stats()["memory"]
        aud = runner.audit_ledger()
        out.update({
            "memledger_spilled_blocks": int(spilled),
            "memledger_readmit_blocks": int(tier.readmit_blocks),
            "memledger_preemptions": int(preempted),
        })
        if spilled < 1 or tier.readmit_blocks < 1 or preempted < 1:
            out["memledger_invalid"] = (
                "no churn occurred (spill/readmit/preempt) — the ledger "
                "numbers below would measure an idle pool, not memory "
                "accountability under pressure")
            _note(f"memledger phase INVALID: {out['memledger_invalid']}")
            return out
        out.update({
            "kv_fragmentation_ratio": mem_mid.get("fragmentation_ratio"),
            "kv_idle_age_p50_s": (mem.get("idle_age_s") or {}).get("p50"),
            "kv_host_tier_watermark": int(tier.watermark),
            "kv_leaked_blocks_total": int(aud["leaked_blocks"]),
            "memledger_audit_ok": bool(aud["ok"]),
        })
        if aud["leaked_blocks"] or not aud["ok"]:
            _note(f"MEMLEDGER PHASE REGRESSION: leaked="
                  f"{aud['leaked_blocks']} audit_ok={aud['ok']} "
                  f"violations={aud['violations'][:3]}")
        return out
    finally:
        _drain_runner(runner)


def _paged_spec_selfdraft(app, batch):
    """Self-draft speculation: draft IS the target (same weights object — no
    extra HBM for params; the draft needs its own paged pool). Greedy
    acceptance then accepts (nearly) everything THROUGH THE REAL
    accept/commit/rollback path, so the measured committed-token throughput
    validates the full-accept ceiling arithmetic (VERDICT r5 #5: the ceiling
    was previously pure arithmetic; this drives the actual accept path).
    Within ~10% of the ceiling = validated; any residual gap is the cost the
    ceiling arithmetic hides (host replay, acceptance select, numeric-tie
    argmax flips between the 1-token draft pass and the K-wide verify)."""
    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)

    k = 4
    runner = ContinuousBatchingRunner(app, draft=app, speculation_length=k)
    try:
        tok_s, accept_mean, iter_ms, ceiling = _spec_runner_measure(
            runner, batch, k)
        return {
            "paged_spec_selfdraft_tok_per_s": tok_s,
            "paged_spec_selfdraft_accept_mean": accept_mean,
            "paged_spec_selfdraft_iter_ms": iter_ms,
            # the self-draft iteration runs the FULL target as its own draft
            # (k-1 extra target passes), so it validates the accept path
            # against its OWN measured-iteration ceiling, not the small-draft
            # one: at full acceptance this ratio should be within ~10% of 1.0
            "paged_spec_selfdraft_vs_own_ceiling": round(tok_s / ceiling, 3),
        }
    finally:
        _drain_runner(runner)


if __name__ == "__main__":
    main()
