"""Shared test bootstrap: force the virtual 8-device CPU mesh.

Imported (for its side effects) by tests/conftest.py and contrib/conftest.py —
one copy of the platform forcing, mirroring the reference's CPU-mode SPMD
validation (`NXD_CPU_MODE` + gloo, `models/application_base.py:554-626`).
Must run before the first jax device query.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# the environment's TPU plugin overrides JAX_PLATFORMS; force CPU explicitly
jax.config.update("jax_platforms", "cpu")
