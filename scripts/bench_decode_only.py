"""Fast decode-step timing for the 8B bench config (params cached on disk after the
first run). Prints per-step ms + tok/s, and token parity kernel-vs-jnp."""
import os
import pickle
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

CACHE = "/tmp/bench8b_params.pkl"


def get_params(hf_cfg):
    import bench
    if os.path.exists(CACHE):
        with open(CACHE, "rb") as f:
            return pickle.load(f)
    p = bench._random_quantized_llama_params(hf_cfg, seed=0)
    with open(CACHE, "wb") as f:
        pickle.dump(p, f, protocol=4)
    return p


def main():
    from neuronx_distributed_inference_tpu.config import (
        QuantizationConfig, TpuConfig, load_pretrained_config)
    from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
        LlamaForCausalLM, LlamaInferenceConfig)

    hf_cfg = {
        "model_type": "llama", "vocab_size": 128256, "hidden_size": 4096,
        "intermediate_size": 14336, "num_hidden_layers": 32,
        "num_attention_heads": 32, "num_key_value_heads": 8, "head_dim": 128,
        "max_position_embeddings": 131072, "rms_norm_eps": 1e-5,
        "rope_theta": 500000.0,
        "rope_scaling": {"rope_type": "llama3", "factor": 8.0,
                         "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                         "original_max_position_embeddings": 8192},
        "tie_word_embeddings": False,
    }
    batch = int(os.environ.get("BENCH_BS", "64"))
    kernel = os.environ.get("BENCH_KERNEL", "1") == "1"
    kvd = os.environ.get("BENCH_KVD", "float8_e4m3")
    w4 = os.environ.get("BENCH_W4", "0") == "1"
    quant = QuantizationConfig.for_kv_dtype(
        kvd, quantize_weights=True, weight_dtype="int4" if w4 else "int8")
    tpu_cfg = TpuConfig(batch_size=batch, seq_len=512, max_context_length=256,
                        dtype="bfloat16", tp_degree=1,
                        context_encoding_buckets=[128, 256],
                        token_generation_buckets=[256, 512],
                        quantization_config=quant,
                        decode_kernel_enabled=kernel)
    config = LlamaInferenceConfig(tpu_cfg, load_config=load_pretrained_config(hf_cfg))
    app = LlamaForCausalLM(None, config)
    t0 = time.time()
    params = get_params(hf_cfg)
    if w4:
        from neuronx_distributed_inference_tpu.ops.quantization import (
            W4_DEFAULT_PARAMS)
        from neuronx_distributed_inference_tpu.ops.w4 import repack_int8_to_int4
        params = dict(params)
        params["layers"] = {
            k: (repack_int8_to_int4(v) if k in W4_DEFAULT_PARAMS else v)
            for k, v in params["layers"].items()}
    app.load_host_params(params)
    print(f"params on device in {time.time()-t0:.0f}s", flush=True)

    rng = np.random.default_rng(0)
    ids = rng.integers(1, hf_cfg["vocab_size"], size=(batch, 128)).astype(np.int32)
    app.generate(ids, max_new_tokens=128)                      # compile+warm
    out = app.generate(ids, max_new_tokens=128, collect_latency=True)
    s = np.array([x for x, _ in out.decode_latencies_s])
    n = np.array([x for _, x in out.decode_latencies_s])
    per_step = 1000.0 * s / n
    toks = n.sum() * batch / s.sum()
    print(f"kernel={kernel} w4={w4} bs={batch}: p50 step "
          f"{np.percentile(per_step, 50):.2f} ms -> {toks:.0f} tok/s, "
          f"ttft {out.ttft_s:.3f}s", flush=True)


if __name__ == "__main__":
    main()
