"""Probe: raw Mosaic dot cost at the paged-attend cell shapes.

Hypothesis: s = dot_general(q (64,128), k (4096,128), contract (1,1)) forces a
per-cell transpose of the 4096x128 K operand (MXU wants the contraction on
dim 0 of B), while the PV dot p (64,4096) @ v (4096,128) is layout-native.
Measures, per kernel invocation (grid of 32 cells to mimic the attend):
  a) qk_t  : dot(q, k, ((1,),(1,)))      - the current attend's K dot
  b) qk_n  : dot(q, kT, ((1,),(0,)))     - same math, K pre-transposed (128,4096)
  c) pv    : dot(p, v, ((1,),(0,)))      - the PV dot for reference
  d) full  : a) + exp + masks + b)-style PV (one flash-ish cell)
"""

import functools
import shutil
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

CELLS = 32
M, K, N = 64, 128, 4096      # q rows, head dim, cell kv width


def run(name, kernel, args_shapes, dtype):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    rng = np.random.default_rng(0)
    ops = [jnp.asarray(rng.normal(size=s), dtype=dtype) * 0.3
           for s in args_shapes]
    out_shape = jax.ShapeDtypeStruct((M, 128), jnp.float32)

    fn = pl.pallas_call(
        kernel,
        grid=(CELLS,),
        in_specs=[pl.BlockSpec(s, lambda i: tuple(0 for _ in s))
                  for s in args_shapes],
        out_specs=pl.BlockSpec((M, 128), lambda i: (0, 0)),
        out_shape=out_shape,
    )
    f = jax.jit(fn)
    jax.block_until_ready(f(*ops))
    d = f"/tmp/probe_dot_{name}"
    shutil.rmtree(d, ignore_errors=True)
    iters = 30
    with jax.profiler.trace(d):
        for _ in range(iters):
            jax.block_until_ready(f(*ops))
    sys.path.insert(0, "/root/repo/scripts")
    from probe_paged_perf import xplane_table

    tot = xplane_table(d)
    dev_us = sum(ms for n, ms in tot.items() if n.startswith("jit_")) / iters * 1e3
    print(f"{name:6s} {dev_us:8.1f} us/call  ({dev_us / CELLS:6.2f} us/cell)",
          flush=True)


def main():
    import jax.numpy as jnp
    from jax import lax

    def qk_t(q_ref, k_ref, o_ref):
        s = lax.dot_general(q_ref[...], k_ref[...], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        o_ref[...] = jnp.sum(s, axis=1, keepdims=True) + jnp.zeros((M, 128),
                                                                   jnp.float32)

    def qk_n(q_ref, kt_ref, o_ref):
        s = lax.dot_general(q_ref[...], kt_ref[...], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
        o_ref[...] = jnp.sum(s, axis=1, keepdims=True) + jnp.zeros((M, 128),
                                                                   jnp.float32)

    def pv(p_ref, v_ref, o_ref):
        s = lax.dot_general(p_ref[...], v_ref[...], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
        o_ref[...] = s.astype(jnp.float32)

    def full_t(q_ref, k_ref, v_ref, o_ref):
        s = lax.dot_general(q_ref[...], k_ref[...], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        p = jnp.exp(s - jnp.max(s, axis=1, keepdims=True))
        o_ref[...] = lax.dot_general(
            p.astype(v_ref.dtype), v_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    def full_n(q_ref, kt_ref, v_ref, o_ref):
        s = lax.dot_general(q_ref[...], kt_ref[...], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
        p = jnp.exp(s - jnp.max(s, axis=1, keepdims=True))
        o_ref[...] = lax.dot_general(
            p.astype(v_ref.dtype), v_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dt = jnp.bfloat16
    run("qk_t", qk_t, [(M, K), (N, K)], dt)
    run("qk_n", qk_n, [(M, K), (K, N)], dt)
    run("pv", pv, [(M, N), (N, K)], dt)
    run("full_t", full_t, [(M, K), (N, K), (N, K)], dt)
    run("full_n", full_n, [(M, K), (K, N), (N, K)], dt)


if __name__ == "__main__":
    main()
