#!/usr/bin/env python
"""OOM-and-leak explainer: render a KV block ledger snapshot — owner-state
breakdown, top holders with per-request byte attribution, idle-pool ages,
fragmentation, host-tier occupancy, holdings timelines, and the last OOM
forensics record — from the artifacts the serving stack already writes
(serving/memledger.py is the live side; this is the offline reader).

Inputs, auto-detected by shape:

    # a flight-recorder debug bundle (utils/flight_recorder.py) — the
    # ledger snapshot rides in stats()["memory"] (runner-dumped bundles)
    # or extra["memory"] (the router's on-FAILED bundle)
    python scripts/explain_memory.py replica-0-failed.json

    # a raw runner.stats() snapshot saved as JSON
    python scripts/explain_memory.py stats.json

Exit codes are the integrity contract: 0 = the ledger balances (no
violations, no leaked blocks), 1 = the snapshot records violations or
leaked blocks, 2 = no ledger snapshot found / malformed input. A closed
stdout pipe exits 141, never 1."""

import argparse
import json
import os
import sys


def _find_memory(doc: dict):
    """Locate the ledger snapshot in a bundle or a stats dict."""
    if not isinstance(doc, dict):
        return None
    if "states" in doc and "num_blocks" in doc:
        return doc                                     # the snapshot itself
    for path in (("memory",), ("stats", "memory"), ("extra", "memory")):
        node = doc
        for key in path:
            node = node.get(key) if isinstance(node, dict) else None
        if isinstance(node, dict) and "states" in node:
            return node
    return None


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}"
        n /= 1024
    return f"{n:.1f} GiB"


def _print_states(mem: dict) -> None:
    total = mem.get("num_blocks", 0)
    print(f"pool: {total} blocks x {_fmt_bytes(mem.get('bytes_per_block'))}"
          f"/block")
    for state, n in (mem.get("states") or {}).items():
        bar = "#" * (0 if not total else int(round(28 * n / total)))
        print(f"  {state:<18} {n:>8}  {bar}")


def _print_holders(mem: dict, top: int) -> None:
    holders = mem.get("top_holders") or []
    if not holders:
        print("  (no live holders)")
        return
    print(f"top holders ({mem.get('holder_count', len(holders))} total):")
    for h in holders[:top]:
        cls = f" class={h['sla_class']}" if h.get("sla_class") else ""
        print(f"  request {h['request_id']:<8} {h['blocks']:>6} blocks  "
              f"{_fmt_bytes(h.get('bytes')):>10}  age {h.get('age_s', 0):>8}s"
              f"  seam={h.get('last_seam')}{cls}")


def _print_timeline(rid, events) -> None:
    print(f"  request {rid}:")
    for e in events or []:
        extra = {k: v for k, v in e.items() if k not in ("t", "event")}
        print(f"    t={e.get('t', 0):>10.3f}s {e.get('event'):<16} {extra}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="debug bundle or stats JSON")
    ap.add_argument("--top", type=int, default=8,
                    help="holders to show (default 8)")
    ap.add_argument("--timelines", action="store_true",
                    help="also print the holdings timelines the snapshot "
                         "carries")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the located snapshot as JSON instead of text")
    args = ap.parse_args(argv)

    try:
        with open(args.path) as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        print(f"cannot read {args.path}: {e}", file=sys.stderr)
        return 2
    mem = _find_memory(doc)
    if mem is None:
        print(f"no KV block ledger snapshot in {args.path} (is this a "
              f"debug bundle or runner.stats() dump from a ledgered "
              f"runner?)", file=sys.stderr)
        return 2
    if "error" in mem and "states" not in mem:
        print(f"ledger snapshot is an error record: {mem['error']}",
              file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps(mem, indent=1, default=str))
    else:
        _print_states(mem)
        if mem.get("fragmentation_ratio") is not None:
            print(f"fragmentation_ratio: {mem['fragmentation_ratio']}")
        ages = mem.get("idle_age_s") or {}
        if ages.get("count"):
            print(f"idle ages: n={ages['count']} p50={ages.get('p50')}s "
                  f"p90={ages.get('p90')}s max={ages.get('max')}s")
        tier = mem.get("host_tier")
        if tier:
            print(f"host tier: {tier.get('host_blocks')}/"
                  f"{tier.get('capacity_blocks')} blocks "
                  f"(watermark {tier.get('watermark')}, "
                  f"evictions {tier.get('evictions')}, "
                  f"readmits {tier.get('readmit_blocks')})")
        _print_holders(mem, args.top)
        by_class = mem.get("by_class")
        if by_class:
            print("by SLA class:")
            for cls, e in by_class.items():
                print(f"  {cls:<12} {e['blocks']:>6} blocks  "
                      f"{_fmt_bytes(e.get('bytes'))}")
        if args.timelines and mem.get("timelines"):
            print("holdings timelines:")
            for rid, events in mem["timelines"].items():
                _print_timeline(rid, events)
        oom = mem.get("last_oom")
        if oom:
            print(f"\nLAST OOM (seam={oom.get('seam')}, "
                  f"unix={oom.get('ts_unix')}):")
            _print_states(oom)
            _print_holders(oom, args.top)

    audit = mem.get("audit") or {}
    leaked = mem.get("leaked_blocks", audit.get("leaked_blocks", 0)) or 0
    violations = audit.get("violations", 0) or 0
    if violations or leaked:
        print(f"\nLEDGER OUT OF BALANCE: {violations} violation(s), "
              f"{leaked} leaked block(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    try:
        rc = main()
    except BrokenPipeError:
        # the exit code is this tool's integrity contract: a closed pipe
        # (| head) must not read as a ledger violation — 128+SIGPIPE
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        rc = 141
    sys.exit(rc)
