#!/usr/bin/env python
"""Perf-trajectory renderer + regression checker over the committed snapshots.

Parses every ``BENCH_r*.json`` / ``MULTICHIP_r*.json``, groups them by their
structured provenance fingerprint (ISSUE-14: r1-r5 are TPU-v5e
driver-captured, r6-r7 are CPU-container runs — they must NEVER be read as
one series), renders the per-key trajectory of each group, and in ``--ci``
mode exits non-zero when a tracked key regresses vs the last same-provenance
snapshot beyond its pinned tolerance.

What is gated where (the honesty model):

- ANALYTIC keys (``streamed_bytes_per_step_gb``, ``ici_bytes_per_step``)
  derive from the byte model / compiled schedule, not wall clocks — gated
  TIGHTLY in every provenance group (these are the ROADMAP item-4
  "roofline-style bytes-per-step canaries": a CPU run that silently grows
  the byte model fails here even though its tok/s mean nothing).
- RATIO keys (``paged_vs_dense``, ``megastep_speedup_vs_stepwise``, ...)
  are box-relative — gated loosely in every group.
- ABSOLUTE keys (tok/s, ms) are hardware measurements — gated only inside
  VERIFIED provenance groups. CPU containers differ ~6x box to box (r06 vs
  r07); gating their absolutes would be noise, publishing them as the
  trajectory would be the exact masquerade this tool exists to prevent.

Usage:
    python scripts/perf_trajectory.py              # render the trajectory
    python scripts/perf_trajectory.py --ci         # regression gate
    python scripts/perf_trajectory.py --dir PATH --json report.json

Exit codes: 0 clean; 1 tracked regression (--ci); 2 malformed snapshot.
"""

import argparse
import glob
import json
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SNAP_RE = re.compile(r"(BENCH|MULTICHIP)_r(\d+)\.json$")

# ---------------------------------------------------------------- gate rules
# key -> relative tolerance. Direction + provenance requirement per class.
ANALYTIC_LOWER_BETTER = {          # gated in EVERY provenance group
    "streamed_bytes_per_step_gb": 0.05,
    "ici_bytes_per_step": 0.05,
    "ici_bytes_per_step_est": 0.05,
}
RATIO_HIGHER_BETTER = {            # box-relative ratios: every group, loose
    "paged_vs_dense": 0.15,
    "paged_vs_headline": 0.25,
    "megastep_speedup_vs_stepwise": 0.40,
    "tp_scaling_efficiency": 0.25,
    "prefill_interference_ratio": 0.25,
    "goodput_under_overload_ratio": 0.30,
    "goodput_under_faults_ratio": 0.30,
    "paged_spec_selfdraft_vs_own_ceiling": 0.20,
    "prefix_affinity_hit_ratio": 0.25,
    # ISSUE-17 disaggregated pools: share of handoff bytes moved while the
    # source was still prefilling — the transfer must keep hiding behind
    # prefill compute, not regress to a stop-the-world copy at migration
    "handoff_overlap_ratio": 0.30,
    # ISSUE-18 self-tuning: tuned-over-static tok/s on the committed replay
    # trace — the online controller must keep beating the static config (the
    # bench already REFUSES to publish a ratio < 1.0, so the gate guards
    # against the margin quietly eroding). Loose: the win rides on host
    # round-trip amortization, which is noisy on shared CI boxes.
    "tuned_vs_static_ratio": 0.40,
    # ISSUE-19 kernel-floor legs: spec/mixed megastep vs their step-wise
    # twins, and the auto KV-length split vs the TPUINF_LENPAR=0 control.
    # Loose: the megastep wins ride host round-trip amortization; the lenpar
    # split serializes on a CPU container (its win is TPU grid parallelism,
    # so the CPU ratio hovers near 1.0 and only the erosion is gated).
    "megastep_spec_speedup": 0.40,
    "megastep_mixed_speedup": 0.50,
    "lenpar_split_speedup": 0.50,
    "ok": 0.0,                     # multichip dryrun verdict must stay 1
}
RATIO_LOWER_BETTER = {
    "telemetry_overhead_ratio": 0.50,
    # ISSUE-17: prefill-family dispatch-time share on the DECODE pool —
    # disaggregation exists to keep this near zero; loose tolerance since
    # the residual (migration tail re-inserts) is small and noisy
    "pooled_prefill_interference_ratio": 0.50,
}
ABS_HIGHER_BETTER = {              # hardware measurements: VERIFIED groups only
    "value": 0.15,
    "sync_tok_per_s": 0.15,
    "async_tok_per_s": 0.15,
    "dense_bs64_sync_tok_per_s": 0.15,
    "dense_bs64_async_tok_per_s": 0.15,
    "paged_serving_tok_per_s": 0.15,
    "paged_sync_tok_per_s": 0.15,
    "paged_async_tok_per_s": 0.15,
    "bs1_decode_tok_per_s": 0.20,
    "bs1_stepwise_tok_per_s": 0.20,
    "arrival_paged_serving_tok_per_s": 0.20,
    "router_tok_per_s": 0.20,
}
ABS_LOWER_BETTER = {
    "p50_decode_step_ms": 0.25,
    "decode_step_device_ms": 0.25,
    "ttft_p50_ms": 0.25,
    "ttft_device_ms": 0.25,
    "dispatch_floor_ms": 0.25,
    "dispatch_gap_ms": 0.40,
}


@dataclass
class Snapshot:
    path: str
    family: str                    # "bench" | "multichip"
    round: int
    key: str                       # provenance group key
    verified: bool
    metrics: Dict[str, float] = field(default_factory=dict)
    invalid_markers: Dict[str, str] = field(default_factory=dict)
    notes: List[str] = field(default_factory=list)


class SnapshotError(Exception):
    pass


def _last_json_line(tail: str) -> Optional[dict]:
    parsed = None
    for line in tail.splitlines():
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                parsed = json.loads(line)
            except ValueError:
                continue
    return parsed


def _fold_numeric(metrics: Dict[str, float], markers: Dict[str, str],
                  d: dict) -> None:
    for k, v in d.items():
        if k == "provenance" or isinstance(v, dict):
            continue
        if isinstance(v, str):
            if k.endswith("_invalid"):
                markers[k] = v
            continue
        if isinstance(v, bool):
            metrics[k] = 1.0 if v else 0.0
        elif isinstance(v, (int, float)):
            metrics[k] = float(v)


def load_snapshot(path: str) -> Snapshot:
    m = _SNAP_RE.search(os.path.basename(path))
    if not m:
        raise SnapshotError(f"{path}: not a BENCH_r*/MULTICHIP_r* snapshot")
    family, rnd = m.group(1).lower(), int(m.group(2))
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError) as e:
        raise SnapshotError(f"{path}: unreadable snapshot ({e})")
    if not isinstance(data, dict):
        raise SnapshotError(f"{path}: snapshot is not a JSON object")

    metrics: Dict[str, float] = {}
    markers: Dict[str, str] = {}
    notes: List[str] = []
    parsed = None
    if family == "bench":
        parsed = data.get("parsed") or _last_json_line(data.get("tail", ""))
        if parsed:
            _fold_numeric(metrics, markers,
                          {k: v for k, v in parsed.items() if k != "extra"})
            _fold_numeric(metrics, markers, parsed.get("extra") or {})
        else:
            notes.append("no parseable headline line (timed-out round?)")
    else:
        metrics["ok"] = 1.0 if data.get("ok") else 0.0
        for line in data.get("tail", "").splitlines():
            if line.startswith("MULTICHIP_PERF "):
                try:
                    _fold_numeric(metrics, markers,
                                  json.loads(line[len("MULTICHIP_PERF "):]))
                except ValueError:
                    notes.append("unparseable MULTICHIP_PERF line")

    prov = data.get("provenance")
    if prov is None and parsed:
        prov = (parsed.get("extra") or {}).get("provenance")
    if not isinstance(prov, dict) or not prov.get("key"):
        # fail OPEN into a quarantine group, visibly: an unstamped snapshot
        # is never compared against either real series
        notes.append("no structured provenance block — grouped as 'unknown' "
                     "(backfill it or re-run bench on a stamped tree)")
        prov = {"key": "unknown", "verified": False}
    return Snapshot(path=path, family=family, round=rnd,
                    key=str(prov["key"]), verified=bool(prov.get("verified")),
                    metrics=metrics, invalid_markers=markers, notes=notes)


def load_all(root: str) -> List[Snapshot]:
    paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))
                   + glob.glob(os.path.join(root, "MULTICHIP_r*.json")))
    if not paths:
        raise SnapshotError(f"no BENCH_r*/MULTICHIP_r* snapshots under {root}")
    return [load_snapshot(p) for p in paths]


def group_snapshots(snaps: List[Snapshot]
                    ) -> Dict[Tuple[str, str], List[Snapshot]]:
    groups: Dict[Tuple[str, str], List[Snapshot]] = {}
    for s in snaps:
        groups.setdefault((s.family, s.key), []).append(s)
    for series in groups.values():
        series.sort(key=lambda s: s.round)
    return groups


def _rule_for(key: str, verified: bool):
    """(direction, tolerance) when ``key`` is gated for this provenance,
    else None. direction: +1 higher-better, -1 lower-better."""
    for table, direction in ((ANALYTIC_LOWER_BETTER, -1),
                             (RATIO_HIGHER_BETTER, +1),
                             (RATIO_LOWER_BETTER, -1)):
        if key in table:
            return direction, table[key]
    if verified:
        if key in ABS_HIGHER_BETTER:
            return +1, ABS_HIGHER_BETTER[key]
        if key in ABS_LOWER_BETTER:
            return -1, ABS_LOWER_BETTER[key]
    return None


def check_regressions(series: List[Snapshot]) -> List[dict]:
    """Tracked-key regressions across CONSECUTIVE metric-bearing snapshots
    of one provenance group (a key absent on either side is skipped — new
    keys cannot regress, honestly-refused keys do not false-fail)."""
    out: List[dict] = []
    withm = [s for s in series if s.metrics]
    for prev, cur in zip(withm, withm[1:]):
        for key, new in sorted(cur.metrics.items()):
            if key not in prev.metrics:
                continue
            rule = _rule_for(key, cur.verified and prev.verified)
            if rule is None:
                continue
            direction, tol = rule
            old = prev.metrics[key]
            bad = (new < old * (1 - tol) if direction > 0
                   else new > old * (1 + tol))
            if bad:
                out.append({
                    "key": key, "group": cur.key, "family": cur.family,
                    "rounds": [prev.round, cur.round],
                    "previous": old, "current": new,
                    "tolerance": tol,
                    "direction": "higher-better" if direction > 0
                    else "lower-better",
                })
    return out


def render(groups: Dict[Tuple[str, str], List[Snapshot]]) -> str:
    lines: List[str] = []
    for (family, key), series in sorted(groups.items()):
        rounds = [s.round for s in series]
        verified = all(s.verified for s in series)
        lines.append(f"== {family} :: {key} "
                     f"({'verified' if verified else 'unverified'}) — "
                     f"rounds {rounds}")
        keys = sorted({k for s in series for k in s.metrics})
        for k in keys:
            vals = " ".join(
                f"{s.metrics[k]:>10.4g}" if k in s.metrics else f"{'—':>10}"
                for s in series)
            gated = _rule_for(k, verified)
            tag = (" [gated]" if gated else "")
            lines.append(f"  {k:<42}{vals}{tag}")
        for s in series:
            for k, msg in sorted(s.invalid_markers.items()):
                lines.append(f"  note r{s.round:02d}: {k}: {msg}")
            for n in s.notes:
                lines.append(f"  note r{s.round:02d}: {n}")
        lines.append("")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=REPO,
                    help="directory holding the snapshots (default: repo)")
    ap.add_argument("--ci", action="store_true",
                    help="exit 1 when a tracked key regresses vs the last "
                         "same-provenance snapshot beyond its tolerance")
    ap.add_argument("--json", default=None,
                    help="also write the grouped report as JSON")
    args = ap.parse_args(argv)

    try:
        snaps = load_all(args.dir)
    except SnapshotError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 2
    groups = group_snapshots(snaps)
    print(render(groups))

    regressions: List[dict] = []
    for series in groups.values():
        regressions += check_regressions(series)
    for r in regressions:
        print(f"REGRESSION [{r['family']} :: {r['group']}] {r['key']}: "
              f"r{r['rounds'][0]:02d} {r['previous']:g} -> "
              f"r{r['rounds'][1]:02d} {r['current']:g} "
              f"({r['direction']}, tol {r['tolerance']:.0%})")

    if args.json:
        report = {
            "groups": {
                f"{family}::{key}": [
                    {"round": s.round, "path": os.path.basename(s.path),
                     "verified": s.verified, "metrics": s.metrics,
                     "invalid_markers": s.invalid_markers, "notes": s.notes}
                    for s in series]
                for (family, key), series in sorted(groups.items())},
            "regressions": regressions,
        }
        with open(args.json, "w") as fh:
            json.dump(report, fh, indent=2)
        print(f"report written to {args.json}")

    if regressions:
        print(f"TRAJECTORY {'FAILED' if args.ci else 'REGRESSED'} "
              f"({len(regressions)} tracked regression(s))")
        return 1 if args.ci else 0
    print("TRAJECTORY OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
