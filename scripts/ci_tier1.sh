#!/usr/bin/env bash
# Tier-1 verify: the ONE command a change must keep green (ROADMAP "Tier-1
# verify" — this script IS that command, so CI, pre-commit hooks, and humans
# run the same thing).
#
#   scripts/ci_tier1.sh                 # full tier-1 suite (CPU mesh)
#   T1_TIMEOUT=1200 scripts/ci_tier1.sh # slower box
#
# Exits with pytest's status; prints DOTS_PASSED=<n> (the count of passing
# test dots) so drivers can compare against the seed count without parsing
# pytest's summary line. The log survives at $T1_LOG for triage.
set -o pipefail

REPO="$(cd "$(dirname "$0")/.." && pwd)"
T1_LOG="${T1_LOG:-/tmp/_t1.log}"
T1_TIMEOUT="${T1_TIMEOUT:-1800}"

rm -f "$T1_LOG"
timeout -k 10 "$T1_TIMEOUT" env JAX_PLATFORMS=cpu \
    python -m pytest "$REPO/tests/" -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee "$T1_LOG"
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$T1_LOG" | tr -cd . | wc -c)"

# ISSUE-9 unchanged-semantics guard: the scale-out serving tests (router /
# engine / KV tiering) must be collected INSIDE the tier-1 marker set — a
# stray `slow` mark or a collection error would silently drop them from the
# gate while the suite above still passes. The main command is untouched;
# this only verifies what it selects.
SERVING_TIER1_TESTS=$(env JAX_PLATFORMS=cpu python -m pytest \
    "$REPO/tests/test_serving_router.py" "$REPO/tests/test_kv_tiering.py" \
    -q -m 'not slow' --collect-only -p no:cacheprovider 2>/dev/null \
    | grep -ac '::' || true)
echo "SERVING_TIER1_TESTS=$SERVING_TIER1_TESTS"
if [ "${SERVING_TIER1_TESTS:-0}" -lt 1 ]; then
    echo "ERROR: scale-out serving tests are not in the tier-1 marker set" >&2
    [ "$rc" -eq 0 ] && rc=1
fi

# ISSUE-10 unchanged-semantics guard: the device-resident megastep exactness
# matrix (tests/test_megastep.py) must stay collected inside the tier-1
# marker set — same rationale as the serving guard above.
MEGASTEP_TIER1_TESTS=$(env JAX_PLATFORMS=cpu python -m pytest \
    "$REPO/tests/test_megastep.py" \
    -q -m 'not slow' --collect-only -p no:cacheprovider 2>/dev/null \
    | grep -ac '::' || true)
echo "MEGASTEP_TIER1_TESTS=$MEGASTEP_TIER1_TESTS"
if [ "${MEGASTEP_TIER1_TESTS:-0}" -lt 1 ]; then
    echo "ERROR: megastep exactness tests are not in the tier-1 marker set" >&2
    [ "$rc" -eq 0 ] && rc=1
fi

# ISSUE-11 unchanged-semantics guard: the fault-tolerance suite (injected
# death/corruption/exhaustion recovery, supervision lifecycle) must stay
# collected inside the tier-1 marker set — same rationale as above.
FAULTS_TIER1_TESTS=$(env JAX_PLATFORMS=cpu python -m pytest \
    "$REPO/tests/test_faults.py" \
    -q -m 'not slow' --collect-only -p no:cacheprovider 2>/dev/null \
    | grep -ac '::' || true)
echo "FAULTS_TIER1_TESTS=$FAULTS_TIER1_TESTS"
if [ "${FAULTS_TIER1_TESTS:-0}" -lt 1 ]; then
    echo "ERROR: fault-tolerance tests are not in the tier-1 marker set" >&2
    [ "$rc" -eq 0 ] && rc=1
fi

# ISSUE-12 unchanged-semantics guard: the request-tracing suite (span-tree
# continuity across migration/recovery, waterfall reconciliation, exemplar
# exposition) must stay collected inside the tier-1 marker set.
TRACING_TIER1_TESTS=$(env JAX_PLATFORMS=cpu python -m pytest \
    "$REPO/tests/test_tracing.py" \
    -q -m 'not slow' --collect-only -p no:cacheprovider 2>/dev/null \
    | grep -ac '::' || true)
echo "TRACING_TIER1_TESTS=$TRACING_TIER1_TESTS"
if [ "${TRACING_TIER1_TESTS:-0}" -lt 1 ]; then
    echo "ERROR: request-tracing tests are not in the tier-1 marker set" >&2
    [ "$rc" -eq 0 ] && rc=1
fi

# ISSUE-13 unchanged-semantics guard: the multi-tenant overload suite (SLA
# classes, weighted-fair budgets, preemptive priorities, brown-out ladder,
# autoscaler) must stay collected inside the tier-1 marker set.
MULTITENANT_TIER1_TESTS=$(env JAX_PLATFORMS=cpu python -m pytest \
    "$REPO/tests/test_multitenant.py" \
    -q -m 'not slow' --collect-only -p no:cacheprovider 2>/dev/null \
    | grep -ac '::' || true)
echo "MULTITENANT_TIER1_TESTS=$MULTITENANT_TIER1_TESTS"
if [ "${MULTITENANT_TIER1_TESTS:-0}" -lt 1 ]; then
    echo "ERROR: multi-tenant overload tests are not in the tier-1 marker set" >&2
    [ "$rc" -eq 0 ] && rc=1
fi

# ISSUE-14 unchanged-semantics guard: the roofline perf-model suite (model
# vs hand-computed costs, bound classification, unverified-spec refusal,
# trajectory grouping/regression gate) must stay collected inside the
# tier-1 marker set.
PERF_MODEL_TIER1_TESTS=$(env JAX_PLATFORMS=cpu python -m pytest \
    "$REPO/tests/test_perf_model.py" "$REPO/tests/test_perf_trajectory.py" \
    -q -m 'not slow' --collect-only -p no:cacheprovider 2>/dev/null \
    | grep -ac '::' || true)
echo "PERF_MODEL_TIER1_TESTS=$PERF_MODEL_TIER1_TESTS"
if [ "${PERF_MODEL_TIER1_TESTS:-0}" -lt 1 ]; then
    echo "ERROR: roofline perf-model tests are not in the tier-1 marker set" >&2
    [ "$rc" -eq 0 ] && rc=1
fi

# ISSUE-15 unchanged-semantics guard: the KV block-ledger suite (owner-state
# conservation, leak detection/attribution, OOM forensics, the autouse
# teardown audit) must stay collected inside the tier-1 marker set.
MEMLEDGER_TIER1_TESTS=$(env JAX_PLATFORMS=cpu python -m pytest \
    "$REPO/tests/test_memledger.py" \
    -q -m 'not slow' --collect-only -p no:cacheprovider 2>/dev/null \
    | grep -ac '::' || true)
echo "MEMLEDGER_TIER1_TESTS=$MEMLEDGER_TIER1_TESTS"
if [ "${MEMLEDGER_TIER1_TESTS:-0}" -lt 1 ]; then
    echo "ERROR: KV block-ledger tests are not in the tier-1 marker set" >&2
    [ "$rc" -eq 0 ] && rc=1
fi

# ISSUE-16 unchanged-semantics guard: the MoE serving suite (grouped-kernel
# exactness matrix, EP ring vs GSPMD schedule pins, MoE-through-CB token
# identity, config validation) must stay collected inside the tier-1 marker
# set — the full-model MoE e2e file (test_moe.py) is module-level slow, so
# this file is the ONLY tier-1 coverage of the decode fast paths.
MOE_TIER1_TESTS=$(env JAX_PLATFORMS=cpu python -m pytest \
    "$REPO/tests/test_moe_serving.py" \
    -q -m 'not slow' --collect-only -p no:cacheprovider 2>/dev/null \
    | grep -ac '::' || true)
echo "MOE_TIER1_TESTS=$MOE_TIER1_TESTS"
if [ "${MOE_TIER1_TESTS:-0}" -lt 1 ]; then
    echo "ERROR: MoE serving tests are not in the tier-1 marker set" >&2
    [ "$rc" -eq 0 ] && rc=1
fi

# ISSUE-17 unchanged-semantics guard: the disaggregated-pools suite (live
# prefill->decode KV handoff bit-exactness over both channels, headroom
# deferral, mid-handoff death recovery, checksum re-prefill, ledger
# handoff_inflight accounting, per-pool autoscaling, handoff span) must stay
# collected inside the tier-1 marker set.
POOLS_TIER1_TESTS=$(env JAX_PLATFORMS=cpu python -m pytest \
    "$REPO/tests/test_pools.py" \
    -q -m 'not slow' --collect-only -p no:cacheprovider 2>/dev/null \
    | grep -ac '::' || true)
echo "POOLS_TIER1_TESTS=$POOLS_TIER1_TESTS"
if [ "${POOLS_TIER1_TESTS:-0}" -lt 1 ]; then
    echo "ERROR: disaggregated-pools tests are not in the tier-1 marker set" >&2
    [ "$rc" -eq 0 ] && rc=1
fi

# ISSUE-18 unchanged-semantics guard: the self-tuning suite (knob registry
# bounds/gauges, mid-flight bit-exactness, tuner hysteresis / never-worse
# rollback / decision stamping, committed-trace replay determinism +
# reconciliation) must stay collected inside the tier-1 marker set.
TUNER_TIER1_TESTS=$(env JAX_PLATFORMS=cpu python -m pytest \
    "$REPO/tests/test_tuner.py" \
    -q -m 'not slow' --collect-only -p no:cacheprovider 2>/dev/null \
    | grep -ac '::' || true)
echo "TUNER_TIER1_TESTS=$TUNER_TIER1_TESTS"
if [ "${TUNER_TIER1_TESTS:-0}" -lt 1 ]; then
    echo "ERROR: self-tuning tests are not in the tier-1 marker set" >&2
    [ "$rc" -eq 0 ] && rc=1
fi

# ISSUE-19 unchanged-semantics guard: the kernel-floor suite (AMLA-vs-
# multiply closeness matrix + opt-outs, KV-length-split bit-equality and
# auto-select pins) and the extended megastep file (spec/mixed megastep
# exactness) must stay collected inside the tier-1 marker set — they are
# the ONLY fast coverage of the paged decode hot-loop rewrites
# (test_paged_decode.py is module-level slow).
KERNELS_TIER1_TESTS=$(env JAX_PLATFORMS=cpu python -m pytest \
    "$REPO/tests/test_kernel_floor.py" "$REPO/tests/test_megastep.py" \
    -q -m 'not slow' --collect-only -p no:cacheprovider 2>/dev/null \
    | grep -ac '::' || true)
echo "KERNELS_TIER1_TESTS=$KERNELS_TIER1_TESTS"
if [ "${KERNELS_TIER1_TESTS:-0}" -lt 20 ]; then
    echo "ERROR: kernel-floor/megastep tests fell out of the tier-1 marker set" >&2
    [ "$rc" -eq 0 ] && rc=1
fi

# ISSUE-20 unchanged-semantics guard: the cluster KV store suite (content-
# hash dedup/refcounting under concurrent publish, cross-replica pull
# bit-exactness across KV dtypes, corrupt-entry drop + re-prefill,
# mid-pull death recovery with a clean ledger, teardown audits) must stay
# collected inside the tier-1 marker set — it is the only coverage of the
# fleet rung under the host tier.
CLUSTERKV_TIER1_TESTS=$(env JAX_PLATFORMS=cpu python -m pytest \
    "$REPO/tests/test_cluster_kv.py" \
    -q -m 'not slow' --collect-only -p no:cacheprovider 2>/dev/null \
    | grep -ac '::' || true)
echo "CLUSTERKV_TIER1_TESTS=$CLUSTERKV_TIER1_TESTS"
if [ "${CLUSTERKV_TIER1_TESTS:-0}" -lt 10 ]; then
    echo "ERROR: cluster KV store tests fell out of the tier-1 marker set" >&2
    [ "$rc" -eq 0 ] && rc=1
fi
exit "$rc"
