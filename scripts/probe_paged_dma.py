"""Probe: pure DMA cost of the v2 paged-attend block streaming.

Same grid, BlockSpecs, and clamped index maps as _paged_attend_kernel (bb=4,
kb=4), but the body only touches one element per fetched block — so the
measured time is the cost of STREAMING the blocks through the grid, without
the dots/masks/flash updates. Compare with the full kernel's time to split
DMA vs compute, at bf16 and fp8.
"""

import functools
import shutil
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

B, HKV, D, BS, MB, L = 64, 8, 128, 128, 8, 8
NB = B * MB + 8
KB, BB = 4, 4
CELLS = MB // KB


def main():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rng = np.random.default_rng(0)
    positions = jnp.asarray(rng.integers(200, 900, size=(B,)), dtype=jnp.int32)
    perm = rng.permutation(NB)[: B * MB].reshape(B, MB)
    bt = jnp.asarray(perm, dtype=jnp.int32)

    def kv_index_map(j, g):
        def index_map(bi, ci, pos, lidx, btab):
            row = bi * BB + j
            gg = ci * KB + g
            last_live = pos[row] // BS
            gg = jnp.minimum(gg, last_live)
            return (lidx[0], btab[row, gg], 0, 0, 0)

        return index_map

    def body(pos_ref, lidx_ref, bt_ref, *refs):
        kv_refs = refs[:-1]
        o_ref = refs[-1]
        acc = jnp.zeros((8, 128), jnp.float32)
        for r in kv_refs:
            # touch a sublane-aligned tile so the block fetch isn't elided
            acc = acc + r[0, 0, :, :8, :].astype(jnp.float32).sum(axis=1)
        o_ref[...] = acc[None]

    for dtype_name in ("bfloat16", "float8_e4m3fn"):
        dt = jnp.dtype(dtype_name)
        kc = (jnp.asarray(rng.normal(size=(L, NB, HKV, BS, D)),
                          dtype=jnp.bfloat16) * 0.3).astype(dt)
        vc = (jnp.asarray(rng.normal(size=(L, NB, HKV, BS, D)),
                          dtype=jnp.bfloat16) * 0.3).astype(dt)
        kv_specs = []
        for j in range(BB):
            for g in range(KB):
                kv_specs.append(pl.BlockSpec((1, 1, HKV, BS, D),
                                             kv_index_map(j, g)))
                kv_specs.append(pl.BlockSpec((1, 1, HKV, BS, D),
                                             kv_index_map(j, g)))
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(B // BB, CELLS),
            in_specs=kv_specs,
            out_specs=pl.BlockSpec((1, 8, 128), lambda bi, ci, *_: (bi, 0, 0)),
        )
        fn = pl.pallas_call(
            body, grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B // BB, 8, 128), jnp.float32))

        @jax.jit
        def run(pos, btab, kc, vc):
            return fn(pos, jnp.asarray([3], jnp.int32), btab,
                      *([kc, vc] * (KB * BB)))

        jax.block_until_ready(run(positions, bt, kc, vc))
        d = f"/tmp/probe_dma_{dtype_name}"
        shutil.rmtree(d, ignore_errors=True)
        iters = 30
        with jax.profiler.trace(d):
            for _ in range(iters):
                jax.block_until_ready(run(positions, bt, kc, vc))
        sys.path.insert(0, "/root/repo/scripts")
        from probe_paged_perf import xplane_table

        tot = xplane_table(d)
        dev_us = sum(ms for n, ms in tot.items()
                     if n.startswith("jit_run")) / iters * 1e3
        print(f"dma_only {dtype_name:14s} {dev_us:8.1f} us/call", flush=True)


if __name__ == "__main__":
    main()
