"""Probe v2: scan-consumed stacked int8 weights — weights passed as EXPLICIT jit
arguments (closure constants get shipped to axon's remote-compile service, which
is why v1 spent 10+ min per variant compile)."""
import sys, time
import numpy as np
import jax
import jax.numpy as jnp

L, H, I, B = 8, 4096, 14336, 64

def run(name, fn, *args):
    """Device-timed via profiler xplane: wall timing is invalid on the axon
    remoting platform (unfetched results are lazily/not executed), and each
    blocking fetch pays a ~100 ms tunnel round trip that would swamp the
    kernel time."""
    import shutil
    sys.path.insert(0, "/root/repo")
    from neuronx_distributed_inference_tpu.utils import profiling as prof

    fn_j = jax.jit(fn)
    t0 = time.perf_counter()
    jax.block_until_ready(fn_j(*args))
    compile_s = time.perf_counter() - t0
    d = f"/tmp/probe_trace_{name.split()[0]}"
    shutil.rmtree(d, ignore_errors=True)
    n = 5
    with prof.trace(d):
        for _ in range(n):
            jax.block_until_ready(fn_j(*args))
    dev = prof.device_time_ms(d, "jit_")
    dt = dev / n if dev is not None else float("nan")
    print(f"{name:12s} {dt:7.2f} ms/iter device  (compile {compile_s:.1f}s)",
          flush=True)

def body_mm(h, q, g, d):
    a = h @ q.astype(h.dtype)
    gg = a @ g.astype(h.dtype)
    return jnp.maximum(gg, 0) @ d.astype(h.dtype)

def A(x, wq, wg, wd):          # scan xs (today's path)
    def body(h, xs):
        return body_mm(h, *xs), ()
    h, _ = jax.lax.scan(body, x, (wq, wg, wd))
    return h

def C(x, wqT, wgT, wdT):       # pre-transposed stacks, contract on last axis
    def body(h, xs):
        qT, gT, dT = xs
        a = jax.lax.dot_general(h, qT.astype(h.dtype), (((1,), (1,)), ((), ())))
        g = jax.lax.dot_general(a, gT.astype(h.dtype), (((1,), (1,)), ((), ())))
        return jax.lax.dot_general(jnp.maximum(g, 0), dT.astype(h.dtype),
                                   (((1,), (1,)), ((), ()))), ()
    h, _ = jax.lax.scan(body, x, (wqT, wgT, wdT))
    return h

def D(x, wq, wg, wd):          # int8 x int8 MXU dots (activation quant)
    def q8(v):
        s = jnp.max(jnp.abs(v.astype(jnp.float32)), -1, keepdims=True) / 127.
        s = jnp.maximum(s, 1e-8)
        return jnp.clip(jnp.round(v.astype(jnp.float32) / s), -127, 127
                        ).astype(jnp.int8), s
    def mm8(v, w):
        vq, s = q8(v)
        y = jax.lax.dot_general(vq, w, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.int32)
        return (y.astype(jnp.float32) * s).astype(jnp.bfloat16)
    def body(h, xs):
        q, g, d = xs
        return mm8(jnp.maximum(mm8(mm8(h, q), g), 0), d), ()
    h, _ = jax.lax.scan(body, x, (wq, wg, wd))
    return h

def main():
    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    wq = jnp.asarray(rng.integers(-127, 128, (L, H, H), dtype=np.int8))
    wg = jnp.asarray(rng.integers(-127, 128, (L, H, I), dtype=np.int8))
    wd = jnp.asarray(rng.integers(-127, 128, (L, I, H), dtype=np.int8))
    jax.block_until_ready((wq, wg, wd))
    print(f"transfer {time.perf_counter()-t0:.1f}s", flush=True)
    wqT = jnp.transpose(wq, (0, 2, 1)).copy()
    wgT = jnp.transpose(wg, (0, 2, 1)).copy()
    wdT = jnp.transpose(wd, (0, 2, 1)).copy()
    x = jnp.asarray(rng.standard_normal((B, H)), jnp.bfloat16)
    run("A xs-slices", A, x, wq, wg, wd)
    run("C pre-T", C, x, wqT, wgT, wdT)
    run("D int8dot", D, x, wq, wg, wd)
    wbytes = wq.size + wg.size + wd.size
    print(f"floor {wbytes/819e9*1000:.2f} ms ({wbytes/1e9:.2f} GB)", flush=True)

if __name__ == "__main__":
    main()
