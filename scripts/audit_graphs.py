#!/usr/bin/env python
"""Graph-contract audit driver: lint the package, exercise the serving fleet,
statically verify every registered dispatch, and emit a JSON report.

Exit code 0 iff no unwaived violation — wire it as a CI gate or pre-commit
hook. Waived findings are printed (suppression is visible, never silent).

Usage:
    python scripts/audit_graphs.py                      # full fleet + lint
    python scripts/audit_graphs.py --scopes cb_paged spec
    python scripts/audit_graphs.py --lint-only          # AST pass only (fast)
    python scripts/audit_graphs.py --changed            # pre-commit fast mode:
                                                        #   lint changed files,
                                                        #   audit touched scopes
    python scripts/audit_graphs.py --canaries           # also run the pinned
                                                        #   byte/collective
                                                        #   budget canaries
    python scripts/audit_graphs.py -o report.json
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import _tpu_test_bootstrap  # noqa: F401,E402  (side effect: 8-device CPU mesh)

from neuronx_distributed_inference_tpu.analysis import lint  # noqa: E402

# which audit scopes a changed runtime file invalidates (--changed mode).
# Scopes must cover DEPENDENTS, not just the file's own dispatches:
# application.py is absent on purpose — every engine owns or subclasses
# TpuModelForCausalLM, so touching it re-runs the whole fleet (unmapped →
# broad); speculation.py's accept/commit helpers are imported by the CB
# runner and every spec-family engine; eagle.py's draft_args_from_target
# builds the eagle3 scope's draft.
_FILE_SCOPES = {
    "runtime/continuous_batching.py": ["cb_dense", "cb_paged", "cb_mixed",
                                       "cb_megastep", "cb_mixed_megastep",
                                       "cb_spec", "cb_spec_megastep",
                                       "cb_eagle", "serving_tier"],
    # ISSUE-10 megastep: the token ring is traced only into the while_loop
    # megastep dispatch; an edit re-audits that scope. block_kvcache's
    # device_slot_advance ALSO feeds the megastep, but block_kvcache stays
    # deliberately unmapped (its write/read helpers trace into every paged
    # dispatch — unmapped fails closed to the full fleet).
    "ops/token_ring.py": ["cb_megastep", "cb_mixed_megastep",
                          "cb_spec_megastep"],
    # ISSUE-19 flash-decode registration: the standalone flash.* entry points
    # trace only into their own dispatches (the fleet's tiny apps never set
    # decode_kernel_enabled, so no CB graph imports them at trace time) — an
    # edit re-audits the flash_decode scope. paged_decode.py stays
    # deliberately UNMAPPED: its kernels trace into every paged dispatch AND
    # flash_decode imports its helpers, so it fails closed to the full fleet.
    "ops/flash_decode.py": ["flash_decode"],
    "runtime/speculation.py": ["spec", "cb_spec", "cb_eagle", "eagle",
                               "eagle3", "medusa"],
    "runtime/eagle.py": ["eagle", "cb_eagle", "eagle3"],
    "runtime/eagle3.py": ["eagle3"],
    "runtime/medusa.py": ["medusa"],
    "runtime/image_to_text.py": ["mm"],
    # ISSUE-7 telemetry split: the device carry's tick helpers are traced
    # INTO every CB dispatch kind (continuous_batching threads the carry
    # through plain/spec/mixed/insert/eagle), so a carry-touching edit
    # re-audits the full CB fleet; the host-side observability modules
    # (metrics/flight_recorder/slo) never enter a graph — lint-only ([]
    # audits nothing, which is exactly their graph footprint).
    "utils/device_telemetry.py": ["cb_dense", "cb_paged", "cb_mixed",
                                  "cb_megastep", "cb_mixed_megastep",
                                  "cb_spec", "cb_spec_megastep", "cb_eagle",
                                  "serving_tier"],
    "utils/metrics.py": [],
    "utils/flight_recorder.py": [],
    "utils/slo.py": [],
    # ISSUE-9 engine/frontend split: the router and engine are host-side
    # placement/admission logic over runner APIs — they never enter a graph
    # (lint-only); the KV tier DOES touch cache operands (its readmit scatter
    # is a registered dispatch and its spill gathers read the live pool), so
    # a tiering edit re-audits its own scope plus the paged CB fleet whose
    # caches it shares buffers with. Any OTHER serving/ file stays unmapped
    # and fails closed to the full fleet (test_graph_contracts pins this).
    "serving/__init__.py": [],
    "serving/engine.py": [],
    "serving/router.py": [],
    # ISSUE-11 fault tolerance: the injector/supervisor are host-side seam
    # wrappers over replica APIs — they never enter a graph (lint-only)
    "serving/faults.py": [],
    # ISSUE-12 request tracing: pure post-processing over already-recorded
    # telemetry events — never enters a graph (lint-only)
    "serving/tracing.py": [],
    # ISSUE-13 overload control plane: SLA classes are plain config objects
    # and the autoscaler drives router APIs (add/drain/remove_replica) —
    # neither enters a graph (lint-only). The weighted-fair budget split
    # itself lives in continuous_batching.py, whose row above already
    # re-audits the full CB fleet (cb_mixed included) on any edit.
    "serving/sla.py": [],
    "serving/autoscaler.py": [],
    # ISSUE-18 self-tuning: the knob registry, online controller, and
    # what-if replayer are pure host-side control plane — knobs set plain
    # Python attributes that are DYNAMIC operands of already-audited
    # executables (megastep_k feeds the while_loop trip count as an array
    # argument, never a retrace), the tuner reads telemetry and calls
    # registry setters, and the replayer re-drives router.submit/step from a
    # journal. None enters a graph (lint-only); the knob-consuming schedule
    # logic lives in continuous_batching.py, whose row above already
    # re-audits the full CB fleet on any edit.
    "serving/knobs.py": [],
    "serving/tuner.py": [],
    "serving/replay.py": [],
    # ISSUE-15 KV block ledger: host-side bookkeeping over allocator seams
    # (instance-level wrappers, the fault-injector idiom) — audits the
    # allocator's dicts, never enters a graph (lint-only). The runner-side
    # integration lives in continuous_batching.py, whose row above already
    # re-audits the full CB fleet on any edit.
    "serving/memledger.py": [],
    # ISSUE-14 roofline model + provenance: offline analysis over the
    # ALREADY-captured dispatch examples and compiled cost analysis (the
    # model lowers AOT, it never traces a new dispatch), and the provenance
    # fingerprint is pure host-side probing — lint-only. Any OTHER new
    # analysis/ or utils/ file stays unmapped and fails closed.
    "analysis/perf_model.py": [],
    "utils/provenance.py": [],
    "serving/kv_tiering.py": ["serving_tier", "cb_paged", "cb_mixed",
                              "cb_megastep", "cb_mixed_megastep", "cb_spec",
                              "cb_spec_megastep", "cb_eagle"],
    # ISSUE-20 cluster KV store: the fleet rung under the host tier is pure
    # host-side content-addressed storage (numpy payloads + a transport
    # seam) — cluster pulls ride the EXISTING audited cb.paged.tier_readmit
    # scatter via kv_tiering's restore path, so no graph is traced from this
    # file and it is lint-only. Widening the readmit call pattern itself
    # lands in kv_tiering.py / continuous_batching.py, which re-audit the
    # paged scopes above.
    "serving/cluster_kv.py": [],
    # ISSUE-17 disaggregated pools: the PoolManager is host-side handoff
    # orchestration over runner session APIs (handoff_open/receive/commit) —
    # it never enters a graph itself, but it DRIVES the bucketed
    # cb.paged.kv_handoff scatter's call pattern (chunk staging cadence), so
    # an edit re-audits the serving_tier scope that exercises a live
    # prefill->decode handoff end to end.
    "serving/pools.py": ["serving_tier"],
    # ISSUE-16 MoE serving: the grouped decode kernel and EP ring trace only
    # into MoE-arch graphs — the llama fleet never imports them — so an edit
    # re-audits the moe scope (Mixtral paged CB runner + the standalone
    # grouped/dense dispatch kinds). overlap.py ALSO hosts the TP overlap
    # templates traced into every dense-layer graph, so it re-audits the full
    # CB fleet on top of moe.
    "ops/moe.py": ["moe"],
    "parallel/overlap.py": ["moe", "cb_dense", "cb_paged", "cb_mixed",
                            "cb_megastep", "cb_mixed_megastep", "cb_spec",
                            "cb_spec_megastep", "cb_eagle", "serving_tier"],
}
# any other package .py change (application.py, models/modules/ops/parallel/
# analysis/config/utils/new files) re-runs the whole fleet — see
# _scopes_for_changes


def _changed_files():
    out = subprocess.run(
        ["git", "diff", "--name-only", "HEAD"],
        cwd=REPO, capture_output=True, text=True, check=False).stdout
    staged = subprocess.run(
        ["git", "diff", "--name-only", "--cached", "HEAD"],
        cwd=REPO, capture_output=True, text=True, check=False).stdout
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=REPO, capture_output=True, text=True, check=False).stdout
    return sorted({f for f in (out + staged + untracked).splitlines()
                   if f.strip()})


def _scopes_for_changes(files):
    """None = run the whole fleet. Fail CLOSED: any package .py change that
    is not specifically mapped to scopes (config.py, utils/, a brand-new
    runtime module, ...) re-runs everything — an unmapped file must widen the
    audit, never shrink it."""
    pkg = "neuronx_distributed_inference_tpu/"
    scopes = set()
    broad = False
    for f in files:
        if not f.startswith(pkg) or not f.endswith(".py"):
            continue
        rel = f[len(pkg):]
        if rel in _FILE_SCOPES:
            scopes.update(_FILE_SCOPES[rel])
        else:
            broad = True
    return None if broad else sorted(scopes)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scopes", nargs="*", default=None,
                    help="fleet scopes to audit (default: all)")
    ap.add_argument("--lint-only", action="store_true",
                    help="run only the AST lint pass")
    ap.add_argument("--changed", action="store_true",
                    help="fast pre-commit mode: lint only files changed vs "
                         "HEAD, audit only the scopes those files touch")
    ap.add_argument("--canaries", action="store_true",
                    help="also run the geometry-pinned byte/collective "
                         "budget canaries (slower)")
    ap.add_argument("-o", "--out", default=None,
                    help="write the JSON report here (default: stdout only)")
    args = ap.parse_args(argv)

    report = {"lint": [], "graph": None, "canaries": None, "notes": []}
    failed = False

    # ---- lint pass ---------------------------------------------------------
    # one snapshot of the changed-file list: lint and scope selection must
    # agree even if the worktree moves under us
    changed = _changed_files() if args.changed else []
    if args.changed:
        pkg_files = [os.path.join(REPO, f) for f in changed
                     if f.startswith("neuronx_distributed_inference_tpu/")
                     and f.endswith(".py") and os.path.exists(
                         os.path.join(REPO, f))]
        findings = lint.lint_paths(pkg_files) if pkg_files else []
        report["notes"].append(f"--changed: linted {len(pkg_files)} files")
    else:
        findings = lint.lint_package()
    report["lint"] = [
        {"rule": f.rule, "path": f.path, "line": f.line, "msg": f.msg,
         "status": f.status, "reason": f.reason} for f in findings]
    for f in findings:
        print(("FAIL " if f.violating else "ok   ") + str(f))
        failed |= f.violating

    # ---- graph audit -------------------------------------------------------
    scopes = args.scopes
    if args.changed and scopes is None:
        scopes = _scopes_for_changes(changed)
        report["notes"].append(f"--changed: auditing scopes {scopes}")
    if not args.lint_only and scopes != []:
        from neuronx_distributed_inference_tpu.analysis import harness
        from neuronx_distributed_inference_tpu.analysis.auditor import audit

        units, notes = harness.build_fleet_units(scopes)
        report["notes"] += notes
        rep = audit(units)
        report["graph"] = rep.to_dict()
        for f in rep.findings:
            if f.status in ("pass", "skipped"):
                continue
            tag = "FAIL " if f.violating else "ok   "
            print(f"{tag}{f.unit}: [{f.check}] {f.status} {f.detail}")
        for name in sorted(rep.measurements):
            m = rep.measurements[name]
            print(f"meas {name}: {m.bytes_per_step:.3g} B/step over "
                  f"{m.steps} steps, collectives={m.collective_counts}")
        failed |= not rep.ok

    # ---- pinned canaries ---------------------------------------------------
    if args.canaries and not args.lint_only:
        from neuronx_distributed_inference_tpu.analysis import canaries
        from neuronx_distributed_inference_tpu.analysis.auditor import audit

        crep = audit(*canaries.build_canary_units())
        canaries.clear_caches()           # reports are data; drop the fleets
        report["canaries"] = crep.to_dict()
        for f in crep.findings:
            if f.status in ("pass", "skipped"):
                continue
            tag = "FAIL " if f.violating else "ok   "
            print(f"{tag}{f.unit}: [{f.check}] {f.status} {f.detail}")
        failed |= not crep.ok

    for note in report["notes"]:
        print("note:", note)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=2)
        print("report written to", args.out)
    print("AUDIT", "FAILED" if failed else "OK")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
