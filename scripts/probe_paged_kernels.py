"""Probe: isolated paged decode attention at serving shapes — ours vs the
upstream jax ragged_paged_attention structure vs the XLA gather path.

VERDICT r4 #2: our Pallas ragged attend costs ~0.42 ms/layer vs the dense
attend's ~0.05 at bs=64; ruled out so far: fp8 casts, sampling, block size,
bb-row batching. This probe quantifies, at the exact serving shapes
(B=64, Hq=32, Hkv=8, D=128, BS=128, live ~200-900 of 1024):
  1. ours            — ops/paged_decode.paged_decode_attention_stacked
  2. upstream        — jax.experimental.pallas.ops.tpu.ragged_paged_attention
                       (combined-KV page layout, manual double-buffered DMA)
  3. gather          — XLA take() through the block table + jnp attend
Numerics of the layout conversion are validated against ours (bf16).

Run on TPU:  PYTHONPATH=/root/repo:/root/.axon_site python scripts/probe_paged_kernels.py
"""

import functools
import os
import shutil
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

B, HQ, HKV, D, BS, MB, L = 64, 32, 8, 128, 128, 8, 8
SEQ = MB * BS
NB = B * MB + 8          # physical pool blocks per layer


def build_inputs(kv_dtype, seed=0):
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, HQ, 1, D)), dtype=jnp.bfloat16) * 0.3
    positions = jnp.asarray(rng.integers(200, 900, size=(B,)), dtype=jnp.int32)
    # each row owns MB distinct physical blocks (shuffled, vLLM-style)
    perm = rng.permutation(NB)[: B * MB].reshape(B, MB)
    bt = jnp.asarray(perm, dtype=jnp.int32)
    kc = jnp.asarray(rng.normal(size=(L, NB, HKV, BS, D)), dtype=jnp.bfloat16) * 0.3
    vc = jnp.asarray(rng.normal(size=(L, NB, HKV, BS, D)), dtype=jnp.bfloat16) * 0.3
    kc = kc.astype(kv_dtype)
    vc = vc.astype(kv_dtype)
    return q, positions, bt, kc, vc


def to_combined_pages(kc, vc):
    """(L, NB, HKV, BS, D) K/V -> (L*NB, BS, 2*HKV, D) interleaved combined
    pages (upstream layout: K at even combined heads, V at odd)."""
    import jax.numpy as jnp

    k = kc.reshape(L * NB, HKV, BS, D).transpose(0, 2, 1, 3)   # (P, BS, HKV, D)
    v = vc.reshape(L * NB, HKV, BS, D).transpose(0, 2, 1, 3)
    kv = jnp.stack([k, v], axis=3).reshape(L * NB, BS, 2 * HKV, D)
    return kv


def device_ms(fn, args, iters=30, tag=""):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    d = f"/tmp/probe_pk_{tag}"
    shutil.rmtree(d, ignore_errors=True)
    t0 = time.perf_counter()
    with jax.profiler.trace(d):
        for _ in range(iters):
            jax.block_until_ready(fn(*args))
    wall = (time.perf_counter() - t0) / iters * 1e3
    sys.path.insert(0, "/root/repo/scripts")
    from probe_paged_perf import xplane_table

    tot = xplane_table(d)
    dev = sum(ms for name, ms in tot.items() if name.startswith("jit_")) / iters * 1e3
    top = sorted(tot.items(), key=lambda kv: -kv[1])[:3]
    return wall, dev, [(n[:60], ms / iters * 1e3) for n, ms in top]


def main():
    import jax
    import jax.numpy as jnp

    print("devices:", jax.devices(), flush=True)
    from jax.experimental.pallas.ops.tpu.ragged_paged_attention import (
        ragged_paged_attention)

    from neuronx_distributed_inference_tpu.ops.paged_decode import (
        paged_decode_attention_stacked)

    layer = jnp.asarray(3, dtype=jnp.int32)

    @jax.jit
    def ours(q, kc, vc, pos, bt):
        return paged_decode_attention_stacked(q, kc, vc, pos, layer, bt,
                                              variant=2)

    @jax.jit
    def ours_v3(q, kc, vc, pos, bt):
        return paged_decode_attention_stacked(q, kc, vc, pos, layer, bt,
                                              variant=3)

    @functools.partial(jax.jit, static_argnames=())
    def upstream(q, kv_pages, pos, bt):
        q2 = q[:, :, 0, :]                                   # (B, HQ, D)
        kv_lens = pos + 1
        page_indices = bt + 3 * NB                           # layer 3's pages
        cu = jnp.arange(B + 1, dtype=jnp.int32)
        return ragged_paged_attention(
            q2, kv_pages, kv_lens, page_indices, cu,
            jnp.asarray([B], dtype=jnp.int32), sm_scale=D ** -0.5)

    @jax.jit
    def gather(q, kc, vc, pos, bt):
        kl = kc[3]                                           # (NB, HKV, BS, D)
        vl = vc[3]
        ka = kl[bt].transpose(0, 2, 1, 3, 4).reshape(B, HKV, SEQ, D)
        va = vl[bt].transpose(0, 2, 1, 3, 4).reshape(B, HKV, SEQ, D)
        ka = ka.astype(q.dtype)
        va = va.astype(q.dtype)
        qg = q.reshape(B, HKV, HQ // HKV, D)
        s = jnp.einsum("bhrd,bhsd->bhrs", qg, ka,
                       preferred_element_type=jnp.float32) * D ** -0.5
        mask = jnp.arange(SEQ)[None, None, None, :] <= pos[:, None, None, None]
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        o = jnp.einsum("bhrs,bhsd->bhrd", p, va)
        return o.reshape(B, HQ, 1, D)

    for kv_dtype_name in ("bfloat16", "float8_e4m3fn"):
        kv_dtype = jnp.dtype(kv_dtype_name)
        q, pos, bt, kc, vc = build_inputs(kv_dtype)
        kv_pages = to_combined_pages(kc, vc)
        print(f"\n=== kv dtype {kv_dtype_name} ===", flush=True)

        # numerics: upstream vs ours (both flash; compare to gather fp32-ish)
        o_ours = np.asarray(ours(q, kc, vc, pos, bt))
        try:
            o_up = np.asarray(upstream(q, kv_pages, pos, bt))       # (B, HQ, D)
            o_up = o_up.reshape(B, HQ, 1, D)
            err = np.max(np.abs(o_ours.astype(np.float32)
                                - o_up.astype(np.float32)))
            print(f"upstream vs ours max abs err: {err:.4f}", flush=True)
        except Exception as e:
            print(f"upstream FAILED: {type(e).__name__}: {e}", flush=True)
            o_up = None

        o_v3 = np.asarray(ours_v3(q, kc, vc, pos, bt))
        err3 = np.max(np.abs(o_ours.astype(np.float32) - o_v3.astype(np.float32)))
        print(f"v3 vs v2 max abs err: {err3:.5f}", flush=True)

        for tag, fn, args in (
                ("v2", ours, (q, kc, vc, pos, bt)),
                ("v3", ours_v3, (q, kc, vc, pos, bt)),
                ("upstream", upstream, (q, kv_pages, pos, bt)),
                ("gather", gather, (q, kc, vc, pos, bt))):
            try:
                wall, dev, top = device_ms(fn, args, tag=f"{tag}_{kv_dtype_name}")
                print(f"{tag:9s} wall {wall:7.3f} ms  device(us) {dev:7.1f}",
                      flush=True)
                for n, ms in top:
                    print(f"          {ms:7.1f} us  {n}", flush=True)
            except Exception as e:
                print(f"{tag:9s} FAILED: {type(e).__name__}: {e}", flush=True)


if __name__ == "__main__":
    main()
