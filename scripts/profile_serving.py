#!/usr/bin/env python
"""Per-dispatch device-time attribution for the continuous-batching serving
loop: run a short serving window under a jax.profiler trace, parse the xplane
dump, and report per-step-kind device time vs host span — the dispatch-floor
decomposition (`dispatch_gap_ms`) ROADMAP open item 2 targets.

Drives a tiny (CPU-capable) runner by default so the tool is runnable
anywhere; on TPU hardware the same flow attributes the real device plane
(the default ``--plane tpu``; pass ``--plane ""`` to scan every plane, which
is how the CPU backend's host plane is read).

Usage:
    python scripts/profile_serving.py                       # plain paged CB
    python scripts/profile_serving.py --mode mixed --plane ""
    python scripts/profile_serving.py --mode spec -o timing.json

Output: a JSON report {timing: {kind: {device_ms, host_ms, dispatch_gap_ms,
dispatches, ...}}, device_counters, stats_lite} — the same attribution lands
on the runner's metrics registry (``serving_device_time_ms{kind=}`` /
``serving_dispatch_gap_ms{kind=}``) and in ``runner.stats()["timing"]``.
"""

import argparse
import json
import os
import shutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import _tpu_test_bootstrap  # noqa: F401,E402  (side effect: 8-device CPU mesh)


def build_runner(mode: str):
    from neuronx_distributed_inference_tpu.analysis.harness import (_prompts,
                                                                    _tiny_app)
    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)

    if mode == "spec":
        from neuronx_distributed_inference_tpu.analysis.harness import TINY_HF

        target = _tiny_app(paged=True, cb=True, seed=0)
        draft_hf = dict(TINY_HF, hidden_size=32, intermediate_size=64,
                        num_hidden_layers=1, num_attention_heads=2,
                        num_key_value_heads=2)
        draft = _tiny_app(paged=True, cb=True, hf=draft_hf, seed=1)
        runner = ContinuousBatchingRunner(target, draft=draft,
                                          speculation_length=4, spec_chunk=2,
                                          telemetry=True)
    elif mode == "mixed":
        app = _tiny_app(paged=True, cb=True)
        runner = ContinuousBatchingRunner(app, decode_chunk=4,
                                          prefill_chunk=16,
                                          prefill_token_budget=32,
                                          mixed_decode_steps=2,
                                          telemetry=True)
    elif mode == "megastep":
        # ISSUE-10 device-resident while_loop serving: the attribution's
        # megastep row decomposes the once-per-K-tokens dispatch floor
        app = _tiny_app(paged=True, cb=True)
        runner = ContinuousBatchingRunner(app, decode_chunk=4, megastep_k=8,
                                          telemetry=True)
    else:
        app = _tiny_app(paged=True, cb=True)
        runner = ContinuousBatchingRunner(app, decode_chunk=4, telemetry=True)
    return runner, list(_prompts((12, 19, 40)))


def profile_replicas(n, max_new, logdir, plane, merged_trace=None):
    """Per-replica device-time attribution (ISSUE-9 scale-out split): N
    engine replicas on one tiny app, each traced in its OWN window while the
    others idle. Same-kind dispatches lower to identical program names across
    replicas, so a single shared xplane trace could not split DEVICE time
    between them — sequential solo windows keep that attribution honest.

    ``merged_trace``: additionally write ONE fleet-merged Chrome/Perfetto
    trace of the replicas' HOST-side step/event timelines, normalized onto
    the shared epoch clock with replica-prefixed tracks
    (serving/tracing.py). This supersedes the old per-replica-only trace
    caveat for everything host-side; only the xplane device attribution
    stays per-solo-window."""
    from neuronx_distributed_inference_tpu.analysis.harness import (_prompts,
                                                                    _tiny_app)
    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)
    from neuronx_distributed_inference_tpu.serving import EngineReplica
    from neuronx_distributed_inference_tpu.utils import profiling as prof

    app = _tiny_app(paged=True, cb=True)
    replicas = [
        EngineReplica(str(i),
                      lambda tel: ContinuousBatchingRunner(
                          app, decode_chunk=4, telemetry=tel),
                      telemetry_enabled=True)
        for i in range(n)]
    prompts = list(_prompts((12, 19, 40)))
    timing = {}
    for rep in replicas:
        # warm outside the trace, then a solo traced window
        for p in prompts:
            rep.submit(p, max_new_tokens=max_new)
        while rep.has_work:
            rep.step()
        rep.runner.telemetry.reset()
        rep.runner.reset_device_telemetry()
        rdir = f"{logdir}/replica{rep.replica_id}"
        shutil.rmtree(rdir, ignore_errors=True)
        with prof.trace(rdir):
            for p in prompts:
                rep.submit(p, max_new_tokens=max_new)
            while rep.has_work:
                rep.step()
        for kind, row in rep.runner.attribute_device_time(
                rdir, plane_substr=plane).items():
            timing[f"replica{rep.replica_id}:{kind}"] = row
    if merged_trace:
        from neuronx_distributed_inference_tpu.serving import tracing

        tracing.write_merged_chrome_trace(
            merged_trace, [rep.trace_source() for rep in replicas])
        print(f"fleet-merged Chrome trace written to {merged_trace}",
              file=sys.stderr)
    return timing


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=("plain", "mixed", "spec", "megastep"),
                    default="plain")
    ap.add_argument("--replicas", type=int, default=1,
                    help="profile N engine replicas (serving/engine.py), one "
                         "traced solo window each — timing rows come back "
                         "per replica (plain mode only)")
    ap.add_argument("--merged-trace", default=None, metavar="PATH",
                    help="with --replicas: also write ONE fleet-merged "
                         "Chrome/Perfetto trace of the replicas' host "
                         "timelines on the shared epoch clock "
                         "(serving/tracing.py; device attribution stays "
                         "per-solo-window)")
    ap.add_argument("--max-new-tokens", type=int, default=10)
    ap.add_argument("--logdir", default="/tmp/tpu_profile_serving")
    ap.add_argument("--plane", default="tpu",
                    help='xplane name filter ("tpu" = device plane; "" scans '
                         'every plane — use on the CPU backend)')
    ap.add_argument("-o", "--out", default=None,
                    help="write the JSON report here (default: stdout only)")
    args = ap.parse_args(argv)

    from neuronx_distributed_inference_tpu.utils import profiling as prof

    if args.merged_trace and args.replicas <= 1:
        ap.error("--merged-trace requires --replicas > 1 (a single runner's "
                 "trace needs no merging — use the CLI's --trace-out)")
    if args.replicas > 1:
        if args.mode != "plain":
            ap.error("--replicas composes with --mode plain only")
        timing = profile_replicas(args.replicas, args.max_new_tokens,
                                  args.logdir, args.plane,
                                  merged_trace=args.merged_trace)
        report = {"mode": "plain", "replicas": args.replicas,
                  "plane": args.plane, "logdir": args.logdir,
                  "merged_trace": args.merged_trace,
                  "timing": timing}
        print(json.dumps(report, indent=2))
        if args.out:
            with open(args.out, "w") as fh:
                json.dump(report, fh, indent=2)
            print(f"report written to {args.out}", file=sys.stderr)
        return 0

    runner, prompts = build_runner(args.mode)
    # warm OUTSIDE the trace: every executable this schedule touches compiles
    # here, so the traced window measures steady-state dispatches only
    for p in prompts:
        runner.submit(p, max_new_tokens=args.max_new_tokens)
    runner.run_to_completion()
    runner.telemetry.reset()
    runner.reset_device_telemetry()   # measured window only (carry is cumulative)

    shutil.rmtree(args.logdir, ignore_errors=True)
    with prof.trace(args.logdir):
        for p in prompts:
            runner.submit(p, max_new_tokens=args.max_new_tokens)
        runner.run_to_completion()

    timing = runner.attribute_device_time(args.logdir,
                                          plane_substr=args.plane)
    s = runner.stats()
    report = {
        "mode": args.mode,
        "plane": args.plane,
        "logdir": args.logdir,
        "timing": timing,
        # ISSUE-14 measured-vs-model join: the analytical roofline
        # expectation + efficiency per profiled kind (None device rows
        # leave the expectation without an efficiency; an unverified spec
        # reports bound "unverified" and no expected times)
        "roofline": s.get("roofline"),
        "device_counters": s.get("device"),
        "stats_lite": {
            "tokens_emitted": s["tokens_emitted"],
            "steps": s["steps"],
            "ttft_p50_ms": (None if s["ttft_ms"] is None
                            else round(s["ttft_ms"]["latency_ms_p50"], 2)),
        },
    }
    print(json.dumps(report, indent=2))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=2)
        print(f"report written to {args.out}", file=sys.stderr)
    # device rows can be None on backends whose xplane lacks matching events;
    # the host spans are always attributed, so the tool still reports
    return 0


if __name__ == "__main__":
    sys.exit(main())
