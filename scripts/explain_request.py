#!/usr/bin/env python
"""Tail-latency explainer: rebuild a request's causal span tree from serving
event logs and print a latency waterfall whose components RECONCILE to the
recorded TTFT / E2E (serving/tracing.py — the reconciliation is the test; a
waterfall that doesn't sum is an event-stream integrity failure, and this
tool exits non-zero on it).

Inputs are the JSONL spools the serving stack already writes:

    # single runner (CLI --events-out / bench arrival phase)
    python scripts/explain_request.py events.jsonl --request 3
    python scripts/explain_request.py events.jsonl --all

    # fleet: replica spools + the router journal (CLI routed serve writes
    # events.jsonl.replica<i> and events.jsonl.router)
    python scripts/explain_request.py events.jsonl.replica* \\
        --router events.jsonl.router --trace t-ab12cd34-000001

Every file carries a ``telemetry_epoch`` header line, so timestamps from
different files normalize onto ONE shared clock; a request that migrated (or
survived ``recover_replica``) prints as a single connected trace with
``migrated_from`` / ``recovered_from`` continuity edges and one waterfall
per replica segment."""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from neuronx_distributed_inference_tpu.serving import tracing  # noqa: E402


def _bar(ms: float, total: float, width: int = 28) -> str:
    n = 0 if total <= 0 else int(round(width * ms / total))
    return "#" * max(0, min(width, n))


def _print_waterfall(wf: dict, indent: str = "") -> None:
    for phase, key in (("TTFT", "ttft_components_ms"),
                       ("E2E", "e2e_components_ms")):
        total = wf.get(f"{phase.lower()}_ms")
        comp = wf.get(key)
        if total is None or comp is None:
            continue
        print(f"{indent}{phase} {total:.1f} ms")
        for name, ms in comp.items():
            if ms <= 0:
                continue
            print(f"{indent}  {name:<22} {ms:9.2f} ms  {_bar(ms, total)}")
        resid = wf.get(f"{phase.lower()}_residual_frac")
        print(f"{indent}  reconciliation: components sum within "
              f"{resid * 100:.2f}% of recorded {phase} "
              f"[{'OK' if wf['reconciled'] else 'FAIL'}]")
    if "ttft_device_split_ms" in wf:
        print(f"{indent}  device attribution (profiled per-kind ratios):")
        for kind, d in wf["ttft_device_split_ms"].items():
            print(f"{indent}    {kind:<20} device {d['device_ms']:.2f} ms / "
                  f"host+gap {d['host_gap_ms']:.2f} ms")


def _print_tree(spans, indent: str = "  ") -> None:
    children = {}
    for s in spans:
        children.setdefault(s["parent"], []).append(s)
    def rec(parent, depth):
        for s in sorted(children.get(parent, ()), key=lambda x: x["t0"]):
            dur = ("open" if s["t1"] is None
                   else f"{(s['t1'] - s['t0']) * 1e3:.2f} ms")
            attrs = {k: v for k, v in s["attrs"].items()
                     if k in ("replica", "migrated_from", "recovered_from",
                              "tokens", "slot", "step_kind", "finish_reason",
                              "from_replica", "resumed_tokens",
                              "blocks_held")}
            extra = f"  {attrs}" if attrs else ""
            print(f"{indent}{'  ' * depth}{s['name']:<24} {dur}{extra}")
            rec(s["id"], depth + 1)
    rec(None, 0)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("events", nargs="+",
                    help="ServingTelemetry JSONL spool(s), one per replica")
    ap.add_argument("--router", default=None, metavar="PATH",
                    help="router journal JSONL (PrefixAffinityRouter."
                         "write_trace_events) — enables fleet mode")
    ap.add_argument("--request", type=int, default=None,
                    help="request id to explain (frontend id in fleet mode)")
    ap.add_argument("--trace", default=None, help="trace id to explain")
    ap.add_argument("--all", action="store_true",
                    help="validate EVERY request (the bench coverage mode)")
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="waterfall reconciliation tolerance (default 5%%)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable report instead of text")
    args = ap.parse_args(argv)
    if not (args.all or args.request is not None or args.trace):
        args.all = True

    sources = [tracing.load_jsonl_source(p, name=os.path.basename(p))
               for p in args.events]
    router_source = (tracing.load_jsonl_source(args.router, name="router")
                     if args.router else None)
    sets = {s["name"]: tracing.build_trace_set(s) for s in sources}

    failures = 0
    report = {"tolerance": args.tolerance, "requests": []}

    def explain_local(name, trace):
        nonlocal failures
        wf = tracing.waterfall(trace, sets[name]["steps"],
                               tolerance=args.tolerance)
        problems = tracing.validate_trace(trace)
        ok = wf["reconciled"] and trace["complete"] and not problems
        failures += 0 if ok else 1
        report["requests"].append({"source": name, **wf,
                                   "problems": problems, "ok": ok})
        if not args.as_json:
            print(f"\nrequest {trace['request_id']} "
                  f"(trace {trace['trace_id']}, {name})"
                  + ("" if trace["complete"] else "  [IN FLIGHT]"))
            _print_tree(trace["spans"])
            _print_waterfall(wf, indent="  ")
            for p in problems:
                print(f"  PROBLEM: {p}")

    if router_source is not None or len(sources) > 1:
        fleet = tracing.build_fleet_traces(sources, router_source)
        wanted = fleet
        if args.trace:
            wanted = {k: v for k, v in fleet.items() if k == args.trace}
        elif args.request is not None:
            wanted = {k: v for k, v in fleet.items()
                      if v.get("frontend_request_id") == args.request}
        if not wanted:
            print("no matching trace found", file=sys.stderr)
            return 2
        for tid, ft in sorted(wanted.items()):
            problems = tracing.validate_trace(ft)
            # same integrity contract as single-file mode: an incomplete
            # trace (a stream the fleet never finished) is a FAILURE — the
            # lost-request scenario is exactly what this tool must not
            # green-light
            if not ft["complete"]:
                problems = problems + ["trace incomplete: request never "
                                       "finished"]
            if not args.as_json:
                print(f"\ntrace {tid} (frontend request "
                      f"{ft['frontend_request_id']}): "
                      f"{len(ft['segments'])} segment(s) over "
                      f"{ft['segments']}"
                      + ("" if ft["complete"] else "  [IN FLIGHT]"))
                _print_tree(ft["spans"])
                for p in problems:
                    print(f"  PROBLEM: {p}")
            failures += 1 if problems else 0
            rep_row = {"trace_id": tid, "segments": ft["segments"],
                       "complete": ft["complete"], "problems": problems,
                       "segment_waterfalls": []}
            # one waterfall per replica segment, against THAT replica's
            # dispatch timeline (a segment's latency belongs to its host)
            for name, ts in sets.items():
                for rid, tr in sorted(ts["traces"].items()):
                    if tr.get("trace_id") == tid and tr["complete"]:
                        wf = tracing.waterfall(tr, ts["steps"],
                                               tolerance=args.tolerance)
                        failures += 0 if wf["reconciled"] else 1
                        rep_row["segment_waterfalls"].append(
                            {"source": name, **wf})
                        if not args.as_json:
                            print(f"  segment on {name}:")
                            _print_waterfall(wf, indent="    ")
            report["requests"].append(rep_row)
    else:
        name, ts = next(iter(sets.items()))
        traces = ts["traces"]
        if args.trace:
            traces = {r: t for r, t in traces.items()
                      if t.get("trace_id") == args.trace}
        elif args.request is not None:
            traces = {r: t for r, t in traces.items()
                      if r == args.request}
        if not traces:
            print("no matching request found", file=sys.stderr)
            return 2
        for rid in sorted(traces):
            explain_local(name, traces[rid])

    report["ok"] = failures == 0
    if args.as_json:
        print(json.dumps(report, indent=1))
    elif failures:
        print(f"\n{failures} request(s) FAILED validation/reconciliation",
              file=sys.stderr)
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    try:
        rc = main()
    except BrokenPipeError:
        # piping through `head` closes stdout early; the exit code is this
        # tool's integrity contract, so a closed pipe must not read as a
        # reconciliation failure — exit 141 (128+SIGPIPE), like coreutils
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        rc = 141
    sys.exit(rc)
