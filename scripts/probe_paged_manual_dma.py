"""Probe: manually double-buffered paged decode attend vs the in-repo v2 kernel.

The r5 cell-body probe showed v2's compute is ~141 us/call on resident
operands while the real kernel costs 335 bf16 / 182 int8 — the gap is
UN-OVERLAPPED DMA: Mosaic waits for a grid step's BlockSpec fetches before the
body and only issues the next step's after it. This variant takes the KV pool
as ANY-space operands and hand-pipelines: at each (row, chunk) step it first
ISSUES the next step's block copies, then computes on the buffers fetched one
step ago. Per-block dots (v3-style, no concat).

Shapes: B=64, Hq=32, Hkv=8, D=128, BS=128, table width 8, live 200-900, int8 KV.
"""

import functools
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

B, HQ, HKV, D, BS, MB, L = 64, 32, 8, 128, 128, 8, 8
NB = B * MB + 8
G = 4                      # blocks per chunk
NCH = MB // G              # chunks per row
NSTEP = B * NCH            # flat (row, chunk) work items
NEG_INF = -1e30


def manual_paged_attend(q, k_cache, v_cache, positions, layer_idx, block_table,
                        interpret=False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, hq, t, d = q.shape
    n_rep = hq // HKV
    rows = max(8, n_rep * t)
    scale = d ** -0.5
    qg = q.reshape(b, HKV, n_rep * t, d)
    if rows != n_rep * t:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, rows - n_rep * t), (0, 0)))
    nrows = HKV * rows

    def kernel(pos_ref, lidx_ref, bt_ref, q_ref, k_any, v_any, o_ref,
               kbuf, vbuf, m_s, l_s, acc_s, sems):
        step = pl.program_id(0)
        ri = step // NCH
        ci = step % NCH
        l = lidx_ref[0]

        def issue(s, buf_p):
            # start the G block fetches of flat work item s into buffer parity
            r = s // NCH
            c = s % NCH
            pos = pos_ref[r]
            last_live = pos // BS
            for g in range(G):
                gg = c * G + g
                ggc = jnp.minimum(gg, last_live)     # clamp: harmless refetch
                blk = bt_ref[r, ggc]
                pltpu.make_async_copy(
                    k_any.at[l, blk], kbuf.at[buf_p, g], sems.at[buf_p, g, 0]
                ).start()
                pltpu.make_async_copy(
                    v_any.at[l, blk], vbuf.at[buf_p, g], sems.at[buf_p, g, 1]
                ).start()

        @pl.when(step == 0)
        def _prologue():
            issue(0, 0)

        # issue NEXT step's fetches before computing this one
        @pl.when(step + 1 < NSTEP)
        def _prefetch():
            issue(step + 1, (step + 1) % 2)

        p_ = step % 2
        r = ri
        pos = pos_ref[r]
        # wait this step's buffers
        for g in range(G):
            pltpu.make_async_copy(k_any.at[0, 0], kbuf.at[p_, g],
                                  sems.at[p_, g, 0]).wait()
            pltpu.make_async_copy(v_any.at[0, 0], vbuf.at[p_, g],
                                  sems.at[p_, g, 1]).wait()

        @pl.when(ci == 0)
        def _init():
            m_s[:] = jnp.full_like(m_s, NEG_INF)
            l_s[:] = jnp.zeros_like(l_s)
            acc_s[:] = jnp.zeros_like(acc_s)

        qv = q_ref[0].reshape(nrows, d)
        int8_kv = kbuf.dtype == jnp.int8
        if int8_kv:
            qf = qv.astype(jnp.float32)
            sx = jnp.maximum(jnp.max(jnp.abs(qf), axis=1, keepdims=True),
                             1e-8) / 127.0
            qq = jnp.clip(jnp.round(qf / sx), -127, 127).astype(jnp.int8)
        row_i = jax.lax.broadcasted_iota(jnp.int32, (nrows, HKV * BS), 0)
        col_i = jax.lax.broadcasted_iota(jnp.int32, (nrows, HKV * BS), 1)
        same_head = (row_i // rows) == (col_i // BS)
        col_off = col_i % BS

        run_chunk = ci * G * BS <= pos
        @pl.when(run_chunk)
        def _compute():
            for g in range(G):
                k = kbuf[p_, g].reshape(HKV * BS, d)
                v = vbuf[p_, g].reshape(HKV * BS, d)
                kv_pos = (ci * G + g) * BS + col_off
                mask = jnp.logical_and(same_head, kv_pos <= pos)
                if int8_kv:
                    s = jax.lax.dot_general(
                        qq, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.int32
                    ).astype(jnp.float32) * (sx * scale)
                else:
                    s = jax.lax.dot_general(
                        k.astype(qv.dtype), qv, (((1,), (1,)), ((), ()))
                    ).astype(jnp.float32).T * scale
                s = jnp.where(mask, s, NEG_INF)
                m_prev = m_s[:, 0:1]
                l_prev = l_s[:, 0:1]
                m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
                alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
                p = jnp.exp(s - m_new)
                p = jnp.where(mask, p, 0.0)
                l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
                if int8_kv:
                    pi = jnp.round(p * 127.0).astype(jnp.int8)
                    pv = jax.lax.dot_general(
                        pi, v, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.int32
                    ).astype(jnp.float32) * (1.0 / 127.0)
                else:
                    pv = jax.lax.dot_general(
                        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                acc_s[...] = acc_s[...] * alpha + pv
                m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
                l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

        @pl.when(ci == NCH - 1)
        def _finalize():
            lv = l_s[:, 0:1]
            l_safe = jnp.where(lv == 0.0, 1.0, lv)
            o_ref[0] = (acc_s[...] / l_safe).reshape(HKV, rows, d).astype(
                o_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(NSTEP,),
        in_specs=[
            pl.BlockSpec((1, HKV, rows, d),
                         lambda s, pos, lidx, bt: (s // NCH, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, HKV, rows, d),
                               lambda s, pos, lidx, bt: (s // NCH, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, G, HKV, BS, D), k_cache.dtype),
            pltpu.VMEM((2, G, HKV, BS, D), v_cache.dtype),
            pltpu.VMEM((HKV * rows, 128), jnp.float32),
            pltpu.VMEM((HKV * rows, 128), jnp.float32),
            pltpu.VMEM((HKV * rows, D), jnp.float32),
            pltpu.SemaphoreType.DMA((2, G, 2)),
        ],
    )
    import jax

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, HKV, rows, d), q.dtype),
        interpret=interpret,
    )(positions.astype(jnp.int32), layer_idx.reshape(1).astype(jnp.int32),
      block_table.astype(jnp.int32), qg, k_cache, v_cache)
    out = out[:, :, : n_rep * t, :].reshape(b, HKV, n_rep, t, d)
    return out.reshape(b, hq, t, d)


def main():
    import jax
    import jax.numpy as jnp

    from neuronx_distributed_inference_tpu.ops.paged_decode import (
        paged_decode_attention_stacked)

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, HQ, 1, D)), dtype=jnp.bfloat16) * 0.3
    positions = jnp.asarray(rng.integers(200, 900, size=(B,)), dtype=jnp.int32)
    perm = rng.permutation(NB)[: B * MB].reshape(B, MB)
    bt = jnp.asarray(perm, dtype=jnp.int32)
    kc = jnp.asarray(rng.integers(-80, 81, size=(L, NB, HKV, BS, D)),
                     dtype=jnp.int8)
    vc = jnp.asarray(rng.integers(-80, 81, size=(L, NB, HKV, BS, D)),
                     dtype=jnp.int8)

    ref = np.asarray(paged_decode_attention_stacked(
        q, kc, vc, positions, jnp.int32(3), bt), np.float32)
    got = np.asarray(manual_paged_attend(q, kc, vc, positions, jnp.int32(3), bt),
                     np.float32)
    err = np.abs(got - ref).max() / max(np.abs(ref).max(), 1e-6)
    print("manual vs v2 rel err:", err)
    assert err < 0.05, err

    @jax.jit
    def run_v2(q, kc, vc, pos, bt):
        def step(c, li):
            o = paged_decode_attention_stacked(q, kc, vc, pos, li, bt)
            return c + o.astype(jnp.float32).mean(), None
        return jax.lax.scan(step, 0.0, jnp.arange(L, dtype=jnp.int32))[0]

    @jax.jit
    def run_manual(q, kc, vc, pos, bt):
        def step(c, li):
            o = manual_paged_attend(q, kc, vc, pos, li, bt)
            return c + o.astype(jnp.float32).mean(), None
        return jax.lax.scan(step, 0.0, jnp.arange(L, dtype=jnp.int32))[0]

    @jax.jit
    def _fetch(x):
        return x.reshape(1)[:1]

    def timeit(fn, iters=10, reps=20):
        import jax.numpy as jnp

        @jax.jit
        def reps_fn(q, kc, vc, pos, bt):
            def body(i, c):
                return c + fn.__wrapped__(q, kc, vc, pos, bt) if False else \
                    c + fn(q, kc, vc, pos, bt)
            return jax.lax.fori_loop(0, reps, body, 0.0)

        np.asarray(_fetch(reps_fn(q, kc, vc, positions, bt)))
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = reps_fn(q, kc, vc, positions, bt)
        np.asarray(_fetch(out))
        return (time.perf_counter() - t0) / iters / reps / L

    t2 = timeit(run_v2)
    tm = timeit(run_manual)
    print(f"v2     : {t2*1e6:7.1f} us/layer")
    print(f"manual : {tm*1e6:7.1f} us/layer")


if __name__ == "__main__":
    main()
