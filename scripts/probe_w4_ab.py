"""Isolate the 3x slowdown seen in probe_w4_kernel main_b's scan structure."""
import time

import jax
import jax.numpy as jnp
import numpy as np

B, IN, OUT = 64, 4096, 14336
L = 8
R = 40


@jax.jit
def _fetch(x):
    return jax.lax.slice(x.ravel(), (0,), (1,))


def timeit_chain(fn, state, iters=10):
    state = fn(state)
    np.asarray(_fetch(jax.tree.leaves(state)[0]))
    t0 = time.perf_counter()
    for _ in range(iters):
        state = fn(state)
    np.asarray(_fetch(jax.tree.leaves(state)[0]))
    return (time.perf_counter() - t0) / iters


def main():
    rng = np.random.default_rng(0)
    x8 = jnp.asarray(rng.integers(-127, 128, (B, IN), dtype=np.int8))
    w8 = jnp.asarray(rng.integers(-127, 128, (L, IN, OUT), dtype=np.int8))

    # A: int8 carry (the fast structure from probe_w4_matmul)
    @jax.jit
    def scan_a(x, w):
        def step(c, wl):
            y = jax.lax.dot_general(c, wl, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.int32)
            z = y[:, :IN].astype(jnp.float32)
            s = jnp.maximum(jnp.max(jnp.abs(z), axis=1, keepdims=True), 1e-6) / 127.0
            return jnp.clip(jnp.round(z / s), -127, 127).astype(jnp.int8), None
        def rep(_, c):
            return jax.lax.scan(step, c, w)[0]
        return jax.lax.fori_loop(0, R, rep, x)

    # B: f32 carry, requant at step start (the slow structure from main_b)
    @jax.jit
    def scan_b(x, w):
        def step(c, wl):
            z = c
            s = jnp.maximum(jnp.max(jnp.abs(z), axis=1, keepdims=True), 1e-6) / 127.0
            xq = jnp.clip(jnp.round(z / s), -127, 127).astype(jnp.int8)
            y = jax.lax.dot_general(xq, wl, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.int32)
            return y[:, :IN].astype(jnp.float32) * (s / 127.0), None
        def rep(_, c):
            return jax.lax.scan(step, c, w)[0]
        return jax.lax.fori_loop(0, R, rep, x.astype(jnp.float32))

    ta = timeit_chain(lambda x: scan_a(x, w8), x8) / R
    tb = timeit_chain(lambda x: scan_b(x, w8), x8) / R
    by = L * IN * OUT
    print(f"A int8-carry: {ta*1e3:7.3f} ms ({by/ta/1e9:6.1f} GB/s)")
    print(f"B f32-carry : {tb*1e3:7.3f} ms ({by/tb/1e9:6.1f} GB/s)")




def main2():
    rng = np.random.default_rng(0)
    x8 = jnp.asarray(rng.integers(-127, 128, (B, IN), dtype=np.int8))
    w8 = jnp.asarray(rng.integers(-127, 128, (L, IN, OUT), dtype=np.int8))

    # C: int8 carry + carried scale; dot first, requant at end
    @jax.jit
    def scan_c(x, w):
        def step(c, wl):
            xq, sp = c
            y = jax.lax.dot_general(xq, wl, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.int32)
            z = y[:, :IN].astype(jnp.float32) * sp
            s = jnp.maximum(jnp.max(jnp.abs(z), axis=1, keepdims=True), 1e-6) / 127.0
            xq2 = jnp.clip(jnp.round(z / s), -127, 127).astype(jnp.int8)
            return (xq2, s / 127.0), None
        def rep(_, c):
            return jax.lax.scan(step, (c, jnp.ones((B, 1), jnp.float32)), w)[0][0]
        return jax.lax.fori_loop(0, R, rep, x)

    # D: same as B but bf16 carry
    @jax.jit
    def scan_d(x, w):
        def step(c, wl):
            z = c.astype(jnp.float32)
            s = jnp.maximum(jnp.max(jnp.abs(z), axis=1, keepdims=True), 1e-6) / 127.0
            xq = jnp.clip(jnp.round(z / s), -127, 127).astype(jnp.int8)
            y = jax.lax.dot_general(xq, wl, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.int32)
            return (y[:, :IN].astype(jnp.float32) * (s / 127.0)).astype(jnp.bfloat16), None
        def rep(_, c):
            return jax.lax.scan(step, c, w)[0]
        return jax.lax.fori_loop(0, R, rep, x.astype(jnp.bfloat16))

    tc = timeit_chain(lambda x: scan_c(x, w8), x8) / R
    td = timeit_chain(lambda x: scan_d(x, w8), x8) / R
    by = L * IN * OUT
    print(f"C int8+scale carry: {tc*1e3:7.3f} ms ({by/tc/1e9:6.1f} GB/s)")
    print(f"D bf16 carry      : {td*1e3:7.3f} ms ({by/td/1e9:6.1f} GB/s)")


if __name__ == "__main__":
    main()
    main2()
