"""Probe: paged continuous-batching decode vs dense decode at the same config
(VERDICT r3 #2 — paged must reach >=70% of dense).

8-layer 8B-geometry int8+fp8KV llama at bs=64; measures the dense fixed-batch
chunked decode and the ContinuousBatchingRunner paged step, both device-timed,
and dumps the paged step's top ops so the gap is attributable.
"""

import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def xplane_table(trace_dir):
    import glob
    import os

    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    tot = {}
    for p in glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True):
        xs = xplane_pb2.XSpace()
        xs.ParseFromString(open(p, "rb").read())
        for plane in xs.planes:
            if "TPU" not in plane.name:
                continue
            for line in plane.lines:
                for ev in line.events:
                    name = plane.event_metadata[ev.metadata_id].name
                    tot[name] = tot.get(name, 0) + ev.duration_ps / 1e9
    return tot


def main():
    from neuronx_distributed_inference_tpu.config import (
        QuantizationConfig, TpuConfig, load_pretrained_config)
    from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
        LlamaForCausalLM, LlamaInferenceConfig)
    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)
    from neuronx_distributed_inference_tpu.utils import profiling as prof

    import bench
    import shutil

    hf_cfg = {
        "model_type": "llama", "vocab_size": 128256, "hidden_size": 4096,
        "intermediate_size": 14336, "num_hidden_layers": 8,
        "num_attention_heads": 32, "num_key_value_heads": 8, "head_dim": 128,
        "max_position_embeddings": 131072, "rms_norm_eps": 1e-5,
        "rope_theta": 500000.0,
        "rope_scaling": {"rope_type": "llama3", "factor": 8.0,
                         "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                         "original_max_position_embeddings": 8192},
        "tie_word_embeddings": False,
    }
    batch, seq, block = 64, 1024, int(__import__("os").environ.get("PROBE_BLOCK", 128))
    kvd = __import__("os").environ.get("PROBE_KVD", "float8_e4m3")
    quant = QuantizationConfig.for_kv_dtype(
        kvd, quantize_weights=True, weight_dtype="int8")
    cfg = TpuConfig(batch_size=batch, seq_len=seq, max_context_length=256,
                    dtype="bfloat16", tp_degree=1,
                    context_encoding_buckets=[256],
                    token_generation_buckets=[seq],
                    is_continuous_batching=True, paged_attention_enabled=True,
                    pa_num_blocks=batch * (seq // block) + 8, pa_block_size=block,
                    quantization_config=quant)
    config = LlamaInferenceConfig(cfg, load_config=load_pretrained_config(hf_cfg))
    app = LlamaForCausalLM(None, config)
    t0 = time.time()
    app.load_host_params(bench._random_quantized_llama_params(hf_cfg, seed=0))
    print(f"load {time.time() - t0:.0f}s; paged kernels: "
          f"{app._use_paged_decode_kernel()}", flush=True)

    runner = ContinuousBatchingRunner(app, decode_chunk=32)
    rng = np.random.default_rng(0)
    for _ in range(batch):
        runner.submit(rng.integers(1, 100000, size=(200,)).astype(np.int32),
                      max_new_tokens=700)
    t0 = time.time()
    for _ in range(3):
        runner.step()
    print(f"place+warm {time.time() - t0:.0f}s", flush=True)

    def measure(tag, n_chunks=6):
        t0 = time.time()
        n = 0
        for _ in range(n_chunks):
            runner.step()
            n += runner.decode_chunk
        wall = time.time() - t0
        print(f"paged wall [{tag}]: {batch * n / wall:.0f} tok/s "
              f"({1000 * wall / n:.2f} ms/step)", flush=True)

    measure("sync")
    runner.async_mode = True
    t0 = time.time(); runner.step(); print(f"fill {time.time()-t0:.2f}s", flush=True)
    t0 = time.time(); runner.step(); print(f"async step1 {time.time()-t0:.2f}s", flush=True)
    measure("async")
    runner.async_mode = False

    d = "/tmp/probe_paged_trace"
    shutil.rmtree(d, ignore_errors=True)
    with prof.trace(d):
        for _ in range(2):
            runner.step()
    tot = xplane_table(d)
    steps = 64
    dec = max((ms for name, ms in tot.items() if name.startswith("jit__decode")),
              default=0.0)
    print(f"paged decode device: {dec / steps:.2f} ms/step "
          f"-> {batch * 1000 / (dec / steps):.0f} tok/s device-limit", flush=True)
    for name, ms in sorted(tot.items(), key=lambda kv: -kv[1])[:14]:
        print(f"   {ms / steps:7.3f} ms/step  {name[:100]}", flush=True)


if __name__ == "__main__":
    main()
