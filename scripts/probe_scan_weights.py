"""Probe: how to make `lax.scan` consume stacked int8 layer weights without
materializing per-layer dynamic-slice copies (VERDICT r3 #3: ~0.75 ms/step of
`s8[1,4096,4096]` dynamic-slice fusions in the decode layer scan).

Variants measured on the real chip, device-timed via profiler xplane:
  A. baseline      — weights as scan xs, y = x @ w.astype(bf16)  (today's path)
  B. closure+take  — weights closed over, jnp.take(w, li) inside the body
  C. pre-T         — stacked weights stored transposed (L, O, H); dot_general
                     contracts on w's LAST axis (layout the MXU wants for the
                     stationary operand, maybe avoiding the slice copy)
  D. int8-dot      — activation int8 quant, s8 x s8 dot (no convert between
                     slice and dot)
"""

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

L, H, I = 8, 4096, 14336
B = 64


def run(name, fn, *args):
    fn_j = jax.jit(fn)
    out = fn_j(*args)
    jax.block_until_ready(out)
    # wall timing over many iters (device-bound: wall/iter ~= device time + const)
    n = 20
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn_j(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n * 1000
    print(f"{name:14s} {dt:7.2f} ms/iter", flush=True)
    return dt


def main():
    rng = np.random.default_rng(0)
    wq = jnp.asarray(rng.integers(-127, 128, (L, H, H), dtype=np.int8))
    wg = jnp.asarray(rng.integers(-127, 128, (L, H, I), dtype=np.int8))
    wd = jnp.asarray(rng.integers(-127, 128, (L, I, H), dtype=np.int8))
    wqT = jnp.transpose(wq, (0, 2, 1)).copy()
    wgT = jnp.transpose(wg, (0, 2, 1)).copy()
    wdT = jnp.transpose(wd, (0, 2, 1)).copy()
    x = jnp.asarray(rng.standard_normal((B, H)), jnp.bfloat16)

    def body_mm(h, w_q, w_g, w_d):
        a = h @ w_q.astype(h.dtype)
        g = a @ w_g.astype(h.dtype)
        o = jnp.maximum(g, 0) @ w_d.astype(h.dtype)
        return o

    def A(x):
        def body(h, xs):
            q, g, d = xs
            return body_mm(h, q, g, d), ()
        h, _ = jax.lax.scan(body, x, (wq, wg, wd))
        return h

    def Bv(x):
        def body(h, li):
            q = jnp.take(wq, li, axis=0)
            g = jnp.take(wg, li, axis=0)
            d = jnp.take(wd, li, axis=0)
            return body_mm(h, q, g, d), ()
        h, _ = jax.lax.scan(body, x, jnp.arange(L, dtype=jnp.int32))
        return h

    def C(x):
        def body(h, xs):
            qT, gT, dT = xs          # (O, H) slices: contract on LAST axis
            a = jax.lax.dot_general(h, qT.astype(h.dtype), (((1,), (1,)), ((), ())))
            g = jax.lax.dot_general(a, gT.astype(h.dtype), (((1,), (1,)), ((), ())))
            o = jax.lax.dot_general(jnp.maximum(g, 0), dT.astype(h.dtype),
                                    (((1,), (1,)), ((), ())))
            return o, ()
        h, _ = jax.lax.scan(body, x, (wqT, wgT, wdT))
        return h

    def D(x):
        def q8(v):
            s = jnp.max(jnp.abs(v.astype(jnp.float32)), axis=-1, keepdims=True) / 127.
            s = jnp.maximum(s, 1e-8)
            return jnp.clip(jnp.round(v.astype(jnp.float32) / s),
                            -127, 127).astype(jnp.int8), s

        def mm8(v, w):
            vq, s = q8(v)
            y = jax.lax.dot_general(vq, w, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.int32)
            return (y.astype(jnp.float32) * s).astype(jnp.bfloat16)

        def body(h, xs):
            q, g, d = xs
            a = mm8(h, q)
            gg = mm8(a, g)
            o = mm8(jnp.maximum(gg, 0), d)
            return o, ()
        h, _ = jax.lax.scan(body, x, (wq, wg, wd))
        return h

    run("A baseline", A, x)
    run("B take", Bv, x)
    run("C pre-T", C, x)
    run("D int8dot", D, x)

    # floor: total weight bytes / 819 GB/s
    wbytes = wq.size + wg.size + wd.size
    print(f"weight-stream floor: {wbytes / 819e9 * 1000:.2f} ms "
          f"({wbytes / 1e9:.2f} GB)")

    if "--trace" in sys.argv:
        sys.path.insert(0, "/root/repo")
        from neuronx_distributed_inference_tpu.utils import profiling as prof
        import shutil
        for name, fn in [("A", A), ("C", C), ("D", D)]:
            d = f"/tmp/probe_scan_{name}"
            shutil.rmtree(d, ignore_errors=True)
            fj = jax.jit(fn)
            fj(x).block_until_ready()
            with prof.trace(d):
                for _ in range(5):
                    fj(x).block_until_ready()
            print(name, "trace at", d)


if __name__ == "__main__":
    main()
