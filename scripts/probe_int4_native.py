"""Probe: does XLA's native s4 dtype stream at 4-bit bandwidth on TPU v5e?

If `jnp.int4` arrays are stored packed and the s4->s8 convert fuses into the
dot's operand read, weight-only int4 needs no Pallas kernel at all.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

B, IN, OUT = 64, 4096, 14336
L = 8
R = 40


@jax.jit
def _fetch(x):
    return jax.lax.slice(x.ravel(), (0,), (1,))


def timeit_chain(fn, state, iters=10):
    state = fn(state)
    np.asarray(_fetch(jax.tree.leaves(state)[0]))
    t0 = time.perf_counter()
    for _ in range(iters):
        state = fn(state)
    np.asarray(_fetch(jax.tree.leaves(state)[0]))
    return (time.perf_counter() - t0) / iters


def main():
    rng = np.random.default_rng(0)
    x8 = jnp.asarray(rng.integers(-127, 128, (B, IN), dtype=np.int8))
    w4np = rng.integers(-8, 8, (L, IN, OUT), dtype=np.int8)
    w8 = jnp.asarray(rng.integers(-127, 128, (L, IN, OUT), dtype=np.int8))
    import ml_dtypes
    w4 = jax.device_put(w4np.astype(ml_dtypes.int4))
    print("int4 array OK:", w4.dtype, w4.shape,
          "nbytes (API):", w4.nbytes if hasattr(w4, "nbytes") else "?")

    def _requant(z):
        z = z.astype(jnp.float32)
        s = jnp.maximum(jnp.max(jnp.abs(z), axis=1, keepdims=True), 1e-6) / 127.0
        return jnp.clip(jnp.round(z / s), -127, 127).astype(jnp.int8)

    def make(dot):
        @jax.jit
        def f(x, w):
            def step(c, wl):
                return _requant(dot(c, wl)[:, :IN]), None
            def rep(_, c):
                return jax.lax.scan(step, c, w)[0]
            return jax.lax.fori_loop(0, R, rep, x)
        return f

    dot8 = lambda c, wl: jax.lax.dot_general(
        c, wl, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32)
    dot4 = lambda c, wl: jax.lax.dot_general(
        c, wl.astype(jnp.int8), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    # also try a direct mixed s8 x s4 dot
    def dot4d(c, wl):
        return jax.lax.dot_general(c, wl, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.int32)

    f8, f4, f4d = make(dot8), make(dot4), make(dot4d)
    t8 = timeit_chain(lambda x: f8(x, w8), x8) / R
    t4 = timeit_chain(lambda x: f4(x, w4), x8) / R
    try:
        t4d = timeit_chain(lambda x: f4d(x, w4), x8) / R
    except Exception as e:
        t4d = None
        print("mixed s8xs4 dot unsupported:", type(e).__name__)

    int8_bytes = L * IN * OUT
    bw = 819e9
    print(f"int8       : {t8*1e3:8.3f} ms ({int8_bytes/t8/1e9:6.1f} GB/s) "
          f"floor {int8_bytes/bw*1e3:.3f}")
    print(f"s4 convert : {t4*1e3:8.3f} ms ({int8_bytes/2/t4/1e9:6.1f} GB/s packed) "
          f"floor {int8_bytes/2/bw*1e3:.3f}")
    if t4d is not None:
        print(f"s4 direct  : {t4d*1e3:8.3f} ms ({int8_bytes/2/t4d/1e9:6.1f} GB/s packed)")
    print(f"ratio s4/int8: {t4/t8:.3f}")


if __name__ == "__main__":
    main()
