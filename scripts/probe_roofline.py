"""Probe the chip: HBM roofline + int8-matmul efficiency.

Timing protocol for the axon tunnel: chain N dependent calls, then fetch one
element of the final result to host — the fetch cannot complete until every
chained execution has, so (wall / N) is a true per-call time.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

dev = jax.devices()[0]
print("device:", dev)


@jax.jit
def _probe(x):
    return jax.lax.slice(x.ravel(), (0,), (1,))


def timeit_chain(f, state, n=20):
    state = f(state)            # warmup/compile
    _ = np.asarray(_probe(jax.tree.leaves(state)[0]))
    t0 = time.perf_counter()
    for _i in range(n):
        state = f(state)
    _ = np.asarray(_probe(jax.tree.leaves(state)[0]))
    return (time.perf_counter() - t0) / n


key = jax.random.PRNGKey(0)

# --- 1. HBM copy roofline (read+write), chained x -> x+1 ---------------------
for gib in (1, 4):
    x = jax.random.bits(key, (gib * (1 << 30),), dtype=jnp.uint8)
    f = jax.jit(lambda x: x + 1, donate_argnums=0)
    t = timeit_chain(f, x)
    print(f"copy {gib} GiB (chained): {t*1e3:.2f} ms -> {2*gib/t:.0f} GiB/s (rd+wr)")
    del x, f

# --- 2. int8 matmul pair, chained activation ---------------------------------
H, I = 4096, 14336
for dt, name in ((jnp.int8, "int8"), (jnp.bfloat16, "bf16")):
    w = jax.random.bits(key, (H, I), dtype=jnp.uint8).view(jnp.int8).astype(dt)
    w2 = jax.random.bits(key, (I, H), dtype=jnp.uint8).view(jnp.int8).astype(dt)
    a = jax.random.normal(key, (64, H), dtype=jnp.bfloat16)

    def mm(a, w, w2):
        y = jax.nn.silu(a @ w.astype(jnp.bfloat16)) * 1e-4
        return (y @ w2.astype(jnp.bfloat16)) * 1e-4

    f = jax.jit(mm, donate_argnums=0)
    t = timeit_chain(lambda a: f(a, w, w2), a, n=50)
    bytes_w = (w.size + w2.size) * w.dtype.itemsize
    print(f"matmul pair {name} ({bytes_w/2**20:.0f} MiB weights): {t*1e6:.0f} us -> "
          f"{bytes_w/t/2**30:.0f} GiB/s weight-stream")
    del w, w2, a, f

# --- 3. scan over L layers of int8 matmul pairs (decode MLP structure) -------
L = 32
wg = jax.random.bits(key, (L, H, I), dtype=jnp.uint8).view(jnp.int8)
wd = jax.random.bits(key, (L, I, H), dtype=jnp.uint8).view(jnp.int8)
sg = jnp.full((L, I), 1e-4, dtype=jnp.float32)
sd = jnp.full((L, H), 1e-4, dtype=jnp.float32)
a = jax.random.normal(key, (64, H), dtype=jnp.bfloat16)


def stack(a, wg, wd, sg, sd):
    def body(h, xs):
        g, d, s1, s2 = xs
        t = jax.nn.silu((h @ g.astype(jnp.bfloat16)) * s1.astype(jnp.bfloat16))
        h = (t @ d.astype(jnp.bfloat16)) * s2.astype(jnp.bfloat16)
        return h, ()

    h, _ = jax.lax.scan(body, a, (wg, wd, sg, sd))
    return h


f = jax.jit(stack, donate_argnums=0)
t = timeit_chain(lambda a: f(a, wg, wd, sg, sd), a, n=10)
total = wg.size + wd.size
print(f"scan {L}x int8 MLP pair ({total/2**30:.1f} GiB): {t*1e3:.2f} ms -> "
      f"{total/t/2**30:.0f} GiB/s")
del wg, wd, a, f

# --- 4. decode attention over fp8 cache (bs=64 bucket=256) -------------------
B, Hkv, S, D, rep = 64, 8, 256, 128, 4
kc = (jax.random.bits(key, (32, B, Hkv, S, D), dtype=jnp.uint8)
      .view(jnp.float8_e4m3fn))
vc = (jax.random.bits(key, (32, B, Hkv, S, D), dtype=jnp.uint8)
      .view(jnp.float8_e4m3fn))
q = jax.random.normal(key, (B, Hkv * rep, 1, D), dtype=jnp.bfloat16)


def attn_scan(q, kc, vc):
    def body(h, xs):
        k, v = xs
        k = k.astype(jnp.bfloat16)
        v = v.astype(jnp.bfloat16)
        qg = h.reshape(B, Hkv, rep, 1, D)
        s = jnp.einsum("bkrqd,bktd->bkrqt", qg, k,
                       preferred_element_type=jnp.float32)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bkrqt,bktd->bkrqd", p.astype(jnp.bfloat16), v)
        return o.reshape(B, Hkv * rep, 1, D), ()

    h, _ = jax.lax.scan(body, q, (kc, vc))
    return h


f = jax.jit(attn_scan, donate_argnums=0)
t = timeit_chain(lambda q: f(q, kc, vc), q, n=10)
total = kc.size + vc.size
print(f"scan 32x decode-attend fp8 cache ({total/2**30:.1f} GiB): {t*1e3:.2f} ms -> "
      f"{total/t/2**30:.0f} GiB/s")
