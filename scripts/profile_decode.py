"""Profile the real 8B int8 decode chunk and print the device-op time breakdown.

Usage: python scripts/profile_decode.py [--small]
Parses the jax.profiler xplane output directly (tensorboard's converter is
version-broken in this image).
"""
import glob
import os
import sys
import time

import numpy as np


def main():
    small = "--small" in sys.argv
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    import jax

    from neuronx_distributed_inference_tpu.config import (
        QuantizationConfig, TpuConfig, load_pretrained_config)
    from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
        LlamaForCausalLM, LlamaInferenceConfig)
    from neuronx_distributed_inference_tpu.ops import sampling as sampling_ops

    hf_cfg = {
        "model_type": "llama", "vocab_size": 128256, "hidden_size": 4096,
        "intermediate_size": 14336, "num_hidden_layers": 32,
        "num_attention_heads": 32, "num_key_value_heads": 8, "head_dim": 128,
        "max_position_embeddings": 131072, "rms_norm_eps": 1e-5,
        "rope_theta": 500000.0,
        "rope_scaling": {"rope_type": "llama3", "factor": 8.0,
                         "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                         "original_max_position_embeddings": 8192},
        "tie_word_embeddings": False,
    }
    batch = int(os.environ.get("BENCH_BS", "64"))
    w4 = os.environ.get("BENCH_W4", "0") == "1"
    kvd = os.environ.get("BENCH_KVD", "float8_e4m3")
    quant = QuantizationConfig.for_kv_dtype(
        kvd, quantize_weights=True, weight_dtype="int4" if w4 else "int8")
    tpu_cfg = TpuConfig(batch_size=batch, seq_len=512, max_context_length=256,
                        dtype="bfloat16", tp_degree=1,
                        context_encoding_buckets=[128, 256],
                        token_generation_buckets=[256, 512],
                        quantization_config=quant)
    config = LlamaInferenceConfig(tpu_cfg, load_config=load_pretrained_config(hf_cfg))
    app = LlamaForCausalLM(None, config)
    t0 = time.time()
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts"))
    import bench_decode_only
    params = bench_decode_only.get_params(hf_cfg)
    if w4:
        from neuronx_distributed_inference_tpu.ops.quantization import (
            W4_DEFAULT_PARAMS)
        from neuronx_distributed_inference_tpu.ops.w4 import repack_int8_to_int4
        params = dict(params)
        params["layers"] = {
            k: (repack_int8_to_int4(v) if k in W4_DEFAULT_PARAMS else v)
            for k, v in params["layers"].items()}
    app.load_host_params(params)
    print(f"params loaded in {time.time()-t0:.1f}s", flush=True)

    rng = np.random.default_rng(0)
    input_ids = rng.integers(1, hf_cfg["vocab_size"], size=(batch, 128)).astype(np.int32)

    # warm up (compile both graphs)
    out = app.generate(input_ids, max_new_tokens=64)
    print("warm done", flush=True)

    # profile one fresh generate (prefill + 2 decode chunks)
    trace_dir = "/tmp/jaxprof"
    os.system(f"rm -rf {trace_dir}")
    with jax.profiler.trace(trace_dir):
        out = app.generate(input_ids, max_new_tokens=64, collect_latency=True)
    print("decode chunk latencies:", out.decode_latencies_s)
    print("ttft:", out.ttft_s)

    paths = glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True)
    print("xplane files:", paths)
    analyze(paths)


def analyze(paths):
    os.environ["PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION"] = "python"
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    for p in paths:
        xs = xplane_pb2.XSpace()
        with open(p, "rb") as f:
            xs.ParseFromString(f.read())
        for plane in xs.planes:
            if "TPU" not in plane.name and "tpu" not in plane.name.lower():
                continue
            print(f"\n=== plane: {plane.name} ===")
            md = plane.event_metadata
            agg = {}
            for line in plane.lines:
                for ev in line.events:
                    name = md[ev.metadata_id].name
                    dur = ev.duration_ps / 1e9  # ms
                    a = agg.setdefault(name, [0.0, 0])
                    a[0] += dur
                    a[1] += 1
            top = sorted(agg.items(), key=lambda kv: -kv[1][0])[:40]
            for name, (ms, n) in top:
                print(f"{ms:9.2f} ms  x{n:<5d} {name[:110]}")


if __name__ == "__main__":
    if sys.argv[1:] and sys.argv[1].endswith(".pb"):
        analyze(sys.argv[1:])
    else:
        main()
