"""Corrected A/B probe: consume ALL dot output columns so XLA cannot narrow the
dot through the chain slice (probe_w4_ab's `y[:, :IN]` silently dropped 71% of
the weight reads — the HLO showed s32[64,4096] dots)."""
import time

import jax
import jax.numpy as jnp
import numpy as np

B, IN, OUT = 64, 4096, 14336
L = 8
R = 40


@jax.jit
def _fetch(x):
    return jax.lax.slice(x.ravel(), (0,), (1,))


def timeit_chain(fn, state, iters=10):
    state = fn(state)
    np.asarray(_fetch(jax.tree.leaves(state)[0]))
    t0 = time.perf_counter()
    for _ in range(iters):
        state = fn(state)
    np.asarray(_fetch(jax.tree.leaves(state)[0]))
    return (time.perf_counter() - t0) / iters


def _fold(y):
    """(B, OUT) -> (B, IN) using every column (no narrowing possible)."""
    z = (y[:, :IN] + y[:, IN:2 * IN] + y[:, 2 * IN:3 * IN]
         + y[:, OUT - IN:])
    return z


def main():
    rng = np.random.default_rng(0)
    x8 = jnp.asarray(rng.integers(-127, 128, (B, IN), dtype=np.int8))
    w8 = jnp.asarray(rng.integers(-127, 128, (L, IN, OUT), dtype=np.int8))

    # A: int8 carry, requant at step end
    @jax.jit
    def scan_a(x, w):
        def step(c, wl):
            y = jax.lax.dot_general(c, wl, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.int32)
            z = _fold(y).astype(jnp.float32)
            s = jnp.maximum(jnp.max(jnp.abs(z), axis=1, keepdims=True), 1e-6) / 127.0
            return jnp.clip(jnp.round(z / s), -127, 127).astype(jnp.int8), None
        def rep(_, c):
            return jax.lax.scan(step, c, w)[0]
        return jax.lax.fori_loop(0, R, rep, x)

    # B: f32 carry, quantize at step start (the model's structure)
    @jax.jit
    def scan_b(x, w):
        def step(c, wl):
            s = jnp.maximum(jnp.max(jnp.abs(c), axis=1, keepdims=True), 1e-6) / 127.0
            xq = jnp.clip(jnp.round(c / s), -127, 127).astype(jnp.int8)
            y = jax.lax.dot_general(xq, wl, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.int32)
            return _fold(y).astype(jnp.float32) * (s / 127.0), None
        def rep(_, c):
            return jax.lax.scan(step, c, w)[0]
        return jax.lax.fori_loop(0, R, rep, x.astype(jnp.float32))

    ta = timeit_chain(lambda x: scan_a(x, w8), x8) / R
    tb = timeit_chain(lambda x: scan_b(x, w8), x8) / R
    by = L * IN * OUT
    print(f"A int8-carry : {ta*1e3:7.3f} ms ({by/ta/1e9:6.1f} GB/s) "
          f"floor {by/819e9*1e3:.3f} ms")
    print(f"B f32-carry  : {tb*1e3:7.3f} ms ({by/tb/1e9:6.1f} GB/s)")


if __name__ == "__main__":
    main()
