"""Probe: Pallas w4 (int4-packed) streaming matmul vs int8 XLA baseline.

probe_w4_matmul.py showed XLA cannot fuse the nibble unpack (w4 ratio 0.95 vs
int8 — the whole bandwidth win burned on VPU materialization). This kernel
streams the packed (IN/2, OUT) int8 plane through BlockSpec tiles, unpacks in
VMEM (3 int8 shifts per 2 weights), and runs two int8 MXU dots per tile:

    y = xe @ lo(P) + xo @ hi(P),   lo = (P << 4) >> 4,  hi = P >> 4

Packing puts W[2i] in the low nibble and W[2i+1] in the high nibble of byte i,
so both dots keep the natural (IN/2, OUT) layout — no interleave relayout.
Grid (L, OUT/bo): layer-major so each layer's tiles stream contiguously.
"""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B, IN, OUT = 64, 4096, 14336
L = 8
BO = 512  # out-tile width


@jax.jit
def _fetch(x):
    return jax.lax.slice(x.ravel(), (0,), (1,))


def timeit_chain(fn, state, iters=10):
    state = fn(state)
    np.asarray(_fetch(jax.tree.leaves(state)[0]))
    t0 = time.perf_counter()
    for _ in range(iters):
        state = fn(state)
    np.asarray(_fetch(jax.tree.leaves(state)[0]))
    return (time.perf_counter() - t0) / iters


def _w4_kernel(xe_ref, xo_ref, p_ref, o_ref):
    # int8 vector shifts don't legalize in Mosaic — widen to i32 for the nibble
    # arithmetic (same trick as paged_decode._vmem_cast), narrow to int8 for MXU
    p = p_ref[0].astype(jnp.int32)                 # (IN/2, BO)
    lo = (((p & 15) ^ 8) - 8).astype(jnp.int8)
    hi = jax.lax.shift_right_arithmetic(p, 4).astype(jnp.int8)
    acc = jax.lax.dot_general(xe_ref[...], lo, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    acc = acc + jax.lax.dot_general(xo_ref[...], hi, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.int32)
    o_ref[0] = acc


@functools.partial(jax.jit, static_argnames=())
def w4_matmul_stacked(xe, xo, packed):
    """(B, IN/2) int8 x 2, packed (L, IN/2, OUT) int8 -> (L, B, OUT) int32."""
    l, hin, out = packed.shape
    nt = out // BO
    return pl.pallas_call(
        _w4_kernel,
        grid=(l, nt),
        in_specs=[
            pl.BlockSpec((B, hin), lambda li, ti: (0, 0)),
            pl.BlockSpec((B, hin), lambda li, ti: (0, 0)),
            pl.BlockSpec((1, hin, BO), lambda li, ti: (li, 0, ti)),
        ],
        out_specs=pl.BlockSpec((1, B, BO), lambda li, ti: (li, 0, ti)),
        out_shape=jax.ShapeDtypeStruct((l, B, out), jnp.int32),
    )(xe, xo, packed)


def main():
    rng = np.random.default_rng(0)
    x8 = jnp.asarray(rng.integers(-127, 128, (B, IN), dtype=np.int8))
    w8 = jnp.asarray(rng.integers(-127, 128, (L, IN, OUT), dtype=np.int8))
    w4 = rng.integers(-8, 8, (L, IN, OUT), dtype=np.int8)
    packed = ((w4[:, 1::2] << 4) | (w4[:, 0::2] & 0xF)).astype(np.int8)
    p4 = jnp.asarray(packed)
    xe, xo = x8[:, 0::2], x8[:, 1::2]

    # correctness vs jnp dequant
    got = np.asarray(w4_matmul_stacked(xe, xo, p4)[0])
    want = np.asarray(xe, np.int32) @ w4[0, 0::2] + np.asarray(xo, np.int32) @ w4[0, 1::2]
    assert np.array_equal(got, want), np.abs(got - want).max()
    print("kernel exact vs int reference: OK")

    R = 40  # in-jit repetitions so device work dominates tunnel dispatch

    def _requant(z):
        z = z.astype(jnp.float32)
        s = jnp.maximum(jnp.max(jnp.abs(z), axis=1, keepdims=True), 1e-6) / 127.0
        return jnp.clip(jnp.round(z / s), -127, 127).astype(jnp.int8)

    @jax.jit
    def int8_mm(x, w):
        def step(c, wl):
            y = jax.lax.dot_general(c, wl, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.int32)
            return _requant(y[:, :IN]), None
        def rep(_, c):
            return jax.lax.scan(step, c, w)[0]
        return jax.lax.fori_loop(0, R, rep, x)

    @jax.jit
    def chain_w4(x, p):
        def rep(_, c):
            y = w4_matmul_stacked(c[:, 0::2], c[:, 1::2], p)
            return _requant(y[-1, :, :IN])
        return jax.lax.fori_loop(0, R, rep, x)

    t8 = timeit_chain(lambda x: int8_mm(x, w8), x8, iters=10) / R
    t4 = timeit_chain(lambda x: chain_w4(x, p4), x8, iters=10) / R
    int8_bytes = L * IN * OUT
    bw = 819e9
    print(f"int8 scan (w/ requant chain): {t8*1e3:8.3f} ms  "
          f"({int8_bytes/t8/1e9:6.1f} GB/s)  floor {int8_bytes/bw*1e3:.3f} ms")
    print(f"pallas w4 (one call, {L} layers): {t4*1e3:8.3f} ms  "
          f"({int8_bytes/2/t4/1e9:6.1f} GB/s of packed)  floor {int8_bytes/2/bw*1e3:.3f} ms")
    print(f"w4/int8 ratio : {t4/t8:.3f}")




# --- variant B: the real call shape — bf16 out, fused scales, per-layer calls ---------

BO_B = 512


def _w4b_kernel(lidx_ref, xe_ref, xo_ref, sx_ref, p_ref, s_ref, o_ref):
    p = p_ref[0].astype(jnp.int32)
    lo = (((p & 15) ^ 8) - 8).astype(jnp.int8)
    hi = jax.lax.shift_right_arithmetic(p, 4).astype(jnp.int8)
    acc = jax.lax.dot_general(xe_ref[...], lo, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    acc = acc + jax.lax.dot_general(xo_ref[...], hi, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.int32)
    o_ref[...] = (acc.astype(jnp.float32) * sx_ref[:, 0:1] * s_ref[0, 0]
                  ).astype(o_ref.dtype)


def w4_layer_matmul(xe, xo, sx, packed, scales, lidx):
    """One layer's matmul from the FULL stacked packed array (scalar-prefetch
    layer index — no XLA slice materialization)."""
    l, hin, out = packed.shape
    b = xe.shape[0]
    nt = out // BO_B
    from jax.experimental.pallas import tpu as pltpu2
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((b, hin), lambda ti, lidx: (0, 0)),
            pl.BlockSpec((b, hin), lambda ti, lidx: (0, 0)),
            pl.BlockSpec((b, 128), lambda ti, lidx: (0, 0)),
            pl.BlockSpec((1, hin, BO_B), lambda ti, lidx: (lidx[0], 0, ti)),
            pl.BlockSpec((1, 1, BO_B), lambda ti, lidx: (lidx[0], 0, ti)),
        ],
        out_specs=pl.BlockSpec((b, BO_B), lambda ti, lidx: (0, ti)),
    )
    return pl.pallas_call(
        _w4b_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, out), jnp.bfloat16),
    )(lidx.reshape(1).astype(jnp.int32), xe, xo, sx, packed,
      scales.reshape(l, 1, out))


def main_b():
    rng = np.random.default_rng(0)
    w4 = rng.integers(-8, 8, (L, IN, OUT), dtype=np.int8)
    packed = jnp.asarray(((w4[:, 1::2] << 4) | (w4[:, 0::2] & 0xF)).astype(np.int8))
    scales = jnp.asarray(rng.uniform(0.5, 2.0, (L, OUT)).astype(np.float32)) * 1e-2
    x8 = jnp.asarray(rng.integers(-127, 128, (B, IN), dtype=np.int8))
    w8 = jnp.asarray(rng.integers(-127, 128, (L, IN, OUT), dtype=np.int8))

    # correctness
    sx0 = jnp.ones((B, 128), jnp.float32) * 1e-3
    got = np.asarray(w4_layer_matmul(x8[:, 0::2], x8[:, 1::2], sx0, packed,
                                     scales, jnp.int32(3)))
    x_np = np.asarray(x8, np.int32)
    want = (x_np[:, 0::2] @ w4[3, 0::2] + x_np[:, 1::2] @ w4[3, 1::2]
            ).astype(np.float32) * 1e-3 * np.asarray(scales)[3]
    rel = np.abs(got.astype(np.float32) - want) / np.maximum(np.abs(want), 1e-3)
    assert rel.max() < 0.02, rel.max()
    print("variant B exact-within-bf16: OK")

    R2 = 40

    def _requant8(z):
        z = z.astype(jnp.float32)
        s = jnp.maximum(jnp.max(jnp.abs(z), axis=1, keepdims=True), 1e-6) / 127.0
        return (jnp.clip(jnp.round(z / s), -127, 127).astype(jnp.int8),
                (s / 127.0).astype(jnp.float32))

    @jax.jit
    def w4_scan(x, p, s):
        # the REAL call pattern: per-layer pallas_call inside lax.scan over the
        # layer index, full stacked arrays captured by closure
        def step(c, li):
            xq, sxr = _requant8(c)
            sx = jnp.broadcast_to(sxr, (B, 128))
            y = w4_layer_matmul(xq[:, 0::2], xq[:, 1::2], sx, p, s, li)
            return y[:, :IN].astype(jnp.float32), None

        def rep(_, c):
            return jax.lax.scan(step, c, jnp.arange(L, dtype=jnp.int32))[0]
        return jax.lax.fori_loop(0, R2, rep, x.astype(jnp.float32))

    @jax.jit
    def int8_scan(x, w):
        def step(c, wl):
            xq, sxr = _requant8(c)
            y = jax.lax.dot_general(xq, wl, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.int32)
            return (y[:, :IN].astype(jnp.float32) * sxr), None

        def rep(_, c):
            return jax.lax.scan(step, c, w)[0]
        return jax.lax.fori_loop(0, R2, rep, x.astype(jnp.float32))

    tb = timeit_chain(lambda x: w4_scan(x, packed, scales), x8, iters=10) / R2
    t8 = timeit_chain(lambda x: int8_scan(x, w8), x8, iters=10) / R2
    int8_bytes = L * IN * OUT
    print(f"int8 scan        : {t8*1e3:8.3f} ms ({int8_bytes/t8/1e9:6.1f} GB/s)")
    print(f"w4 scan (real)   : {tb*1e3:8.3f} ms ({int8_bytes/2/tb/1e9:6.1f} GB/s packed)")
    print(f"per-layer: int8 {t8/L*1e6:.1f} us  w4 {tb/L*1e6:.1f} us  "
          f"(floors {IN*OUT/819e9*1e6:.1f} / {IN*OUT/2/819e9*1e6:.1f})")
    print(f"ratio w4/int8    : {tb/t8:.3f}")


if __name__ == "__main__":
    main()
    main_b()
