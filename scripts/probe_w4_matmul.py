"""Probe: is an int4-packed weight matmul viable at XLA level, or does it need Pallas?

Decode is HBM-bound; int8 weights stream at ~90% of roofline (ROUND5_NOTES §12).
int4 packing halves weight bytes — worth ~2x on the MLP matmuls IF the unpack
(two nibbles per int8 byte) can ride along without materializing the unpacked
tensor in HBM. The packing scheme avoids any interleave relayout: byte[i, o]
holds W[2i, o] in the low nibble and W[2i+1, o] in the high nibble, so

    y = x[:, 0::2] @ lo(P) + x[:, 1::2] @ hi(P)

with lo/hi each (in/2, out) — same-shaped dots, no lane shuffles. This script
times, at the 8B decode shapes (bs=64):

  a) int8 baseline          x8 @ w8                     (what the model runs today)
  b) XLA w4                 nibble-ops feeding two dots (fused? or materialized?)
  c) DMA floor              int4 bytes / 819 GB/s       (printed, not run)
"""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

B, IN, OUT = 64, 4096, 14336
L = 8
R = 40  # in-jit repetitions: one dispatch carries R*L layer matmuls  # stacked layers to defeat caching between iterations


@jax.jit
def _fetch(x):
    return jax.lax.slice(x.ravel(), (0,), (1,))


def timeit_chain(fn, state, iters=30):
    """Axon-tunnel-safe timing: chain dependent calls, fetch one element at the
    end — wall/iters is true per-call time (see probe_roofline.py)."""
    state = fn(state)
    np.asarray(_fetch(jax.tree.leaves(state)[0]))
    t0 = time.perf_counter()
    for _ in range(iters):
        state = fn(state)
    np.asarray(_fetch(jax.tree.leaves(state)[0]))
    return (time.perf_counter() - t0) / iters


def main():
    rng = np.random.default_rng(0)
    x8 = jnp.asarray(rng.integers(-127, 128, (B, IN), dtype=np.int8))
    w8 = jnp.asarray(rng.integers(-127, 128, (L, IN, OUT), dtype=np.int8))
    # packed: byte = (W[2i+1] << 4) | (W[2i] & 0xF), values in [-8, 7]
    w4 = rng.integers(-8, 8, (L, IN, OUT), dtype=np.int8)
    packed = ((w4[:, 1::2] << 4) | (w4[:, 0::2] & 0xF)).astype(np.int8)
    p4 = jnp.asarray(packed)

    def requant(y):
        # fold the (B, OUT) int32 output back to a (B, IN) int8 activation so
        # calls chain through real data dependencies
        z = y[:, :IN].astype(jnp.float32)
        s = jnp.maximum(jnp.max(jnp.abs(z), axis=1, keepdims=True), 1e-6) / 127.0
        return jnp.clip(jnp.round(z / s), -127, 127).astype(jnp.int8)

    @jax.jit
    def int8_mm(x, w):
        def step(c, wl):
            y = jax.lax.dot_general(c, wl, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.int32)
            return requant(y), None
        def rep(_, c):
            return jax.lax.scan(step, c, w)[0]
        return jax.lax.fori_loop(0, R, rep, x)

    @jax.jit
    def w4_mm(x, p):
        def step(c, pl_):
            lo = ((pl_ & 0xF) ^ 8) - 8          # sign-extended low nibble
            hi = jax.lax.shift_right_arithmetic(pl_, jnp.int8(4))
            y = (jax.lax.dot_general(c[:, 0::2], lo, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.int32)
                 + jax.lax.dot_general(c[:, 1::2], hi, (((1,), (0,)), ((), ())),
                                       preferred_element_type=jnp.int32))
            return requant(y), None
        def rep(_, c):
            return jax.lax.scan(step, c, p)[0]
        return jax.lax.fori_loop(0, R, rep, x)

    t8 = timeit_chain(lambda x: int8_mm(x, w8), x8, iters=10)
    t4 = timeit_chain(lambda x: w4_mm(x, p4), x8, iters=10)
    t8, t4 = t8 / R, t4 / R
    int8_bytes = L * IN * OUT
    bw = 819e9
    print(f"int8 baseline : {t8*1e3:8.3f} ms  ({int8_bytes/t8/1e9:6.1f} GB/s)  "
          f"floor {int8_bytes/bw*1e3:.3f} ms")
    print(f"XLA w4        : {t4*1e3:8.3f} ms  ({int8_bytes/2/t4/1e9:6.1f} GB/s)  "
          f"floor {int8_bytes/2/bw*1e3:.3f} ms")
    print(f"w4/int8 ratio : {t4/t8:.3f}  (win if < 1; ~0.5 = full bandwidth win)")


if __name__ == "__main__":
    main()
