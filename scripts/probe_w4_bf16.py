"""Probe the REAL baseline and w4 candidates for the weight-only decode path:

  E: bf16 x int8 (convert fused into dot) — what the headline runs today
  F: bf16 x native-s4 (convert fused?) — dream path, no kernel needed
  G: bf16 x XLA nibble-unpack — does XLA fuse int ops into the dot read?

All chains consume every output column (see probe_w4_ab2 narrowing bug).
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

B, IN, OUT = 64, 4096, 14336
L = 8
R = 40


@jax.jit
def _fetch(x):
    return jax.lax.slice(x.ravel(), (0,), (1,))


def timeit_chain(fn, state, iters=10):
    state = fn(state)
    np.asarray(_fetch(jax.tree.leaves(state)[0]))
    t0 = time.perf_counter()
    for _ in range(iters):
        state = fn(state)
    np.asarray(_fetch(jax.tree.leaves(state)[0]))
    return (time.perf_counter() - t0) / iters


def _fold(y):
    return (y[:, :IN] + y[:, IN:2 * IN] + y[:, 2 * IN:3 * IN] + y[:, OUT - IN:])


def _norm(z):
    # keep the carry bounded like a norm would
    return (z / jnp.maximum(jnp.max(jnp.abs(z), axis=1, keepdims=True), 1e-6)
            ).astype(jnp.bfloat16)


def make_scan(dot):
    @jax.jit
    def f(x, w):
        def step(c, wl):
            y = dot(c, wl)
            return _norm(_fold(y).astype(jnp.float32)), None
        def rep(_, c):
            return jax.lax.scan(step, c, w)[0]
        return jax.lax.fori_loop(0, R, rep, x)
    return f


def main():
    rng = np.random.default_rng(0)
    xb = jnp.asarray(rng.standard_normal((B, IN)).astype(np.float32)).astype(jnp.bfloat16)
    w8 = jnp.asarray(rng.integers(-127, 128, (L, IN, OUT), dtype=np.int8))
    w4np = rng.integers(-8, 8, (L, IN, OUT), dtype=np.int8)
    packed = jnp.asarray(((w4np[:, 1::2] << 4) | (w4np[:, 0::2] & 0xF)).astype(np.int8))

    dot_e = lambda c, wl: jax.lax.dot_general(
        c, wl.astype(jnp.bfloat16), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    def dot_g(c, wl):
        p = wl.astype(jnp.int32)
        lo = ((((p & 15) ^ 8) - 8)).astype(jnp.bfloat16)
        hi = jax.lax.shift_right_arithmetic(p, 4).astype(jnp.bfloat16)
        return (jax.lax.dot_general(c[:, 0::2], lo, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
                + jax.lax.dot_general(c[:, 1::2], hi, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32))

    by = L * IN * OUT
    fe = make_scan(dot_e)
    te = timeit_chain(lambda x: fe(x, w8), xb) / R
    print(f"E bf16 x int8 : {te*1e3:7.3f} ms ({by/te/1e9:6.1f} GB/s) "
          f"floor {by/819e9*1e3:.3f} ms")
    try:
        import ml_dtypes
        w4n = jax.device_put(w4np.astype(ml_dtypes.int4))
        np.asarray(_fetch(w4n))  # surface transfer errors here, not later
        ff = make_scan(dot_e)  # same convert-into-dot form, s4 operand
        tf = timeit_chain(lambda x: ff(x, w4n), xb) / R
        print(f"F bf16 x s4   : {tf*1e3:7.3f} ms ({by/2/tf/1e9:6.1f} GB/s packed) "
              f"floor {by/2/819e9*1e3:.3f} ms")
    except Exception as e:
        print("F bf16 x s4   : FAILED", type(e).__name__, str(e)[:120])
    fg = make_scan(dot_g)
    tg = timeit_chain(lambda x: fg(x, packed), xb) / R
    print(f"G bf16 x nibble: {tg*1e3:7.3f} ms ({by/2/tg/1e9:6.1f} GB/s packed)")


if __name__ == "__main__":
    main()
