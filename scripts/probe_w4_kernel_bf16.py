"""Pallas w4 kernel, bf16-activation variant, vs the real weight-only int8
baseline (bf16 x int8-convert dot, ~113 us/layer at these shapes).

Chain consumes all output columns (see probe_w4_ab2 narrowing bug).
"""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B, IN, OUT = 64, 4096, 14336
L = 8
R = 40
BO = 512


@jax.jit
def _fetch(x):
    return jax.lax.slice(x.ravel(), (0,), (1,))


def timeit_chain(fn, state, iters=10):
    state = fn(state)
    np.asarray(_fetch(jax.tree.leaves(state)[0]))
    t0 = time.perf_counter()
    for _ in range(iters):
        state = fn(state)
    np.asarray(_fetch(jax.tree.leaves(state)[0]))
    return (time.perf_counter() - t0) / iters


def _w4_kernel(lidx_ref, xe_ref, xo_ref, p_ref, s_ref, o_ref):
    p = p_ref[0].astype(jnp.int32)
    lo = (((p & 15) ^ 8) - 8).astype(jnp.bfloat16)
    hi = jax.lax.shift_right_arithmetic(p, 4).astype(jnp.bfloat16)
    acc = jax.lax.dot_general(xe_ref[...], lo, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    acc = acc + jax.lax.dot_general(xo_ref[...], hi, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
    o_ref[...] = (acc * s_ref[0, 0]).astype(o_ref.dtype)


def w4_layer_matmul(xe, xo, packed, scales, lidx):
    l, hin, out = packed.shape
    b = xe.shape[0]
    nt = out // BO
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((b, hin), lambda ti, lidx: (0, 0)),
            pl.BlockSpec((b, hin), lambda ti, lidx: (0, 0)),
            pl.BlockSpec((1, hin, BO), lambda ti, lidx: (lidx[0], 0, ti)),
            pl.BlockSpec((1, 1, BO), lambda ti, lidx: (lidx[0], 0, ti)),
        ],
        out_specs=pl.BlockSpec((b, BO), lambda ti, lidx: (0, ti)),
    )
    return pl.pallas_call(
        _w4_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, out), jnp.bfloat16),
    )(lidx.reshape(1).astype(jnp.int32), xe, xo, packed,
      scales.reshape(l, 1, out))


def _fold(y):
    return (y[:, :IN] + y[:, IN:2 * IN] + y[:, 2 * IN:3 * IN] + y[:, OUT - IN:])


def _norm(z):
    return (z / jnp.maximum(jnp.max(jnp.abs(z), axis=1, keepdims=True), 1e-6)
            ).astype(jnp.bfloat16)


def main():
    rng = np.random.default_rng(0)
    xb = jnp.asarray(rng.standard_normal((B, IN)).astype(np.float32)).astype(jnp.bfloat16)
    w8 = jnp.asarray(rng.integers(-127, 128, (L, IN, OUT), dtype=np.int8))
    w4np = rng.integers(-8, 8, (L, IN, OUT), dtype=np.int8)
    packed = jnp.asarray(((w4np[:, 1::2] << 4) | (w4np[:, 0::2] & 0xF)).astype(np.int8))
    scales = jnp.asarray(rng.uniform(0.5, 2.0, (L, OUT)).astype(np.float32))

    # correctness
    got = np.asarray(w4_layer_matmul(xb[:, 0::2], xb[:, 1::2], packed, scales,
                                     jnp.int32(3))).astype(np.float32)
    xf = np.asarray(xb).astype(np.float32)
    want = (xf[:, 0::2] @ w4np[3, 0::2] + xf[:, 1::2] @ w4np[3, 1::2]) * np.asarray(scales)[3]
    rel = np.abs(got - want) / np.maximum(np.abs(want), 1e-2)
    assert rel.max() < 0.05, rel.max()
    print("w4 bf16 kernel correct: OK")

    @jax.jit
    def scan_e(x, w):
        def step(c, wl):
            y = jax.lax.dot_general(c, wl.astype(jnp.bfloat16),
                                    (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            return _norm(_fold(y)), None
        def rep(_, c):
            return jax.lax.scan(step, c, w)[0]
        return jax.lax.fori_loop(0, R, rep, x)

    @jax.jit
    def scan_w4(x, p, s):
        def step(c, li):
            y = w4_layer_matmul(c[:, 0::2], c[:, 1::2], p, s, li)
            return _norm(_fold(y.astype(jnp.float32))), None
        def rep(_, c):
            return jax.lax.scan(step, c, jnp.arange(L, dtype=jnp.int32))[0]
        return jax.lax.fori_loop(0, R, rep, x)

    by = L * IN * OUT
    te = timeit_chain(lambda x: scan_e(x, w8), xb) / R
    t4 = timeit_chain(lambda x: scan_w4(x, packed, scales), xb) / R
    print(f"E bf16 x int8 : {te*1e3:7.3f} ms ({by/te/1e9:6.1f} GB/s) "
          f"per-layer {te/L*1e6:5.1f} us (floor {IN*OUT/819e9*1e6:.1f})")
    print(f"W4 pallas     : {t4*1e3:7.3f} ms ({by/2/t4/1e9:6.1f} GB/s packed) "
          f"per-layer {t4/L*1e6:5.1f} us (floor {IN*OUT/2/819e9*1e6:.1f})")
    print(f"ratio w4/int8 : {t4/te:.3f}")




# --- W4A8: int8 activations (quantized outside), int8 MXU dots, bf16 out -------------


def _w4a8_kernel(lidx_ref, xe_ref, xo_ref, sx_ref, p_ref, s_ref, o_ref):
    p = p_ref[0].astype(jnp.int32)
    lo = (((p & 15) ^ 8) - 8).astype(jnp.int8)
    hi = jax.lax.shift_right_arithmetic(p, 4).astype(jnp.int8)
    acc = jax.lax.dot_general(xe_ref[...], lo, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    acc = acc + jax.lax.dot_general(xo_ref[...], hi, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.int32)
    o_ref[...] = (acc.astype(jnp.float32) * sx_ref[:, 0:1] * s_ref[0, 0]
                  ).astype(o_ref.dtype)


def w4a8_layer_matmul(xq, sx, packed, scales, lidx):
    l, hin, out = packed.shape
    b = xq.shape[0]
    nt = out // BO
    xe, xo = xq[:, 0::2], xq[:, 1::2]
    sxp = jnp.broadcast_to(sx, (b, 128))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((b, hin), lambda ti, lidx: (0, 0)),
            pl.BlockSpec((b, hin), lambda ti, lidx: (0, 0)),
            pl.BlockSpec((b, 128), lambda ti, lidx: (0, 0)),
            pl.BlockSpec((1, hin, BO), lambda ti, lidx: (lidx[0], 0, ti)),
            pl.BlockSpec((1, 1, BO), lambda ti, lidx: (lidx[0], 0, ti)),
        ],
        out_specs=pl.BlockSpec((b, BO), lambda ti, lidx: (0, ti)),
    )
    return pl.pallas_call(
        _w4a8_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, out), jnp.bfloat16),
    )(lidx.reshape(1).astype(jnp.int32), xe, xo, sxp, packed,
      scales.reshape(l, 1, out))


def main_a8():
    rng = np.random.default_rng(0)
    xb = jnp.asarray(rng.standard_normal((B, IN)).astype(np.float32)).astype(jnp.bfloat16)
    w4np = rng.integers(-8, 8, (L, IN, OUT), dtype=np.int8)
    packed = jnp.asarray(((w4np[:, 1::2] << 4) | (w4np[:, 0::2] & 0xF)).astype(np.int8))
    scales = jnp.asarray(rng.uniform(0.5, 2.0, (L, OUT)).astype(np.float32))

    def quant(x):
        xf = x.astype(jnp.float32)
        s = jnp.maximum(jnp.max(jnp.abs(xf), axis=1, keepdims=True), 1e-6) / 127.0
        return (jnp.clip(jnp.round(xf / s), -127, 127).astype(jnp.int8),
                s)

    # correctness
    xq0, sx0 = quant(xb)
    got = np.asarray(w4a8_layer_matmul(xq0, sx0, packed, scales, jnp.int32(5))
                     ).astype(np.float32)
    xf = np.asarray(xq0, np.int32)
    want = ((xf[:, 0::2] @ w4np[5, 0::2] + xf[:, 1::2] @ w4np[5, 1::2])
            * np.asarray(sx0) * np.asarray(scales)[5])
    rel = np.abs(got - want) / np.maximum(np.abs(want), 1e-2)
    assert rel.max() < 0.05, rel.max()
    print("w4a8 kernel correct: OK")

    @jax.jit
    def scan_w4a8(x, p, s):
        def step(c, li):
            xq, sx = quant(c)
            y = w4a8_layer_matmul(xq, sx, p, s, li)
            return _norm(_fold(y.astype(jnp.float32))), None
        def rep(_, c):
            return jax.lax.scan(step, c, jnp.arange(L, dtype=jnp.int32))[0]
        return jax.lax.fori_loop(0, R, rep, x)

    by = L * IN * OUT
    t = timeit_chain(lambda x: scan_w4a8(x, packed, scales), xb) / R
    print(f"W4A8 pallas   : {t*1e3:7.3f} ms ({by/2/t/1e9:6.1f} GB/s packed) "
          f"per-layer {t/L*1e6:5.1f} us (floor {IN*OUT/2/819e9*1e6:.1f})")


def main_a8_half():
    """Half-split packing: byte[i] = (W[i+hin] << 4) | (W[i] & 0xF) — xe/xo are
    contiguous halves of x (no strided lane relayout per step)."""
    rng = np.random.default_rng(0)
    xb = jnp.asarray(rng.standard_normal((B, IN)).astype(np.float32)).astype(jnp.bfloat16)
    w4np = rng.integers(-8, 8, (L, IN, OUT), dtype=np.int8)
    hin = IN // 2
    packed = jnp.asarray(((w4np[:, hin:] << 4) | (w4np[:, :hin] & 0xF)).astype(np.int8))
    scales = jnp.asarray(rng.uniform(0.5, 2.0, (L, OUT)).astype(np.float32))

    def quant(x):
        xf = x.astype(jnp.float32)
        s = jnp.maximum(jnp.max(jnp.abs(xf), axis=1, keepdims=True), 1e-6) / 127.0
        return (jnp.clip(jnp.round(xf / s), -127, 127).astype(jnp.int8), s)

    def w4a8_half(xq, sx, p, s, lidx):
        l, hn, out = p.shape
        b = xq.shape[0]
        nt = out // BO
        sxp = jnp.broadcast_to(sx, (b, 128))
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nt,),
            in_specs=[
                pl.BlockSpec((b, hn), lambda ti, lidx: (0, 0)),
                pl.BlockSpec((b, hn), lambda ti, lidx: (0, 1)),
                pl.BlockSpec((b, 128), lambda ti, lidx: (0, 0)),
                pl.BlockSpec((1, hn, BO), lambda ti, lidx: (lidx[0], 0, ti)),
                pl.BlockSpec((1, 1, BO), lambda ti, lidx: (lidx[0], 0, ti)),
            ],
            out_specs=pl.BlockSpec((b, BO), lambda ti, lidx: (0, ti)),
        )
        return pl.pallas_call(
            _w4a8_kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, out), jnp.bfloat16),
        )(lidx.reshape(1).astype(jnp.int32), xq, xq, sxp, p,
          s.reshape(l, 1, out))

    xq0, sx0 = quant(xb)
    got = np.asarray(w4a8_half(xq0, sx0, packed, scales, jnp.int32(5))
                     ).astype(np.float32)
    xf = np.asarray(xq0, np.int32)
    want = ((xf[:, :hin] @ w4np[5, :hin] + xf[:, hin:] @ w4np[5, hin:])
            * np.asarray(sx0) * np.asarray(scales)[5])
    rel = np.abs(got - want) / np.maximum(np.abs(want), 1e-2)
    assert rel.max() < 0.05, rel.max()
    print("w4a8-half kernel correct: OK")

    @jax.jit
    def scan_h(x, p, s):
        def step(c, li):
            xq, sx = quant(c)
            y = w4a8_half(xq, sx, p, s, li)
            return _norm(_fold(y.astype(jnp.float32))), None
        def rep(_, c):
            return jax.lax.scan(step, c, jnp.arange(L, dtype=jnp.int32))[0]
        return jax.lax.fori_loop(0, R, rep, x)

    by = L * IN * OUT
    t = timeit_chain(lambda x: scan_h(x, packed, scales), xb) / R
    print(f"W4A8 half-split: {t*1e3:7.3f} ms ({by/2/t/1e9:6.1f} GB/s packed) "
          f"per-layer {t/L*1e6:5.1f} us (floor {IN*OUT/2/819e9*1e6:.1f})")




def main_iso():
    """Isolate the ~45us/call gap: epilogue cost (int32-out variant) and tile
    count (BO=1024)."""
    rng = np.random.default_rng(0)
    xb = jnp.asarray(rng.standard_normal((B, IN)).astype(np.float32)).astype(jnp.bfloat16)
    w4np = rng.integers(-8, 8, (L, IN, OUT), dtype=np.int8)
    hin = IN // 2
    packed = jnp.asarray(((w4np[:, hin:] << 4) | (w4np[:, :hin] & 0xF)).astype(np.int8))
    scales = jnp.asarray(rng.uniform(0.5, 2.0, (L, OUT)).astype(np.float32))

    def quant(x):
        xf = x.astype(jnp.float32)
        s = jnp.maximum(jnp.max(jnp.abs(xf), axis=1, keepdims=True), 1e-6) / 127.0
        return (jnp.clip(jnp.round(xf / s), -127, 127).astype(jnp.int8), s)

    def _kern_raw(lidx_ref, xe_ref, xo_ref, p_ref, o_ref):
        p = p_ref[0].astype(jnp.int32)
        lo = (((p & 15) ^ 8) - 8).astype(jnp.int8)
        hi = jax.lax.shift_right_arithmetic(p, 4).astype(jnp.int8)
        acc = jax.lax.dot_general(xe_ref[...], lo, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        acc = acc + jax.lax.dot_general(xo_ref[...], hi, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.int32)
        o_ref[...] = acc

    def call_raw(xq, p, lidx, bo):
        l, hn, out = p.shape
        b = xq.shape[0]
        nt = out // bo
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nt,),
            in_specs=[
                pl.BlockSpec((b, hn), lambda ti, lidx: (0, 0)),
                pl.BlockSpec((b, hn), lambda ti, lidx: (0, 1)),
                pl.BlockSpec((1, hn, bo), lambda ti, lidx: (lidx[0], 0, ti)),
            ],
            out_specs=pl.BlockSpec((b, bo), lambda ti, lidx: (0, ti)),
        )
        return pl.pallas_call(
            _kern_raw, grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, out), jnp.int32),
        )(lidx.reshape(1).astype(jnp.int32), xq, xq, p)

    def make(bo):
        @jax.jit
        def scan_f(x, p):
            def step(c, li):
                xq, sx = quant(c)
                y = call_raw(xq, p, li, bo)
                return _norm(_fold(y.astype(jnp.float32)) * sx), None
            def rep(_, c):
                return jax.lax.scan(step, c, jnp.arange(L, dtype=jnp.int32))[0]
            return jax.lax.fori_loop(0, R, rep, x)
        return scan_f

    by = L * IN * OUT
    for bo in (512, 1024, 2048):
        f = make(bo)
        t = timeit_chain(lambda x: f(x, packed), xb) / R
        print(f"W4A8 raw BO={bo:4d}: {t*1e3:7.3f} ms per-layer {t/L*1e6:5.1f} us "
              f"({by/2/t/1e9:6.1f} GB/s packed)")


if __name__ == "__main__":
    main()
    main_a8()
    main_a8_half()
    main_iso()
