"""Real-TPU validation of the stacked decode kernels: tiny-model token parity
(kernel vs jnp decode) + per-step timing at the bench shape."""
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

from neuronx_distributed_inference_tpu.config import (
    TpuConfig, load_pretrained_config)
from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
    LlamaForCausalLM, LlamaInferenceConfig)

TINY = {
    "model_type": "llama", "vocab_size": 256, "hidden_size": 256,
    "intermediate_size": 512, "num_hidden_layers": 2, "num_attention_heads": 2,
    "num_key_value_heads": 2, "max_position_embeddings": 512,
    "rms_norm_eps": 1e-5, "rope_theta": 10000.0, "tie_word_embeddings": False,
}


def make(kernel, dtype="float32"):
    cfg = TpuConfig(batch_size=2, seq_len=256, max_context_length=128,
                    dtype=dtype, context_encoding_buckets=[128],
                    token_generation_buckets=[256],
                    decode_kernel_enabled=kernel)
    config = LlamaInferenceConfig(cfg, load_config=load_pretrained_config(TINY))
    app = LlamaForCausalLM(None, config)
    app.load_random(seed=0)
    return app


def main():
    rng = np.random.default_rng(3)
    ids = np.zeros((2, 20), dtype=np.int32)
    mask = np.zeros((2, 20), dtype=np.int32)
    for i, n in enumerate((20, 11)):
        ids[i, :n] = rng.integers(1, 256, size=(n,))
        mask[i, :n] = 1
    t0 = time.time()
    want = make(False).generate(ids, attention_mask=mask, max_new_tokens=24).tokens
    print(f"jnp path done in {time.time()-t0:.0f}s", flush=True)
    t0 = time.time()
    got = make(True).generate(ids, attention_mask=mask, max_new_tokens=24).tokens
    print(f"kernel path done in {time.time()-t0:.0f}s", flush=True)
    if np.array_equal(got, want):
        print("TOKEN PARITY OK (real TPU, kernel vs jnp)")
    else:
        print("PARITY FAIL")
        print("want", want)
        print("got ", got)
        sys.exit(1)


if __name__ == "__main__":
    main()
