"""Probe: kill the decode scan's s8[1,4096,4096] dynamic-slice copies by forcing
NATURAL layouts on the stacked attention weights (VERDICT r3 #3).

xplane shows XLA stores the (L, 4096, 4096) attention stacks TRANSPOSED
({1,2,0}) and then must materialize each layer's slice per step
(`constant_dynamic-slice_fusion`, ~0.75 ms/step at 32 layers), while the MLP
stacks keep natural {2,1,0} layout and their slices fuse straight into the
matmuls at ~90% of the HBM floor (scripts/probe_scan_weights2.py). Forcing
major_to_minor=(0,1,2) on wq/wk/wv/wo should put attention on the MLP path.

Run on the real chip; builds an 8-layer 8B-geometry int8+fp8KV llama at bs=64.
"""

import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def step_ms_and_copies(app, input_ids, tag):
    import shutil

    import jax

    from neuronx_distributed_inference_tpu.utils import profiling as prof

    app.generate(input_ids, max_new_tokens=8)       # compile + warm
    d = f"/tmp/probe_layout_{tag}"
    shutil.rmtree(d, ignore_errors=True)
    steps = 64
    app.generate(input_ids, max_new_tokens=1)
    with prof.trace(d):
        app.generate(input_ids, max_new_tokens=steps)

    import glob
    import os

    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    tot = {}
    for p in glob.glob(f"{d}/**/*.xplane.pb", recursive=True):
        xs = xplane_pb2.XSpace()
        xs.ParseFromString(open(p, "rb").read())
        for plane in xs.planes:
            if "TPU" not in plane.name:
                continue
            for line in plane.lines:
                for ev in line.events:
                    name = plane.event_metadata[ev.metadata_id].name
                    tot[name] = tot.get(name, 0) + ev.duration_ps / 1e9
    decode_ms = sum(ms for n, ms in tot.items() if "while" in n and
                    "jit__decode" not in n)
    dec = max((ms for n, ms in tot.items()
               if n.startswith("jit__decode")), default=None)
    copies = sum(ms for n, ms in tot.items() if "dynamic-slice" in n and
                 "s8[1,4096" in n)
    print(f"[{tag}] decode total {dec:.1f} ms / {steps} steps = "
          f"{dec / steps:.2f} ms/step; s8 slice-copies {copies / steps:.3f} ms/step",
          flush=True)
    top = sorted(tot.items(), key=lambda kv: -kv[1])[:12]
    for n, ms in top:
        print(f"   {ms / steps:7.3f} ms/step  {n[:100]}", flush=True)
    return dec / steps


def main():
    import jax

    from neuronx_distributed_inference_tpu.config import (
        QuantizationConfig, TpuConfig, load_pretrained_config)
    from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
        LlamaForCausalLM, LlamaInferenceConfig)

    sys.path.insert(0, "/root/repo")
    import bench

    hf_cfg = {
        "model_type": "llama", "vocab_size": 128256, "hidden_size": 4096,
        "intermediate_size": 14336, "num_hidden_layers": 8,
        "num_attention_heads": 32, "num_key_value_heads": 8, "head_dim": 128,
        "max_position_embeddings": 131072, "rms_norm_eps": 1e-5,
        "rope_theta": 500000.0,
        "rope_scaling": {"rope_type": "llama3", "factor": 8.0,
                         "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                         "original_max_position_embeddings": 8192},
        "tie_word_embeddings": False,
    }
    batch = 64
    quant = QuantizationConfig(quantize_weights=True, weight_dtype="int8",
                               kv_cache_dtype="float8_e4m3")
    tpu_cfg = TpuConfig(batch_size=batch, seq_len=512, max_context_length=256,
                        dtype="bfloat16", tp_degree=1,
                        context_encoding_buckets=[128, 256],
                        token_generation_buckets=[256, 512],
                        quantization_config=quant)
    config = LlamaInferenceConfig(tpu_cfg, load_config=load_pretrained_config(hf_cfg))
    app = LlamaForCausalLM(None, config)
    t0 = time.time()
    app.load_host_params(bench._random_quantized_llama_params(hf_cfg, seed=0))
    print(f"load {time.time() - t0:.0f}s", flush=True)

    rng = np.random.default_rng(0)
    input_ids = rng.integers(1, hf_cfg["vocab_size"],
                             size=(batch, 128)).astype(np.int32)

    base = step_ms_and_copies(app, input_ids, "baseline")

    from jax.experimental.layout import Format, Layout

    for name in ("wq", "wk", "wv", "wo"):
        leaf = app.params["layers"][name]["q"]
        fmt = Format(Layout(major_to_minor=(0, 1, 2)), leaf.sharding)
        app.params["layers"][name]["q"] = jax.device_put(leaf, fmt)
        print(name, "->", app.params["layers"][name]["q"].format.layout,
              flush=True)
    forced = step_ms_and_copies(app, input_ids, "natural-layout")
    print(f"baseline {base:.2f} -> natural {forced:.2f} ms/step", flush=True)


if __name__ == "__main__":
    main()
