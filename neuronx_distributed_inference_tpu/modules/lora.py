"""Multi-LoRA serving: adapter-id-indexed batched low-rank deltas on the projections.

≈ reference `modules/lora_serving/` (`wrap_model_with_lora` `lora_model.py:28`,
`MultiLoraColumnParallelLinear`/... `lora_layer.py:10-353`: adapter weights stacked on a
leading n_adapters dim, einsum against per-request adapter indices; checkpoint
shard/load `lora_checkpoint.py:232-336`). TPU redesign:

- Adapter weights live **inside the model param tree** as extra per-layer keys
  (``wq_lora_a`` (L, N, in, r), ``wq_lora_b`` (L, N, r, out), ...), so the layer `scan`
  carries them automatically and sharding rules apply per logical axis like any other
  parameter (B matrices shard on the projection's output axis, matching the reference's
  column/row-sharded multi-LoRA variants).
- Per request, ``adapter_ids`` (B,) selects each row's adapter; the delta is two batched
  einsums ``(x @ A[ids]) @ B[ids] * scaling`` fused by XLA into the surrounding matmuls.
  Adapter slot 0 is the zero adapter ("no LoRA") by convention, so mixed batches of
  base-model and adapter traffic need no masking.
- Static multi-LoRA: all adapters resident in HBM, traced into the graph.
- Dynamic multi-LoRA (`DynamicLoraManager`): a host-side store holds ANY number of
  converted adapters; serving swaps them into the fixed device slots between requests
  with a tiny jitted slot-update (traced slot index + donated buffers — in-place HBM
  writes, NO recompilation), LRU-evicting adapters the current batch doesn't need.
  ≈ the reference's dynamic mode: CPU-side sharded adapter store swapped into device
  weights at serve time (`lora_checkpoint.py:232-336`, dynamic update
  `models/model_base.py:3389-3396`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# projection name -> logical axis of its output dim (for B-matrix sharding)
TARGET_OUT_AXIS = {
    "wq": "heads", "wk": "kv_heads", "wv": "kv_heads", "wo": None,
    "wg": "mlp", "wu": "mlp", "wd": None,
}
DEFAULT_TARGETS = ("wq", "wk", "wv", "wo", "wg", "wu", "wd")


@dataclass(frozen=True)
class LoraSpec:
    """Static multi-LoRA description (hashable; nested in ModelArchArgs)."""

    max_loras: int = 1                   # adapter slots EXCLUDING the zero adapter
    rank: int = 16
    alpha: float = 32.0
    targets: Tuple[str, ...] = DEFAULT_TARGETS

    @property
    def num_slots(self) -> int:
        return self.max_loras + 1        # slot 0 = zero adapter

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank


def _target_dims(args, name: str) -> Tuple[int, int]:
    H, I = args.hidden_size, args.intermediate_size
    return {
        "wq": (H, args.q_size), "wk": (H, args.kv_size), "wv": (H, args.kv_size),
        "wo": (args.q_size, H), "wg": (H, I), "wu": (H, I), "wd": (I, H),
    }[name]


def lora_logical_axes(args, spec: LoraSpec) -> Dict[str, tuple]:
    """Logical sharding axes for the per-layer LoRA keys (merged into the model's
    ``layers`` axis tree)."""
    out = {}
    for name in spec.targets:
        out[f"{name}_lora_a"] = ("layers", None, "embed", None)
        out[f"{name}_lora_b"] = ("layers", None, None, TARGET_OUT_AXIS[name])
    return out


def init_lora_params(args, spec: LoraSpec, dtype=jnp.bfloat16) -> Dict[str, np.ndarray]:
    """Zero-initialized adapter slots (host-side); real adapters land via
    `convert_peft_state_dicts` or `set_adapter_`. Layout: A (L, N, in, r),
    B (L, N, r, out)."""
    L, N, r = args.num_layers, spec.num_slots, spec.rank
    out = {}
    for name in spec.targets:
        d_in, d_out = _target_dims(args, name)
        out[f"{name}_lora_a"] = np.zeros((L, N, d_in, r), dtype=np.float32)
        out[f"{name}_lora_b"] = np.zeros((L, N, r, d_out), dtype=np.float32)
    return out


def lora_delta(x: jnp.ndarray, la: jnp.ndarray, lb: jnp.ndarray,
               adapter_ids: jnp.ndarray, scaling: float) -> jnp.ndarray:
    """Batched low-rank delta: x (B, S, in), la (N, in, r), lb (N, r, out),
    adapter_ids (B,) -> (B, S, out)."""
    a_sel = jnp.take(la, adapter_ids, axis=0).astype(x.dtype)   # (B, in, r)
    b_sel = jnp.take(lb, adapter_ids, axis=0).astype(x.dtype)   # (B, r, out)
    low = jnp.einsum("bsh,bhr->bsr", x, a_sel)
    return jnp.einsum("bsr,bro->bso", low, b_sel) * jnp.asarray(scaling, x.dtype)


def apply_lora(lp: Dict, name: str, x: jnp.ndarray, y: jnp.ndarray,
               adapter_ids: Optional[jnp.ndarray], scaling: float) -> jnp.ndarray:
    """Add the selected adapters' delta for projection ``name`` to base output ``y``
    (no-op when the layer has no adapter keys or no ids are provided)."""
    la = lp.get(f"{name}_lora_a")
    if la is None or adapter_ids is None:
        return y
    return y + lora_delta(x, la, lp[f"{name}_lora_b"], adapter_ids, scaling)


# ---------------------------------------------------------------------------
# PEFT checkpoint conversion
# ---------------------------------------------------------------------------

_PEFT_NAME = {
    "wq": "self_attn.q_proj", "wk": "self_attn.k_proj", "wv": "self_attn.v_proj",
    "wo": "self_attn.o_proj", "wg": "mlp.gate_proj", "wu": "mlp.up_proj",
    "wd": "mlp.down_proj",
}


def convert_single_peft(sd: Dict[str, np.ndarray], args, spec: LoraSpec,
                        alpha: Optional[float] = None) -> Dict[str, np.ndarray]:
    """Convert ONE HF-PEFT adapter checkpoint to per-target stacked host arrays
    ``{name}_a (L, in, r)`` / ``{name}_b (L, r, out)``.

    PEFT stores ``...layers.{l}.{proj}.lora_A.weight`` as (r, in) and ``lora_B`` as
    (out, r) (torch Linear layout); both are transposed into the x-@-w layout. The
    adapter's true ``lora_alpha / rank`` scaling (default = its own rank, i.e.
    scaling 1.0) is **folded into B**, divided by the runtime ``spec.scaling``
    applied in `apply_lora`, so adapters with different alphas/ranks serve correctly
    side by side. Rank < spec.rank zero-pads (padded dims contribute nothing).
    ≈ reference `lora_checkpoint.py:232-336`."""
    L, r = args.num_layers, spec.rank
    stripped = {}
    for k, v in sd.items():
        k = k.replace("base_model.model.", "").replace("model.layers.", "layers.")
        stripped[k] = np.asarray(v)
    out = {}
    for name in spec.targets:
        d_in, d_out = _target_dims(args, name)
        out[f"{name}_a"] = np.zeros((L, d_in, r), dtype=np.float32)
        out[f"{name}_b"] = np.zeros((L, r, d_out), dtype=np.float32)
        proj = _PEFT_NAME[name]
        for layer in range(L):
            ka = f"layers.{layer}.{proj}.lora_A.weight"
            kb = f"layers.{layer}.{proj}.lora_B.weight"
            if ka not in stripped:
                continue   # adapter doesn't target this projection/layer
            a = stripped[ka].T          # (in, r_i)
            b = stripped[kb].T          # (r_i, out)
            r_i = a.shape[1]
            if r_i > r:
                raise ValueError(f"adapter rank {r_i} exceeds configured max "
                                 f"rank {r}")
            true_scaling = (alpha / r_i) if alpha is not None else 1.0
            out[f"{name}_a"][layer, :, :r_i] = a
            out[f"{name}_b"][layer, :r_i, :] = b * (true_scaling / spec.scaling)
    return out


def convert_peft_state_dicts(
    adapter_state_dicts: Sequence[Dict[str, np.ndarray]],
    args, spec: LoraSpec,
    alphas: Optional[Sequence[Optional[float]]] = None,
) -> Dict[str, np.ndarray]:
    """Stack HF-PEFT adapter checkpoints into the multi-LoRA layout.

    Adapter ``i`` (0-based) lands in slot ``i + 1`` (slot 0 stays the zero adapter).
    See `convert_single_peft` for the per-adapter layout/scaling rules.
    """
    if len(adapter_state_dicts) > spec.max_loras:
        raise ValueError(f"{len(adapter_state_dicts)} adapters exceed "
                         f"max_loras={spec.max_loras}")
    params = init_lora_params(args, spec)
    for i, sd in enumerate(adapter_state_dicts):
        one = convert_single_peft(
            sd, args, spec, alpha=None if alphas is None else alphas[i])
        for name in spec.targets:
            params[f"{name}_lora_a"][:, i + 1] = one[f"{name}_a"]
            params[f"{name}_lora_b"][:, i + 1] = one[f"{name}_b"]
    return params


class DynamicLoraManager:
    """Dynamic multi-LoRA: host-side adapter store + device slot swapper.

    Any number of adapters register on the host; serving calls `adapter_ids()` with
    the batch's adapter names and gets back per-row slot indices, swapping
    non-resident adapters into device slots first. The swap is a jitted in-place
    slot write (traced slot index, donated buffers): ONE compiled updater serves
    every slot, so swaps never recompile the model. Eviction is LRU among slots the
    current batch does not need. Slot 0 stays the zero adapter (name=None).

    ≈ reference dynamic multi-LoRA (`lora_checkpoint.py:232-336` CPU-side store,
    `models/model_base.py:3389-3396` dynamic device update).
    """

    def __init__(self, app):
        if app.arch_args.lora is None:
            raise ValueError("construct the application with lora_serving_config")
        if app.params is None:
            raise RuntimeError("load base weights before attaching the manager")
        self.app = app
        self.spec: LoraSpec = app.arch_args.lora
        self.host: Dict[str, Dict[str, np.ndarray]] = {}
        # slots 1..max_loras; index 0 of this list = slot 1
        self.slot_names: list = [None] * self.spec.max_loras
        self.last_used: Dict[str, int] = {}
        self._tick = 0
        self.swaps = 0
        self._installer = None

    # --- host store -------------------------------------------------------------
    def register(self, name: str, state_dict: Dict[str, np.ndarray],
                 alpha: Optional[float] = None) -> None:
        """Convert and store an adapter host-side (no device traffic)."""
        self.host[name] = convert_single_peft(state_dict, self.app.arch_args,
                                              self.spec, alpha=alpha)

    def register_path(self, name: str, path: str) -> None:
        sd, alpha, _rank = load_peft_adapter(path)
        self.register(name, sd, alpha=alpha)

    def register_host_arrays(self, name: str, arrays: Dict[str, np.ndarray]) -> None:
        """Store already-converted ``{name}_a``/``{name}_b`` arrays (tests,
        distilled adapters)."""
        self.host[name] = arrays

    # --- device swap ------------------------------------------------------------
    def _build_installer(self):
        targets = self.spec.targets

        def _install(layers, slot, new):
            out = dict(layers)
            for name in targets:
                out[f"{name}_lora_a"] = out[f"{name}_lora_a"].at[:, slot].set(
                    new[f"{name}_a"].astype(out[f"{name}_lora_a"].dtype))
                out[f"{name}_lora_b"] = out[f"{name}_lora_b"].at[:, slot].set(
                    new[f"{name}_b"].astype(out[f"{name}_lora_b"].dtype))
            return out

        return jax.jit(_install, donate_argnums=(0,))

    def _install(self, slot: int, name: str) -> None:
        if self._installer is None:
            self._installer = self._build_installer()
        new = {k: jnp.asarray(v) for k, v in self.host[name].items()}
        params = dict(self.app.params)
        params["layers"] = self._installer(
            params["layers"], jnp.asarray(slot, jnp.int32), new)
        self.app.params = params
        self.swaps += 1

    def ensure(self, names: Sequence[str]) -> Dict[str, int]:
        """Make every named adapter resident; returns {name: device slot}."""
        needed = [n for n in dict.fromkeys(names) if n is not None]
        unknown = [n for n in needed if n not in self.host]
        if unknown:
            raise KeyError(f"adapters not registered: {unknown}")
        if len(needed) > self.spec.max_loras:
            raise ValueError(f"batch needs {len(needed)} adapters but only "
                             f"{self.spec.max_loras} device slots exist")
        self._tick += 1
        for n in needed:
            self.last_used[n] = self._tick
        for n in needed:
            if n in self.slot_names:
                continue
            # free slot first, else LRU-evict a resident adapter not in this batch
            if None in self.slot_names:
                idx = self.slot_names.index(None)
            else:
                evictable = [i for i, s in enumerate(self.slot_names)
                             if s not in needed]
                idx = min(evictable, key=lambda i: self.last_used.get(
                    self.slot_names[i], 0))
            self.slot_names[idx] = n
            self._install(idx + 1, n)
        return {n: self.slot_names.index(n) + 1 for n in needed}

    def adapter_ids(self, names_per_row: Sequence[Optional[str]]) -> np.ndarray:
        """(B,) slot ids for a batch of adapter names (None = base model)."""
        slots = self.ensure([n for n in names_per_row if n is not None])
        return np.array([0 if n is None else slots[n] for n in names_per_row],
                        dtype=np.int32)


def load_peft_adapter(path: str):
    """Read a PEFT adapter directory: returns (state_dict, lora_alpha, rank) from
    adapter_model.safetensors (or .bin) + adapter_config.json."""
    import json
    import os

    sd_path = os.path.join(path, "adapter_model.safetensors")
    if os.path.exists(sd_path):
        from safetensors.numpy import load_file

        sd = load_file(sd_path)
    else:
        import torch

        sd = {k: v.numpy() for k, v in
              torch.load(os.path.join(path, "adapter_model.bin"),
                         map_location="cpu").items()}
    alpha, rank = None, None
    cfg_path = os.path.join(path, "adapter_config.json")
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            cfg = json.load(f)
        alpha, rank = cfg.get("lora_alpha"), cfg.get("r")
    return sd, alpha, rank


def merge_adapter(base_w: np.ndarray, la: np.ndarray, lb: np.ndarray,
                  scaling: float) -> np.ndarray:
    """Offline merge W' = W + scaling * A @ B (reference semantics; used by tests to
    validate the runtime path)."""
    return np.asarray(base_w) + scaling * (np.asarray(la) @ np.asarray(lb))
