"""Multi-LoRA serving: adapter-id-indexed batched low-rank deltas on the projections.

≈ reference `modules/lora_serving/` (`wrap_model_with_lora` `lora_model.py:28`,
`MultiLoraColumnParallelLinear`/... `lora_layer.py:10-353`: adapter weights stacked on a
leading n_adapters dim, einsum against per-request adapter indices; checkpoint
shard/load `lora_checkpoint.py:232-336`). TPU redesign:

- Adapter weights live **inside the model param tree** as extra per-layer keys
  (``wq_lora_a`` (L, N, in, r), ``wq_lora_b`` (L, N, r, out), ...), so the layer `scan`
  carries them automatically and sharding rules apply per logical axis like any other
  parameter (B matrices shard on the projection's output axis, matching the reference's
  column/row-sharded multi-LoRA variants).
- Per request, ``adapter_ids`` (B,) selects each row's adapter; the delta is two batched
  einsums ``(x @ A[ids]) @ B[ids] * scaling`` fused by XLA into the surrounding matmuls.
  Adapter slot 0 is the zero adapter ("no LoRA") by convention, so mixed batches of
  base-model and adapter traffic need no masking.
- "Static multi-LoRA": all adapters are resident in HBM and traced into the graph
  (≈ the reference's static mode; dynamic host-side adapter swapping is a later round).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# projection name -> logical axis of its output dim (for B-matrix sharding)
TARGET_OUT_AXIS = {
    "wq": "heads", "wk": "kv_heads", "wv": "kv_heads", "wo": None,
    "wg": "mlp", "wu": "mlp", "wd": None,
}
DEFAULT_TARGETS = ("wq", "wk", "wv", "wo", "wg", "wu", "wd")


@dataclass(frozen=True)
class LoraSpec:
    """Static multi-LoRA description (hashable; nested in ModelArchArgs)."""

    max_loras: int = 1                   # adapter slots EXCLUDING the zero adapter
    rank: int = 16
    alpha: float = 32.0
    targets: Tuple[str, ...] = DEFAULT_TARGETS

    @property
    def num_slots(self) -> int:
        return self.max_loras + 1        # slot 0 = zero adapter

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank


def _target_dims(args, name: str) -> Tuple[int, int]:
    H, I = args.hidden_size, args.intermediate_size
    return {
        "wq": (H, args.q_size), "wk": (H, args.kv_size), "wv": (H, args.kv_size),
        "wo": (args.q_size, H), "wg": (H, I), "wu": (H, I), "wd": (I, H),
    }[name]


def lora_logical_axes(args, spec: LoraSpec) -> Dict[str, tuple]:
    """Logical sharding axes for the per-layer LoRA keys (merged into the model's
    ``layers`` axis tree)."""
    out = {}
    for name in spec.targets:
        out[f"{name}_lora_a"] = ("layers", None, "embed", None)
        out[f"{name}_lora_b"] = ("layers", None, None, TARGET_OUT_AXIS[name])
    return out


def init_lora_params(args, spec: LoraSpec, dtype=jnp.bfloat16) -> Dict[str, np.ndarray]:
    """Zero-initialized adapter slots (host-side); real adapters land via
    `convert_peft_state_dicts` or `set_adapter_`. Layout: A (L, N, in, r),
    B (L, N, r, out)."""
    L, N, r = args.num_layers, spec.num_slots, spec.rank
    out = {}
    for name in spec.targets:
        d_in, d_out = _target_dims(args, name)
        out[f"{name}_lora_a"] = np.zeros((L, N, d_in, r), dtype=np.float32)
        out[f"{name}_lora_b"] = np.zeros((L, N, r, d_out), dtype=np.float32)
    return out


def lora_delta(x: jnp.ndarray, la: jnp.ndarray, lb: jnp.ndarray,
               adapter_ids: jnp.ndarray, scaling: float) -> jnp.ndarray:
    """Batched low-rank delta: x (B, S, in), la (N, in, r), lb (N, r, out),
    adapter_ids (B,) -> (B, S, out)."""
    a_sel = jnp.take(la, adapter_ids, axis=0).astype(x.dtype)   # (B, in, r)
    b_sel = jnp.take(lb, adapter_ids, axis=0).astype(x.dtype)   # (B, r, out)
    low = jnp.einsum("bsh,bhr->bsr", x, a_sel)
    return jnp.einsum("bsr,bro->bso", low, b_sel) * jnp.asarray(scaling, x.dtype)


def apply_lora(lp: Dict, name: str, x: jnp.ndarray, y: jnp.ndarray,
               adapter_ids: Optional[jnp.ndarray], scaling: float) -> jnp.ndarray:
    """Add the selected adapters' delta for projection ``name`` to base output ``y``
    (no-op when the layer has no adapter keys or no ids are provided)."""
    la = lp.get(f"{name}_lora_a")
    if la is None or adapter_ids is None:
        return y
    return y + lora_delta(x, la, lp[f"{name}_lora_b"], adapter_ids, scaling)


# ---------------------------------------------------------------------------
# PEFT checkpoint conversion
# ---------------------------------------------------------------------------

_PEFT_NAME = {
    "wq": "self_attn.q_proj", "wk": "self_attn.k_proj", "wv": "self_attn.v_proj",
    "wo": "self_attn.o_proj", "wg": "mlp.gate_proj", "wu": "mlp.up_proj",
    "wd": "mlp.down_proj",
}


def convert_peft_state_dicts(
    adapter_state_dicts: Sequence[Dict[str, np.ndarray]],
    args, spec: LoraSpec,
    alphas: Optional[Sequence[Optional[float]]] = None,
) -> Dict[str, np.ndarray]:
    """Stack HF-PEFT adapter checkpoints into the multi-LoRA layout.

    Adapter ``i`` (0-based) lands in slot ``i + 1`` (slot 0 stays the zero adapter).
    PEFT stores ``...layers.{l}.{proj}.lora_A.weight`` as (r, in) and ``lora_B`` as
    (out, r) (torch Linear layout); both are transposed into the x-@-w layout.

    Each adapter's true ``lora_alpha / rank`` scaling (``alphas[i]``, from its
    adapter_config.json; default = its own rank, i.e. scaling 1.0) is **folded into B**
    so adapters with different alphas/ranks serve correctly side by side; the folded
    value is divided by the runtime ``spec.scaling`` applied in `apply_lora`. Adapters
    with rank < spec.rank are zero-padded (padded dims contribute nothing).
    ≈ reference `lora_checkpoint.py:232-336`.
    """
    if len(adapter_state_dicts) > spec.max_loras:
        raise ValueError(f"{len(adapter_state_dicts)} adapters exceed "
                         f"max_loras={spec.max_loras}")
    params = init_lora_params(args, spec)
    for i, sd in enumerate(adapter_state_dicts):
        slot = i + 1
        stripped = {}
        for k, v in sd.items():
            k = k.replace("base_model.model.", "").replace("model.layers.", "layers.")
            stripped[k] = np.asarray(v)
        for name in spec.targets:
            proj = _PEFT_NAME[name]
            for layer in range(args.num_layers):
                ka = f"layers.{layer}.{proj}.lora_A.weight"
                kb = f"layers.{layer}.{proj}.lora_B.weight"
                if ka not in stripped:
                    continue   # adapter doesn't target this projection/layer
                a = stripped[ka].T          # (in, r_i)
                b = stripped[kb].T          # (r_i, out)
                r_i = a.shape[1]
                if r_i > spec.rank:
                    raise ValueError(
                        f"adapter {i} rank {r_i} exceeds configured max rank "
                        f"{spec.rank}")
                alpha_i = None if alphas is None else alphas[i]
                true_scaling = (alpha_i / r_i) if alpha_i is not None else 1.0
                b = b * (true_scaling / spec.scaling)
                params[f"{name}_lora_a"][layer, slot, :, :r_i] = a
                params[f"{name}_lora_b"][layer, slot, :r_i, :] = b
    return params


def load_peft_adapter(path: str):
    """Read a PEFT adapter directory: returns (state_dict, lora_alpha, rank) from
    adapter_model.safetensors (or .bin) + adapter_config.json."""
    import json
    import os

    sd_path = os.path.join(path, "adapter_model.safetensors")
    if os.path.exists(sd_path):
        from safetensors.numpy import load_file

        sd = load_file(sd_path)
    else:
        import torch

        sd = {k: v.numpy() for k, v in
              torch.load(os.path.join(path, "adapter_model.bin"),
                         map_location="cpu").items()}
    alpha, rank = None, None
    cfg_path = os.path.join(path, "adapter_config.json")
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            cfg = json.load(f)
        alpha, rank = cfg.get("lora_alpha"), cfg.get("r")
    return sd, alpha, rank


def merge_adapter(base_w: np.ndarray, la: np.ndarray, lb: np.ndarray,
                  scaling: float) -> np.ndarray:
    """Offline merge W' = W + scaling * A @ B (reference semantics; used by tests to
    validate the runtime path)."""
    return np.asarray(base_w) + scaling * (np.asarray(la) @ np.asarray(lb))
