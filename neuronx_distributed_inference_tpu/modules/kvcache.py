"""Dense KV cache: allocation, bucketed reads, prefill/decode writes.

≈ reference `modules/kvcache/kv_cache_manager.py` (`KVCacheManager` :107, `_init_kv_shape`
:195-237, `get_cache` :349-372, `update_kv_by_layer_id` :436-592). TPU redesign:

- The cache is a plain pytree ``{"k": (L, B, H_kv, S_max, D), "v": ...}`` of `jax.Array`s
  *donated* into every jitted step — JAX buffer donation replaces the reference's
  TorchScript input/output aliasing (`models/model_wrapper.py:1571-1612`); decode steps
  mutate cache memory in place on device.
- Layer-stacked layout (leading L dim) so the model's `lax.scan` over layers carries one
  cache slice per step and re-stacks updates for free.
- "Bucketed read": decode compiles one graph per token-generation bucket; the graph
  statically slices ``cache[..., :bucket, :]`` so short sequences pay attention cost
  proportional to their bucket, exactly like the reference's bucket-sliced `get_cache`.
- Continuous batching writes scatter each sequence at its own position via a vmapped
  `dynamic_update_slice` (the TPU analog of the reference's per-seq-id scatter,
  `kv_cache_manager.py:493-497`).

Sharding (see parallel/sharding.py): heads on tp, batch on dp — matching the
reference's (B, H/tp, S, D) per-core layout (`kv_cache_manager.py:195-237`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

KVCache = Dict[str, jnp.ndarray]

# logical axes for sharding the stacked cache; the decode_* axes resolve to the
# standard dp/tp layout unless attention-DP remaps them (parallel/sharding.py)
CACHE_LOGICAL = ("layers", "decode_batch", "decode_kv_heads", "kv_seq", None)


# logical axes for the optional per-(layer, kv-head) static fp8 scales
SCALE_LOGICAL = ("layers", "decode_kv_heads")


@dataclass(frozen=True)
class KVCacheSpec:
    num_layers: int
    batch_size: int
    num_kv_heads: int
    max_seq_len: int
    head_dim: int
    dtype: jnp.dtype = jnp.bfloat16
    # static-scale fp8: the cache stores K/σ_k, V/σ_v; σ (L, H_kv) fp32 rides the
    # pytree (≈ reference static-scale fp8 KV, `kv_cache_manager.py` fp8 paths)
    static_scales: bool = False

    @property
    def shape(self) -> Tuple[int, int, int, int, int]:
        return (self.num_layers, self.batch_size, self.num_kv_heads,
                self.max_seq_len, self.head_dim)


def init_cache(spec: KVCacheSpec) -> KVCache:
    out = {
        "k": jnp.zeros(spec.shape, dtype=spec.dtype),
        "v": jnp.zeros(spec.shape, dtype=spec.dtype),
    }
    if spec.static_scales:
        # distinct buffers: the cache pytree is donated whole, and donating the
        # same buffer twice is a runtime error
        out["k_scale"] = jnp.ones((spec.num_layers, spec.num_kv_heads), jnp.float32)
        out["v_scale"] = jnp.ones((spec.num_layers, spec.num_kv_heads), jnp.float32)
    return out


def cache_bytes(spec: KVCacheSpec) -> int:
    import numpy as np

    return 2 * int(np.prod(spec.shape)) * jnp.dtype(spec.dtype).itemsize


def read_bucket(cache_layer: jnp.ndarray, bucket: int) -> jnp.ndarray:
    """Static slice of the seq dim: (B, H, S_max, D) -> (B, H, bucket, D).

    ``bucket`` must be a Python int (static per compiled graph), ≈ the reference's
    bucket-sliced `get_cache` (`kv_cache_manager.py:349-372`).
    """
    return jax.lax.slice_in_dim(cache_layer, 0, bucket, axis=2)



def to_cache_dtype(x: jnp.ndarray, dtype) -> jnp.ndarray:
    """Cast K/V values to the cache dtype, SATURATING for fp8 caches.

    A plain astype overflows to NaN (e4m3fn) / Inf (e5m2) for |v| beyond the
    format's range; outlier keys past the dynamic range would poison attention
    (and the kernels' fast bit-surgery fp8 decode assumes finite payloads, so
    the corruption would surface as plausible-looking wrong logits rather than
    NaN). Every cache-write path funnels through this helper."""
    dt = jnp.dtype(dtype)
    if dt.itemsize == 1 and dt.kind not in "iub":   # fp8 dtypes report kind 'V'
        import ml_dtypes

        fmax = float(ml_dtypes.finfo(dt).max)
        x = jnp.clip(x, -fmax, fmax)
    elif dt == jnp.int8:
        # int8 KV (static scales only): values arrive pre-scaled to [-127, 127]
        # (cache stores round(K/sigma * 127) via sigma' = sigma/127); round +
        # saturate so serving outliers past the calibrated range clip, and the
        # int8-native attend kernels can consume the payload on the MXU
        x = jnp.clip(jnp.round(x.astype(jnp.float32)), -127, 127)
    return x.astype(dtype)


def write_prefill(cache_layer: jnp.ndarray, new_kv: jnp.ndarray,
                  start: int = 0, batch_start: int = 0) -> jnp.ndarray:
    """Write (B, H, S_new, D) into the cache at [start, start+S_new) along seq,
    batch rows [batch_start, batch_start+B).

    ≈ `fill_prefix` CTE write. ``start``/``batch_start`` may be traced (chunked prefill
    resumes mid-way; continuous batching inserts a fresh sequence at its batch slot).
    """
    return jax.lax.dynamic_update_slice(
        cache_layer, to_cache_dtype(new_kv, cache_layer.dtype),
        (batch_start, 0, start, 0))


def write_decode(cache_layer: jnp.ndarray, new_kv: jnp.ndarray,
                 positions: jnp.ndarray) -> jnp.ndarray:
    """Scatter (B, H, T, D) new tokens at per-sequence positions (B,) int32.

    Each batch row b writes its T tokens at [positions[b], positions[b]+T) — positions
    differ across rows under continuous batching (≈ scatter at position_ids,
    `kv_cache_manager.py:436-592`).
    """
    def _one(row_cache, row_new, pos):
        # row_cache (H, S, D), row_new (H, T, D)
        return jax.lax.dynamic_update_slice(
            row_cache, to_cache_dtype(row_new, row_cache.dtype), (0, pos, 0))

    return jax.vmap(_one)(cache_layer, new_kv, positions)


def init_cache_pattern(spec: KVCacheSpec, pattern, window: int) -> KVCache:
    """Dual-stack cache for per-layer attention patterns (gemma3/gpt-oss alternating
    sliding/full layers): full-attention layers get a (L_full, B, H, S_max, D) stack,
    sliding layers a **window-sized rolling** (L_sliding, B, H, W, D) stack — at long
    seq_len this is the difference between fitting and OOM (≈ reference per-layer
    cache sizes, `modules/kvcache/kv_cache_manager.py:199-237`)."""
    import dataclasses as _dc

    n_full = sum(1 for kind in pattern if kind != "sliding")
    n_slide = len(pattern) - n_full
    w = rolling_width(spec.max_seq_len, window)
    full = _dc.replace(spec, num_layers=max(n_full, 1))
    slide = _dc.replace(spec, num_layers=max(n_slide, 1), max_seq_len=w)
    return {
        "k": jnp.zeros(full.shape, dtype=spec.dtype),
        "v": jnp.zeros(full.shape, dtype=spec.dtype),
        "k_sliding": jnp.zeros(slide.shape, dtype=spec.dtype),
        "v_sliding": jnp.zeros(slide.shape, dtype=spec.dtype),
    }


def rolling_width(max_seq_len: int, window: int) -> int:
    """Allocated width of a rolling sliding-window cache."""
    return min(max_seq_len, window)


def write_prefill_rolling(cache_layer: jnp.ndarray, new_kv: jnp.ndarray,
                          true_lengths: jnp.ndarray, batch_start=0) -> jnp.ndarray:
    """Prefill write into a rolling (B, H, W, D) cache: slot j receives the row's
    newest token at a position ≡ j (mod W) — i.e. the last min(l, W) tokens land at
    their positions' modular slots, preserving the rolling invariant decode relies
    on (slot j holds the LARGEST written position congruent to j).

    new_kv (B, H, S, D) holds the bucket's keys; true_lengths (B,) the row's real
    token count l (padded tail tokens are junk and must not land in slots).
    ``batch_start`` lands the write at cache rows [batch_start, batch_start+B)
    (continuous-batching insert).
    """
    w = cache_layer.shape[2]
    s = new_kv.shape[2]
    b = new_kv.shape[0]
    slots = jnp.arange(w)[None, :]                       # (1, W)
    last = true_lengths[:, None] - 1                     # (B, 1)
    # largest q <= last with q % W == j; negative -> row never wrote that slot
    q = last - (last - slots) % w                        # (B, W)
    gather_idx = jnp.clip(q, 0, s - 1)
    gathered = jnp.take_along_axis(
        new_kv, gather_idx[:, None, :, None].astype(jnp.int32), axis=2)
    keep = (q >= 0)[:, None, :, None]
    rows = jax.lax.dynamic_slice_in_dim(cache_layer, batch_start, b, axis=0)
    updated = jnp.where(keep, to_cache_dtype(gathered, cache_layer.dtype), rows)
    return jax.lax.dynamic_update_slice_in_dim(cache_layer, updated, batch_start,
                                               axis=0)


def rolling_mask(positions: jnp.ndarray, t: int, w: int, window: int
                 ) -> jnp.ndarray:
    """Decode mask over a rolling cache's W slots.

    positions (B,): write position of the step's first token. After the step's
    writes at (pos + i) % W, slot j holds the key of position
    q_j = p_i - ((p_i - j) mod W) for query token i at p_i = positions + i; the
    mask admits slots with 0 <= q_j > p_i - window. Returns (B, 1, T, W) bool."""
    slots = jnp.arange(w)[None, None, None, :]
    q_pos = (positions[:, None] + jnp.arange(t)[None, :])[:, None, :, None]
    held = q_pos - (q_pos - slots) % w
    return (held >= 0) & (held > q_pos - window)


def batched_gather(cache: KVCache, seq_ids: jnp.ndarray) -> KVCache:
    """Reorder the batch dim by seq_ids (continuous batching batch remap,
    ≈ `model_wrapper.py:569-698` batch sorting)."""
    return {k: jnp.take(v, seq_ids, axis=1) for k, v in cache.items()}


def compact_decode_slots(cache: KVCache, src_slots: jnp.ndarray,
                         dst_start: jnp.ndarray) -> KVCache:
    """Gather accepted tree-verify slots into contiguous positions.

    After a tree verify writes N nodes at cache slots [p, p+N) (see
    `models/base.decode_forward` tree mode), acceptance keeps a root-to-leaf path; the
    kept nodes' KV entries move to [dst_start, dst_start+K) so the cache is again a
    plain left-to-right sequence (≈ the reference's accepted-index KV compaction,
    `modules/kvcache/kv_cache_manager.py:266-322`).

    src_slots (B, K) int32: absolute cache slots to keep, in commit order. Rows that
    accept fewer than K nodes may pad src_slots arbitrarily — padded slots copy garbage
    that later decode writes overwrite before any read (decode masks are
    position-bounded).
    dst_start (B,) int32: first destination slot per row.
    """
    def _one_layer(cache_layer):
        def _one_row(row_cache, row_src, row_dst):
            # row_cache (H, S, D): gather K source slots then write them contiguously
            kept = jnp.take(row_cache, row_src, axis=1)       # (H, K, D)
            return jax.lax.dynamic_update_slice(row_cache, kept, (0, row_dst, 0))

        return jax.vmap(_one_row)(cache_layer, src_slots, dst_start)

    return {k: jax.vmap(_one_layer)(v) for k, v in cache.items()}
