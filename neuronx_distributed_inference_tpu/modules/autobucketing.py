"""Bucket-ladder generation and selection.

≈ reference `modules/autobucketing.py` (`generate_buckets` :8, `generate_buckets_for_cte`
:149, `for_tkg` :226, 2D ladders :22-63). On TPU a "bucket" is simply a static shape that
`jax.jit` compiles once and caches; the host wrapper pads inputs up to the chosen bucket
(first-fit), exactly like the reference's NEFF-per-bucket selection
(`models/model_wrapper.py:826-916`).
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple


def powers_of_two_ladder(min_len: int, max_len: int) -> List[int]:
    """[min, 2*min, ..., max]; max is always included even if not a power-of-two step."""
    if min_len < 1 or max_len < min_len:
        raise ValueError(f"bad ladder bounds ({min_len}, {max_len})")
    out = []
    v = 1 << max(0, math.ceil(math.log2(min_len)))
    while v < max_len:
        out.append(v)
        v *= 2
    out.append(max_len)
    return out


def generate_buckets_for_cte(tpu_config) -> List[int]:
    """Context-encoding (prefill) sequence buckets (≈ :149)."""
    if not tpu_config.enable_bucketing:
        return [tpu_config.max_context_length]
    if tpu_config.context_encoding_buckets:
        return list(tpu_config.context_encoding_buckets)
    return powers_of_two_ladder(min(128, tpu_config.max_context_length),
                                tpu_config.max_context_length)


def generate_buckets_for_tkg(tpu_config) -> List[int]:
    """Token-generation buckets over *total* sequence length (cache width) (≈ :226)."""
    if not tpu_config.enable_bucketing:
        return [tpu_config.seq_len]
    if tpu_config.token_generation_buckets:
        return list(tpu_config.token_generation_buckets)
    return powers_of_two_ladder(min(128, tpu_config.seq_len), tpu_config.seq_len)


def generate_batch_buckets(tpu_config) -> List[int]:
    """Batch-dim buckets (≈ 2D batch x seq bucketing :22-63): a request batch smaller
    than ``max_batch_size`` runs at the first-fit batch bucket, so prefill/decode cost
    scales with the live batch instead of the compiled maximum. Opt-in via
    ``tpu_config.batch_buckets`` (each bucket compiles its own graphs)."""
    if not tpu_config.enable_bucketing or not tpu_config.batch_buckets:
        return [tpu_config.max_batch_size]
    buckets = sorted(set(tpu_config.batch_buckets))
    if buckets[-1] != tpu_config.max_batch_size:
        raise ValueError(f"batch_buckets {buckets} must end at max_batch_size "
                         f"{tpu_config.max_batch_size}")
    return buckets


def select_bucket(buckets: Sequence[int], length: int) -> int:
    """First-fit bucket selection (≈ `model_wrapper.py:826-916`)."""
    for b in buckets:
        if length <= b:
            return b
    raise ValueError(f"length {length} exceeds largest bucket {buckets[-1]}")


# NOTE: the reference's 2D (prefill x prefix) bucket logic (`model_wrapper.py:918-1142`)
# has no analog here: paged prefix caching reuses prior blocks through the block table,
# whose width is static, so prefix length never changes a compiled shape.
