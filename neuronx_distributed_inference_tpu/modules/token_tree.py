"""Static token trees for tree-structured speculative decoding (Medusa / EAGLE tree).

≈ reference `modules/eagle/token_tree.py` (`TokenTree` :8-60+): a tree is declared as a
set of root-to-node paths; from it we precompute everything the traced verify step needs
— per-node depth (RoPE position offset), the ancestor ("tree attention") mask, and
parent/child tables for host-side acceptance walking. The reference additionally
precomputes KV "cache scatter indices" for compacting accepted nodes
(`token_tree.py` level masks / permute indices); here compaction is a gather over cache
slots (see `modules/kvcache.compact_decode_slots`) driven by the accepted node indices.

Nodes are numbered in path-declaration order with node 0 the implicit root (the last
committed token). Paths use Medusa convention: path ``(a, b, c)`` means "take the
``a``-th top-k candidate of head 0, then the ``b``-th of head 1, ...", so a node at
depth d carries the candidate index ``path[-1]`` into draft head ``d-1``'s top-k list.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Tuple

import numpy as np

# the default Medusa "sparse" tree used when none is configured: a chain of the top-1
# candidates plus first-level alternatives — small but captures most acceptance mass
DEFAULT_TREE_PATHS: Tuple[Tuple[int, ...], ...] = (
    (0,), (1,), (2,), (3,),
    (0, 0), (0, 1), (1, 0),
    (0, 0, 0), (0, 0, 1),
    (0, 0, 0, 0),
)


@dataclass(frozen=True)
class TokenTree:
    """Precomputed static tree structure. All arrays are host numpy; the jitted verify
    step closes over `depths` / `ancestor_mask` as constants."""

    paths: Tuple[Tuple[int, ...], ...]
    num_nodes: int                      # including the root
    depths: np.ndarray                  # (N,) int32, depth[0] = 0
    parents: np.ndarray                 # (N,) int32, parent[0] = -1
    branch: np.ndarray                  # (N,) int32 candidate index at the node's head
    ancestor_mask: np.ndarray           # (N, N) bool: [i, j] = j is ancestor-of-or-is i
    children: Tuple[Tuple[int, ...], ...] = field(repr=False, default=())

    @property
    def max_depth(self) -> int:
        return int(self.depths.max())

    @property
    def max_branch(self) -> int:
        """Top-k width each draft head must produce."""
        return int(self.branch[1:].max()) + 1 if self.num_nodes > 1 else 1

    @classmethod
    def from_paths(cls, paths: Sequence[Sequence[int]]) -> "TokenTree":
        # every path's prefix must also be a declared path (a node needs its parent)
        canonical = [tuple(p) for p in paths]
        if len(set(canonical)) != len(canonical):
            raise ValueError("duplicate tree paths")
        path_set = {(): 0}
        ordered = sorted(canonical, key=lambda p: (len(p), p))
        for p in ordered:
            if not p:
                raise ValueError("empty path: the root is implicit")
            if tuple(p[:-1]) not in path_set:
                raise ValueError(f"path {p} missing parent prefix {p[:-1]}")
            path_set[p] = len(path_set)

        n = len(path_set)
        depths = np.zeros((n,), dtype=np.int32)
        parents = np.full((n,), -1, dtype=np.int32)
        branch = np.zeros((n,), dtype=np.int32)
        ancestor = np.zeros((n, n), dtype=bool)
        children: List[List[int]] = [[] for _ in range(n)]
        for p, idx in path_set.items():
            depths[idx] = len(p)
            ancestor[idx, idx] = True
            if p:
                parent = path_set[tuple(p[:-1])]
                parents[idx] = parent
                branch[idx] = p[-1]
                children[parent].append(idx)
                ancestor[idx] |= ancestor[parent]
        return cls(paths=tuple(ordered), num_nodes=n, depths=depths, parents=parents,
                   branch=branch, ancestor_mask=ancestor,
                   children=tuple(tuple(c) for c in children))

    # ------------------------------------------------------------------ acceptance
    def walk_accept(self, node_tokens: np.ndarray, target_tokens: np.ndarray
                    ) -> Tuple[List[int], int]:
        """Greedy tree acceptance for one row (host side, ≈ the reference's CPU-side
        Medusa acceptance in `utils/hf_adapter.py:798-925`).

        node_tokens (N,): the drafted token at each node (node 0 = committed root).
        target_tokens (N,): the target's argmax emitted AT each node.

        Returns (accepted_node_indices, bonus_token): the accepted nodes' drafted
        tokens are committed in order, then ``bonus`` (the target's prediction at the
        last accepted node) commits as the correction/bonus token.
        """
        cur = 0
        accepted: List[int] = []
        while True:
            want = int(target_tokens[cur])
            nxt = next((c for c in self.children[cur]
                        if int(node_tokens[c]) == want), None)
            if nxt is None:
                return accepted, want
            accepted.append(nxt)
            cur = nxt

    def assemble_tokens(self, root_token: np.ndarray,
                        head_topk: np.ndarray) -> np.ndarray:
        """Build the (B, N) node-token matrix for the next verify call.

        head_topk (B, num_heads, K): per-draft-head top-k candidate ids at the
        current root. Node at depth d takes head d-1's candidate `branch[node]`.
        """
        b = root_token.shape[0]
        out = np.zeros((b, self.num_nodes), dtype=np.int32)
        out[:, 0] = root_token
        for i in range(1, self.num_nodes):
            out[:, i] = head_topk[:, self.depths[i] - 1, self.branch[i]]
        return out
