"""GQA head-sharding strategies.

≈ reference `modules/attention/gqa.py` (`determine_sharding_strategy` :89,
`get_shardable_head_counts` :105, replicate/pad helpers :164-271). On TPU the only case
needing weight surgery is kv-head replication when tp_degree exceeds (or doesn't divide)
the kv-head count: kv heads are repeat-interleaved at conversion time so the ``kv_heads``
axis shards evenly; query heads keep their order because consecutive q-head groups map to
consecutive replicated kv heads.
"""

from __future__ import annotations

import enum
from typing import Tuple

import numpy as np


class GQASharding(enum.Enum):
    NATIVE = "native"                       # kv_heads % tp == 0, no surgery
    REPLICATE = "replicate-to-tp-degree"    # repeat kv heads so tp divides the count


def determine_sharding_strategy(tp_degree: int, num_kv_heads: int) -> GQASharding:
    if num_kv_heads % tp_degree == 0:
        return GQASharding.NATIVE
    if tp_degree % num_kv_heads == 0:
        return GQASharding.REPLICATE
    raise ValueError(
        f"kv_heads={num_kv_heads} and tp={tp_degree} are incompatible: one must divide "
        f"the other (reference supports the same constraint via pad/replicate)")


def replication_factor(tp_degree: int, num_kv_heads: int) -> int:
    strategy = determine_sharding_strategy(tp_degree, num_kv_heads)
    return tp_degree // num_kv_heads if strategy is GQASharding.REPLICATE else 1


def replicate_kv_weight(w: np.ndarray, num_kv_heads: int, head_dim: int,
                        factor: int) -> np.ndarray:
    """Repeat-interleave kv heads in a (hidden, kv_heads*head_dim) projection weight."""
    if factor == 1:
        return w
    hidden = w.shape[0]
    w = w.reshape(hidden, num_kv_heads, head_dim)
    w = np.repeat(w, factor, axis=1)
    return w.reshape(hidden, num_kv_heads * factor * head_dim)


def replicate_kv_bias(b: np.ndarray, num_kv_heads: int, head_dim: int,
                      factor: int) -> np.ndarray:
    if factor == 1:
        return b
    b = b.reshape(num_kv_heads, head_dim)
    return np.repeat(b, factor, axis=0).reshape(-1)


def effective_kv_heads(tp_degree: int, num_kv_heads: int) -> int:
    return num_kv_heads * replication_factor(tp_degree, num_kv_heads)
