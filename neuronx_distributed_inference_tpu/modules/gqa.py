"""GQA head-sharding strategies.

≈ reference `modules/attention/gqa.py` (`determine_sharding_strategy` :89,
`get_shardable_head_counts` :105, replicate/pad helpers :164-271). Weight surgery at
conversion time makes any (tp, kv_heads) combination shardable:

- kv heads repeat-interleave by ``f = lcm(kv, tp) / kv`` so the ``kv_heads`` axis
  shards evenly;
- when the replication factor does not divide the per-kv-head query group, query
  heads PAD with zero heads (zero wq rows, zero wo columns — the padded heads'
  outputs vanish through wo), the TPU analog of the reference's interleaved-pad
  strategy.
"""

from __future__ import annotations

import enum
import math
from typing import Tuple

import numpy as np


class GQASharding(enum.Enum):
    NATIVE = "native"                       # kv_heads % tp == 0, no surgery
    REPLICATE = "replicate-to-tp-degree"    # repeat kv heads so tp divides the count


def determine_sharding_strategy(tp_degree: int, num_kv_heads: int) -> GQASharding:
    if num_kv_heads % tp_degree == 0:
        return GQASharding.NATIVE
    return GQASharding.REPLICATE


def replication_factor(tp_degree: int, num_kv_heads: int) -> int:
    if num_kv_heads % tp_degree == 0:
        return 1
    return math.lcm(num_kv_heads, tp_degree) // num_kv_heads


def padded_group_size(tp_degree: int, num_q_heads: int, num_kv_heads: int) -> int:
    """Query heads per REPLICATED kv head (padded up so every replica gets an equal
    group; padded heads are zero)."""
    f = replication_factor(tp_degree, num_kv_heads)
    group = num_q_heads // num_kv_heads
    return -(-group // f)


def effective_q_heads(tp_degree: int, num_q_heads: int, num_kv_heads: int) -> int:
    return (effective_kv_heads(tp_degree, num_kv_heads)
            * padded_group_size(tp_degree, num_q_heads, num_kv_heads))


def expand_q_weight(w: np.ndarray, num_q_heads: int, num_kv_heads: int,
                    head_dim: int, tp_degree: int) -> np.ndarray:
    """Reorder/pad a (hidden, q_heads*head_dim) query projection for the replicated
    kv layout: each original kv group's q heads split across the f replicas, padded
    with zero heads."""
    f = replication_factor(tp_degree, num_kv_heads)
    if f == 1:
        return w
    hidden = w.shape[0]
    group = num_q_heads // num_kv_heads
    gp = padded_group_size(tp_degree, num_q_heads, num_kv_heads)
    w = w.reshape(hidden, num_kv_heads, group, head_dim)
    out = np.zeros((hidden, num_kv_heads, f, gp, head_dim), dtype=w.dtype)
    for r in range(f):
        take = w[:, :, r * gp : (r + 1) * gp, :]
        out[:, :, r, : take.shape[2], :] = take
    return out.reshape(hidden, -1)


def expand_o_weight(w: np.ndarray, num_q_heads: int, num_kv_heads: int,
                    head_dim: int, tp_degree: int) -> np.ndarray:
    """Matching reorder/pad of the (q_heads*head_dim, hidden) output projection."""
    f = replication_factor(tp_degree, num_kv_heads)
    if f == 1:
        return w
    hidden = w.shape[1]
    group = num_q_heads // num_kv_heads
    gp = padded_group_size(tp_degree, num_q_heads, num_kv_heads)
    w = w.reshape(num_kv_heads, group, head_dim, hidden)
    out = np.zeros((num_kv_heads, f, gp, head_dim, hidden), dtype=w.dtype)
    for r in range(f):
        take = w[:, r * gp : (r + 1) * gp, :, :]
        out[:, r, : take.shape[1], :, :] = take
    return out.reshape(-1, hidden)


def expand_q_bias(b: np.ndarray, num_q_heads: int, num_kv_heads: int,
                  head_dim: int, tp_degree: int) -> np.ndarray:
    f = replication_factor(tp_degree, num_kv_heads)
    if f == 1:
        return b
    group = num_q_heads // num_kv_heads
    gp = padded_group_size(tp_degree, num_q_heads, num_kv_heads)
    b = b.reshape(num_kv_heads, group, head_dim)
    out = np.zeros((num_kv_heads, f, gp, head_dim), dtype=b.dtype)
    for r in range(f):
        take = b[:, r * gp : (r + 1) * gp, :]
        out[:, r, : take.shape[1], :] = take
    return out.reshape(-1)


def replicate_kv_weight(w: np.ndarray, num_kv_heads: int, head_dim: int,
                        factor: int) -> np.ndarray:
    """Repeat-interleave kv heads in a (hidden, kv_heads*head_dim) projection weight."""
    if factor == 1:
        return w
    hidden = w.shape[0]
    w = w.reshape(hidden, num_kv_heads, head_dim)
    w = np.repeat(w, factor, axis=1)
    return w.reshape(hidden, num_kv_heads * factor * head_dim)


def replicate_kv_bias(b: np.ndarray, num_kv_heads: int, head_dim: int,
                      factor: int) -> np.ndarray:
    if factor == 1:
        return b
    b = b.reshape(num_kv_heads, head_dim)
    return np.repeat(b, factor, axis=0).reshape(-1)


def effective_kv_heads(tp_degree: int, num_kv_heads: int) -> int:
    return num_kv_heads * replication_factor(tp_degree, num_kv_heads)
