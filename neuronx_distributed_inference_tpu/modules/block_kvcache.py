"""Paged (block) KV cache: block tables, slot-mapped writes, gathered reads, and a
host-side block allocator with prefix caching.

≈ reference `modules/kvcache/block_kv_cache_manager.py` (`BlockKVCacheManager` :11-374:
cache = (num_blocks, block_size, H, D), gather via active_block_table, write via
slot_mapping) and `modules/kvcache/utils.py` (`get_active_block_table` :40-). TPU
redesign:

- Device layout is layer-stacked ``(L, num_blocks, H_kv, block_size, D)``: each
  (block, head) holds a contiguous (block_size, D) tile run — the layout the Pallas
  ragged paged decode kernel streams (ops/paged_decode.py) — and the model's
  `lax.scan` over layers carries one (NB, H, BS, D) slice per step, exactly like the
  dense cache's (B, H, S, D) with blocks in the batch position.
- Writes flatten blocks to a (NB*BS, H, D) slot view and scatter rows at
  ``slot = block_id * block_size + offset`` with out-of-bounds drop semantics — padding
  rows use slot -1 and vanish, replacing the reference's garbage-position padding writes
  (`kv_cache_manager.py:463-466`).
- Reads gather each sequence's blocks through its block table row into a contiguous
  (B, H, S_logical, D) view; logical order is preserved, so the dense position-based
  causal masks apply unchanged.
- The host `BlockAllocator` owns the free list and (optionally) a prefix cache: chained
  content hashes map full blocks to physical ids with refcounts, so shared prompt
  prefixes reuse blocks across sequences (the reference's prefix-caching 2D bucket flow,
  `model_wrapper.py:918-1142`, redesigned as vLLM-style block reuse).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

PagedKVCache = Dict[str, jnp.ndarray]

# logical axes for sharding the stacked paged cache (blocks stay unsharded — each
# shard holds full blocks for its kv_heads slice)
PAGED_CACHE_LOGICAL = ("layers", None, "kv_heads", None, None)


@dataclass(frozen=True)
class PagedKVCacheSpec:
    num_layers: int
    num_blocks: int
    block_size: int
    num_kv_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.bfloat16

    @property
    def shape(self) -> Tuple[int, int, int, int, int]:
        return (self.num_layers, self.num_blocks, self.num_kv_heads,
                self.block_size, self.head_dim)

    @property
    def num_slots(self) -> int:
        return self.num_blocks * self.block_size


def init_paged_cache(spec: PagedKVCacheSpec) -> PagedKVCache:
    return {
        "k": jnp.zeros(spec.shape, dtype=spec.dtype),
        "v": jnp.zeros(spec.shape, dtype=spec.dtype),
    }


def write_slots(cache_layer: jnp.ndarray, new_kv: jnp.ndarray,
                slot_mapping: jnp.ndarray) -> jnp.ndarray:
    """Scatter (B, H, T, D) new tokens at flat slots (B, T) int32.

    ``slot = block_id * block_size + offset``; negative slots are dropped (padding).
    ≈ the reference's index_put write strategy (`block_kv_cache_manager.py:268-374`).
    """
    nb, h, bs, d = cache_layer.shape
    b, hh, t, dd = new_kv.shape
    from .kvcache import to_cache_dtype

    rows = to_cache_dtype(new_kv.transpose(0, 2, 1, 3).reshape(b * t, hh, dd),
                          cache_layer.dtype)                # (N, H, D)
    slots = slot_mapping.reshape(b * t)
    # negative indices WRAP in jnp (NumPy semantics) — only indices >= size are dropped
    # by mode="drop"; remap the -1 sentinel to an explicitly out-of-bounds block, else
    # every padding write would clobber a live slot.
    blk = jnp.where(slots < 0, nb, slots // bs)
    off = jnp.where(slots < 0, 0, slots % bs)
    # advanced indices (blk, off) separated by the head slice -> result (N, H, D)
    return cache_layer.at[blk, :, off, :].set(rows, mode="drop")


def read_seq(cache_layer: jnp.ndarray, block_table: jnp.ndarray) -> jnp.ndarray:
    """Gather (NB, H, BS, D) through block tables (B, MB) -> (B, H, MB*BS, D).

    Unused table entries may be any valid block id (masking is positional downstream).
    ≈ `get_active_block_table` + gather (`kvcache/utils.py:40-`).
    """
    gathered = jnp.take(cache_layer, block_table, axis=0)   # (B, MB, H, BS, D)
    b, mb, h, bs, d = gathered.shape
    return gathered.transpose(0, 2, 1, 3, 4).reshape(b, h, mb * bs, d)


def make_slot_mapping(block_table: np.ndarray, positions: np.ndarray,
                      num_tokens: int, block_size: int,
                      valid: Optional[np.ndarray] = None) -> np.ndarray:
    """Host helper: flat slots (B, T) for tokens written at positions
    ``positions[b] + t``. Rows with ``valid[b] == False`` (or positions beyond the
    table) get slot -1 (dropped).

    ≈ `generate_tokengen_slot_mapping` (`block_kv_cache_manager.py:376`).
    """
    b = block_table.shape[0]
    pos = positions[:, None] + np.arange(num_tokens)[None, :]       # (B, T)
    blk_idx = pos // block_size
    offset = pos % block_size
    in_range = blk_idx < block_table.shape[1]
    blk_idx = np.minimum(blk_idx, block_table.shape[1] - 1)
    phys = np.take_along_axis(block_table, blk_idx, axis=1)
    slots = phys * block_size + offset
    slots[~in_range] = -1
    if valid is not None:
        slots[~valid] = -1
    return slots.astype(np.int32)


def device_slot_advance(block_table: jnp.ndarray, positions: jnp.ndarray,
                        alive: jnp.ndarray, block_size: int) -> jnp.ndarray:
    """IN-GRAPH single-token slot mapping from DEVICE-resident positions: the
    ``lax.while_loop`` megastep's per-inner-step analog of
    :func:`make_slot_mapping` (ISSUE-10). The host cannot precompute the
    megastep's slot chunk — early exits make the executed positions
    data-dependent — so each inner step derives its own write slot from the
    authoritative device positions through the (host-pre-reserved) block
    table. Rows advance INTO pre-reserved table entries as positions cross
    block boundaries; the megastep's coverage early-exit guarantees no live
    row ever reads past its reserved run, and frozen rows get slot -1 (the
    dropped-write sentinel, same as the scan path's ``slots_live``).
    """
    mb = block_table.shape[1]
    blk_idx = jnp.minimum(positions // block_size, mb - 1)
    phys = jnp.take_along_axis(block_table, blk_idx[:, None], axis=1)[:, 0]
    slots = phys * block_size + positions % block_size
    return jnp.where(alive, slots, -1)


def make_chunk_slot_mapping(block_table: np.ndarray, positions: np.ndarray,
                            lengths: np.ndarray, num_tokens: int,
                            block_size: int) -> np.ndarray:
    """Host helper: flat slots (B, T) for per-row CONTIGUOUS token runs of
    ragged lengths — the mixed-step prefill-chunk commit shape. Row b writes
    ``lengths[b]`` tokens at positions ``positions[b] + t``; the suffix gets
    slot -1 (dropped). The result satisfies the chunk-write kernel's contract
    (live slots are a position-consecutive prefix; see
    ops/paged_decode._paged_write_kernel)."""
    valid = np.arange(num_tokens)[None, :] < np.asarray(lengths)[:, None]
    return make_slot_mapping(block_table, positions, num_tokens, block_size,
                             valid=valid)


# ---------------------------------------------------------------------------
# Host-side block allocator with prefix caching
# ---------------------------------------------------------------------------


class KVBlocksExhausted(RuntimeError):
    """The paged pool (free list + idle pool) cannot satisfy an allocation.

    A RuntimeError subclass so every existing ``except RuntimeError`` recovery
    path (preempting growth, allocation rollback, partial megastep
    reservation) keeps working, while new callers — request placement, the
    serving router's shed path — can catch exhaustion SPECIFICALLY and
    degrade (preempt-or-shed) instead of treating it as a generic crash.

    OOM forensics (serving/memledger.py): when the raising allocator carries
    a KV block ledger, the exception is stamped with ``ledger_snapshot`` —
    the owner-state breakdown and top holders (request ids, ages, SLA
    classes) at the exhaustion point, so "out of KV blocks" names who holds
    the pool instead of just that it is full."""

    ledger_snapshot: Optional[dict] = None


class BlockAllocator:
    """Free-list block allocator with optional prefix-cache reuse.

    Prefix caching: a *full* block holding tokens ``t[i*bs:(i+1)*bs]`` of some sequence
    is keyed by ``hash(prev_block_hash, tokens)``; a new sequence sharing that prefix
    maps its logical block to the same physical block (refcounted) and skips recomputing
    it. Only full blocks are shared; the trailing partial block is always private.
    """

    def __init__(self, num_blocks: int, block_size: int, enable_prefix_caching: bool = False):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.enable_prefix_caching = enable_prefix_caching
        self.free: List[int] = list(range(num_blocks - 1, -1, -1))   # pop() -> lowest last
        self.refcount: Dict[int, int] = {}
        self.hash_to_block: Dict[bytes, int] = {}
        self.block_to_hash: Dict[int, bytes] = {}

    @property
    def num_free(self) -> int:
        return len(self.free)

    def _alloc_one(self) -> int:
        if not self.free:
            raise KVBlocksExhausted("out of KV blocks")
        blk = self.free.pop()
        self.refcount[blk] = 1
        return blk

    def _release_one(self, blk: int) -> None:
        self.refcount[blk] -= 1
        if self.refcount[blk] == 0:
            del self.refcount[blk]
            h = self.block_to_hash.pop(blk, None)
            if h is not None:
                self.hash_to_block.pop(h, None)
            self.free.append(blk)

    @staticmethod
    def _chain_hash(prev: bytes, tokens: np.ndarray) -> bytes:
        m = hashlib.sha256()
        m.update(prev)
        m.update(np.ascontiguousarray(tokens, dtype=np.int32).tobytes())
        return m.digest()

    def allocate_for_prompt(self, tokens: Sequence[int]
                            ) -> Tuple[List[int], int]:
        """Allocate blocks covering ``tokens`` (+ room for the next token).

        Returns (block_ids, num_cached_tokens): with prefix caching on, leading full
        blocks already resident are shared and counted in num_cached_tokens (the caller
        may skip prefilling them). On exhaustion every block taken here is released
        before raising (clean rollback — matching native/engine.cpp).
        """
        tokens = np.asarray(tokens, dtype=np.int32)
        n = len(tokens)
        bs = self.block_size
        n_full = n // bs
        blocks: List[int] = []
        num_cached = 0
        prev = b""
        reusing = self.enable_prefix_caching
        try:
            for i in range(n_full):
                chunk = tokens[i * bs : (i + 1) * bs]
                h = self._chain_hash(prev, chunk)
                prev = h
                if reusing and h in self.hash_to_block:
                    blk = self.hash_to_block[h]
                    self.refcount[blk] += 1
                    blocks.append(blk)
                    num_cached += bs
                    continue
                reusing = False   # first miss ends the shared prefix
                blk = self._alloc_one()
                if self.enable_prefix_caching:
                    self.hash_to_block[h] = blk
                    self.block_to_hash[blk] = h
                blocks.append(blk)
            # trailing partial block (or room for the next token) is always private
            remaining = n - n_full * bs
            if remaining > 0 or n_full == len(blocks):
                blocks.append(self._alloc_one())
        except RuntimeError:
            for blk in blocks:
                self._release_one(blk)
            raise
        return blocks, num_cached

    def extend(self, blocks: List[int], seq_len: int) -> None:
        """Ensure ``blocks`` covers positions [0, seq_len); appends new blocks.
        On exhaustion the appended blocks are released and ``blocks`` restored
        (clean rollback — matching native/engine.cpp)."""
        n_in = len(blocks)
        try:
            while len(blocks) * self.block_size < seq_len:
                blocks.append(self._alloc_one())
        except RuntimeError:
            for blk in blocks[n_in:]:
                self._release_one(blk)
            del blocks[n_in:]
            raise

    def free_sequence(self, blocks: Sequence[int]) -> None:
        for blk in blocks:
            self._release_one(blk)
