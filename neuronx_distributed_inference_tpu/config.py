"""Configuration system for the TPU inference framework.

Capability parity with the reference NeuronConfig / InferenceConfig
(`/root/reference/src/neuronx_distributed_inference/models/config.py:92-997`), redesigned
as typed dataclasses instead of a kwargs bag:

- ``TpuConfig``         ≈ NeuronConfig: runtime/feature flags (parallelism degrees,
                          bucketing, dtypes, sampling, continuous batching, ...).
- ``InferenceConfig``   : wraps the HF model config attributes + a TpuConfig, with JSON
                          round-trip (save/load of ``tpu_config.json`` in a compiled dir).
- Sub-configs           ≈ OnDeviceSamplingConfig, ChunkedPrefillConfig, etc.

Validation mirrors the reference's config-time cross checks
(`models/config.py:610-686`): invalid flag combinations fail at construction, not at
trace time.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp

_DTYPE_MAP = {
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
    "float32": jnp.float32,
    "int8": jnp.int8,
    "float8_e4m3": jnp.float8_e4m3fn,
}


def to_jax_dtype(name) -> Any:
    """Map a dtype name (or jnp dtype) to the jnp dtype object."""
    if isinstance(name, str):
        if name.startswith("torch."):  # tolerate HF configs that carry torch dtypes
            name = name[len("torch."):]
        if name == "float8_e4m3fn":
            name = "float8_e4m3"
        if name not in _DTYPE_MAP:
            raise ValueError(f"unsupported dtype {name!r}; one of {sorted(_DTYPE_MAP)}")
        return _DTYPE_MAP[name]
    return name


def dtype_name(dtype) -> str:
    for k, v in _DTYPE_MAP.items():
        if v == dtype:
            return k
    return str(dtype)


@dataclass
class OnDeviceSamplingConfig:
    """On-device sampling knobs (≈ reference OnDeviceSamplingConfig,
    `models/config.py:1000-1035`)."""

    do_sample: bool = False          # False -> greedy argmax
    top_k: int = 1
    top_p: float = 1.0
    temperature: float = 1.0
    # Pre-filter to the global top-k before top-k/top-p masking, which bounds the
    # sort/cumsum to a small constant width (reference default 256).
    global_topk: int = 256
    dynamic: bool = True             # accept per-request (B, 3) sampling params at runtime
    deterministic: bool = False      # fixed PRNG seed stream for reproducible sampling
    seed: int = 0

    def validate(self) -> None:
        if self.top_k < 1 and self.top_k != -1:
            raise ValueError("top_k must be >= 1 (or -1 for 'all')")
        if not (0.0 < self.top_p <= 1.0):
            raise ValueError("top_p must be in (0, 1]")
        if self.temperature <= 0.0:
            raise ValueError("temperature must be > 0")
        if self.global_topk < 1:
            raise ValueError("global_topk must be >= 1")


@dataclass
class ChunkedPrefillConfig:
    """Chunked-prefill knobs (≈ reference ChunkedPrefillConfig)."""

    max_num_seqs: int = 8
    chunk_size: int = 512
    kernel_q_tile_size: int = 128
    kernel_kv_tile_size: int = 512


@dataclass
class SpeculationConfig:
    """Speculative-decoding knobs (draft/target; fused graph comes later rounds)."""

    speculation_length: int = 0      # 0 = disabled
    spec_batch_size: int = 1
    draft_model_path: Optional[str] = None


@dataclass
class LoraServingConfig:
    """Multi-LoRA serving knobs (≈ reference LoraServingConfig)."""

    max_loras: int = 1
    max_lora_rank: int = 16
    lora_ckpt_paths: Optional[Dict[str, str]] = None


@dataclass
class MoEHybridShardingConfig:
    """Decode-time MoE dispatch layout override (≈ reference hybrid sharding:
    different TP/EP degrees for CTE vs TKG, `models/config.py:1055-1061`, and the
    EP dispatch collective options `:602,685-686`).

    Values name mesh axes for each graph's expert-activation constraints:
    "ep", "tp", "ep_tp" (both), None (replicated), or "default" (keep the
    DEFAULT_RULES experts->ep / expert_mlp->tp layout — the prefill fields'
    default, so existing decode-only configs are unchanged). A TP-heavy
    prefill + EP-heavy decode split selects, per trace, the layout each
    phase's arithmetic intensity wants. GSPMD derives each graph's
    dispatch/combine collectives from these shardings — the TPU equivalent of
    the reference hand-picking AR_AG/RS_AG/AG_AR per sub-model — and the
    decode EP ring (parallel/overlap.expert_ring_moe) engages only when the
    decode experts land on exactly "ep"."""

    decode_experts: Optional[str] = "ep"
    decode_expert_mlp: Optional[str] = "tp"
    prefill_experts: Optional[str] = "default"
    prefill_expert_mlp: Optional[str] = "default"

    _VALID = (None, "ep", "tp", "ep_tp")

    def validate(self) -> None:
        for name in ("decode_experts", "decode_expert_mlp",
                     "prefill_experts", "prefill_expert_mlp"):
            valid = self._VALID + (("default",) if name.startswith("prefill")
                                   else ())
            if getattr(self, name) not in valid:
                raise ValueError(f"{name} must be one of {valid}")
        for phase in ("decode", "prefill"):
            e = self.mesh_axes(f"{phase}_experts")
            m = self.mesh_axes(f"{phase}_expert_mlp")
            e = () if e in (None, "default") else (
                (e,) if isinstance(e, str) else e)
            m = () if m in (None, "default") else (
                (m,) if isinstance(m, str) else m)
            if set(e) & set(m):
                raise ValueError(
                    f"{phase}_experts and {phase}_expert_mlp must use disjoint "
                    f"mesh axes (got {getattr(self, f'{phase}_experts')!r} / "
                    f"{getattr(self, f'{phase}_expert_mlp')!r})")

    def mesh_axes(self, name: str):
        v = getattr(self, name)
        return ("ep", "tp") if v == "ep_tp" else v


@dataclass
class QuantizationConfig:
    """Weight/KV quantization knobs.

    ``kv_cache_scale_mode``: "direct" casts K/V straight to the fp8 cache dtype
    (range-lossy on outlier-heavy KV); "static" stores K/σ_k, V/σ_v with calibrated
    per-(layer, kv-head) scales riding the cache pytree — σ_k folds into q and σ_v
    into the attention output, so every attend path (jnp, Pallas dense/paged)
    serves scaled caches without kernel changes. Calibrate via
    ``app.calibrate_kv_scales(sample_ids)``. ≈ reference static-scale fp8 KV
    (`modules/kvcache/kv_cache_manager.py` fp8 paths, `models/config.py:511-515`).
    """

    quantize_weights: bool = False
    # int8 | float8_e4m3 | int4 ("int4" packs the large streaming projections
    # — including MoE expert stacks — to 4 bits via the Pallas w4 matmuls,
    # ops/w4.py, and keeps the small ones int8)
    weight_dtype: str = "int8"
    kv_cache_dtype: Optional[str] = None  # None = same as model dtype
    kv_cache_scale_mode: str = "direct"   # direct | static (fp8/int8 caches)

    # int8 dynamic per-token activation quant on qkv/mlp projections (the TPU
    # rmsnorm_quant analog — int8 x int8 rides the doubled-throughput MXU path);
    # requires weight_dtype == "int8"
    activation_quant: bool = False

    @classmethod
    def for_kv_dtype(cls, kv_cache_dtype: str, **kw) -> "QuantizationConfig":
        """Config for a KV cache dtype with the right scale mode (int8 REQUIRES
        static per-head scales; fp8 defaults to direct cast) — the single place
        scripts/benches derive the pairing from."""
        mode = "static" if kv_cache_dtype == "int8" else "direct"
        return cls(kv_cache_dtype=kv_cache_dtype, kv_cache_scale_mode=mode, **kw)


@dataclass
class TpuConfig:
    """Runtime/feature configuration (≈ reference NeuronConfig,
    `models/config.py:92-608`).

    Everything the host wrapper and the traced graphs need to know that is *not* part of
    the model architecture: batch/sequence geometry, parallelism degrees, bucket ladders,
    dtypes, sampling, serving features.
    """

    # --- geometry ---
    batch_size: int = 1
    max_batch_size: int = 0          # 0 -> batch_size
    seq_len: int = 2048              # max total sequence length (context + generated)
    max_context_length: int = 0      # 0 -> seq_len
    max_new_tokens: int = 0          # informational; generate() takes an explicit arg
    n_active_tokens: int = 1         # decode width (speculation_length when speculating)

    # --- parallelism (world = dp * cp * tp * ep, pp carried for parity) ---
    tp_degree: int = 1
    dp_degree: int = 1
    cp_degree: int = 1
    ep_degree: int = 1
    pp_degree: int = 1
    sequence_parallel_enabled: bool = False
    vocab_parallel: bool = True      # shard embed/lm_head on vocab dim
    flash_decoding_enabled: bool = False
    # decode attention in batch-parallel layout over ALL chips (batch sharded over
    # dp x tp, GQA kv heads replicated) — ≈ reference attention DP
    # (`attention_process_groups.py:125-163`); the rest of the model stays TP
    attention_dp_enabled: bool = False

    # --- dtypes ---
    dtype: str = "bfloat16"
    rpl_reduce_dtype: str = "float32"   # accumulation dtype for cross-rank reductions
    logits_dtype: str = "float32"

    # --- bucketing (≈ modules/autobucketing.py) ---
    enable_bucketing: bool = True
    context_encoding_buckets: Optional[List[int]] = None   # None -> auto ladder
    token_generation_buckets: Optional[List[int]] = None
    batch_buckets: Optional[List[int]] = None

    # --- serving features ---
    is_continuous_batching: bool = False
    padding_side: str = "right"
    # decode tokens generated per device call (lax.scan chunk); amortizes dispatch
    # latency — the TPU-native answer to the reference's async double-buffering
    decode_chunk_size: int = 32
    attention_kernel_enabled: Optional[bool] = None  # None = auto (TPU yes, CPU no)
    # Pallas stacked-cache decode kernels (KV-write DMA + length-aware attention,
    # ≈ reference TKG kernels); None = auto (TPU yes when the arch supports it)
    decode_kernel_enabled: Optional[bool] = None
    moe_hybrid_sharding: Optional[MoEHybridShardingConfig] = None
    async_mode: bool = False
    # store quantized attention stacks transposed ((L, out, in) "qT" payloads).
    # Measured NEUTRAL on v5e (round 4): the decode scan's wq/wo slice copies
    # move to wk/wv instead of disappearing — XLA re-picks a copy for one QKV
    # operand either way (ROUND4_NOTES §9). Kept as an opt-in knob for other
    # geometries/compilers; default off.
    transpose_attention_stacks: bool = False
    paged_attention_enabled: bool = False
    pa_num_blocks: int = 0
    pa_block_size: int = 128

    # --- sub-configs ---
    on_device_sampling_config: Optional[OnDeviceSamplingConfig] = None
    chunked_prefill_config: Optional[ChunkedPrefillConfig] = None
    speculation_config: Optional[SpeculationConfig] = None
    lora_serving_config: Optional[LoraServingConfig] = None
    quantization_config: Optional[QuantizationConfig] = None

    def __post_init__(self) -> None:
        if self.max_batch_size == 0:
            self.max_batch_size = self.batch_size
        if self.max_context_length == 0:
            self.max_context_length = self.seq_len
        self.validate()

    # ≈ reference NeuronConfig validation `models/config.py:610-686`
    def validate(self) -> None:
        if self.padding_side not in ("right", "left"):
            raise ValueError("padding_side must be 'right' or 'left'")
        if self.seq_len < 1 or self.batch_size < 1:
            raise ValueError("seq_len and batch_size must be >= 1")
        if self.max_context_length > self.seq_len:
            raise ValueError("max_context_length must be <= seq_len")
        for deg_name in ("tp_degree", "dp_degree", "cp_degree", "ep_degree", "pp_degree"):
            if getattr(self, deg_name) < 1:
                raise ValueError(f"{deg_name} must be >= 1")
        if self.sequence_parallel_enabled and \
                self.seq_len % (self.cp_degree * self.tp_degree) != 0:
            # residuals shard their sequence dim over BOTH model axes (the
            # act_seq rule maps to (cp, tp), parallel/sharding.py), so the
            # divisibility requirement is the product, not tp alone
            raise ValueError(
                f"sequence_parallel_enabled requires seq_len divisible by "
                f"cp_degree * tp_degree (seq_len={self.seq_len}, "
                f"cp_degree={self.cp_degree}, tp_degree={self.tp_degree}, "
                f"cp*tp={self.cp_degree * self.tp_degree})")
        if self.dp_degree > 1 and not self.is_continuous_batching:
            raise ValueError("attention data parallelism requires continuous batching")
        if self.attention_dp_enabled and \
                self.max_batch_size % (self.dp_degree * self.tp_degree) != 0:
            raise ValueError(
                "attention_dp_enabled requires max_batch_size divisible by "
                "dp_degree * tp_degree (batch is sharded over both axes)")
        if self.paged_attention_enabled and self.pa_num_blocks < 1:
            raise ValueError("paged attention requires pa_num_blocks >= 1")
        q = self.quantization_config
        if q is not None and q.quantize_weights:
            from .ops.quantization import WEIGHT_DTYPES

            if q.weight_dtype not in WEIGHT_DTYPES:
                raise ValueError(f"weight_dtype must be one of {WEIGHT_DTYPES}")
        if q is not None and q.kv_cache_scale_mode not in ("direct", "static"):
            raise ValueError("kv_cache_scale_mode must be 'direct' or 'static'")
        if q is not None and q.activation_quant and (
                not q.quantize_weights or q.weight_dtype != "int8"):
            raise ValueError("activation_quant requires int8 weight quantization")
        if q is not None and q.kv_cache_scale_mode == "static" and (
                q.kv_cache_dtype is None
                or not (q.kv_cache_dtype.startswith("float8")
                        or q.kv_cache_dtype == "int8")):
            raise ValueError("kv_cache_scale_mode='static' requires an fp8 or "
                             "int8 kv_cache_dtype (e.g. float8_e4m3, int8)")
        if (q is not None and q.kv_cache_dtype == "int8"
                and q.kv_cache_scale_mode != "static"):
            raise ValueError("int8 kv_cache_dtype requires "
                             "kv_cache_scale_mode='static' (an unscaled round "
                             "to int8 destroys K/V values)")
        if self.on_device_sampling_config is not None:
            self.on_device_sampling_config.validate()
        if self.moe_hybrid_sharding is not None:
            self.moe_hybrid_sharding.validate()
        for cfg, bound, name in (
                (self.context_encoding_buckets, self.max_context_length,
                 "context_encoding_buckets"),
                (self.token_generation_buckets, self.seq_len,
                 "token_generation_buckets")):
            if cfg is not None:
                if len(cfg) == 0:
                    raise ValueError(f"{name} must be non-empty (or None for auto)")
                if sorted(cfg) != list(cfg) or len(set(cfg)) != len(cfg):
                    raise ValueError(f"{name} must be strictly increasing")
                if cfg[-1] > bound:
                    raise ValueError(f"largest {name} bucket {cfg[-1]} exceeds {bound}")

    @property
    def world_size(self) -> int:
        # orthogonal mesh axes (see parallel/mesh.py); pp carried for parity, degree 1
        return (self.tp_degree * self.dp_degree * self.cp_degree * self.ep_degree
                * self.pp_degree)

    @property
    def jax_dtype(self):
        return to_jax_dtype(self.dtype)

    @property
    def kv_cache_jax_dtype(self):
        q = self.quantization_config
        if q is not None and q.kv_cache_dtype is not None:
            return to_jax_dtype(q.kv_cache_dtype)
        return self.jax_dtype


# ---------------------------------------------------------------------------
# JSON round-trip helpers
# ---------------------------------------------------------------------------

_SUBCONFIG_TYPES = {
    "on_device_sampling_config": OnDeviceSamplingConfig,
    "chunked_prefill_config": ChunkedPrefillConfig,
    "speculation_config": SpeculationConfig,
    "lora_serving_config": LoraServingConfig,
    "quantization_config": QuantizationConfig,
    "moe_hybrid_sharding": MoEHybridShardingConfig,
}


def _tpu_config_to_dict(cfg: TpuConfig) -> Dict[str, Any]:
    return dataclasses.asdict(cfg)


def _tpu_config_from_dict(d: Dict[str, Any]) -> TpuConfig:
    d = dict(d)
    for key, typ in _SUBCONFIG_TYPES.items():
        if d.get(key) is not None:
            d[key] = typ(**d[key])
    known = {f.name for f in dataclasses.fields(TpuConfig)}
    unknown = set(d) - known
    if unknown:
        raise ValueError(f"unknown TpuConfig keys: {sorted(unknown)}")
    return TpuConfig(**d)


class InferenceConfig:
    """Model-architecture config + TpuConfig, with JSON round-trip.

    ≈ reference InferenceConfig (`models/config.py:886-997`): carries arbitrary HF config
    attributes (hidden_size, num_attention_heads, ...) as plain attributes, plus
    ``tpu_config``. ``save``/``load`` persist to ``tpu_config.json`` in a compiled
    artifact directory.
    """

    CONFIG_FILE = "tpu_config.json"

    # attrs most models need; subclasses may extend (≈ get_required_attributes)
    REQUIRED_ATTRIBUTES: Tuple[str, ...] = ()

    def __init__(self, tpu_config: TpuConfig, load_config=None, metadata=None, **kwargs):
        self.tpu_config = tpu_config
        self.metadata = metadata or {}
        if load_config is not None:
            load_config(self)   # callable that populates attributes (≈ load_pretrained_config)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self.add_derived_config()
        self.validate()

    def add_derived_config(self) -> None:
        """Hook for architecture subclasses to derive attributes."""

    def validate(self) -> None:
        missing = [a for a in self.get_required_attributes() if not hasattr(self, a)]
        if missing:
            raise ValueError(f"InferenceConfig missing required attributes: {missing}")

    def get_required_attributes(self) -> Tuple[str, ...]:
        return self.REQUIRED_ATTRIBUTES

    # --- serialization -----------------------------------------------------
    def to_json_dict(self) -> Dict[str, Any]:
        d = {
            k: v
            for k, v in self.__dict__.items()
            if k not in ("tpu_config",) and _is_jsonable(v)
        }
        d["tpu_config"] = _tpu_config_to_dict(self.tpu_config)
        d["_config_class"] = f"{type(self).__module__}.{type(self).__qualname__}"
        return d

    def to_json_string(self) -> str:
        return json.dumps(self.to_json_dict(), indent=2, sort_keys=True)

    def save(self, directory: str) -> str:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, self.CONFIG_FILE)
        with open(path, "w") as f:
            f.write(self.to_json_string())
        return path

    @classmethod
    def from_json_dict(cls, d: Dict[str, Any]) -> "InferenceConfig":
        d = dict(d)
        cls_path = d.pop("_config_class", None)
        config_cls = cls
        if cls_path is not None:
            # reflection-based reload, like the reference storing __module__/__name__
            # (`models/config.py:915-997`)
            mod_name, _, qualname = cls_path.rpartition(".")
            import importlib

            try:
                mod = importlib.import_module(mod_name)
                config_cls = getattr(mod, qualname)
            except (ImportError, AttributeError):
                config_cls = cls
        tpu_config = _tpu_config_from_dict(d.pop("tpu_config"))
        obj = config_cls.__new__(config_cls)
        obj.tpu_config = tpu_config
        obj.metadata = d.pop("metadata", {})
        for k, v in d.items():
            setattr(obj, k, v)
        obj.add_derived_config()
        obj.validate()
        return obj

    @classmethod
    def load(cls, directory: str) -> "InferenceConfig":
        path = os.path.join(directory, cls.CONFIG_FILE)
        with open(path) as f:
            return cls.from_json_dict(json.load(f))


def _is_jsonable(v: Any) -> bool:
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False


def load_pretrained_config(model_path_or_config) -> Any:
    """Return a ``load_config`` callable populating an InferenceConfig from a HF model dir
    (reads ``config.json``) or an in-memory dict / transformers config.

    ≈ reference `utils/hf_adapter.py:36` (load_pretrained_config).
    """

    def _load(cfg: InferenceConfig) -> None:
        src = model_path_or_config
        if isinstance(src, str):
            with open(os.path.join(src, "config.json")) as f:
                d = json.load(f)
        elif isinstance(src, dict):
            d = dict(src)
        else:  # transformers PretrainedConfig
            d = src.to_dict()
        d.pop("torch_dtype", None)
        for k, v in d.items():
            if not k.startswith("_"):
                setattr(cfg, k, v)

    return _load
