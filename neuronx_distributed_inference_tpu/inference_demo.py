"""CLI demo: load → (accuracy check) → generate → benchmark.

≈ reference `inference_demo.py` (arg parser :69-408, `run_inference` :493, console
script `inference_demo` :782). Flags mirror the TpuConfig surface 1:1 the way the
reference's flags mirror NeuronConfig.

Usage:
    python -m neuronx_distributed_inference_tpu.inference_demo \
        --model-path /path/to/hf_ckpt --model-type llama \
        --tp-degree 8 --batch-size 2 --seq-len 1024 --max-context-length 512 \
        --prompt "I believe the meaning of life is" \
        --check-accuracy-mode logit-matching --benchmark
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import List, Optional

import numpy as np

from .config import OnDeviceSamplingConfig, TpuConfig
from .models import get_model_cls
from .utils.benchmark import benchmark_sampling

logger = logging.getLogger("tpu-inference")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="TPU inference demo")
    p.add_argument("--model-path", default=None,
                   help="HF checkpoint directory (optional with "
                        "--artifacts-path)")
    p.add_argument("--artifacts-path", default=None, metavar="DIR",
                   help="warm start from a serving-artifact dir saved by "
                        "--save-artifacts: skips HF ingest + quantization and "
                        "reuses the dir's compile cache (≈ reference "
                        "--skip-compile)")
    p.add_argument("--save-artifacts", default=None, metavar="DIR",
                   help="after load, persist config + converted/quantized "
                        "weights + compile cache dir for warm starts")
    p.add_argument("--model-type", default=None,
                   help="model family (default: read model_type from config.json)")
    p.add_argument("--compiled-path", default=None,
                   help="directory for saved config artifacts")

    g = p.add_argument_group("geometry")
    g.add_argument("--batch-size", type=int, default=1)
    g.add_argument("--seq-len", type=int, default=2048)
    g.add_argument("--max-context-length", type=int, default=0)
    g.add_argument("--max-new-tokens", type=int, default=64)

    g = p.add_argument_group("parallelism")
    g.add_argument("--tp-degree", type=int, default=1)
    g.add_argument("--dp-degree", type=int, default=1)
    g.add_argument("--cp-degree", type=int, default=1)
    g.add_argument("--ep-degree", type=int, default=1)
    g.add_argument("--pp-degree", type=int, default=1,
                   help="accepted for config parity; must be 1 (same as the "
                        "reference, whose pp is a no-op)")
    g.add_argument("--mlp-cp-degree", type=int, default=None,
                   help="MLP-CP is structural here: the mlp logical axis "
                        "already shards over (cp, tp); value must equal "
                        "cp-degree when given")
    g.add_argument("--moe-tp-degree", dest="moe_tkg_tp", type=int, default=None,
                   help="decode-graph MoE expert_mlp axis override (hybrid "
                        "sharding): 0 replicates, >0 shards over tp")
    g.add_argument("--moe-ep-degree", dest="moe_tkg_ep", type=int, default=None,
                   help="decode-graph MoE experts axis override (hybrid "
                        "sharding): 0 replicates, >0 shards over ep")

    g = p.add_argument_group("parallelism (advanced)")
    g.add_argument("--sequence-parallel", action="store_true",
                   help="shard prefill activations along seq (sp)")
    g.add_argument("--attention-dp", action="store_true",
                   help="decode attention batch-parallel over dp x tp "
                        "(replicated GQA kv heads)")
    g.add_argument("--flash-decoding", action="store_true",
                   help="KV-seq-sharded decode over the cp axis (flash decoding; "
                        "requires --cp-degree > 1)")
    g.add_argument("--no-vocab-parallel", dest="vocab_parallel",
                   action="store_false", default=True)

    g = p.add_argument_group("execution")
    g.add_argument("--dtype", default="bfloat16",
                   choices=["bfloat16", "float16", "float32"])
    g.add_argument("--enable-bucketing", action="store_true", default=True)
    g.add_argument("--no-bucketing", dest="enable_bucketing", action="store_false")
    g.add_argument("--context-encoding-buckets", type=int, nargs="*", default=None)
    g.add_argument("--token-generation-buckets", type=int, nargs="*", default=None)
    g.add_argument("--decode-chunk-size", type=int, default=32)
    g.add_argument("--transpose-attention-stacks", action="store_true",
                   help="store quantized attention stacks transposed "
                        "((L, out, in) qT payloads) — measured neutral on "
                        "v5e, opt-in for other geometries (ops/quantization)")
    g.add_argument("--async-mode", action="store_true",
                   help="pipeline decode-chunk dispatch ahead of the host sync")
    g.add_argument("--async-depth", type=int, default=None,
                   help="dispatch-ahead pipeline depth for --serve (chunks in "
                        "flight before the host syncs the oldest; default 2, "
                        "eos/max-new stops tracked on device)")
    g.add_argument("--attention-kernel", dest="attention_kernel", default=None,
                   action="store_true",
                   help="force the Pallas flash prefill kernel on")
    g.add_argument("--decode-kernel", dest="decode_kernel", default=None,
                   action="store_true",
                   help="force the Pallas stacked-cache decode path on")
    g.add_argument("--batch-buckets", type=int, nargs="*", default=None,
                   help="batch-dim buckets (small request batches run smaller "
                        "graphs); must end at the max batch size")
    g.add_argument("--cpu", action="store_true",
                   help="force the CPU backend (debug / no-accelerator runs)")
    g.add_argument("--compilation-cache-dir", default=None,
                   help="persistent XLA compile cache (utils/runtime_env.py)")

    g = p.add_argument_group("serving features")
    g.add_argument("--continuous-batching", action="store_true")
    g.add_argument("--paged-attention", action="store_true")
    g.add_argument("--pa-num-blocks", type=int, default=0)
    g.add_argument("--pa-block-size", type=int, default=128)
    g.add_argument("--quantize-weights", choices=["int8", "float8_e4m3", "int4"],
                   default=None, help="weight-only quantization dtype")
    g.add_argument("--kv-cache-scale-mode", choices=["direct", "static"],
                   default=None,
                   help="direct cast, or calibrated static per-head scales "
                        "(default: static for int8 KV, direct for fp8)")
    g.add_argument("--kv-cache-dtype", default=None,
                   choices=["float8_e4m3", "float8_e5m2", "int8"],
                   help="KV cache dtype (int8 rides the MXU-native attend "
                        "kernels and requires static scales)")
    g.add_argument("--lora-ckpt", action="append", default=None, metavar="NAME=DIR",
                   help="repeatable; PEFT adapter dirs for multi-LoRA serving")
    g.add_argument("--max-loras", type=int, default=1)
    g.add_argument("--max-lora-rank", type=int, default=16)
    g.add_argument("--dynamic-lora", action="store_true",
                   help="host-side adapter store with LRU device-slot swapping "
                        "(adapters registered from --lora-ckpt)")
    g.add_argument("--adapter-names", default=None,
                   help="comma-separated adapter name per prompt row "
                        "('-' = base model); requires --dynamic-lora")
    g.add_argument("--replicas", type=int, default=1, metavar="N",
                   help="with --serve: shard serving over N engine replicas "
                        "(independent runners on shared weights) behind the "
                        "prefix-affinity router (serving/router.py)")
    g.add_argument("--kv-host-tier", action="store_true",
                   help="with --serve + paged attention: tier cold paged KV "
                        "blocks to host RAM (serving/kv_tiering.py) — evicted "
                        "on headroom pressure, re-admitted bit-identically "
                        "on prefix hits")
    g.add_argument("--kv-tier-blocks", type=int, default=1024, metavar="N",
                   help="host-RAM tier capacity in KV blocks (default 1024)")
    g.add_argument("--cluster-kv-blocks", type=int, default=0, metavar="N",
                   help="with --kv-host-tier: attach a fleet-wide "
                        "content-addressed cluster KV store of N blocks "
                        "(serving/cluster_kv.py) under per-replica host "
                        "tiers — spilled prefixes dedup by content hash and "
                        "serve cross-replica through the audited readmit "
                        "scatter (0 = off)")
    g.add_argument("--pool-split", default=None, metavar="P:D",
                   help="with --serve --replicas N: disaggregate the fleet "
                        "into P prefill-pool + D decode-pool replicas "
                        "(P+D=N) under the remote_prefill policy — arrivals "
                        "prefill on the P pool, then their KV blocks hand "
                        "off LIVE to the D pool for decode "
                        "(serving/pools.py). Requires --paged-attention")
    g.add_argument("--handoff-channel", default="device",
                   choices=("device", "tier"),
                   help="with --pool-split: how handed-off KV blocks move — "
                        "'device' (bucketed gather/scatter sessions, "
                        "cb.paged.kv_handoff) or 'tier' (through the "
                        "checksummed host tier; requires --kv-host-tier)")
    g.add_argument("--sla-classes", default=None, metavar="SPEC",
                   help="with --serve: SLA class set (serving/sla.py "
                        "grammar, e.g. \"interactive:priority=0,weight=4;"
                        "batch:priority=1,weight=1\"; the literal "
                        "\"default\" = the stock interactive/standard/batch "
                        "set). Turns on weighted-fair mixed-step prefill "
                        "budgets in every runner and — on the routed path — "
                        "priority placement, preemptive priorities, and the "
                        "SLO-driven brown-out ladder")
    g.add_argument("--inject-faults", default=None, metavar="SPEC",
                   help="with --serve --replicas N: drive the routed fleet "
                        "through the deterministic fault injector "
                        "(serving/faults.py) — semicolon-separated "
                        "'kind[@replica][:at_step=N|every_n=N,...]' entries, "
                        "e.g. 'death@0:at_step=4;corrupt@1:every_n=1,once=1'. "
                        "Kinds: exception, stall, death, alloc, corrupt, "
                        "truncate. The router supervises: failures retry "
                        "with backoff, dead replicas FAIL and their streams "
                        "auto-recover onto survivors")
    g.add_argument("--serve", action="store_true",
                   help="drive the prompts through the continuous-batching "
                        "runner (slot-based serving; honors --paged-attention "
                        "and prefix caching)")
    g.add_argument("--prefill-chunk", type=int, default=0,
                   help="serve with MIXED prefill+decode steps (paged only): "
                        "prompts stream as chunk rows of this bucket inside "
                        "the decode dispatches (the token-budget scheduler)")
    g.add_argument("--prefill-token-budget", type=int, default=0,
                   help="max prompt tokens packed per mixed serving step "
                        "(default 2x --prefill-chunk)")
    g.add_argument("--megastep", type=int, default=0, metavar="K",
                   help="with --serve + paged attention: run plain decode as "
                        "device-resident MEGASTEPS — one jitted "
                        "lax.while_loop of up to K inner steps per dispatch "
                        "with on-device scheduler state and in-graph early "
                        "exits (bs=1 pays the dispatch floor once per K "
                        "tokens instead of once per token)")
    g.add_argument("--megastep-ring", type=int, default=0, metavar="N",
                   help="with --megastep: emitted-token ring capacity "
                        "(default K) — the megastep yields for host service "
                        "when the ring fills, bounding commit latency "
                        "independently of K")
    g.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="with --serve: write the final metrics registry as "
                        "Prometheus text exposition to PATH (enables serving "
                        "telemetry, utils/metrics.py)")
    g.add_argument("--trace-out", default=None, metavar="PATH",
                   help="with --serve: write the step timeline + request "
                        "lifecycle as Chrome/Perfetto trace-event JSON to "
                        "PATH (enables serving telemetry)")
    g.add_argument("--events-out", default=None, metavar="PATH",
                   help="with --serve: spool per-request lifecycle and step "
                        "events to PATH as JSONL while serving (enables "
                        "serving telemetry)")
    g.add_argument("--stats-interval", type=int, default=0, metavar="N",
                   help="with --serve: log a runner.stats() JSON snapshot "
                        "every N serving steps (enables serving telemetry)")
    g.add_argument("--slo", default=None, metavar="SPEC",
                   help="with --serve: rolling-window SLO targets as "
                        "key=value pairs (utils/slo.py), e.g. "
                        "'ttft_p99_ms=500,queue_p99_ms=200,window_s=30'. "
                        "Evaluated every --slo-interval steps; exports the "
                        "serving_slo_healthy gauge + structured violation "
                        "logs (enables serving telemetry)")
    g.add_argument("--slo-interval", type=int, default=25, metavar="N",
                   help="with --slo: serving steps between SLO evaluations "
                        "(N >= 1; 0 disables periodic evaluation — the "
                        "final evaluation at exit still runs)")
    g.add_argument("--debug-bundle", default=None, metavar="PATH",
                   help="with --serve: write a flight-recorder debug bundle "
                        "(config, versions, metrics, last-N step records "
                        "with drained device counters) to PATH at exit AND "
                        "on a serving-loop fault; SIGUSR1 dumps one from a "
                        "live process (enables serving telemetry)")
    g.add_argument("--speculation-length", type=int, default=0)
    g.add_argument("--speculation-type", default="fused",
                   choices=["fused", "eagle", "eagle3", "medusa"],
                   help="speculative engine: fused draft/target, EAGLE chain, "
                        "EAGLE3 dynamic tree, or Medusa heads")
    g.add_argument("--eagle-depth", type=int, default=3)
    g.add_argument("--eagle-beam", type=int, default=2)
    g.add_argument("--eagle-branch", type=int, default=2)
    g.add_argument("--medusa-heads", type=int, default=4)
    g.add_argument("--token-tree-json", default=None, metavar="JSON",
                   help="static speculation tree as a JSON list of root-to-node "
                        "token paths (modules/token_tree); medusa only — eagle "
                        "builds its tree dynamically (--eagle-beam/branch)")
    g.add_argument("--draft-model-tp-degree", type=int, default=None,
                   help="tp degree for the draft model (default: target's)")
    g.add_argument("--draft-model-path", default=None,
                   help="draft checkpoint for speculative decoding")

    g = p.add_argument_group("sampling")
    g.add_argument("--pad-token-id", type=int, default=0)
    g.add_argument("--do-sample", action="store_true")
    g.add_argument("--top-k", type=int, default=1)
    g.add_argument("--top-p", type=float, default=1.0)
    g.add_argument("--temperature", type=float, default=1.0)
    g.add_argument("--global-topk", type=int, default=256)
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--deterministic", action="store_true",
                   help="fixed PRNG seed stream for reproducible sampling")

    g = p.add_argument_group("run modes")
    g.add_argument("--prompt", action="append", default=None,
                   help="repeatable; prompts to generate from")
    g.add_argument("--check-accuracy-mode",
                   choices=["skip", "token-matching", "logit-matching",
                            "chunked-prefill-logit-matching"],
                   default="skip",
                   help="chunked-prefill-logit-matching drives the paged "
                        "chunked-prefill loop (utils/accuracy."
                        "generate_with_chunked_prefill, ≈ reference "
                        "accuracy.py:940) and logit-matches it vs HF CPU")
    g.add_argument("--draft-golden-path", default=None, metavar="DIR",
                   help="draft-logit goldens dir for fused speculation "
                        "(≈ reference run_accuracy_draft_logit_test_flow, "
                        "accuracy.py:1214); with --save-draft-goldens the dir "
                        "is written instead of checked")
    g.add_argument("--save-draft-goldens", action="store_true",
                   help="write draft logits to --draft-golden-path instead of "
                        "checking against it")
    g.add_argument("--num-draft-loops-to-check", type=int, default=6)
    g.add_argument("--divergence-difference-tol", type=float, default=0.001)
    g.add_argument("--tol-map", default=None, metavar="JSON",
                   help='''per-position tolerance map for logit matching, e.g.
                        {"64": [1e-3, 1e-4]} — the entry with the largest key
                        <= position applies (utils/accuracy.py)''')
    g.add_argument("--num-tokens-to-check", type=int, default=None,
                   help="limit token/logit matching to the first N generated "
                        "tokens")
    g.add_argument("--expected-outputs-path", default=None, metavar="NPY",
                   help="golden token matrix (.npy) for token matching instead "
                        "of running the HF CPU model")
    g.add_argument("--output-logits", action="store_true",
                   help="also print per-step last-token logits stats")
    g.add_argument("--allow-input-truncation", action="store_true",
                   help="truncate prompts longer than max_context_length "
                        "instead of raising")
    g.add_argument("--input-capture-save-dir", default=None, metavar="DIR",
                   help="snapshot every request's inputs (and weights once) to "
                        "DIR (utils/snapshot; sets TPUINF_CAPTURE_*)")
    g.add_argument("--max-num-seqs", type=int, default=None,
                   help="continuous-batching slot count (default: batch size)")
    g.add_argument("--capture-on-divergence-dir", default=None, metavar="DIR",
                   help="on a failed logit match, re-run the failing request "
                        "with input+weight snapshots written to DIR "
                        "(≈ reference auto-capture, inference_demo.py:635-649)")
    g.add_argument("--benchmark", action="store_true")
    g.add_argument("--benchmark-runs", type=int, default=5)
    g.add_argument("--verbose", action="store_true")
    return p


def create_tpu_config(args: argparse.Namespace) -> TpuConfig:
    """≈ reference `create_neuron_config` (`inference_demo.py:436-490`)."""
    sampling = OnDeviceSamplingConfig(
        do_sample=args.do_sample, top_k=args.top_k, top_p=args.top_p,
        temperature=args.temperature, global_topk=args.global_topk,
        deterministic=args.deterministic)
    from .config import (LoraServingConfig, QuantizationConfig, SpeculationConfig)

    quant = None
    if args.quantize_weights or args.kv_cache_dtype:
        kw = dict(quantize_weights=bool(args.quantize_weights),
                  weight_dtype=args.quantize_weights or "int8")
        if args.kv_cache_scale_mode is None and args.kv_cache_dtype:
            # the dtype -> scale-mode pairing lives in ONE place
            quant = QuantizationConfig.for_kv_dtype(args.kv_cache_dtype, **kw)
        else:
            quant = QuantizationConfig(
                kv_cache_dtype=args.kv_cache_dtype,
                kv_cache_scale_mode=args.kv_cache_scale_mode or "direct", **kw)
    lora = None
    if args.lora_ckpt:
        for spec in args.lora_ckpt:
            if "=" not in spec:
                raise SystemExit(f"--lora-ckpt expects NAME=DIR, got {spec!r}")
        paths = dict(spec.split("=", 1) for spec in args.lora_ckpt)
        lora = LoraServingConfig(max_loras=max(args.max_loras, len(paths)),
                                 max_lora_rank=args.max_lora_rank,
                                 lora_ckpt_paths=paths)
    if args.max_num_seqs:
        # serving slot count IS the compiled batch (the runner packs requests
        # into cfg.batch_size rows)
        args.batch_size = max(args.batch_size, args.max_num_seqs)
    spec_cfg = None
    if args.speculation_length:
        spec_cfg = SpeculationConfig(speculation_length=args.speculation_length,
                                     draft_model_path=args.draft_model_path)
    if args.pp_degree != 1:
        raise SystemExit("--pp-degree must be 1 (pipeline parallelism is a "
                         "config no-op, matching the reference)")
    if args.mlp_cp_degree not in (None, args.cp_degree):
        raise SystemExit(f"--mlp-cp-degree must equal --cp-degree "
                         f"({args.cp_degree}): the mlp logical axis shards "
                         f"over (cp, tp) structurally")
    moe_hybrid = None
    if args.moe_tkg_tp is not None or args.moe_tkg_ep is not None:
        from .config import MoEHybridShardingConfig

        def axis(v, name):
            if v is None:
                return name          # keep the default layout on that axis
            return None if v == 0 else name

        moe_hybrid = MoEHybridShardingConfig(
            decode_experts=axis(args.moe_tkg_ep, "ep"),
            decode_expert_mlp=axis(args.moe_tkg_tp, "tp"))
    return TpuConfig(
        batch_size=args.batch_size,
        seq_len=args.seq_len,
        max_context_length=args.max_context_length,
        max_new_tokens=args.max_new_tokens,
        tp_degree=args.tp_degree,
        dp_degree=args.dp_degree,
        cp_degree=args.cp_degree,
        ep_degree=args.ep_degree,
        sequence_parallel_enabled=args.sequence_parallel,
        attention_dp_enabled=args.attention_dp,
        flash_decoding_enabled=args.flash_decoding,
        vocab_parallel=args.vocab_parallel,
        dtype=args.dtype,
        enable_bucketing=args.enable_bucketing,
        context_encoding_buckets=args.context_encoding_buckets,
        token_generation_buckets=args.token_generation_buckets,
        decode_chunk_size=args.decode_chunk_size,
        async_mode=args.async_mode,
        transpose_attention_stacks=args.transpose_attention_stacks,
        attention_kernel_enabled=args.attention_kernel,
        decode_kernel_enabled=args.decode_kernel,
        batch_buckets=args.batch_buckets,
        is_continuous_batching=args.continuous_batching,
        moe_hybrid_sharding=moe_hybrid,
        paged_attention_enabled=args.paged_attention,
        pa_num_blocks=args.pa_num_blocks,
        pa_block_size=args.pa_block_size,
        quantization_config=quant,
        lora_serving_config=lora,
        speculation_config=spec_cfg,
        on_device_sampling_config=sampling,
    )


def run_inference(args: argparse.Namespace) -> int:
    if not args.model_path and not args.artifacts_path:
        raise SystemExit("one of --model-path or --artifacts-path is required")
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.compilation_cache_dir:
        from .utils.runtime_env import set_runtime_env

        set_runtime_env(args.seq_len,
                        compilation_cache_dir=args.compilation_cache_dir)
    if args.save_artifacts and not args.compilation_cache_dir:
        # register the artifact compile cache BEFORE the cold run's jits so the
        # first warm start already skips compilation (the --skip-compile analog)
        import os

        from .utils.runtime_env import set_runtime_env

        set_runtime_env(args.seq_len,
                        compilation_cache_dir=os.path.join(args.save_artifacts,
                                                           "compile_cache"))
    if args.input_capture_save_dir:
        import os

        os.environ["TPUINF_CAPTURE_DIR"] = args.input_capture_save_dir
        os.environ["TPUINF_CAPTURE_WEIGHTS"] = "1"

    if args.artifacts_path:
        # warm start from a saved serving-artifact dir: no HF ingest, no
        # re-quantize, compile cache reused (≈ reference --skip-compile,
        # `inference_demo.py:367-372`)
        if args.check_accuracy_mode != "skip" and not args.model_path:
            raise SystemExit("--check-accuracy-mode needs the HF golden model: "
                             "pass --model-path alongside --artifacts-path")
        logger.warning("--artifacts-path: serving config comes from the saved "
                       "tpu_config.json; serving flags (batch-size, seq-len, "
                       "buckets, quantization, parallelism, ...) on this "
                       "command line are ignored")
        with open(f"{args.artifacts_path}/tpu_config.json") as f:
            model_type = args.model_type or json.load(f).get("model_type",
                                                             "llama")
        model_cls = get_model_cls(model_type)
        logger.info("warm start: %s from artifacts %s", model_cls.__name__,
                    args.artifacts_path)
        app = model_cls.from_artifacts(args.artifacts_path)
    else:
        model_type = args.model_type
        if model_type is None:
            with open(f"{args.model_path}/config.json") as f:
                model_type = json.load(f).get("model_type", "llama")
        model_cls = get_model_cls(model_type)

        tpu_config = create_tpu_config(args)
        logger.info("building %s (%s) tp=%d", model_cls.__name__, model_type,
                    tpu_config.tp_degree)
        app = model_cls.from_pretrained(args.model_path, tpu_config)
    if args.save_artifacts:
        app.save_artifacts(args.save_artifacts)
    if args.compiled_path:
        app.save_config(args.compiled_path)

    tokenizer = _try_load_tokenizer(args.model_path)
    _maybe_calibrate_kv(app, args, tokenizer)

    if args.dynamic_lora:
        if not args.lora_ckpt:
            raise SystemExit("--dynamic-lora requires --lora-ckpt NAME=DIR entries")
        from .modules.lora import DynamicLoraManager

        mgr = DynamicLoraManager(app)
        for spec in args.lora_ckpt:
            name, adir = spec.split("=", 1)
            mgr.register_path(name, adir)
        app._dynamic_lora = mgr
        logger.info("dynamic LoRA: %d adapters registered, %d device slots",
                    len(mgr.host), mgr.spec.max_loras)

    if args.check_accuracy_mode != "skip":
        rc = _run_accuracy_check(args, app, tokenizer)
        if rc != 0:
            return rc

    if args.draft_golden_path and not (args.speculation_length
                                       or args.speculation_type != "fused"):
        raise SystemExit("--draft-golden-path requires a speculative run "
                         "(--speculation-length with --draft-model-path)")
    if args.speculation_length or args.speculation_type != "fused":
        spec_model = _build_spec_engine(args, app, tokenizer)
        input_ids, attention_mask = _encode_prompts(args, tokenizer,
                                                    app.arch_args.vocab_size)
        kwargs = {}
        if args.speculation_type == "fused":
            kwargs = dict(attention_mask=attention_mask, seed=args.seed)
            if args.draft_golden_path:
                kwargs["capture_draft_logits"] = True
        out = spec_model.generate(input_ids, max_new_tokens=args.max_new_tokens,
                                  **kwargs)
        if args.draft_golden_path and args.speculation_type == "fused":
            # draft/target divergence reported separately (≈ reference
            # `run_accuracy_draft_logit_test_flow`, accuracy.py:1214)
            from .utils import accuracy as accuracy_lib

            if args.save_draft_goldens:
                accuracy_lib.save_draft_goldens(args.draft_golden_path,
                                                out.draft_logits)
                print(f"draft goldens: saved {len(out.draft_logits)} loops "
                      f"to {args.draft_golden_path}")
            else:
                drep = accuracy_lib.check_accuracy_draft_logits(
                    out.draft_logits,
                    accuracy_lib.load_draft_goldens(args.draft_golden_path),
                    num_loops_to_check=args.num_draft_loops_to_check)
                print(f"draft logit matching: passed={drep.passed} "
                      f"loops_checked={drep.checked_loops} "
                      f"max_topk_err={drep.max_topk_err:.5f} "
                      f"first_failure={drep.first_failure}")
                if not drep.passed:
                    return 1
        if tokenizer is not None:
            for row in out.tokens:
                print(tokenizer.decode([t for t in row if t >= 0]))
        else:
            print("speculative tokens:")
            print(out.tokens)
    elif args.serve:
        _run_serving(args, app, tokenizer)
    elif args.prompt:
        _run_generation(args, app, tokenizer)

    if args.benchmark:
        report = benchmark_sampling(app, max_new_tokens=args.max_new_tokens,
                                    n_runs=args.benchmark_runs,
                                    report_dir=args.compiled_path)
        print(json.dumps(report.to_dict(), indent=2))
    return 0


def _build_spec_engine(args, app, tokenizer=None):
    """Construct the requested speculative engine (≈ reference draft-model setup,
    `inference_demo.py`: fused/standard/Medusa/EAGLE routing)."""
    if args.speculation_type == "fused":
        if not args.draft_model_path:
            raise SystemExit("--speculation-length requires --draft-model-path")
        from .runtime.speculation import FusedSpeculativeModel

        logger.info("loading draft model from %s", args.draft_model_path)
        with open(f"{args.draft_model_path}/config.json") as f:
            draft_type = json.load(f).get("model_type", "llama")
        draft_cls = get_model_cls(draft_type)
        draft_cfg = create_tpu_config(args)
        draft_cfg.speculation_config = None
        if args.draft_model_tp_degree:
            import dataclasses

            target_world = (draft_cfg.tp_degree * draft_cfg.dp_degree
                            * draft_cfg.cp_degree * draft_cfg.ep_degree)
            # re-runs __post_init__ so degree validation applies to the override
            draft_cfg = dataclasses.replace(
                draft_cfg, tp_degree=args.draft_model_tp_degree)
            draft_world = (draft_cfg.tp_degree * draft_cfg.dp_degree
                           * draft_cfg.cp_degree * draft_cfg.ep_degree)
            if draft_world != target_world:
                raise SystemExit(
                    f"--draft-model-tp-degree {args.draft_model_tp_degree}: "
                    f"draft world size {draft_world} must equal the target's "
                    f"{target_world} (both run inside one jitted step)")
        draft = draft_cls.from_pretrained(args.draft_model_path, draft_cfg)
        _maybe_calibrate_kv(draft, args,
                            tokenizer or _try_load_tokenizer(args.draft_model_path))
        return FusedSpeculativeModel(app, draft, args.speculation_length,
                                     greedy=not args.do_sample)
    if args.speculation_type == "medusa":
        from .runtime.medusa import MedusaModel

        tree = None
        if args.token_tree_json:
            from .modules.token_tree import TokenTree

            tree = TokenTree.from_paths(json.loads(args.token_tree_json))
        engine = MedusaModel(app, num_medusa_heads=args.medusa_heads, tree=tree)
        if args.draft_model_path:
            from .utils import checkpoint as ckpt_lib

            engine.load_heads(ckpt_lib.load_state_dict(args.draft_model_path))
        else:
            logger.warning("no --draft-model-path: random Medusa heads "
                           "(exactness holds; acceptance will be ~1)")
            engine.load_random_heads()
        return engine
    # EAGLE / EAGLE3 chain or dynamic-tree drafts
    if args.token_tree_json:
        raise SystemExit("--token-tree-json is medusa-only; eagle drafts build "
                         "their tree dynamically (--eagle-beam/--eagle-branch)")
    from .runtime.eagle import EagleSpeculativeModel, draft_args_from_target

    d_args = draft_args_from_target(app.arch_args, num_layers=1)
    if args.speculation_type == "eagle":
        engine = EagleSpeculativeModel(app, d_args,
                                       args.speculation_length or 5)
    else:
        from .runtime.eagle3 import Eagle3SpeculativeModel

        engine = Eagle3SpeculativeModel(app, d_args, depth=args.eagle_depth,
                                        beam=args.eagle_beam,
                                        branch=args.eagle_branch)
    if args.draft_model_path:
        from .utils import checkpoint as ckpt_lib

        engine.load_draft(ckpt_lib.load_state_dict(args.draft_model_path))
    else:
        logger.warning("no --draft-model-path: random EAGLE draft "
                       "(exactness holds; acceptance will be ~1)")
        engine.load_random_draft()
    return engine


def _parse_sla_classes(spec: str):
    """--sla-classes SPEC -> SLAClassSet; \"default\" = the stock set."""
    from .serving.sla import SLAClassSet, default_class_set

    if spec.strip().lower() == "default":
        return default_class_set()
    return SLAClassSet.parse(spec)


def _merge_class_slo_targets(slo_cfg, sla_classes) -> None:
    """The class set's declared latency targets (--sla-classes
    \"interactive:ttft_target_ms=150,...\") feed the SLO monitor's
    per-class evaluation; explicit dotted --slo keys win on collision.
    A dotted --slo key naming a class OUTSIDE the set raises — a typo'd
    per-class SLO must not silently never evaluate."""
    if sla_classes is None:
        return
    unknown = [c for c in slo_cfg.class_targets
               if c not in sla_classes.names()]
    if unknown:
        raise SystemExit(
            f"--slo names unknown SLA class(es) {unknown} "
            f"(--sla-classes defines {sla_classes.names()})")
    for cls, targets in sla_classes.slo_class_targets().items():
        merged = dict(targets)
        merged.update(slo_cfg.class_targets.get(cls, {}))
        slo_cfg.class_targets[cls] = merged


def _run_serving(args, app, tokenizer) -> None:
    """Slot-based continuous-batching serving over the CLI prompts
    (≈ the reference's continuous-batching serve path). Any of
    --metrics-out / --trace-out / --events-out / --stats-interval turns the
    serving telemetry on (utils/metrics.py): per-request lifecycle events,
    the per-dispatch step timeline, and the metrics registry. With
    --replicas > 1 (or --kv-host-tier) the requests route through the
    scale-out frontend instead: N engine replicas on shared weights behind
    the prefix-affinity router, optionally with the host-RAM KV tier."""
    from .runtime.continuous_batching import ContinuousBatchingRunner

    if args.replicas > 1 or args.kv_host_tier or args.cluster_kv_blocks:
        return _run_serving_routed(args, app, tokenizer)
    if args.inject_faults:
        raise SystemExit("--inject-faults requires the routed serving path "
                         "(--replicas N and/or --kv-host-tier): faults are "
                         "injected at the replica seams the router "
                         "supervises")
    kw = {}
    if args.async_depth is not None:
        kw["async_depth"] = args.async_depth
    if args.prefill_chunk:
        kw["prefill_chunk"] = args.prefill_chunk
    if args.prefill_token_budget:
        # forwarded even without --prefill-chunk so the runner's own
        # validation raises instead of silently ignoring the flag
        kw["prefill_token_budget"] = args.prefill_token_budget
    if args.megastep:
        kw["megastep_k"] = args.megastep
    if args.megastep_ring:
        # forwarded even without --megastep so the runner's own validation
        # raises instead of silently ignoring the flag
        kw["megastep_ring"] = args.megastep_ring
    if args.sla_classes:
        # single-runner serving gets the weighted-fair mixed-step budgets;
        # the router-level machinery (priority placement, preemption,
        # brown-out) lives on the routed path
        kw["sla_classes"] = _parse_sla_classes(args.sla_classes)
    telemetry = None
    if (args.metrics_out or args.trace_out or args.events_out
            or args.stats_interval or args.slo or args.debug_bundle):
        from .utils.metrics import ServingTelemetry

        telemetry = ServingTelemetry(jsonl_path=args.events_out)
    runner = ContinuousBatchingRunner(app, telemetry=telemetry, **kw)
    slo_monitor = None
    if args.slo:
        from .utils.slo import SLOConfig, SLOMonitor

        slo_cfg = SLOConfig.parse(args.slo)
        _merge_class_slo_targets(slo_cfg, kw.get("sla_classes"))
        slo_monitor = SLOMonitor(telemetry, slo_cfg)

    def _dump_bundle(reason: str) -> str:
        from .serving import tracing

        return telemetry.flight.dump_bundle(
            args.debug_bundle, config=app.tpu_config,
            metrics=telemetry.registry.to_dict(), stats=runner.stats(),
            spans=tracing.inflight_span_trees_safe(telemetry), reason=reason)

    if args.debug_bundle:
        from .utils.flight_recorder import install_signal_dump

        install_signal_dump(_dump_bundle)
    input_ids, attention_mask = _encode_prompts(args, tokenizer,
                                                app.arch_args.vocab_size)
    rids = []
    for i in range(input_ids.shape[0]):
        row = input_ids[i]
        if attention_mask is not None:
            row = row[attention_mask[i] > 0]
        rids.append(runner.submit(row, max_new_tokens=args.max_new_tokens))
    def _log_stats(n_steps: int) -> None:
        if args.stats_interval and n_steps % args.stats_interval == 0:
            logger.info("serving stats @ step %d: %s", n_steps,
                        json.dumps(runner.stats(), default=str))
        if (slo_monitor is not None and args.slo_interval > 0
                and n_steps % args.slo_interval == 0):
            rep = slo_monitor.evaluate()
            if not rep.healthy:
                logger.warning("SLO unhealthy @ step %d: %s", n_steps,
                               "; ".join(rep.violations))

    try:
        results = runner.run_to_completion(seed=args.seed, on_step=_log_stats)
    except BaseException:
        # a faulting serving loop leaves its last N step records + drained
        # device counters in the bundle — the post-mortem artifact
        if args.debug_bundle:
            logger.warning("serving loop fault: debug bundle at %s",
                           _dump_bundle("exception"))
        raise
    if slo_monitor is not None:
        rep = slo_monitor.evaluate()
        logger.info("final SLO evaluation: healthy=%s%s", rep.healthy,
                    "" if rep.healthy else " (" + "; ".join(rep.violations)
                    + ")")
    if args.debug_bundle:
        logger.info("debug bundle written to %s", _dump_bundle("exit"))
    for rid in rids:
        toks = results[rid]
        if tokenizer is not None:
            print(tokenizer.decode(toks))
        else:
            print(f"request {rid}: {toks}")
    if telemetry is not None:
        telemetry.close()
        if args.metrics_out:
            with open(args.metrics_out, "w") as f:
                f.write(telemetry.prometheus_text())
            logger.info("wrote Prometheus metrics to %s", args.metrics_out)
        if args.trace_out:
            telemetry.write_chrome_trace(args.trace_out)
            logger.info("wrote Chrome trace to %s", args.trace_out)
        s = runner.stats()
        logger.info(
            "serving summary: %d requests, %d tokens, steps=%s, ttft_p50=%s ms",
            s["requests_finished"], s["tokens_emitted"], s["steps"],
            None if s["ttft_ms"] is None
            else round(s["ttft_ms"]["latency_ms_p50"], 1))


def _run_serving_routed(args, app, tokenizer) -> None:
    """Scale-out serving path (--replicas / --kv-host-tier): N engine
    replicas — independent continuous-batching runners sharing the loaded
    weights — behind the prefix-affinity router, with an optional host-RAM
    KV tier SHARED by the replicas (the store is content-addressed, so a
    prefix spilled by one replica re-admits on any of them). With
    --cluster-kv-blocks the tiers are instead PER replica over one shared
    content-addressed ClusterKVStore (serving/cluster_kv.py): the fleet
    rung dedups spilled prefixes by content hash and serves them
    cross-replica through the audited readmit scatter."""
    from .runtime.continuous_batching import ContinuousBatchingRunner
    from .serving import EngineReplica, HostKVTier, PrefixAffinityRouter

    if args.replicas < 1:
        raise SystemExit("--replicas must be >= 1")
    if args.kv_host_tier and not app.tpu_config.paged_attention_enabled:
        raise SystemExit("--kv-host-tier requires --paged-attention")
    kw = {}
    if args.async_depth is not None:
        kw["async_depth"] = args.async_depth
    if args.prefill_chunk:
        kw["prefill_chunk"] = args.prefill_chunk
    if args.prefill_token_budget:
        kw["prefill_token_budget"] = args.prefill_token_budget
    if args.megastep:
        kw["megastep_k"] = args.megastep
    if args.megastep_ring:
        kw["megastep_ring"] = args.megastep_ring
    sla_classes = (_parse_sla_classes(args.sla_classes)
                   if args.sla_classes else None)
    if sla_classes is not None:
        kw["sla_classes"] = sla_classes
    telemetry_on = bool(args.metrics_out or args.trace_out or args.events_out
                        or args.stats_interval or args.slo
                        or args.debug_bundle)
    cluster = None
    if args.cluster_kv_blocks:
        if not args.kv_host_tier:
            raise SystemExit("--cluster-kv-blocks requires --kv-host-tier "
                             "(the host tier is the publisher/puller)")
        from .serving import ClusterKVStore

        cluster = ClusterKVStore(capacity_blocks=args.cluster_kv_blocks)
        # per-replica tiers over the shared fleet store: ownership (and the
        # death-reconciliation path) is per replica, dedup is fleet-wide
        tiers = [HostKVTier(capacity_blocks=args.kv_tier_blocks,
                            cluster=cluster, owner=f"rep{i}")
                 for i in range(args.replicas)]
    else:
        tier = (HostKVTier(capacity_blocks=args.kv_tier_blocks)
                if args.kv_host_tier else None)
        tiers = [tier] * args.replicas
    pool_roles = None
    if args.pool_split:
        # disaggregated pools (serving/pools.py): P prefill + D decode
        try:
            n_pre, n_dec = (int(x) for x in args.pool_split.split(":"))
        except ValueError:
            raise SystemExit("--pool-split wants PREFILL:DECODE, e.g. 1:1")
        if n_pre < 1 or n_dec < 1:
            raise SystemExit("--pool-split needs >= 1 replica per pool")
        if n_pre + n_dec != args.replicas:
            raise SystemExit(f"--pool-split {args.pool_split} must sum to "
                             f"--replicas {args.replicas}")
        if not app.tpu_config.paged_attention_enabled:
            raise SystemExit("--pool-split requires --paged-attention")
        if args.handoff_channel == "tier" and not args.kv_host_tier:
            raise SystemExit("--handoff-channel tier requires --kv-host-tier")
        if args.handoff_channel == "tier" and cluster is not None:
            raise SystemExit("--handoff-channel tier moves blocks through "
                             "ONE shared host tier; with --cluster-kv-blocks "
                             "the tiers are per-replica — use the 'device' "
                             "channel")
        pool_roles = ["prefill"] * n_pre + ["decode"] * n_dec
    replicas = [
        EngineReplica(str(i),
                      lambda tel, t=tiers[i]: ContinuousBatchingRunner(
                          app, telemetry=tel, kv_tier=t, **kw),
                      telemetry_enabled=telemetry_on,
                      pool_role=(pool_roles[i] if pool_roles else "unified"),
                      # one JSONL spool per replica (events interleave
                      # meaninglessly in one file; suffix keeps them apart)
                      jsonl_path=(f"{args.events_out}.replica{i}"
                                  if args.events_out else None))
        for i in range(args.replicas)]
    injector = None
    if args.inject_faults:
        from .serving.faults import FaultInjector

        injector = FaultInjector(args.inject_faults)
    router = PrefixAffinityRouter(
        replicas,
        policy=("remote_prefill" if pool_roles else "affinity"),
        fault_injector=injector, auto_recover=True,
        sla_classes=sla_classes,
        pool_config=({"channel": args.handoff_channel}
                     if pool_roles else None),
        debug_bundle_dir=(os.path.dirname(args.debug_bundle) or "."
                          if args.debug_bundle else None))
    logger.info("routed serving: %d replicas, pools: %s, kv host tier: %s, "
                "cluster kv: %s, faults: %s, sla: %s",
                args.replicas,
                (f"{args.pool_split} via {args.handoff_channel}"
                 if pool_roles else "off"),
                (f"{args.kv_tier_blocks} blocks"
                 + ("/replica" if cluster is not None else "")
                 if args.kv_host_tier else "off"),
                (f"{args.cluster_kv_blocks} blocks"
                 if cluster is not None else "off"),
                args.inject_faults or "off",
                sla_classes if sla_classes is not None else "off")

    slo_monitors = []
    if args.slo:
        from .utils.slo import SLOConfig, SLOMonitor

        slo_cfg = SLOConfig.parse(args.slo)
        _merge_class_slo_targets(slo_cfg, sla_classes)
        slo_monitors = [(rep, SLOMonitor(rep.runner.telemetry, slo_cfg))
                        for rep in replicas]

    def _dump_bundles(reason: str):
        from .serving import tracing

        paths = []
        for rep in replicas:
            paths.append(rep.runner.telemetry.flight.dump_bundle(
                f"{args.debug_bundle}.replica{rep.replica_id}",
                config=app.tpu_config,
                metrics=rep.registry.to_dict(),
                spans=tracing.inflight_span_trees_safe(rep.runner.telemetry),
                stats=rep.stats(), reason=reason))
        return paths

    if args.debug_bundle:
        from .utils.flight_recorder import install_signal_dump

        install_signal_dump(lambda reason: ", ".join(_dump_bundles(reason)))

    input_ids, attention_mask = _encode_prompts(args, tokenizer,
                                                app.arch_args.vocab_size)
    rids = []
    for i in range(input_ids.shape[0]):
        row = input_ids[i]
        if attention_mask is not None:
            row = row[attention_mask[i] > 0]
        rids.append(router.submit(row, max_new_tokens=args.max_new_tokens))

    n_steps = 0
    try:
        while router.has_work:
            router.step()
            n_steps += 1
            if args.stats_interval and n_steps % args.stats_interval == 0:
                logger.info("router stats @ step %d: %s", n_steps,
                            json.dumps(router.stats(), default=str))
            if (slo_monitors and args.slo_interval > 0
                    and n_steps % args.slo_interval == 0):
                for rep, mon in slo_monitors:
                    rep_r = mon.evaluate()
                    if not rep_r.healthy:
                        logger.warning(
                            "SLO unhealthy @ step %d replica %s: %s",
                            n_steps, rep.replica_id,
                            "; ".join(rep_r.violations))
            if n_steps > 100000:
                raise SystemExit("routed serving did not converge")
    except BaseException:
        if args.debug_bundle:
            logger.warning("routed serving fault: debug bundles at %s",
                           ", ".join(_dump_bundles("exception")))
        raise
    for rep, mon in slo_monitors:
        rep_r = mon.evaluate()
        logger.info("final SLO evaluation replica %s: healthy=%s%s",
                    rep.replica_id, rep_r.healthy,
                    "" if rep_r.healthy
                    else " (" + "; ".join(rep_r.violations) + ")")
    if args.debug_bundle:
        logger.info("debug bundles written to %s",
                    ", ".join(_dump_bundles("exit")))
    results = {rid: req.generated for rid, req in router.requests.items()}
    for rid in rids:
        toks = results[rid]
        if tokenizer is not None:
            print(tokenizer.decode(toks))
        else:
            print(f"request {rid}: {toks}")
    s = router.stats()
    logger.info("router summary: %d requests, %d tokens, "
                "affinity_hits=%d, spills=%d, migrations=%d",
                s["finished"], s["tokens"], s["affinity_hits"],
                s["affinity_spills"], s["migrations"])
    if "pools" in s:
        ps = s["pools"]
        logger.info("pool summary: %d handoffs completed (%d deferred, "
                    "aborted=%s), %d blocks / %d bytes moved, "
                    "overlap_ratio=%.3f, latency_ms_p50=%s",
                    ps["completed"], ps["deferred"], ps["aborted"],
                    ps["blocks_total"], ps["bytes_total"],
                    ps["overlap_ratio"], ps["latency_ms_p50"])
    if injector is not None or s["failures"]:
        logger.info("fault-tolerance summary: faults_injected=%d, "
                    "failures=%d, recoveries=%d, recovered_requests=%d, "
                    "replica_state=%s",
                    s["faults_injected"], s["failures"], s["recoveries"],
                    s["recovered_requests"], s["replica_state"])
    if args.metrics_out:
        # ONE exposition: router series + every replica's replica-labelled
        # registry (utils/metrics.py default_labels merging)
        with open(args.metrics_out, "w") as f:
            f.write(router.prometheus_text())
        logger.info("wrote merged Prometheus metrics to %s", args.metrics_out)
    if args.trace_out:
        from .serving import tracing

        for rep in replicas:
            path = f"{args.trace_out}.replica{rep.replica_id}"
            rep.runner.telemetry.write_chrome_trace(path)
            logger.info("wrote replica %s Chrome trace to %s",
                        rep.replica_id, path)
        # the fleet-merged view: router + every replica on ONE shared epoch
        # clock, replica-prefixed tracks (serving/tracing.py — supersedes
        # the per-replica-only exports this path used to settle for)
        tracing.write_merged_chrome_trace(
            args.trace_out, [rep.trace_source() for rep in replicas],
            router.trace_source())
        logger.info("wrote fleet-merged Chrome trace to %s", args.trace_out)
    if args.events_out:
        # the router journal rides next to the per-replica spools so
        # scripts/explain_request.py can rebuild fleet traces offline
        path = router.write_trace_events(f"{args.events_out}.router")
        logger.info("wrote router trace journal to %s", path)
    for rep in replicas:
        rep.runner.telemetry.close()


def _try_load_tokenizer(model_path: Optional[str]):
    import os

    if model_path is None:
        return None
    if not any(os.path.exists(os.path.join(model_path, f))
               for f in ("tokenizer.json", "tokenizer_config.json",
                         "tokenizer.model")):
        logger.info("no tokenizer files at %s; using raw token ids", model_path)
        return None
    try:
        from transformers import AutoTokenizer

        tok = AutoTokenizer.from_pretrained(model_path)
        if tok.pad_token_id is None:
            tok.pad_token = tok.eos_token
        return tok
    except Exception:
        logger.info("no tokenizer found at %s; using raw token ids", model_path)
        return None


def _maybe_calibrate_kv(app, args, tokenizer) -> None:
    """Static KV scales (int8 KV's requirement) silently run at sigma=1
    without calibration — sub-unit K/V round to zero and generation degrades
    with NO error (found by review). The demo calibrates on its own prompts;
    artifact warm starts carry their saved scales and skip this."""
    if (not hasattr(app, "calibrate_kv_scales")
            or not getattr(app, "_static_kv_scales_enabled", lambda: False)()
            or getattr(app, "_kv_scales", None) is not None):
        return
    cal_ids, _ = _encode_prompts(args, tokenizer, app.arch_args.vocab_size)
    logger.info("calibrating static KV scales on the CLI prompts")
    app.calibrate_kv_scales(cal_ids)


def _encode_prompts(args, tokenizer, vocab_size: int = 1000) -> tuple:
    prompts: List[str] = args.prompt or ["I believe the meaning of life is"]
    if tokenizer is None:
        rng = np.random.default_rng(args.seed)
        ids = rng.integers(1, min(1000, vocab_size),
                           size=(args.batch_size, 16)).astype(np.int32)
        return ids, None
    if len(prompts) > args.batch_size:
        logger.warning("%d prompts exceed --batch-size %d; using the first %d",
                       len(prompts), args.batch_size, args.batch_size)
        prompts = prompts[: args.batch_size]
    if len(prompts) < args.batch_size:
        prompts = (prompts * args.batch_size)[: args.batch_size]
    if tokenizer.pad_token_id is None:
        tokenizer.pad_token_id = args.pad_token_id
    enc = tokenizer(prompts, return_tensors="np", padding=True,
                    truncation=bool(args.allow_input_truncation),
                    max_length=(args.max_context_length or args.seq_len
                                if args.allow_input_truncation else None))
    return enc["input_ids"].astype(np.int32), enc["attention_mask"].astype(np.int32)


def _run_accuracy_check(args, app, tokenizer) -> int:
    """≈ reference `run_accuracy_check` (`inference_demo.py:622`)."""
    import transformers

    from .utils.accuracy import check_accuracy_vs_hf, check_token_accuracy

    need_hf = not (args.expected_outputs_path
                   and args.check_accuracy_mode == "token-matching")
    hf_model = None
    if need_hf:
        logger.info("loading HF CPU golden model from %s", args.model_path)
        hf_model = transformers.AutoModelForCausalLM.from_pretrained(
            args.model_path, torch_dtype="float32").eval()
    input_ids, attention_mask = _encode_prompts(args, tokenizer,
                                                app.arch_args.vocab_size)

    n_check = args.num_tokens_to_check or args.max_new_tokens
    tol_map = None
    if args.tol_map:
        tol_map = {int(k): tuple(v) for k, v in json.loads(args.tol_map).items()}
    if args.check_accuracy_mode == "chunked-prefill-logit-matching":
        from .utils.accuracy import (check_logit_accuracy,
                                     generate_with_chunked_prefill,
                                     get_hf_expected_outputs)

        if attention_mask is not None and not np.asarray(attention_mask).all():
            # the chunked-prefill loop feeds the padded batch as-is (lockstep
            # chunks, the reference's [max_num_seqs, input_len] contract) while
            # HF goldens are computed per-row unpadded — unequal-length prompts
            # would spuriously fail
            raise SystemExit("chunked-prefill-logit-matching requires "
                             "equal-length prompts (lockstep chunk contract)")
        expected_tokens, expected_logits = get_hf_expected_outputs(
            hf_model, input_ids, n_check, attention_mask)
        tokens, logits = generate_with_chunked_prefill(app, input_ids, n_check)
        report = check_logit_accuracy(
            logits, expected_logits,
            divergence_difference_tol=args.divergence_difference_tol,
            tol_map=tol_map)
        tok_ok = bool((tokens == expected_tokens[:, : tokens.shape[1]]).all())
        print(f"chunked-prefill logit matching: passed={report.passed} "
              f"tokens_match={tok_ok} max_abs_err={report.max_abs_error:.5f} "
              f"divergence_index={report.divergence_index}")
        return 0 if (report.passed and tok_ok) else 1
    if args.check_accuracy_mode == "logit-matching":
        report = check_accuracy_vs_hf(
            app, hf_model, input_ids, n_check, attention_mask,
            divergence_difference_tol=args.divergence_difference_tol,
            tol_map=tol_map)
        print(f"logit matching: passed={report.passed} "
              f"max_abs_err={report.max_abs_error:.5f} "
              f"top1_match={report.top1_match_rate:.4f} "
              f"divergence_index={report.divergence_index}")
        if args.output_logits:
            for i, step in enumerate(report.per_step_max_err or []):
                print(f"  step {i}: max_abs_err={step:.5f}")
        if not report.passed and args.capture_on_divergence_dir:
            # ≈ reference auto-capture of failing inputs
            # (`inference_demo.py:635-649`): replay the failing request with
            # env-driven snapshots (utils/snapshot.py) for offline repro
            import os

            logger.warning("logit match failed; capturing repro snapshots "
                           "to %s", args.capture_on_divergence_dir)
            os.environ["TPUINF_CAPTURE_DIR"] = args.capture_on_divergence_dir
            os.environ["TPUINF_CAPTURE_WEIGHTS"] = "1"
            try:
                app.generate(input_ids, attention_mask=attention_mask,
                             max_new_tokens=args.max_new_tokens)
            finally:
                os.environ.pop("TPUINF_CAPTURE_DIR", None)
                os.environ.pop("TPUINF_CAPTURE_WEIGHTS", None)
        return 0 if report.passed else 1

    from .utils.accuracy import get_hf_expected_outputs

    if args.expected_outputs_path:
        expected_tokens = np.load(args.expected_outputs_path)
    else:
        expected_tokens, _ = get_hf_expected_outputs(hf_model, input_ids,
                                                     n_check, attention_mask)
    out = app.generate(input_ids, attention_mask=attention_mask,
                       max_new_tokens=n_check)
    ok = check_token_accuracy(out.tokens, expected_tokens)
    print(f"token matching: passed={ok}")
    return 0 if ok else 1


def _run_generation(args, app, tokenizer) -> None:
    """≈ reference `run_generation` (`inference_demo.py:652`)."""
    from .utils.hf_adapter import HuggingFaceGenerationAdapter

    adapter = HuggingFaceGenerationAdapter(app, tokenizer)
    prompts = list(args.prompt)
    if len(prompts) > args.batch_size:
        logger.warning("%d prompts exceed --batch-size %d; generating the first %d",
                       len(prompts), args.batch_size, args.batch_size)
        prompts = prompts[: args.batch_size]
    if tokenizer is not None:
        texts = adapter.generate_text(prompts, max_new_tokens=args.max_new_tokens,
                                      do_sample=args.do_sample, top_k=args.top_k,
                                      top_p=args.top_p, temperature=args.temperature,
                                      seed=args.seed)
        for prompt, text in zip(prompts, texts):
            print(f"--- prompt: {prompt!r}\n{text}\n")
    else:
        from .ops.sampling import prepare_sampling_params

        input_ids, attention_mask = _encode_prompts(args, tokenizer,
                                                    app.arch_args.vocab_size)
        if args.do_sample:
            sp = prepare_sampling_params(input_ids.shape[0], top_k=args.top_k,
                                         top_p=args.top_p,
                                         temperature=args.temperature)
        else:
            sp = None
        out = app.generate(input_ids, attention_mask=attention_mask,
                           max_new_tokens=args.max_new_tokens,
                           sampling_params=sp, seed=args.seed)
        print("generated token ids:")
        print(out.tokens)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    return run_inference(args)


if __name__ == "__main__":
    sys.exit(main())
