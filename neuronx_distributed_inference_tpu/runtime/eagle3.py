"""EAGLE3 speculative decoding with a dynamic (beam-expanded) token tree.

≈ reference EAGLE3 + dynamic token tree (`models/model_base.py:1429-1432` 3-layer
target-hidden capture, :2136-2558 tree decoding, `modules/eagle/dynamic_token_tree.py`).
TPU redesign — everything runs inside ONE jitted step with static shapes:

- The target's prefill/verify decode captures THREE layers' hidden states
  (capture_layers); the draft conditions on ``fc(concat(h_low, h_mid, h_high))``.
- The draft proposes a **dynamic tree**: ``depth`` beam-expansion rounds, each keeping
  the global top-``beam`` (node, token) continuations by cumulative log-probability —
  the tree's PARENTS and TOKENS are traced per batch row, only the depth schedule is
  static (node i of round r has depth r+1), so one compiled graph serves every tree
  the expansion discovers (the reference builds its dynamic tree on CPU per step).
- Verification is one wide target decode over the N = 1 + depth*beam nodes with a
  per-row traced ancestor mask; greedy acceptance walks the tree on device; accepted
  nodes' KV entries are compacted into contiguous slots in both caches
  (kvcache.compact_decode_slots), so rejected branches never need rollback.
- The draft predicts over an auxiliary vocabulary (``lm_head_d`` + d2t offsets,
  target_id = draft_id + d2t[draft_id]).

Greedy acceptance only: output equals the target's plain greedy decode exactly.
"""

from __future__ import annotations

import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.registry import audited_jit
from ..utils import profiling
from ..models import base as model_base
from ..models import eagle as eagle_lib
from ..models.base import ModelArchArgs
from ..modules import autobucketing, kvcache
from . import model_wrapper
from . import speculation as spec_lib
from .speculation import (SpecGenerateOutput, assemble_spec_output,
                          chunk_advance, quantize_chunk_iters, replay_chunk)


class Eagle3SpeculativeModel:
    """Target `TpuModelForCausalLM` + EAGLE3 draft, fused dynamic-tree speculation."""

    def __init__(self, target, draft_args: ModelArchArgs, *,
                 depth: int = 3, beam: int = 2, branch: int = 2,
                 capture_layers: Optional[tuple] = None,
                 draft_vocab: Optional[int] = None, spec_chunk: int = 8):
        if depth < 1 or beam < 1:
            raise ValueError("depth and beam must be >= 1")
        if branch < beam:
            # each round draws candidates from beam*branch continuations; fewer
            # branches than beams could not fill the next beam
            raise ValueError("branch must be >= beam")
        if draft_args.hidden_size != target.arch_args.hidden_size:
            raise ValueError("EAGLE3 draft must share the target's hidden size")
        self.target = target
        self.draft_args = draft_args
        self.depth = depth
        self.beam = beam
        self.branch = branch
        self.num_nodes = 1 + depth * beam
        L = target.arch_args.num_layers
        self.capture_layers = (capture_layers if capture_layers is not None
                               else (1, L // 2, L - 2 if L > 1 else 0))
        self.draft_vocab = draft_vocab or target.arch_args.vocab_size
        # fused tree iterations per device dispatch (positions / fused
        # conditioning hiddens / eos-stops advance in-graph; the host replays
        # the exact commit rules after the sync)
        self.spec_chunk = max(1, spec_chunk)
        self.draft_params = None
        self.draft_cache = None
        spec_lib.attach_spec_metrics(self, self.depth + 1, "eagle3 tree")
        self._build_steps()

    # ------------------------------------------------------------------ weights
    def load_random_draft(self, seed: int = 0) -> None:
        self.draft_params = eagle_lib.init_eagle3_params(
            self.draft_args, jax.random.PRNGKey(seed), self.draft_vocab,
            dtype=self.target.tpu_config.jax_dtype,
            inv_freq=self.target.inv_freq_from_config(self.target.config))

    def load_draft(self, state_dict) -> None:
        host = eagle_lib.convert_eagle3_state_dict(
            state_dict, self.draft_args,
            self.target.inv_freq_from_config(self.target.config))
        dtype = self.target.tpu_config.jax_dtype
        self.draft_params = jax.tree.map(
            lambda x: jnp.asarray(np.asarray(x)).astype(dtype)
            if np.asarray(x).dtype.kind == "f" else jnp.asarray(x), host)
        self.draft_params["rope_inv_freq"] = jnp.asarray(
            np.asarray(host["rope_inv_freq"]), jnp.float32)

    def load_host_draft(self, host_params) -> None:
        """Install an already-built draft pytree (tests / distilled drafts)."""
        self.draft_params = jax.tree.map(jnp.asarray, host_params)

    def _draft_cache_spec(self) -> kvcache.KVCacheSpec:
        a = self.draft_args
        cfg = self.target.tpu_config
        return kvcache.KVCacheSpec(
            num_layers=1, batch_size=cfg.max_batch_size,
            num_kv_heads=a.num_kv_heads, max_seq_len=cfg.seq_len,
            head_dim=a.head_dim, dtype=cfg.kv_cache_jax_dtype)

    # ------------------------------------------------------------------ device steps
    def _build_steps(self) -> None:
        t = self.target
        t_args, d_args = t.arch_args, self.draft_args
        mesh, rules = t.mesh, t.sharding_rules
        depth, beam, branch = self.depth, self.beam, self.branch
        n_nodes = self.num_nodes
        caps_idx = tuple(self.capture_layers)
        precision = "highest" if t.tpu_config.dtype == "float32" else "default"
        # static depth schedule: node 0 = root, node 1+(r-1)*beam + j has depth r
        node_depth = np.zeros((n_nodes,), np.int32)
        for r in range(1, depth + 1):
            node_depth[1 + (r - 1) * beam : 1 + r * beam] = r

        def _prefill(t_params, d_params, input_ids, position_ids, last_token_idx,
                     t_cache, d_cache):
            with jax.default_matmul_precision(precision):
                logits, t_cache, caps = model_base.prefill_forward(
                    t_params, t_args, input_ids, position_ids, last_token_idx,
                    t_cache, mesh=mesh, rules=rules, capture_layers=caps_idx)
                tok0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                g = eagle_lib.eagle3_fuse_hiddens(d_params, caps)   # (B, S, H)
                cond = jnp.concatenate(
                    [jnp.zeros_like(g[:, :1]), g[:, :-1]], axis=1)
                _, _, d_cache = eagle_lib.eagle3_forward(
                    d_params, t_params, d_args, input_ids, cond,
                    jnp.zeros_like(last_token_idx), d_cache, None,
                    mesh=mesh, rules=rules)
                g_last = jnp.take_along_axis(
                    g, last_token_idx[:, None, None], axis=1)[:, 0]   # (B, H)
            return tok0, g_last, t_cache, d_cache

        def _step(t_params, d_params, last_tok, g_cond, positions, t_cache, d_cache,
                  decode_bucket):
            """One fused dynamic-tree step: beam expansion + verify + acceptance."""
            b = last_tok.shape[0]
            d2t = d_params["d2t"]

            # --- dynamic beam expansion -------------------------------------------
            # node state (B, N): target-vocab tokens, parents, cumulative logp
            tokens = jnp.zeros((b, n_nodes), jnp.int32).at[:, 0].set(last_tok)
            parents = jnp.full((b, n_nodes), -1, jnp.int32)
            # ancestor-or-self closure (B, N, N), grown per round
            anc = jnp.broadcast_to(jnp.eye(n_nodes, dtype=bool)[None],
                                   (b, n_nodes, n_nodes))
            cum_logp = jnp.zeros((b, n_nodes), jnp.float32)
            h_all = jnp.zeros((b, n_nodes, t_args.hidden_size),
                              t.tpu_config.jax_dtype)

            frontier_tok = last_tok[:, None]                     # (B, 1) round-0 input
            frontier_cond = g_cond[:, None]                      # (B, 1, H)
            frontier_idx = jnp.zeros((b, 1), jnp.int32)          # node ids

            kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
            # depth+1 rounds: rounds 0..depth-1 expand the tree; the final round
            # only feeds the deepest-level frontier so its draft KV is written
            # (those nodes were created in round depth-1 but never forwarded —
            # without this, a fully-accepted path compacts an unwritten slot
            # into committed context and later draft steps attend to garbage).
            for r in range(depth + 1):
                width = frontier_tok.shape[1]                    # 1 or beam (static)
                slot0 = 0 if r == 0 else 1 + (r - 1) * beam
                # visibility: committed context + ancestors among written tree slots
                committed = kv_pos < positions[:, None, None, None]
                rel = kv_pos - positions[:, None, None, None]
                in_tree = (rel >= 0) & (rel < slot0 + width)
                # anc rows of the frontier nodes: (B, width, N)
                anc_f = jnp.take_along_axis(
                    anc, frontier_idx[:, :, None], axis=1)
                rel_c = jnp.clip(rel, 0, n_nodes - 1)
                vis = jnp.take_along_axis(
                    jnp.broadcast_to(anc_f[:, None], (b, 1, width, n_nodes)),
                    jnp.broadcast_to(rel_c, (b, 1, width, rel.shape[-1])), axis=3)
                mask = committed | (in_tree & vis)
                dep = tuple(int(node_depth[slot0 + j]) for j in range(width))
                with jax.default_matmul_precision(precision):
                    d_logits, h_out, d_cache = eagle_lib.eagle3_forward(
                        d_params, t_params, d_args, frontier_tok, frontier_cond,
                        positions, d_cache, decode_bucket, slot_offset=slot0,
                        depths=dep, extra_mask=mask, mesh=mesh, rules=rules)
                if r == depth:
                    break
                h_all = jax.lax.dynamic_update_slice(
                    h_all, h_out.astype(h_all.dtype), (0, slot0, 0))

                logp = jax.nn.log_softmax(d_logits, axis=-1)     # (B, width, V_d)
                top_lp, top_id = jax.lax.top_k(logp, branch)     # (B, width, branch)
                cand_scores = (jnp.take_along_axis(cum_logp, frontier_idx, axis=1)
                               [:, :, None] + top_lp).reshape(b, width * branch)
                sel_lp, sel = jax.lax.top_k(cand_scores, beam)   # (B, beam)
                parent_local = sel // branch                     # frontier-local
                parent_node = jnp.take_along_axis(frontier_idx, parent_local, axis=1)
                draft_ids = jnp.take_along_axis(
                    top_id.reshape(b, width * branch), sel, axis=1)
                new_toks = (draft_ids + jnp.take(d2t, draft_ids)).astype(jnp.int32)

                new0 = 1 + r * beam
                new_ids = new0 + jnp.arange(beam, dtype=jnp.int32)[None, :]
                tokens = jax.lax.dynamic_update_slice(tokens, new_toks, (0, new0))
                parents = jax.lax.dynamic_update_slice(parents, parent_node,
                                                       (0, new0))
                cum_logp = jax.lax.dynamic_update_slice(cum_logp, sel_lp, (0, new0))
                # anc rows for the new nodes: parent's closure + self
                anc_parent = jnp.take_along_axis(anc, parent_node[:, :, None], axis=1)
                self_hot = jax.nn.one_hot(new_ids, n_nodes, dtype=bool)
                anc = jax.lax.dynamic_update_slice(
                    anc, anc_parent | self_hot, (0, new0, 0))

                frontier_tok = new_toks
                frontier_cond = jnp.take_along_axis(
                    h_all, jnp.broadcast_to(parent_node[:, :, None],
                                            (b, beam, h_all.shape[-1])), axis=1)
                frontier_idx = jnp.broadcast_to(new_ids, (b, beam))

            # --- target verify over the N tree nodes ------------------------------
            with jax.default_matmul_precision(precision):
                t_logits, t_cache, caps = model_base.decode_forward(
                    t_params, t_args, tokens, positions, t_cache, decode_bucket,
                    mesh=mesh, rules=rules,
                    tree=(node_depth, anc), capture_layers=caps_idx)
            t_toks = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)   # (B, N)

            # --- greedy tree walk (device) ----------------------------------------
            node_depth_j = jnp.asarray(node_depth)[None, :]            # (1, N)
            node_ids = jnp.arange(n_nodes)[None, :]

            def walk(carry, r):
                cur, n_acc, path = carry
                want = jnp.take_along_axis(t_toks, cur[:, None], axis=1)[:, 0]
                ok = ((parents == cur[:, None]) & (node_depth_j == r + 1)
                      & (tokens == want[:, None]) & (n_acc == r)[:, None])
                found = ok.any(axis=1)
                child = jnp.where(found, jnp.argmax(ok, axis=1), cur)
                path = path.at[:, r].set(jnp.where(found, child, 0))
                return (child.astype(jnp.int32),
                        n_acc + found.astype(jnp.int32), path), None

            path0 = jnp.zeros((b, depth), jnp.int32)
            (last_node, n, path), _ = jax.lax.scan(
                walk, (jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.int32),
                       path0), jnp.arange(depth))

            # committed tokens: accepted path tokens + bonus (target at last node)
            path_toks = jnp.take_along_axis(tokens, path, axis=1)      # (B, depth)
            bonus = jnp.take_along_axis(t_toks, last_node[:, None], axis=1)[:, 0]
            slot_idx = jnp.arange(depth + 1)[None, :]
            out_toks = jnp.where(
                slot_idx < n[:, None],
                jnp.pad(path_toks, ((0, 0), (0, 1))), bonus[:, None])   # (B, depth+1)

            # --- KV compaction: accepted nodes -> contiguous slots ----------------
            # node i sits at cache slot positions + i; keep the accepted path at
            # [positions+1, positions+1+n) (root already at positions)
            src = positions[:, None] + path                            # (B, depth)
            t_cache = kvcache.compact_decode_slots(
                {"k": t_cache["k"], "v": t_cache["v"]}, src, positions + 1) | {
                key: val for key, val in t_cache.items()
                if key not in ("k", "v")}
            d_cache = kvcache.compact_decode_slots(
                {"k": d_cache["k"], "v": d_cache["v"]}, src, positions + 1)

            # next conditioning: fused captured hiddens at the last accepted node
            g_all = eagle_lib.eagle3_fuse_hiddens(d_params, caps)      # (B, N, H)
            g_next = jnp.take_along_axis(
                g_all, jnp.broadcast_to(last_node[:, None, None],
                                        (b, 1, g_all.shape[-1])), axis=1)[:, 0]
            return out_toks, n, g_next, t_cache, d_cache

        def _chunk(t_params, d_params, tok0, g0, positions0, alive0, t_cache,
                   d_cache, eos_ids, decode_bucket, num_iters):
            """``num_iters`` fused dynamic-tree iterations in ONE dispatch:
            per-row positions and fused conditioning hiddens advance in-graph
            by each row's accepted length; a row whose committed window
            contains its eos stops advancing (host replays the exact stop
            rules after the sync)."""
            def one_iter(carry, _):
                tok, g, pos, alive, t_cache, d_cache = carry
                out_toks, n, g_next, t_cache, d_cache = _step(
                    t_params, d_params, tok, g, pos, t_cache, d_cache,
                    decode_bucket)
                take, new_tok, alive_next = chunk_advance(alive, out_toks, n,
                                                          eos_ids)
                tok = jnp.where(take > 0, new_tok, tok)
                g = jnp.where((take > 0)[:, None], g_next, g)
                pos = pos + take
                return (tok, g, pos, alive_next, t_cache, d_cache), (out_toks, n)

            (_, g_out, _, _, t_cache, d_cache), (outs, ns) = jax.lax.scan(
                one_iter, (tok0, g0, positions0, alive0, t_cache, d_cache),
                None, length=num_iters)
            return outs, ns, g_out, t_cache, d_cache

        self._prefill_step = audited_jit(
            _prefill, kind="eagle3.prefill", cache_args=("t_cache", "d_cache"))
        self._spec_chunk = audited_jit(
            _chunk, kind="eagle3.chunk", cache_args=("t_cache", "d_cache"),
            static_argnames=("decode_bucket", "num_iters"),
            steps_arg="num_iters")

    # ------------------------------------------------------------------ generate
    def generate(
        self,
        input_ids: np.ndarray,
        attention_mask: Optional[np.ndarray] = None,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        pad_token_id: int = 0,
    ) -> SpecGenerateOutput:
        target = self.target
        cfg = target.tpu_config
        if target.params is None or self.draft_params is None:
            raise RuntimeError("load target weights and draft params before generate")
        input_ids = model_wrapper.to_int32(input_ids)
        b = input_ids.shape[0]
        compiled_b = cfg.max_batch_size

        padded = model_wrapper.pad_prefill_inputs(
            input_ids, attention_mask, target.cte_buckets, pad_token_id=pad_token_id,
            batch_size=compiled_b)
        target.reset_cache()
        from ..parallel.sharding import named_sharding

        sharding = named_sharding(target.mesh, kvcache.CACHE_LOGICAL,
                                  target.sharding_rules)
        self.draft_cache = jax.tree.map(
            lambda x: jax.device_put(x, sharding),
            kvcache.init_cache(self._draft_cache_spec()))

        t_start = time.perf_counter()
        with profiling.annotate("dispatch:eagle3.prefill"):
            tok0_dev, g_dev, target.kv_cache, self.draft_cache = \
                self._prefill_step(
                    target.params, self.draft_params, padded.input_ids,
                    padded.position_ids, padded.last_token_idx,
                    target.kv_cache, self.draft_cache)
        tok0 = np.asarray(tok0_dev)
        ttft = time.perf_counter() - t_start

        committed: List[List[int]] = [[int(tok0[i])] for i in range(b)]
        done = np.zeros((compiled_b,), dtype=bool)
        done[b:] = True
        if eos_token_id is not None:
            done[:b] |= tok0[:b] == eos_token_id
        positions = padded.true_lengths.astype(np.int32).copy()
        last_tok = tok0.astype(np.int32)
        g_cond = g_dev
        accept_hist = np.zeros((self.depth + 1,), dtype=np.int64)
        steps = 0

        eos_ids = np.full((compiled_b,),
                          -1 if eos_token_id is None else eos_token_id,
                          dtype=np.int32)
        while not all(len(c) >= max_new_tokens or done[i]
                      for i, c in enumerate(committed)):
            live_pos = [int(positions[i]) for i, c in enumerate(committed)
                        if not done[i] and len(c) < max_new_tokens]
            max_pos = max(live_pos)
            if max_pos + self.num_nodes >= cfg.seq_len:
                break
            # an iteration advances a row by at most depth+1 positions but
            # needs num_nodes cache slots of headroom for its tree
            room = ((cfg.seq_len - 1 - max_pos - (self.num_nodes - 1))
                    // (self.depth + 1) + 1)
            remaining = min(max_new_tokens - len(c)
                            for i, c in enumerate(committed)
                            if not done[i] and len(c) < max_new_tokens)
            iters = quantize_chunk_iters(self.spec_chunk, room, remaining)
            bucket = autobucketing.select_bucket(
                target.tkg_buckets,
                max_pos + (self.depth + 1) * (iters - 1) + self.num_nodes)
            alive0 = np.array([i < b and not done[i]
                               and len(committed[i]) < max_new_tokens
                               for i in range(compiled_b)])
            with profiling.annotate("dispatch:eagle3.chunk"):
                out_dev, n_dev, g_cond, target.kv_cache, self.draft_cache = \
                    self._spec_chunk(
                        target.params, self.draft_params,
                        jnp.asarray(last_tok), g_cond,
                        jnp.asarray(positions), jnp.asarray(alive0),
                        target.kv_cache, self.draft_cache,
                        jnp.asarray(eos_ids), decode_bucket=bucket,
                        num_iters=iters)
            out = np.asarray(out_dev)    # (iters, B, depth+1)
            n = np.asarray(n_dev)        # (iters, B)
            steps += replay_chunk(out, n, committed, done, positions, last_tok,
                                  accept_hist, eos_token_id, max_new_tokens)

        spec_lib.record_spec_metrics(self, accept_hist, steps)
        return assemble_spec_output(committed, padded, b, pad_token_id, accept_hist,
                                    steps, ttft)
