"""Continuous batching: slot-based serving over a shared batch, dense or paged KV.

≈ reference continuous batching (`models/model_wrapper.py:569-698` batch pad/sort by
seq_id, `modules/kvcache/data_parallel_kv_cache_manager.py`, block-KV slot mapping
`block_kv_cache_manager.py:376-431`). TPU redesign:

- The compiled batch is a fixed set of ``max_batch_size`` slots; requests are inserted
  into free slots and all slots decode together (SPMD). Inactive slots keep stepping
  with frozen positions and their KV writes dropped (paged: slot -1; dense: harmless
  rewrites at a frozen position) — shapes never change, so no recompilation.
- Insertion runs a batch-1 context encoding that writes straight into the shared cache:
  dense mode lands at the slot's batch row (`write_prefill(batch_start=slot)`); paged
  mode scatters into freshly allocated blocks.
- Prefix caching (paged only): a prompt whose leading full blocks are already resident
  (chained content hash, see modules/block_kvcache.BlockAllocator) prefills only the
  suffix with a *prefix-prefill*: a wide `decode_forward` call whose queries are the
  suffix tokens and whose KV view gathers prior blocks + fresh writes — the TPU analog
  of the reference's `prefix_caching_attention_fwd_isa_kernel` path
  (`attention_base.py:909`).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import base as model_base
from ..modules import autobucketing, block_kvcache
from ..ops import sampling as sampling_ops
from ..parallel.sharding import named_sharding
from . import model_wrapper

logger = logging.getLogger("tpu-inference")


@dataclass
class Request:
    request_id: int
    prompt: np.ndarray                   # (S,) int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    generated: List[int] = field(default_factory=list)
    slot: int = -1
    blocks: List[int] = field(default_factory=list)
    # KV write position of the *next fed token* == len(prompt) + len(generated) - 1
    # (the newest generated token is the next input; its KV is not yet written)
    position: int = 0
    done: bool = False
    truncated: bool = False              # force-finished out of cache room
    placed_seq: int = -1                 # placement order; newest = preemption victim


class ContinuousBatchingRunner:
    """Slot-based continuous batching engine over a `TpuModelForCausalLM`."""

    def __init__(self, app, decode_chunk: Optional[int] = None,
                 async_mode: Optional[bool] = None):
        cfg = app.tpu_config
        if not cfg.is_continuous_batching:
            raise ValueError("tpu_config.is_continuous_batching must be enabled")
        self.app = app
        self.cfg = cfg
        self.paged = cfg.paged_attention_enabled
        if self.paged and app.arch_args.layer_pattern is not None:
            raise ValueError("paged attention is not supported for per-layer "
                             "attention patterns (rolling sliding caches)")
        self.num_slots = cfg.max_batch_size
        self.decode_chunk = decode_chunk or min(8, max(1, cfg.decode_chunk_size))
        self.sampling_config = app.sampling_config
        # async dispatch-ahead (≈ application.generate's async_mode and the
        # reference's 2-deep async decode, `modules/async_execution.py:190-306`):
        # in steady state chunk N+1 is dispatched from chunk N's device-resident
        # last tokens BEFORE N is synced, hiding the per-chunk host round trip.
        # Only entered when provably safe (no placements pending, no row with an
        # eos stop, every row >2 chunks from its max/seq bound, block headroom);
        # anything else drains the pipeline and runs the exact sync path, so
        # emitted-token semantics only ever LAG by one chunk, never change.
        self.async_mode = (cfg.async_mode if async_mode is None else async_mode)
        self._pending = None                   # (toks_dev (slots, steps), steps)

        # host-side greedy detection (== application.generate's): every slot
        # argmax -> the decode chunk compiles without the dynamic sampling
        # window (measured 6.3 ms/step of global-topk at bs=64, 128k vocab)
        sp = sampling_ops.prepare_sampling_params(
            1, top_k=self.sampling_config.top_k,
            top_p=self.sampling_config.top_p,
            temperature=self.sampling_config.temperature)
        self._greedy = (not self.sampling_config.do_sample
                        and bool((np.asarray(sp)[:, 0] == 1).all()))

        self.queue: List[Request] = []
        self.active: List[Optional[Request]] = [None] * self.num_slots
        self.finished: Dict[int, Request] = {}
        self._next_id = 0
        self._place_counter = 0
        self._key = jax.random.PRNGKey(0)

        self.positions = np.zeros((self.num_slots,), dtype=np.int32)
        self.last_tok = np.zeros((self.num_slots,), dtype=np.int32)

        if self.paged:
            # native host engine (allocator + slot mapping) when available; the
            # non-paged path never touches either, so the build is gated here
            from .. import native as native_lib

            self._slot_mapping_fn = native_lib.get_slot_mapping_fn()
            bs = cfg.pa_block_size
            self.block_size = bs
            self.max_blocks_per_seq = -(-cfg.seq_len // bs)
            # C++ engine when the toolchain permits (native/engine.cpp); Python
            # fallback keeps identical semantics (tests/test_native_engine.py)
            self.allocator = native_lib.make_block_allocator(
                cfg.pa_num_blocks, bs, enable_prefix_caching=True)
            # family hook: custom cache layouts (e.g. DeepSeek latent) page too
            self.cache = app.make_paged_cache(cfg.pa_num_blocks, bs)
            self.block_table = np.zeros((self.num_slots, self.max_blocks_per_seq),
                                        dtype=np.int32)
        else:
            app.reset_cache()
            self.cache = app.kv_cache
            app.kv_cache = None   # the runner owns the cache now

        self._build_steps()

    # ------------------------------------------------------------------ jitted steps
    def _build_steps(self) -> None:
        app = self.app
        args, mesh, rules = app.arch_args, app.mesh, app.sharding_rules
        odsc = self.sampling_config
        precision = "highest" if self.cfg.dtype == "float32" else "default"
        # family forward cores (custom layouts — MLA, Llama4 — serve through their
        # own prefill/decode fns; the base family gets models/base.*)
        prefill_core = app.prefill_fn()
        decode_core = app.decode_fn()

        if self.paged:
            # ragged paged decode: the Pallas block-table kernels serve the chunked
            # decode body when the family/layout supports them (the serving hot
            # path — ≈ SURVEY §7 "ragged paged attention is the performance cliff");
            # inserts (wide prefix-prefill queries) keep the gather path
            paged_kernel_kw = (
                {"use_kernel": True} if app._use_paged_decode_kernel() else {})

            def _insert(params, input_ids, position_ids, last_token_idx, cache,
                        block_table_row, slot_mapping, sampling_params, key):
                """Batch-1 (prefix-)prefill into paged blocks: a wide decode call whose
                queries are the (suffix) tokens; prior blocks are visible through the
                block table."""
                with jax.default_matmul_precision(precision):
                    logits, cache = decode_core(
                        params, args, input_ids, position_ids, cache, None,
                        mesh=mesh, rules=rules, block_table=block_table_row,
                        slot_mapping=slot_mapping)
                last = jnp.take_along_axis(
                    logits, last_token_idx[:, None, None], axis=1)[:, 0]
                tok = sampling_ops.sample(last, sampling_params, key, odsc)
                return tok, cache

            def _decode(params, tok0, positions, cache, block_table, slot_chunk,
                        sampling_params, key, num_steps, greedy=False):
                keys = jax.random.split(key, num_steps)
                slots_t = slot_chunk.T[:, :, None]          # (T, B, 1)

                def body(carry, xs):
                    tok, pos, cache = carry
                    step_key, slots_j = xs
                    with jax.default_matmul_precision(precision):
                        logits, cache = decode_core(
                            params, args, tok[:, None], pos, cache, None,
                            mesh=mesh, rules=rules, block_table=block_table,
                            slot_mapping=slots_j, **paged_kernel_kw)
                        if greedy:
                            # all rows argmax: skip the global-topk sampling
                            # window (measured 6.3 ms/step at bs=64, 128k vocab)
                            nxt = sampling_ops.greedy(logits[:, -1])
                        else:
                            nxt = sampling_ops.sample(logits[:, -1],
                                                      sampling_params,
                                                      step_key, odsc)
                    return (nxt, pos + 1, cache), nxt

                (_, _, cache), toks = jax.lax.scan(
                    body, (tok0, positions, cache), (keys, slots_t))
                return toks.T, cache

            self._insert_step = jax.jit(_insert, donate_argnums=(4,))
            self._decode_step = jax.jit(_decode, donate_argnums=(3,),
                                        static_argnames=("num_steps", "greedy"))
        else:
            # thread the app's prefill strategy (ring for cp>1, Pallas flash, or
            # dense attend) into insert-time context encoding; decode chunks take
            # the Pallas stacked-cache path when the arch supports it
            use_ring = app._use_ring_attention()
            use_flash = (not use_ring) and app._use_flash_attention()
            kernel_kw = ({"use_kernel": True} if app._use_decode_kernel() else {})

            def _insert(params, input_ids, position_ids, last_token_idx, cache,
                        slot, sampling_params, key):
                with jax.default_matmul_precision(precision):
                    logits, cache = prefill_core(
                        params, args, input_ids, position_ids, last_token_idx, cache,
                        mesh=mesh, rules=rules, cache_batch_start=slot,
                        use_flash=use_flash, use_ring=use_ring)
                tok = sampling_ops.sample(logits, sampling_params, key, odsc)
                return tok, cache

            def _decode(params, tok0, positions, cache, sampling_params, key,
                        decode_bucket, num_steps, greedy=False):
                keys = jax.random.split(key, num_steps)

                def body(carry, step_key):
                    tok, pos, cache = carry
                    with jax.default_matmul_precision(precision):
                        logits, cache = decode_core(
                            params, args, tok[:, None], pos, cache, decode_bucket,
                            mesh=mesh, rules=rules, **kernel_kw)
                        if greedy:
                            nxt = sampling_ops.greedy(logits[:, -1])
                        else:
                            nxt = sampling_ops.sample(logits[:, -1],
                                                      sampling_params,
                                                      step_key, odsc)
                    return (nxt, pos + 1, cache), nxt

                (_, _, cache), toks = jax.lax.scan(body, (tok0, positions, cache), keys)
                return toks.T, cache

            def _window(params, input_ids, start, slot, cache, decode_bucket):
                """Batch-1 dense windowed-prefill step at cache row ``slot`` (dense
                analog of the paged chunked insert; ≈ windowed CTE,
                `model_base.py:918-973`)."""
                pos = jnp.full((1,), start, dtype=jnp.int32)
                with jax.default_matmul_precision(precision):
                    _, cache = model_base.decode_forward(
                        params, args, input_ids, pos, cache, decode_bucket,
                        mesh=mesh, rules=rules, window_row=slot)
                return cache

            def _seed(params, tok, pos, slot, cache, sampling_params, key,
                      decode_bucket):
                """Re-feed the prompt's last token (idempotent KV rewrite) to obtain
                seed logits after a windowed insert."""
                with jax.default_matmul_precision(precision):
                    logits, cache = model_base.decode_forward(
                        params, args, tok[:, None], pos, cache, decode_bucket,
                        mesh=mesh, rules=rules, window_row=slot)
                out = sampling_ops.sample(logits[:, -1], sampling_params, key, odsc)
                return out, cache

            self._insert_step = jax.jit(_insert, donate_argnums=(4,))
            self._decode_step = jax.jit(
                _decode, donate_argnums=(3,),
                static_argnames=("decode_bucket", "num_steps", "greedy"))
            self._window_step = jax.jit(_window, donate_argnums=(4,),
                                        static_argnames=("decode_bucket",))
            self._seed_step = jax.jit(_seed, donate_argnums=(4,),
                                      static_argnames=("decode_bucket",))

    # ------------------------------------------------------------------ API
    def submit(self, prompt: np.ndarray, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None) -> int:
        prompt = np.asarray(prompt).astype(np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if prompt.size + max_new_tokens > self.cfg.seq_len:
            raise ValueError(f"prompt ({prompt.size}) + max_new_tokens "
                             f"({max_new_tokens}) exceeds seq_len {self.cfg.seq_len}")
        if not self.paged and prompt.size > self.app.cte_buckets[-1]:
            if (self.app.decode_fn() is not model_base.decode_forward
                    or self.app.arch_args.layer_pattern is not None):
                raise ValueError(
                    f"prompt ({prompt.size}) exceeds the largest context bucket "
                    f"({self.app.cte_buckets[-1]}) and this family has no dense "
                    f"windowed prefill")
            # dense windowed prefill rounds the prompt up to full windows; those
            # cache slots must exist
            w = self.app.cte_buckets[-1]
            total = -(-prompt.size // w) * w
            if total > self.cfg.seq_len:
                raise ValueError(
                    f"windowed prefill needs {total} cache slots (prompt rounded up "
                    f"to {w}-wide windows) but seq_len is {self.cfg.seq_len}")
        req = Request(self._next_id, prompt, max_new_tokens, eos_token_id)
        self._next_id += 1
        self.queue.append(req)
        return req.request_id

    @property
    def has_work(self) -> bool:
        return bool(self.queue) or any(r is not None for r in self.active)

    def _async_ok(self, extra_steps: int) -> bool:
        """True when dispatch-ahead is provably exact for the next chunk(s):
        no queued placements, no row that could stop (eos or max/seq bound)
        within ``extra_steps``, and (paged) enough free blocks that growth
        cannot preempt while a chunk is in flight."""
        if not self.async_mode or self.queue:
            return False
        rows = [r for r in self.active if r is not None and not r.done]
        if not rows:
            return False
        # bound by ACTIVE rows only: finished slots keep their frozen position
        # (possibly seq_len-1), which must not cap live rows
        if max(r.position for r in rows) + extra_steps >= self.cfg.seq_len - 1:
            return False
        for r in rows:
            if r.eos_token_id is not None:
                return False
            if len(r.generated) + extra_steps >= r.max_new_tokens:
                return False
        if self.paged:
            worst = len(rows) * (-(-extra_steps // self.block_size) + 1)
            if self.allocator.num_free < worst:
                return False
        return True

    def _drain(self, emitted: Dict[int, List[int]]) -> None:
        """Sync + commit the in-flight chunk (no-op when nothing is pending)."""
        if self._pending is None:
            return
        toks_dev, steps = self._pending
        self._pending = None
        self._commit(np.asarray(toks_dev), steps, emitted)

    def _commit(self, toks: np.ndarray, steps: int,
                emitted: Dict[int, List[int]]) -> None:
        """Fold one synced chunk's tokens (slots, steps) into request state."""
        for slot, req in enumerate(self.active):
            if req is None or req.done:
                continue
            for j in range(steps):
                t = int(toks[slot, j])
                req.generated.append(t)
                req.position += 1
                emitted.setdefault(req.request_id, []).append(t)
                if ((req.eos_token_id is not None and t == req.eos_token_id)
                        or len(req.generated) >= req.max_new_tokens):
                    break
            self.positions[slot] = req.position
            self.last_tok[slot] = req.generated[-1]
            self._maybe_finish(req, emitted)

    def step(self, key: Optional[jax.Array] = None) -> Dict[int, List[int]]:
        """Place queued requests into free slots, then run one decode chunk.

        Returns {request_id: newly generated tokens} for this step (in
        async steady state the tokens lag one chunk behind the dispatches).
        """
        if key is None:
            self._key, key = jax.random.split(self._key)
        emitted: Dict[int, List[int]] = {}

        # leaving steady state (placements pending, a row near a stop bound, or
        # async off) drains the pipeline first so the sync path sees exact state
        if self._pending is not None and (
                self.queue or not self._async_ok(
                    self._pending[1] + 2 * self.decode_chunk)):
            self._drain(emitted)

        # --- placement (≈ CTE dispatch for new seq_ids) -------------------------
        for slot in range(self.num_slots):
            if not self.queue or self.active[slot] is not None:
                continue
            req = self.queue[0]
            fed_len = len(req.prompt) + max(0, len(req.generated) - 1)
            if self.paged:
                # require room for the prompt plus one decode chunk, else a fresh
                # insert can be preempted before generating a single token (thrash)
                need = -(-(fed_len + 1 + self.decode_chunk) // self.block_size)
                if self.allocator.num_free < need:
                    break
            self.queue.pop(0)
            key, sub = jax.random.split(key)
            resumed = bool(req.generated)   # preempted earlier; KV recomputed now
            tok0 = self._insert(req, slot, sub)
            req.slot = slot
            req.position = fed_len
            self._place_counter += 1
            req.placed_seq = self._place_counter
            if not resumed:
                req.generated = [tok0]
                emitted.setdefault(req.request_id, []).append(tok0)
            self.active[slot] = req
            self.positions[slot] = req.position
            self.last_tok[slot] = req.generated[-1]
            self._maybe_finish(req, emitted)

        active_rows = [r for r in self.active if r is not None]
        if not active_rows:
            self._drain(emitted)
            return emitted

        # --- one decode chunk for every slot ------------------------------------
        # while a chunk is in flight, the dispatch state is the committed state
        # advanced uniformly by its width (_async_ok guarantees no row stops
        # mid-pipeline, so the advance is exact); its last tokens feed the next
        # chunk as a DEVICE array — no host sync on the hot path
        chunk = self.decode_chunk
        pend_steps = self._pending[1] if self._pending is not None else 0
        positions = self.positions + pend_steps
        # room is bounded by the LIVE rows; finished slots keep a frozen
        # position (possibly seq_len-1) that must not truncate active requests
        live = [r for r in active_rows if not r.done]
        max_pos = (max(r.position for r in live) + pend_steps if live
                   else int(positions.max()))
        steps = min(chunk, self.cfg.seq_len - 1 - max_pos)
        if steps <= 0:
            # longest row is out of seq_len room; force-finish (truncate) it
            self._drain(emitted)
            victim = max(active_rows, key=lambda r: r.position)
            victim.truncated = True
            self._finish(victim)
            return emitted
        valid = np.array([r is not None and not r.done for r in self.active])
        key, sub = jax.random.split(key)
        sp = self._sampling_matrix()
        tok0 = (self._pending[0][:, -1] if self._pending is not None
                else jnp.asarray(self.last_tok))
        if self.paged:
            active_rows = self._grow_blocks(active_rows, pend_steps + steps)
            if not active_rows:
                self._drain(emitted)
                return emitted
            valid = np.array([r is not None and not r.done for r in self.active])
            slot_chunk = self._slot_mapping_fn(
                self.block_table, positions, steps, self.block_size, valid=valid)
            toks_dev, self.cache = self._decode_step(
                self.app.params, tok0,
                jnp.asarray(positions), self.cache,
                jnp.asarray(self.block_table), jnp.asarray(slot_chunk), sp, sub,
                num_steps=steps, greedy=self._greedy)
        else:
            bucket = autobucketing.select_bucket(self.app.tkg_buckets,
                                                 max_pos + steps)
            toks_dev, self.cache = self._decode_step(
                self.app.params, tok0,
                jnp.asarray(positions), self.cache, sp, sub,
                decode_bucket=bucket, num_steps=steps, greedy=self._greedy)

        if self._async_ok(pend_steps + steps + chunk):
            prior, self._pending = self._pending, (toks_dev, steps)
            if prior is not None:
                self._commit(np.asarray(prior[0]), prior[1], emitted)
        else:
            self._drain(emitted)                       # older chunk commits first
            self._commit(np.asarray(toks_dev), steps, emitted)
        return emitted

    def run_to_completion(self, seed: int = 0) -> Dict[int, List[int]]:
        """Drive step() until every submitted request finishes; returns all outputs."""
        self._key = jax.random.PRNGKey(seed)
        guard = 0
        while self.has_work:
            self.step()
            guard += 1
            if guard > 10000:
                raise RuntimeError("continuous batching did not converge")
        return {rid: req.generated for rid, req in self.finished.items()}

    # --- paged block growth with preemption (≈ vLLM-style recompute preemption) ------
    def _grow_blocks(self, active_rows: List[Request], steps: int) -> List[Request]:
        """Extend every active row's blocks to cover the chunk; on exhaustion, preempt
        the newest-placed *other* request (requeue, KV recomputed at next placement —
        prefix caching recovers most of it) and retry. A lone request that still cannot
        grow is truncated."""
        while True:
            try:
                for req in active_rows:
                    self.allocator.extend(req.blocks, req.position + steps + 1)
                    self.block_table[req.slot, : len(req.blocks)] = req.blocks
                return active_rows
            except RuntimeError:
                if len(active_rows) > 1:
                    victim = max(active_rows, key=lambda r: r.placed_seq)
                    self._preempt(victim)
                else:
                    active_rows[0].truncated = True
                    self._finish(active_rows[0])
                active_rows = [r for r in self.active if r is not None]
                if not active_rows:
                    return []

    def _preempt(self, req: Request) -> None:
        logger.info("preempting request %d (out of KV blocks)", req.request_id)
        self.active[req.slot] = None
        if self.paged:
            self.allocator.free_sequence(req.blocks)
            self.block_table[req.slot, :] = 0
            req.blocks = []
        req.slot = -1
        self.queue.insert(0, req)   # resumes first; _insert refeeds prompt + generated

    # ------------------------------------------------------------------ internals
    def _sampling_matrix(self) -> np.ndarray:
        return sampling_ops.prepare_sampling_params(
            self.num_slots,
            top_k=self.sampling_config.top_k, top_p=self.sampling_config.top_p,
            temperature=self.sampling_config.temperature)

    def _insert(self, req: Request, slot: int, key) -> int:
        # resumed (preempted) requests refeed prompt + generated[:-1]; the newest
        # generated token stays the next decode input (its KV is never written here)
        fed = req.prompt
        if req.generated:
            fed = np.concatenate(
                [req.prompt, np.asarray(req.generated[:-1], dtype=np.int32)])
        cached_len = 0
        if self.paged:
            req.blocks, cached_len = self.allocator.allocate_for_prompt(fed)
            # never skip the whole prompt: the last token's logits seed generation
            cached_len = min(cached_len, len(fed) - 1)
            self.block_table[slot, : len(req.blocks)] = req.blocks

        sp_row = self._sampling_matrix()[slot : slot + 1]

        if self.paged:
            # windowed (chunked) prefill: feed CTE-bucket-size windows sequentially;
            # each window's queries see the prior windows' KV through the block table
            # (≈ windowed context encoding, reference `model_base.py:918-973`, and the
            # chunked-prefill flow of `ChunkedPrefillConfig`).
            max_window = self.app.cte_buckets[-1]
            start = cached_len
            tok_dev = None
            while start < len(fed):
                window = fed[start : min(start + max_window, len(fed))]
                padded = model_wrapper.pad_prefill_inputs(
                    window[None, :], None, self.app.cte_buckets, batch_size=1)
                pos_row = np.array([start], dtype=np.int32)
                valid = np.ones((1, padded.bucket), dtype=bool)
                valid[0, len(window):] = False
                slot_map = self._slot_mapping_fn(
                    self.block_table[slot : slot + 1], pos_row, padded.bucket,
                    self.block_size, valid=valid)
                key, sub = jax.random.split(key)
                tok_dev, self.cache = self._insert_step(
                    self.app.params, padded.input_ids, pos_row,
                    padded.last_token_idx, self.cache,
                    jnp.asarray(self.block_table[slot : slot + 1]),
                    jnp.asarray(slot_map), sp_row, sub)
                start += len(window)
        elif len(fed) > self.app.cte_buckets[-1]:
            # dense windowed (chunked) prefill at this slot's cache row, then a
            # 1-token seed decode re-feeding the last prompt token (idempotent
            # rewrite) for the first sampled token
            w = self.app.cte_buckets[-1]
            total = -(-len(fed) // w) * w
            ids = np.zeros((1, total), dtype=np.int32)
            ids[0, : len(fed)] = fed
            for w0 in range(0, total, w):
                bkt = autobucketing.select_bucket(self.app.tkg_buckets, w0 + w)
                self.cache = self._window_step(
                    self.app.params, ids[:, w0 : w0 + w], np.int32(w0),
                    np.int32(slot), self.cache, decode_bucket=bkt)
            key, sub = jax.random.split(key)
            tok_dev, self.cache = self._seed_step(
                self.app.params, jnp.asarray(fed[-1:]),
                np.array([len(fed) - 1], dtype=np.int32), np.int32(slot),
                self.cache, sp_row, sub,
                decode_bucket=autobucketing.select_bucket(self.app.tkg_buckets,
                                                          len(fed)))
        else:
            padded = model_wrapper.pad_prefill_inputs(
                fed[None, :], None, self.app.cte_buckets, batch_size=1)
            tok_dev, self.cache = self._insert_step(
                self.app.params, padded.input_ids, padded.position_ids,
                padded.last_token_idx, self.cache, jnp.asarray(slot, dtype=jnp.int32),
                sp_row, key)
        return int(np.asarray(tok_dev)[0])

    def _maybe_finish(self, req: Request, emitted) -> None:
        if (len(req.generated) >= req.max_new_tokens
                or (req.eos_token_id is not None
                    and req.generated[-1] == req.eos_token_id)):
            self._finish(req)

    def _finish(self, req: Request) -> None:
        req.done = True
        self.finished[req.request_id] = req
        if req.slot >= 0:
            self.active[req.slot] = None
            if self.paged:
                self.allocator.free_sequence(req.blocks)
                self.block_table[req.slot, :] = 0
            req.slot = -1
