"""Continuous batching: slot-based serving over a shared batch, dense or paged KV.

≈ reference continuous batching (`models/model_wrapper.py:569-698` batch pad/sort by
seq_id, `modules/kvcache/data_parallel_kv_cache_manager.py`, block-KV slot mapping
`block_kv_cache_manager.py:376-431`). TPU redesign:

- The compiled batch is a fixed set of ``max_batch_size`` slots; requests are inserted
  into free slots and all slots decode together (SPMD). Inactive slots keep stepping
  with frozen positions and their KV writes dropped (paged: slot -1; dense: harmless
  rewrites at a frozen position) — shapes never change, so no recompilation.
- Insertion runs a batch-1 context encoding that writes straight into the shared cache:
  dense mode lands at the slot's batch row (`write_prefill(batch_start=slot)`); paged
  mode scatters into freshly allocated blocks.
- Prefix caching (paged only): a prompt whose leading full blocks are already resident
  (chained content hash, see modules/block_kvcache.BlockAllocator) prefills only the
  suffix with a *prefix-prefill*: a wide `decode_forward` call whose queries are the
  suffix tokens and whose KV view gathers prior blocks + fresh writes — the TPU analog
  of the reference's `prefix_caching_attention_fwd_isa_kernel` path
  (`attention_base.py:909`).
"""

from __future__ import annotations

import contextlib
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.registry import audited_jit, step_loop_body
from ..models import base as model_base
from ..modules import autobucketing, block_kvcache
from ..ops import sampling as sampling_ops
from ..ops import token_ring
from ..parallel.sharding import named_sharding
from ..utils import device_telemetry as dtel
from . import model_wrapper

logger = logging.getLogger("tpu-inference")

# Device-resident megastep (ISSUE-10 / ROADMAP open item 2): in-graph exit
# codes of the lax.while_loop serving loop, in evaluation priority order.
# ``iters`` = ran the full requested inner-step count; ``stopped`` = every row
# froze in-graph (eos / max-new budget); ``blocks`` = a live row reached its
# host-pre-reserved block coverage; ``arrival`` = the host's pending-arrival
# service flag cut the loop after one step; ``ring`` = the emitted-token ring
# filled before the requested count (the host drains — "services" — it and
# the next megastep continues).
MEGASTEP_EXIT_ITERS = 0
MEGASTEP_EXIT_STOPPED = 1
MEGASTEP_EXIT_BLOCKS = 2
MEGASTEP_EXIT_ARRIVAL = 3
MEGASTEP_EXIT_RING = 4
MEGASTEP_EXITS = {0: "iters", 1: "stopped", 2: "blocks", 3: "arrival",
                  4: "ring"}


def _emitted_count(emitted: Dict[int, List[int]]) -> int:
    """Total tokens in a {request_id: new tokens} step-emission dict."""
    return sum(len(v) for v in emitted.values())


@dataclass
class Request:
    request_id: int
    prompt: np.ndarray                   # (S,) int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    # per-request (3,) [top_k, top_p, temperature]; None = runner defaults
    # (≈ reference per-request sampling params, `generation/sampling.py:99-209`)
    sampling_params: Optional[np.ndarray] = None
    # multi-LoRA adapter slot (0 = base weights; ≈ reference CB forward carrying
    # adapter_ids per batch line, `models/model_wrapper.py:252-311`)
    adapter_id: int = 0
    generated: List[int] = field(default_factory=list)
    slot: int = -1
    blocks: List[int] = field(default_factory=list)
    # chunked-prefill state (paged, max_insert_tokens_per_step): the request
    # holds its slot while its prompt streams in bounded windows, excluded from
    # decode until complete (≈ reference chunked prefill, `kvcache/utils.py`)
    inserting: bool = False
    fed: Optional[np.ndarray] = None     # prompt (+ resumed generated) to write
    insert_pos: int = 0                  # fed tokens already written
    tok0_dev: object = None              # final window's sampled seed token
    # KV write position of the *next fed token* == len(prompt) + len(generated) - 1
    # (the newest generated token is the next input; its KV is not yet written)
    position: int = 0
    done: bool = False
    truncated: bool = False              # force-finished out of cache room
    placed_seq: int = -1                 # placement order; newest = preemption victim
    # SLA class (serving/sla.py): the tenant tier this request serves under.
    # None = runner has no class set (every scheduling decision is legacy
    # FIFO); with a class set, the mixed-step weighted-fair budget split and
    # the router's priority placement / preemption / brown-out read it.
    sla_class: Optional[str] = None


class ContinuousBatchingRunner:
    """Slot-based continuous batching engine over a `TpuModelForCausalLM`.

    With ``draft``/``speculation_length`` the serving loop runs FUSED SPECULATIVE
    decode chunks instead of one-token steps (≈ the reference serving fused spec
    through CB + paged KV: per-sequence multi-token slot mapping
    `block_kv_cache_manager.py:402-431` ``generate_fusedspec_slot_mapping``, CB +
    fused-spec config coupling `models/config.py:245-258`). TPU redesign: each
    dispatch scans ``spec_chunk`` fused iterations ON DEVICE — draft loop + wide
    K-token verify + acceptance — with per-row positions advancing in-graph by
    each row's accepted length and the (B, K) block slot mapping recomputed from
    the live positions inside the graph, so the host round trip amortizes over
    the whole chunk. Rejected-token KV needs no rollback: the next window's
    writes start at the committed position and cover the stale region before any
    length-aware read (same position-masked discipline as runtime/speculation.py).
    """

    def __init__(self, app, decode_chunk: Optional[int] = None,
                 async_mode: Optional[bool] = None,
                 async_depth: Optional[int] = None, draft=None,
                 speculation_length: Optional[int] = None,
                 spec_chunk: Optional[int] = None,
                 max_insert_tokens_per_step: Optional[int] = None,
                 eagle_draft=None, spec_adaptive: bool = False,
                 spec_min_accept: float = 1.25, spec_probe_every: int = 8,
                 prefill_chunk: Optional[int] = None,
                 prefill_token_budget: Optional[int] = None,
                 mixed_decode_steps: Optional[int] = None,
                 megastep_k: Optional[int] = None,
                 megastep_ring: Optional[int] = None,
                 telemetry=None, kv_tier=None, sla_classes=None,
                 memledger: Optional[bool] = None):
        cfg = app.tpu_config
        if not cfg.is_continuous_batching:
            raise ValueError("tpu_config.is_continuous_batching must be enabled")
        # --- serving telemetry (utils/metrics.py) -----------------------------
        # ``telemetry``: a ServingTelemetry, True (enable with defaults), or
        # None/False (disabled — the default). The REGISTRY stays live either
        # way: the runner's always-on counters (preemptions, spec acceptance,
        # spec iterations) migrate onto it with thin back-compat properties;
        # only per-step / per-token EVENT recording is gated on ``enabled``
        # (the near-zero-cost path pinned by tests/test_perf_regression.py).
        from ..utils import metrics as metrics_lib

        if telemetry is None or telemetry is False:
            telemetry = metrics_lib.ServingTelemetry(enabled=False)
        elif telemetry is True:
            telemetry = metrics_lib.ServingTelemetry()
        self.telemetry = telemetry
        reg = telemetry.registry
        # roofline perf model (analysis/perf_model.py): built LAZILY by the
        # first attribute_device_time() — the serving loop itself never
        # constructs it (tests/test_perf_regression.py pins that the
        # disabled-telemetry path leaves this None)
        self._perf_model = None
        self._m_preempt = reg.counter(
            "serving_preemptions_total",
            "requests preempted (KV blocks exhausted; requeued for recompute)")
        self._m_spec_iters = reg.counter(
            "serving_spec_iterations_total",
            "fused speculative iterations actually dispatched")
        self._m_round_trip = reg.gauge(
            "serving_async_round_trip_seconds",
            "measured host<->device round trip (async auto mode)")
        self._m_chunk_wall = reg.histogram(
            "serving_chunk_wall_seconds",
            help="wall time of full-size sync decode chunks (async auto mode)")
        if max_insert_tokens_per_step is not None:
            if not cfg.paged_attention_enabled:
                raise ValueError("max_insert_tokens_per_step (chunked-prefill "
                                 "scheduling) requires paged attention")
            if max_insert_tokens_per_step < 1:
                raise ValueError("max_insert_tokens_per_step must be >= 1")
        # chunked-prefill scheduling: cap prompt tokens written per step so a
        # long insert interleaves with resident decode chunks instead of
        # stalling them (bounds resident decode latency / TTFT jitter; ≈ the
        # reference's chunked prefill interleave, `modules/kvcache/utils.py`)
        self.insert_cap = max_insert_tokens_per_step
        # --- MIXED prefill+decode serving steps (token-budget scheduler) -------
        # With ``prefill_chunk`` every serving step that has an insert in flight
        # packs ALL alive decode rows (a short chained-decode scan) plus up to
        # ``prefill_token_budget`` prompt tokens — as prefill-CHUNK rows of the
        # variable-q_len ragged paged attend — into ONE jitted dispatch,
        # replacing the per-window bs=1 _insert_step loop (≈ "Ragged Paged
        # Attention", PAPERS.md: decode rows q=1 + prefill chunks in the same
        # kernel). Decode rows never stall behind inserts; inserts never wait
        # behind full decode chunks.
        if prefill_chunk is not None:
            if not cfg.paged_attention_enabled:
                raise ValueError("prefill_chunk (mixed-step scheduling) "
                                 "requires paged attention")
            if prefill_chunk < 1:
                raise ValueError("prefill_chunk must be >= 1")
            if max_insert_tokens_per_step is not None:
                raise ValueError("prefill_chunk and max_insert_tokens_per_step "
                                 "are mutually exclusive insert schedulers")
            if draft is not None or eagle_draft is not None:
                raise ValueError("mixed-step scheduling does not compose with "
                                 "speculative serving yet")
        elif prefill_token_budget is not None or mixed_decode_steps is not None:
            raise ValueError("prefill_token_budget/mixed_decode_steps require "
                             "prefill_chunk")
        # --- device-resident serving megasteps (ISSUE-10) ----------------------
        # With ``megastep_k`` every plain decode dispatch becomes ONE jitted
        # lax.while_loop of up to K inner steps with the scheduler state that
        # used to be a host replica — alive masks, positions, remaining
        # budgets, slot-mapping advance through the block table, eos stops,
        # the emitted-token ring — living AUTHORITATIVELY on device. The loop
        # early-exits in-graph (all rows stopped / host-pre-reserved block
        # coverage reached / emitted ring full / pending-arrival service
        # flag), so bs=1 decode pays the ~109 ms dispatch floor once per K
        # tokens instead of once per token while insert latency stays bounded
        # by the ring's service condition, not by K. The host syncs ONCE per
        # megastep (executed-count + ring) and replays the exact commit rules
        # over the drained prefix. Composes with async_depth (megasteps
        # pipeline like scan chunks), with spec serving (the near-boundary /
        # adaptive plain fall-through runs megasteps), and with the mixed
        # scheduler (its pure-decode fall-through runs megasteps).
        if megastep_k is not None:
            if not cfg.paged_attention_enabled:
                raise ValueError("megastep_k (device-resident serving "
                                 "megasteps) requires paged attention — the "
                                 "in-loop slot-mapping advance consumes the "
                                 "block table")
            if megastep_k < 1:
                raise ValueError("megastep_k must be >= 1")
            if megastep_ring is not None and megastep_ring < 1:
                raise ValueError("megastep_ring must be >= 1")
        elif megastep_ring is not None:
            raise ValueError("megastep_ring requires megastep_k")
        self.megastep_k = megastep_k
        self.megastep_ring = (megastep_ring if megastep_ring is not None
                              else megastep_k)
        # host mirrors of the megastep's in-graph exit/progress accounting:
        # per-reason exit counters (stats()["megastep"]["exits"] reads their
        # live values, so a telemetry.reset() between bench windows scopes
        # exits, dispatches AND inner_steps to the same window) plus the
        # committed-inner-step counter that must equal the device carry's
        # ``megastep_iters`` field at every pipeline flush
        self._megastep_exit_counters: Dict[str, object] = {}
        self._m_megastep_iters = reg.counter(
            "serving_megastep_inner_steps_total",
            "decode inner steps committed through device-resident megasteps")
        # scheduler fall-through visibility (ISSUE-10 satellite): every
        # degradation to the plain path goes through ONE guarded exit that
        # counts the reason and stamps it on the next step-timeline record
        # of ANY kind — a megastep/mixed run that quietly degrades is
        # visible in telemetry. Pending notes accumulate (a truncation
        # immediately followed by a pure-decode fall-through loses neither).
        self._pending_fall_through: List[str] = []
        self._ft_counters: Dict[tuple, object] = {}
        # --- SLA classes (serving/sla.py, overload control plane) -------------
        # ``sla_classes``: an SLAClassSet. None (the default) keeps every
        # scheduling decision bit-identical to the classless runner: requests
        # carry sla_class=None and the mixed-step budget assignment stays
        # pure FIFO. With a set, submits resolve (and validate) their class,
        # telemetry labels TTFT/TPOT/queue observations with it, and
        # _step_mixed splits the prefill token budget across the classes
        # present by weight (work-conserving — see _assign_prefill_chunks).
        if sla_classes is not None:
            from ..serving.sla import SLAClassSet

            if not isinstance(sla_classes, SLAClassSet):
                raise ValueError("sla_classes must be a serving.sla."
                                 "SLAClassSet (or None)")
        self.sla = sla_classes
        # per-class prompt-token accounting (weighted-fair visibility):
        # serving_class_prefill_tokens_total{sla_class=} counts what each
        # class actually drew from the budget
        self._class_prefill_counters: Dict[str, object] = {}
        self.mixed = prefill_chunk is not None
        self.prefill_chunk = prefill_chunk
        self.prefill_budget = (prefill_token_budget
                               if prefill_token_budget is not None
                               else (2 * prefill_chunk if self.mixed else 0))
        # chunk-row bucket count: the dispatch carries a FIXED number of chunk
        # rows (unused rows are fully padded), so the executable never varies
        # with the instantaneous insert load
        self.chunk_rows = (max(1, self.prefill_budget // prefill_chunk)
                           if self.mixed else 0)
        # decode iterations chained inside each mixed dispatch: enough to keep
        # resident decode throughput healthy while inserts stream, short enough
        # that a chunk lands (and TTFT accrues) every few iterations
        self.mixed_decode_steps = mixed_decode_steps or min(
            8, decode_chunk or max(1, cfg.decode_chunk_size))
        self.app = app
        self.cfg = cfg
        self.paged = cfg.paged_attention_enabled
        if self.paged and app.arch_args.layer_pattern is not None:
            raise ValueError("paged attention is not supported for per-layer "
                             "attention patterns (rolling sliding caches)")
        self.num_slots = cfg.max_batch_size
        # config-consistent with the dense path (decode_chunk_size default 32):
        # the serving loop pays the host round trip once per chunk
        self.decode_chunk = decode_chunk or max(1, cfg.decode_chunk_size)
        self.sampling_config = app.sampling_config
        # async dispatch-ahead (≈ application.generate's async_mode and the
        # reference's 2-deep async decode, `modules/async_execution.py:190-306`):
        # in steady state chunk N+1..N+depth are dispatched from chunk N's
        # DEVICE-RESIDENT carry state (last token / position / alive / budget
        # per row) before N is synced, so host commit work (np.asarray of the
        # oldest chunk's tokens, bookkeeping, telemetry) fully overlaps device
        # execution instead of gating the next dispatch. Stops are tracked ON
        # DEVICE: a row that emits its eos or exhausts max_new_tokens FREEZES
        # in-graph (token/position pinned, KV writes dropped — exactly the
        # host's replay rules), so rows with eos stops pipeline too. The
        # pipeline still drains to the exact sync path whenever placements are
        # pending, a row nears the seq_len bound, or block headroom runs out —
        # emitted-token semantics only ever LAG by up to ``async_depth``
        # chunks, never change.
        #
        # Modes: True = always (exactness-gated), False = never, "auto" =
        # measured self-selection — dispatch-ahead only pays when the host
        # round trip is a sizable fraction of the chunk's wall time (measured
        # r4: +32% at short chunks, a 5% REGRESSION at 0.9 s chunks where the
        # ~100 ms round trip is already amortized), so auto times the first
        # sync chunks and a blocking round trip, then decides.
        # ``async_depth`` (default 2, matching the reference's 2-deep async
        # decode) bounds the chunks in flight after a dispatch.
        self.async_mode = (cfg.async_mode if async_mode is None else async_mode)
        self._async_auto = self.async_mode == "auto"
        if self._async_auto:
            self.async_mode = False            # until measured
        self.async_depth = max(1, int(
            async_depth if async_depth is not None
            else getattr(cfg, "async_depth", None) or 2))
        # fused paged-decode DMA pipeline depth; 0 = the kernel's per-dtype
        # VMEM-budget auto policy (ops/paged_decode.py). Schedule-only: a
        # change re-jits the next traced step, never a stream.
        self.prefetch_depth = 0
        self._chunk_times: List[float] = []
        # _round_trip_s lives on the registry gauge (back-compat property below)
        # FIFO of in-flight chunks [(toks_dev (slots, steps), steps)] plus the
        # device-resident carry state of the NEWEST dispatch
        self._inflight: List[tuple] = []
        self._dev_state = None                 # (tok, pos, alive, budget) dev
        self._m_depth = reg.gauge(
            "serving_dispatch_depth",
            "configured dispatch-ahead pipeline depth")
        self._m_depth.set(self.async_depth)
        self._m_inflight = reg.gauge(
            "serving_inflight_chunks",
            "decode chunks currently in flight (dispatch-ahead pipeline)")
        # multichip visibility: the serving mesh's tp degree as a gauge, and a
        # shape-derived PER-TOKEN-ROW ICI traffic estimate (parallel/overlap)
        # attached to every step-timeline record on tp > 1 meshes — decode
        # iterations charge the compiled slot count, prefill windows/chunks
        # charge their written token widths (see _ici_bytes)
        self._m_tp = reg.gauge(
            "serving_tp_degree",
            "tensor-parallel degree of the serving mesh")
        self._m_tp.set(cfg.tp_degree)
        from ..parallel import overlap as overlap_lib

        self._ici_bytes_per_token = overlap_lib.estimated_ici_bytes_per_step(
            app.arch_args, cfg.tp_degree, batch=1, t=1,
            dtype_bytes=jnp.dtype(cfg.jax_dtype).itemsize)

        # host-side greedy detection (== application.generate's): every slot
        # argmax -> the decode chunk compiles without the dynamic sampling
        # window (measured 6.3 ms/step of global-topk at bs=64, 128k vocab).
        # With per-request params the flag is re-derived per chunk over the
        # LIVE rows (_chunk_greedy), so all-greedy traffic keeps the fast
        # executable and mixed traffic falls back to the (B, 3) sampler.
        sp = sampling_ops.prepare_sampling_params(
            1, top_k=self.sampling_config.top_k,
            top_p=self.sampling_config.top_p,
            temperature=self.sampling_config.temperature)
        self._greedy = (not self.sampling_config.do_sample
                        and bool((np.asarray(sp)[:, 0] == 1).all()))
        # per-slot (slots, 3) sampling matrix; rows overwritten at placement
        self._default_sp_row = np.asarray(sp)[0]
        self._slot_sp = np.tile(self._default_sp_row, (self.num_slots, 1))
        # per-slot LoRA adapter slots (0 = base), threaded into every chunk
        self.adapter_ids = np.zeros((self.num_slots,), dtype=np.int32)
        self._lora_on = app.arch_args.lora is not None

        # --- speculation through the serving loop ------------------------------
        # two draft kinds: ``draft`` (a full TpuModelForCausalLM — fused spec)
        # or ``eagle_draft`` ((draft_args, draft_params) — EAGLE-style hidden-
        # state-conditioned 1-layer draft, greedy, paged serving only)
        self.draft = draft
        self.eagle = eagle_draft
        self.k = 0
        if draft is not None and eagle_draft is not None:
            raise ValueError("draft and eagle_draft are mutually exclusive")
        if (draft is None and eagle_draft is None
                and (speculation_length is not None or spec_chunk is not None)):
            raise ValueError("speculation_length/spec_chunk require a draft "
                             "model (pass draft= or eagle_draft=)")
        if eagle_draft is not None:
            if speculation_length is None or speculation_length < 2:
                raise ValueError(
                    "speculation_length must be >= 2 (1 draft + 1 verify)")
            if not self.paged:
                raise ValueError("eagle_draft serving requires paged attention")
            if not self._greedy:
                raise ValueError("EAGLE serving is greedy-only (matches "
                                 "runtime/eagle.py)")
            if max_insert_tokens_per_step is not None:
                raise ValueError("eagle_draft does not compose with "
                                 "max_insert_tokens_per_step (the draft "
                                 "conditioning hidden must be continuous "
                                 "across insert windows)")
            self.k = speculation_length
            self.spec_chunk = spec_chunk or max(1, self.decode_chunk)
            self.async_mode = False
            self._async_auto = False
        if draft is not None:
            if speculation_length is None or speculation_length < 2:
                raise ValueError(
                    "speculation_length must be >= 2 (1 draft + 1 verify)")
            if app.arch_args.vocab_size != draft.arch_args.vocab_size:
                raise ValueError("target and draft must share a vocabulary")
            for attr in ("seq_len", "max_batch_size", "max_context_length"):
                if getattr(cfg, attr) != getattr(draft.tpu_config, attr):
                    raise ValueError(
                        f"target/draft tpu_config.{attr} mismatch: "
                        f"{getattr(cfg, attr)} vs "
                        f"{getattr(draft.tpu_config, attr)}")
            if (app.arch_args.layer_pattern is not None
                    or draft.arch_args.layer_pattern is not None):
                raise ValueError(
                    "speculative continuous batching does not support per-layer "
                    "attention patterns (the wide verify would alias rolling "
                    "sliding-cache slots)")
            if not self._greedy:
                odsc = self.sampling_config
                if not (odsc.do_sample or odsc.dynamic):
                    raise ValueError(
                        "multinomial speculation requires a sampling config with "
                        "do_sample or dynamic params (see FusedSpeculativeModel)")
            self.k = speculation_length
            # per-dispatch fused iterations; each commits 1..K tokens per row.
            # Default: the PLAIN chunk's iteration count (not its token
            # count): a spec chunk of N iterations commits N..N*K tokens, and
            # what the chunk amortizes is the fixed host-dispatch cost PER
            # ITERATION — at decode_chunk//K (the old default, 8 iters) a
            # ~109 ms dispatch floor added ~13.6 ms to every measured
            # iteration; at decode_chunk (32) it adds the same ~3.4 ms a
            # plain decode step pays
            self.spec_chunk = spec_chunk or max(1, self.decode_chunk)
            # dispatch-ahead needs a host-predictable uniform advance; spec
            # advance is data-dependent (accepted length), so the pipeline
            # cannot be proven exact — the on-device chunk amortizes instead
            self.async_mode = False
            self._async_auto = False
        if self.k:
            # histogram over tokens-committed-per-(row, iteration), length K
            # (registry-backed; ``acceptance_counts`` is the back-compat
            # view) — ONE registration for both draft kinds
            self._m_accept = reg.histogram(
                "serving_spec_acceptance_tokens",
                buckets=list(range(1, self.k + 1)),
                help="tokens committed per (row, fused iteration)")

        # adaptive speculation (the serving FLOOR guard): when the measured
        # per-iteration acceptance of a spec chunk falls below
        # ``spec_min_accept`` committed tokens/row/iteration, subsequent
        # chunks run the PLAIN decode path (a spec iteration costs more than
        # a decode step, so at chance-level acceptance speculation is a pure
        # loss — this bounds the worst case at ~plain-paged throughput
        # instead of ~plain/2). Every ``spec_probe_every`` plain chunks one
        # spec chunk re-probes acceptance. Exactness is unaffected (both
        # chunk kinds are exact); the draft cache develops KV gaps over the
        # plain stretches, which only depresses probe acceptance — the
        # re-enable path is intentionally pessimistic.
        self.spec_adaptive = spec_adaptive
        self.spec_min_accept = spec_min_accept
        self.spec_probe_every = spec_probe_every
        self._spec_off = False
        self._spec_plain_chunks = 0
        # guard-state gauge: 1 while the floor guard is serving plain chunks
        # (scrapes + runner.stats() surface WHY spec throughput reads like
        # plain-paged throughput at chance acceptance)
        self._m_spec_guard = reg.gauge(
            "serving_spec_adaptive_fallback",
            "1 while the adaptive spec floor guard is serving plain chunks")
        # total fused iterations actually DISPATCHED (clamps can shrink a
        # chunk below spec_chunk near request tails) — the honest denominator
        # for measured iteration time; registry-backed (``spec_iters_run`` is
        # the back-compat property)

        self.queue: List[Request] = []
        self.active: List[Optional[Request]] = [None] * self.num_slots
        self.finished: Dict[int, Request] = {}
        self._next_id = 0
        self._place_counter = 0
        self._key = jax.random.PRNGKey(0)

        # device-resident telemetry carry (utils/device_telemetry.py): a
        # (CARRY_LEN,) int32 counter block threaded DONATED+ALIASED through
        # every jitted step below and accumulated with in-graph adds (the
        # analysis/ auditor proves the aliasing and host-sync freedom).
        # Threaded regardless of telemetry.enabled — the counter adds are
        # noise next to a decode iteration's weight stream and one executable
        # per step kind keeps the telemetry=False token stream bit-identical
        # — but only ever FETCHED (np.asarray) when telemetry is enabled AND
        # the dispatch pipeline is empty, i.e. at a sync the runner already
        # pays. Zero new host syncs.
        self._telem_dev = dtel.init_carry()
        self._telem_drained = None      # last-drained carry object (identity)

        self.positions = np.zeros((self.num_slots,), dtype=np.int32)
        self.last_tok = np.zeros((self.num_slots,), dtype=np.int32)

        # --- host-RAM KV tier (serving/kv_tiering.py) -------------------------
        # ``kv_tier``: a HostKVTier. Swaps the block allocator for the tiered
        # variant (idle pool + host store behind the free list) and installs
        # the cb.paged.tier_readmit dispatch that restores spilled blocks
        # before a prefix-hit request's first insert window.
        self.kv_tier = kv_tier
        if kv_tier is not None:
            if not cfg.paged_attention_enabled:
                raise ValueError("kv_tier (host-RAM KV tiering) requires "
                                 "paged attention")
            if draft is not None or eagle_draft is not None:
                raise ValueError("kv_tier does not compose with speculative "
                                 "serving yet (the draft pool's blocks are "
                                 "not captured by the spill path)")
        # --- pool-to-pool KV handoff sessions (serving/pools.py) --------------
        # destination-side state: open transfer sessions keyed by session id.
        # The cb.paged.kv_handoff scatter is built lazily on first receive so
        # runners that never join a disaggregated pool register no dispatch.
        self._handoff_sessions: Dict[int, dict] = {}
        self._handoff_seq = 0
        self._kv_handoff_step = None
        # --- KV block ledger (serving/memledger.py) ---------------------------
        # ``memledger``: None = auto (attach whenever the allocator exposes
        # the Python seams — the tiered allocator always does; the native C++
        # allocator is opaque), True = require a ledger (selects the Python
        # allocator over the native one), False = off. All host-side — zero
        # new dispatches or syncs.
        if memledger is True and not cfg.paged_attention_enabled:
            raise ValueError("memledger (the KV block ledger) requires paged "
                             "attention — there are no blocks to account "
                             "for on the dense path")
        if self.paged:
            # native host engine (allocator + slot mapping) when available; the
            # non-paged path never touches either, so the build is gated here
            from .. import native as native_lib

            self._slot_mapping_fn = native_lib.get_slot_mapping_fn()
            bs = cfg.pa_block_size
            self.block_size = bs
            self.max_blocks_per_seq = -(-cfg.seq_len // bs)
            if kv_tier is not None:
                from ..serving.kv_tiering import (TieredBlockAllocator,
                                                  build_readmit_step)

                self.allocator = TieredBlockAllocator(cfg.pa_num_blocks, bs,
                                                      kv_tier)
                self._tier_readmit_step = build_readmit_step()
            elif memledger is True:
                # a required ledger needs the Python seams the native C++
                # engine cannot expose — same semantics, auditable
                from ..modules.block_kvcache import (
                    BlockAllocator as _PyBlockAllocator)

                self.allocator = _PyBlockAllocator(
                    cfg.pa_num_blocks, bs, enable_prefix_caching=True)
            else:
                # C++ engine when the toolchain permits (native/engine.cpp);
                # Python fallback keeps identical semantics
                # (tests/test_native_engine.py)
                self.allocator = native_lib.make_block_allocator(
                    cfg.pa_num_blocks, bs, enable_prefix_caching=True)
            # family hook: custom cache layouts (e.g. DeepSeek latent) page too
            self.cache = app.make_paged_cache(cfg.pa_num_blocks, bs)
            if kv_tier is not None:
                # base layout: block-indexed k/v pools plus (quantized KV)
                # global per-(layer, head) scale tensors, which spill/readmit
                # pass through untouched — custom family layouts (e.g.
                # DeepSeek latent) have no generic spill/readmit shape
                extra = set(self.cache.keys()) - {"k", "v", "k_scale",
                                                  "v_scale"}
                if "k" not in self.cache or extra:
                    raise ValueError("kv_tier supports the base {k, v} paged "
                                     "layout only (custom family cache "
                                     f"layouts — extra keys {sorted(extra)} "
                                     "— have no spill/readmit shape)")
                self.allocator.read_blocks = self._read_tier_blocks
            self.block_table = np.zeros((self.num_slots, self.max_blocks_per_seq),
                                        dtype=np.int32)
            # KV block ledger: attach when the allocator has Python seams
            # (tiered always; plain paged under the Python fallback or
            # memledger=True). Every allocator mutation below runs under a
            # _led() attribution context so the ledger can name holders.
            self.ledger = None
            if memledger is not False and hasattr(self.allocator,
                                                  "_alloc_one"):
                from ..serving import memledger as memledger_lib

                self.ledger = memledger_lib.BlockLedger(
                    self.allocator, tier=kv_tier, registry=reg)
                self.ledger.bytes_per_block = self._bytes_per_block()
                memledger_lib.note_runner(self)
            elif memledger is True:
                raise ValueError("memledger=True but the allocator has no "
                                 "Python seams to ledger")
        else:
            self.ledger = None
            app.reset_cache()
            self.cache = app.kv_cache
            app.kv_cache = None   # the runner owns the cache now

        if draft is not None:
            # the draft cache shares the block geometry (and block TABLE) with
            # the target: one allocator decision covers both pools, and the
            # prefix-cache hash stays valid because every insert writes both
            if self.paged:
                self.d_cache = draft.make_paged_cache(cfg.pa_num_blocks,
                                                      cfg.pa_block_size)
            else:
                draft.reset_cache()
                self.d_cache = draft.kv_cache
                draft.kv_cache = None
        elif eagle_draft is not None:
            # EAGLE draft pool: same block table, own (1-layer) pool in the
            # MODEL dtype (the quantized-KV scale folds don't apply to the
            # draft; its pool is tiny)
            from ..modules import block_kvcache
            from ..parallel.sharding import named_sharding

            d_args = eagle_draft[0]
            spec = block_kvcache.PagedKVCacheSpec(
                num_layers=d_args.num_layers, num_blocks=cfg.pa_num_blocks,
                block_size=cfg.pa_block_size,
                num_kv_heads=d_args.num_kv_heads, head_dim=d_args.head_dim,
                dtype=cfg.jax_dtype)
            sharding = named_sharding(app.mesh,
                                      block_kvcache.PAGED_CACHE_LOGICAL,
                                      app.sharding_rules)
            self.d_cache = jax.tree.map(
                lambda x: jax.device_put(x, sharding),
                block_kvcache.init_paged_cache(spec))
            # per-slot draft conditioning hidden (device-resident across steps)
            self._h_cond = jnp.zeros(
                (self.num_slots, app.arch_args.hidden_size), cfg.jax_dtype)

        # --- live knob registry (serving/knobs.py, ISSUE-18) -----------------
        # every schedule-only tunable enumerated with bounds + live gauges.
        # Sets QUEUE into _pending_knobs and apply at the next pipeline-drain
        # safe point (step() top, or immediately when nothing is in flight),
        # so a mid-flight change can re-batch work but never change a stream.
        self._pending_knobs: Dict[str, object] = {}
        self._knob_change_counters: Dict[str, object] = {}
        from ..serving.knobs import build_runner_knobs

        self.knobs = build_runner_knobs(self)

        self._build_steps()

    # ------------------------------------------------------------------ knobs
    def set_knob(self, name: str, value) -> None:
        """Queue one schedule-knob change (called through the KnobRegistry,
        which validated bounds). Applied at the next safe point: immediately
        when the dispatch pipeline is empty, else at the top of the next
        step() after a drain — the same exact-sync path every other
        steady-state exit uses."""
        if name not in self._KNOB_APPLIERS:
            raise KeyError(f"runner has no live applier for knob {name!r}")
        self._pending_knobs[name] = value
        if not self._inflight:
            self._apply_pending_knobs()

    def _apply_pending_knobs(self) -> None:
        """Apply queued knob changes. Caller guarantees the pipeline is
        empty (drained), so host state is exact and the change lands on a
        commit boundary. Each applied change is stamped onto the next
        step-timeline record (``knob:<name>=<value>``) and counted in
        ``serving_knob_changes_total{knob=}`` — the same visibility contract
        brown-out transitions have."""
        if not self._pending_knobs:
            return
        assert not self._inflight, "knob apply requires a drained pipeline"
        pending, self._pending_knobs = self._pending_knobs, {}
        for name, value in pending.items():
            self._KNOB_APPLIERS[name](self, value)
            self._note_fall_through("knob", name, detail=str(value))
            c = self._knob_change_counters.get(name)
            if c is None:
                c = self.telemetry.registry.counter(
                    "serving_knob_changes_total",
                    "live schedule-knob changes applied by the runner",
                    labels={"knob": name})
                self._knob_change_counters[name] = c
            c.inc()
        self.knobs.refresh()

    def _apply_async_depth(self, v) -> None:
        self.async_depth = int(v)
        self._m_depth.set(self.async_depth)

    def _apply_megastep_k(self, v) -> None:
        # K is a DYNAMIC operand of the one megastep executable (the ring
        # size is the static bound, enforced by the knob's hi); no retrace
        self.megastep_k = int(v)

    def _apply_decode_chunk(self, v) -> None:
        self.decode_chunk = int(v)

    def _apply_prefill_budget(self, v) -> None:
        self.prefill_budget = int(v)
        # chunk-row bucket count follows the budget; a row-count change means
        # the next mixed dispatch jits a new (fixed-row) executable — trace
        # cost only, schedule-only semantics
        self.chunk_rows = max(1, self.prefill_budget // self.prefill_chunk)

    def _apply_mixed_decode_steps(self, v) -> None:
        self.mixed_decode_steps = int(v)

    def _apply_spec_chunk(self, v) -> None:
        self.spec_chunk = int(v)

    def _apply_spec_adaptive(self, v) -> None:
        self.spec_adaptive = bool(v)
        if not self.spec_adaptive:
            # leaving adaptive mode clears the floor guard: the next chunk
            # speculates again instead of inheriting a stale fallback
            self._spec_off = False
            self._spec_plain_chunks = 0

    def _apply_prefetch_depth(self, v) -> None:
        self.prefetch_depth = int(v)
        from ..ops.paged_decode import set_prefetch_depth

        # 0 clears to the kernel's auto policy; applies to dispatches traced
        # AFTER the change (the static argname keys the jit cache)
        set_prefetch_depth(self.prefetch_depth or None)

    _KNOB_APPLIERS = {
        "async_depth": _apply_async_depth,
        "megastep_k": _apply_megastep_k,
        "decode_chunk": _apply_decode_chunk,
        "prefill_token_budget": _apply_prefill_budget,
        "mixed_decode_steps": _apply_mixed_decode_steps,
        "spec_chunk": _apply_spec_chunk,
        "spec_adaptive": _apply_spec_adaptive,
        "prefetch_depth": _apply_prefetch_depth,
    }

    # ------------------------------------------------------------------ jitted steps
    def _build_steps(self) -> None:
        app = self.app
        args, mesh, rules = app.arch_args, app.mesh, app.sharding_rules
        odsc = self.sampling_config
        precision = "highest" if self.cfg.dtype == "float32" else "default"
        # family forward cores (custom layouts — MLA, Llama4 — serve through their
        # own prefill/decode fns; the base family gets models/base.*)
        prefill_core = app.prefill_fn()
        decode_core = app.decode_fn()

        if self.paged:
            # ragged paged decode: the Pallas block-table kernels serve the chunked
            # decode body when the family/layout supports them (the serving hot
            # path — ≈ SURVEY §7 "ragged paged attention is the performance cliff");
            # inserts (wide prefix-prefill queries) keep the gather path
            paged_kernel_kw = (
                {"use_kernel": True} if app._use_paged_decode_kernel() else {})
            # the base decode path supports the epilogue/ragged extras
            # (logit_idx, skip_logits, q_lens); custom family forwards (MLA,
            # Llama4) keep the plain full-logits insert
            base_decode = decode_core is model_base.decode_forward
            if self.mixed and not base_decode:
                raise ValueError("mixed-step scheduling requires the base "
                                 "decode path (custom family decode forwards "
                                 "lack q_lens/logit_idx)")

            bs_blk = self.block_size

            def _insert(params, input_ids, position_ids, last_token_idx, cache,
                        telem, block_table_row, slot_mapping, sampling_params,
                        key, adapter_row, emit_seed):
                """Batch-1 (prefix-)prefill into paged blocks: a wide decode call whose
                queries are the (suffix) tokens; prior blocks are visible through the
                block table. On the base decode path only the last real token
                pays the lm_head (logit_idx gather — a padded 256-wide window
                over a 128k vocab would otherwise materialize ~131 MB of
                discarded logits). ``emit_seed`` is the host-known 0/1 flag:
                the sampled seed counts as an emitted token only when the host
                will emit it (resumed re-inserts discard it)."""
                with jax.default_matmul_precision(precision):
                    if base_decode:
                        logits, cache = decode_core(
                            params, args, input_ids, position_ids, cache, None,
                            mesh=mesh, rules=rules, block_table=block_table_row,
                            slot_mapping=slot_mapping, adapter_ids=adapter_row,
                            logit_idx=last_token_idx)
                        last = logits[:, 0]
                    else:
                        logits, cache = decode_core(
                            params, args, input_ids, position_ids, cache, None,
                            mesh=mesh, rules=rules, block_table=block_table_row,
                            slot_mapping=slot_mapping, adapter_ids=adapter_row)
                        last = jnp.take_along_axis(
                            logits, last_token_idx[:, None, None], axis=1)[:, 0]
                tok = sampling_ops.sample(last, sampling_params, key, odsc,
                                          mesh=mesh, rules=rules)
                telem = dtel.prefill_tick(telem, slot_mapping, bs_blk)
                telem = dtel.seed_tick(telem, emit_seed)
                telem = dtel.bump_kind(telem, dtel.KIND_INSERT_WINDOW)
                return tok, cache, telem

            def _insert_nol(params, input_ids, position_ids, cache, telem,
                            block_table_row, slot_mapping, adapter_row):
                """INTERMEDIATE insert window: KV-only. The sampled token of a
                non-final window is discarded, so skip the final norm, lm_head
                and sampling entirely (skip_logits — same discipline as the
                k-th draft step of a fused speculative iteration)."""
                with jax.default_matmul_precision(precision):
                    _, cache = decode_core(
                        params, args, input_ids, position_ids, cache, None,
                        mesh=mesh, rules=rules, block_table=block_table_row,
                        slot_mapping=slot_mapping, adapter_ids=adapter_row,
                        skip_logits=True)
                telem = dtel.prefill_tick(telem, slot_mapping, bs_blk)
                telem = dtel.bump_kind(telem, dtel.KIND_INSERT_WINDOW)
                return cache, telem

            def _decode(params, tok0, positions, alive0, budget0, cache,
                        telem, block_table, slot_chunk, sampling_params, key,
                        adapter_ids, eos_ids, num_steps, greedy=False):
                """``num_steps`` chained decode iterations with ON-DEVICE stop
                tracking: a row that emits its eos or exhausts its max-new
                budget FREEZES in-graph (token/position pinned, KV writes
                dropped) — exactly the host's commit/stop replay rules, so
                dispatch-ahead stays exact across chunk boundaries without
                the host having to prove no row can stop mid-pipeline. The
                returned (tok, pos, alive, budget) carry feeds the NEXT
                chunk's dispatch device-resident."""
                keys = jax.random.split(key, num_steps)
                slots_t = slot_chunk.T[:, :, None]          # (T, B, 1)

                def body(carry, xs):
                    tok, pos, alive, budget, cache, telem = carry
                    step_key, slots_j = xs
                    # frozen rows write nothing (their precomputed slots were
                    # host-estimated past their stop point)
                    slots_live = jnp.where(alive[:, None], slots_j, -1)
                    with jax.default_matmul_precision(precision):
                        logits, cache = decode_core(
                            params, args, tok[:, None], pos, cache, None,
                            mesh=mesh, rules=rules, block_table=block_table,
                            slot_mapping=slots_live, adapter_ids=adapter_ids,
                            **paged_kernel_kw)
                        if greedy:
                            # all rows argmax: skip the global-topk sampling
                            # window (measured 6.3 ms/step at bs=64, 128k vocab)
                            nxt = sampling_ops.greedy(logits[:, -1],
                                                      mesh=mesh, rules=rules)
                        else:
                            nxt = sampling_ops.sample(logits[:, -1],
                                                      sampling_params,
                                                      step_key, odsc,
                                                      mesh=mesh, rules=rules)
                    telem = dtel.decode_tick(telem, alive, nxt, eos_ids)
                    telem = dtel.kv_tick(telem, slots_live, bs_blk)
                    nxt = jnp.where(alive, nxt, tok)
                    pos = pos + alive.astype(pos.dtype)
                    budget = budget - alive.astype(budget.dtype)
                    alive = jnp.logical_and(alive, budget > 0)
                    alive = jnp.logical_and(alive, nxt != eos_ids)
                    return (nxt, pos, alive, budget, cache, telem), nxt

                (tok_l, pos_l, alive_l, budget_l, cache, telem), toks = \
                    jax.lax.scan(
                        body, (tok0, positions, alive0, budget0, cache, telem),
                        (keys, slots_t))
                telem = dtel.bump_kind(telem, dtel.KIND_DECODE)
                return toks.T, (tok_l, pos_l, alive_l, budget_l), cache, telem

            self._insert_step = audited_jit(
                _insert, kind="cb.paged.insert", cache_args=("cache",),
                carry_args=("telem",))
            self._insert_step_nol = (
                audited_jit(_insert_nol, kind="cb.paged.insert_nol",
                            cache_args=("cache",), carry_args=("telem",))
                if base_decode else None)
            self._decode_step = audited_jit(
                _decode, kind="cb.paged.decode", cache_args=("cache",),
                carry_args=("telem",),
                static_argnames=("num_steps", "greedy"),
                steps_arg="num_steps")

            if self.megastep_k is not None:
                def _megastep(params, tok0, positions, alive0, budget0, cache,
                              telem, block_table, coverage, sampling_params,
                              key, adapter_ids, eos_ids, n_iters, service,
                              ring_cap, greedy=False):
                    """ONE device-resident serving megastep: a lax.while_loop
                    of up to ``min(n_iters, ring_cap)`` decode inner steps
                    whose scheduler state — token/position/alive/budget
                    carry, per-step slot-mapping advance through the block
                    table, eos/budget stops, the emitted-token ring — is
                    AUTHORITATIVE on device (the host state is the replica
                    now). Early exits, checked before every inner step:

                    - all rows stopped (the in-graph mirror of the host's
                      commit/stop replay — same freeze rules as the scan);
                    - a live row's next write position reached ``coverage``
                      (its host-pre-reserved block budget, in positions:
                      ``len(blocks) * block_size``) — in-loop block
                      consumption never outruns the reservation;
                    - the emitted ring filled (``ring_cap`` < requested);
                    - the host's pending-arrival ``service`` flag (the loop
                      yields after ONE step so queued work is serviced at
                      step-wise latency, not K-step latency).

                    ``n_iters`` and ``service`` are DYNAMIC operands — one
                    executable serves every seq-room clamp and queue state;
                    only ``ring_cap``/``greedy`` are static. Returns the
                    ring, the executed count, the exit code, and the device
                    carry that seeds the next dispatch (async megasteps
                    pipeline exactly like scan chunks)."""
                    keys = jax.random.split(key, ring_cap)
                    ring0 = token_ring.init_ring(ring_cap, tok0.shape[0])
                    n_eff = jnp.minimum(n_iters, ring_cap)

                    def in_coverage(pos, alive):
                        return jnp.all(jnp.where(alive, pos < coverage, True))

                    def cond(carry):
                        i, tok, pos, alive, budget, ring, cache, telem = carry
                        more = (jnp.any(alive) & (i < n_eff)
                                & in_coverage(pos, alive))
                        return more & ((i == 0) | (service == 0))

                    def body(carry):
                        i, tok, pos, alive, budget, ring, cache, telem = carry
                        slots = block_kvcache.device_slot_advance(
                            block_table, pos, alive, bs_blk)[:, None]
                        with jax.default_matmul_precision(precision):
                            logits, cache = decode_core(
                                params, args, tok[:, None], pos, cache, None,
                                mesh=mesh, rules=rules,
                                block_table=block_table, slot_mapping=slots,
                                adapter_ids=adapter_ids, **paged_kernel_kw)
                            if greedy:
                                nxt = sampling_ops.greedy(logits[:, -1],
                                                          mesh=mesh,
                                                          rules=rules)
                            else:
                                nxt = sampling_ops.sample(logits[:, -1],
                                                          sampling_params,
                                                          keys[i], odsc,
                                                          mesh=mesh,
                                                          rules=rules)
                        telem = dtel.decode_tick(telem, alive, nxt, eos_ids)
                        telem = dtel.kv_tick(telem, slots, bs_blk)
                        telem = dtel.megastep_iter_tick(telem)
                        nxt = jnp.where(alive, nxt, tok)
                        ring = token_ring.push(ring, i, nxt)
                        pos = pos + alive.astype(pos.dtype)
                        budget = budget - alive.astype(budget.dtype)
                        alive = jnp.logical_and(alive, budget > 0)
                        alive = jnp.logical_and(alive, nxt != eos_ids)
                        return (i + 1, nxt, pos, alive, budget, ring, cache,
                                telem)

                    (n_run, tok_l, pos_l, alive_l, budget_l, ring, cache,
                     telem) = jax.lax.while_loop(
                        cond, body,
                        (jnp.asarray(0, jnp.int32), tok0, positions, alive0,
                         budget0, ring0, cache, telem))
                    stopped = ~jnp.any(alive_l)
                    blocks = ~in_coverage(pos_l, alive_l)
                    served = (service != 0) & (n_run < n_eff)
                    ring_full = (n_run >= ring_cap) & (ring_cap < n_iters)
                    exit_code = jnp.where(
                        stopped, MEGASTEP_EXIT_STOPPED,
                        jnp.where(blocks, MEGASTEP_EXIT_BLOCKS,
                                  jnp.where(served, MEGASTEP_EXIT_ARRIVAL,
                                            jnp.where(ring_full,
                                                      MEGASTEP_EXIT_RING,
                                                      MEGASTEP_EXIT_ITERS))))
                    telem = dtel.bump_kind(telem, dtel.KIND_MEGASTEP)
                    return ((ring, n_run, exit_code.astype(jnp.int32)),
                            (tok_l, pos_l, alive_l, budget_l), cache, telem)

                self._megastep_step = audited_jit(
                    _megastep, kind="cb.paged.megastep",
                    cache_args=("cache",), carry_args=("telem",),
                    static_argnames=("ring_cap", "greedy"))

            if self.mixed:
                def _mixed(params, tok0, positions, alive0, budget0, cache,
                           telem, block_table, slot_chunk, chunk_ids,
                           chunk_pos, chunk_qlens, chunk_bt, chunk_slots,
                           chunk_emit, sampling_params, chunk_sp,
                           key, adapter_ids, chunk_adapters, eos_ids,
                           num_steps, greedy=False):
                    """One MIXED serving step, ONE dispatch: the C prefill-chunk
                    rows run the variable-q_len ragged paged attend (each row's
                    last live token alone pays the lm_head via logit_idx;
                    padded rows carry slot -1 everywhere), then ``num_steps``
                    chained decode iterations advance every slot exactly as a
                    plain chunk would. Chunk rows and decode rows touch
                    disjoint blocks (shared prefix blocks are rewritten with
                    identical content), so the order inside the dispatch is
                    immaterial.

                    ``alive0``/``budget0``/``eos_ids`` feed the telemetry
                    carry's COUNTING-ONLY replay of the host commit rules
                    (tokens stay ungated — the host ignores post-stop tokens,
                    exactly as before); ``chunk_emit`` flags chunk rows whose
                    final-window seed the host will emit."""
                    key_c, key_d = jax.random.split(key)
                    with jax.default_matmul_precision(precision):
                        logits_c, cache = decode_core(
                            params, args, chunk_ids, chunk_pos, cache, None,
                            mesh=mesh, rules=rules, block_table=chunk_bt,
                            slot_mapping=chunk_slots,
                            adapter_ids=chunk_adapters, q_lens=chunk_qlens,
                            logit_idx=chunk_qlens - 1, **paged_kernel_kw)
                        if greedy:
                            chunk_tok = sampling_ops.greedy(logits_c[:, 0],
                                                            mesh=mesh,
                                                            rules=rules)
                        else:
                            chunk_tok = sampling_ops.sample(
                                logits_c[:, 0], chunk_sp, key_c, odsc,
                                mesh=mesh, rules=rules)
                    telem = dtel.prefill_tick(telem, chunk_slots, bs_blk)
                    telem = dtel.seed_tick(telem, jnp.sum(chunk_emit))

                    keys = jax.random.split(key_d, num_steps)
                    slots_t = slot_chunk.T[:, :, None]          # (steps, B, 1)

                    def body(carry, xs):
                        tok, pos, cache, alive_t, budget_t, telem = carry
                        step_key, slots_j = xs
                        with jax.default_matmul_precision(precision):
                            logits, cache = decode_core(
                                params, args, tok[:, None], pos, cache, None,
                                mesh=mesh, rules=rules, block_table=block_table,
                                slot_mapping=slots_j, adapter_ids=adapter_ids,
                                **paged_kernel_kw)
                            if greedy:
                                nxt = sampling_ops.greedy(logits[:, -1],
                                                          mesh=mesh,
                                                          rules=rules)
                            else:
                                nxt = sampling_ops.sample(logits[:, -1],
                                                          sampling_params,
                                                          step_key, odsc,
                                                          mesh=mesh,
                                                          rules=rules)
                        telem = dtel.decode_tick(telem, alive_t, nxt, eos_ids)
                        telem = dtel.kv_tick(telem, slots_j, bs_blk)
                        budget_t = budget_t - alive_t.astype(budget_t.dtype)
                        alive_t = jnp.logical_and(alive_t, budget_t > 0)
                        alive_t = jnp.logical_and(alive_t, nxt != eos_ids)
                        return (nxt, pos + 1, cache, alive_t, budget_t,
                                telem), nxt

                    (_, _, cache, _, _, telem), toks = jax.lax.scan(
                        body, (tok0, positions, cache, alive0, budget0, telem),
                        (keys, slots_t))
                    telem = dtel.bump_kind(telem, dtel.KIND_MIXED)
                    return toks.T, chunk_tok, cache, telem

                self._mixed_step = audited_jit(
                    _mixed, kind="cb.paged.mixed", cache_args=("cache",),
                    carry_args=("telem",),
                    static_argnames=("num_steps", "greedy"),
                    steps_arg="num_steps")

                if self.megastep_k is not None:
                    def _mixed_megastep(params, tok0, positions, alive0,
                                        budget0, cache, telem, block_table,
                                        slot_chunk, chunk_ids, chunk_pos,
                                        chunk_qlens, chunk_bt, chunk_slots,
                                        chunk_emit, sampling_params, chunk_sp,
                                        key, adapter_ids, chunk_adapters,
                                        eos_ids, num_windows, num_steps,
                                        greedy=False):
                        """``num_windows`` MIXED serving steps in ONE
                        dispatch: a lax.scan over whole insert windows, each
                        window the exact _mixed body (C budgeted prefill-chunk
                        rows through the variable-q_len ragged attend, then
                        ``num_steps`` chained decode iterations), the decode
                        carry (token/position/alive/budget/cache/telem)
                        threaded ACROSS windows exactly as the host would
                        re-seed it between step-wise dispatches. The window
                        plan (which rows, which chunk lengths, emit flags,
                        per-window slot mappings) is HOST-deterministic — the
                        FIFO/weighted chunk assignment depends only on host
                        bookkeeping the device never changes — so every
                        window's operands stack into leading-axis-W arrays at
                        dispatch time; a window whose completion would change
                        the plan (a prompt finishing joins the decode roster)
                        is always the LAST window of the plan."""
                        w_keys = jax.random.split(key, num_windows)
                        bsz = tok0.shape[0]
                        slots_w = slot_chunk.T.reshape(
                            num_windows, num_steps, bsz)[..., None]

                        def window(carry, xs):
                            tok, pos, cache, alive_t, budget_t, telem = carry
                            (key_w, c_ids, c_pos, c_qlens, c_bt, c_slots,
                             c_emit, c_sp, c_ad, slots_j) = xs
                            key_c, key_d = jax.random.split(key_w)
                            with jax.default_matmul_precision(precision):
                                logits_c, cache = decode_core(
                                    params, args, c_ids, c_pos, cache, None,
                                    mesh=mesh, rules=rules, block_table=c_bt,
                                    slot_mapping=c_slots, adapter_ids=c_ad,
                                    q_lens=c_qlens, logit_idx=c_qlens - 1,
                                    **paged_kernel_kw)
                                if greedy:
                                    c_tok = sampling_ops.greedy(
                                        logits_c[:, 0], mesh=mesh,
                                        rules=rules)
                                else:
                                    c_tok = sampling_ops.sample(
                                        logits_c[:, 0], c_sp, key_c, odsc,
                                        mesh=mesh, rules=rules)
                            telem = dtel.prefill_tick(telem, c_slots, bs_blk)
                            telem = dtel.seed_tick(telem, jnp.sum(c_emit))

                            d_keys = jax.random.split(key_d, num_steps)

                            def body(dc, dxs):
                                tok, pos, cache, alive_t, budget_t, \
                                    telem = dc
                                step_key, slots_i = dxs
                                with jax.default_matmul_precision(precision):
                                    logits, cache = decode_core(
                                        params, args, tok[:, None], pos,
                                        cache, None, mesh=mesh, rules=rules,
                                        block_table=block_table,
                                        slot_mapping=slots_i,
                                        adapter_ids=adapter_ids,
                                        **paged_kernel_kw)
                                    if greedy:
                                        nxt = sampling_ops.greedy(
                                            logits[:, -1], mesh=mesh,
                                            rules=rules)
                                    else:
                                        nxt = sampling_ops.sample(
                                            logits[:, -1], sampling_params,
                                            step_key, odsc, mesh=mesh,
                                            rules=rules)
                                telem = dtel.decode_tick(telem, alive_t, nxt,
                                                         eos_ids)
                                telem = dtel.kv_tick(telem, slots_i, bs_blk)
                                budget_t = budget_t - alive_t.astype(
                                    budget_t.dtype)
                                alive_t = jnp.logical_and(alive_t,
                                                          budget_t > 0)
                                alive_t = jnp.logical_and(alive_t,
                                                          nxt != eos_ids)
                                return (nxt, pos + 1, cache, alive_t,
                                        budget_t, telem), nxt

                            (tok, pos, cache, alive_t, budget_t,
                             telem), toks_w = jax.lax.scan(
                                body, (tok, pos, cache, alive_t, budget_t,
                                       telem), (d_keys, slots_j))
                            telem = dtel.megastep_iter_tick(telem)
                            return (tok, pos, cache, alive_t, budget_t,
                                    telem), (toks_w, c_tok)

                        (_, _, cache, _, _, telem), (toks, chunk_toks) = \
                            jax.lax.scan(
                                window,
                                (tok0, positions, cache, alive0, budget0,
                                 telem),
                                (w_keys, chunk_ids, chunk_pos, chunk_qlens,
                                 chunk_bt, chunk_slots, chunk_emit, chunk_sp,
                                 chunk_adapters, slots_w))
                        telem = dtel.bump_kind(telem,
                                               dtel.KIND_MIXED_MEGASTEP)
                        # (W, T, B) -> (B, W*T): the host's commit order
                        return (toks.transpose(2, 0, 1).reshape(bsz, -1),
                                chunk_toks, cache, telem)

                    self._mixed_megastep_step = audited_jit(
                        _mixed_megastep, kind="cb.paged.mixed_megastep",
                        cache_args=("cache",), carry_args=("telem",),
                        static_argnames=("num_windows", "num_steps",
                                        "greedy"),
                        steps_arg="num_steps")
        else:
            # thread the app's prefill strategy (ring for cp>1, Pallas flash, or
            # dense attend) into insert-time context encoding; decode chunks take
            # the Pallas stacked-cache path when the arch supports it
            use_ring = app._use_ring_attention()
            use_flash = (not use_ring) and app._use_flash_attention()
            kernel_kw = ({"use_kernel": True} if app._use_decode_kernel() else {})

            def _insert(params, input_ids, position_ids, last_token_idx, cache,
                        telem, slot, sampling_params, key, adapter_row,
                        emit_seed):
                with jax.default_matmul_precision(precision):
                    logits, cache = prefill_core(
                        params, args, input_ids, position_ids, last_token_idx, cache,
                        mesh=mesh, rules=rules, cache_batch_start=slot,
                        use_flash=use_flash, use_ring=use_ring,
                        adapter_ids=adapter_row)
                tok = sampling_ops.sample(logits, sampling_params, key, odsc,
                                          mesh=mesh, rules=rules)
                n_real = jnp.sum(last_token_idx + 1)
                telem = telem.at[dtel.IDX_PREFILL].add(n_real)
                telem = telem.at[dtel.IDX_KV_WRITES].add(n_real)
                telem = dtel.seed_tick(telem, emit_seed)
                telem = dtel.bump_kind(telem, dtel.KIND_INSERT)
                return tok, cache, telem

            def _decode(params, tok0, positions, alive0, budget0, cache,
                        telem, sampling_params, key, adapter_ids, eos_ids,
                        decode_bucket, num_steps, greedy=False):
                """Dense decode chunk with the same ON-DEVICE stop tracking as
                the paged chunk (see above); frozen rows re-write their frozen
                position with identical bytes — the dense path's existing
                harmless-rewrite discipline for inactive slots."""
                keys = jax.random.split(key, num_steps)

                def body(carry, step_key):
                    tok, pos, alive, budget, cache, telem = carry
                    with jax.default_matmul_precision(precision):
                        logits, cache = decode_core(
                            params, args, tok[:, None], pos, cache, decode_bucket,
                            mesh=mesh, rules=rules, adapter_ids=adapter_ids,
                            **kernel_kw)
                        if greedy:
                            nxt = sampling_ops.greedy(logits[:, -1],
                                                      mesh=mesh, rules=rules)
                        else:
                            nxt = sampling_ops.sample(logits[:, -1],
                                                      sampling_params,
                                                      step_key, odsc,
                                                      mesh=mesh, rules=rules)
                    telem = dtel.decode_tick(telem, alive, nxt, eos_ids)
                    telem = dtel.dense_kv_tick(telem, alive)
                    nxt = jnp.where(alive, nxt, tok)
                    pos = pos + alive.astype(pos.dtype)
                    budget = budget - alive.astype(budget.dtype)
                    alive = jnp.logical_and(alive, budget > 0)
                    alive = jnp.logical_and(alive, nxt != eos_ids)
                    return (nxt, pos, alive, budget, cache, telem), nxt

                (tok_l, pos_l, alive_l, budget_l, cache, telem), toks = \
                    jax.lax.scan(
                        body, (tok0, positions, alive0, budget0, cache, telem),
                        keys)
                telem = dtel.bump_kind(telem, dtel.KIND_DECODE)
                return toks.T, (tok_l, pos_l, alive_l, budget_l), cache, telem

            def _window(params, input_ids, start, slot, cache, telem, n_real,
                        adapter_row, decode_bucket):
                """Batch-1 dense windowed-prefill step at cache row ``slot`` (dense
                analog of the paged chunked insert; ≈ windowed CTE,
                `model_base.py:918-973`). ``n_real``: host-known count of real
                (non-padding) prompt tokens in this window, for the carry."""
                pos = jnp.full((1,), start, dtype=jnp.int32)
                with jax.default_matmul_precision(precision):
                    _, cache = model_base.decode_forward(
                        params, args, input_ids, pos, cache, decode_bucket,
                        mesh=mesh, rules=rules, window_row=slot,
                        adapter_ids=adapter_row)
                telem = telem.at[dtel.IDX_PREFILL].add(n_real)
                telem = telem.at[dtel.IDX_KV_WRITES].add(n_real)
                telem = dtel.bump_kind(telem, dtel.KIND_INSERT_WINDOW)
                return cache, telem

            def _seed(params, tok, pos, slot, cache, telem, sampling_params,
                      key, adapter_row, emit_seed, decode_bucket):
                """Re-feed the prompt's last token (idempotent KV rewrite) to obtain
                seed logits after a windowed insert."""
                with jax.default_matmul_precision(precision):
                    logits, cache = model_base.decode_forward(
                        params, args, tok[:, None], pos, cache, decode_bucket,
                        mesh=mesh, rules=rules, window_row=slot,
                        adapter_ids=adapter_row)
                out = sampling_ops.sample(logits[:, -1], sampling_params, key,
                                          odsc, mesh=mesh, rules=rules)
                telem = dtel.seed_tick(telem, emit_seed)
                telem = dtel.bump_kind(telem, dtel.KIND_INSERT_WINDOW)
                return out, cache, telem

            self._insert_step = audited_jit(
                _insert, kind="cb.dense.insert", cache_args=("cache",),
                carry_args=("telem",))
            self._decode_step = audited_jit(
                _decode, kind="cb.dense.decode", cache_args=("cache",),
                carry_args=("telem",),
                static_argnames=("decode_bucket", "num_steps", "greedy"),
                steps_arg="num_steps")
            self._window_step = audited_jit(
                _window, kind="cb.dense.window", cache_args=("cache",),
                carry_args=("telem",),
                static_argnames=("decode_bucket",))
            self._seed_step = audited_jit(
                _seed, kind="cb.dense.seed", cache_args=("cache",),
                carry_args=("telem",),
                static_argnames=("decode_bucket",))

        if self.draft is not None:
            self._build_spec_steps()
        elif self.eagle is not None:
            self._build_eagle_steps()

    def _build_eagle_steps(self) -> None:
        """EAGLE speculation through paged serving: hidden-state-conditioned
        1-layer draft (≈ runtime/eagle.py fused step, re-hosted on the CB block
        layout). The per-slot conditioning hidden rides DEVICE-resident runner
        state; inserts run the target's windowed prefix-prefill with
        return_hidden and stream the shifted hiddens into the draft pool."""
        from ..models import eagle as eagle_lib
        from . import speculation as spec_lib

        app = self.app
        t_args, mesh, rules = app.arch_args, app.mesh, app.sharding_rules
        d_args = self.eagle[0]
        k = self.k
        bs_blk = self.block_size
        mb = self.max_blocks_per_seq
        precision = "highest" if self.cfg.dtype == "float32" else "default"
        t_decode = app.decode_fn()
        t_kw = ({"use_kernel": True}
                if app._use_paged_decode_kernel() else {})
        odsc = self.sampling_config

        def _insert_eagle(t_params, d_params, input_ids, position_ids,
                          last_token_idx, t_cache, d_cache, telem, bt_row,
                          slot_map, sampling_params, key, h_prev, emit_seed):
            """One prefix-prefill window: target (samples seed token, returns
            hiddens) + EAGLE draft prefill conditioned on the shifted hiddens
            (h_prev = last hidden of the previous window; zeros for the first)."""
            with jax.default_matmul_precision(precision):
                logits, t_cache, h_full = t_decode(
                    t_params, t_args, input_ids, position_ids, t_cache, None,
                    mesh=mesh, rules=rules, block_table=bt_row,
                    slot_mapping=slot_map, return_hidden=True)
                last = jnp.take_along_axis(
                    logits, last_token_idx[:, None, None], axis=1)[:, 0]
                tok = sampling_ops.sample(last, sampling_params, key, odsc,
                                          mesh=mesh, rules=rules)
                cond = jnp.concatenate(
                    [h_prev[:, None].astype(h_full.dtype), h_full[:, :-1]],
                    axis=1)
                pos_grid = position_ids[:, None] + jnp.arange(
                    input_ids.shape[1], dtype=jnp.int32)[None, :]
                d_cache = eagle_lib.eagle_prefill_forward(
                    d_params, t_params, d_args, input_ids, cond, pos_grid,
                    last_token_idx, d_cache, mesh=mesh, rules=rules,
                    slot_mapping=slot_map)
                h_last = jnp.take_along_axis(
                    h_full, last_token_idx[:, None, None], axis=1)[:, 0]
            telem = dtel.prefill_tick(telem, slot_map, bs_blk)
            telem = dtel.seed_tick(telem, emit_seed)
            telem = dtel.bump_kind(telem, dtel.KIND_INSERT_WINDOW)
            return tok, h_last, t_cache, d_cache, telem

        self._insert_step_eagle = audited_jit(
            _insert_eagle, kind="cb.eagle.insert",
            cache_args=("t_cache", "d_cache"), carry_args=("telem",))

        def _eagle_chunk(t_params, d_params, tok0, h0, positions, alive0,
                         budget0, t_cache, d_cache, telem, block_table,
                         eos_ids, key, num_iters):
            """``num_iters`` on-device EAGLE iterations: K-1 hidden-conditioned
            draft proposals + wide K verify (greedy exact-match acceptance),
            per-row positions AND conditioning hiddens advancing in-graph.
            ``budget0`` feeds the telemetry carry's counting-only replay of
            the host commit rules (the real advance ignores budgets — the
            host truncates at commit, utils/device_telemetry.spec_tick)."""
            del key                      # greedy: no sampling noise

            def one_iter(carry, _):
                tok, h, pos, alive, alive_t, budget_t, t_cache, d_cache, \
                    telem = carry
                p = pos[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
                blk = jnp.take_along_axis(
                    block_table, jnp.minimum(p // bs_blk, mb - 1), axis=1)
                sm = jnp.where(alive[:, None], blk * bs_blk + p % bs_blk, -1)
                sm_cols = sm.T[:, :, None]                  # (K, B, 1)

                # k-1 proposal steps + one KV-only step (skip_logits: the
                # k-th proposal is discarded, and the EAGLE draft head is the
                # TARGET's full lm_head — the largest stream in the step)
                def draft_body(dc, sm_j):
                    dtok, dh, dpos, cache = dc
                    with jax.default_matmul_precision(precision):
                        logits, h_d, cache = eagle_lib.eagle_decode_forward(
                            d_params, t_params, d_args, dtok[:, None],
                            dh[:, None, :], dpos, cache, None, mesh=mesh,
                            rules=rules, block_table=block_table,
                            slot_mapping=sm_j)
                    nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                    return (nxt, h_d[:, -1], dpos + 1, cache), nxt

                (d_last, d_h, d_pos, d_cache), d_toks = jax.lax.scan(
                    draft_body, (tok, h, pos, d_cache), sm_cols[: k - 1])
                d_toks = d_toks.T                           # (B, K-1)
                with jax.default_matmul_precision(precision):
                    _, _, d_cache = eagle_lib.eagle_decode_forward(
                        d_params, t_params, d_args, d_last[:, None],
                        d_h[:, None, :], d_pos, d_cache, None, mesh=mesh,
                        rules=rules, block_table=block_table,
                        slot_mapping=sm_cols[k - 1], skip_logits=True)

                t_in = jnp.concatenate([tok[:, None], d_toks], axis=1)
                with jax.default_matmul_precision(precision):
                    t_logits, t_cache, t_h = t_decode(
                        t_params, t_args, t_in, pos, t_cache, None,
                        mesh=mesh, rules=rules, block_table=block_table,
                        slot_mapping=sm, return_hidden=True, **t_kw)
                t_toks = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
                matches = d_toks == t_toks[:, :-1]
                n = jnp.cumprod(matches.astype(jnp.int32), axis=1).sum(
                    axis=1).astype(jnp.int32)

                take, new_tok, alive_next = spec_lib.chunk_advance(
                    alive, t_toks, n, eos_ids)
                telem = dtel.kv_tick(telem, sm, bs_blk)
                telem, alive_t, budget_t = dtel.spec_tick(
                    telem, alive_t, budget_t, t_toks, n, eos_ids)
                h_next = jnp.take_along_axis(
                    t_h, n[:, None, None], axis=1)[:, 0]    # hidden at slot n
                tok = jnp.where(take > 0, new_tok, tok)
                h = jnp.where((take > 0)[:, None], h_next, h)
                pos = pos + take
                return (tok, h, pos, alive_next, alive_t, budget_t, t_cache,
                        d_cache, telem), (t_toks, n)

            (_, h_out, _, _, _, _, t_cache, d_cache, telem), (outs, ns) = \
                jax.lax.scan(
                    one_iter, (tok0, h0, positions, alive0, alive0, budget0,
                               t_cache, d_cache, telem),
                    None, length=num_iters)
            telem = dtel.bump_kind(telem, dtel.KIND_SPEC)
            return outs, ns, h_out, t_cache, d_cache, telem

        self._spec_step_eagle = audited_jit(
            _eagle_chunk, kind="cb.eagle.chunk",
            cache_args=("t_cache", "d_cache"), carry_args=("telem",),
            static_argnames=("num_iters",), steps_arg="num_iters")

    def _build_spec_steps(self) -> None:
        """Fused-speculation serving chunks: per dispatch, ``num_iters`` on-device
        iterations of (draft scan -> wide K verify -> acceptance), per-row
        positions advancing in-graph by each row's accepted length.

        ≈ reference fused spec over CB + block KV (`block_kv_cache_manager.py:402`
        ``generate_fusedspec_slot_mapping``): here the (B, K) slot mapping is
        recomputed from the live positions INSIDE the graph each iteration (a
        block-table gather), because the host cannot know them in advance."""
        from . import speculation as spec_lib
        from .speculation import speculative_accept

        app, draft = self.app, self.draft
        t_args, mesh, rules = app.arch_args, app.mesh, app.sharding_rules
        d_args, d_mesh, d_rules = (draft.arch_args, draft.mesh,
                                   draft.sharding_rules)
        odsc = self.sampling_config
        k = self.k
        vocab = t_args.vocab_size
        precision = "highest" if self.cfg.dtype == "float32" else "default"
        t_decode = app.decode_fn()
        d_decode = draft.decode_fn()

        paged = self.paged
        if paged:
            bs = self.block_size
            mb = self.max_blocks_per_seq
            t_kw = ({"use_kernel": True}
                    if app._use_paged_decode_kernel() else {})
            d_kw = ({"use_kernel": True}
                    if draft._use_paged_decode_kernel() else {})
        else:
            t_kw = {"use_kernel": True} if app._use_decode_kernel() else {}
            d_kw = {"use_kernel": True} if draft._use_decode_kernel() else {}

        # the k-th draft step is KV-only (its proposal is discarded): skip the
        # draft's final norm + lm_head when the family forward supports it —
        # streaming the draft lm_head for a discarded proposal is pure waste
        d_skip = (dict(skip_logits=True)
                  if d_decode is model_base.decode_forward else {})

        def _spec_iter_factory(t_params, d_params, block_table,
                               sampling_params, eos_ids, adapter_ids, greedy,
                               decode_bucket):
            """ONE draft(k-1) -> KV-only draft -> wide-K verify -> acceptance
            iteration, shared verbatim by the step-wise scan (_spec_chunk)
            and the device-resident while_loop (_spec_megastep): bit-identity
            between the two paths is structural, not re-proved per edit."""

            def one_iter_core(tok, pos, alive, alive_t, budget_t, t_cache,
                              d_cache, telem, key_i):
                key_d, key_acc = jax.random.split(key_i)
                d_keys = jax.random.split(key_d, k - 1)
                if paged:
                    # per-sequence K-wide slot mapping from the LIVE positions
                    p = pos[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
                    blk = jnp.take_along_axis(
                        block_table, jnp.minimum(p // bs, mb - 1), axis=1)
                    sm = jnp.where(alive[:, None], blk * bs + p % bs, -1)
                    d_extra = dict(block_table=block_table)
                    t_extra = dict(block_table=block_table, slot_mapping=sm)
                    sm_cols = sm.T[:, :, None]                    # (K, B, 1)
                else:
                    d_extra = t_extra = {}
                    sm_cols = jnp.zeros((k, 1, 1), dtype=jnp.int32)

                # draft loop: k-1 proposal steps, then one KV-only step so
                # d_{k-1}'s KV lands before a possible full accept (no logits
                # for it — see d_skip). Greedy chunks stack only the proposed
                # tokens; the (B, V) per-step logits are stacked ONLY when the
                # rejection sampler needs them (multinomial acceptance).
                def draft_body(dc, xs):
                    dtok, dpos, cache = dc
                    key_j, sm_j = xs
                    kwj = dict(d_extra)
                    if paged:
                        kwj["slot_mapping"] = sm_j
                    with jax.default_matmul_precision(precision):
                        logits, cache = d_decode(
                            d_params, d_args, dtok[:, None], dpos, cache,
                            decode_bucket, mesh=d_mesh, rules=d_rules,
                            **kwj, **d_kw)
                    last = logits[:, -1]
                    if greedy:
                        nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)
                        return (nxt, dpos + 1, cache), nxt
                    nxt = sampling_ops.sample(last, sampling_params,
                                              key_j, odsc, mesh=d_mesh,
                                              rules=d_rules)
                    return (nxt, dpos + 1, cache), (nxt, last)

                (d_last, d_pos, d_cache), ys = jax.lax.scan(
                    draft_body, (tok, pos, d_cache),
                    (d_keys, sm_cols[: k - 1]))
                if greedy:
                    d_toks, d_logits = ys.T, None                 # (B, K-1)
                else:
                    d_toks = ys[0].T                              # (B, K-1)
                    d_logits = ys[1].transpose(1, 0, 2)           # (B, K-1, V)
                kwf = dict(d_extra)
                if paged:
                    kwf["slot_mapping"] = sm_cols[k - 1]
                with jax.default_matmul_precision(precision):
                    _, d_cache = d_decode(
                        d_params, d_args, d_last[:, None], d_pos, d_cache,
                        decode_bucket, mesh=d_mesh, rules=d_rules,
                        **kwf, **d_kw, **d_skip)

                t_in = jnp.concatenate([tok[:, None], d_toks], axis=1)
                with jax.default_matmul_precision(precision):
                    # adapters apply to the TARGET only: the draft proposes from
                    # base weights (acceptance corrects any drift — exactness
                    # never depends on the draft)
                    t_logits, t_cache = t_decode(
                        t_params, t_args, t_in, pos, t_cache, decode_bucket,
                        mesh=mesh, rules=rules, adapter_ids=adapter_ids,
                        **t_extra, **t_kw)
                out_toks, n = speculative_accept(
                    d_toks, d_logits, t_logits, sampling_params, key_acc,
                    greedy=greedy, odsc=odsc, vocab=vocab)

                # rows whose committed window contains their eos stop advancing
                # (the host replays the exact same stopping rule when committing)
                take, new_tok, alive_next = spec_lib.chunk_advance(
                    alive, out_toks, n, eos_ids)
                if paged:
                    telem = dtel.kv_tick(telem, sm, bs)
                else:
                    # dense verify writes K slots per live row
                    telem = telem.at[dtel.IDX_KV_WRITES].add(
                        k * jnp.sum(alive))
                telem, alive_t, budget_t = dtel.spec_tick(
                    telem, alive_t, budget_t, out_toks, n, eos_ids)
                tok = jnp.where(take > 0, new_tok, tok)
                pos = pos + take
                return (tok, pos, alive_next, alive_t, budget_t, t_cache,
                        d_cache, telem, out_toks, n)

            return one_iter_core

        def _spec_chunk(t_params, d_params, tok0, positions, alive0, budget0,
                        t_cache, d_cache, telem, block_table, sampling_params,
                        eos_ids, key, adapter_ids, num_iters, greedy,
                        decode_bucket=None):
            iter_keys = jax.random.split(key, num_iters)
            iter_core = _spec_iter_factory(t_params, d_params, block_table,
                                           sampling_params, eos_ids,
                                           adapter_ids, greedy, decode_bucket)

            def one_iter(carry, key_i):
                tok, pos, alive, alive_t, budget_t, t_cache, d_cache, \
                    telem = carry
                (tok, pos, alive, alive_t, budget_t, t_cache, d_cache, telem,
                 out_toks, n) = iter_core(tok, pos, alive, alive_t, budget_t,
                                          t_cache, d_cache, telem, key_i)
                return (tok, pos, alive, alive_t, budget_t, t_cache, d_cache,
                        telem), (out_toks, n)

            (_, _, _, _, _, t_cache, d_cache, telem), (outs, ns) = \
                jax.lax.scan(
                    one_iter, (tok0, positions, alive0, alive0, budget0,
                               t_cache, d_cache, telem), iter_keys)
            telem = dtel.bump_kind(telem, dtel.KIND_SPEC)
            return outs, ns, t_cache, d_cache, telem

        self._spec_step = audited_jit(
            _spec_chunk, kind="cb.spec.chunk",
            cache_args=("t_cache", "d_cache"), carry_args=("telem",),
            static_argnames=("num_iters", "greedy", "decode_bucket"),
            steps_arg="num_iters")

        if paged and self.megastep_k is not None:
            def _spec_megastep(t_params, d_params, tok0, positions, alive0,
                               budget0, t_cache, d_cache, telem, block_table,
                               coverage, sampling_params, eos_ids, key,
                               adapter_ids, n_iters, service, ring_cap,
                               greedy, decode_bucket=None):
                """ONE device-resident SPECULATIVE serving megastep: a
                lax.while_loop of up to ``min(n_iters, ring_cap)`` fused
                draft-verify-accept iterations (each the exact one_iter_core
                the step-wise _spec_chunk scans over), the per-iteration
                (out_toks, n) acceptance results ringed into fixed (ring_cap,
                B, K)/(ring_cap, B) buffers the host drains after ONE sync
                instead of one sync per chunk. Early exits, checked before
                every iteration against the COUNTING replay mask ``alive_t``
                (the in-graph mirror of the host's commit_row budget/eos
                stops — the device ``alive`` mask ignores budgets exactly as
                in the step-wise path):

                - all replay-live rows stopped (budget/eos);
                - a still-WRITING row's next K-wide verify window would cross
                  its host-pre-reserved block ``coverage`` (positions) —
                  masked over the device ``alive`` rows, because those are
                  the rows that keep writing KV even once replay-dead;
                - the host's pending-arrival ``service`` flag (one iteration,
                  then yield — queued work is serviced at chunk latency).

                ``n_iters``/``service`` are DYNAMIC operands: one executable
                serves every seq-room clamp, K sweep (via ring_cap statics
                only) and queue state."""
                iter_keys = jax.random.split(key, ring_cap)
                iter_core = _spec_iter_factory(t_params, d_params,
                                               block_table, sampling_params,
                                               eos_ids, adapter_ids, greedy,
                                               decode_bucket)
                b = tok0.shape[0]
                outs0 = jnp.zeros((ring_cap, b, k), jnp.int32)
                ns0 = jnp.zeros((ring_cap, b), jnp.int32)
                n_eff = jnp.minimum(n_iters, ring_cap)

                def in_coverage(pos, writing):
                    return jnp.all(jnp.where(writing, pos + k <= coverage,
                                             True))

                def cond(carry):
                    (i, tok, pos, alive, alive_t, budget_t, outs_r, ns_r,
                     t_cache, d_cache, telem) = carry
                    more = (jnp.any(alive_t) & (i < n_eff)
                            & in_coverage(pos, alive))
                    return more & ((i == 0) | (service == 0))

                def body(carry):
                    (i, tok, pos, alive, alive_t, budget_t, outs_r, ns_r,
                     t_cache, d_cache, telem) = carry
                    (tok, pos, alive, alive_t, budget_t, t_cache, d_cache,
                     telem, out_toks, n) = iter_core(
                        tok, pos, alive, alive_t, budget_t, t_cache, d_cache,
                        telem, iter_keys[i])
                    telem = dtel.megastep_iter_tick(telem)
                    outs_r = jax.lax.dynamic_update_index_in_dim(
                        outs_r, out_toks, i, 0)
                    ns_r = jax.lax.dynamic_update_index_in_dim(ns_r, n, i, 0)
                    return (i + 1, tok, pos, alive, alive_t, budget_t,
                            outs_r, ns_r, t_cache, d_cache, telem)

                (n_run, _, pos_l, alive_l, alive_tl, _, outs_r, ns_r,
                 t_cache, d_cache, telem) = jax.lax.while_loop(
                    cond, body,
                    (jnp.asarray(0, jnp.int32), tok0, positions, alive0,
                     alive0, budget0, outs0, ns0, t_cache, d_cache, telem))
                stopped = ~jnp.any(alive_tl)
                blocks = ~in_coverage(pos_l, alive_l)
                served = (service != 0) & (n_run < n_eff)
                ring_full = (n_run >= ring_cap) & (ring_cap < n_iters)
                exit_code = jnp.where(
                    stopped, MEGASTEP_EXIT_STOPPED,
                    jnp.where(blocks, MEGASTEP_EXIT_BLOCKS,
                              jnp.where(served, MEGASTEP_EXIT_ARRIVAL,
                                        jnp.where(ring_full,
                                                  MEGASTEP_EXIT_RING,
                                                  MEGASTEP_EXIT_ITERS))))
                telem = dtel.bump_kind(telem, dtel.KIND_SPEC_MEGASTEP)
                return ((outs_r, ns_r, n_run, exit_code.astype(jnp.int32)),
                        t_cache, d_cache, telem)

            self._spec_megastep_step = audited_jit(
                _spec_megastep, kind="cb.spec.megastep",
                cache_args=("t_cache", "d_cache"), carry_args=("telem",),
                static_argnames=("ring_cap", "greedy", "decode_bucket"))

        if paged:
            t_base = t_decode is model_base.decode_forward

            def _insert_pair(t_params, d_params, input_ids, position_ids,
                             last_token_idx, t_cache, d_cache, telem, bt_row,
                             slot_mapping, sampling_params, key, adapter_row,
                             emit_seed, final):
                """One prefix-prefill window for BOTH pools in ONE dispatch —
                the draft insert was previously a second jitted call per
                window (its own ~dispatch-floor of host latency every
                window). Only the prompt-FINAL window (static ``final``)
                pays the target's lm_head + sampling; intermediate windows
                run both models KV-only (skip_logits)."""
                with jax.default_matmul_precision(precision):
                    if final:
                        tkw = dict(logit_idx=last_token_idx) if t_base else {}
                        logits, t_cache = t_decode(
                            t_params, t_args, input_ids, position_ids, t_cache,
                            None, mesh=mesh, rules=rules, block_table=bt_row,
                            slot_mapping=slot_mapping, adapter_ids=adapter_row,
                            **tkw)
                        last = (logits[:, 0] if t_base else jnp.take_along_axis(
                            logits, last_token_idx[:, None, None], axis=1)[:, 0])
                        tok = sampling_ops.sample(last, sampling_params, key,
                                                  odsc, mesh=mesh, rules=rules)
                    else:
                        tkw = dict(skip_logits=True) if t_base else {}
                        _, t_cache = t_decode(
                            t_params, t_args, input_ids, position_ids, t_cache,
                            None, mesh=mesh, rules=rules, block_table=bt_row,
                            slot_mapping=slot_mapping, adapter_ids=adapter_row,
                            **tkw)
                        tok = jnp.zeros((input_ids.shape[0],), jnp.int32)
                    _, d_cache = d_decode(
                        d_params, d_args, input_ids, position_ids, d_cache,
                        None, mesh=d_mesh, rules=d_rules, block_table=bt_row,
                        slot_mapping=slot_mapping, **d_skip)
                telem = dtel.prefill_tick(telem, slot_mapping, bs)
                if final:
                    telem = dtel.seed_tick(telem, emit_seed)
                telem = dtel.bump_kind(telem, dtel.KIND_INSERT_WINDOW)
                return tok, t_cache, d_cache, telem

            self._insert_pair_step = audited_jit(
                _insert_pair, kind="cb.spec.insert_pair",
                cache_args=("t_cache", "d_cache"), carry_args=("telem",),
                static_argnames=("final",))
        else:
            d_prefill = draft.prefill_fn()
            use_ring = draft._use_ring_attention()
            use_flash = (not use_ring) and draft._use_flash_attention()

            def _d_insert(d_params, input_ids, position_ids, last_token_idx,
                          cache, slot):
                with jax.default_matmul_precision(precision):
                    _, cache = d_prefill(
                        d_params, d_args, input_ids, position_ids,
                        last_token_idx, cache, mesh=d_mesh, rules=d_rules,
                        cache_batch_start=slot, use_flash=use_flash,
                        use_ring=use_ring)
                return cache

            self._d_insert_step = audited_jit(
                _d_insert, kind="cb.spec.d_insert", cache_args=("cache",))

    # ------------------------------------------------ host-RAM KV tier hooks
    def _read_tier_blocks(self, block_ids: np.ndarray):
        """Tier spill gather: (L, N, H, BS, D) device views of the named
        blocks from both pools. A fresh gather buffer, so the snapshot stays
        valid however the (donated) cache buffers move afterwards."""
        idx = jnp.asarray(block_ids, dtype=jnp.int32)
        return self.cache["k"][:, idx], self.cache["v"][:, idx]

    def _dispatch_readmits(self, for_request: Optional[int] = None) -> None:
        """Scatter queued host-tier blocks back into the paged pool — ONE
        bucketed ``cb.paged.tier_readmit`` dispatch, issued BEFORE the
        requesting prompt's first insert window so the windows (and every
        later decode) read the restored prefix through the block table.
        ``for_request`` stamps the step-timeline record with the request
        whose prefix walk reserved the bytes, so its span tree
        (serving/tracing.py) carries the readmit as its own."""
        if self.kv_tier is None:
            return
        pending = self.allocator.take_pending_readmits()
        if not pending:
            return
        from ..serving.kv_tiering import READMIT_BUCKET_CAP, readmit_bucket

        tier = self.kv_tier
        tier.note_readmitted(len(pending))
        # one dispatch per <=cap-block chunk (a >cap batch would overflow the
        # largest bucket); padding rows carry block id -1 and drop
        for lo in range(0, len(pending), READMIT_BUCKET_CAP):
            chunk = pending[lo : lo + READMIT_BUCKET_CAP]
            ks, vs, ids = [], [], []
            for blk, _h, host_blk in chunk:
                k, v = host_blk.materialize()
                ks.append(k)
                vs.append(v)
                ids.append(blk)
            b = readmit_bucket(len(ids))
            # (L, N, H, BS, D) stacked on the block axis
            k_new = np.stack(ks, axis=1)
            v_new = np.stack(vs, axis=1)
            if b > len(ids):
                pad_shape = (k_new.shape[0], b - len(ids)) + k_new.shape[2:]
                k_new = np.concatenate(
                    [k_new, np.zeros(pad_shape, dtype=k_new.dtype)], axis=1)
                v_new = np.concatenate(
                    [v_new, np.zeros(pad_shape, dtype=v_new.dtype)], axis=1)
            id_arr = np.full((b,), -1, dtype=np.int32)
            id_arr[: len(ids)] = ids
            tel = self.telemetry
            t0 = tel.step_start()
            with tel.annotate("tier_readmit"):
                self.cache, self._telem_dev = self._tier_readmit_step(
                    self.cache, self._telem_dev, jnp.asarray(k_new),
                    jnp.asarray(v_new), jnp.asarray(id_arr),
                    block_size=self.block_size)
            if self.ledger is not None:
                # the scatter is enqueued: the blocks' KV is authoritative
                # on device again (readmit_inflight -> live)
                self.ledger.readmit_committed(ids)
            # cluster pulls ride the same dispatch; commit releases the
            # store-side pin (local _HostBlocks have no commit — no-op)
            n_cluster = 0
            for _blk, _h, host_blk in chunk:
                commit = getattr(host_blk, "commit", None)
                if commit is not None:
                    commit()
                    n_cluster += 1
            if t0 is not None:
                tel.step_record(
                    t0, "tier_readmit", iterations=1,
                    prefill_tokens=len(ids) * self.block_size,
                    slots=self.num_slots,
                    kv_free=self.allocator.num_free,
                    kv_total=self.allocator.num_blocks,
                    request_id=for_request,
                    extra=({"cluster_blocks": n_cluster}
                           if n_cluster else None))

    def _bytes_per_block(self) -> int:
        """Per-block KV bytes across the pool arrays (block axis 1) — the
        ledger's byte-attribution scale. 0 when the layout is opaque."""
        try:
            nb = self.allocator.num_blocks
            total = sum(
                int(v.nbytes) for v in self.cache.values()
                if getattr(v, "ndim", 0) >= 2 and v.shape[1] == nb)
            d_cache = getattr(self, "d_cache", None)
            if isinstance(d_cache, dict):
                total += sum(
                    int(v.nbytes) for v in d_cache.values()
                    if getattr(v, "ndim", 0) >= 2 and v.shape[1] == nb)
            return total // max(1, nb)
        # lint: ok(silent-except): attribution scale only — an exotic family cache layout degrades bytes to 0, never breaks construction
        except Exception:
            return 0

    def _led(self, req: Optional[Request], seam: str,
             expect_exhaustion: bool = False):
        """Ledger attribution context for one allocator seam (a shared null
        context when no ledger is attached). ``expect_exhaustion``: the seam
        probes headroom and handles KVBlocksExhausted as designed
        degradation — no OOM forensics capture."""
        if self.ledger is None:
            return contextlib.nullcontext()
        return self.ledger.context(
            request_id=None if req is None else req.request_id, seam=seam,
            sla_class=None if req is None else req.sla_class,
            expect_exhaustion=expect_exhaustion)

    def _expected_holders(self) -> Dict[int, Dict[int, int]]:
        """The runner's own roster of legitimate block holders: every live
        (placed, unfinished) request and its blocks list — the audit's
        cross-check that turns a dropped release into an attributed leak."""
        exp: Dict[int, Dict[int, int]] = {}
        for r in self.active:
            if r is None or r.done:
                continue
            held: Dict[int, int] = {}
            for blk in r.blocks:
                held[blk] = held.get(blk, 0) + 1
            exp[r.request_id] = held
        # open KV handoff sessions hold their staged destination blocks under
        # a negative session id — legitimate for as long as the transfer
        # overlaps the source's prefill; an abandoned session stops appearing
        # here and audits as a leak attributed to its session id
        for sess in self._handoff_sessions.values():
            held = {}
            for blk in sess["blocks"]:
                held[blk] = held.get(blk, 0) + 1
            exp[sess["rid"]] = held
        return exp

    def _kv_fragmentation(self) -> float:
        """Internal fragmentation over live requests: the fraction of
        allocated slots not (yet) holding committed KV — tail-block padding
        plus growth reservations."""
        held = used = 0
        for r in self.active:
            if r is None or r.done or not r.blocks:
                continue
            held += len(r.blocks) * self.block_size
            used += r.insert_pos if r.inserting else r.position
        return round(1.0 - used / held, 4) if held else 0.0

    def audit_ledger(self, raise_on_violation: bool = False) -> Optional[dict]:
        """Run the ledger's conservation audit against the runner's roster.
        None when no ledger is attached. Non-raising mode (serving) logs one
        structured ``memledger_violation {json}`` line and bumps
        ``memledger_violations_total`` on failure."""
        if self.ledger is None:
            return None
        return self.ledger.audit(expected_holders=self._expected_holders(),
                                 raise_on_violation=raise_on_violation)

    def _free_blocks(self, req: Request, seam: str = "release") -> None:
        """Release a request's blocks. With the tiered allocator a mid-prompt
        preemption/truncation must not park the (possibly unwritten) tail
        blocks as idle prefix-cache entries — their hashes are registered at
        allocation but the KV streams in over later windows."""
        with self._led(req, seam):
            if self.kv_tier is not None and req.inserting:
                no_park = set(req.blocks[req.insert_pos // self.block_size:])
                self.allocator.free_sequence(req.blocks, no_park=no_park)
            else:
                self.allocator.free_sequence(req.blocks)

    def spill_idle_blocks(self, keep: int = 0) -> int:
        """Force the tier's evict path: spill all but ``keep`` idle blocks to
        host RAM (drain/maintenance hook; tests and the audit harness use it
        to exercise evict→readmit deterministically). No-op without a tier."""
        if self.kv_tier is None:
            return 0
        return self.allocator.spill_idle(keep)

    # -------------------------------------------- pool KV handoff (dest side)
    # serving/pools.py drives these on a DECODE-pool replica's runner: a
    # handoff session allocates destination blocks under a NEGATIVE session
    # holder id (collides with no request id; the roster includes open
    # sessions so an abandoned one audits as an attributed leak), stages
    # bytes chunk by chunk with the bucketed cb.paged.kv_handoff scatter
    # while the SOURCE replica is still prefilling, and publishes the blocks'
    # prefix-cache hashes only at commit — an aborted session leaves nothing
    # behind.

    HANDOFF_HOLDER_BASE = -1000

    def handoff_headroom(self) -> int:
        """Allocatable destination headroom (free + idle blocks) — the
        decode-pool admission signal (``PoolManager.can_admit``)."""
        return self.allocator.num_free if self.paged else 0

    def _handoff_ctx(self, sess: dict, seam: str,
                     expect_exhaustion: bool = False):
        if self.ledger is None:
            return contextlib.nullcontext()
        return self.ledger.context(request_id=sess["rid"], seam=seam,
                                   expect_exhaustion=expect_exhaustion)

    def handoff_open(self) -> int:
        """Open a transfer session on this (destination) runner; returns the
        session id the staging/commit/abort calls key on."""
        if not self.paged:
            raise ValueError("KV handoff requires paged attention")
        if not hasattr(self.allocator, "_alloc_one"):
            # the native C++ allocator exposes no Python alloc/release/hash
            # seams for the session to stage through — same constraint as
            # the fault injector's alloc/leak seams
            raise ValueError(
                "KV handoff requires the Python block allocator (enable a "
                "host KV tier or memledger=True on the destination runner)")
        self._handoff_seq += 1
        sid = self._handoff_seq
        self._handoff_sessions[sid] = {
            "rid": self.HANDOFF_HOLDER_BASE - sid,
            "blocks": [], "hashes": []}
        return sid

    def handoff_receive(self, sid: int, k_new, v_new, hashes,
                        request_id: Optional[int] = None):
        """Stage one chunk of handed-off blocks: allocate destination blocks,
        scatter the bytes (device-to-device when ``k_new``/``v_new`` are the
        source cache's gather results — ``_read_tier_blocks`` shaped
        ``(L, n, H, BS, D)``), and hold them ``handoff_inflight`` until
        commit. Returns the destination block ids, or None when the pool
        cannot take the chunk (allocation rolled back; the caller defers or
        falls back to the host-tier channel). ``request_id`` stamps the
        step-timeline records with the migrating request so its span tree
        (serving/tracing.py) carries the transfer."""
        sess = self._handoff_sessions[sid]
        n = len(hashes)
        if n == 0:
            return []
        fresh: List[int] = []
        try:
            with self._handoff_ctx(sess, "handoff_in",
                                   expect_exhaustion=True):
                for _ in range(n):
                    fresh.append(self.allocator._alloc_one())
        # lint: ok(silent-except): the None return IS the signal — the pool manager counts the deferral (pools stats) and retries next tick or finishes at source
        except block_kvcache.KVBlocksExhausted:
            with self._handoff_ctx(sess, "handoff_in"):
                for blk in fresh:
                    self.allocator._release_one(blk)
            return None
        if self.ledger is not None:
            self.ledger.handoff_begin(fresh)
        if self._kv_handoff_step is None:
            from ..serving.kv_tiering import build_handoff_step

            self._kv_handoff_step = build_handoff_step()
        from ..serving.kv_tiering import READMIT_BUCKET_CAP, readmit_bucket

        k_new = jnp.asarray(k_new)
        v_new = jnp.asarray(v_new)
        tel = self.telemetry
        for lo in range(0, n, READMIT_BUCKET_CAP):
            ids = fresh[lo : lo + READMIT_BUCKET_CAP]
            kc = k_new[:, lo : lo + len(ids)]
            vc = v_new[:, lo : lo + len(ids)]
            b = readmit_bucket(len(ids))
            if b > len(ids):
                pad = (kc.shape[0], b - len(ids)) + tuple(kc.shape[2:])
                kc = jnp.concatenate(
                    [kc, jnp.zeros(pad, dtype=kc.dtype)], axis=1)
                vc = jnp.concatenate(
                    [vc, jnp.zeros(pad, dtype=vc.dtype)], axis=1)
            id_arr = np.full((b,), -1, dtype=np.int32)
            id_arr[: len(ids)] = ids
            t0 = tel.step_start()
            with tel.annotate("kv_handoff"):
                self.cache, self._telem_dev = self._kv_handoff_step(
                    self.cache, self._telem_dev, kc, vc,
                    jnp.asarray(id_arr), block_size=self.block_size)
            if t0 is not None:
                tel.step_record(
                    t0, "kv_handoff", iterations=1,
                    prefill_tokens=len(ids) * self.block_size,
                    slots=self.num_slots,
                    kv_free=self.allocator.num_free,
                    kv_total=self.allocator.num_blocks,
                    request_id=request_id)
        sess["blocks"].extend(fresh)
        sess["hashes"].extend(hashes)
        return fresh

    def handoff_commit(self, sid: int) -> Dict[bytes, int]:
        """Finalize a session: the staged bytes are authoritative, their
        hashes publish to the prefix cache, and the session's hold releases
        — on a tiered allocator the hashed blocks park IDLE, exactly the
        shape ``allocate_for_prompt``'s prefix walk reuses for free when the
        migrated request re-places here (a plain allocator drops the hash at
        release, so the transfer commits but yields no cache entry). A hash
        the destination already holds is skipped — its duplicate block
        returns to the free list. Returns {hash: block} for the published
        entries."""
        sess = self._handoff_sessions.pop(sid)
        if self.ledger is not None:
            self.ledger.handoff_committed(sess["blocks"])
        published: Dict[bytes, int] = {}
        with self._handoff_ctx(sess, "handoff_commit"):
            for blk, h in zip(sess["blocks"], sess["hashes"]):
                if h not in self.allocator.hash_to_block:
                    self.allocator.hash_to_block[h] = blk
                    self.allocator.block_to_hash[blk] = h
                    published[h] = blk
                self.allocator._release_one(blk)
        return published

    def handoff_abort(self, sid: int) -> int:
        """Tear a session down (source replica death, admission fallback):
        staged blocks return to the free list UNHASHED — nothing
        half-transferred can ever serve as a prefix-cache entry. Idempotent
        on unknown session ids; returns the block count released."""
        sess = self._handoff_sessions.pop(sid, None)
        if sess is None:
            return 0
        if self.ledger is not None:
            self.ledger.handoff_aborted(sess["blocks"])
        with self._handoff_ctx(sess, "handoff_abort"):
            for blk in sess["blocks"]:
                self.allocator._release_one(blk)
        return len(sess["blocks"])

    # ------------------------------------------------ telemetry (utils/metrics)
    # The runner's historical ad-hoc counters live on the metrics registry
    # now; these thin properties keep the old attribute surface working
    # (bench.py's measurement windows, tests poking _round_trip_s, ...).
    @property
    def num_preemptions(self) -> int:
        return self._m_preempt.value

    @num_preemptions.setter
    def num_preemptions(self, v: int) -> None:
        self._m_preempt.value = int(v)

    @property
    def spec_iters_run(self) -> int:
        return self._m_spec_iters.value

    @spec_iters_run.setter
    def spec_iters_run(self, v: int) -> None:
        self._m_spec_iters.value = int(v)

    @property
    def acceptance_counts(self) -> np.ndarray:
        """Live length-K view of the acceptance histogram's counts (bucket
        i = iterations that committed i+1 tokens). Spec serving only."""
        return self._m_accept.counts[: self.k]

    @property
    def _round_trip_s(self) -> Optional[float]:
        g = self._m_round_trip
        return g.value if g.updated else None

    @_round_trip_s.setter
    def _round_trip_s(self, v: Optional[float]) -> None:
        if v is None:
            self._m_round_trip.value, self._m_round_trip.updated = 0.0, False
        else:
            self._m_round_trip.set(v)

    # ------------------------------------------ device-resident telemetry carry
    def _dispatch_carry(self, alive_h, budget_h):
        """(tok, pos, alive, budget) operands for the next decode dispatch:
        the device-resident carry of the newest in-flight dispatch when one
        exists (authoritative — stops tracked in-graph), else the host
        state. THE one definition both the scan-chunk and megastep paths
        seed from, so the carry-vs-host precedence cannot desynchronize."""
        if self._dev_state is not None:
            return self._dev_state
        return (jnp.asarray(self.last_tok), jnp.asarray(self.positions),
                jnp.asarray(alive_h), jnp.asarray(budget_h))

    def _carry_replay_state(self):
        """Per-row (alive, budget, eos_id) counting state for the telemetry
        carry's in-graph replay of the host commit rules — THE one
        definition all step kinds share (plain/mixed/spec), so the replay
        rule cannot desynchronize between sites. Must be built AFTER any
        block-growth preemption: a preempted victim's tokens were always
        host-discarded, so the counting roster has to see the
        post-preemption state."""
        alive = np.array([r is not None and not r.done and not r.inserting
                          for r in self.active])
        budget = np.array([(r.max_new_tokens - len(r.generated))
                           if (r is not None and not r.done
                               and not r.inserting)
                           else 0 for r in self.active], dtype=np.int32)
        eos_ids = np.array(
            [(-1 if r is None or r.eos_token_id is None else r.eos_token_id)
             for r in self.active], dtype=np.int32)
        return alive, budget, eos_ids

    def _drain_device_telemetry(self) -> None:
        """Fetch the cumulative in-graph counter block and fold it into the
        telemetry (latest snapshot + the flight-recorder ring's newest step
        record). Zero new host syncs by construction: only runs when the
        dispatch pipeline is EMPTY, i.e. the newest dispatch's tokens were
        already synced this step — in async steady state the fetch is skipped
        and the drained counters lag by up to ``async_depth`` chunks (they
        catch up exactly at the next pipeline flush)."""
        # identity dirty-check: every dispatch returns a NEW carry array, so
        # `is` on the last-drained object skips the fetch (and a duplicate
        # JSONL device_counters line) when nothing was dispatched since —
        # e.g. a stats() call right after the step epilogue already drained
        if (not self.telemetry.enabled or self._inflight
                or self._telem_dev is self._telem_drained):
            return
        self.telemetry.note_device_counters(
            dtel.to_dict(np.asarray(self._telem_dev)))
        self._telem_drained = self._telem_dev

    def reset_device_telemetry(self) -> None:
        """Zero the device counter block (bench measurement windows). Only
        legal with an empty dispatch pipeline — the carry of an in-flight
        chunk cannot be replaced without corrupting the chain."""
        if self._inflight:
            raise RuntimeError("cannot reset the device telemetry carry with "
                               "chunks in flight — drain the pipeline first")
        fresh = dtel.init_carry()
        if hasattr(self._telem_dev, "sharding"):
            # preserve the live carry's placement: a default-placed zeros
            # block silently RECOMPILES every warm step executable on a
            # multi-device mesh (the donated carry's sharding is part of the
            # jit cache key) — measured 287 ms on the 8-device CPU mesh,
            # paid by the first step of every bench measurement window
            fresh = jax.device_put(fresh, self._telem_dev.sharding)
        self._telem_dev = fresh
        self._telem_drained = self._telem_dev
        self.telemetry.note_device_counters(
            dtel.to_dict(np.zeros((dtel.CARRY_LEN,), np.int32)))

    # telemetry step kind -> jit-program name substrings of the dispatches
    # that serve it (the profiler's device-time attribution key; the jitted
    # fn `_decode` lowers as `jit__decode`). The insert FAMILY shares
    # substrings (`_insert` also matches `_insert_nol`/`_insert_pair`/
    # `_insert_eagle`), so attribution MERGES the `insert`/`insert_window`
    # step kinds into one `insert` row — per-kind rows would double-count
    # the shared device events and publish a meaningless (often negative)
    # gap whenever both kinds occur in one profiled window.
    DISPATCH_KIND_EVENTS = {
        "decode": ("_decode",),
        "spec_chunk": ("_spec_chunk", "_eagle_chunk"),
        "mixed": ("_mixed",),
        "insert": ("_insert", "_window", "_seed"),
        "tier_readmit": ("_tier_readmit",),
        "kv_handoff": ("_kv_handoff",),
        "megastep": ("_megastep",),
    }

    @staticmethod
    def _attr_family(kind: str) -> str:
        return "insert" if kind in ("insert", "insert_window") else kind

    def attribute_device_time(self, logdir: str, plane_substr: str = "tpu",
                              since_ts: Optional[float] = None
                              ) -> Dict[str, dict]:
        """Per-dispatch-kind device-time attribution from a jax.profiler trace
        captured over a serving window (scripts/profile_serving.py drives
        this; utils/profiling.device_time_by_substr parses the xplane dump).

        For every step kind the telemetry observed, reports total on-device
        time, total host span (the step timeline's dur_s), dispatch count,
        and the host-device GAP — the dispatch-floor decomposition ROADMAP
        open item 2 targets. Lands in the metrics registry
        (``serving_device_time_ms{kind=}`` / ``serving_dispatch_gap_ms{kind=}``)
        and in ``stats()["timing"]``. Device totals are None when the trace
        carries no matching events (e.g. an unlabelled backend).

        PRECONDITION: host spans come from the telemetry step timeline, so
        the timeline must cover the SAME window as the trace — either call
        ``telemetry.reset()`` immediately before tracing (what
        scripts/profile_serving.py and bench.py do) or pass ``since_ts``
        (telemetry-epoch seconds: the newest ``steps[-1]["ts"]`` before the
        trace started) to window the host side; otherwise host_ms covers the
        whole session while device_ms covers only the trace, and the gap
        inflates silently."""
        from ..utils import profiling

        steps = [s for s in self.telemetry.steps
                 if since_ts is None or s["ts"] >= since_ts]
        kinds = sorted({self._attr_family(s["kind"]) for s in steps})
        dev = profiling.device_time_by_substr(
            logdir, {k: self.DISPATCH_KIND_EVENTS.get(k, (k,))
                     for k in kinds}, plane_substr=plane_substr)
        host_ms: Dict[str, float] = {}
        n_disp: Dict[str, int] = {}
        for s in steps:
            k = self._attr_family(s["kind"])
            host_ms[k] = host_ms.get(k, 0.0) + s["dur_s"] * 1e3
            n_disp[k] = n_disp.get(k, 0) + 1
        reg = self.telemetry.registry
        timing: Dict[str, dict] = {}
        for kind in kinds:
            d_ms = dev.get(kind)
            h_ms = host_ms.get(kind, 0.0)
            n = max(1, n_disp.get(kind, 0))
            gap = None if d_ms is None else h_ms - d_ms
            timing[kind] = {
                "dispatches": n_disp.get(kind, 0),
                "device_ms": None if d_ms is None else round(d_ms, 3),
                "host_ms": round(h_ms, 3),
                "device_ms_per_dispatch": (None if d_ms is None
                                           else round(d_ms / n, 3)),
                "dispatch_gap_ms": (None if gap is None
                                    else round(gap / n, 3)),
            }
            if d_ms is not None:
                reg.gauge("serving_device_time_ms",
                          "on-device ms attributed to this dispatch kind "
                          "over the profiled window",
                          labels={"kind": kind}).set(d_ms)
                reg.gauge("serving_dispatch_gap_ms",
                          "host-span minus device-time per dispatch "
                          "(the dispatch floor's host share)",
                          labels={"kind": kind}).set(gap / n)
        self.telemetry.set_device_timing(timing)
        # measured-vs-model join (ISSUE-14): per-kind roofline efficiency
        # from the analytical model over the same window. Guarded — a model
        # failure (unlowerable example, missing cost key) degrades to an
        # error entry in stats()["roofline"], never breaks the attribution.
        iters_by_kind: Dict[str, int] = {}
        for s in steps:
            k = self._attr_family(s["kind"])
            iters_by_kind[k] = (iters_by_kind.get(k, 0)
                                + max(1, int(s.get("iterations") or 1)))
        self.telemetry.set_roofline(
            self._roofline_join(timing, iters_by_kind))
        return timing

    def _roofline_dispatch(self, kind: str):
        """This runner's own AuditedDispatch serving a telemetry step kind
        (None when the kind has no single owning dispatch here). Using the
        runner's objects — not the global registry — keeps the join honest
        when several runners of different geometry are alive at once."""
        if kind == "spec_chunk":
            return (getattr(self, "_spec_step_eagle", None)
                    if self.eagle is not None
                    else getattr(self, "_spec_step", None))
        # the merged "insert" timing row aggregates device events from the
        # whole insert FAMILY (_insert/_insert_nol/_window/_seed — see
        # DISPATCH_KIND_EVENTS), so no single dispatch's expectation can
        # honestly divide its measured time: the family is EXCLUDED from
        # the join rather than modeled wrong (a deflated efficiency would
        # emit spurious roofline_below_bound warnings for healthy runners)
        return {
            "decode": getattr(self, "_decode_step", None),
            "mixed": getattr(self, "_mixed_step", None),
            "megastep": getattr(self, "_megastep_step", None),
            "tier_readmit": getattr(self, "_tier_readmit_step", None),
            "kv_handoff": getattr(self, "_kv_handoff_step", None),
        }.get(kind)

    def _roofline_join(self, timing: Dict[str, dict],
                       iters_by_kind: Dict[str, int]) -> Dict[str, object]:
        """Join the profiled timing table with the analytical roofline model
        (analysis/perf_model.py): ``serving_roofline_efficiency{kind=}``
        gauges, the stats()["roofline"] block, the provenance build_info
        stamp, and ONE structured ``roofline_below_bound {json}`` log line
        per kind running far below its bound."""
        import json as _json

        try:
            from ..analysis import perf_model
            from ..utils import provenance

            if self._perf_model is None:
                self._perf_model = perf_model.PerfModel()
            provenance.stamp_registry(self.telemetry.registry)
            dispatches = {k: self._roofline_dispatch(k) for k in timing}
            roof = self._perf_model.join(
                timing, iters_by_kind,
                {k: d for k, d in dispatches.items() if d is not None})
            reg = self.telemetry.registry
            for kind, entry in roof["by_kind"].items():
                eff = entry.get("efficiency")
                if eff is None:
                    continue
                reg.gauge(
                    "serving_roofline_efficiency",
                    "measured-vs-roofline-model efficiency over the last "
                    "profiled window (1.0 = at the bound)",
                    labels={"kind": kind}).set(eff)
                if eff < perf_model.LOW_EFFICIENCY:
                    logger.warning("roofline_below_bound %s", _json.dumps({
                        "kind": kind, "bound": entry.get("bound"),
                        "efficiency": eff,
                        "expected_window_ms": entry.get("expected_window_ms"),
                        "measured_window_ms": entry.get("measured_window_ms"),
                        "bytes_per_step": entry.get("bytes_per_step"),
                    }))
            return roof
        except Exception as e:
            # visible degradation: the error lands in stats()["roofline"]
            # AND the log — the attribution result must survive regardless
            logger.warning("roofline join failed: %s: %s",
                           type(e).__name__, e)
            return {"error": f"{type(e).__name__}: {e}"}

    def stats(self) -> Dict[str, object]:
        """Point-in-time serving snapshot: telemetry aggregates (TTFT/TPOT/
        queue-wait percentiles, per-kind step counts, drained device counters,
        profiled per-kind timing — populated only when telemetry is enabled)
        plus the always-on runner state (queue depth, occupancy, KV blocks,
        preemptions, spec acceptance)."""
        from ..utils import metrics as metrics_lib

        # refresh the drained device counters when it costs nothing (pipeline
        # empty — the sync already happened); in async steady state the last
        # drained snapshot is reported as-is (it lags by design)
        self._drain_device_telemetry()
        s = self.telemetry.snapshot()
        s["num_slots"] = self.num_slots
        s["queue_depth"] = len(self.queue)
        s["active_requests"] = sum(r is not None for r in self.active)
        s["num_preemptions"] = self.num_preemptions
        s["async"] = {
            "mode": bool(self.async_mode),
            "depth": self.async_depth,
            "in_flight": len(self._inflight),
        }
        # live knob table (serving/knobs.py): every tunable's current value
        # + bounds — the tuner's enumeration surface and the audit trail's
        # ground truth ("what was the fleet actually running?")
        s["knobs"] = self.knobs.snapshot()
        if self.paged:
            s["kv_blocks_total"] = self.allocator.num_blocks
            s["kv_blocks_free"] = self.allocator.num_free
        if self.kv_tier is not None:
            # idle blocks count in kv_blocks_free (they are allocatable
            # headroom — the router's admission signal); the strict free-list
            # count and the host-store state ride alongside
            s["kv_blocks_free_device"] = self.allocator.num_free_device
            s["kv_tier"] = self.kv_tier.stats()
        if self.ledger is not None:
            # byte attribution + conservation view (serving/memledger.py):
            # owner-state counts, top holders by request/class, idle ages,
            # fragmentation, the last OOM snapshot, and an on-demand audit.
            # GUARDED: a ledger failure degrades to an error entry — the
            # rest of the snapshot (and any bundle embedding it) survives.
            try:
                mem = self.ledger.snapshot()
                mem["fragmentation_ratio"] = self._kv_fragmentation()
                aud = self.audit_ledger()
                mem["audit"] = {"ok": aud["ok"],
                                "violations": len(aud["violations"]),
                                "leaked_blocks": aud["leaked_blocks"]}
                if self.ledger.last_oom is not None:
                    mem["last_oom"] = self.ledger.last_oom
                self.ledger.export_gauges(
                    fragmentation=mem["fragmentation_ratio"])
                s["memory"] = mem
            except Exception as e:
                logger.warning("memledger stats failed: %s: %s",
                               type(e).__name__, e)
                s["memory"] = {"error": f"{type(e).__name__}: {e}"}
        if self.megastep_k is not None:
            # committed megastep accounting (host mirror of the device
            # carry's megastep fields — equal at every pipeline flush):
            # per-exit-reason dispatch counts + total inner steps, the
            # honesty surface the bench's bs=1 phase reads before publishing
            # a megastep number. All three read the registry counters, so a
            # telemetry.reset() between bench windows scopes them together.
            exits = {r: int(c.value)
                     for r, c in sorted(self._megastep_exit_counters.items())
                     if c.value}
            s["megastep"] = {
                "k": self.megastep_k,
                "ring": self.megastep_ring,
                "dispatches": sum(exits.values()),
                "inner_steps": self._m_megastep_iters.value,
                "exits": exits,
            }
        if self.k:
            s["spec"] = {
                "iterations": self.spec_iters_run,
                "acceptance_counts": self.acceptance_counts.tolist(),
                "accept_mean": metrics_lib.acceptance_mean(
                    self.acceptance_counts),
                # the adaptive floor guard's CURRENT state: when
                # fallback_active, spec throughput reads as ~plain-paged
                # throughput BY DESIGN (chance-level acceptance detected)
                "adaptive": {
                    "enabled": self.spec_adaptive,
                    "fallback_active": self._spec_off,
                    "plain_chunks_since_probe": self._spec_plain_chunks,
                    "min_accept": self.spec_min_accept,
                    "probe_every": self.spec_probe_every,
                },
            }
        return s

    # ------------------------------------------------------------------ API
    def submit(self, prompt: np.ndarray, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None,
               sampling_params=None, adapter_id: int = 0,
               arrival_ts: Optional[float] = None,
               resume_tokens: Optional[Sequence[int]] = None,
               trace_id: Optional[str] = None,
               sla_class: Optional[str] = None) -> int:
        """``sampling_params``: per-request (3,) [top_k, top_p, temperature]
        (≈ reference per-request sampling, `generation/sampling.py:99-209`);
        ``adapter_id``: multi-LoRA slot, 0 = base (≈ CB forward adapter_ids,
        `models/model_wrapper.py:252-311`); ``arrival_ts``: optional
        ``time.perf_counter()`` timestamp of the request's true upstream
        arrival for telemetry TTFT/queue-wait (defaults to now — open-loop
        drivers backdate it so wait spent inside a blocking step() counts);
        ``resume_tokens``: tokens this request ALREADY generated elsewhere
        (cross-replica migration, serving/router.py) — the request enters the
        same resume path a preempted request takes (KV recomputed from
        prompt + resume_tokens at placement; none of them re-emitted), so a
        migrated stream continues exactly where the source replica stopped;
        ``trace_id``: request-scoped trace context (serving/tracing.py) —
        the router threads its frontend-minted id here so this runner's
        lifecycle events stay joinable with the other replicas' into one
        causal span tree (default: the telemetry mints a local one);
        ``sla_class``: the tenant tier (serving/sla.py) — requires the
        runner to have been built with ``sla_classes=``; unlabelled submits
        map to the set's default class."""
        prompt = np.asarray(prompt).astype(np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if sampling_params is not None:
            sampling_params = np.asarray(sampling_params,
                                         dtype=np.float32).reshape(-1)
            if sampling_params.shape != (3,):
                raise ValueError("sampling_params must be (top_k, top_p, "
                                 "temperature)")
            if self.eagle is not None and sampling_params[0] != 1:
                raise ValueError("EAGLE serving is greedy-only")
            if not (self.sampling_config.dynamic
                    or self.sampling_config.do_sample):
                raise ValueError(
                    "per-request sampling_params require a sampling config "
                    "with dynamic=True or do_sample=True (otherwise the "
                    "on-device sampler is a plain argmax and the params "
                    "would be silently ignored)")
        if self.eagle is not None and adapter_id != 0:
            raise ValueError("eagle_draft serving does not route per-request "
                             "adapters yet")
        if adapter_id != 0:
            if not self._lora_on:
                raise ValueError("adapter_id given but the model has no "
                                 "lora_serving_config")
            n_slots = self.app.arch_args.lora.num_slots
            if not (0 <= adapter_id < n_slots):
                raise ValueError(f"adapter_id must be in [0, {n_slots})")
        if prompt.size + max_new_tokens > self.cfg.seq_len:
            raise ValueError(f"prompt ({prompt.size}) + max_new_tokens "
                             f"({max_new_tokens}) exceeds seq_len {self.cfg.seq_len}")
        if not self.paged and prompt.size > self.app.cte_buckets[-1]:
            if self.draft is not None:
                raise ValueError(
                    f"prompt ({prompt.size}) exceeds the largest context bucket "
                    f"({self.app.cte_buckets[-1]}); speculative CB supports "
                    f"windowed (chunked) prefill only in paged mode")
            if (self.app.decode_fn() is not model_base.decode_forward
                    or self.app.arch_args.layer_pattern is not None):
                raise ValueError(
                    f"prompt ({prompt.size}) exceeds the largest context bucket "
                    f"({self.app.cte_buckets[-1]}) and this family has no dense "
                    f"windowed prefill")
            # dense windowed prefill rounds the prompt up to full windows; those
            # cache slots must exist
            w = self.app.cte_buckets[-1]
            total = -(-prompt.size // w) * w
            if total > self.cfg.seq_len:
                raise ValueError(
                    f"windowed prefill needs {total} cache slots (prompt rounded up "
                    f"to {w}-wide windows) but seq_len is {self.cfg.seq_len}")
        if resume_tokens is not None and len(resume_tokens) >= max_new_tokens:
            raise ValueError("resume_tokens already meets max_new_tokens — "
                             "the migrated request is finished, not served")
        if self.sla is not None:
            sla_class = self.sla.resolve(sla_class)    # unknown class raises
        elif sla_class is not None:
            raise ValueError("sla_class given but the runner has no "
                             "sla_classes set (pass sla_classes= at "
                             "construction)")
        req = Request(self._next_id, prompt, max_new_tokens, eos_token_id,
                      sampling_params=sampling_params, adapter_id=adapter_id,
                      sla_class=sla_class)
        if resume_tokens:
            # cross-replica migration: enters the preemption-resume path at
            # placement (prompt + resume_tokens[:-1] refed, last token is the
            # next decode input; nothing re-emitted)
            req.generated = [int(t) for t in resume_tokens]
        self._next_id += 1
        self.queue.append(req)
        self.telemetry.request_arrival(req.request_id, int(prompt.size),
                                       max_new_tokens, ts=arrival_ts,
                                       trace_id=trace_id,
                                       sla_class=sla_class)
        return req.request_id

    def _row_greedy(self, req: Request) -> bool:
        """Does this request's sampling reduce to exact argmax? (top_k == 1
        rows take the argmax branch inside ops/sampling.sample regardless of
        temperature/top_p/noise.)"""
        if req.sampling_params is None:
            return self._greedy
        return float(req.sampling_params[0]) == 1.0

    def _chunk_greedy(self, rows: List[Request]) -> bool:
        """All-greedy chunks compile without the dynamic sampling window
        (measured 6.3 ms/step at bs=64 over a 128k vocab); any sampled row
        falls the whole chunk back to the per-request (B, 3) sampler."""
        return all(self._row_greedy(r) for r in rows)

    @property
    def has_work(self) -> bool:
        return bool(self.queue) or any(r is not None for r in self.active)

    def _pend_steps(self) -> int:
        """Upper bound on decode steps currently in flight (dispatch-ahead
        pipeline). Scan entries advance exactly their step count; megastep
        entries advance AT MOST their dispatched inner-step bound (early
        exits advance less — the device carry is exact, this host estimate
        only feeds the conservative seq-room / block-growth clamps)."""
        return sum(e[4] if e[0] == "mega" else e[2] for e in self._inflight)

    def _async_ok(self, extra_steps: int) -> bool:
        """True when dispatch-ahead is exact for the next chunk(s): no queued
        placements, no mid-insert rows, seq-room for the optimistic uniform
        advance, and (paged) enough free blocks that growth cannot preempt
        while chunks are in flight. Rows that may STOP (eos / max-new) no
        longer veto the pipeline: the decode chunk freezes stopped rows ON
        DEVICE (the same rules the host replays at commit), so the pipeline
        stays exact however deep it runs."""
        if not self.async_mode or self.queue:
            return False
        if any(r is not None and r.inserting for r in self.active):
            return False     # mid-insert rows activate at unpredictable steps
        rows = [r for r in self.active if r is not None and not r.done]
        if not rows:
            return False
        # bound by ACTIVE rows only: finished slots keep their frozen position
        # (possibly seq_len-1), which must not cap live rows. The host
        # estimate is an upper bound (device-frozen rows stop advancing), so
        # the seq-room check stays conservative.
        if max(r.position for r in rows) + extra_steps >= self.cfg.seq_len - 1:
            return False
        if self.paged:
            worst = len(rows) * (-(-extra_steps // self.block_size) + 1)
            if self.allocator.num_free < worst:
                return False
        return True

    def _drain(self, emitted: Dict[int, List[int]]) -> None:
        """Sync + commit every in-flight dispatch, oldest first (no-op when
        the pipeline is empty)."""
        while self._inflight:
            self._commit_entry(self._inflight.pop(0), emitted)
        self._dev_state = None
        self._m_inflight.set(0)

    def _commit_entry(self, entry, emitted: Dict[int, List[int]]):
        """Sync + commit one in-flight dispatch result.

        Scan entries ``("scan", toks_dev, steps)`` carry a host-known step
        count; megastep entries ``("mega", ring_dev, n_dev, exit_dev, n_max)``
        sync the device's executed-iteration count, the exit code, and the
        token ring in the megastep's ONE host sync, then replay the exact
        same per-token commit rules over the drained ``ring[:n]`` prefix.
        Returns ``(steps_committed, exit_reason-or-None)``."""
        if entry[0] == "mega":
            _, ring_dev, n_dev, exit_dev, _n_max = entry
            n = int(np.asarray(n_dev))
            code = int(np.asarray(exit_dev))
            if n:
                self._commit(token_ring.drain(ring_dev, n), n, emitted)
            reason = MEGASTEP_EXITS.get(code, str(code))
            self._m_megastep_iters.inc(n)
            self._count_megastep_exit(reason)
            return n, reason
        _, toks_dev, steps = entry
        self._commit(np.asarray(toks_dev), steps, emitted)
        return steps, None

    def _count_megastep_exit(self, reason: str) -> None:
        """serving_megastep_exits_total{reason=}: in-graph early-exit/
        completion reasons, shared by the plain/spec/mixed megastep paths."""
        c = self._megastep_exit_counters.get(reason)
        if c is None:
            c = self.telemetry.registry.counter(
                "serving_megastep_exits_total",
                "megastep in-graph early-exit/completion reasons",
                labels={"reason": reason})
            self._megastep_exit_counters[reason] = c
        c.inc()

    def _commit(self, toks: np.ndarray, steps: int,
                emitted: Dict[int, List[int]]) -> None:
        """Fold one synced chunk's tokens (slots, steps) into request state."""
        for slot, req in enumerate(self.active):
            if req is None or req.done or req.inserting:
                continue
            for j in range(steps):
                t = int(toks[slot, j])
                req.generated.append(t)
                req.position += 1
                emitted.setdefault(req.request_id, []).append(t)
                if ((req.eos_token_id is not None and t == req.eos_token_id)
                        or len(req.generated) >= req.max_new_tokens):
                    break
            self.positions[slot] = req.position
            self.last_tok[slot] = req.generated[-1]
            self._maybe_finish(req, emitted)

    def _place_queued(self, key, emitted: Dict[int, List[int]]):
        """Place queued requests into free slots (≈ CTE dispatch for new
        seq_ids); returns the advanced PRNG key."""
        for slot in range(self.num_slots):
            if not self.queue or self.active[slot] is not None:
                continue
            req = self.queue[0]
            fed_len = len(req.prompt) + max(0, len(req.generated) - 1)
            if self.paged:
                # require room for the prompt plus one decode chunk, else a fresh
                # insert can be preempted before generating a single token (thrash)
                chunk_tokens = (self.spec_chunk * self.k if self.k
                                else self.decode_chunk)
                need = -(-(fed_len + 1 + chunk_tokens) // self.block_size)
                if self.allocator.num_free < need:
                    break
            self.queue.pop(0)
            # per-slot sampling/adapter rows must be live BEFORE the insert
            # samples the request's first token
            self._slot_sp[slot] = (req.sampling_params
                                   if req.sampling_params is not None
                                   else self._default_sp_row)
            self.adapter_ids[slot] = req.adapter_id
            req.slot = slot
            self._place_counter += 1
            req.placed_seq = self._place_counter
            self.active[slot] = req
            self.telemetry.request_placed(req.request_id, slot,
                                          resumed=bool(req.generated))
            try:
                if self.insert_cap is not None or self.mixed:
                    # chunked-prefill scheduling: the slot is held, the
                    # prompt streams in bounded windows via _advance_inserts
                    # (insert_cap) or as chunk rows of the mixed dispatches
                    # (_step_mixed)
                    self._begin_insert(req, slot)
                    continue
                key, sub = jax.random.split(key)
                resumed = bool(req.generated)   # preempted; KV recomputed now
                tok0 = self._insert(req, slot, sub)
            # lint: ok(silent-except): _unplace_on_exhaustion logs and counts serving_fallthrough_total{from=place}
            except block_kvcache.KVBlocksExhausted:
                # PREEMPT-OR-SHED, not a crash (ISSUE-11): the free-count
                # precheck above can still lose to allocation (a tiered
                # reclaim spilling mid-walk, an injected failure, prefix
                # blocks growing under a shared pool). The request un-places
                # back to the queue front and the NEWEST insert preempts to
                # the resume path to open headroom; placement resumes next
                # step (the router's shed path handles sustained pressure).
                self._unplace_on_exhaustion(req, slot)
                break
            req.position = fed_len
            if not resumed:
                req.generated = [tok0]
                emitted.setdefault(req.request_id, []).append(tok0)
            self.positions[slot] = req.position
            self.last_tok[slot] = req.generated[-1]
            self._maybe_finish(req, emitted)
        return key

    def _advance_inserts(self, key, emitted: Dict[int, List[int]]):
        """Chunked-prefill scheduling: spend at most ``insert_cap`` prompt
        tokens across the in-progress inserts, activating each request for
        decode once its final window lands. Returns the advanced PRNG key."""
        budget = self.insert_cap
        for slot, req in enumerate(self.active):
            if req is None or not req.inserting or budget <= 0:
                continue
            key, used = self._insert_windows(req, slot, key, budget=budget)
            budget -= used
            if req.insert_pos >= len(req.fed):
                req.inserting = False
                resumed = bool(req.generated)
                req.position = len(req.fed)
                tok0 = int(np.asarray(req.tok0_dev)[0])
                req.tok0_dev = None
                if not resumed:
                    req.generated = [tok0]
                    emitted.setdefault(req.request_id, []).append(tok0)
                self.positions[slot] = req.position
                self.last_tok[slot] = req.generated[-1]
                self._maybe_finish(req, emitted)
        return key

    def step(self, key: Optional[jax.Array] = None) -> Dict[int, List[int]]:
        """Place queued requests into free slots, then run one decode chunk.

        Returns {request_id: newly generated tokens} for this step (in
        async steady state the tokens lag one chunk behind the dispatches).
        """
        if key is None:
            self._key, key = jax.random.split(self._key)
        emitted: Dict[int, List[int]] = {}

        # queued live knob changes (serving/knobs.py) land FIRST, on a
        # drained pipeline — the same exact sync path every steady-state
        # exit uses, so the change is schedule-only by construction
        if self._pending_knobs:
            self._drain(emitted)
            self._apply_pending_knobs()

        # leaving steady state (placements pending, a row near the seq bound,
        # block headroom gone, or async off) drains the pipeline first so the
        # sync path sees exact state
        look_ahead = (self.megastep_k if self.megastep_k is not None
                      else self.decode_chunk)
        if self._inflight and (
                self.queue or not self._async_ok(
                    self._pend_steps() + 2 * look_ahead)):
            self._drain(emitted)

        key = self._place_queued(key, emitted)
        if self.insert_cap is not None:
            key = self._advance_inserts(key, emitted)
        if self.k:
            emitted = self._step_spec(key, emitted)
        elif self.mixed:
            emitted = self._step_mixed(key, emitted)
        else:
            emitted = self._step_plain(key, emitted)
        # all requests finished with chunks still in flight: the trailing
        # dispatch-ahead chunks hold only device-frozen rows (the in-graph
        # stop rules), so committing them adds nothing — flush the pipeline
        # so the runner (and the telemetry carry drain below) ends clean
        # instead of parking a dead chunk forever
        if self._inflight and not self.has_work:
            self._drain(emitted)
        # telemetry epilogue (single attribute test when disabled): fold this
        # step's emissions into the per-request records (first-token / commit
        # events), refresh the queue gauge, and drain the device counter
        # carry when the pipeline is empty (zero new syncs — the newest
        # dispatch was already synced on that path)
        if self.telemetry.enabled:
            self.telemetry.note_emitted(emitted)
            self.telemetry.set_queue_depth(len(self.queue))
            self._drain_device_telemetry()
        return emitted

    @step_loop_body
    def _step_plain(self, key, emitted: Dict[int, List[int]]
                    ) -> Dict[int, List[int]]:
        """One plain (non-speculative) decode chunk for every slot. Also the
        exact near-boundary fallback for spec mode (see _step_spec). With
        ``megastep_k`` the plain dispatch is the device-resident while_loop
        megastep instead of the host-stepped scan chunk — every caller
        (step(), the spec fall-through, the mixed fall-through) inherits it
        through this one interception point."""
        if self.megastep_k is not None:
            return self._step_device_loop(key, emitted)
        tel = self.telemetry
        t_step = tel.step_start()
        n_emit0 = _emitted_count(emitted) if t_step is not None else 0
        active_rows = [r for r in self.active if r is not None]
        if not active_rows:
            self._drain(emitted)
            return emitted

        # --- one decode chunk for every slot ------------------------------------
        # while chunks are in flight, the dispatch state is the DEVICE carry of
        # the newest chunk (token / position / alive / budget per row — stops
        # are tracked in-graph, so the carry is exact even when rows stop
        # mid-pipeline); the host's uniform-advance estimate is only used for
        # the conservative seq-room clamp and the slot precompute
        chunk = self.decode_chunk
        pend_steps = self._pend_steps()
        positions = self.positions + pend_steps
        # room is bounded by the LIVE rows; finished slots keep a frozen
        # position (possibly seq_len-1) that must not truncate active requests;
        # mid-insert rows don't decode yet
        live = [r for r in active_rows if not r.done and not r.inserting]
        if not live:
            self._drain(emitted)
            return emitted
        max_pos = max(r.position for r in live) + pend_steps
        steps = min(chunk, self.cfg.seq_len - 1 - max_pos)
        if steps <= 0:
            # longest row is out of seq_len room; force-finish (truncate) it
            self._drain(emitted)
            victim = max(active_rows, key=lambda r: r.position)
            victim.truncated = True
            self._finish(victim)
            return emitted
        key, sub = jax.random.split(key)
        sp = self._sampling_matrix()
        greedy = self._chunk_greedy(live)
        adapters = jnp.asarray(self.adapter_ids)
        t_dispatch = time.perf_counter() if self._async_auto else None
        if self.paged:
            # grow (and possibly PREEMPT) before building the dispatch state:
            # a preempted victim must not be counted alive by the device
            # telemetry carry (its tokens were always host-discarded; the
            # counting replay has to see the post-preemption roster too)
            active_rows = self._grow_blocks(active_rows, pend_steps + steps)
            if not active_rows:
                self._drain(emitted)
                return emitted
        alive_h, budget_h, eos_h = self._carry_replay_state()
        tok0, pos_dev, alive_dev, budget_dev = self._dispatch_carry(
            alive_h, budget_h)
        eos_ids = jnp.asarray(eos_h)
        if self.paged:
            slot_chunk = self._slot_mapping_fn(
                self.block_table, positions, steps, self.block_size,
                valid=alive_h)
            with tel.annotate("decode"):
                toks_dev, dev_state, self.cache, self._telem_dev = \
                    self._decode_step(
                        self.app.params, tok0, pos_dev, alive_dev, budget_dev,
                        self.cache, self._telem_dev,
                        jnp.asarray(self.block_table), jnp.asarray(slot_chunk),
                        sp, sub, adapters, eos_ids, num_steps=steps,
                        greedy=greedy)
        else:
            bucket = autobucketing.select_bucket(self.app.tkg_buckets,
                                                 max_pos + steps)
            with tel.annotate("decode"):
                toks_dev, dev_state, self.cache, self._telem_dev = \
                    self._decode_step(
                        self.app.params, tok0, pos_dev, alive_dev, budget_dev,
                        self.cache, self._telem_dev, sp, sub, adapters,
                        eos_ids, decode_bucket=bucket, num_steps=steps,
                        greedy=greedy)

        if self._async_ok(pend_steps + steps + chunk):
            # steady state: append the new chunk, keep at most async_depth in
            # flight — committing the oldest overlaps the newer dispatches
            self._inflight.append(("scan", toks_dev, steps))
            self._dev_state = dev_state
            while len(self._inflight) > self.async_depth:
                # committing the OLDEST in-flight chunk is the one designed
                # host sync of dispatch-ahead
                # lint: ok(step-loop-sync): oldest-chunk commit, the designed sync
                self._commit_entry(self._inflight.pop(0), emitted)
            self._m_inflight.set(len(self._inflight))
        else:
            self._drain(emitted)                       # older chunks commit first
            self._commit(np.asarray(toks_dev), steps, emitted)
            if t_dispatch is not None:
                self._note_chunk_time(time.perf_counter() - t_dispatch, steps)
        if t_step is not None:
            tel.step_record(
                t_step, "decode", iterations=steps,
                tokens=_emitted_count(emitted) - n_emit0,
                occupancy=len(live), slots=self.num_slots,
                in_flight=len(self._inflight),
                kv_free=self.allocator.num_free if self.paged else None,
                kv_total=self.allocator.num_blocks if self.paged else None,
                ici_bytes=self._ici_bytes(steps),
                extra=self._consume_fall_through())
        return emitted

    @step_loop_body
    def _step_device_loop(self, key, emitted: Dict[int, List[int]]
                          ) -> Dict[int, List[int]]:
        """One device-resident serving MEGASTEP (ISSUE-10 / ROADMAP open item
        2): dispatch ONE jitted lax.while_loop of up to ``megastep_k`` decode
        inner steps, then sync once and replay the host commit rules over the
        drained emitted-token ring. The scheduler state the step-wise path
        keeps authoritative on the host — alive/budget/eos stops, positions,
        the slot-mapping advance — lives on device for the whole loop; the
        host contributes only the conservative pre-dispatch clamps (seq room,
        best-effort block reservation) and the pending-arrival service flag.
        Exactness: the in-graph freeze rules are the scan chunk's, the ring
        replay is ``_commit``'s, and early exits only regroup dispatches —
        the emitted stream is bit-identical to the step-wise path."""
        tel = self.telemetry
        t_step = tel.step_start()
        n_emit0 = _emitted_count(emitted) if t_step is not None else 0
        active_rows = [r for r in self.active if r is not None]
        live = [r for r in active_rows if not r.done and not r.inserting]
        if not live:
            self._drain(emitted)
            return emitted
        pend = self._pend_steps()
        max_pos = max(r.position for r in live) + pend
        # seq-room clamp rides as a DYNAMIC operand (n_iters): unlike the
        # scan chunk's static num_steps, tail-of-generation rooms never sweep
        # fresh executables — ONE megastep executable serves every clamp
        n = min(self.megastep_k, self.cfg.seq_len - 1 - max_pos)
        if n <= 0:
            self._drain(emitted)
            victim = max(live, key=lambda r: r.position)
            victim.truncated = True
            self._finish(victim)
            return emitted
        active_rows = self._reserve_megastep_blocks(active_rows, pend + n)
        if not active_rows:
            self._drain(emitted)
            return emitted
        live = [r for r in active_rows if not r.done and not r.inserting]
        if not live:
            self._drain(emitted)
            return emitted
        alive_h, budget_h, eos_h = self._carry_replay_state()
        tok0, pos_dev, alive_dev, budget_dev = self._dispatch_carry(
            alive_h, budget_h)
        # per-row coverage of the host-pre-reserved block budget, in
        # POSITIONS: the loop's in-graph block consumption early-exits when a
        # live row's true device position reaches it (the host estimate can
        # be short under allocator pressure — that costs loop iterations,
        # never correctness)
        coverage = np.zeros((self.num_slots,), np.int32)
        for slot, r in enumerate(self.active):
            if r is not None:
                coverage[slot] = len(r.blocks) * self.block_size
        # pending-arrival service flag: with queued work that could not place
        # (no free slot / blocks), yield after ONE inner step so a finishing
        # row is serviced at step-wise latency instead of K-step latency
        service = np.int32(1 if self.queue else 0)
        greedy = self._chunk_greedy(live)
        key, sub = jax.random.split(key)
        with tel.annotate("megastep"):
            (ring_dev, n_dev, exit_dev), dev_state, self.cache, \
                self._telem_dev = self._megastep_step(
                    self.app.params, tok0, pos_dev, alive_dev, budget_dev,
                    self.cache, self._telem_dev,
                    jnp.asarray(self.block_table), jnp.asarray(coverage),
                    self._sampling_matrix(), sub,
                    jnp.asarray(self.adapter_ids), jnp.asarray(eos_h),
                    np.int32(n), service, ring_cap=self.megastep_ring,
                    greedy=greedy)
        entry = ("mega", ring_dev, n_dev, exit_dev, min(n, self.megastep_ring))
        n_done = None
        if self._async_ok(pend + n + self.megastep_k):
            self._inflight.append(entry)
            self._dev_state = dev_state
            while len(self._inflight) > self.async_depth:
                # committing the OLDEST in-flight megastep is the one
                # designed host sync of dispatch-ahead
                # lint: ok(step-loop-sync): oldest-chunk commit, the designed sync
                self._commit_entry(self._inflight.pop(0), emitted)
            self._m_inflight.set(len(self._inflight))
        else:
            self._drain(emitted)                    # older dispatches first
            n_done = self._commit_entry(entry, emitted)
        if t_step is not None:
            extra = self._consume_fall_through() or {}
            extra["megastep_requested"] = n
            if n_done is not None:
                # sync path: the executed count and in-graph exit reason are
                # already on the host (async records them at commit time via
                # the exits counter instead — the dispatch-time record only
                # knows the upper bound)
                extra["megastep_exit"] = n_done[1]
            tel.step_record(
                t_step, "megastep",
                iterations=n_done[0] if n_done is not None else n,
                tokens=_emitted_count(emitted) - n_emit0,
                occupancy=len(live), slots=self.num_slots,
                in_flight=len(self._inflight),
                kv_free=self.allocator.num_free,
                kv_total=self.allocator.num_blocks,
                ici_bytes=self._ici_bytes(
                    n_done[0] if n_done is not None else n),
                extra=extra)
        return emitted

    def _reserve_megastep_blocks(self, active_rows: List[Request],
                                 steps: int) -> List[Request]:
        """Best-effort block reservation for one megastep: extend every
        decoding row toward ``position + steps + 1`` coverage but STOP at
        allocator exhaustion instead of preempting — the megastep's in-graph
        coverage check early-exits when a live row reaches its reserved
        budget, so partial coverage costs loop iterations, never
        correctness. The preempting grower (``_grow_blocks``) only runs when
        some row cannot cover even its next KV write (zero-progress stall)."""
        bs = self.block_size
        for req in active_rows:
            if req.inserting or req.done:
                continue        # insert rows hold their full-prompt blocks
            want = req.position + steps + 1
            if len(req.blocks) * bs < want:
                # this walk PROBES the free list until it raises (partial
                # coverage by design) — suppress the OOM forensics capture
                with self._led(req, "megastep_reserve",
                               expect_exhaustion=True):
                    try:
                        self.allocator.extend(req.blocks, want)
                    # lint: ok(silent-except): designed partial reservation — short coverage costs loop iterations (in-graph coverage early-exit), never correctness
                    except RuntimeError:
                        # partial reservation: take what the free list still
                        # has, one block at a time (extend() rolls back
                        # all-or-nothing)
                        while len(req.blocks) * bs < want:
                            try:
                                self.allocator.extend(
                                    req.blocks, len(req.blocks) * bs + 1)
                            # lint: ok(silent-except): end of the best-effort walk — the megastep's coverage exit handles the shortfall
                            except RuntimeError:
                                break
            self.block_table[req.slot, : len(req.blocks)] = req.blocks
        if any(not r.inserting and not r.done
               and len(r.blocks) * bs <= r.position for r in active_rows):
            active_rows = self._grow_blocks(active_rows, 1)
        return active_rows

    def _fall_through(self, from_kind: str, reason: str, key,
                      emitted: Dict[int, List[int]]) -> Dict[int, List[int]]:
        """The ONE guarded scheduler exit to the plain path (ISSUE-10
        satellite): count the degradation, stamp the reason on the next
        step-timeline record, then run the plain step (which is the megastep
        when megastep_k is set — a mixed/spec run that quietly degrades is
        visible in telemetry, never silent)."""
        self._note_fall_through(from_kind, reason)
        return self._step_plain(key, emitted)

    def _note_fall_through(self, from_kind: str, reason: str,
                           detail: Optional[str] = None) -> None:
        """``detail``: free-form suffix stamped onto the timeline note but
        NOT onto the counter labels (replica ids / knob values would blow up
        the label cardinality; the timeline and journal carry them)."""
        note = f"{from_kind}:{reason}"
        if detail:
            note = f"{note}={detail}"
        self._pending_fall_through.append(note)
        c = self._ft_counters.get((from_kind, reason))
        if c is None:
            c = self.telemetry.registry.counter(
                "serving_fallthrough_total",
                "scheduler fall-throughs / degradations by origin and reason",
                labels={"from": from_kind, "reason": reason})
            self._ft_counters[(from_kind, reason)] = c
        c.inc()

    def _consume_fall_through(self) -> Optional[Dict[str, object]]:
        """Step-timeline payload for the pending fall-through notes (one-shot
        — consumed by the NEXT recorded step of any kind, so a note from a
        branch that records no step itself, e.g. the mixed seq-room
        truncation, still lands on the timeline instead of going stale)."""
        if not self._pending_fall_through:
            return None
        reasons = ",".join(self._pending_fall_through)
        self._pending_fall_through = []
        return {"fall_through": reasons}

    def _ici_bytes(self, iterations: int, prefill_tokens: int = 0
                   ) -> Optional[int]:
        """Step-timeline ICI traffic: per-token-row estimate times the token
        rows the dispatch moves — each decode iteration carries the compiled
        slot count of rows, prefill windows/chunks carry their written token
        widths. None on tp=1 meshes, so single-chip step records keep their
        exact pre-multichip shape."""
        if not self._ici_bytes_per_token:
            return None
        units = int(iterations) * self.num_slots + int(prefill_tokens)
        return self._ici_bytes_per_token * max(1, units)

    def _note_chunk_time(self, wall_s: float, steps: int) -> None:
        """async_mode="auto": time full-size sync chunks (sample 1 discarded —
        it includes compilation), measure one blocking round trip, then enable
        dispatch-ahead only when the round trip is >20% of the chunk's wall
        time (the r4 measurement: +32% at that regime, -5% when the chunk
        already amortizes the trip)."""
        if not self._async_auto or steps != self.decode_chunk:
            return
        self._m_chunk_wall.observe(wall_s)
        self._chunk_times.append(wall_s)
        if len(self._chunk_times) < 3:
            return
        if self._round_trip_s is None:
            np.asarray(jnp.asarray(np.int32(0)) + 1)   # warm (compile once)
            t0 = time.perf_counter()
            np.asarray(jnp.asarray(np.int32(1)) + 1)   # host->device->host
            self._round_trip_s = time.perf_counter() - t0
        chunk_s = min(self._chunk_times[1:])
        self._async_auto = False
        self.async_mode = self._round_trip_s / max(chunk_s, 1e-9) > 0.2
        logger.info(
            "async auto-decision: round_trip=%.1fms chunk=%.1fms -> %s",
            1e3 * self._round_trip_s, 1e3 * chunk_s,
            "dispatch-ahead ON" if self.async_mode else "sync")

    def _assign_prefill_chunks(self, inserting: List[Request]) -> List[tuple]:
        """Token budget -> mixed-step chunk assignments ``[(req, wlen), ...]``.

        Classless (``sla_classes=None``) or single-class traffic: oldest
        placement first (FIFO completion; every in-flight insert advances
        before any one hogs the budget twice) — bit-identical to the
        pre-SLA scheduler.

        With more than one SLA class inserting: WEIGHTED-FAIR (ISSUE-13
        tentpole b). The per-step prefill token budget splits across the
        classes PRESENT by their configured weights, each class spends its
        share FIFO over its own rows, and unspent share redistributes to the
        remaining rows most-important-class first (work-conserving: the full
        budget is always offered). A bulk tenant's 100k-token prompt can
        therefore never starve interactive prefill — the interactive class
        draws its weight share every step — while an idle-class budget is
        never wasted. Only chunk ordering/sizing changes; the host commit
        rules (and therefore every emitted stream) stay exact."""
        c_rows, t_bucket = self.chunk_rows, self.prefill_chunk
        budget = self.prefill_budget
        fifo = sorted(inserting, key=lambda r: r.placed_seq)
        if self.sla is None or len({r.sla_class for r in fifo}) <= 1:
            chosen: List[tuple] = []
            for r in fifo:
                if len(chosen) == c_rows or budget <= 0:
                    break
                wlen = min(t_bucket, len(r.fed) - r.insert_pos, budget)
                if wlen <= 0:
                    continue
                chosen.append((r, wlen))
                budget -= wlen
            return chosen
        # chunk rows are a fixed resource: hand them out most-important
        # class first, FIFO within a class
        ranked = sorted(fifo, key=lambda r: (self.sla.priority(r.sla_class),
                                             r.placed_seq))
        rows = [r for r in ranked if len(r.fed) - r.insert_pos > 0][:c_rows]
        if not rows:
            return []
        present = sorted({r.sla_class for r in rows}, key=self.sla.priority)
        wsum = sum(self.sla.weight(c) for c in present)
        share = {c: int(budget * self.sla.weight(c) / wsum) for c in present}
        for c in present:       # integer-rounding remainder, top class first
            if budget - sum(share.values()) <= 0:
                break
            share[c] += 1
        width = {r.request_id: 0 for r in rows}

        def give(r: Request, amount: int) -> int:
            take = min(amount, t_bucket - width[r.request_id],
                       len(r.fed) - r.insert_pos - width[r.request_id])
            width[r.request_id] += take
            return take

        for r in rows:                          # pass 1: class weight shares
            share[r.sla_class] -= give(r, share[r.sla_class])
        left = sum(share.values())
        for r in rows:                          # pass 2: work-conserving
            if left <= 0:
                break
            left -= give(r, left)
        return [(r, width[r.request_id]) for r in rows
                if width[r.request_id] > 0]

    def _count_class_prefill(self, sla_class: Optional[str],
                             tokens: int) -> None:
        """serving_class_prefill_tokens_total{sla_class=}: what each class
        actually drew from the mixed-step budget (weighted-fair visibility)."""
        if sla_class is None or not tokens:
            return
        c = self._class_prefill_counters.get(sla_class)
        if c is None:
            c = self.telemetry.registry.counter(
                "serving_class_prefill_tokens_total",
                "prompt tokens drawn from the mixed-step prefill budget, "
                "by SLA class", labels={"sla_class": sla_class})
            self._class_prefill_counters[sla_class] = c
        c.inc(tokens)

    @step_loop_body
    def _step_mixed(self, key, emitted: Dict[int, List[int]]
                    ) -> Dict[int, List[int]]:
        """One MIXED prefill+decode serving step (the token-budget scheduler).

        While any placed request is still streaming its prompt, each dispatch
        packs ALL alive decode rows (``mixed_decode_steps`` chained decode
        iterations) PLUS up to ``prefill_token_budget`` prompt tokens from the
        in-flight inserts — as prefill-chunk rows of the variable-q_len ragged
        paged attend — into ONE jitted call. Residents never stall behind a
        prompt (the insert-window loop's stop-the-world bs=1 dispatches), and
        a prompt makes progress every step regardless of decode load. With no
        insert in flight this falls through to the full-width plain chunks.

        Exact host-side commit rules: a chunk advances ``insert_pos`` only; the
        chunk whose last token completes the prompt samples tok0 (discarded on
        preemption-resume, exactly like _advance_inserts); prefix-cache hits
        entered at _begin_insert mean the first chunk starts mid-prompt; eos
        and max_new_tokens replay on the host via _commit/_maybe_finish."""
        active_rows = [r for r in self.active if r is not None]
        inserting = [r for r in active_rows if r.inserting]
        if not inserting:
            # pure-decode steady state: fall through BEFORE draining so async
            # dispatch-ahead keeps overlapping (_step_plain owns the pipeline)
            return self._fall_through("mixed", "no_insert_in_flight", key,
                                      emitted)
        tel = self.telemetry
        t_step = tel.step_start()
        n_emit0 = _emitted_count(emitted) if t_step is not None else 0
        self._drain(emitted)

        live = [r for r in active_rows if not r.done and not r.inserting]
        # no live decode rows: a 1-iteration decode scan rides along (all its
        # writes slot -1, tokens discarded) instead of mixed_decode_steps of
        # pure waste — cold-start TTFT is chunk-bound, not scan-bound
        steps = self.mixed_decode_steps if live else 1
        if live:
            from .speculation import quantize_chunk_iters

            max_pos = max(r.position for r in live)
            # num_steps is a STATIC jit arg: quantize the seq-room clamp to
            # powers of two (same discipline as the spec chunk) so tail-of-
            # generation rooms don't sweep fresh executables
            room = self.cfg.seq_len - 1 - max_pos
            steps = (quantize_chunk_iters(steps, room) if room > 0 else 0)
            if steps <= 0:
                victim = max(live, key=lambda r: r.position)
                victim.truncated = True
                self._finish(victim)
                self._note_fall_through("mixed", "seq_room_truncated")
                return emitted
            active_rows = self._grow_blocks(active_rows, steps)
            if not active_rows:
                self._note_fall_through("mixed", "all_rows_preempted")
                return emitted
            # growth may have preempted an inserting request
            inserting = [r for r in active_rows if r.inserting]
            live = [r for r in active_rows if not r.done and not r.inserting]
            if not inserting:
                return self._fall_through("mixed", "inserts_preempted", key,
                                          emitted)

        if self.megastep_k is not None:
            if self.queue:
                # the window PLAN depends on placements the host makes
                # between steps — with arrivals pending, serve step-wise so
                # they land at one-window latency (the host-side mirror of
                # the plain megastep's service flag)
                self._note_fall_through("mixed_mega", "pending_arrival")
            else:
                out = self._step_mixed_megastep(
                    key, emitted, tel, t_step, n_emit0, active_rows,
                    inserting, live, steps)
                if out is not None:
                    return out

        # token budget -> chunk assignments (weighted-fair across SLA
        # classes when >1 class is inserting; plain FIFO otherwise)
        c_rows, t_bucket = self.chunk_rows, self.prefill_chunk
        chosen = self._assign_prefill_chunks(inserting)

        mb = self.max_blocks_per_seq
        chunk_ids = np.zeros((c_rows, t_bucket), np.int32)
        chunk_pos = np.zeros((c_rows,), np.int32)
        chunk_qlens = np.ones((c_rows,), np.int32)  # padded rows: 1 dead query
        chunk_bt = np.zeros((c_rows, mb), np.int32)
        chunk_lens = np.zeros((c_rows,), np.int32)
        chunk_sp = np.tile(self._default_sp_row, (c_rows, 1))
        chunk_ad = np.zeros((c_rows,), np.int32)
        # telemetry-carry seed flag: 1 for chunk rows whose window completes
        # the prompt AND whose sampled seed the host will emit (resumed
        # re-inserts discard it) — host-known at dispatch time
        chunk_emit = np.zeros((c_rows,), np.int32)
        for i, (r, wlen) in enumerate(chosen):
            chunk_ids[i, :wlen] = r.fed[r.insert_pos : r.insert_pos + wlen]
            chunk_pos[i] = r.insert_pos
            chunk_qlens[i] = wlen
            chunk_bt[i] = self.block_table[r.slot]
            chunk_lens[i] = wlen
            chunk_sp[i] = self._slot_sp[r.slot]
            chunk_ad[i] = self.adapter_ids[r.slot]
            chunk_emit[i] = int(r.insert_pos + wlen >= len(r.fed)
                                and not r.generated)
        # padded chunk rows write nothing (all slots -1); live rows commit
        # their consecutive run through the chunk-length one-RMW-per-window
        # write path
        chunk_slots = block_kvcache.make_chunk_slot_mapping(
            chunk_bt, chunk_pos, chunk_lens, t_bucket, self.block_size)

        # telemetry-carry counting state: the mixed scan itself advances every
        # slot; the carry replays the host's budget/eos commit rules so the
        # drained counters match the host exactly (tokens stay ungated)
        valid, budget0, eos_ids = self._carry_replay_state()
        slot_chunk = self._slot_mapping_fn(
            self.block_table, self.positions, steps, self.block_size,
            valid=valid)
        greedy = self._chunk_greedy(live + [r for r, _ in chosen])
        key, sub = jax.random.split(key)
        with tel.annotate("mixed"):
            toks_dev, chunk_tok_dev, self.cache, self._telem_dev = \
                self._mixed_step(
                    self.app.params, jnp.asarray(self.last_tok),
                    jnp.asarray(self.positions), jnp.asarray(valid),
                    jnp.asarray(budget0), self.cache, self._telem_dev,
                    jnp.asarray(self.block_table), jnp.asarray(slot_chunk),
                    jnp.asarray(chunk_ids), jnp.asarray(chunk_pos),
                    jnp.asarray(chunk_qlens), jnp.asarray(chunk_bt),
                    jnp.asarray(chunk_slots), jnp.asarray(chunk_emit),
                    self._sampling_matrix(),
                    jnp.asarray(chunk_sp), sub, jnp.asarray(self.adapter_ids),
                    jnp.asarray(chunk_ad), jnp.asarray(eos_ids),
                    num_steps=steps, greedy=greedy)

        if live:
            self._commit(np.asarray(toks_dev), steps, emitted)
        chunk_tok = np.asarray(chunk_tok_dev)
        for i, (r, wlen) in enumerate(chosen):
            tel.request_prefill_chunk(r.request_id, wlen, r.insert_pos)
            self._count_class_prefill(r.sla_class, wlen)
            r.insert_pos += wlen
            if r.insert_pos < len(r.fed):
                continue
            r.inserting = False
            resumed = bool(r.generated)   # preempted earlier; KV recomputed now
            r.position = len(r.fed)
            if not resumed:
                tok0 = int(chunk_tok[i])
                r.generated = [tok0]
                emitted.setdefault(r.request_id, []).append(tok0)
            self.positions[r.slot] = r.position
            self.last_tok[r.slot] = r.generated[-1]
            self._maybe_finish(r, emitted)
        if t_step is not None:
            tel.step_record(
                t_step, "mixed", iterations=steps,
                tokens=_emitted_count(emitted) - n_emit0,
                occupancy=len(live), slots=self.num_slots,
                prefill_tokens=sum(w for _, w in chosen),
                prefill_budget=self.prefill_budget,
                kv_free=self.allocator.num_free,
                kv_total=self.allocator.num_blocks,
                ici_bytes=self._ici_bytes(steps,
                                          sum(w for _, w in chosen)),
                extra=self._consume_fall_through())
        return emitted

    def _plan_mixed_megastep(self, inserting: List[Request],
                             max_windows: int) -> List[List[tuple]]:
        """Simulate ``_assign_prefill_chunks`` over up to ``max_windows``
        successive mixed steps WITHOUT touching request state: the
        FIFO/weighted assignment reads only host bookkeeping (insert_pos,
        placed_seq, sla_class, the fixed per-step budget), so overlaying
        ``insert_pos`` between rounds reproduces the exact window sequence
        the step-wise scheduler would emit. Each plan entry is a window
        ``[(req, wlen, pos0), ...]`` with ``pos0`` the pre-window insert
        position. The plan STOPS after the first window in which any prompt
        completes — a completion changes the decode roster for subsequent
        dispatches, which the megastep's pre-staged operands cannot model,
        so a completing window is always the plan's LAST."""
        saved = {r.request_id: r.insert_pos for r in inserting}
        plan: List[List[tuple]] = []
        try:
            for _ in range(max_windows):
                chosen = self._assign_prefill_chunks(inserting)
                if not chosen:
                    break
                window = []
                complete = False
                for r, wlen in chosen:
                    window.append((r, wlen, r.insert_pos))
                    r.insert_pos += wlen
                    if r.insert_pos >= len(r.fed):
                        complete = True
                plan.append(window)
                if complete:
                    break
        finally:
            for r in inserting:
                r.insert_pos = saved[r.request_id]
        return plan

    def _step_mixed_megastep(self, key, emitted: Dict[int, List[int]], tel,
                             t_step, n_emit0: int,
                             active_rows: List[Request],
                             inserting: List[Request], live: List[Request],
                             steps: int) -> Optional[Dict[int, List[int]]]:
        """Up to ``megastep_k`` whole MIXED insert windows in ONE scanned
        dispatch (cb.paged.mixed_megastep): the host pre-plans the window
        sequence (_plan_mixed_megastep), stacks every window's chunk
        operands on a leading W axis, and the device threads the decode
        carry across windows exactly as the host would re-seed it between
        step-wise dispatches — the per-token host round-trip between insert
        windows disappears. Returns None (no state mutated) when the plan
        is too short to beat step-wise; otherwise the committed emissions.

        Exactness: window j's chunk rows/lengths equal the step-wise
        assignment (same pure host policy over the same overlaid
        insert_pos), the decode chain equals the step-wise re-seeded chain
        for every host-live row, and the one big ``_commit`` over
        ``W * steps`` columns equals W sequential commits (per-row commit
        stops at eos/budget and ignores later columns either way)."""
        from .speculation import quantize_chunk_iters

        if live:
            room = self.cfg.seq_len - 1 - max(r.position for r in live)
            cap = min(self.megastep_k, room // steps)
        else:
            cap = self.megastep_k
        if cap < 2:
            self._note_fall_through("mixed_mega", "window_short")
            return None
        plan = self._plan_mixed_megastep(inserting, cap)
        wq = (quantize_chunk_iters(self.megastep_k, len(plan))
              if len(plan) >= 2 else 0)
        if wq < 2:
            # one (or zero) windows of prompt left: step-wise is already
            # optimal and the plan simulation touched nothing
            self._note_fall_through("mixed_mega", "window_short")
            return None
        plan = plan[:wq]
        num_w = len(plan)
        if live:
            # the step-wise preamble grew ONE window of decode room; extend
            # to the full in-graph advance
            active_rows = self._grow_blocks(active_rows, num_w * steps)
            if not active_rows:
                self._note_fall_through("mixed_mega", "all_rows_preempted")
                return emitted
            live = [r for r in active_rows if not r.done and not r.inserting]
            still = {r.request_id for r in active_rows if r.inserting}
            if {r.request_id for r in inserting} - still:
                # growth preempted an inserting row the plan references
                return self._fall_through("mixed_mega", "inserts_preempted",
                                          key, emitted)

        c_rows, t_bucket = self.chunk_rows, self.prefill_chunk
        mb = self.max_blocks_per_seq
        chunk_ids = np.zeros((num_w, c_rows, t_bucket), np.int32)
        chunk_pos = np.zeros((num_w, c_rows), np.int32)
        chunk_qlens = np.ones((num_w, c_rows), np.int32)
        chunk_bt = np.zeros((num_w, c_rows, mb), np.int32)
        chunk_sp = np.tile(self._default_sp_row, (num_w, c_rows, 1))
        chunk_ad = np.zeros((num_w, c_rows), np.int32)
        chunk_emit = np.zeros((num_w, c_rows), np.int32)
        slots_l = []
        for j, window in enumerate(plan):
            lens = np.zeros((c_rows,), np.int32)
            for i, (r, wlen, pos0) in enumerate(window):
                chunk_ids[j, i, :wlen] = r.fed[pos0 : pos0 + wlen]
                chunk_pos[j, i] = pos0
                chunk_qlens[j, i] = wlen
                chunk_bt[j, i] = self.block_table[r.slot]
                lens[i] = wlen
                chunk_sp[j, i] = self._slot_sp[r.slot]
                chunk_ad[j, i] = self.adapter_ids[r.slot]
                chunk_emit[j, i] = int(pos0 + wlen >= len(r.fed)
                                       and not r.generated)
            slots_l.append(block_kvcache.make_chunk_slot_mapping(
                chunk_bt[j], chunk_pos[j], lens, t_bucket, self.block_size))
        chunk_slots = np.stack(slots_l)

        valid, budget0, eos_ids = self._carry_replay_state()
        slot_chunk = self._slot_mapping_fn(
            self.block_table, self.positions, num_w * steps,
            self.block_size, valid=valid)
        greedy = self._chunk_greedy(
            live + [r for w in plan for (r, _, _) in w])
        key, sub = jax.random.split(key)
        with tel.annotate("mixed_megastep"):
            toks_dev, chunk_toks_dev, self.cache, self._telem_dev = \
                self._mixed_megastep_step(
                    self.app.params, jnp.asarray(self.last_tok),
                    jnp.asarray(self.positions), jnp.asarray(valid),
                    jnp.asarray(budget0), self.cache, self._telem_dev,
                    jnp.asarray(self.block_table), jnp.asarray(slot_chunk),
                    jnp.asarray(chunk_ids), jnp.asarray(chunk_pos),
                    jnp.asarray(chunk_qlens), jnp.asarray(chunk_bt),
                    jnp.asarray(chunk_slots), jnp.asarray(chunk_emit),
                    self._sampling_matrix(), jnp.asarray(chunk_sp), sub,
                    jnp.asarray(self.adapter_ids), jnp.asarray(chunk_ad),
                    jnp.asarray(eos_ids), num_windows=num_w,
                    num_steps=steps, greedy=greedy)

        if live:
            self._commit(np.asarray(toks_dev), num_w * steps, emitted)
        chunk_toks = np.asarray(chunk_toks_dev)          # (W, c_rows)
        for j, window in enumerate(plan):
            for i, (r, wlen, pos0) in enumerate(window):
                tel.request_prefill_chunk(r.request_id, wlen, pos0)
                self._count_class_prefill(r.sla_class, wlen)
                r.insert_pos = pos0 + wlen
                if r.insert_pos < len(r.fed):
                    continue
                r.inserting = False
                resumed = bool(r.generated)   # preempted; KV recomputed now
                r.position = len(r.fed)
                if not resumed:
                    tok0 = int(chunk_toks[j, i])
                    r.generated = [tok0]
                    emitted.setdefault(r.request_id, []).append(tok0)
                self.positions[r.slot] = r.position
                self.last_tok[r.slot] = r.generated[-1]
                self._maybe_finish(r, emitted)
        self._m_megastep_iters.inc(num_w)
        if t_step is not None:
            extra = self._consume_fall_through() or {}
            extra["megastep_windows"] = num_w
            prefill_total = sum(w for win in plan for (_, w, _) in win)
            tel.step_record(
                t_step, "mixed_megastep", iterations=num_w * steps,
                tokens=_emitted_count(emitted) - n_emit0,
                occupancy=len(live), slots=self.num_slots,
                prefill_tokens=prefill_total,
                prefill_budget=self.prefill_budget,
                kv_free=self.allocator.num_free,
                kv_total=self.allocator.num_blocks,
                ici_bytes=self._ici_bytes(num_w * steps, prefill_total),
                extra=extra)
        return emitted

    @step_loop_body
    def _step_spec(self, key, emitted: Dict[int, List[int]]
                   ) -> Dict[int, List[int]]:
        """One fused-speculation serving dispatch: ``spec_chunk`` on-device
        iterations, then an exact host replay of the commit/stopping rules."""
        from .speculation import commit_row

        active_rows = [r for r in self.active if r is not None]
        live = [r for r in active_rows if not r.done and not r.inserting]
        if not live:
            return emitted
        tel = self.telemetry
        t_step = tel.step_start()
        n_emit0 = _emitted_count(emitted) if t_step is not None else 0
        if self.spec_adaptive and self._spec_off:
            self._spec_plain_chunks += 1
            if self._spec_plain_chunks < self.spec_probe_every:
                return self._fall_through("spec", "adaptive_floor", key,
                                          emitted)
            self._spec_plain_chunks = 0
            self._spec_off = False         # re-probe with one spec chunk
            self._m_spec_guard.set(0)
        max_pos = max(r.position for r in live)
        # every fused iteration needs a full K-token cache window
        room = (self.cfg.seq_len - 1 - max_pos) // self.k
        if room <= 0:
            # a row within K-1 positions of seq_len still has budget for its
            # remaining tokens: finish it with EXACT plain decode steps (draft
            # KV gaps from this path only dent later acceptance rates, never
            # correctness — the target verifies every token)
            return self._fall_through("spec", "seq_room", key, emitted)
        if self.megastep_k is not None and self.paged:
            if self.eagle is None:
                # device-resident spec megastep (ISSUE-19 leg c): up to
                # megastep_k fused iterations in ONE while_loop dispatch
                return self._step_spec_megastep(key, emitted, tel, t_step,
                                                n_emit0, live, active_rows,
                                                room)
            # the eagle chunk threads hidden-state re-injection the
            # while_loop carry does not model yet — visible degradation,
            # never a silent one
            self._note_fall_through("spec_mega", "eagle")
        # an iteration commits >=1 token/row: running past the tightest row's
        # remaining budget only wastes flops. Clamped values quantize to
        # powers of two — num_iters is a static jit arg (see
        # speculation.quantize_chunk_iters).
        from .speculation import quantize_chunk_iters

        iters = quantize_chunk_iters(
            self.spec_chunk, room,
            min(r.max_new_tokens - len(r.generated) for r in live))
        if self.paged:
            active_rows = self._grow_blocks(active_rows, iters * self.k)
            if not active_rows:
                return emitted
        # per-row remaining budgets for the telemetry carry's commit_row
        # replay (the real in-graph advance ignores budgets by design)
        alive0, budget0, eos_ids = self._carry_replay_state()
        key, sub = jax.random.split(key)
        sp = self._sampling_matrix()
        bt = (jnp.asarray(self.block_table) if self.paged
              else jnp.zeros((1, 1), dtype=jnp.int32))
        if self.eagle is not None:
            with tel.annotate("spec_chunk"):
                outs, ns, self._h_cond, self.cache, self.d_cache, \
                    self._telem_dev = self._spec_step_eagle(
                        self.app.params, self.eagle[1],
                        jnp.asarray(self.last_tok),
                        self._h_cond, jnp.asarray(self.positions),
                        jnp.asarray(alive0), jnp.asarray(budget0),
                        self.cache, self.d_cache, self._telem_dev, bt,
                        jnp.asarray(eos_ids), sub, num_iters=iters)
        else:
            bucket = (None if self.paged
                      else autobucketing.select_bucket(self.app.tkg_buckets,
                                                       max_pos + iters * self.k))
            with tel.annotate("spec_chunk"):
                outs, ns, self.cache, self.d_cache, self._telem_dev = \
                    self._spec_step(
                        self.app.params, self.draft.params,
                        jnp.asarray(self.last_tok),
                        jnp.asarray(self.positions), jnp.asarray(alive0),
                        jnp.asarray(budget0), self.cache, self.d_cache,
                        self._telem_dev, bt, sp, jnp.asarray(eos_ids),
                        sub, jnp.asarray(self.adapter_ids), num_iters=iters,
                        greedy=self._chunk_greedy(live), decode_bucket=bucket)
        outs = np.asarray(outs)           # (iters, slots, K)
        ns = np.asarray(ns)               # (iters, slots)
        self._m_spec_iters.inc(iters)
        chunk_added, chunk_cells = self._commit_spec_outs(outs, ns, iters,
                                                          emitted)
        if t_step is not None:
            tel.step_record(
                t_step, "spec_chunk", iterations=iters,
                tokens=_emitted_count(emitted) - n_emit0,
                occupancy=len(live), slots=self.num_slots,
                kv_free=self.allocator.num_free if self.paged else None,
                kv_total=self.allocator.num_blocks if self.paged else None,
                accept_mean=(chunk_added / chunk_cells if chunk_cells
                             else None),
                ici_bytes=self._ici_bytes(iters),
                extra=self._consume_fall_through())
        self._spec_adaptive_check(chunk_added, chunk_cells)
        return emitted

    def _commit_spec_outs(self, outs: np.ndarray, ns: np.ndarray, iters: int,
                          emitted: Dict[int, List[int]]):
        """EXACT host replay of a fused-spec result block: per iteration,
        per live slot, ``commit_row`` over the accepted ``outs[it, slot,
        :n+1]`` prefix (budget/eos stops included). One code path commits
        the step-wise chunk and the megastep ring drain, so the two emitted
        streams can only differ if the device results differ. Returns
        ``(chunk_added, chunk_cells)`` for the acceptance metrics/guard."""
        from .speculation import commit_row

        chunk_added = chunk_cells = 0
        for it in range(iters):
            for slot, req in enumerate(self.active):
                if req is None or req.done or req.inserting:
                    continue
                take = int(ns[it, slot]) + 1
                pre = len(req.generated)
                done = commit_row(req.generated, outs[it, slot, :take],
                                  req.eos_token_id, req.max_new_tokens)
                added = len(req.generated) - pre
                if added:
                    self._m_accept.observe(added)
                chunk_added += added
                chunk_cells += 1
                req.position += added
                emitted.setdefault(req.request_id, []).extend(
                    req.generated[pre:])
                self.positions[slot] = req.position
                self.last_tok[slot] = req.generated[-1]
                if done:
                    self._finish(req)
        return chunk_added, chunk_cells

    def _spec_adaptive_check(self, chunk_added: int, chunk_cells: int) -> None:
        """Acceptance-floor guard shared by the step-wise and megastep spec
        paths: below ``spec_min_accept`` committed tokens/row/iteration the
        runner serves plain chunks until the next re-probe."""
        if (self.spec_adaptive and chunk_cells
                and chunk_added / chunk_cells < self.spec_min_accept):
            self._spec_off = True
            self._m_spec_guard.set(1)
            logger.info(
                "adaptive speculation: %.2f committed tokens/row/iteration "
                "< %.2f — serving plain decode chunks (spec re-probe every "
                "%d chunks)", chunk_added / chunk_cells,
                self.spec_min_accept, self.spec_probe_every)

    def _step_spec_megastep(self, key, emitted: Dict[int, List[int]], tel,
                            t_step, n_emit0: int, live: List[Request],
                            active_rows: List[Request], room: int
                            ) -> Dict[int, List[int]]:
        """One device-resident SPECULATIVE megastep: up to ``megastep_k``
        fused draft-verify-accept iterations in ONE ``lax.while_loop``
        dispatch (cb.spec.megastep), synced ONCE, then the exact
        ``_commit_spec_outs`` replay over the ringed ``(outs, ns)[:n_run]``
        prefix. The caller (_step_spec) already handled the adaptive guard
        and the seq-room fall-through; ``room`` >= 1 fused iterations fit.

        Greedy streams are bit-identical to the step-wise chunks (same
        iteration math via _spec_iter_factory, same commit replay); sampled
        streams draw per-iteration keys from a megastep-level split exactly
        like the plain megastep — same distribution, different stream."""
        self._drain(emitted)
        n = min(self.megastep_k, room)
        active_rows = self._reserve_megastep_blocks(active_rows,
                                                    n * self.k)
        if not active_rows:
            return emitted
        live = [r for r in active_rows if not r.done and not r.inserting]
        if not live:
            return emitted
        alive0, budget0, eos_ids = self._carry_replay_state()
        coverage = np.zeros((self.num_slots,), np.int32)
        for slot, r in enumerate(self.active):
            if r is not None:
                coverage[slot] = len(r.blocks) * self.block_size
        service = np.int32(1 if self.queue else 0)
        greedy = self._chunk_greedy(live)
        key, sub = jax.random.split(key)
        with tel.annotate("spec_megastep"):
            (outs_dev, ns_dev, n_dev, exit_dev), self.cache, self.d_cache, \
                self._telem_dev = self._spec_megastep_step(
                    self.app.params, self.draft.params,
                    jnp.asarray(self.last_tok), jnp.asarray(self.positions),
                    jnp.asarray(alive0), jnp.asarray(budget0), self.cache,
                    self.d_cache, self._telem_dev,
                    jnp.asarray(self.block_table), jnp.asarray(coverage),
                    self._sampling_matrix(), jnp.asarray(eos_ids), sub,
                    jnp.asarray(self.adapter_ids), np.int32(n), service,
                    ring_cap=self.megastep_ring, greedy=greedy)
        n_run = int(np.asarray(n_dev))
        code = int(np.asarray(exit_dev))
        reason = MEGASTEP_EXITS.get(code, str(code))
        self._count_megastep_exit(reason)
        self._m_megastep_iters.inc(n_run)
        self._m_spec_iters.inc(n_run)
        chunk_added = chunk_cells = 0
        if n_run:
            chunk_added, chunk_cells = self._commit_spec_outs(
                np.asarray(outs_dev)[:n_run], np.asarray(ns_dev)[:n_run],
                n_run, emitted)
        if t_step is not None:
            extra = self._consume_fall_through() or {}
            extra["megastep_requested"] = n
            extra["megastep_exit"] = reason
            tel.step_record(
                t_step, "spec_megastep", iterations=n_run,
                tokens=_emitted_count(emitted) - n_emit0,
                occupancy=len(live), slots=self.num_slots,
                kv_free=self.allocator.num_free,
                kv_total=self.allocator.num_blocks,
                accept_mean=(chunk_added / chunk_cells if chunk_cells
                             else None),
                ici_bytes=self._ici_bytes(n_run),
                extra=extra)
        self._spec_adaptive_check(chunk_added, chunk_cells)
        return emitted

    def drain_requests(self):
        """Evict every unfinished request through the existing preemption/
        resume path (serving/router.py replica drain): flush the dispatch
        pipeline (its tokens still count), preempt live rows — mid-prompt
        inserts included — and hand back the evicted Request objects for
        re-placement elsewhere. Returns (emitted, requests): ``emitted`` is
        the final {request_id: tokens} of the flush, ``requests`` preserve
        prompt/generated/sampling/adapter state so ``submit(...,
        resume_tokens=req.generated)`` on another runner continues the exact
        stream."""
        emitted: Dict[int, List[int]] = {}
        self._drain(emitted)
        if self.telemetry.enabled and emitted:
            self.telemetry.note_emitted(emitted)
        for req in list(self.active):
            if req is not None and not req.done:
                self._preempt(req)
        out = list(self.queue)
        self.queue.clear()
        if self.kv_tier is not None:
            # the replica is leaving the placement set: park nothing — spill
            # every committed prefix to host RAM so the bytes survive the
            # replica (a re-added replica re-admits them on the next hit)
            self.spill_idle_blocks()
        # migration hand-off audit point: the drained pool must balance
        # bit-for-bit (every evicted request's blocks released, idle spills
        # accounted) before the streams move elsewhere
        self.audit_ledger()
        return emitted, out

    def evict_request(self, request_id: int):
        """Evict ONE unfinished request through the preemption/resume path
        and REMOVE it from this runner — the single-request counterpart of
        ``drain_requests`` (router-level SLA preemption, serving/router.py:
        a high-class arrival that cannot place preempts the newest
        lowest-class victim, which then migrates to another replica or
        re-queues here later via ``submit(resume_tokens=)``; greedy streams
        resume bit-identically either way).

        The dispatch pipeline is flushed first (its committed tokens still
        belong to their streams), so the preempted state is exact. With a KV
        tier attached the victim's committed full blocks park in the idle
        pool (and spill to host RAM under pressure) exactly as any
        preemption's do. Returns ``(emitted, request-or-None)``: ``emitted``
        is the flush's {request_id: tokens}; the Request preserves
        prompt/generated/sampling/adapter/sla state for re-submission."""
        emitted: Dict[int, List[int]] = {}
        self._drain(emitted)
        if self.telemetry.enabled and emitted:
            self.telemetry.note_emitted(emitted)
        req = next((r for r in self.active
                    if r is not None and r.request_id == request_id), None)
        if req is not None and not req.done:
            self._preempt(req)               # re-queues at the front ...
            self.queue.remove(req)           # ... and leaves with us instead
            self.audit_ledger()              # single-request hand-off audit
            return emitted, req
        req = next((r for r in self.queue if r.request_id == request_id),
                   None)
        if req is not None:
            self.queue.remove(req)
        return emitted, req

    def run_to_completion(self, seed: int = 0,
                          on_step=None) -> Dict[int, List[int]]:
        """Drive step() until every submitted request finishes; returns all
        outputs. ``on_step(step_count)`` is called after every step (e.g. the
        CLI's periodic stats logging)."""
        self._key = jax.random.PRNGKey(seed)
        guard = 0
        while self.has_work:
            self.step()
            guard += 1
            if on_step is not None:
                on_step(guard)
            if guard > 10000:
                raise RuntimeError("continuous batching did not converge")
        return {rid: req.generated for rid, req in self.finished.items()}

    # --- paged block growth with preemption (≈ vLLM-style recompute preemption) ------
    def _grow_blocks(self, active_rows: List[Request], steps: int) -> List[Request]:
        """Extend every active row's blocks to cover the chunk; on exhaustion, preempt
        the newest-placed *other* request (requeue, KV recomputed at next placement —
        prefix caching recovers most of it) and retry. A lone request that still cannot
        grow is truncated."""
        while True:
            try:
                for req in active_rows:
                    if req.inserting:
                        continue   # blocks for the full prompt already held
                    # exhaustion here is handled by the preempting grower —
                    # designed degradation, not an OOM forensics event
                    with self._led(req, "grow", expect_exhaustion=True):
                        self.allocator.extend(req.blocks,
                                              req.position + steps + 1)
                    self.block_table[req.slot, : len(req.blocks)] = req.blocks
                return active_rows
            # lint: ok(silent-except): recovery IS the handler — _preempt (logs + counts serving_preemptions_total) or truncate-finish
            except RuntimeError:
                if len(active_rows) > 1:
                    victim = max(active_rows, key=lambda r: r.placed_seq)
                    self._preempt(victim)
                else:
                    active_rows[0].truncated = True
                    self._finish(active_rows[0])
                active_rows = [r for r in self.active if r is not None]
                if not active_rows:
                    return []

    def _unplace_on_exhaustion(self, req: Request, slot: int) -> None:
        """Placement hit allocator exhaustion (ISSUE-11 graceful
        degradation): undo the half-done placement (allocate_for_prompt
        already rolled its blocks back), re-queue the request at the front,
        and PREEMPT the newest inserting row — the resume path the
        mechanism already has — so the next placement attempt finds
        headroom. Counted as a visible scheduler degradation
        (``serving_fallthrough_total{from="place",reason="kv_exhausted"}``)
        — serving slows down under exhaustion; it never dies of it."""
        logger.warning(
            "placement of request %d hit KV-block exhaustion: re-queued; "
            "preempting the newest insert for headroom", req.request_id)
        if self.ledger is not None:
            # OOM forensics: who holds the pool at the exhaustion point —
            # covers injected alloc faults too (they raise ABOVE the
            # ledger's own exception-path capture in the wrapped seam)
            self.ledger.note_exhaustion("place")
        self.active[slot] = None
        self._slot_sp[slot] = self._default_sp_row
        self.adapter_ids[slot] = 0
        req.slot = -1
        req.inserting = False
        req.fed = None
        req.insert_pos = 0
        req.tok0_dev = None
        self.queue.insert(0, req)
        self._note_fall_through("place", "kv_exhausted")
        inserting = [r for r in self.active
                     if r is not None and r.inserting and not r.done]
        if inserting:
            self._preempt(max(inserting, key=lambda r: r.placed_seq))

    def _preempt(self, req: Request) -> None:
        logger.info("preempting request %d (out of KV blocks)", req.request_id)
        self._m_preempt.inc()
        self.telemetry.request_preempted(
            req.request_id,
            blocks_held=len(req.blocks) if self.paged else None)
        self.active[req.slot] = None
        if self.paged:
            if self.ledger is not None:
                # holdings-timeline hand-off marker: blocks held AT preempt
                self.ledger.note_event(req.request_id, "preempt",
                                       tokens=len(req.generated))
            self._free_blocks(req, seam="preempt")
            self.block_table[req.slot, :] = 0
            req.blocks = []
        self._slot_sp[req.slot] = self._default_sp_row
        self.adapter_ids[req.slot] = 0
        req.slot = -1
        req.inserting = False       # chunked-insert progress restarts at resume
        req.fed = None
        req.insert_pos = 0
        req.tok0_dev = None
        self.queue.insert(0, req)   # resumes first; _insert refeeds prompt + generated

    # ------------------------------------------------------------------ internals
    def _sampling_matrix(self) -> np.ndarray:
        """Current per-slot (slots, 3) sampling params (rows set at placement)."""
        return self._slot_sp

    def _begin_insert(self, req: Request, slot: int) -> None:
        """Allocate blocks + prefix-cache lookup for the request's full prompt;
        initialize the windowed-insert cursor (paged mode)."""
        # resumed (preempted) requests refeed prompt + generated[:-1]; the newest
        # generated token stays the next decode input (its KV is never written here)
        fed = req.prompt
        if req.generated:
            fed = np.concatenate(
                [req.prompt, np.asarray(req.generated[:-1], dtype=np.int32)])
        # prefix-cache identity must include the ADAPTER: LoRA changes the
        # K/V projections, so the same prompt under different adapters has
        # different cache content. Salting the first hashed token keys the
        # whole chain (every later block hash chains on the first).
        hashed = fed
        if req.adapter_id != 0:
            hashed = fed.copy()
            hashed[0] ^= np.int32(req.adapter_id << 20)
        with self._led(req, "place"):
            req.blocks, cached_len = self.allocator.allocate_for_prompt(
                hashed)
        # never skip the whole prompt: the last token's logits seed generation
        cached_len = min(cached_len, len(fed) - 1)
        if (self.insert_cap is not None or self.mixed) and cached_len > 0:
            # chunked-prefill race (found by review): the allocator registers
            # prefix hashes at ALLOCATION, but with capped inserts the KV
            # streams in over later steps — a same-prefix request placed
            # meanwhile would reuse blocks whose KV hasn't landed. Trust the
            # skip only through blocks every in-progress insert has fully
            # written; shared-but-unwritten blocks are simply REwritten here
            # (identical content: the chained hash keys tokens + adapter).
            unsafe = set()
            for r in self.active:
                if r is not None and r.inserting and r is not req:
                    unsafe.update(r.blocks[r.insert_pos // self.block_size:])
            safe_tokens = 0
            for i, blk in enumerate(req.blocks):
                end = (i + 1) * self.block_size
                if end > cached_len or blk in unsafe:
                    break
                safe_tokens = end
            cached_len = min(cached_len, safe_tokens)
        if cached_len > 0:
            self.telemetry.request_prefix_hit(req.request_id, int(cached_len))
        self.block_table[slot, : len(req.blocks)] = req.blocks
        # host-tier prefix hits: restore the spilled blocks BEFORE any insert
        # window dispatches (the windows' queries read them via the table)
        self._dispatch_readmits(for_request=req.request_id)
        req.fed = fed
        req.insert_pos = cached_len
        req.tok0_dev = None
        req.inserting = True

    def _insert_windows(self, req: Request, slot: int, key, budget=None):
        """Run paged prefill windows from ``req.insert_pos``, consuming at most
        ``budget`` prompt tokens (None = all): each window's queries see the
        prior windows' KV through the block table (≈ windowed context encoding,
        reference `model_base.py:918-973`, and the chunked-prefill flow of
        `ChunkedPrefillConfig`). Only the prompt-FINAL window samples (and
        stores ``req.tok0_dev``); intermediate windows run KV-only
        (skip_logits), and with a draft model both pools are written by ONE
        fused dispatch per window. Returns (key, tokens_consumed)."""
        fed = req.fed
        tel = self.telemetry
        max_window = self.app.cte_buckets[-1]
        sp_row = self._slot_sp[slot : slot + 1]
        ad_row = jnp.asarray(self.adapter_ids[slot : slot + 1])
        # hoisted: the row's blocks are fully allocated at _begin_insert and
        # the table row never changes across this request's windows
        bt_row = jnp.asarray(self.block_table[slot : slot + 1])
        used = 0
        while req.insert_pos < len(fed) and (budget is None or used < budget):
            t_w = tel.step_start()
            wlen = len(fed) - req.insert_pos
            if budget is not None:
                wlen = min(wlen, budget - used)
            wlen = min(wlen, max_window)
            window = fed[req.insert_pos : req.insert_pos + wlen]
            padded = model_wrapper.pad_prefill_inputs(
                window[None, :], None, self.app.cte_buckets, batch_size=1)
            pos_row = np.array([req.insert_pos], dtype=np.int32)
            valid = np.ones((1, padded.bucket), dtype=bool)
            valid[0, len(window):] = False
            slot_map = jnp.asarray(self._slot_mapping_fn(
                self.block_table[slot : slot + 1], pos_row, padded.bucket,
                self.block_size, valid=valid))
            final = req.insert_pos + wlen >= len(fed)
            # seed flag for the telemetry carry: the final window's sampled
            # token counts as emitted only when the host will emit it
            emit = np.int32(int(final and not req.generated))
            with tel.annotate("insert_window"):
                if self.draft is not None:
                    key, sub = jax.random.split(key)
                    tok_dev, self.cache, self.d_cache, self._telem_dev = \
                        self._insert_pair_step(
                            self.app.params, self.draft.params,
                            padded.input_ids, pos_row, padded.last_token_idx,
                            self.cache, self.d_cache, self._telem_dev, bt_row,
                            slot_map, sp_row, sub, ad_row, emit, final=final)
                    if final:
                        req.tok0_dev = tok_dev
                elif final or self._insert_step_nol is None:
                    key, sub = jax.random.split(key)
                    req.tok0_dev, self.cache, self._telem_dev = \
                        self._insert_step(
                            self.app.params, padded.input_ids, pos_row,
                            padded.last_token_idx, self.cache,
                            self._telem_dev, bt_row, slot_map,
                            sp_row, sub, ad_row, emit)
                else:
                    self.cache, self._telem_dev = self._insert_step_nol(
                        self.app.params, padded.input_ids, pos_row, self.cache,
                        self._telem_dev, bt_row, slot_map, ad_row)
            tel.request_prefill_chunk(req.request_id, int(wlen),
                                      int(req.insert_pos))
            req.insert_pos += wlen
            used += wlen
            if t_w is not None:
                tel.step_record(
                    t_w, "insert_window", iterations=1,
                    prefill_tokens=int(wlen), slots=self.num_slots,
                    kv_free=self.allocator.num_free,
                    kv_total=self.allocator.num_blocks,
                    request_id=req.request_id,
                    ici_bytes=self._ici_bytes(0, int(wlen)))
        return key, used

    def _insert(self, req: Request, slot: int, key) -> int:
        # resumed (preempted) requests refeed prompt + generated[:-1]; the newest
        # generated token stays the next decode input (its KV is never written here)
        fed = req.prompt
        if req.generated:
            fed = np.concatenate(
                [req.prompt, np.asarray(req.generated[:-1], dtype=np.int32)])

        if self.paged and self.eagle is not None:
            return self._insert_eagle_host(req, slot, key, fed)
        tel = self.telemetry
        # paged inserts are timed per window inside _insert_windows; only the
        # dense branches below consume this timer
        t_i = None if self.paged else tel.step_start()
        sp_row = self._slot_sp[slot : slot + 1]
        ad_row = jnp.asarray(self.adapter_ids[slot : slot + 1])

        # telemetry-carry seed flag: resumed (preempted) re-inserts discard
        # their sampled seed, so the host passes 0
        emit = np.int32(int(not req.generated))
        if self.paged:
            self._begin_insert(req, slot)
            key, _ = self._insert_windows(req, slot, key)   # records per window
            req.inserting = False
            tok_dev = req.tok0_dev
        elif len(fed) > self.app.cte_buckets[-1]:
            # dense windowed (chunked) prefill at this slot's cache row, then a
            # 1-token seed decode re-feeding the last prompt token (idempotent
            # rewrite) for the first sampled token
            w = self.app.cte_buckets[-1]
            total = -(-len(fed) // w) * w
            ids = np.zeros((1, total), dtype=np.int32)
            ids[0, : len(fed)] = fed
            for w0 in range(0, total, w):
                bkt = autobucketing.select_bucket(self.app.tkg_buckets, w0 + w)
                self.cache, self._telem_dev = self._window_step(
                    self.app.params, ids[:, w0 : w0 + w], np.int32(w0),
                    np.int32(slot), self.cache, self._telem_dev,
                    np.int32(max(0, min(w, len(fed) - w0))), ad_row,
                    decode_bucket=bkt)
            key, sub = jax.random.split(key)
            tok_dev, self.cache, self._telem_dev = self._seed_step(
                self.app.params, jnp.asarray(fed[-1:]),
                np.array([len(fed) - 1], dtype=np.int32), np.int32(slot),
                self.cache, self._telem_dev, sp_row, sub, ad_row, emit,
                decode_bucket=autobucketing.select_bucket(self.app.tkg_buckets,
                                                          len(fed)))
        else:
            padded = model_wrapper.pad_prefill_inputs(
                fed[None, :], None, self.app.cte_buckets, batch_size=1)
            tok_dev, self.cache, self._telem_dev = self._insert_step(
                self.app.params, padded.input_ids, padded.position_ids,
                padded.last_token_idx, self.cache, self._telem_dev,
                jnp.asarray(slot, dtype=jnp.int32),
                sp_row, key, ad_row, emit)
            if self.draft is not None:
                self.d_cache = self._d_insert_step(
                    self.draft.params, padded.input_ids, padded.position_ids,
                    padded.last_token_idx, self.d_cache,
                    jnp.asarray(slot, dtype=jnp.int32))
        if t_i is not None and not self.paged:
            tel.request_prefill_chunk(req.request_id, len(fed), 0)
            tel.step_record(t_i, "insert", iterations=1,
                            prefill_tokens=len(fed), slots=self.num_slots,
                            request_id=req.request_id,
                            ici_bytes=self._ici_bytes(0, len(fed)))
        return int(np.asarray(tok_dev)[0])

    def _insert_eagle_host(self, req: Request, slot: int, key, fed) -> int:
        """EAGLE-mode paged insert: windowed prefix-prefill with the target's
        hiddens streamed (shifted) into the draft pool; the conditioning hidden
        carries across windows and seeds the slot's device-resident state.

        Prefix-cache SKIPPING is disabled here (cached_len forced 0): the draft
        conditioning needs the hidden of the token before each window, which a
        skipped prefix doesn't produce. Shared full blocks are simply rewritten
        with identical content (the chain hash keys tokens), so block SHARING
        still dedups memory."""
        with self._led(req, "place"):
            req.blocks, _ = self.allocator.allocate_for_prompt(fed)
        self.block_table[slot, : len(req.blocks)] = req.blocks
        sp_row = self._slot_sp[slot : slot + 1]
        max_window = self.app.cte_buckets[-1]
        h_prev = jnp.zeros((1, self.app.arch_args.hidden_size),
                           self.cfg.jax_dtype)
        start = 0
        tok_dev = None
        while start < len(fed):
            window = fed[start : start + max_window]
            padded = model_wrapper.pad_prefill_inputs(
                window[None, :], None, self.app.cte_buckets, batch_size=1)
            pos_row = np.array([start], dtype=np.int32)
            valid = np.ones((1, padded.bucket), dtype=bool)
            valid[0, len(window):] = False
            slot_map = self._slot_mapping_fn(
                self.block_table[slot : slot + 1], pos_row, padded.bucket,
                self.block_size, valid=valid)
            key, sub = jax.random.split(key)
            t_w = self.telemetry.step_start()
            final = start + len(window) >= len(fed)
            emit = np.int32(int(final and not req.generated))
            with self.telemetry.annotate("insert_window"):
                tok_dev, h_prev, self.cache, self.d_cache, self._telem_dev = \
                    self._insert_step_eagle(
                        self.app.params, self.eagle[1], padded.input_ids,
                        pos_row, padded.last_token_idx, self.cache,
                        self.d_cache, self._telem_dev,
                        jnp.asarray(self.block_table[slot : slot + 1]),
                        jnp.asarray(slot_map), sp_row, sub, h_prev, emit)
            self.telemetry.request_prefill_chunk(req.request_id, len(window),
                                                 start)
            if t_w is not None:
                self.telemetry.step_record(
                    t_w, "insert_window", iterations=1,
                    prefill_tokens=len(window), slots=self.num_slots,
                    kv_free=self.allocator.num_free,
                    kv_total=self.allocator.num_blocks,
                    request_id=req.request_id,
                    ici_bytes=self._ici_bytes(0, len(window)))
            start += len(window)
        self._h_cond = self._h_cond.at[slot].set(h_prev[0])
        return int(np.asarray(tok_dev)[0])

    def _maybe_finish(self, req: Request, emitted) -> None:
        if (len(req.generated) >= req.max_new_tokens
                or (req.eos_token_id is not None
                    and req.generated[-1] == req.eos_token_id)):
            self._finish(req)

    def _finish(self, req: Request) -> None:
        req.done = True
        self.finished[req.request_id] = req
        reason = ("truncated" if req.truncated
                  else "eos" if (req.eos_token_id is not None and req.generated
                                 and req.generated[-1] == req.eos_token_id)
                  else "length")
        self.telemetry.request_finished(req.request_id, reason,
                                        len(req.generated))
        if req.slot >= 0:
            self.active[req.slot] = None
            if self.paged:
                self._free_blocks(req, seam="finish")
                self.block_table[req.slot, :] = 0
            # reset the slot's sampling/adapter rows so all-greedy traffic
            # re-engages the fast argmax executable
            self._slot_sp[req.slot] = self._default_sp_row
            self.adapter_ids[req.slot] = 0
            req.slot = -1
