"""Multi-host launch (≈ reference `scripts/nxdi_distributed_launcher.py:29-151`).

The reference builds an mpirun command with EFA env + `NEURON_RT_ROOT_COMM_ID` and
runs one process per node; device collectives live inside the compiled NEFFs. The TPU
equivalent is `jax.distributed.initialize`: one process per host, XLA collectives over
ICI/DCN are compiled into the jitted graphs, and the only host-side coordination is the
coordinator handshake.

Usage patterns:

- **TPU pod (GKE / queued resources)**: the scheduler starts one process per host with
  the TPU env populated; call ``initialize_multihost()`` with no args — JAX infers
  coordinator/process_id from the TPU metadata.
- **Explicit cluster** (≈ mpirun --hosts): every host runs
  ``initialize_multihost(coordinator, num_processes, process_id)``.
- **Local simulation** (≈ the reference's gloo CPU mode): ``launch_local`` forks N
  processes with ``JAX_PLATFORMS=cpu`` + per-process env so SPMD logic can be
  validated without a pod (tests use the 8-device single-process mesh instead, see
  tests/conftest.py).
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import List, Optional

__all__ = ["initialize_multihost", "launch_local", "main"]


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Bring up the JAX distributed runtime (idempotent)."""
    import jax

    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except RuntimeError as e:  # already initialized
        if "already" not in str(e):
            raise


def launch_local(script: str, num_processes: int, script_args: List[str],
                 coordinator_port: int = 9911) -> int:
    """Fork ``num_processes`` CPU processes running ``script`` with the distributed
    env set (coordinator on localhost). Returns the first nonzero exit code or 0."""
    procs = []
    for rank in range(num_processes):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "TPUINF_COORDINATOR": f"localhost:{coordinator_port}",
            "TPUINF_NUM_PROCESSES": str(num_processes),
            "TPUINF_PROCESS_ID": str(rank),
        })
        procs.append(subprocess.Popen([sys.executable, script, *script_args],
                                      env=env))
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


def init_from_env() -> bool:
    """Initialize from the TPUINF_* env vars set by launch_local (no-op without)."""
    coord = os.environ.get("TPUINF_COORDINATOR")
    if not coord:
        return False
    initialize_multihost(coord, int(os.environ["TPUINF_NUM_PROCESSES"]),
                         int(os.environ["TPUINF_PROCESS_ID"]))
    return True


def main(argv: Optional[List[str]] = None) -> int:
    """CLI: ``python -m neuronx_distributed_inference_tpu.runtime.launcher
    --num-processes 2 -- script.py args...``"""
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--num-processes", type=int, required=True)
    parser.add_argument("--coordinator-port", type=int, default=9911)
    parser.add_argument("script")
    parser.add_argument("script_args", nargs="*")
    args = parser.parse_args(argv)
    return launch_local(args.script, args.num_processes, args.script_args,
                        coordinator_port=args.coordinator_port)


if __name__ == "__main__":
    raise SystemExit(main())
