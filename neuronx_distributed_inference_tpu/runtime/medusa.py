"""Medusa speculative decoding: multi-head drafting + tree-attention verification.

≈ reference `_medusa_forward` (`models/model_base.py:433-548`) + the Medusa HF loop
(`utils/hf_adapter.py:798-925`) + medusa head modules (`models/llama/modeling_llama.py:1304`
ResBlock). TPU redesign:

- Medusa heads are a stacked pytree ``{"w": (M, H, H), "b": (M, H), "out": (M, H, V)}``
  applied as one batched einsum (ResBlock ``h + silu(h @ w + b)`` then the head's
  lm_head) — M heads cost one fused matmul pair, not M module calls.
- Each step is ONE verify dispatch: the candidate token tree (assembled host-side from
  the previous step's per-head top-k) runs through `decode_forward` in tree mode
  (ancestor mask + depth positions, `models/base.py`), which returns the target argmax
  AND every node's medusa-head top-k in the same graph, so the next tree needs no extra
  device call.
- Acceptance walks the tree on the host (≈ the reference's CPU-side medusa acceptance)
  and a second small dispatch compacts accepted KV slots
  (`modules/kvcache.compact_decode_slots` ≈ accepted-index KV gather/scatter,
  `kv_cache_manager.py:266-322`).

Greedy-only, like the reference's medusa path. The exactness guarantee holds regardless
of head quality: committed tokens are always the target's argmax in context, so output
== the base model's plain greedy decode.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.registry import audited_jit
from ..models import base as model_base
from ..modules import autobucketing, kvcache
from ..modules.token_tree import DEFAULT_TREE_PATHS, TokenTree
from . import model_wrapper
from .speculation import SpecGenerateOutput, assemble_spec_output, commit_row

MedusaParams = Dict[str, jnp.ndarray]


def init_medusa_params(num_heads: int, hidden: int, vocab: int, key: jax.Array,
                       dtype=jnp.bfloat16) -> MedusaParams:
    k1, k2 = jax.random.split(key)
    scale = 0.02
    return {
        "w": (jax.random.normal(k1, (num_heads, hidden, hidden), jnp.float32)
              * scale).astype(dtype),
        "b": jnp.zeros((num_heads, hidden), dtype=dtype),
        "out": (jax.random.normal(k2, (num_heads, hidden, vocab), jnp.float32)
                * scale).astype(dtype),
    }


def convert_medusa_state_dict(state_dict: Dict[str, np.ndarray], num_heads: int
                              ) -> Dict[str, np.ndarray]:
    """HF medusa checkpoint (``medusa_head.{i}.0.linear.{weight,bias}`` ResBlock +
    ``medusa_head.{i}.1.weight`` head) -> stacked pytree (weights transposed to
    (in, out) per this repo's layout)."""
    w, b, out = [], [], []
    for i in range(num_heads):
        w.append(np.ascontiguousarray(
            state_dict[f"medusa_head.{i}.0.linear.weight"].T))
        b.append(state_dict[f"medusa_head.{i}.0.linear.bias"])
        out.append(np.ascontiguousarray(state_dict[f"medusa_head.{i}.1.weight"].T))
    return {"w": np.stack(w), "b": np.stack(b), "out": np.stack(out)}


def _head_topk(medusa_params: MedusaParams, h: jnp.ndarray, k: int) -> jnp.ndarray:
    """Per-head top-k candidate ids from hidden states.

    h (..., H) -> (..., M, k) int32. ResBlock then head lm_head, batched over heads.
    """
    w, b, out = medusa_params["w"], medusa_params["b"], medusa_params["out"]
    pre = jnp.einsum("...h,mhk->...mk", h, w) + b          # (..., M, H)
    res = h[..., None, :] + jax.nn.silu(pre)
    logits = jnp.einsum("...mh,mhv->...mv", res, out)      # (..., M, V)
    _, idx = jax.lax.top_k(logits, k)
    return idx.astype(jnp.int32)


class MedusaModel:
    """Owns a base `TpuModelForCausalLM` plus medusa heads and runs tree decoding."""

    def __init__(self, app, num_medusa_heads: int = 4,
                 tree: Optional[TokenTree] = None):
        self.app = app
        self.num_heads = num_medusa_heads
        self.tree = tree if tree is not None else TokenTree.from_paths(
            [p for p in DEFAULT_TREE_PATHS if len(p) <= num_medusa_heads])
        if self.tree.max_depth > num_medusa_heads:
            raise ValueError(f"tree depth {self.tree.max_depth} exceeds "
                             f"{num_medusa_heads} medusa heads")
        self.medusa_params: Optional[MedusaParams] = None
        self._build_steps()

    def load_random_heads(self, seed: int = 0) -> None:
        a = self.app.arch_args
        self.medusa_params = init_medusa_params(
            self.num_heads, a.hidden_size, a.vocab_size, jax.random.PRNGKey(seed),
            dtype=self.app.tpu_config.jax_dtype)

    def load_heads(self, state_dict: Dict[str, np.ndarray]) -> None:
        host = convert_medusa_state_dict(state_dict, self.num_heads)
        dtype = self.app.tpu_config.jax_dtype
        self.medusa_params = {k: jnp.asarray(v).astype(dtype)
                              for k, v in host.items()}

    # ------------------------------------------------------------------ device steps
    def _build_steps(self) -> None:
        app = self.app
        args = app.arch_args
        mesh, rules = app.mesh, app.sharding_rules
        tree = self.tree
        kb = tree.max_branch
        precision = ("highest" if app.tpu_config.dtype == "float32" else "default")
        depths = tree.depths
        ancestor = tree.ancestor_mask

        def _prefill(params, medusa_params, input_ids, position_ids, last_token_idx,
                     cache):
            with jax.default_matmul_precision(precision):
                logits, cache, h = model_base.prefill_forward(
                    params, args, input_ids, position_ids, last_token_idx, cache,
                    mesh=mesh, rules=rules, return_hidden=True)
                root = jnp.argmax(logits, axis=-1).astype(jnp.int32)       # (B,)
                h_last = jnp.take_along_axis(
                    h, last_token_idx[:, None, None], axis=1)[:, 0]        # (B, H)
                topk = _head_topk(medusa_params, h_last, kb)               # (B, M, kb)
            return root, topk, cache

        def _verify(params, medusa_params, tree_tokens, positions, cache,
                    decode_bucket):
            with jax.default_matmul_precision(precision):
                logits, cache, h = model_base.decode_forward(
                    params, args, tree_tokens, positions, cache, decode_bucket,
                    mesh=mesh, rules=rules, tree=(depths, ancestor),
                    return_hidden=True)
                target = jnp.argmax(logits, axis=-1).astype(jnp.int32)     # (B, N)
                topk = _head_topk(medusa_params, h, kb)                    # (B,N,M,kb)
            return target, topk, cache

        self._prefill_step = audited_jit(
            _prefill, kind="medusa.prefill", cache_args=("cache",))
        self._verify_step = audited_jit(
            _verify, kind="medusa.verify", cache_args=("cache",),
            static_argnames=("decode_bucket",))
        self._compact_step = audited_jit(
            kvcache.compact_decode_slots, kind="medusa.compact",
            cache_args=("cache",))

    # ------------------------------------------------------------------ generate
    def generate(
        self,
        input_ids: np.ndarray,
        attention_mask: Optional[np.ndarray] = None,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        pad_token_id: int = 0,
    ) -> SpecGenerateOutput:
        app, tree = self.app, self.tree
        cfg = app.tpu_config
        if app.params is None:
            raise RuntimeError("load base weights before generate")
        if self.medusa_params is None:
            raise RuntimeError("load medusa heads before generate")
        input_ids = model_wrapper.to_int32(input_ids)
        b = input_ids.shape[0]
        compiled_b = cfg.max_batch_size
        n_nodes = tree.num_nodes
        max_commit = tree.max_depth + 1      # accepted path + bonus per step

        padded = model_wrapper.pad_prefill_inputs(
            input_ids, attention_mask, app.cte_buckets, pad_token_id=pad_token_id,
            batch_size=compiled_b)
        app.reset_cache()

        t_start = time.perf_counter()
        root_dev, topk_dev, app.kv_cache = self._prefill_step(
            app.params, self.medusa_params, padded.input_ids, padded.position_ids,
            padded.last_token_idx, app.kv_cache)
        root = np.asarray(root_dev).copy()   # (B,)
        topk = np.asarray(topk_dev).copy()   # (B, M, kb)
        ttft = time.perf_counter() - t_start

        committed: List[List[int]] = [[int(root[i])] for i in range(b)]
        done = np.zeros((compiled_b,), dtype=bool)
        done[b:] = True
        if eos_token_id is not None:
            done[:b] |= root[:b] == eos_token_id
        positions = padded.true_lengths.astype(np.int32).copy()
        accept_hist = np.zeros((max_commit,), dtype=np.int64)
        steps = 0

        while not all(len(c) >= max_new_tokens or done[i]
                      for i, c in enumerate(committed)):
            max_pos = int(positions.max())
            if max_pos + n_nodes >= cfg.seq_len:
                break
            tree_tokens = tree.assemble_tokens(root, topk)           # (B, N)
            bucket = autobucketing.select_bucket(app.tkg_buckets, max_pos + n_nodes)
            target_dev, topk_all_dev, app.kv_cache = self._verify_step(
                app.params, self.medusa_params, jnp.asarray(tree_tokens),
                jnp.asarray(positions), app.kv_cache, decode_bucket=bucket)
            target = np.asarray(target_dev)          # (B, N)
            topk_all = np.asarray(topk_all_dev)      # (B, N, M, kb)
            steps += 1

            # host-side tree walk + KV compaction indices; dst row j receives the
            # j-th kept node (root stays at its slot, accepted nodes pack after it)
            src_slots = np.zeros((compiled_b, max_commit), dtype=np.int32)
            dst_start = positions.copy()             # pre-update root slot per row
            for i in range(compiled_b):
                if done[i]:
                    src_slots[i, :] = positions[i]   # harmless self-copy
                    continue
                accepted, bonus = tree.walk_accept(tree_tokens[i], target[i])
                take_nodes = [0] + accepted          # root stays in place
                for j in range(max_commit):
                    src_slots[i, j] = positions[i] + (
                        take_nodes[j] if j < len(take_nodes) else take_nodes[-1])
                n_acc = len(accepted)
                if i < b:
                    accept_hist[n_acc] += 1
                    step_toks = [int(tree_tokens[i, a]) for a in accepted] + [bonus]
                    done[i] = commit_row(committed[i], step_toks, eos_token_id,
                                         max_new_tokens)
                    if not done[i]:
                        last_node = accepted[-1] if accepted else 0
                        topk[i] = topk_all[i, last_node]
                        root[i] = bonus
                        positions[i] += n_acc + 1
            app.kv_cache = self._compact_step(
                app.kv_cache, jnp.asarray(src_slots), jnp.asarray(dst_start))

        return assemble_spec_output(committed, padded, b, pad_token_id, accept_hist,
                                    steps, ttft)
