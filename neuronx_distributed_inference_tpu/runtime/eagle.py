"""EAGLE speculative decoding runtime: fused hidden-conditioned draft + target verify.

≈ reference EAGLE flow (`NeuronFusedSpecModel._eagle_context_encoding_forward`
`models/model_base.py:2075-2134`, `_eagle_token_gen_forward` :2559-2797): the draft is a
shallow decoder whose layer-0 input fuses the token embedding with the target's hidden
state at the previous position (see `models/eagle.py`). Per fused step the draft
autoregressively proposes ``k-1`` candidates (substituting its own output hidden for the
unavailable target hidden — the EAGLE-1 approximation), then the target verifies all
candidates in one wide decode that also returns its hidden states; the hidden at the
last accepted position becomes the next step's conditioning, replacing the reference's
`HiddenStateRollingBuffer` (`modules/eagle/hidden_state.py`) with explicit jit-carried
state.

Greedy acceptance only (exact: output always equals the target's plain greedy decode).
"""

from __future__ import annotations

import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.registry import audited_jit
from ..utils import profiling
from ..models import base as model_base
from ..models import eagle as eagle_lib
from ..models.base import ModelArchArgs
from ..modules import autobucketing, kvcache
from . import model_wrapper
from . import speculation as spec_lib
from .speculation import (SpecGenerateOutput, assemble_spec_output,
                          chunk_advance, quantize_chunk_iters, replay_chunk)


def draft_args_from_target(target_args: ModelArchArgs, num_layers: int = 1,
                           num_heads: Optional[int] = None,
                           num_kv_heads: Optional[int] = None,
                           intermediate_size: Optional[int] = None) -> ModelArchArgs:
    """Draft geometry: target's hidden/vocab with a shallow stack.

    Target-specific arch flags (biases, qk/sandwich norms, sinks, layer patterns)
    are reset to the llama-style defaults the EAGLE draft checkpoints actually use
    (`convert_eagle_state_dict` emits only llama-shaped keys); inheriting e.g. a
    qwen2 target's attention_bias would make the fused step trace look up bias
    params the draft pytree doesn't have."""
    import dataclasses

    return dataclasses.replace(
        target_args,
        num_layers=num_layers,
        num_heads=num_heads or target_args.num_heads,
        num_kv_heads=num_kv_heads or target_args.num_kv_heads,
        intermediate_size=intermediate_size or target_args.intermediate_size,
        moe=None, lora=None,
        attention_bias=False, o_bias=False, attn_sinks=False, qk_norm=False,
        sandwich_norms=False, zero_centered_norms=False,
        layer_pattern=None, local_rope_theta=None, sliding_window=None,
    )


class EagleSpeculativeModel:
    """Owns a target `TpuModelForCausalLM` + EAGLE draft params; runs fused spec."""

    def __init__(self, target, draft_args: ModelArchArgs, speculation_length: int,
                 spec_chunk: int = 8):
        if speculation_length < 2:
            raise ValueError("speculation_length must be >= 2")
        if draft_args.hidden_size != target.arch_args.hidden_size:
            raise ValueError("EAGLE draft must share the target's hidden size")
        self.target = target
        self.draft_args = draft_args
        self.k = speculation_length
        # fused iterations per device dispatch (positions / conditioning
        # hiddens / eos-stops advance in-graph; the host replays the exact
        # commit rules after the sync — same discipline as the CB EAGLE chunk)
        self.spec_chunk = max(1, spec_chunk)
        self.draft_params = None
        self.draft_cache = None
        spec_lib.attach_spec_metrics(self, self.k, "eagle chain")
        self._build_steps()

    def load_random_draft(self, seed: int = 0) -> None:
        self.draft_params = eagle_lib.init_eagle_params(
            self.draft_args, jax.random.PRNGKey(seed),
            dtype=self.target.tpu_config.jax_dtype,
            inv_freq=self.target.inv_freq_from_config(self.target.config))

    def load_draft(self, state_dict) -> None:
        host = eagle_lib.convert_eagle_state_dict(
            state_dict, self.draft_args,
            self.target.inv_freq_from_config(self.target.config))
        dtype = self.target.tpu_config.jax_dtype
        self.draft_params = jax.tree.map(
            lambda x: jnp.asarray(np.asarray(x)).astype(dtype)
            if np.asarray(x).dtype.kind == "f" else jnp.asarray(x), host)
        self.draft_params["rope_inv_freq"] = jnp.asarray(
            np.asarray(host["rope_inv_freq"]), jnp.float32)

    def _draft_cache_spec(self) -> kvcache.KVCacheSpec:
        a = self.draft_args
        cfg = self.target.tpu_config
        return kvcache.KVCacheSpec(
            num_layers=a.num_layers, batch_size=cfg.max_batch_size,
            num_kv_heads=a.num_kv_heads, max_seq_len=cfg.seq_len,
            head_dim=a.head_dim, dtype=cfg.kv_cache_jax_dtype)

    # ------------------------------------------------------------------ device steps
    def _build_steps(self) -> None:
        t = self.target
        t_args, d_args = t.arch_args, self.draft_args
        mesh, rules = t.mesh, t.sharding_rules
        k = self.k
        precision = "highest" if t.tpu_config.dtype == "float32" else "default"
        t_kernel = {"use_kernel": True} if t._use_decode_kernel() else {}

        def _prefill(t_params, d_params, input_ids, position_ids, last_token_idx,
                     t_cache, d_cache):
            with jax.default_matmul_precision(precision):
                logits, t_cache, h_full = model_base.prefill_forward(
                    t_params, t_args, input_ids, position_ids, last_token_idx,
                    t_cache, mesh=mesh, rules=rules, return_hidden=True)
                tok0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                # draft conditioning: target hidden of the previous position
                cond = jnp.concatenate(
                    [jnp.zeros_like(h_full[:, :1]), h_full[:, :-1]], axis=1)
                d_cache = eagle_lib.eagle_prefill_forward(
                    d_params, t_params, d_args, input_ids, cond, position_ids,
                    last_token_idx, d_cache, mesh=mesh, rules=rules)
                h_last = jnp.take_along_axis(
                    h_full, last_token_idx[:, None, None], axis=1)[:, 0]
            return tok0, h_last, t_cache, d_cache

        def _iter(t_params, d_params, last_tok, h_cond, positions, t_cache,
                  d_cache, decode_bucket):
            """One fused EAGLE iteration: k-1 draft proposals + one KV-only
            draft step (skip_logits — the k-th proposal is discarded and the
            draft head is the TARGET's full lm_head) + one target verify."""
            def draft_body(carry, _):
                tok, h, pos, cache = carry
                with jax.default_matmul_precision(precision):
                    logits, h_d, cache = eagle_lib.eagle_decode_forward(
                        d_params, t_params, d_args, tok[:, None], h[:, None, :],
                        pos, cache, decode_bucket, mesh=mesh, rules=rules)
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return (nxt, h_d[:, -1], pos + 1, cache), nxt

            (d_last, d_h, d_pos, d_cache), draft_toks = jax.lax.scan(
                draft_body, (last_tok, h_cond, positions, d_cache), None,
                length=k - 1)
            draft_toks = draft_toks.T                                # (B, K-1)
            with jax.default_matmul_precision(precision):
                _, _, d_cache = eagle_lib.eagle_decode_forward(
                    d_params, t_params, d_args, d_last[:, None],
                    d_h[:, None, :], d_pos, d_cache, decode_bucket,
                    mesh=mesh, rules=rules, skip_logits=True)

            target_in = jnp.concatenate([last_tok[:, None], draft_toks], axis=1)
            with jax.default_matmul_precision(precision):
                t_logits, t_cache, t_h = model_base.decode_forward(
                    t_params, t_args, target_in, positions, t_cache, decode_bucket,
                    mesh=mesh, rules=rules, return_hidden=True,
                    **t_kernel)                                   # (B, K, V/H)
            t_toks = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
            matches = draft_toks == t_toks[:, :-1]
            n = jnp.cumprod(matches.astype(jnp.int32), axis=1).sum(axis=1)
            # conditioning hidden for the next step: target hidden at input slot n
            h_next = jnp.take_along_axis(
                t_h, n[:, None, None], axis=1)[:, 0]                 # (B, H)
            return t_toks, n.astype(jnp.int32), h_next, t_cache, d_cache

        def _chunk(t_params, d_params, tok0, h0, positions0, alive0, t_cache,
                   d_cache, eos_ids, decode_bucket, num_iters):
            """``num_iters`` fused EAGLE iterations in ONE dispatch: per-row
            positions AND conditioning hiddens advance in-graph by each row's
            accepted length; a row whose committed window contains its eos
            stops advancing (host replays the exact stop rules)."""
            def one_iter(carry, _):
                tok, h, pos, alive, t_cache, d_cache = carry
                t_toks, n, h_next, t_cache, d_cache = _iter(
                    t_params, d_params, tok, h, pos, t_cache, d_cache,
                    decode_bucket)
                take, new_tok, alive_next = chunk_advance(alive, t_toks, n,
                                                          eos_ids)
                tok = jnp.where(take > 0, new_tok, tok)
                h = jnp.where((take > 0)[:, None], h_next, h)
                pos = pos + take
                return (tok, h, pos, alive_next, t_cache, d_cache), (t_toks, n)

            (_, h_out, _, _, t_cache, d_cache), (outs, ns) = jax.lax.scan(
                one_iter, (tok0, h0, positions0, alive0, t_cache, d_cache),
                None, length=num_iters)
            return outs, ns, h_out, t_cache, d_cache

        self._prefill_step = audited_jit(
            _prefill, kind="eagle.prefill", cache_args=("t_cache", "d_cache"))
        self._spec_chunk = audited_jit(
            _chunk, kind="eagle.chunk", cache_args=("t_cache", "d_cache"),
            static_argnames=("decode_bucket", "num_iters"),
            steps_arg="num_iters")

    # ------------------------------------------------------------------ generate
    def generate(
        self,
        input_ids: np.ndarray,
        attention_mask: Optional[np.ndarray] = None,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        pad_token_id: int = 0,
    ) -> SpecGenerateOutput:
        target = self.target
        cfg = target.tpu_config
        if target.params is None or self.draft_params is None:
            raise RuntimeError("load target weights and draft params before generate")
        input_ids = model_wrapper.to_int32(input_ids)
        b = input_ids.shape[0]
        compiled_b = cfg.max_batch_size

        padded = model_wrapper.pad_prefill_inputs(
            input_ids, attention_mask, target.cte_buckets, pad_token_id=pad_token_id,
            batch_size=compiled_b)
        target.reset_cache()
        from ..parallel.sharding import named_sharding

        sharding = named_sharding(target.mesh, kvcache.CACHE_LOGICAL,
                               target.sharding_rules)
        self.draft_cache = jax.tree.map(
            lambda x: jax.device_put(x, sharding),
            kvcache.init_cache(self._draft_cache_spec()))

        t_start = time.perf_counter()
        with profiling.annotate("dispatch:eagle.prefill"):
            tok0_dev, h_dev, target.kv_cache, self.draft_cache = \
                self._prefill_step(
                    target.params, self.draft_params, padded.input_ids,
                    padded.position_ids, padded.last_token_idx,
                    target.kv_cache, self.draft_cache)
        tok0 = np.asarray(tok0_dev)
        ttft = time.perf_counter() - t_start

        committed: List[List[int]] = [[int(tok0[i])] for i in range(b)]
        done = np.zeros((compiled_b,), dtype=bool)
        done[b:] = True
        if eos_token_id is not None:
            done[:b] |= tok0[:b] == eos_token_id
        positions = padded.true_lengths.astype(np.int32).copy()
        last_tok = tok0.astype(np.int32)
        h_cond = h_dev                         # (B, H) stays device-resident
        accept_hist = np.zeros((self.k,), dtype=np.int64)
        steps = 0

        eos_ids = np.full((compiled_b,),
                          -1 if eos_token_id is None else eos_token_id,
                          dtype=np.int32)
        while not all(len(c) >= max_new_tokens or done[i]
                      for i, c in enumerate(committed)):
            live_pos = [int(positions[i]) for i, c in enumerate(committed)
                        if not done[i] and len(c) < max_new_tokens]
            max_pos = max(live_pos)
            if max_pos + self.k >= cfg.seq_len:
                break
            room = (cfg.seq_len - 1 - max_pos) // self.k
            remaining = min(max_new_tokens - len(c)
                            for i, c in enumerate(committed)
                            if not done[i] and len(c) < max_new_tokens)
            iters = quantize_chunk_iters(self.spec_chunk, room, remaining)
            bucket = autobucketing.select_bucket(target.tkg_buckets,
                                                 max_pos + self.k * iters)
            alive0 = np.array([i < b and not done[i]
                               and len(committed[i]) < max_new_tokens
                               for i in range(compiled_b)])
            with profiling.annotate("dispatch:eagle.chunk"):
                out_dev, n_dev, h_cond, target.kv_cache, self.draft_cache = \
                    self._spec_chunk(
                        target.params, self.draft_params,
                        jnp.asarray(last_tok), h_cond,
                        jnp.asarray(positions), jnp.asarray(alive0),
                        target.kv_cache, self.draft_cache,
                        jnp.asarray(eos_ids), decode_bucket=bucket,
                        num_iters=iters)
            out = np.asarray(out_dev)    # (iters, B, K)
            n = np.asarray(n_dev)        # (iters, B)
            steps += replay_chunk(out, n, committed, done, positions, last_tok,
                                  accept_hist, eos_token_id, max_new_tokens)

        spec_lib.record_spec_metrics(self, accept_hist, steps)
        return assemble_spec_output(committed, padded, b, pad_token_id, accept_hist,
                                    steps, ttft)
