"""Host-side input marshaling: bucket selection and padding.

≈ reference `models/model_wrapper.py` (`pad_inputs` :725-824, `get_target_bucket`
:826-916, int64→int32 :1334). On TPU the "compiled graph per bucket" is `jax.jit`'s
shape-keyed cache plus an explicit static ``decode_bucket`` argument; this module keeps
the same observable behavior: first-fit bucket choice, right-padding of inputs, batch
padding up to the compiled batch size.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..modules import autobucketing


@dataclass
class PaddedPrefill:
    input_ids: np.ndarray      # (B, S_bucket) int32
    position_ids: np.ndarray   # (B, S_bucket) int32
    last_token_idx: np.ndarray  # (B,) int32
    true_lengths: np.ndarray   # (B,) int32
    bucket: int


def pad_prefill_inputs(
    input_ids: np.ndarray,
    attention_mask: Optional[np.ndarray],
    buckets: Sequence[int],
    pad_token_id: int = 0,
    batch_size: Optional[int] = None,
    allow_longer: bool = False,
) -> PaddedPrefill:
    """Right-pad (B, S) int inputs to the first-fit sequence bucket.

    ``attention_mask`` (B, S) of 0/1 marks real tokens (right-padded). Inputs arriving
    left-padded are normalized to right padding, like the reference's CTE path
    (`model_wrapper.py:725-824`).

    ``allow_longer``: a prompt longer than the largest bucket pads to the next
    multiple of the largest bucket instead of raising — the layout for dense
    windowed (chunked) prefill, which slices the result into largest-bucket windows.
    """
    input_ids = np.asarray(input_ids)
    if input_ids.ndim != 2:
        raise ValueError("input_ids must be (batch, seq)")
    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = np.ones((b, s), dtype=np.int32)
    attention_mask = np.asarray(attention_mask).astype(np.int32)
    true_lengths = attention_mask.sum(axis=1).astype(np.int32)
    if np.any(true_lengths == 0):
        raise ValueError("each sequence needs at least one real token")

    max_len = int(true_lengths.max())
    if allow_longer and max_len > buckets[-1]:
        w = buckets[-1]
        bucket = -(-max_len // w) * w
    else:
        bucket = autobucketing.select_bucket(buckets, max_len)
    out_b = batch_size or b
    if b > out_b:
        raise ValueError(f"batch {b} exceeds compiled batch size {out_b}")

    ids = np.full((out_b, bucket), pad_token_id, dtype=np.int32)
    for i in range(b):
        row = input_ids[i][attention_mask[i].astype(bool)]
        ids[i, : row.shape[0]] = row
    # batch-pad rows replicate row 0 (harmless work, keeps shapes static
    # ≈ `model_wrapper.py:569-698` batch padding)
    for i in range(b, out_b):
        ids[i] = ids[0]

    positions = np.broadcast_to(np.arange(bucket, dtype=np.int32), (out_b, bucket)).copy()
    lengths_padded = np.ones((out_b,), dtype=np.int32)
    lengths_padded[:b] = true_lengths
    last_idx = np.maximum(lengths_padded - 1, 0).astype(np.int32)
    return PaddedPrefill(ids, positions, last_idx, lengths_padded, bucket)


def decode_bucket_for_position(buckets: Sequence[int], max_position: int) -> int:
    """Smallest token-generation bucket covering cache index ``max_position``."""
    return autobucketing.select_bucket(buckets, max_position + 1)


def to_int32(x: np.ndarray) -> np.ndarray:
    """≈ convert_int64_to_int32 (`model_wrapper.py:1334`)."""
    x = np.asarray(x)
    return x.astype(np.int32) if x.dtype in (np.int64, np.uint64) else x
