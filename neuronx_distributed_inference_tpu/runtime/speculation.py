"""Fused speculative decoding: draft + target in ONE jitted device step.

≈ reference `NeuronFusedSpecModel` (`models/model_base.py:1641`): context encoding runs
target then draft over the same prompt in one flow (`_context_encoding_forward` :1792);
each decode step loops the draft model ``speculation_length - 1`` times then verifies all
candidates with the target in a single wide call (`_token_gen_forward` :1854-1971);
acceptance follows the standard speculative-sampling rules — exact token match for
greedy, rejection sampling with the residual distribution ``norm(max(p_t - p_d, 0))``
for multinomial (acceptance math ≈ `model_base.py:1706-1790`).

TPU redesign:

- The draft loop is a `lax.scan` *inside* the same jitted function as the target verify,
  so one fused step = one device dispatch (the reference fuses draft+target into one
  NEFF for the same reason).
- Acceptance runs **on device** (the reference computes accepted length on CPU in
  `utils/hf_adapter.py:494` `_fused_assisted_decoding`); the host only receives
  ``(candidate_tokens (B, K), num_valid (B,))`` and appends — no logits ever leave HBM.
- KV discipline: candidates are written into both caches at ``[pos, pos+K)``; after an
  acceptance of ``n`` tokens the next step starts at ``pos + n + 1`` and its writes cover
  the entire stale region before any read (decode masks are position-bounded), so
  rejected-token cache entries never need rollback — same trick as the reference's
  position-masked cache reads. The PAGED serving variant (the CB spec chunk,
  `runtime/continuous_batching.py`) rides the FUSED append+attend kernel for both the
  draft chain (q_len 1) and the wide verify (q_len K <= 8): the fresh window attends
  from VMEM operands and committed blocks mask ``kv_pos < pos``, so the stale region is
  never even read — the position-masking discipline moves into the kernel
  (ops/paged_decode.fused_paged_decode_stacked).

Per step, the target emits between 1 and ``speculation_length`` committed tokens:
``n`` accepted drafts plus one correction/bonus token.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.registry import audited_jit
from ..utils import profiling
from ..config import OnDeviceSamplingConfig
from ..models import base as model_base
from ..modules import autobucketing
from ..ops import sampling as sampling_ops
from ..utils import benchmark as benchmark_lib
from . import model_wrapper


@dataclass
class SpecGenerateOutput:
    sequences: np.ndarray             # (B, prompt + generated)
    tokens: np.ndarray                # (B, generated) right-padded with pad_token_id
    num_generated: np.ndarray         # (B,) actual generated count per row
    acceptance_counts: np.ndarray     # histogram over tokens-emitted-per-step (len K)
    steps: int = 0
    ttft_s: Optional[float] = None
    # per-step (B, K-1, V) draft logits when requested via capture_draft_logits
    draft_logits: Optional[List[np.ndarray]] = None


def quantize_chunk_iters(spec_chunk: int, *clamps: int) -> int:
    """Iteration count for the next fused chunk: ``spec_chunk`` when no clamp
    binds, else the largest power of two <= the tightest clamp.

    ``num_iters`` is a STATIC jit argument — every distinct value compiles a
    fresh executable of the whole draft+verify chunk graph. Near the tail of
    a generation the seq-room / remaining-budget clamps would otherwise sweep
    arbitrary values (31, 14, 5, 2, ...), each paying a full compile;
    restricting the set to {spec_chunk} ∪ powers-of-two bounds the executables
    at ~log2(spec_chunk) for a few wasted-iteration percent."""
    cap = min(clamps)
    if cap >= spec_chunk:
        return max(1, spec_chunk)
    if cap <= 1:
        return 1
    return 1 << (cap.bit_length() - 1)


def chunk_advance(alive, out_toks, n, eos_ids):
    """Shared in-graph advance for one fused-speculation iteration.

    Given the iteration's committed-window tokens ``out_toks`` (B, W) and
    accepted-draft counts ``n`` (B,), returns ``(take, new_tok, alive)``:
    rows take ``n + 1`` tokens while alive (0 when frozen), the new last
    committed token, and the alive mask with eos-hitting rows frozen — the
    device-side mirror of the host's commit_row stop rule. Every speculative
    runtime's chunk body (fused / EAGLE / EAGLE3 / CB) advances through this
    one helper so the in-graph rule cannot drift from the host replay."""
    width = out_toks.shape[1]
    take = jnp.where(alive, n + 1, 0)
    new_tok = jnp.take_along_axis(
        out_toks, jnp.maximum(take - 1, 0)[:, None], axis=1)[:, 0]
    win = jnp.arange(width, dtype=jnp.int32)[None, :] < take[:, None]
    hit_eos = jnp.any(win & (out_toks == eos_ids[:, None]), axis=1)
    return take, new_tok, alive & ~hit_eos


def replay_chunk(out, n, committed: List[List[int]], done, positions, last_tok,
                 accept_hist, eos_token_id: Optional[int],
                 max_new_tokens: int) -> int:
    """Exact host replay of one chunk's commits (the authority over device
    state): folds the per-iteration outputs ``out`` (iters, B, W) / ``n``
    (iters, B) into the committed lists via commit_row, advancing positions /
    last_tok for rows that stay live. Returns the number of iterations that
    still had live rows (tail iterations past everyone's stop ran — the
    device cannot know acceptance in advance — but committed nothing)."""
    b = len(committed)
    used_iters = 0
    for it in range(out.shape[0]):
        used = False
        for i in range(b):
            if done[i] or len(committed[i]) >= max_new_tokens:
                continue
            used = True
            take = int(n[it, i]) + 1
            accept_hist[take - 1] += 1
            done[i] = commit_row(committed[i], out[it, i, :take],
                                 eos_token_id, max_new_tokens)
            if not done[i]:
                positions[i] += take
                last_tok[i] = out[it, i, take - 1]
        used_iters += int(used)
    return used_iters


def commit_row(committed_i: List[int], toks, eos_token_id: Optional[int],
               max_new_tokens: int) -> bool:
    """Append a step's committed tokens to one row; True if the row is now done.

    Shared by every speculative runtime (fused / EAGLE / Medusa): stops at
    max_new_tokens or at the first EOS (which is kept as the row's last token).
    """
    for t in toks:
        if len(committed_i) >= max_new_tokens:
            return True
        committed_i.append(int(t))
        if eos_token_id is not None and int(t) == eos_token_id:
            return True
    return len(committed_i) >= max_new_tokens


def attach_spec_metrics(engine, k: int, kind: str) -> None:
    """Give a speculative engine a cumulative acceptance registry
    (utils/metrics.py): a fixed-bucket histogram over tokens-committed-per-
    verify-step plus step/token counters, accumulated ACROSS generate()
    calls (the per-call histogram stays on SpecGenerateOutput). Shared by
    FusedSpeculativeModel / EagleSpeculativeModel / Eagle3SpeculativeModel."""
    from ..utils import metrics as metrics_lib

    engine.metrics = metrics_lib.MetricsRegistry()
    engine._m_accept = engine.metrics.histogram(
        "spec_acceptance_tokens", buckets=list(range(1, k + 1)),
        help=f"tokens committed per verify step ({kind})")
    engine._m_steps = engine.metrics.counter(
        "spec_steps_total", "verify steps run across generate() calls")
    engine._m_tokens = engine.metrics.counter(
        "spec_tokens_committed_total", "tokens committed by acceptance")


def record_spec_metrics(engine, accept_hist: np.ndarray, steps: int) -> None:
    """Fold one generate() call's acceptance histogram into the engine's
    cumulative registry."""
    h = engine._m_accept
    h.counts[: accept_hist.size] += accept_hist
    tokens = int((accept_hist * (np.arange(accept_hist.size) + 1)).sum())
    h.sum += float(tokens)
    engine._m_steps.inc(steps)
    engine._m_tokens.inc(tokens)


def spec_accept_mean(engine) -> float:
    """Cumulative mean committed tokens per verify step (the one shared
    definition — utils/metrics.acceptance_mean over the engine histogram)."""
    from ..utils import metrics as metrics_lib

    return metrics_lib.acceptance_mean(engine._m_accept.counts[:-1])


def assemble_spec_output(committed: List[List[int]], padded, b: int,
                         pad_token_id: int, accept_hist: np.ndarray, steps: int,
                         ttft: Optional[float]) -> SpecGenerateOutput:
    """Pack per-row committed token lists into the SpecGenerateOutput arrays."""
    num_gen = np.array([len(c) for c in committed], dtype=np.int32)
    width = int(num_gen.max()) if b else 0
    tokens = np.full((b, width), pad_token_id, dtype=np.int32)
    for i in range(b):
        tokens[i, : num_gen[i]] = committed[i]
    prompt_lens = padded.true_lengths[:b]
    max_len = (int(prompt_lens.max()) if b else 0) + width
    sequences = np.full((b, max_len), pad_token_id, dtype=np.int32)
    for i in range(b):
        pl = int(prompt_lens[i])
        sequences[i, :pl] = padded.input_ids[i, :pl]
        sequences[i, pl : pl + num_gen[i]] = committed[i]
    return SpecGenerateOutput(sequences=sequences, tokens=tokens,
                              num_generated=num_gen,
                              acceptance_counts=accept_hist, steps=steps,
                              ttft_s=ttft)


def speculative_accept(draft_toks, draft_logits, t_logits, sampling_params, key,
                       odsc, greedy: bool, vocab: int):
    """Speculative acceptance for one verify window — shared by the whole-batch
    fused flow and the continuous-batching serving path.

    draft_toks (B, K-1) int32, draft_logits (B, K-1, V), t_logits (B, K, V).
    Greedy: exact token match (`n` = longest accepted prefix). Multinomial:
    rejection sampling — accept d_j with prob min(1, p_t(d_j)/p_d(d_j)), resample
    the first rejection from norm(max(p_t - p_d, 0)) (acceptance math ≈ reference
    `model_base.py:1706-1790`). Returns (out_toks (B, K), n (B,)):
    out_toks[:, :n+1] are the committed tokens (n accepted drafts + one
    correction/bonus)."""
    k = t_logits.shape[1]
    if greedy:
        t_toks = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)      # (B, K)
        matches = draft_toks == t_toks[:, :-1]                        # (B, K-1)
        n = jnp.cumprod(matches.astype(jnp.int32), axis=1).sum(axis=1)
        return t_toks, n.astype(jnp.int32)
    key_acc, key_res, key_bonus = jax.random.split(key, 3)
    sp = sampling_params[:, None, :]      # broadcast over the K-1 positions
    pt_w, pt_idx = sampling_ops.window_probs(t_logits[:, :-1], sp, odsc)
    pd_w, pd_idx = sampling_ops.window_probs(draft_logits, sp, odsc)
    p_t = sampling_ops.scatter_to_vocab(pt_w, pt_idx, vocab)          # (B,K-1,V)
    p_d = sampling_ops.scatter_to_vocab(pd_w, pd_idx, vocab)
    d_sel = draft_toks[..., None]
    pt_d = jnp.take_along_axis(p_t, d_sel, axis=-1)[..., 0]           # (B, K-1)
    pd_d = jnp.take_along_axis(p_d, d_sel, axis=-1)[..., 0]
    u = jax.random.uniform(key_acc, pt_d.shape, dtype=jnp.float32)
    accept = u < jnp.minimum(1.0, pt_d / jnp.maximum(pd_d, 1e-20))
    n = jnp.cumprod(accept.astype(jnp.int32), axis=1).sum(axis=1)

    resid = jnp.maximum(p_t - p_d, 0.0)
    resid_sum = resid.sum(axis=-1, keepdims=True)
    # all-accepted positions may have a zero residual; fall back to p_t
    resid = jnp.where(resid_sum > 1e-9, resid / jnp.maximum(resid_sum, 1e-20),
                      p_t)
    resampled = jax.random.categorical(
        key_res, jnp.log(jnp.maximum(resid, 1e-20)), axis=-1
    ).astype(jnp.int32)                                               # (B, K-1)
    bonus = sampling_ops.sample(t_logits[:, -1], sampling_params, key_bonus, odsc)
    drafts_ext = jnp.concatenate([draft_toks, bonus[:, None]], axis=1)
    correction = jnp.concatenate([resampled, bonus[:, None]], axis=1)
    slot = jnp.arange(k)[None, :]
    out_toks = jnp.where(slot < n[:, None], drafts_ext, correction)
    return out_toks, n.astype(jnp.int32)


class FusedSpeculativeModel:
    """Owns a target and a draft `TpuModelForCausalLM` and runs fused spec decode.

    Both apps must share vocab and tpu_config geometry; the draft is typically a much
    smaller model of the same family (or any arch with the same tokenizer).
    """

    def __init__(self, target, draft, speculation_length: int, greedy: bool = True,
                 spec_chunk: int = 8):
        if speculation_length < 2:
            raise ValueError("speculation_length must be >= 2 (1 draft + 1 verify)")
        if target.arch_args.vocab_size != draft.arch_args.vocab_size:
            raise ValueError("target and draft must share a vocabulary")
        t_cfg, d_cfg = target.tpu_config, draft.tpu_config
        for attr in ("seq_len", "max_batch_size", "max_context_length"):
            if getattr(t_cfg, attr) != getattr(d_cfg, attr):
                raise ValueError(
                    f"target/draft tpu_config.{attr} mismatch: "
                    f"{getattr(t_cfg, attr)} vs {getattr(d_cfg, attr)} — both caches "
                    f"must cover the same positions (out-of-range draft writes would "
                    f"clamp silently)")
        if not greedy:
            odsc = target.sampling_config
            if not (odsc.do_sample or odsc.dynamic):
                raise ValueError(
                    "multinomial speculation (greedy=False) requires a sampling config "
                    "with do_sample or dynamic params — with both off, sample() is a "
                    "full-vocab argmax while acceptance uses windowed probabilities, "
                    "which breaks the rejection-sampling guarantee")
        self.target = target
        self.draft = draft
        self.k = speculation_length
        self.greedy = greedy
        # fused iterations per device dispatch (the host round trip amortizes
        # over the whole chunk; positions/eos-stops advance IN-GRAPH and the
        # host replays the exact commit rules after the sync)
        self.spec_chunk = max(1, spec_chunk)
        self.sampling_config = target.sampling_config
        attach_spec_metrics(self, self.k, "fused draft-target")
        self._build_step()

    # ------------------------------------------------------------------ step
    def _build_step(self) -> None:
        t_args = self.target.arch_args
        d_args = self.draft.arch_args
        mesh, rules = self.target.mesh, self.target.sharding_rules
        d_mesh, d_rules = self.draft.mesh, self.draft.sharding_rules
        k = self.k
        odsc = self.sampling_config
        greedy = self.greedy
        vocab = t_args.vocab_size
        precision = ("highest" if self.target.tpu_config.dtype == "float32"
                     else "default")
        # Pallas stacked-cache decode for both models when supported (the draft
        # chain and the wide verify are both plain chain decodes). Under
        # flash_decoding_enabled the verify is a multi-token chain over the
        # KV-seq-sharded cache — decode_forward's flash-decoding path now
        # scatters each of the K fresh tokens to its owning cp shard.
        if self.target._use_flash_decoding():
            t_kernel = {"flash_decoding": True}
        else:
            t_kernel = ({"use_kernel": True}
                        if self.target._use_decode_kernel() else {})
        if self.draft._use_flash_decoding():
            d_kernel = {"flash_decoding": True}
        else:
            d_kernel = ({"use_kernel": True}
                        if self.draft._use_decode_kernel() else {})

        def _iter(t_params, d_params, last_tok, positions, t_cache, d_cache,
                  sampling_params, key, decode_bucket, with_draft_logits):
            """One fused speculative iteration (draft loop + wide verify + accept).

            last_tok (B,) int32: last committed token (its KV not yet written).
            positions (B,) int32: write position of last_tok.
            Returns (out_tokens (B, K), num_valid (B,), draft_logits|None,
            t_cache, d_cache).
            """
            key_d, key_acc = jax.random.split(key)
            d_keys = jax.random.split(key_d, k - 1)
            want_d_logits = with_draft_logits or not greedy

            # --- draft loop: k-1 proposal steps, then ONE KV-only step. The
            # k-th forward runs so that d_{k-1}'s KV lands in the draft cache —
            # on full acceptance the next step starts past it and would
            # otherwise read a never-written slot (the reference loops the
            # draft spec_len times for the same reason, `model_base.py:1881-1930`)
            # — but its PROPOSAL is discarded, so it skips the draft's final
            # norm + lm_head (skip_logits). Greedy chunks also skip stacking
            # the (B, V) per-step draft logits through the scan: only the
            # rejection sampler (or a draft-logit capture) reads them.
            def draft_body(carry, key_j):
                tok, pos, cache = carry
                with jax.default_matmul_precision(precision):
                    logits, cache = model_base.decode_forward(
                        d_params, d_args, tok[:, None], pos, cache, decode_bucket,
                        mesh=d_mesh, rules=d_rules, **d_kernel)
                last = logits[:, -1]
                if greedy:
                    nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)
                else:
                    nxt = sampling_ops.sample(last, sampling_params, key_j,
                                              odsc, mesh=d_mesh, rules=d_rules)
                return (nxt, pos + 1, cache), ((nxt, last) if want_d_logits
                                               else nxt)

            (d_last, d_pos, d_cache), ys = jax.lax.scan(
                draft_body, (last_tok, positions, d_cache), d_keys)
            if want_d_logits:
                draft_toks = ys[0].T                          # (B, K-1)
                draft_logits = ys[1].transpose(1, 0, 2)       # (B, K-1, V)
            else:
                draft_toks, draft_logits = ys.T, None
            with jax.default_matmul_precision(precision):
                _, d_cache = model_base.decode_forward(
                    d_params, d_args, d_last[:, None], d_pos, d_cache,
                    decode_bucket, mesh=d_mesh, rules=d_rules,
                    skip_logits=True, **d_kernel)

            # --- target verify: one wide decode over [last, d_1, ..., d_{k-1}] ------
            target_in = jnp.concatenate([last_tok[:, None], draft_toks], axis=1)
            with jax.default_matmul_precision(precision):
                t_logits, t_cache = model_base.decode_forward(
                    t_params, t_args, target_in, positions, t_cache, decode_bucket,
                    mesh=mesh, rules=rules, **t_kernel)  # (B, K, V)

            out_toks, n = speculative_accept(draft_toks, draft_logits, t_logits,
                                             sampling_params, key_acc, odsc,
                                             greedy, vocab)
            return out_toks, n, draft_logits, t_cache, d_cache

        def _chunk(t_params, d_params, tok0, positions0, alive0, t_cache,
                   d_cache, sampling_params, eos_ids, key, decode_bucket,
                   num_iters, with_draft_logits=False):
            """``num_iters`` fused iterations in ONE device dispatch: per-row
            positions advance in-graph by each row's accepted length and a row
            whose committed window contains its eos stops advancing (the host
            replays the exact same stopping rules after the sync — same
            discipline as the CB serving chunk). Returns
            ((out_toks (N, B, K), n (N, B)[, draft_logits (N, B, K-1, V)]),
            t_cache, d_cache)."""
            iter_keys = jax.random.split(key, num_iters)

            def one_iter(carry, key_i):
                tok, pos, alive, t_cache, d_cache = carry
                out_toks, n, d_logits, t_cache, d_cache = _iter(
                    t_params, d_params, tok, pos, t_cache, d_cache,
                    sampling_params, key_i, decode_bucket, with_draft_logits)
                take, new_tok, alive = chunk_advance(alive, out_toks, n,
                                                     eos_ids)
                tok = jnp.where(take > 0, new_tok, tok)
                pos = pos + take
                ys = (out_toks, n) + ((d_logits,) if with_draft_logits else ())
                return (tok, pos, alive, t_cache, d_cache), ys

            (_, _, _, t_cache, d_cache), ys = jax.lax.scan(
                one_iter, (tok0, positions0, alive0, t_cache, d_cache),
                iter_keys)
            return ys, t_cache, d_cache

        self._spec_chunk = audited_jit(
            _chunk, kind="spec.chunk", cache_args=("t_cache", "d_cache"),
            static_argnames=("decode_bucket", "num_iters",
                             "with_draft_logits"),
            steps_arg="num_iters")

    # ------------------------------------------------------------------ generate
    def generate(
        self,
        input_ids: np.ndarray,
        attention_mask: Optional[np.ndarray] = None,
        max_new_tokens: int = 32,
        sampling_params: Optional[np.ndarray] = None,
        eos_token_id: Optional[int] = None,
        pad_token_id: int = 0,
        seed: int = 0,
        capture_draft_logits: bool = False,
    ) -> SpecGenerateOutput:
        """Host orchestration loop (≈ `_fused_assisted_decoding`, `hf_adapter.py:494`).

        Rows commit a variable 1..K tokens per step, so rows advance unevenly; finished
        rows keep stepping (SPMD batch) with frozen positions and their outputs dropped.

        Each device dispatch runs up to ``spec_chunk`` fused iterations with
        positions / eos-stops advancing IN-GRAPH (one host round trip per
        chunk, not per iteration); the host then replays the exact commit /
        stopping rules over the chunk's per-iteration outputs.

        ``capture_draft_logits`` returns the per-iteration (B, K-1, V) draft
        logits in ``output.draft_logits`` for draft-logit accuracy checking
        (≈ reference `run_accuracy_draft_logit_test_flow`, `utils/accuracy.py:1214`).
        """
        target, draft = self.target, self.draft
        cfg = target.tpu_config
        if target.params is None or draft.params is None:
            raise RuntimeError("load weights on both target and draft before generate")
        input_ids = model_wrapper.to_int32(input_ids)
        b = input_ids.shape[0]
        compiled_b = cfg.max_batch_size
        if sampling_params is None:
            sampling_params = sampling_ops.prepare_sampling_params(compiled_b)
        elif sampling_params.shape[0] > compiled_b:
            raise ValueError(f"sampling_params batch {sampling_params.shape[0]} exceeds "
                             f"compiled batch size {compiled_b}")
        elif sampling_params.shape[0] < compiled_b:
            pad = np.ones((compiled_b - sampling_params.shape[0], 3), dtype=np.float32)
            sampling_params = np.concatenate([sampling_params, pad], axis=0)
        key = jax.random.PRNGKey(seed if not self.sampling_config.deterministic
                                 else self.sampling_config.seed)

        padded = model_wrapper.pad_prefill_inputs(
            input_ids, attention_mask, target.cte_buckets, pad_token_id=pad_token_id,
            batch_size=compiled_b)
        target.reset_cache()
        draft.reset_cache()

        # --- fused context encoding: target prefill (samples t0) + draft prefill ----
        t_start = time.perf_counter()
        key, sub = jax.random.split(key)
        tok0_dev, _, target.kv_cache = target._prefill_step(
            target.params, padded.input_ids, padded.position_ids,
            padded.last_token_idx, target.kv_cache, sampling_params, sub)
        _, _, draft.kv_cache = draft._prefill_step(
            draft.params, padded.input_ids, padded.position_ids,
            padded.last_token_idx, draft.kv_cache, sampling_params, sub)
        tok0 = np.asarray(tok0_dev)
        ttft = time.perf_counter() - t_start
        benchmark_lib.record_submodel(benchmark_lib.CONTEXT_ENCODING_MODEL, ttft)

        committed: List[List[int]] = [[int(tok0[i])] for i in range(b)]
        done = np.zeros((compiled_b,), dtype=bool)
        done[b:] = True
        if eos_token_id is not None:
            done[:b] |= tok0[:b] == eos_token_id
        positions = padded.true_lengths.astype(np.int32).copy()
        last_tok = tok0.astype(np.int32)
        accept_hist = np.zeros((self.k,), dtype=np.int64)
        steps = 0
        draft_logits_loops: List[np.ndarray] = []

        eos_ids = np.full((compiled_b,),
                          -1 if eos_token_id is None else eos_token_id,
                          dtype=np.int32)
        while not all(len(c) >= max_new_tokens or done[i] for i, c in enumerate(committed)):
            # live rows only bound the chunk: a finished row's frozen position
            # must not shrink (or end) the live rows' budget, and alive0=False
            # freezes it in-graph
            live_pos = [int(positions[i]) for i, c in enumerate(committed)
                        if not done[i] and len(c) < max_new_tokens]
            max_pos = max(live_pos)
            if max_pos + self.k >= cfg.seq_len:
                break
            room = (cfg.seq_len - 1 - max_pos) // self.k
            remaining = min(max_new_tokens - len(c)
                            for i, c in enumerate(committed)
                            if not done[i] and len(c) < max_new_tokens)
            iters = quantize_chunk_iters(self.spec_chunk, room, remaining)
            bucket = autobucketing.select_bucket(target.tkg_buckets,
                                                 max_pos + self.k * iters)
            alive0 = np.array([i < b and not done[i]
                               and len(committed[i]) < max_new_tokens
                               for i in range(compiled_b)])
            key, sub = jax.random.split(key)
            t_step0 = time.perf_counter()
            with profiling.annotate("dispatch:spec.chunk"):
                ys, target.kv_cache, draft.kv_cache = self._spec_chunk(
                    target.params, draft.params, jnp.asarray(last_tok),
                    jnp.asarray(positions), jnp.asarray(alive0),
                    target.kv_cache, draft.kv_cache, sampling_params,
                    jnp.asarray(eos_ids), sub,
                    decode_bucket=bucket, num_iters=iters,
                    with_draft_logits=capture_draft_logits)
            out = np.asarray(ys[0])      # (iters, B, K)
            n = np.asarray(ys[1])        # (iters, B)
            benchmark_lib.record_submodel(benchmark_lib.SPECULATION_MODEL,
                                          time.perf_counter() - t_step0)
            if capture_draft_logits:
                chunk_logits = np.asarray(ys[2])               # (iters, B, K-1, V)
                draft_logits_loops.extend(chunk_logits[j] for j in range(iters))
            steps += replay_chunk(out, n, committed, done, positions, last_tok,
                                  accept_hist, eos_token_id, max_new_tokens)
            # frozen rows re-step harmlessly at their last position

        record_spec_metrics(self, accept_hist, steps)
        out = assemble_spec_output(committed, padded, b, pad_token_id, accept_hist,
                                   steps, ttft)
        if capture_draft_logits:
            out.draft_logits = draft_logits_loops
        return out
