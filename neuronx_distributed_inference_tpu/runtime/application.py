"""Application lifecycle: model construction, weight loading, compiled-step management,
and the generation loop.

≈ reference `models/application_base.py` (`NeuronApplicationBase`: compile :292, load
:317, warmup :348) + the CausalLM orchestration half of `models/model_base.py`
(`NeuronBaseForCausalLM` :3066: sub-model dispatch :3594-3780, preprocess :3255). TPU
redesign:

- "compile" = construct jitted prefill/decode step functions; per-bucket compilation
  happens lazily on first call (or eagerly via `warmup()`, ≈ `application_base.py:348`),
  cached by XLA's jit cache keyed on (shape, static bucket).
- "load" = read HF checkpoint, convert to the stacked pytree, `jax.device_put` with the
  sharding derived from logical axis rules over the config's mesh.
- The KV cache lives as a `jax.Array` pytree owned by the application and *donated*
  through every step (≈ aliased graph I/O, `model_wrapper.py:1571-1612`).
- Sampling runs inside the same jitted step (on-device sampling,
  ≈ `model_base.py:1041` `_sample_on_device`).
"""

from __future__ import annotations

import functools
import logging
import os
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.registry import audited_jit
from ..config import InferenceConfig, OnDeviceSamplingConfig, TpuConfig
from ..modules import autobucketing, kvcache
from ..models import base as model_base
from ..ops import sampling as sampling_ops
from ..parallel import mesh as mesh_lib
from ..parallel.sharding import named_sharding, shard_put, tree_shardings
from ..utils import benchmark as benchmark_lib
from ..utils import checkpoint as ckpt_lib
from . import model_wrapper

logger = logging.getLogger("tpu-inference")


def _mask_after_eos(tokens: np.ndarray, eos_token_id: int, pad_token_id: int
                    ) -> np.ndarray:
    """Replace everything after each row's first EOS with pad (chunked decode generates
    past EOS; the trim mirrors HF stopping-criteria semantics host-side)."""
    tokens = tokens.copy()
    hit = tokens == eos_token_id
    seen = np.cumsum(hit, axis=1) - hit.astype(int)   # strictly-after-first-eos count
    tokens[seen > 0] = pad_token_id
    return tokens


@dataclass
class GenerateOutput:
    sequences: np.ndarray            # (B, prompt + generated) int32, right-trimmed pads
    tokens: np.ndarray               # (B, generated) int32
    logits: Optional[List[np.ndarray]] = None  # per-step (B, V) fp32 when requested
    ttft_s: Optional[float] = None
    # per decode chunk: (wall seconds, tokens generated in the chunk)
    decode_latencies_s: Optional[List[Tuple[float, int]]] = None


class TpuModelForCausalLM:
    """Base application class; model families subclass and provide arch args + weight
    conversion (see models/llama)."""

    def __init__(self, model_path: Optional[str], config: InferenceConfig,
                 mesh: Optional[mesh_lib.Mesh] = None):
        self.model_path = model_path
        self.config = config
        self.tpu_config: TpuConfig = config.tpu_config
        self.arch_args = self.arch_args_from_config(config)
        lora_cfg = self.tpu_config.lora_serving_config
        if lora_cfg is not None:
            import dataclasses as _dc

            from ..modules.lora import LoraSpec

            targets = ("wq", "wk", "wv", "wo", "wg", "wu", "wd")
            if self.arch_args.moe is not None:
                # MoE FFNs route through moe_block, which has no LoRA hook yet;
                # restrict adapters to the attention projections so nothing is
                # silently inactive
                targets = ("wq", "wk", "wv", "wo")
                logger.info("MoE model: LoRA restricted to attention projections")
            # alpha == rank -> runtime scaling 1.0; each adapter's true alpha/rank is
            # folded into its B matrices at conversion (modules/lora.py)
            self.arch_args = _dc.replace(
                self.arch_args,
                lora=LoraSpec(max_loras=lora_cfg.max_loras,
                              rank=lora_cfg.max_lora_rank,
                              alpha=float(lora_cfg.max_lora_rank),
                              targets=targets))
        qcfg = self.tpu_config.quantization_config
        if qcfg is not None and qcfg.activation_quant:
            import dataclasses as _dc

            self.arch_args = _dc.replace(self.arch_args, activation_quant=True)
        self.mesh = mesh if mesh is not None else mesh_lib.mesh_from_config(
            self.tpu_config)
        self.sampling_config = (self.tpu_config.on_device_sampling_config
                                or OnDeviceSamplingConfig())

        self.cte_buckets = autobucketing.generate_buckets_for_cte(self.tpu_config)
        self.tkg_buckets = autobucketing.generate_buckets_for_tkg(self.tpu_config)
        self.batch_buckets = autobucketing.generate_batch_buckets(self.tpu_config)

        from ..parallel.sharding import DEFAULT_RULES

        self.sharding_rules = dict(DEFAULT_RULES)
        if not self.tpu_config.vocab_parallel:
            self.sharding_rules["vocab"] = None
        if self.tpu_config.sequence_parallel_enabled:
            # sequence-parallel residual/norm path (≈ reference sequence-
            # parallel norm in the attention/MLP blocks): prefill residuals
            # shard over seq on the model axes; decode residuals (T≈1) shard
            # over hidden — converting the per-layer all-reduces into
            # all-gather + reduce-scatter halves, which the overlap-scheduled
            # collective matmuls (parallel/overlap.py) fuse into the qkv /
            # gate-up / o-proj / down-proj matmuls at tp > 1
            from ..parallel.mesh import AXIS_CP, AXIS_TP

            self.sharding_rules["act_seq"] = (AXIS_CP, AXIS_TP)
            self.sharding_rules["act_embed"] = AXIS_TP
        if self.tpu_config.flash_decoding_enabled:
            # flash decoding: decode-time KV caches shard their sequence dim over
            # the cp axis (≈ reference flashdecode KV-replication groups,
            # `modules/flashdecode/utils.py:11-58`)
            from ..parallel.mesh import AXIS_CP

            self.sharding_rules["kv_seq"] = AXIS_CP
        if self.tpu_config.attention_dp_enabled:
            # decode attention goes batch-parallel over every chip; GQA kv heads
            # replicate within each batch shard (≈ attention DP + DP KV cache
            # manager, `data_parallel_kv_cache_manager.py:8-39`)
            from ..parallel.mesh import AXIS_DP, AXIS_TP

            self.sharding_rules["decode_batch"] = (AXIS_DP, AXIS_TP)
            self.sharding_rules["decode_heads"] = None
            self.sharding_rules["decode_kv_heads"] = None
        if self.tpu_config.moe_hybrid_sharding is not None:
            # hybrid MoE sharding: each phase's expert activations take their
            # own axis split (≈ reference CTE-vs-TKG TP/EP groups + dispatch CC
            # options, `models/config.py:1055-1061,602`): e.g. TP-heavy prefill
            # / EP-heavy decode. "default" prefill values keep DEFAULT_RULES.
            h = self.tpu_config.moe_hybrid_sharding
            self.sharding_rules["decode_experts"] = h.mesh_axes("decode_experts")
            self.sharding_rules["decode_expert_mlp"] = h.mesh_axes(
                "decode_expert_mlp")
            for field, rule in (("prefill_experts", "experts"),
                                ("prefill_expert_mlp", "expert_mlp")):
                v = h.mesh_axes(field)
                if v != "default":
                    self.sharding_rules[rule] = v
        moe_args = getattr(self.arch_args, "moe", None)
        if moe_args is not None and self.tpu_config.ep_degree > 1 and \
                moe_args.num_experts % self.tpu_config.ep_degree:
            # the experts logical axis shards E over ep; a non-dividing degree
            # used to surface as an opaque GSPMD partition error mid-trace
            raise ValueError(
                f"num_experts={moe_args.num_experts} must be divisible by "
                f"ep_degree={self.tpu_config.ep_degree} (the experts axis "
                f"shards over the ep mesh axis)")

        self.params = None
        self.kv_cache = None
        self._build_steps()

    @staticmethod
    def _require_base_layout(tc: TpuConfig, family: str,
                             allow: Tuple[str, ...] = ()) -> None:
        """Reject serving features a custom-layout family (e.g. MLA/Llama4) has not
        implemented — fail loudly at construction rather than deep inside lax.scan
        tracing. ``allow`` names features the family DOES support."""
        unsupported = [name for name, v in (
            ("lora_serving_config", tc.lora_serving_config),
            ("quantization_config", tc.quantization_config),
            ("speculation_config", tc.speculation_config),
            ("paged_attention_enabled", tc.paged_attention_enabled or None),
            ("is_continuous_batching", tc.is_continuous_batching or None),
        ) if v is not None and name not in allow]
        if unsupported:
            raise ValueError(f"{', '.join(unsupported)} not supported for the "
                             f"{family} family yet")

    # --- per-arch hooks (≈ get_config_cls / convert_hf_to_neuron_state_dict) ---------
    @classmethod
    def get_config_cls(cls):
        raise NotImplementedError

    @classmethod
    def arch_args_from_config(cls, config: InferenceConfig) -> model_base.ModelArchArgs:
        raise NotImplementedError

    @classmethod
    def convert_hf_state_dict(cls, state_dict, config) -> Dict:
        raise NotImplementedError

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        from ..ops import rope as rope_ops

        return rope_ops.default_inv_freq(config.head_dim,
                                         getattr(config, "rope_theta", 10000.0))

    # --- forward cores (overridable by arch, e.g. MoE) -------------------------------
    def prefill_fn(self):
        return model_base.prefill_forward

    def decode_fn(self):
        return model_base.decode_forward

    # --- param layout hooks (overridable by archs with non-standard params, e.g.
    # DeepSeek MLA) --------------------------------------------------------------------
    def logical_axes(self) -> Dict:
        return model_base.param_logical_axes(self.arch_args)

    def init_random_params(self, key) -> Dict:
        return model_base.init_params(
            self.arch_args, key, dtype=self.tpu_config.jax_dtype,
            inv_freq=self.inv_freq_from_config(self.config))

    # --- step construction ------------------------------------------------------------
    def _build_steps(self) -> None:
        args = self.arch_args
        mesh = self.mesh
        odsc = self.sampling_config
        prefill_core = self.prefill_fn()
        decode_core = self.decode_fn()

        # fp32 runs (accuracy harness) need true-fp32 matmuls; bf16 runs keep the fast
        # default so the MXU runs native bf16
        precision = "highest" if self.tpu_config.dtype == "float32" else "default"

        rules = self.sharding_rules
        use_ring = self._use_ring_attention()
        use_flash = (not use_ring) and self._use_flash_attention()
        use_fd = self._use_flash_decoding()
        use_decode_kernel = (not use_fd) and self._use_decode_kernel()

        def _prefill(params, input_ids, position_ids, last_token_idx, cache,
                     sampling_params, key, adapter_ids=None):
            with jax.default_matmul_precision(precision):
                logits, cache = prefill_core(params, args, input_ids, position_ids,
                                             last_token_idx, cache, mesh=mesh,
                                             rules=rules, use_flash=use_flash,
                                             adapter_ids=adapter_ids,
                                             use_ring=use_ring)
                tokens = sampling_ops.sample(logits, sampling_params, key, odsc,
                                             mesh=mesh, rules=rules)
            return tokens, logits, cache

        def _decode(params, tokens0, position_ids, cache, sampling_params, key,
                    decode_bucket, num_steps, with_logits, adapter_ids=None,
                    greedy=False):
            """Generate ``num_steps`` tokens in ONE device call via lax.scan.

            Host-driven per-token loops pay a host<->device round trip per token; the
            scan keeps the whole decode chunk on device (the TPU-native analog of the
            reference's async double-buffered decode, `modules/async_execution.py`).
            ``greedy`` (static) skips the dynamic sampling window entirely — the host
            sets it when every request is argmax, saving the per-step 128k-vocab
            top-k (~10%% of decode time at 1B scale).
            """
            keys = jax.random.split(key, num_steps)

            kernel_kw = {"use_kernel": True} if use_decode_kernel else {}
            if use_fd:
                kernel_kw = {"flash_decoding": True}

            def body(carry, step_key):
                tok, pos, cache = carry
                with jax.default_matmul_precision(precision):
                    logits, cache = decode_core(params, args, tok[:, None], pos, cache,
                                                decode_bucket, mesh=mesh, rules=rules,
                                                adapter_ids=adapter_ids, **kernel_kw)
                    last = logits[:, -1, :]
                    if greedy:
                        nxt = sampling_ops.greedy(last, mesh=mesh, rules=rules)
                    else:
                        nxt = sampling_ops.sample(last, sampling_params, step_key,
                                                  odsc, mesh=mesh, rules=rules)
                out = (nxt, last) if with_logits else (nxt, ())
                return (nxt, pos + 1, cache), out

            (_, positions, cache), (toks, step_logits) = jax.lax.scan(
                body, (tokens0, position_ids, cache), keys)
            toks = toks.T  # (num_steps, B) -> (B, num_steps)
            return toks, step_logits, cache

        def _window(params, input_ids, start, window_row, cache, decode_bucket):
            """One dense windowed-prefill step: write the (B, W) prompt window's KV at
            absolute positions [start, start+W), cache rows [window_row, +B), attending
            over the rows' earlier windows (≈ windowed CTE, `model_base.py:918-973`).
            Logits are discarded — the caller seeds generation with a 1-token decode
            re-feeding each row's true last token."""
            b = input_ids.shape[0]
            pos = jnp.full((b,), start, dtype=jnp.int32)
            with jax.default_matmul_precision(precision):
                _, cache = decode_core(params, args, input_ids, pos, cache,
                                       decode_bucket, mesh=mesh, rules=rules,
                                       window_row=window_row)
            return cache

        self._prefill_step = audited_jit(
            _prefill, kind="plain.prefill", cache_args=("cache",))
        self._decode_step = audited_jit(
            _decode, kind="plain.decode", cache_args=("cache",),
            static_argnames=("decode_bucket", "num_steps", "with_logits",
                             "greedy"),
            steps_arg="num_steps")
        self._window_step = audited_jit(
            _window, kind="plain.window", cache_args=("cache",),
            static_argnames=("decode_bucket",))

    def _use_ring_attention(self) -> bool:
        """Context-parallel (ring attention) prefill when the mesh has a cp axis.

        ≈ the reference's CP strategy selection (`attention_base.py:647-734`): CP is a
        prefill-time strategy; decode stays on the TP layout over the full cache (the
        analog of the reference's CP-prefill -> TP-decode KV handover,
        `kv_cache_manager.py:469-486` — here GSPMD reshards the cache write)."""
        cp = self.mesh.shape["cp"]
        if cp <= 1:
            return False
        if self.tpu_config.attention_kernel_enabled is True:
            raise ValueError(
                "attention_kernel_enabled=True conflicts with cp_degree > 1: "
                "context-parallel prefill uses the ring-attention path, not the "
                "single-shard Pallas kernel")
        a = self.arch_args
        unsupported = None
        if a.layer_pattern is not None:
            unsupported = "per-layer attention patterns"
        elif a.logits_soft_cap is not None:
            unsupported = "logits_soft_cap"
        elif a.num_kv_heads % self.mesh.shape["tp"] != 0:
            unsupported = "kv heads not divisible by tp"
        if unsupported is not None:
            raise ValueError(
                f"cp_degree > 1 requires the ring-attention prefill path, which does "
                f"not support {unsupported} for this architecture yet")
        for bucket in self.cte_buckets:
            if bucket % cp != 0:
                raise ValueError(
                    f"context bucket {bucket} not divisible by cp_degree {cp}")
        return True

    def _use_flash_decoding(self) -> bool:
        """KV-seq-sharded decode (flash decoding) over the cp axis
        (≈ reference `modules/flashdecode/`): explicit opt-in via
        ``flash_decoding_enabled``; requires cp > 1 and the base decode path."""
        if not self.tpu_config.flash_decoding_enabled:
            return False
        cp = self.mesh.shape["cp"]
        if cp <= 1:
            raise ValueError("flash_decoding_enabled requires cp_degree > 1 "
                             "(the KV sequence dim shards over the cp axis)")
        a = self.arch_args
        unsupported = None
        if self.decode_fn() is not model_base.decode_forward:
            unsupported = "custom decode paths"
        elif a.attn_sinks or a.logits_soft_cap is not None or a.alibi:
            unsupported = "attention sinks / logits_soft_cap / ALiBi"
        elif a.layer_pattern is not None:
            unsupported = "per-layer attention patterns"
        elif self.tpu_config.paged_attention_enabled:
            unsupported = "paged attention"
        elif self.tpu_config.seq_len % cp != 0:
            unsupported = f"seq_len not divisible by cp ({cp})"
        if unsupported is not None:
            raise ValueError(f"flash_decoding_enabled does not support "
                             f"{unsupported}")
        return True

    def _decode_kernel_arch_gate(self) -> Optional[str]:
        """Arch features the Pallas decode kernels (dense and paged) do not serve;
        returns the unsupported-feature name or None. Shared by both selectors so
        the gates cannot drift from each other."""
        a = self.arch_args
        if self.decode_fn() is not model_base.decode_forward:
            return "custom decode paths"
        if a.head_dim % 128 != 0 and jax.default_backend() != "cpu":
            # the KV-write DMA slices the cache's minor dim, which Mosaic requires
            # aligned to the 128-lane tiling (interpret mode on CPU is unconstrained)
            return "head_dim not a multiple of 128"
        return None

    def _decode_kernel_select(self, unsupported: Optional[str]) -> bool:
        """Shared decision tail: explicit config wins (raising when it demands an
        unsupported combination); otherwise on for TPU backends when supported."""
        a = self.arch_args
        cfg = self.tpu_config.decode_kernel_enabled
        if cfg is not None:
            if cfg and unsupported is not None:
                raise ValueError(f"decode_kernel_enabled=True but the decode kernel "
                                 f"does not support {unsupported}")
            return cfg
        if unsupported is not None:
            return False
        tp = self.mesh.shape["tp"]
        if a.num_heads % tp != 0 or a.num_kv_heads % tp != 0:
            return False
        return jax.default_backend() not in ("cpu",)

    def _use_decode_kernel(self) -> bool:
        """Auto-select the Pallas stacked-cache decode path (KV-write DMA scatter +
        length-aware decode attention, ≈ reference TKG kernel selection,
        `attention_base.py:1483-1677`): explicit config wins; otherwise on for TPU
        backends for architectures the kernel supports."""
        return self._decode_kernel_select(self._decode_kernel_arch_gate())

    def _use_paged_decode_kernel(self) -> bool:
        """Auto-select the Pallas ragged paged decode path for continuous-batching
        serving (block-table-indexed, length-aware kernels — ops/paged_decode.py,
        ≈ the reference's paged TKG hot path, `block_kv_cache_manager.py:268-374`).
        Same arch gates as the dense kernel, plus paged-layout constraints."""
        from ..ops.paged_decode import _pack

        if self.arch_args.layer_pattern is not None:
            # rolling sliding stacks don't page; the DENSE kernel serves pattern
            # families (see _run_stack_pattern_decode_kernel) but the block-pool
            # layout cannot. decode_kernel_enabled=True refers to the dense
            # kernel, so this is a quiet decline, not a config error (paged
            # serving for pattern families is rejected by the CB runner anyway).
            return False
        unsupported = self._decode_kernel_arch_gate()
        if unsupported is None:
            pack = _pack(self.tpu_config.kv_cache_jax_dtype)
            if self.tpu_config.pa_block_size % pack != 0:
                unsupported = (f"pa_block_size {self.tpu_config.pa_block_size} not "
                               f"a multiple of the {pack}-row KV tile packing")
        if unsupported is None and (
                self.mesh.shape.get("dp", 1) * self.mesh.shape.get("cp", 1) != 1
                or self.tpu_config.attention_dp_enabled):
            # the block pool is replicated over dp/cp and its kv_heads axis is
            # plain-tp-sharded; a dp/cp-split batch (or the attention-DP
            # decode_batch->(dp,tp) remap) is inconsistent with those specs. A
            # mixed config (dense kernel on, paged serving on such a mesh) is
            # legitimate, so fall back loudly instead of raising.
            logger.warning(
                "paged decode kernels disabled: dp/cp-sharded or attention-DP "
                "decode layout (the block pool is replicated, kv_heads "
                "plain-tp-sharded); continuous batching uses the gather path")
            return False
        return self._decode_kernel_select(unsupported)

    def _use_flash_attention(self) -> bool:
        """Auto-select the Pallas prefill kernel (≈ reference
        `get_flash_attention_strategy`, `attention_base.py:1330`): explicit config wins;
        otherwise on for TPU backends when the arch has no unsupported extras, off for
        CPU (Pallas needs interpret mode there)."""
        a = self.arch_args
        cfg = self.tpu_config.attention_kernel_enabled
        # soft-cap / sinks / ALiBi are served in-kernel (ops/flash_attention.py,
        # ≈ the reference's new CTE kernel extras, `attention_base.py:88-121`)
        if cfg is not None:
            return cfg
        if a.num_heads % self.mesh.shape["tp"] != 0:
            return False
        return jax.default_backend() not in ("cpu",)

    # --- weights ----------------------------------------------------------------------
    def _quantization(self):
        q = self.tpu_config.quantization_config
        return q if (q is not None and q.quantize_weights) else None

    def quantized_param_names(self):
        """Param leaf names converted by weight quantization (overridable by families
        with custom layouts, e.g. DeepSeek-MLA's absorbed projections)."""
        from ..ops.quantization import DEFAULT_QUANTIZED_PARAMS

        return DEFAULT_QUANTIZED_PARAMS

    def _int4_param_names(self):
        """Quantized names packed to int4 under weight_dtype='int4' (the large
        streaming projections; see ops/quantization.W4_DEFAULT_PARAMS)."""
        from ..ops.quantization import W4_DEFAULT_PARAMS

        q = self._quantization()
        if q is None or q.weight_dtype != "int4":
            return ()
        return tuple(n for n in W4_DEFAULT_PARAMS
                     if n in self.quantized_param_names())

    def _transposed_param_names(self):
        """Quantized attention stacks stored transposed (see
        ops/quantization.TRANSPOSED_ATTENTION_PARAMS); intersected with this
        family's quantized names so custom layouts (e.g. DeepSeek's absorbed
        projections) are never touched."""
        from ..ops.quantization import TRANSPOSED_ATTENTION_PARAMS

        if (self._quantization() is None
                or not self.tpu_config.transpose_attention_stacks):
            return ()
        return tuple(n for n in TRANSPOSED_ATTENTION_PARAMS
                     if n in self.quantized_param_names())

    def _param_shardings(self):
        from ..ops.quantization import quantized_logical_axes

        logical = self.logical_axes()
        if self._quantization() is not None:
            logical = quantized_logical_axes(
                logical, self.quantized_param_names(),
                transposed_names=self._transposed_param_names(),
                int4_names=self._int4_param_names())
        return tree_shardings(self.mesh, logical, self.sharding_rules)

    def load(self, model_path: Optional[str] = None) -> None:
        """Load + convert + shard HF weights onto the mesh (≈ `application_base.py:317`)."""
        path = model_path or self.model_path
        if path is None:
            raise ValueError("no model path to load from")
        t0 = time.time()
        state_dict = ckpt_lib.load_state_dict(path)
        host_params = self.convert_hf_state_dict(state_dict, self.config)
        self._put_params(host_params)
        self._post_load_state_dict(state_dict)
        logger.info("loaded weights in %.1fs", time.time() - t0)
        lora_cfg = self.tpu_config.lora_serving_config
        if lora_cfg is not None and lora_cfg.lora_ckpt_paths:
            from ..modules.lora import load_peft_adapter

            sds, alphas = [], []
            for name, adir in lora_cfg.lora_ckpt_paths.items():
                sd, alpha, _rank = load_peft_adapter(adir)
                sds.append(sd)
                alphas.append(alpha)
                logger.info("loaded LoRA adapter %r from %s (alpha=%s)",
                            name, adir, alpha)
            self.set_lora_adapters(sds, alphas=alphas)

    def _post_load_state_dict(self, state_dict) -> None:
        """Hook: called by load() with the already-read checkpoint (multimodal
        subclasses convert their vision weights here without a second disk pass)."""

    def load_random(self, seed: int = 0) -> None:
        """Random weights at the configured shapes (tests / synthetic benchmarks)."""
        self._put_params(self.init_random_params(jax.random.PRNGKey(seed)))

    def load_host_params(self, host_params) -> None:
        """Install an already-converted host param pytree (public hook for synthetic
        benchmarks and externally pre-quantized checkpoints)."""
        self._put_params(host_params)

    def set_lora_adapters(self, adapter_state_dicts, alphas=None) -> None:
        """Install PEFT adapter checkpoints into the resident multi-LoRA slots
        (adapter i -> slot i+1; slot 0 stays the zero adapter). ``alphas[i]`` is the
        adapter's lora_alpha from its adapter_config.json (None = scaling 1.0).
        ≈ reference LoRA checkpoint shard/load (`lora_checkpoint.py:232-336`)."""
        from ..modules.lora import convert_peft_state_dicts, lora_logical_axes

        if self.arch_args.lora is None:
            raise RuntimeError("construct with lora_serving_config to serve LoRA")
        if self.params is None:
            raise RuntimeError("load base weights before adapters")
        host = convert_peft_state_dicts(adapter_state_dicts, self.arch_args,
                                        self.arch_args.lora, alphas=alphas)
        axes = lora_logical_axes(self.arch_args, self.arch_args.lora)
        dtype = self.tpu_config.jax_dtype
        for name, arr in host.items():
            sharding = named_sharding(self.mesh, axes[name], self.sharding_rules)
            self.params["layers"][name] = jax.device_put(
                np.asarray(arr).astype(dtype), sharding)

    def _put_params(self, host_params) -> None:
        if self.arch_args.lora is not None:
            # HF checkpoints carry no adapter weights; materialize the zero slots so
            # the param tree always matches the sharding tree (adapters land later
            # via set_lora_adapters)
            from ..modules.lora import init_lora_params

            missing = {k: v for k, v in init_lora_params(
                self.arch_args, self.arch_args.lora).items()
                if k not in host_params["layers"]}
            if missing:
                host_params = dict(host_params)
                host_params["layers"] = {**host_params["layers"], **missing}
        qcfg = self._quantization()
        if qcfg is not None:
            from ..ops.quantization import (quantize_params,
                                            transpose_attention_stacks)

            # per-leaf: already-quantized leaves pass through (pre-quantized ckpts)
            host_params = quantize_params(host_params, qcfg.weight_dtype,
                                          names=self.quantized_param_names(),
                                          int4_names=self._int4_param_names()
                                          or None)
            tnames = self._transposed_param_names()
            if tnames:
                host_params = transpose_attention_stacks(host_params,
                                                         names=tnames)
        shardings = self._param_shardings()
        dtype = self.tpu_config.jax_dtype

        def _put(path, x, s):
            arr = np.asarray(x)
            last = getattr(path[-1], "key", None) if path else None
            first = getattr(path[0], "key", "") if path else ""
            if first.startswith("rope_inv_freq") or last == "s":
                # rope tables and quantization scales stay fp32
                arr = arr.astype(np.float32)
            elif last in ("q", "qT", "q4"):
                pass                      # int8/fp8/int4-packed payloads keep dtype
            elif arr.dtype.kind == "f" or arr.dtype.name == "bfloat16":
                arr = arr.astype(dtype) if arr.dtype != dtype else arr
            return jax.device_put(arr, s)

        self.params = jax.tree_util.tree_map_with_path(_put, host_params, shardings)

    # --- cache ------------------------------------------------------------------------
    def _static_kv_scales_enabled(self) -> bool:
        q = self.tpu_config.quantization_config
        return q is not None and q.kv_cache_scale_mode == "static"

    def cache_spec(self) -> kvcache.KVCacheSpec:
        a = self.arch_args
        static = self._static_kv_scales_enabled()
        if static and a.layer_pattern is not None:
            raise ValueError("static fp8 KV scales are not supported with "
                             "per-layer attention patterns (rolling caches) yet")
        return kvcache.KVCacheSpec(
            num_layers=a.num_layers,
            batch_size=self.tpu_config.max_batch_size,
            num_kv_heads=a.num_kv_heads,
            max_seq_len=self.tpu_config.seq_len,
            head_dim=a.head_dim,
            dtype=self.tpu_config.kv_cache_jax_dtype,
            static_scales=static,
        )

    def _apply_kv_scales(self, cache):
        """Overwrite the pytree's σ entries with the calibrated host scales."""
        if getattr(self, "_kv_scales", None) is None or "k_scale" not in cache:
            return cache
        sharding = named_sharding(self.mesh, kvcache.SCALE_LOGICAL,
                                  self.sharding_rules)
        cache = dict(cache)
        cache["k_scale"] = jax.device_put(self._kv_scales[0], sharding)
        cache["v_scale"] = jax.device_put(self._kv_scales[1], sharding)
        return cache

    def make_paged_cache(self, num_blocks: int, block_size: int):
        """Sharded paged KV cache for continuous batching (overridable by families
        with custom cache layouts, e.g. DeepSeek's latent cache)."""
        from ..modules import block_kvcache

        a = self.arch_args
        spec = block_kvcache.PagedKVCacheSpec(
            num_layers=a.num_layers, num_blocks=num_blocks, block_size=block_size,
            num_kv_heads=a.num_kv_heads, head_dim=a.head_dim,
            dtype=self.tpu_config.kv_cache_jax_dtype)
        sharding = named_sharding(self.mesh, block_kvcache.PAGED_CACHE_LOGICAL,
                                  self.sharding_rules)
        cache = jax.tree.map(lambda x: jax.device_put(x, sharding),
                             block_kvcache.init_paged_cache(spec))
        if self._static_kv_scales_enabled():
            scale_sharding = named_sharding(self.mesh, kvcache.SCALE_LOGICAL,
                                            self.sharding_rules)
            cache["k_scale"] = jax.device_put(
                jnp.ones((a.num_layers, a.num_kv_heads), jnp.float32),
                scale_sharding)
            cache["v_scale"] = jax.device_put(
                jnp.ones((a.num_layers, a.num_kv_heads), jnp.float32),
                scale_sharding)
            cache = self._apply_kv_scales(cache)
        return cache

    def reset_cache(self, batch_size: Optional[int] = None) -> None:
        """Fresh zero cache; ``batch_size`` overrides the compiled batch for
        batch-bucketed requests (see autobucketing.generate_batch_buckets).
        Calibrated static KV scales persist across resets."""
        import dataclasses as _dc

        spec = self.cache_spec()
        if batch_size is not None and batch_size != spec.batch_size:
            spec = _dc.replace(spec, batch_size=batch_size)
        sharding = named_sharding(self.mesh, kvcache.CACHE_LOGICAL,
                                  self.sharding_rules)
        scale_sharding = named_sharding(self.mesh, kvcache.SCALE_LOGICAL,
                                        self.sharding_rules)
        a = self.arch_args
        if a.layer_pattern is not None:
            # dual-stack cache: rolling window-sized stacks for sliding layers
            host = kvcache.init_cache_pattern(spec, a.layer_pattern,
                                              a.sliding_window or spec.max_seq_len)
        else:
            host = kvcache.init_cache(spec)
        self.kv_cache = {
            k: jax.device_put(v, scale_sharding if k.endswith("_scale")
                              else sharding)
            for k, v in host.items()}
        self.kv_cache = self._apply_kv_scales(self.kv_cache)

    def calibrate_kv_scales(self, sample_input_ids: np.ndarray,
                            attention_mask: Optional[np.ndarray] = None) -> None:
        """Calibrate per-(layer, kv-head) static fp8 scales from sample prompts.

        Runs ONE full-precision prefill over the samples into a temporary
        model-dtype cache, takes each (layer, head)'s |K|/|V| max over the written
        positions, and sets σ = absmax / fp8_max (so outliers land inside the fp8
        range instead of clipping). Scales persist across `reset_cache`.
        ≈ reference static-scale fp8 KV calibration (`kv_cache_manager.py` fp8
        paths)."""
        import dataclasses as _dc

        import ml_dtypes

        if not self._static_kv_scales_enabled():
            raise RuntimeError("kv_cache_scale_mode='static' is not enabled")
        if self.params is None:
            raise RuntimeError("load weights before calibration")
        spec = _dc.replace(self.cache_spec(), dtype=self.tpu_config.jax_dtype,
                           static_scales=False)
        b = spec.batch_size
        ids = model_wrapper.to_int32(np.asarray(sample_input_ids))
        padded = model_wrapper.pad_prefill_inputs(ids, attention_mask,
                                                  self.cte_buckets, batch_size=b)
        cache = kvcache.init_cache(spec)
        n_real = ids.shape[0]
        precision = "highest" if self.tpu_config.dtype == "float32" else "default"

        def _cal(params, input_ids, position_ids, last, cache):
            with jax.default_matmul_precision(precision):
                _, cache = self.prefill_fn()(
                    params, self.arch_args, input_ids, position_ids, last, cache,
                    mesh=self.mesh, rules=self.sharding_rules)
            # per (L, H) absmax over the real rows' written positions
            valid = (jnp.arange(cache["k"].shape[3])[None, :]
                     <= last[:n_real, None])[None, :, None, :, None]
            absmax = []
            for key in ("k", "v"):
                x = jnp.abs(cache[key][:, :n_real].astype(jnp.float32))
                absmax.append(jnp.max(jnp.where(valid, x, 0.0), axis=(1, 3, 4)))
            return absmax[0], absmax[1]

        # one-shot calibration over a throwaway local cache — not a serving
        # dispatch  # lint: ok(raw-jit, jit-no-donate): one-shot, cache discarded
        k_max, v_max = jax.jit(_cal)(
            self.params, padded.input_ids, padded.position_ids,
            padded.last_token_idx, cache)
        kv_dt = jnp.dtype(self.tpu_config.kv_cache_jax_dtype)
        if kv_dt == jnp.int8:
            cache_max = 127.0
        else:
            cache_max = float(ml_dtypes.finfo(kv_dt).max)
        eps = 1e-6
        k_scale = np.maximum(np.asarray(k_max) / cache_max, eps).astype(np.float32)
        v_scale = np.maximum(np.asarray(v_max) / cache_max, eps).astype(np.float32)
        self._kv_scales = (k_scale, v_scale)
        if self.kv_cache is not None and "k_scale" in self.kv_cache:
            self.kv_cache = self._apply_kv_scales(self.kv_cache)
        logger.info("calibrated static KV scales: k in [%.4g, %.4g], "
                    "v in [%.4g, %.4g]", k_scale.min(), k_scale.max(),
                    v_scale.min(), v_scale.max())

    # --- warmup (≈ `application_base.py:348-372`) -------------------------------------
    def warmup(self) -> None:
        if self.params is None:
            raise RuntimeError("load weights before warmup")
        b = self.tpu_config.max_batch_size
        sp = sampling_ops.prepare_sampling_params(b)
        key = jax.random.PRNGKey(0)
        # warm the same pytree structure production uses: LoRA-enabled apps always
        # pass an adapter array (None would be a different jit cache entry)
        warm_adapters = (np.zeros((b,), dtype=np.int32)
                         if self.arch_args.lora is not None else None)
        for bucket in self.cte_buckets:
            self.reset_cache()
            ids = np.zeros((b, bucket), dtype=np.int32)
            pos = np.broadcast_to(np.arange(bucket, dtype=np.int32), (b, bucket)).copy()
            last = np.zeros((b,), dtype=np.int32)
            tokens, _, self.kv_cache = self._prefill_step(
                self.params, ids, pos, last, self.kv_cache, sp, key, warm_adapters)
            tokens.block_until_ready()
        chunk = max(1, self.tpu_config.decode_chunk_size)
        # only the reachable decode specializations: do_sample configs never take the
        # static-greedy graph; pure-greedy non-dynamic configs never take the dynamic
        if self.sampling_config.do_sample:
            variants = (False,)
        elif not self.sampling_config.dynamic:
            variants = (True,)
        else:
            variants = (True, False)
        for bucket in self.tkg_buckets:
            for greedy in variants:
                tok0 = jnp.zeros((b,), dtype=jnp.int32)
                pos = np.zeros((b,), dtype=np.int32)
                tokens, _, self.kv_cache = self._decode_step(
                    self.params, tok0, pos, self.kv_cache, sp, key,
                    decode_bucket=bucket, num_steps=min(chunk, bucket),
                    with_logits=False, adapter_ids=warm_adapters, greedy=greedy)
                tokens.block_until_ready()
        self.reset_cache()
        logger.info("warmup complete: %d CTE + %d TKG buckets",
                    len(self.cte_buckets), len(self.tkg_buckets))

    # --- debug: tensor capture / replacement (≈ reference extra-output capture,
    # `models/model_base.py:1076-1182`, and golden injection `models/config.py:1131`) --
    def prefill_with_capture(self, input_ids, attention_mask=None,
                             names=None, replacements=None, adapter_ids=None):
        """Run ONE context-encoding pass with tensor taps active.

        Returns (logits (B, V) fp32, {tap_name: np.ndarray}). Compiles a dedicated
        graph per call (debug path) using the SAME attention strategy as serving
        (flash/ring/adapters), so captures localize divergence in the graph actually
        served. ``replacements`` injects goldens at tap points before downstream
        compute (divergence isolation)."""
        from ..utils import tensor_capture as tc

        names = tuple(names if names is not None else tc.KNOWN_TAPS)
        padded = model_wrapper.pad_prefill_inputs(
            model_wrapper.to_int32(np.asarray(input_ids)), attention_mask,
            self.cte_buckets, batch_size=self.tpu_config.max_batch_size)
        self.reset_cache()
        args, mesh, rules = self.arch_args, self.mesh, self.sharding_rules
        prefill_core = self.prefill_fn()
        precision = "highest" if self.tpu_config.dtype == "float32" else "default"
        use_ring = self._use_ring_attention()
        use_flash = (not use_ring) and self._use_flash_attention()

        def fn(params, ids, pos, last, cache, adapters):
            with tc.capture(names, replacements) as st:
                with jax.default_matmul_precision(precision):
                    logits, cache = prefill_core(params, args, ids, pos, last, cache,
                                                 mesh=mesh, rules=rules,
                                                 use_flash=use_flash,
                                                 use_ring=use_ring,
                                                 adapter_ids=adapters)
                return logits, st.captured

        # debug tap path: compiles per call, cache reset right after
        # lint: ok(raw-jit, jit-no-donate): debug capture path, not serving
        logits, captured = jax.jit(fn)(
            self.params, padded.input_ids, padded.position_ids,
            padded.last_token_idx, self.kv_cache, adapter_ids)
        self.reset_cache()
        b = np.asarray(input_ids).shape[0]
        return (np.asarray(logits)[:b],
                {k: np.asarray(v) for k, v in captured.items()})

    def _run_prefill(self, padded, sampling_params, key, adapter_ids, mm=None):
        """Dispatch the context-encoding graph (multimodal subclasses override to run
        the embed-merge variant when image features are present)."""
        if mm is not None:
            raise ValueError("image features given but this application has no "
                             "vision encoder (use an image-to-text family)")
        return self._prefill_step(
            self.params, padded.input_ids, padded.position_ids, padded.last_token_idx,
            self.kv_cache, sampling_params, key, adapter_ids)

    # --- generation (≈ HF adapter `_sample` loop, `utils/hf_adapter.py:139-257`) ------
    def generate(
        self,
        input_ids: np.ndarray,
        attention_mask: Optional[np.ndarray] = None,
        max_new_tokens: int = 32,
        sampling_params: Optional[np.ndarray] = None,
        eos_token_id: Optional[int] = None,
        pad_token_id: int = 0,
        seed: int = 0,
        return_logits: bool = False,
        collect_latency: bool = False,
        adapter_ids: Optional[np.ndarray] = None,   # (B,) multi-LoRA slots (0 = base)
        _mm_embeds=None,   # (mask, override) from TpuModelForImageToText.generate
    ) -> GenerateOutput:
        if self.params is None:
            raise RuntimeError("load weights before generate")
        input_ids = model_wrapper.to_int32(input_ids)
        b = input_ids.shape[0]
        compiled_b = self.tpu_config.max_batch_size
        if len(self.batch_buckets) > 1 and _mm_embeds is None:
            batch_bucket = autobucketing.select_bucket(self.batch_buckets, b)
            if batch_bucket != compiled_b:
                if type(self).reset_cache is not TpuModelForCausalLM.reset_cache:
                    raise ValueError(
                        "batch_buckets not supported for families with a custom "
                        "cache layout")
                compiled_b = batch_bucket
        if adapter_ids is not None:
            if self.arch_args.lora is None:
                raise ValueError("adapter_ids given but lora_serving_config is not set")
            ids_in = np.asarray(adapter_ids, dtype=np.int32)
            n_slots = self.arch_args.lora.num_slots
            if ids_in.min() < 0 or ids_in.max() >= n_slots:
                # out-of-range gathers would silently produce NaN rows on device
                raise ValueError(f"adapter_ids must be in [0, {n_slots}); "
                                 f"got {ids_in.tolist()}")
            ids_arr = np.zeros((compiled_b,), dtype=np.int32)
            ids_arr[:b] = ids_in
            adapter_ids = ids_arr
        if sampling_params is None:
            sampling_params = sampling_ops.prepare_sampling_params(compiled_b)
        elif sampling_params.shape[0] > compiled_b:
            raise ValueError(f"sampling_params batch {sampling_params.shape[0]} exceeds "
                             f"compiled batch size {compiled_b}")
        elif sampling_params.shape[0] < compiled_b:
            pad = np.ones((compiled_b - sampling_params.shape[0], 3), dtype=np.float32)
            sampling_params = np.concatenate([sampling_params, pad], axis=0)
        key = jax.random.PRNGKey(seed if not self.sampling_config.deterministic
                                 else self.sampling_config.seed)
        # host-side greedy detection: all rows argmax -> compile the decode chunk
        # without the dynamic sampling window (exact same tokens, less work)
        sp_arr = np.asarray(sampling_params)
        greedy_only = (not self.sampling_config.do_sample
                       and bool((sp_arr[:, 0] == 1).all()))

        max_prompt = (int(np.asarray(attention_mask).sum(axis=1).max())
                      if attention_mask is not None else input_ids.shape[1])
        windowed = max_prompt > self.cte_buckets[-1]
        if windowed and (self.decode_fn() is not model_base.decode_forward
                         or self.arch_args.layer_pattern is not None):
            raise ValueError(
                f"prompt ({max_prompt}) exceeds the largest context bucket "
                f"({self.cte_buckets[-1]}) and this family has no dense windowed "
                f"prefill (custom decode path or rolling sliding caches)")
        padded = model_wrapper.pad_prefill_inputs(
            input_ids, attention_mask,
            self.cte_buckets if not windowed else [self.cte_buckets[-1]],
            pad_token_id=pad_token_id, batch_size=compiled_b,
            allow_longer=windowed)
        if compiled_b != self.tpu_config.max_batch_size:
            self.reset_cache(batch_size=compiled_b)
        else:
            self.reset_cache()

        # env-driven repro snapshots (≈ NXD_INFERENCE_CAPTURE_*, utils/snapshot.py)
        from ..utils import snapshot as snapshot_lib

        snapshot_lib.new_request()
        snap = {
            "input_ids": padded.input_ids, "position_ids": padded.position_ids,
            "last_token_idx": padded.last_token_idx,
            "sampling_params": sampling_params, "adapter_ids": adapter_ids}
        if _mm_embeds is not None:          # multimodal requests must replay too
            if isinstance(_mm_embeds, dict):
                snap.update({f"mm_{k}": v for k, v in _mm_embeds.items()})
            else:
                snap["mm_features"] = _mm_embeds
        snapshot_lib.maybe_capture("prefill", snap)
        snapshot_lib.maybe_capture_weights(self.params)

        t_start = time.perf_counter()
        key, sub = jax.random.split(key)
        if windowed:
            # dense windowed (chunked) prefill: largest-bucket windows write the
            # prompt's KV in sequence; a 1-token decode re-feeding each row's true
            # last token (an idempotent cache rewrite) then yields the seed logits.
            if _mm_embeds is not None:
                raise ValueError("multimodal prompts exceed the largest context "
                                 "bucket; raise max_context_length")
            if adapter_ids is not None:
                raise ValueError("windowed prefill does not thread LoRA adapters "
                                 "into window writes yet; raise "
                                 "max_context_length to cover the prompt")
            w = self.cte_buckets[-1]
            total = padded.input_ids.shape[1]
            if total > self.tpu_config.seq_len:
                raise ValueError(
                    f"windowed prefill needs {total} cache slots (prompt rounded up "
                    f"to {w}-wide windows) but seq_len is {self.tpu_config.seq_len}")
            for w0 in range(0, total, w):
                bkt = autobucketing.select_bucket(self.tkg_buckets, w0 + w)
                self.kv_cache = self._window_step(
                    self.params, padded.input_ids[:, w0 : w0 + w],
                    np.int32(w0), np.int32(0), self.kv_cache, decode_bucket=bkt)
            seed_tok = padded.input_ids[np.arange(padded.input_ids.shape[0]),
                                        padded.last_token_idx]
            seed_bucket = autobucketing.select_bucket(
                self.tkg_buckets, int(padded.true_lengths.max()))
            toks, step_logits, self.kv_cache = self._decode_step(
                self.params, jnp.asarray(seed_tok), padded.last_token_idx,
                self.kv_cache, sampling_params, sub, decode_bucket=seed_bucket,
                num_steps=1, with_logits=return_logits, adapter_ids=adapter_ids,
                greedy=greedy_only)
            tokens_dev = toks[:, 0]
            logits_dev = step_logits[0] if return_logits else None
        else:
            tokens_dev, logits_dev, self.kv_cache = self._run_prefill(
                padded, sampling_params, sub, adapter_ids, mm=_mm_embeds)
        tokens_dev.block_until_ready()
        ttft = time.perf_counter() - t_start
        benchmark_lib.record_submodel(benchmark_lib.CONTEXT_ENCODING_MODEL, ttft)

        all_logits = [np.asarray(logits_dev)[:b]] if return_logits else None
        chunks = [np.asarray(tokens_dev)[:, None]]
        decode_lat: List[float] = []
        base_positions = padded.true_lengths.astype(np.int32)
        chunk_size = max(1, self.tpu_config.decode_chunk_size)
        last_tok = tokens_dev            # (B,) device-resident between chunks
        n_done = 1
        eos_done = np.zeros((b,), dtype=bool)
        if eos_token_id is not None:
            eos_done |= chunks[0][:b, 0] == eos_token_id

        # decode runs in fixed-size on-device chunks (lax.scan); host only touches the
        # boundary between chunks, so tunnel/dispatch latency amortizes over the chunk.
        # Chunks always run the full chunk_size (trailing excess discarded host-side)
        # so every chunk reuses one compiled graph per bucket — a variable remainder
        # would recompile mid-stream.
        #
        # async_mode pipelines the chunk boundary itself (≈ reference 2-deep async
        # decode, `modules/async_execution.py:190-306`): chunk N+1 is dispatched from
        # the device-resident last token of chunk N *before* chunk N is synced to host,
        # so the device never idles waiting for the host to read results. The EOS check
        # then lags one chunk (the reference likewise drops to sync at boundaries to
        # keep state consistent); at most one surplus chunk runs and is trimmed here.
        async_mode = self.tpu_config.async_mode
        pending = None                   # (toks_dev, logits_dev, steps, t_dispatch)
        gen_limit = max_new_tokens       # shrunk to the EOS-stop width on early break

        last_sync_t = time.perf_counter()

        def _sync_chunk(p):
            nonlocal last_sync_t
            toks_dev_p, logits_p, steps_p, t0_p = p
            toks = np.asarray(toks_dev_p)          # (B, steps); blocks
            # async_mode: this chunk was dispatched while the PREVIOUS chunk was
            # still in flight, so wall time since its dispatch t0 overlaps the
            # prior chunk's — summing those would double-count. Time since the
            # previous sync instead: syncs are serialized, so sync-to-sync deltas
            # partition wall time exactly.
            now = time.perf_counter()
            start = max(t0_p, last_sync_t) if async_mode else t0_p
            benchmark_lib.record_submodel(benchmark_lib.TOKEN_GENERATION_MODEL,
                                          now - start)
            if collect_latency:
                decode_lat.append((now - start, steps_p))
            last_sync_t = now
            chunks.append(toks)
            if return_logits:
                lc = np.asarray(logits_p)          # (steps, B, V)
                all_logits.extend(lc[i][:b] for i in range(lc.shape[0]))
            return toks

        while n_done < max_new_tokens:
            max_pos = int(base_positions.max()) + (n_done - 1)
            steps = min(chunk_size, self.tpu_config.seq_len - 1 - max_pos)
            if steps <= 0:
                logger.warning("hit seq_len %d during decode", self.tpu_config.seq_len)
                break
            bucket = autobucketing.select_bucket(self.tkg_buckets, max_pos + steps)
            positions = base_positions + (n_done - 1)
            key, sub = jax.random.split(key)
            t0 = time.perf_counter()
            toks_dev, logits_chunk, self.kv_cache = self._decode_step(
                self.params, last_tok, positions, self.kv_cache, sampling_params, sub,
                decode_bucket=bucket, num_steps=steps, with_logits=return_logits,
                adapter_ids=adapter_ids, greedy=greedy_only)
            last_tok = toks_dev[:, -1]             # device-resident; no sync needed
            n_done += steps
            if async_mode:
                prior, pending = pending, (toks_dev, logits_chunk, steps, t0)
                toks = _sync_chunk(prior) if prior is not None else None
            else:
                toks = _sync_chunk((toks_dev, logits_chunk, steps, t0))
            if toks is not None and eos_token_id is not None:
                eos_done |= (toks[:b] == eos_token_id).any(axis=1)
                if eos_done.all():
                    # async: the in-flight surplus chunk is synced below but its tokens
                    # are dropped so both modes stop at the same width
                    gen_limit = min(gen_limit, sum(c.shape[1] for c in chunks))
                    break
        if pending is not None:
            _sync_chunk(pending)

        gen = np.concatenate(chunks, axis=1)[:b, :gen_limit]        # (B, T)
        if return_logits:
            all_logits = all_logits[:gen_limit]
        if eos_token_id is not None:
            gen = _mask_after_eos(gen, eos_token_id, pad_token_id)
        seqs = []
        prompt_lens = padded.true_lengths[:b]
        max_len = int(prompt_lens.max()) + gen.shape[1]
        sequences = np.full((b, max_len), pad_token_id, dtype=np.int32)
        for i in range(b):
            pl = int(prompt_lens[i])
            sequences[i, :pl] = padded.input_ids[i, :pl]
            sequences[i, pl : pl + gen.shape[1]] = gen[i]
        return GenerateOutput(
            sequences=sequences, tokens=gen,
            logits=all_logits, ttft_s=ttft,
            decode_latencies_s=decode_lat if collect_latency else None)

    # --- artifact save/load (compiled dir ≈ model.pt + neuron_config.json) ------------
    def save_config(self, directory: str) -> str:
        return self.config.save(directory)

    def save_artifacts(self, directory: str) -> str:
        """Persist the full serving artifact dir: config JSON + the CONVERTED
        (HF-rewritten, quantized, serving-layout) weights + calibrated KV scales.

        A second process start via :meth:`from_artifacts` skips HF ingest and
        re-quantization entirely and reuses the artifact dir's XLA compile cache
        — the TPU form of the reference's quantized-checkpoint generation,
        pre-sharded weight save, and ``--skip-compile`` compiled-dir reuse
        (`models/application_base.py:744-797`, `:240-265`, `inference_demo.py:367-372`).
        """
        if self.params is None:
            raise RuntimeError("load weights before save_artifacts")
        self.config.save(directory)
        host = jax.device_get(self.params)
        ckpt_lib.save_param_tree(os.path.join(directory, "weights"), host)
        vision = getattr(self, "vision_params", None)
        if vision is not None:   # multimodal families: the artifact must be whole
            ckpt_lib.save_param_tree(os.path.join(directory, "vision_weights"),
                                     jax.device_get(vision))
        if getattr(self, "_kv_scales", None) is not None:
            ckpt_lib.save_param_tree(
                os.path.join(directory, "kv_scales"),
                {"k": np.asarray(self._kv_scales[0]),
                 "v": np.asarray(self._kv_scales[1])})
        os.makedirs(os.path.join(directory, "compile_cache"), exist_ok=True)
        logger.info("serving artifacts saved to %s", directory)
        return directory

    def load_artifacts(self, directory: str) -> None:
        """Install weights from an artifact dir (no HF ingest, no re-quantize:
        already-quantized leaves pass through `_put_params` untouched)."""
        t0 = time.time()
        host = ckpt_lib.load_param_tree(os.path.join(directory, "weights"))
        vdir = os.path.join(directory, "vision_weights")
        if os.path.isdir(vdir):
            self._put_vision_params(ckpt_lib.load_param_tree(vdir))
        scales_dir = os.path.join(directory, "kv_scales")
        if os.path.isdir(scales_dir):
            sc = ckpt_lib.load_param_tree(scales_dir)
            self._kv_scales = (np.asarray(sc["k"]), np.asarray(sc["v"]))
        self._put_params(host)
        logger.info("loaded artifacts in %.1fs", time.time() - t0)

    @classmethod
    def from_artifacts(cls, directory: str, mesh=None):
        """Reconstruct an application from :meth:`save_artifacts` output.

        Reflection-based config reload picks the saved config class; the
        artifact dir's ``compile_cache/`` is registered as the persistent XLA
        compilation cache BEFORE any jit, so warm starts also skip compilation
        (the ``--skip-compile`` analog)."""
        from ..config import InferenceConfig
        from ..utils.runtime_env import set_runtime_env

        config = InferenceConfig.load(directory)
        if not jax.config.jax_compilation_cache_dir:
            # respect an explicitly configured cache (e.g. a shared
            # --compilation-cache-dir); otherwise reuse the artifact dir's
            set_runtime_env(config.tpu_config.seq_len,
                            compilation_cache_dir=os.path.join(
                                directory, "compile_cache"))
        app = cls(None, config, mesh=mesh)
        app.load_artifacts(directory)
        return app

    @classmethod
    def from_pretrained(cls, model_path: str, tpu_config: TpuConfig,
                        mesh=None) -> "TpuModelForCausalLM":
        from ..config import load_pretrained_config

        cfg_cls = cls.get_config_cls()
        config = cfg_cls(tpu_config, load_config=load_pretrained_config(model_path))
        app = cls(model_path, config, mesh=mesh)
        app.load()
        return app
