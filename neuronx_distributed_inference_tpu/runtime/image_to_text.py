"""Image-to-text application base: vision-encoder sub-model + embed-merge prefill.

≈ reference `models/image_to_text_model_base.py` (`ImageToTextInferenceConfig` :34,
`NeuronBaseForImageToText`: separate text/vision ModelBuilders, vision-encoder
ModelWrapper pipelined into the text CTE) and `models/encoder_base.py`. TPU redesign:

- The vision encoder is its own jitted function over its own param pytree (≈ a separate
  ModelWrapper/NEFF); the text model is the unchanged causal-LM stack.
- Image features are merged by *embedding override*: the text prefill takes an optional
  (mask, override) pair and replaces token-embedding rows at image-token positions
  (≈ HF `masked_scatter` merge, which the reference's pipelined execution reproduces
  on device).
- `generate(pixel_values=...)` encodes all images in one batched vision call (images
  attend only within themselves, so batching the vision encoder over images is exactly
  the reference's block-diagonal mask over a concatenated sequence), scatters features
  into the *padded* prompt (so bucket padding / row compaction cannot misalign them),
  and runs the multimodal prefill graph.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from ..analysis.registry import audited_jit
from ..config import InferenceConfig
from .application import GenerateOutput, TpuModelForCausalLM

__all__ = ["ImageToTextInferenceConfig", "TpuModelForImageToText"]


class ImageToTextInferenceConfig(InferenceConfig):
    """Text + vision config pair (≈ reference ImageToTextInferenceConfig).

    HF multimodal configs nest ``text_config``/``vision_config``; the text attributes
    are flattened onto this object (the causal-LM base reads them) while the vision
    dict stays available as ``vision_config``.
    """

    REQUIRED_ATTRIBUTES = ("vision_config",)

    def add_derived_config(self) -> None:
        if hasattr(self, "text_config"):
            tc = self.text_config
            if not isinstance(tc, dict):
                tc = tc.to_dict()
            # text attrs are authoritative for the LM: the OUTER HF config serializes
            # top-level defaults (e.g. tie_word_embeddings=True) that must not shadow
            # the text model's values
            for k, v in tc.items():
                if not k.startswith("_"):
                    setattr(self, k, v)
        if hasattr(self, "vision_config") and not isinstance(self.vision_config, dict):
            self.vision_config = self.vision_config.to_dict()


class TpuModelForImageToText(TpuModelForCausalLM):
    """Causal LM + vision encoder sub-model (≈ NeuronBaseForImageToText).

    Families implement ``vision_encode_fn`` (pure: (vision_params, pixel_values) ->
    (N_images, tokens_per_image, text_hidden)) and
    ``convert_hf_vision_state_dict``; the text side is inherited unchanged.
    """

    def __init__(self, model_path, config, mesh=None):
        super().__init__(model_path, config, mesh=mesh)
        self.vision_params = None
        # serving dispatch (the vision tower runs per request): registered so
        # the auditor can prove it callback-free like the text-side steps
        self._encode_step = audited_jit(self.vision_encode_fn(),
                                        kind="mm.encode")
        self._mm_prefill_step = self._build_mm_prefill()

    # --- per-family hooks -------------------------------------------------------------
    def vision_encode_fn(self):
        """Return the pure vision-encoder function (vision_params, pixel_values) ->
        (N, T_img, H_text) image features (already projected to text hidden size)."""
        raise NotImplementedError

    @classmethod
    def convert_hf_vision_state_dict(cls, state_dict, config) -> Dict:
        raise NotImplementedError

    @property
    def image_token_index(self) -> int:
        return self.config.image_token_index

    # --- weights ----------------------------------------------------------------------
    # vision params are replicated (vision towers are small relative to the LM;
    # shard via a vision logical-axes hook later if profiling justifies)

    def _post_load_state_dict(self, state_dict) -> None:
        # hook from TpuModelForCausalLM.load: reuse the already-read checkpoint
        # instead of a second multi-GB disk pass
        self.load_vision_from_state_dict(state_dict)

    def load_vision_from_state_dict(self, state_dict) -> None:
        host = self.convert_hf_vision_state_dict(state_dict, self.config)
        self._put_vision_params(host)

    def _put_vision_params(self, host: Dict) -> None:
        dtype = self.tpu_config.jax_dtype

        def _put(x):
            arr = np.asarray(x)
            if arr.dtype.kind == "f" or arr.dtype.name == "bfloat16":
                arr = arr.astype(dtype)
            return jax.device_put(arr)

        self.vision_params = jax.tree.map(_put, host)

    # --- multimodal prefill graph -----------------------------------------------------
    def _mm_strategy(self):
        """(matmul precision, use_ring, use_flash) — mirrors _build_steps exactly so
        multimodal prefill graphs never diverge from the serving strategy."""
        precision = ("highest" if self.tpu_config.dtype == "float32" else "default")
        use_ring = self._use_ring_attention()
        use_flash = (not use_ring) and self._use_flash_attention()
        return precision, use_ring, use_flash

    def _build_mm_prefill(self):
        args = self.arch_args
        mesh = self.mesh
        rules = self.sharding_rules
        odsc = self.sampling_config
        prefill_core = self.prefill_fn()
        from ..ops import sampling as sampling_ops

        precision, use_ring, use_flash = self._mm_strategy()

        def _prefill_mm(params, input_ids, position_ids, last_token_idx, cache,
                        sampling_params, key, mm_mask, mm_override, adapter_ids=None):
            with jax.default_matmul_precision(precision):
                logits, cache = prefill_core(
                    params, args, input_ids, position_ids, last_token_idx, cache,
                    mesh=mesh, rules=rules, use_flash=use_flash, use_ring=use_ring,
                    adapter_ids=adapter_ids,
                    merge_embeds=(mm_mask, mm_override))
                tokens = sampling_ops.sample(logits, sampling_params, key, odsc)
            return tokens, logits, cache

        return audited_jit(_prefill_mm, kind="mm.prefill",
                           cache_args=("cache",))

    def encode_images(self, pixel_values: np.ndarray) -> np.ndarray:
        """(N_images, C, H, W) -> (N_images, T_img, H_text) via the jitted encoder."""
        if self.vision_params is None:
            raise RuntimeError("load vision weights before encoding images")
        import time as _time

        from ..utils import benchmark as benchmark_lib

        t0 = _time.perf_counter()
        feats = np.asarray(self._encode_step(self.vision_params, pixel_values))
        benchmark_lib.record_submodel(benchmark_lib.VISION_ENCODER_MODEL,
                                      _time.perf_counter() - t0)
        return feats

    # --- warmup -----------------------------------------------------------------------
    def warmup(self) -> None:
        """Also compile the vision encoder and the multimodal prefill graphs, so the
        first image request doesn't pay XLA compilation (extends the base warmup
        contract, ≈ `application_base.py:348`)."""
        super().warmup()
        if self.vision_params is None:
            return
        vc = self.config.vision_config
        side = vc.get("image_size")
        chans = vc.get("num_channels", 3)
        if side:
            pixels = np.zeros((1, chans, side, side), dtype=np.float32)
            self.encode_images(pixels)
        from ..ops import sampling as sampling_ops

        b = self.tpu_config.max_batch_size
        sp = sampling_ops.prepare_sampling_params(b)
        key = jax.random.PRNGKey(0)
        h = self.arch_args.hidden_size
        for bucket in self.cte_buckets:
            self.reset_cache()
            ids = np.zeros((b, bucket), dtype=np.int32)
            pos = np.broadcast_to(np.arange(bucket, dtype=np.int32), (b, bucket)).copy()
            last = np.zeros((b,), dtype=np.int32)
            pm = np.zeros((b, bucket, 1), dtype=bool)
            po = np.zeros((b, bucket, h), dtype=np.float32)
            tokens, _, self.kv_cache = self._mm_prefill_step(
                self.params, ids, pos, last, self.kv_cache, sp, key, pm, po)
            tokens.block_until_ready()
        self.reset_cache()

    # --- generation -------------------------------------------------------------------
    def generate(self, input_ids: np.ndarray, pixel_values: Optional[np.ndarray] = None,
                 **kwargs) -> GenerateOutput:
        """`generate` with optional images.

        ``pixel_values`` (N_images, C, H, W): every image-token position in
        ``input_ids`` (== config.image_token_index) receives one image-feature row, in
        image order — rows must carry exactly T_img image tokens per image, matching
        HF's placeholder convention."""
        if pixel_values is None:
            return super().generate(input_ids, **kwargs)
        feats = self.encode_images(np.asarray(pixel_values))   # (N, T_img, H)
        flat = feats.reshape(-1, feats.shape[-1])
        # the scatter happens against the PADDED ids inside _run_prefill — padding /
        # row compaction must not misalign features, so only the flat rows travel here
        return super().generate(input_ids, _mm_embeds=flat, **kwargs)

    def _scatter_features(self, padded, flat_feats):
        """Scatter flattened image features at image-token positions of the PADDED
        prompt (compaction-safe). Returns (mask (B, S, 1), override (B, S, H))."""
        ids = np.asarray(padded.input_ids)
        mask = ids == self.image_token_index
        n_positions = int(mask.sum())
        if n_positions != flat_feats.shape[0]:
            raise ValueError(
                f"prompt holds {n_positions} image tokens but the vision tower "
                f"produced {flat_feats.shape[0]} feature rows")
        override = np.zeros(ids.shape + (flat_feats.shape[-1],), dtype=np.float32)
        override[mask] = flat_feats
        return mask[..., None], override

    # hook used by TpuModelForCausalLM.generate to run the mm prefill graph
    def _run_prefill(self, padded, sampling_params, key, adapter_ids, mm=None):
        if mm is None:
            return super()._run_prefill(padded, sampling_params, key, adapter_ids)
        mask, override = self._scatter_features(padded, mm)
        return self._mm_prefill_step(
            self.params, padded.input_ids, padded.position_ids,
            padded.last_token_idx, self.kv_cache, sampling_params, key,
            mask, override, adapter_ids)
