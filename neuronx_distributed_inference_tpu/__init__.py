"""TPU-native distributed LLM inference framework.

A from-scratch JAX/XLA/Pallas framework with the capability surface of
`neuronx-distributed-inference` (reference at /root/reference): bucket-compiled
prefill/decode graphs, device-resident KV caches, tensor/sequence/context/expert
parallelism over a `jax.sharding.Mesh`, Pallas kernels for the hot ops, on-device
sampling, and a model hub. See SURVEY.md at the repo root for the capability map.
"""

__version__ = "0.1.0"

from .config import (  # noqa: F401
    InferenceConfig,
    OnDeviceSamplingConfig,
    TpuConfig,
    load_pretrained_config,
)
