"""Analytical roofline performance model over the audited serving dispatches.

Every registered dispatch (analysis/registry.py) carries a captured example
spec, and its compiled module carries XLA cost analysis — HBM bytes accessed,
FLOPs — plus a collective schedule (the ICI bytes the tp overlap machinery
already counts). That is everything a roofline needs: against a device-spec
table (peak FLOP/s, HBM GB/s, ICI GB/s) each dispatch classifies as
memory-/compute-/interconnect-bound and gets an EXPECTED step time

    t_expected = max(bytes / BW_hbm,  flops / peak_flops,  ici_bytes / BW_ici)

so a measured per-dispatch device time (PR 7 ``attribute_device_time``)
divides into an EFFICIENCY (1.0 = running at the roofline of its bound).
``hbm_bw_utilization`` stops being one hand-derived bench number: for a
memory-bound dispatch the efficiency IS the bandwidth utilization, derived
per kind from the same compiled costs the graph auditor budgets.

Honesty contract: a device the spec table does not know (this CPU container,
an unrecognized accelerator) resolves to an UNVERIFIED spec — byte/FLOP
derivations still work (they are hardware-independent), but expected times
and efficiencies are None and ``bound`` reads ``"unverified"``. The bench
refuses hardware-claim keys under an unverified spec (utils/provenance.py);
nothing in this module ever substitutes a made-up peak.

Everything here is OFFLINE analysis: the model reads captured example specs
and AOT cost analysis only — no new dispatches, no host syncs on the serving
path (the graph auditor keeps that true: this module traces nothing).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, replace
from typing import Dict, Mapping, Optional

logger = logging.getLogger("tpu-inference")

__all__ = ["DeviceSpec", "DEVICE_SPECS", "UNVERIFIED_SPEC",
           "resolve_device_spec", "DispatchExpectation", "classify",
           "PerfModel", "LOW_EFFICIENCY", "BOUND_MEMORY", "BOUND_COMPUTE",
           "BOUND_ICI", "BOUND_UNVERIFIED", "hbm_utilization"]

BOUND_MEMORY = "memory"
BOUND_COMPUTE = "compute"
BOUND_ICI = "interconnect"
BOUND_UNVERIFIED = "unverified"

# below this measured-vs-model efficiency a dispatch is "far below its bound"
# and the join emits one structured ``roofline_below_bound {json}`` log line
# (the r5 hbm_bw_utilization 0.46 would NOT trip this — 0.46 of roofline is
# normal serving; 0.1 catches a dispatch that is pathologically off, e.g. a
# gather fallback or a host-sync stall inside the measured window)
LOW_EFFICIENCY = 0.1


@dataclass(frozen=True)
class DeviceSpec:
    """Peak capabilities of one device generation.

    ``peak_flops`` is the bf16 dense peak (the serving dispatches' int8/int4
    matmuls run at up to 2x this on the MXU — the classification is
    conservative toward "compute-bound", which only sharpens a memory-bound
    verdict). ``ici_bytes_per_s`` is the aggregate per-chip interconnect
    bandwidth. ``verified=False`` marks the catch-all spec for hardware the
    table does not know: no peaks, no expected times, no efficiency claims.
    """

    name: str                 # provenance hardware class, e.g. "tpu-v5e"
    kind_substr: str          # matched against jax Device.device_kind
    peak_flops: Optional[float]
    hbm_bytes_per_s: Optional[float]
    ici_bytes_per_s: Optional[float]
    verified: bool = True

    def to_dict(self) -> dict:
        return {"name": self.name, "verified": self.verified,
                "peak_flops": self.peak_flops,
                "hbm_bytes_per_s": self.hbm_bytes_per_s,
                "ici_bytes_per_s": self.ici_bytes_per_s}


# ORDER MATTERS: "TPU v5" is a substring of "TPU v5 lite", so the lite entry
# must match first (same ordering contract the old bench-local table had).
# HBM numbers are the ones the r1-r5 utilization figures were derived
# against; ICI aggregates are per-chip link totals (v5e 1600 Gb/s, v4
# 2400 Gb/s, v5p 4800 Gb/s, v6e 3584 Gb/s).
DEVICE_SPECS = (
    DeviceSpec("tpu-v5e", "TPU v5 lite", 197e12, 819e9, 200e9),
    DeviceSpec("tpu-v5p", "TPU v5", 459e12, 2765e9, 600e9),
    DeviceSpec("tpu-v4", "TPU v4", 275e12, 1228e9, 300e9),
    DeviceSpec("tpu-v6e", "TPU v6 lite", 918e12, 1640e9, 448e9),
)

UNVERIFIED_SPEC = DeviceSpec("unverified", "", None, None, None,
                             verified=False)


def resolve_device_spec(device=None) -> DeviceSpec:
    """Spec for ``device`` (default: ``jax.devices()[0]``) by device_kind
    substring. Anything the table does not know — this CPU container, a
    future TPU generation, a GPU — resolves to an unverified spec named
    after its platform: measured numbers on it are real, but nothing may be
    normalized against a peak the table cannot vouch for."""
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "") or ""
    for spec in DEVICE_SPECS:
        if spec.kind_substr and spec.kind_substr in kind:
            return spec
    platform = getattr(device, "platform", "unknown") or "unknown"
    return replace(UNVERIFIED_SPEC, name=f"unverified-{platform}")


@dataclass
class DispatchExpectation:
    """Analytical expectation for ONE dispatch kind, normalized per inner
    step (the registration-time ``steps_arg`` — a decode chunk of 48
    iterations divides by 48; a while_loop megastep's cost analysis already
    counts the body once, so steps stays 1 and per-step means per inner
    iteration there too)."""

    kind: str
    steps: int
    bytes_per_step: float
    flops_per_step: float
    ici_bytes_per_step: float
    t_hbm_ms: Optional[float]
    t_flops_ms: Optional[float]
    t_ici_ms: Optional[float]
    bound: str
    expected_ms_per_step: Optional[float]

    def to_dict(self) -> dict:
        return {
            "kind": self.kind, "steps": self.steps,
            "bytes_per_step": round(self.bytes_per_step, 1),
            "flops_per_step": round(self.flops_per_step, 1),
            "ici_bytes_per_step": round(self.ici_bytes_per_step, 1),
            "t_hbm_ms": self.t_hbm_ms, "t_flops_ms": self.t_flops_ms,
            "t_ici_ms": self.t_ici_ms, "bound": self.bound,
            "expected_ms_per_step": self.expected_ms_per_step,
        }


def _ms(num: float, denom: Optional[float]) -> Optional[float]:
    if denom is None or denom <= 0:
        return None
    return 1e3 * num / denom


def classify(kind: str, bytes_accessed: float, flops: float,
             ici_bytes: float, spec: DeviceSpec,
             steps: int = 1) -> DispatchExpectation:
    """Roofline-classify one dispatch's compiled costs against ``spec``.

    The expected time is the MAX of the three resource times — the roofline
    lower bound on execution. On an unverified spec the byte/FLOP derivation
    still happens (it is hardware-independent) but every time and the bound
    verdict are refused (None / "unverified")."""
    steps = max(1, int(steps))
    b = bytes_accessed / steps
    f = flops / steps
    i = ici_bytes / steps
    t_hbm = _ms(b, spec.hbm_bytes_per_s)
    t_flops = _ms(f, spec.peak_flops)
    t_ici = _ms(i, spec.ici_bytes_per_s) if i > 0 else (
        0.0 if spec.verified else None)
    if not spec.verified:
        bound, expected = BOUND_UNVERIFIED, None
    else:
        times = {BOUND_MEMORY: t_hbm or 0.0, BOUND_COMPUTE: t_flops or 0.0,
                 BOUND_ICI: t_ici or 0.0}
        bound = max(times, key=times.get)
        expected = times[bound]
    # full precision throughout: toy-scale audits have sub-microsecond
    # expectations and rounding here would corrupt every downstream ratio
    return DispatchExpectation(
        kind=kind, steps=steps, bytes_per_step=b, flops_per_step=f,
        ici_bytes_per_step=i, t_hbm_ms=t_hbm, t_flops_ms=t_flops,
        t_ici_ms=t_ici, bound=bound, expected_ms_per_step=expected)


def hbm_utilization(bytes_per_step: float, step_ms: float,
                    spec: Optional[DeviceSpec] = None) -> Optional[float]:
    """Fraction of ``spec``'s peak HBM bandwidth a measured step achieved —
    the bench's headline roofline number, now derived from the ONE spec
    table. None on an unverified spec (the caller renames or refuses the
    key; it must not divide by a peak nobody vouched for)."""
    spec = spec if spec is not None else resolve_device_spec()
    if spec.hbm_bytes_per_s is None or step_ms <= 0:
        return None
    return bytes_per_step / (step_ms * 1e-3) / spec.hbm_bytes_per_s


class PerfModel:
    """Per-dispatch roofline expectations over the live dispatch registry.

    Expectations are cached per (kind, dispatch identity): the underlying
    ``AuditedDispatch.example_cost()`` AOT-compiles the captured example
    ONCE (hitting jax's persistent compile cache when enabled) — this runs
    only from offline analysis paths (profiled-window attribution, bench,
    scripts), never on the serving hot path."""

    def __init__(self, spec: Optional[DeviceSpec] = None):
        self.spec = spec if spec is not None else resolve_device_spec()
        self._cache: Dict[str, tuple] = {}    # kind -> (dispatch, expectation)

    def spec_dict(self) -> dict:
        return self.spec.to_dict()

    def expectation_for(self, dispatch) -> DispatchExpectation:
        """Expectation for a registered dispatch (raises when the dispatch
        has no captured example or cannot be AOT-compiled — callers on
        guarded paths catch and report, never mask)."""
        kind = dispatch.contract.kind
        hit = self._cache.get(kind)
        # validity = same dispatch AND same captured example: set_example()
        # re-captures build a new example tuple (and reset the registry-side
        # cost cache), so a stale expectation cannot outlive the example it
        # was derived from
        if (hit is not None and hit[0] is dispatch
                and hit[1] is dispatch.example):
            return hit[2]
        cost = dispatch.example_cost()
        exp = classify(kind, cost["bytes_accessed"], cost["flops"],
                       cost["collective_bytes"], self.spec,
                       steps=cost["steps"])
        self._cache[kind] = (dispatch, dispatch.example, exp)
        return exp

    def expectation(self, kind: str) -> Optional[DispatchExpectation]:
        """Expectation for the newest LIVE dispatch registered under
        ``kind`` (None when no such dispatch has captured an example)."""
        from .registry import find

        d = find(kind)
        if d is None or d.example is None:
            return None
        return self.expectation_for(d)

    @staticmethod
    def efficiency(expected_ms: Optional[float],
                   measured_ms: Optional[float]) -> Optional[float]:
        """Measured-vs-model efficiency: model expectation over measured
        device time (1.0 = at the roofline; >1 means the model under-counts
        — worth a look, not a victory)."""
        if expected_ms is None or not measured_ms or measured_ms <= 0:
            return None
        return expected_ms / measured_ms

    def join(self, timing: Mapping[str, dict],
             iterations: Optional[Mapping[str, int]] = None,
             dispatches: Optional[Mapping[str, object]] = None) -> dict:
        """Join a profiled per-kind ``timing`` table (PR 7
        ``attribute_device_time`` shape: ``{kind: {device_ms, dispatches,
        ...}}``) with the model: per kind, the expectation, the expected
        window time (``expected_ms_per_step x window iterations``) and the
        efficiency. ``dispatches`` maps timing kinds to the owning runner's
        AuditedDispatch objects (default: the global registry by kind name).
        Per-kind failures degrade to an ``error`` entry — one bad lowering
        must not take down the whole join."""
        by_kind: Dict[str, dict] = {}
        for kind, t in timing.items():
            d = (dispatches or {}).get(kind)
            try:
                exp = (self.expectation_for(d) if d is not None
                       else self.expectation(kind))
            except Exception as e:
                logger.warning("roofline model failed for %r: %s", kind, e)
                by_kind[kind] = {"error": f"{type(e).__name__}: {e}"}
                continue
            if exp is None:
                continue
            entry = exp.to_dict()
            iters = max(1, int((iterations or {}).get(
                kind, t.get("dispatches") or 1)))
            entry["window_iterations"] = iters
            dev_ms = t.get("device_ms")
            if dev_ms and exp.expected_ms_per_step is not None:
                expected = exp.expected_ms_per_step * iters
                entry["expected_window_ms"] = expected
                entry["measured_window_ms"] = dev_ms
                entry["efficiency"] = self.efficiency(expected, dev_ms)
            by_kind[kind] = entry
        return {"spec": self.spec_dict(), "by_kind": by_kind}
