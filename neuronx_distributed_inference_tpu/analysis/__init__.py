"""Graph-contract auditor + repo-specific lint pass.

Static verification of the dispatch invariants the serving stack's perf and
correctness story rests on (≈ the reference's compile-time guarantees: the KV
cache is ALIASED between graph inputs and outputs, `model_wrapper.py:1600-1612`,
and the serving loop never syncs mid-step):

- ``registry``:  every serving dispatch registers (fn, declared contract,
  captured example args) through ``audited_jit`` — donation is DERIVED from the
  declared cache args, so a mis-indexed ``donate_argnums`` cannot be written.
- ``auditor``:   lowers each registered dispatch to StableHLO + compiled HLO
  and statically verifies aliasing, host-sync freedom, dtype contracts,
  collective schedules and HBM/ICI byte budgets.
- ``lint``:      AST pass over the package with repo-specific rules (host syncs
  in step loops, unregistered ``jax.jit`` sites, tracer branches, stray
  prints, ...).

Run both via ``scripts/audit_graphs.py`` (JSON report, non-zero exit on any
unwaived violation) or the tier-1 ``contracts`` tests.
"""

from . import contracts, registry  # noqa: F401

__all__ = ["contracts", "registry"]
