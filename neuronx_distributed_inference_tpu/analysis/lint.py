"""Repo-specific AST lint pass over the package.

These are not style rules — each one encodes a serving invariant that grep or
review keeps missing:

``stray-print``     bare ``print(`` in library code (the CLI and env-gated
                    ``# debug-ok`` prints excepted) — subsumes the old
                    test_hygiene grep.
``raw-jit``         a ``jax.jit`` call inside ``runtime/`` or ``serving/``
                    that never registers with the auditor (every serving
                    dispatch must go through
                    ``analysis.registry.audited_jit`` so its contract is
                    machine-checked; one-shot utility jits carry an explicit
                    waiver comment).
``jit-no-donate``   a jitted function taking cache-named parameters
                    (``cache``/``t_cache``/``d_cache``/``kv_cache``/...)
                    whose donation does not cover them — the statically
                    visible half of the "donation silently failed" bug.
``tracer-branch``   a Python ``if`` on a (non-static) parameter of a traced
                    function — a retrace/ConcretizationError landmine.
``time-in-jit``     ``time.*`` inside a traced function (measures trace time
                    once, then becomes a constant).
``step-loop-sync``  ``.item()`` / ``.block_until_ready()`` /
                    ``jax.device_get`` inside a ``@step_loop_body``-marked
                    serving loop, or ``asarray`` conversions inside a
                    per-row python loop there (hoist them — PR 2 measured
                    per-window conversions at milliseconds per dispatch).
``telemetry-in-jit`` host telemetry/registry mutation inside TRACED code
                    (a jitted/audited_jit step fn or a def nested in one):
                    ``self.telemetry.*``, instrument mutators
                    (``._m_x.inc/observe``), or registry get-or-create calls
                    — a host-object mutation under trace runs once per
                    TRACE, not per step, so it silently records garbage.
                    Under a ``@step_loop_body`` host loop only registry
                    GET-OR-CREATE (``registry.counter/gauge/histogram``) is
                    flagged: instruments must be cached at construction, not
                    looked up per step (mutating a cached instrument there
                    is the designed pattern). The in-graph device carry
                    (utils/device_telemetry.py) is the sanctioned way to
                    count inside a graph.
``silent-except``   an ``except`` handler in ``serving/``/``runtime/`` that
                    SWALLOWS the failure: no re-raise, no logged reason, no
                    metrics counter anywhere in its body. Serving code
                    treats partial failure as the steady state — a
                    swallowed exception is a recovery path that silently
                    stopped recovering (the pre-ISSUE-11 fleet died of
                    exactly one of these reaching the frontend). Degrade
                    VISIBLY (log / count / re-raise) or waive with a
                    reason.

Waive a line with ``# lint: ok(<rule>)`` or ``# lint: ok(<rule>): reason``
(``# debug-ok`` keeps working for ``stray-print``). Waived findings are
REPORTED with their reason — suppression is visible, never silent.
"""

from __future__ import annotations

import ast
import functools
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["LintFinding", "lint_package", "lint_paths", "lint_source",
           "RULES", "PKG_ROOT"]

RULES = ("stray-print", "raw-jit", "jit-no-donate", "tracer-branch",
         "time-in-jit", "step-loop-sync", "telemetry-in-jit",
         "silent-except")

PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# files whose prints ARE the user interface
_PRINT_ALLOWED = {"inference_demo.py"}
_CACHE_PARAM_RE = re.compile(r"^(.*_)?cache$")
_WAIVE_RE = re.compile(r"lint:\s*ok\(([\w, -]+)\)(?::\s*(.*?))?\s*(?:#|$)")


@dataclass
class LintFinding:
    rule: str
    path: str
    line: int
    msg: str
    status: str = "fail"          # "fail" | "waived"
    reason: str = ""

    @property
    def violating(self) -> bool:
        return self.status == "fail"

    def __str__(self) -> str:
        tag = "" if self.status == "fail" else f" [waived: {self.reason}]"
        return f"{self.path}:{self.line}: [{self.rule}] {self.msg}{tag}"


def _dotted(node: ast.AST) -> str:
    """'jax.jit' for Attribute chains, 'audited_jit' for Names."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _const_str_tuple(node: Optional[ast.AST]) -> Tuple[str, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def _const_int_tuple(node: Optional[ast.AST]) -> Tuple[int, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, int))
    return ()


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


class _ModuleLint:
    def __init__(self, src: str, path: str, rel: str):
        self.src = src
        self.path = path
        self.rel = rel                       # package-relative, '/'-separated
        self.lines = src.splitlines()
        self.tree = ast.parse(src)
        self.findings: List[LintFinding] = []
        # names that mean jax.jit in this module: the dotted form plus any
        # `from jax import jit [as x]` / `import jax as j` alias — an
        # alias-imported dispatch site must not evade the raw-jit growth gate
        self.raw_jit_names = {"jax.jit"}
        for n in ast.walk(self.tree):
            if isinstance(n, ast.ImportFrom) and n.module == "jax":
                for a in n.names:
                    if a.name == "jit":
                        self.raw_jit_names.add(a.asname or a.name)
            elif isinstance(n, ast.Import):
                for a in n.names:
                    if a.name == "jax" and a.asname:
                        self.raw_jit_names.add(f"{a.asname}.jit")
        # every FunctionDef in the module, by name, ALL of them: local step
        # bodies reuse names across builder methods (continuous_batching.py
        # defines `_insert` three times), so a flat last-wins map would check
        # the wrong body — resolution picks the nearest def above the call
        self.fn_defs: Dict[str, List[ast.FunctionDef]] = {}
        for n in ast.walk(self.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.fn_defs.setdefault(n.name, []).append(n)

    # ---- waiver / emit ---------------------------------------------------
    def _line_waiver(self, lineno: int, rule: str) -> Optional[str]:
        # a waiver holds on the flagged line itself or on a COMMENT-ONLY line
        # directly above it (long call expressions push the comment onto its
        # own line). A waiver trailing a code line must NOT bleed onto the
        # next line — that would silently suppress an unwaived violation.
        for ln in (lineno, lineno - 1):
            line = self.lines[ln - 1] if 0 < ln <= len(self.lines) else ""
            if ln != lineno and not line.lstrip().startswith("#"):
                continue
            if rule == "stray-print" and "debug-ok" in line:
                m = re.search(r"debug-ok:?\s*(.*)", line)
                return (m.group(1).strip() if m and m.group(1).strip()
                        else "env-gated debug print")
            m = _WAIVE_RE.search(line)
            if m and rule in [r.strip() for r in m.group(1).split(",")]:
                return m.group(2) or "waived at line"
        return None

    def emit(self, rule: str, node: ast.AST, msg: str) -> None:
        reason = self._line_waiver(node.lineno, rule)
        self.findings.append(LintFinding(
            rule, self.rel, node.lineno, msg,
            status="waived" if reason is not None else "fail",
            reason=reason or ""))

    # ---- rules -----------------------------------------------------------
    def run(self) -> List[LintFinding]:
        self._rule_print()
        self._rule_silent_except()
        jit_calls = [n for n in ast.walk(self.tree)
                     if isinstance(n, ast.Call)
                     and (_dotted(n.func) in self.raw_jit_names
                          or _dotted(n.func) in ("audited_jit",
                                                 "registry.audited_jit"))]
        traced: List[Tuple[ast.FunctionDef, Tuple[str, ...]]] = []
        for call in jit_calls:
            is_raw = _dotted(call.func) in self.raw_jit_names
            if is_raw and self.rel.startswith(("runtime/", "serving/")):
                self.emit("raw-jit", call,
                          "jax.jit dispatch site never registers with the "
                          "graph auditor (use analysis.registry.audited_jit)")
            self._rule_no_donate(call, is_raw)
            target = self._resolve_target(call)
            if target is not None:
                statics = _const_str_tuple(_kw(call, "static_argnames"))
                traced.append((target, statics))
        for fn, statics in traced:
            self._rule_tracer_branch(fn, statics)
            self._rule_time(fn)
            self._rule_telemetry(fn, traced=True)
        for fn in (f for defs in self.fn_defs.values() for f in defs):
            if any(_dotted(d).split(".")[-1] == "step_loop_body"
                   for d in fn.decorator_list):
                self._rule_step_loop(fn)
                self._rule_telemetry(fn, traced=False)
        return self.findings

    def _rule_print(self) -> None:
        base = os.path.basename(self.path)
        if base in _PRINT_ALLOWED:
            return
        for n in ast.walk(self.tree):
            if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                    and n.func.id == "print"):
                self.emit("stray-print", n,
                          "bare print( in library code — log through the "
                          "tpu-inference logger or record telemetry")

    # visibility markers that make an except handler non-silent: the failure
    # is re-raised, logged, or counted — anything else is a swallow
    def _except_visible(self, handler: ast.ExceptHandler) -> bool:
        for n in ast.walk(handler):
            if isinstance(n, ast.Raise):
                return True
            if not isinstance(n, ast.Call):
                continue
            parts = _dotted(n.func).split(".")
            if not parts:
                continue
            if parts[0] in ("logger", "logging", "warnings"):
                return True
            attr, owner = parts[-1], parts[:-1]
            if attr in ("inc", "observe"):
                return True              # metrics counter/histogram mutation
            if attr == "set" and any(self._INSTRUMENT_RE.match(p)
                                     for p in owner):
                return True
        return False

    def _rule_silent_except(self) -> None:
        """Serving/runtime invariant (ISSUE-11): partial failure is the
        steady state, so every except handler must degrade VISIBLY. A
        handler with no re-raise, no log line, and no metrics mutation
        swallowed a failure the fleet will never hear about."""
        if not self.rel.startswith(("runtime/", "serving/")):
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Try):
                continue
            for h in node.handlers:
                if self._except_visible(h):
                    continue
                what = ("bare except" if h.type is None
                        else f"except {ast.unparse(h.type)}")
                self.emit("silent-except", h,
                          f"{what} swallows the failure — no re-raise, "
                          f"logged reason, or metrics counter in the "
                          f"handler; degrade visibly or waive with a reason")

    def _resolve_target(self, call: ast.Call) -> Optional[ast.FunctionDef]:
        if not (call.args and isinstance(call.args[0], ast.Name)):
            return None
        cands = self.fn_defs.get(call.args[0].id, [])
        # the `def _step(...)` -> jit(_step) idiom binds the def lexically
        # above the call: nearest preceding def wins (a same-named def further
        # down belongs to a different builder scope)
        prior = [f for f in cands if f.lineno <= call.lineno]
        if prior:
            return max(prior, key=lambda f: f.lineno)
        return cands[0] if cands else None

    def _rule_no_donate(self, call: ast.Call, is_raw: bool) -> None:
        target = self._resolve_target(call)
        if target is None:                    # cross-module target: can't see
            return
        params = [a.arg for a in target.args.posonlyargs + target.args.args]
        cache_idx = [i for i, p in enumerate(params)
                     if _CACHE_PARAM_RE.match(p)]
        if not cache_idx:
            return
        if is_raw:
            covered = set(_const_int_tuple(_kw(call, "donate_argnums")))
            covered |= {params.index(nm) for nm in
                        _const_str_tuple(_kw(call, "donate_argnames"))
                        if nm in params}
        else:
            names = _const_str_tuple(_kw(call, "cache_args")) + \
                _const_str_tuple(_kw(call, "donate_extra"))
            covered = {params.index(nm) for nm in names if nm in params}
        missing = [params[i] for i in cache_idx if i not in covered]
        if missing:
            self.emit("jit-no-donate", call,
                      f"jitted {target.name}() takes cache-shaped "
                      f"{missing} without donating them — the pool is "
                      f"double-buffered (2x KV HBM)")

    def _rule_tracer_branch(self, fn: ast.FunctionDef,
                            statics: Tuple[str, ...]) -> None:
        traced_params = {a.arg for a in fn.args.posonlyargs + fn.args.args
                         + fn.args.kwonlyargs} - set(statics)
        for sub in ast.walk(fn):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not fn:
                traced_params |= {a.arg for a in
                                  sub.args.posonlyargs + sub.args.args}
        for node in ast.walk(fn):
            if not isinstance(node, ast.If):
                continue
            # `x is None` / `x is not None` is a static pytree-shape branch
            if isinstance(node.test, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in node.test.ops):
                continue
            names = {n.id for n in ast.walk(node.test)
                     if isinstance(n, ast.Name)}
            hot = sorted(names & traced_params)
            if hot:
                self.emit("tracer-branch", node,
                          f"python `if` on tracer-typed {hot} inside traced "
                          f"{fn.name}() — use lax.cond/jnp.where or declare "
                          f"it static")

    def _rule_time(self, fn: ast.FunctionDef) -> None:
        for node in ast.walk(fn):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "time"):
                self.emit("time-in-jit", node,
                          f"time.{node.attr} inside traced {fn.name}() — "
                          f"evaluates once at trace time")

    # metric-instrument attribute prefixes the runner/telemetry caches use
    _INSTRUMENT_RE = re.compile(r"^_(m|c|g|h)_")

    def _rule_telemetry(self, fn: ast.FunctionDef, traced: bool) -> None:
        """Host telemetry under trace records once per TRACE; registry
        get-or-create in a host step loop allocates/hashes per step."""
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            parts = _dotted(node.func).split(".")
            if len(parts) < 2:
                continue
            attr, owner = parts[-1], parts[:-1]
            is_registry_create = (attr in ("counter", "gauge", "histogram")
                                  and any(p in ("registry", "reg", "metrics")
                                          for p in owner))
            if traced:
                is_tel = "telemetry" in owner
                is_mutator = (attr in ("inc", "observe", "set")
                              and any(self._INSTRUMENT_RE.match(p)
                                      for p in owner))
                if is_tel or is_mutator or is_registry_create:
                    self.emit("telemetry-in-jit", node,
                              f"host telemetry/registry call "
                              f"{_dotted(node.func)}() inside traced "
                              f"{fn.name}() — runs once per trace, not per "
                              f"step; thread the device telemetry carry "
                              f"(utils/device_telemetry.py) instead")
            elif is_registry_create:
                self.emit("telemetry-in-jit", node,
                          f"registry get-or-create {_dotted(node.func)}() "
                          f"inside step-loop body {fn.name}() — cache the "
                          f"instrument at construction (per-step name "
                          f"hashing + dict lookup on the hot path)")

    def _rule_step_loop(self, fn: ast.FunctionDef) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func,
                                                         ast.Attribute):
                if node.func.attr in ("item", "block_until_ready"):
                    self.emit("step-loop-sync", node,
                              f".{node.func.attr}() host sync inside "
                              f"step-loop body {fn.name}()")
                elif _dotted(node.func) == "jax.device_get":
                    self.emit("step-loop-sync", node,
                              f"jax.device_get inside step-loop body "
                              f"{fn.name}()")
        seen = set()          # nested loops re-walk inner bodies: one finding
        for loop in ast.walk(fn):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "asarray"
                        and (node.lineno, node.col_offset) not in seen):
                    seen.add((node.lineno, node.col_offset))
                    self.emit("step-loop-sync", node,
                              f"per-row asarray conversion inside a python "
                              f"loop in step-loop body {fn.name}() — hoist "
                              f"to one batched conversion per dispatch")


def lint_source(src: str, rel: str = "<memory>.py") -> List[LintFinding]:
    """Lint one source string (test hook)."""
    return _ModuleLint(src, rel, rel).run()


def lint_paths(paths: Sequence[str], root: str = PKG_ROOT
               ) -> List[LintFinding]:
    findings: List[LintFinding] = []
    for path in paths:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path) as fh:
            src = fh.read()
        try:
            findings += _ModuleLint(src, path, rel).run()
        except SyntaxError as e:
            findings.append(LintFinding("parse", rel, e.lineno or 0, str(e)))
    return findings


def package_files(root: str = PKG_ROOT) -> List[str]:
    out = []
    for dirpath, dirnames, files in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        out += [os.path.join(dirpath, f) for f in sorted(files)
                if f.endswith(".py")]
    return sorted(out)


@functools.lru_cache(maxsize=4)
def _lint_package_cached(root: str) -> Tuple[LintFinding, ...]:
    return tuple(lint_paths(package_files(root), root))


def lint_package(root: str = PKG_ROOT) -> List[LintFinding]:
    """Lint the whole package. Cached per root for the lifetime of the
    process (three tier-1 tests walk the package; source does not change
    mid-session) — `_lint_package_cached.cache_clear()` if it ever does."""
    return list(_lint_package_cached(root))
