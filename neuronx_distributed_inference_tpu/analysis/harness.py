"""Serving-fleet harness: build one tiny runtime of every dispatch flavor,
exercise it so each registered dispatch captures a real example, and hand the
auditor the resulting AuditUnits.

Shared by ``scripts/audit_graphs.py`` (full fleet, JSON report) and the tier-1
``tests/test_graph_contracts.py`` (reduced scope so the fast gate stays fast).
Everything here runs at toy scale — 2-layer 64-hidden llama on the CPU mesh —
because the properties the auditor checks (aliasing, host callbacks, dtype
discipline, collective multisets, RELATIVE byte budgets) are scale-invariant:
a dispatch that double-buffers its KV pool does so at every size.

Byte budgets: generic units get a declared ceiling of
``GENERIC_HBM_BUDGET_X x (example input bytes)`` per step — loose enough for
the known scan/gather taxes and XLA's conservative pallas-operand accounting,
tight enough to catch the round-1 class of regression (cache copies multiplying
traffic). The sharp, geometry-pinned budgets live in analysis/canaries.py.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from .auditor import AuditUnit
from .contracts import DispatchContract
from .registry import AuditedDispatch, find, live_dispatches

__all__ = ["FLEET_KINDS", "TINY_HF", "build_fleet_units", "generic_contract",
           "GENERIC_HBM_BUDGET_X"]

TINY_HF = {
    "model_type": "llama", "vocab_size": 256, "hidden_size": 64,
    "intermediate_size": 128, "num_hidden_layers": 2,
    "num_attention_heads": 4, "num_key_value_heads": 2,
    "max_position_embeddings": 512, "rms_norm_eps": 1e-5,
    "rope_theta": 10000.0, "tie_word_embeddings": False,
}

# generic per-step bytes-accessed ceiling, as a multiple of the dispatch's
# example input bytes (params + caches + activations). The jnp scan path's
# known cache-movement tax is ~2.6x the ideal working set; XLA charges pallas
# custom-call operands conservatively (whole pool per operand) — 8x input
# bytes clears both with margin while still failing on an extra O(pool) copy
# per layer.
GENERIC_HBM_BUDGET_X = 8.0


def _example_input_bytes(d: AuditedDispatch) -> Optional[float]:
    if d.example is None:
        return None
    args, kwargs = d.example
    leaves = jax.tree_util.tree_leaves((args, kwargs))
    sizes = [math.prod(x.shape) * np.dtype(x.dtype).itemsize
             for x in leaves if hasattr(x, "shape") and hasattr(x, "dtype")]
    return float(sum(sizes)) if sizes else None


def generic_contract(d: AuditedDispatch, *,
                     collectives="forbid") -> DispatchContract:
    """The fleet-wide declared contract for one registered dispatch: its own
    registration-time declarations plus the harness-level collective schedule
    (tp=1 fleet: no collectives at all) and the generic byte budget."""
    c = d.contract
    in_bytes = _example_input_bytes(d)
    return DispatchContract(
        kind=c.kind, cache_args=c.cache_args, carry_args=c.carry_args,
        donate_extra=c.donate_extra,
        steps_arg=c.steps_arg, host_sync_free=c.host_sync_free,
        fp32_accum=c.fp32_accum, max_upcast_elems=c.max_upcast_elems,
        collectives=collectives,
        hbm_bytes=(GENERIC_HBM_BUDGET_X * in_bytes
                   if in_bytes is not None else None),
        ici_bytes=0 if collectives == "forbid" else None,
        waivers=dict(c.waivers))


def _unit(kind: str, *, require: bool = True,
          collectives="forbid") -> List[AuditUnit]:
    d = find(kind)
    if d is None or d.example is None:
        if require:
            raise RuntimeError(
                f"fleet dispatch {kind!r} was never registered/exercised — "
                f"a runtime stopped registering its steps (or the harness "
                f"stopped exercising it)")
        return []
    return [AuditUnit(kind, d, contract=generic_contract(
        d, collectives=collectives))]


# ------------------------------------------------------------------- builders
# Each _exercise_* builder RETURNS the app/runner/engine it drove: the
# registry holds dispatches by weakref, so the caller must keep the owner
# alive until the AuditUnits take their own strong dispatch references.
def _tiny_app(paged: bool = False, cb: bool = False, slots: int = 2,
              hf: Optional[dict] = None, seed: int = 0, seq_len: int = 96):
    from ..config import (OnDeviceSamplingConfig, TpuConfig,
                          load_pretrained_config)
    from ..models.llama.modeling_llama import (LlamaForCausalLM,
                                               LlamaInferenceConfig)

    tpu_cfg = TpuConfig(
        batch_size=slots, seq_len=seq_len, max_context_length=32,
        dtype="float32", context_encoding_buckets=[16, 32],
        token_generation_buckets=[48, 96],
        is_continuous_batching=cb, paged_attention_enabled=paged,
        pa_num_blocks=48, pa_block_size=8,
        on_device_sampling_config=OnDeviceSamplingConfig())
    config = LlamaInferenceConfig(tpu_cfg,
                                  load_config=load_pretrained_config(hf or
                                                                     TINY_HF))
    app = LlamaForCausalLM(None, config)
    app.load_random(seed=seed)
    return app


def _prompts(sizes: Sequence[int], seed: int = 7) -> List[np.ndarray]:
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 256, size=(n,)).astype(np.int32) for n in sizes]


def _exercise_plain() -> Any:
    app = _tiny_app()
    (p_short, p_long) = _prompts((12, 40))
    # short prompt: prefill + decode; >max-CE-bucket prompt: windowed prefill
    app.generate(p_short[None, :], max_new_tokens=4)
    app.generate(np.stack([p_long, p_long]), max_new_tokens=4)
    return app


def _exercise_cb(paged: bool, mixed: bool = False) -> Any:
    from ..runtime.continuous_batching import ContinuousBatchingRunner

    app = _tiny_app(paged=paged, cb=True)
    kw = dict(prefill_chunk=16) if mixed else {}
    if paged and not mixed:
        # chunked inserts: a >cap prompt runs intermediate (KV-only) windows
        # through cb.paged.insert_nol before the final sampling window
        kw = dict(max_insert_tokens_per_step=16)
    runner = ContinuousBatchingRunner(app, decode_chunk=4, **kw)
    for p in _prompts((12, 19, 40)):
        runner.submit(p, max_new_tokens=6)
    runner.run_to_completion()
    return runner


def _exercise_cb_megastep() -> Any:
    """Device-resident serving megastep (ISSUE-10): run a paged CB runner
    whose plain decode dispatch is the lax.while_loop megastep, with a ring
    smaller than K so the ring-full service exit is exercised too (the
    executable is ONE program either way — n_iters is a dynamic operand)."""
    from ..runtime.continuous_batching import ContinuousBatchingRunner

    app = _tiny_app(paged=True, cb=True)
    runner = ContinuousBatchingRunner(app, decode_chunk=4, megastep_k=4,
                                      megastep_ring=4)
    for p in _prompts((12, 19)):
        runner.submit(p, max_new_tokens=6)
    runner.run_to_completion()
    if not runner._megastep_exit_counters:
        raise RuntimeError("megastep harness never dispatched a megastep — "
                           "the cb.paged.megastep example was not captured")
    return runner


def _exercise_flash_decode() -> Any:
    """Standalone flash-decode entry points (ISSUE-19 satellite): the four
    ``flash.*`` dispatches are module-level ``register_external`` wrappers, so
    they exist from import — but the auditor needs CPU-lowerable examples, and
    a prior caller may have captured non-interpret specs. Inject interpret-mode
    examples explicitly, then run each once."""
    import jax.numpy as jnp

    from ..ops import flash_decode as fd

    rng = np.random.default_rng(17)
    l, b, hq, hkv, d, s, bucket = 2, 2, 4, 2, 16, 64, 48
    q = jnp.asarray(rng.standard_normal((b, hq, 1, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hkv, bucket, d)), jnp.float32)
    pos = jnp.asarray([5, 9], jnp.int32)
    cache = jnp.asarray(rng.standard_normal((l, b, hkv, s, d)), jnp.float32)
    new = jnp.asarray(rng.standard_normal((b, hkv, 1, d)), jnp.float32)
    layer = jnp.asarray(0, jnp.int32)

    # block_k=16: the default 256 pads the 48-wide KV slice >5x, which reads
    # as byte traffic against the generic ceiling — pin an unpadded tiling
    fd.flash_decode_attention.set_example(q, k, k, pos, block_k=16,
                                          interpret=True)
    fd.flash_decode_attention(q, k, k, pos, block_k=16, interpret=True)
    fd.write_decode_stacked.set_example(cache, new, pos, layer, interpret=True)
    fd.write_decode_stacked(cache, new, pos, layer, interpret=True)
    fd.write_decode_stacked_kv.set_example(cache, cache, new, new, pos, layer,
                                           interpret=True)
    fd.write_decode_stacked_kv(cache, cache, new, new, pos, layer,
                               interpret=True)
    fd.flash_decode_attention_stacked.set_example(
        q, cache, cache, pos, layer, bucket=bucket, interpret=True)
    fd.flash_decode_attention_stacked(q, cache, cache, pos, layer,
                                      bucket=bucket, interpret=True)
    return fd


def _exercise_cb_spec_megastep() -> Any:
    """Device-resident speculative megastep (ISSUE-19 leg c): a paged spec
    runner with ``megastep_k`` set serves its draft-verify chunks through the
    cb.spec.megastep while_loop; the exit counters prove it dispatched."""
    from ..runtime.continuous_batching import ContinuousBatchingRunner

    target = _tiny_app(paged=True, cb=True, seed=0)
    draft_hf = dict(TINY_HF, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=1, num_attention_heads=2,
                    num_key_value_heads=2)
    draft = _tiny_app(paged=True, cb=True, hf=draft_hf, seed=1)
    runner = ContinuousBatchingRunner(target, draft=draft,
                                      speculation_length=4, spec_chunk=2,
                                      megastep_k=4, megastep_ring=4)
    for p in _prompts((12, 19)):
        runner.submit(p, max_new_tokens=6)
    runner.run_to_completion()
    if not runner._megastep_exit_counters:
        raise RuntimeError("spec megastep harness never dispatched — the "
                           "cb.spec.megastep example was not captured")
    return runner


def _exercise_cb_mixed_megastep() -> Any:
    """Mixed insert+decode megastep (ISSUE-19 leg c): a chunked-prefill runner
    with ``megastep_k`` set batches whole insert windows + decode steps into
    one cb.paged.mixed_megastep scan dispatch."""
    from ..runtime.continuous_batching import ContinuousBatchingRunner

    app = _tiny_app(paged=True, cb=True)
    runner = ContinuousBatchingRunner(app, decode_chunk=4, prefill_chunk=16,
                                      megastep_k=4, megastep_ring=4)
    for p in _prompts((12, 40)):
        runner.submit(p, max_new_tokens=8)
    runner.run_to_completion()
    d = find("cb.paged.mixed_megastep")
    if d is None or d.example is None:
        raise RuntimeError("mixed megastep harness never dispatched — the "
                           "cb.paged.mixed_megastep example was not captured")
    return runner


def _exercise_cb_spec() -> Any:
    from ..runtime.continuous_batching import ContinuousBatchingRunner

    target = _tiny_app(paged=True, cb=True, seed=0)
    draft_hf = dict(TINY_HF, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=1, num_attention_heads=2,
                    num_key_value_heads=2)
    draft = _tiny_app(paged=True, cb=True, hf=draft_hf, seed=1)
    runner = ContinuousBatchingRunner(target, draft=draft,
                                      speculation_length=4, spec_chunk=2)
    for p in _prompts((12, 40)):
        runner.submit(p, max_new_tokens=6)
    runner.run_to_completion()
    return runner


def _exercise_cb_eagle() -> Any:
    from ..models import eagle as eagle_lib
    from ..runtime.continuous_batching import ContinuousBatchingRunner
    from ..runtime.eagle import draft_args_from_target

    target = _tiny_app(paged=True, cb=True, seed=0)
    d_args = draft_args_from_target(target.arch_args, num_layers=1)
    d_params = eagle_lib.init_eagle_params(
        d_args, jax.random.PRNGKey(3),
        dtype=target.tpu_config.jax_dtype,
        inv_freq=target.inv_freq_from_config(target.config))
    runner = ContinuousBatchingRunner(
        target, eagle_draft=(d_args, d_params), speculation_length=3)
    for p in _prompts((12, 40)):
        runner.submit(p, max_new_tokens=6)
    runner.run_to_completion()
    return runner


def _exercise_spec() -> Any:
    from ..runtime.speculation import FusedSpeculativeModel

    target = _tiny_app(seed=0)
    draft_hf = dict(TINY_HF, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=1, num_attention_heads=2,
                    num_key_value_heads=2)
    draft = _tiny_app(hf=draft_hf, seed=1)
    spec = FusedSpeculativeModel(target, draft, speculation_length=3,
                                 greedy=True)
    ids = np.stack(_prompts((10, 10), seed=9))
    spec.generate(ids, max_new_tokens=6)
    return spec


def _exercise_eagle() -> Any:
    from ..runtime.eagle import EagleSpeculativeModel, draft_args_from_target

    target = _tiny_app(seed=0, seq_len=128)
    d_args = draft_args_from_target(target.arch_args, num_layers=1)
    spec = EagleSpeculativeModel(target, d_args, speculation_length=3)
    spec.load_random_draft(seed=5)
    ids = np.stack(_prompts((10, 10), seed=11))
    spec.generate(ids, max_new_tokens=6)
    return spec


def _exercise_eagle3() -> Any:
    from ..runtime.eagle import draft_args_from_target
    from ..runtime.eagle3 import Eagle3SpeculativeModel

    target = _tiny_app(seed=0, seq_len=128)
    d_args = draft_args_from_target(target.arch_args, num_layers=1)
    spec = Eagle3SpeculativeModel(target, d_args, depth=2, beam=2, branch=2)
    spec.load_random_draft(seed=6)
    ids = np.stack(_prompts((10, 10), seed=13))
    spec.generate(ids, max_new_tokens=6)
    return spec


def _exercise_medusa() -> Any:
    from ..runtime.medusa import MedusaModel

    app = _tiny_app(seed=0, seq_len=128)
    medusa = MedusaModel(app, num_medusa_heads=4)
    medusa.load_random_heads(seed=1)
    ids = np.stack(_prompts((10, 10), seed=15))
    medusa.generate(ids, max_new_tokens=6)
    return medusa


def _exercise_serving_tier() -> Any:
    """Host-RAM KV tiering through a paged CB runner: serve a prompt with two
    full prefix blocks, force the idle blocks to spill to the host tier, then
    serve a same-prefix prompt so the cb.paged.tier_readmit scatter actually
    dispatches (the audit needs its captured example). Then run a two-pool
    disaggregated fleet (serving/pools.py) so a prefill->decode live handoff
    drives the bucketed cb.paged.kv_handoff scatter on the decode side."""
    from ..runtime.continuous_batching import ContinuousBatchingRunner
    from ..serving.engine import EngineReplica
    from ..serving.kv_tiering import HostKVTier
    from ..serving.router import PrefixAffinityRouter

    app = _tiny_app(paged=True, cb=True)
    rng = np.random.default_rng(21)

    # pooled fleet FIRST: every tiered runner eagerly registers the
    # tier_readmit dispatch (later-wins), so the standalone spill/readmit
    # runner below must be constructed LAST to own the captured example; the
    # kv_handoff step is built lazily on first receive, so only d0 ever
    # registers it and its example survives.
    def _rep(rid: str, role: str) -> EngineReplica:
        # chunked prefill (insert cap) so committed blocks exist while the
        # source is still prefilling — the handoff stages DURING prefill
        return EngineReplica(
            rid, lambda tel: ContinuousBatchingRunner(
                app, decode_chunk=4, telemetry=tel,
                max_insert_tokens_per_step=16,
                kv_tier=HostKVTier(capacity_blocks=16)),
            pool_role=role)

    router = PrefixAffinityRouter(
        [_rep("p0", "prefill"), _rep("d0", "decode")],
        policy="remote_prefill", pool_config={"channel": "device"})
    router.submit(rng.integers(1, 256, size=(40,)).astype(np.int32),
                  max_new_tokens=6)
    router.run_to_completion()
    if router.pools.stats()["completed"] < 1:
        raise RuntimeError("pool harness never completed a handoff — the "
                           "cb.paged.kv_handoff example was not captured")

    tier = HostKVTier(capacity_blocks=16)
    runner = ContinuousBatchingRunner(app, decode_chunk=4, kv_tier=tier)
    prefix = rng.integers(1, 256, size=(16,)).astype(np.int32)   # 2 blocks
    tail = rng.integers(1, 256, size=(4,)).astype(np.int32)
    runner.submit(np.concatenate([prefix, tail]), max_new_tokens=4)
    runner.run_to_completion()
    if runner.spill_idle_blocks() < 2:
        raise RuntimeError("tier harness failed to spill the prefix blocks")
    runner.submit(np.concatenate([prefix, tail[::-1]]), max_new_tokens=4)
    runner.run_to_completion()
    if runner.kv_tier.readmit_blocks < 2:
        raise RuntimeError("tier harness never re-admitted — the "
                           "cb.paged.tier_readmit example was not captured")
    return (runner, router)


def _exercise_mm() -> Any:
    """Multimodal prefill: a tiny random Llava (Pixtral vision + Mistral text).

    Needs torch/transformers for the vision-side weights — callers treat an
    ImportError as "scope unavailable", never as a pass.
    """
    import torch
    from transformers import (LlavaConfig, LlavaForConditionalGeneration,
                              MistralConfig, PixtralVisionConfig)

    from ..config import TpuConfig, load_pretrained_config
    from ..models.pixtral import PixtralForConditionalGeneration

    vc = PixtralVisionConfig(hidden_size=32, intermediate_size=64,
                             num_hidden_layers=2, num_attention_heads=2,
                             image_size=16, patch_size=4, num_channels=3,
                             rope_theta=10000.0, hidden_act="gelu")
    tc = MistralConfig(vocab_size=256, hidden_size=48, intermediate_size=96,
                       num_hidden_layers=2, num_attention_heads=4,
                       num_key_value_heads=2, head_dim=12, sliding_window=None,
                       rope_theta=10000.0, tie_word_embeddings=False)
    cfg = LlavaConfig(vision_config=vc, text_config=tc, image_token_index=255,
                      projector_hidden_act="gelu", vision_feature_layer=-1,
                      vision_feature_select_strategy="full")
    torch.manual_seed(0)
    hf = LlavaForConditionalGeneration(cfg).eval()
    tpu_cfg = TpuConfig(batch_size=2, seq_len=64, max_context_length=32,
                        dtype="float32", context_encoding_buckets=[32],
                        token_generation_buckets=[64])
    config = PixtralForConditionalGeneration.get_config_cls()(
        tpu_cfg, load_config=load_pretrained_config(cfg.to_dict()))
    app = PixtralForConditionalGeneration(None, config)
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    app._put_params(app.convert_hf_state_dict(state, app.config))
    app.load_vision_from_state_dict(state)

    rng = np.random.default_rng(0)
    ids = rng.integers(1, 250, size=(2, 24)).astype(np.int32)
    ids[:, 2:18] = 255                                    # 16 image tokens
    pixels = rng.normal(size=(2, 3, 16, 16)).astype(np.float32)
    app.generate(ids, max_new_tokens=2, pixel_values=pixels)
    return app


def _exercise_moe() -> Any:
    """MoE serving scope (ISSUE-16): (a) a Mixtral-arch paged CB runner served
    end-to-end so the MoE decode trace (grouped kernel on the single-device
    fleet mesh) flows through the paged CB dispatches, and (b) the grouped
    decode expert matmul and its dense all-experts reference registered as
    standalone audited kinds — the roofline model's per-kind expectations
    (analysis/perf_model.py) read these examples."""
    import jax.numpy as jnp

    from ..config import (OnDeviceSamplingConfig, TpuConfig,
                          load_pretrained_config)
    from ..models.mixtral import MixtralForCausalLM
    from ..ops.moe import (MoEArgs, dense_all_experts, moe_decode_grouped,
                           route)
    from ..runtime.continuous_batching import ContinuousBatchingRunner
    from .registry import audited_jit

    moe_hf = dict(TINY_HF, model_type="mixtral", num_local_experts=4,
                  num_experts_per_tok=2, sliding_window=None)
    tpu_cfg = TpuConfig(
        batch_size=2, seq_len=96, max_context_length=32,
        dtype="float32", context_encoding_buckets=[16, 32],
        token_generation_buckets=[48, 96],
        is_continuous_batching=True, paged_attention_enabled=True,
        pa_num_blocks=48, pa_block_size=8,
        on_device_sampling_config=OnDeviceSamplingConfig())
    config = MixtralForCausalLM.get_config_cls()(
        tpu_cfg, load_config=load_pretrained_config(moe_hf))
    app = MixtralForCausalLM(None, config)
    app.load_random(seed=0)
    runner = ContinuousBatchingRunner(app, decode_chunk=4)
    for p in _prompts((12, 19)):
        runner.submit(p, max_new_tokens=6)
    runner.run_to_completion()

    rng = np.random.default_rng(3)
    e, h, i = 4, 64, 96
    margs = MoEArgs(num_experts=e, experts_per_tok=2)
    lp = {k: jnp.asarray(rng.normal(size=s, scale=0.1).astype(np.float32))
          for k, s in (("router", (h, e)), ("wg", (e, h, i)),
                       ("wu", (e, h, i)), ("wd", (e, i, h)))}

    def _grouped(lp, x):
        gates = route(lp["router"], x, margs)
        y = moe_decode_grouped(x, gates, lp, margs, jax.nn.silu)
        if y is None:
            raise RuntimeError("grouped MoE dispatch declined plain operands")
        return y

    def _dense(lp, x):
        gates = route(lp["router"], x, margs)
        return dense_all_experts(x, gates, lp, margs, jax.nn.silu)

    dg = audited_jit(_grouped, kind="moe.decode.grouped")
    dd = audited_jit(_dense, kind="moe.decode.dense")
    x = jnp.asarray(rng.normal(size=(8, h)).astype(np.float32))
    dg.set_example(lp, x)
    dd.set_example(lp, x)
    dg(lp, x), dd(lp, x)
    return (runner, dg, dd)


# scope name -> (exercise fn, kinds it must register+capture)
SCOPES: Dict[str, Tuple] = {
    "plain": (_exercise_plain,
              ("plain.prefill", "plain.decode", "plain.window")),
    "cb_dense": (lambda: _exercise_cb(False),
                 ("cb.dense.insert", "cb.dense.decode", "cb.dense.window",
                  "cb.dense.seed")),
    "cb_paged": (lambda: _exercise_cb(True),
                 ("cb.paged.insert", "cb.paged.insert_nol",
                  "cb.paged.decode")),
    "cb_mixed": (lambda: _exercise_cb(True, mixed=True),
                 ("cb.paged.mixed",)),
    "cb_megastep": (_exercise_cb_megastep, ("cb.paged.megastep",)),
    "cb_mixed_megastep": (_exercise_cb_mixed_megastep,
                          ("cb.paged.mixed_megastep",)),
    "cb_spec": (_exercise_cb_spec, ("cb.spec.chunk", "cb.spec.insert_pair")),
    "cb_spec_megastep": (_exercise_cb_spec_megastep, ("cb.spec.megastep",)),
    "flash_decode": (_exercise_flash_decode,
                     ("flash.decode", "flash.decode.stacked",
                      "flash.write.stacked", "flash.write.stacked_kv")),
    "cb_eagle": (_exercise_cb_eagle, ("cb.eagle.insert", "cb.eagle.chunk")),
    "serving_tier": (_exercise_serving_tier,
                     ("cb.paged.tier_readmit", "cb.paged.kv_handoff")),
    "spec": (_exercise_spec, ("spec.chunk",)),
    "eagle": (_exercise_eagle, ("eagle.prefill", "eagle.chunk")),
    "eagle3": (_exercise_eagle3, ("eagle3.prefill", "eagle3.chunk")),
    "medusa": (_exercise_medusa,
               ("medusa.prefill", "medusa.verify", "medusa.compact")),
    "mm": (_exercise_mm, ("mm.prefill", "mm.encode")),
    # LAST on purpose: the Mixtral paged-CB runner re-registers cb.paged.*
    # kinds and live_dispatches() is later-wins — keeping moe at the end means
    # the llama cb_* scopes above still own their kinds in a full-fleet run.
    "moe": (_exercise_moe, ("moe.decode.grouped", "moe.decode.dense")),
}

# every dispatch kind the full fleet exercises — DERIVED from SCOPES so the
# two can never drift (the mm scope needs torch/transformers for the tiny
# vision weights; the script skips it with a visible note when missing)
FLEET_KINDS = tuple(k for _, kinds in SCOPES.values() for k in kinds)


def build_fleet_units(scopes: Optional[Sequence[str]] = None,
                      ) -> Tuple[List[AuditUnit], List[str]]:
    """Exercise the requested scopes (default: all) and return
    (units-to-audit, notes). A scope whose optional deps are missing is
    reported in notes, not silently dropped."""
    notes: List[str] = []
    units: List[AuditUnit] = []
    for name in (scopes if scopes is not None else SCOPES):
        if name not in SCOPES:
            raise ValueError(f"unknown scope {name!r} "
                             f"(known: {sorted(SCOPES)})")
        fn, kinds = SCOPES[name]
        try:
            # the returned runner/app keeps the registry's weakrefs alive
            # until the units take their own strong dispatch references
            keepalive = fn()
        except ImportError as e:
            notes.append(f"scope {name!r} skipped: missing dep ({e})")
            continue
        for kind in kinds:
            units += _unit(kind)
        del keepalive
    return units, notes
