"""Dispatch contracts: what a registered serving dispatch PROMISES about its
compiled graph.

A contract is declarative data — the auditor (analysis/auditor.py) is the only
consumer. Every check carries a stable name so a finding can be waived
explicitly (``waivers={"check": "reason"}``): a waiver is a visible, reasoned
suppression recorded in the JSON report, never a silent one.

Check names
-----------
``aliasing``     every leaf of every declared cache arg is donated AND actually
                 aliased input->output in the lowered module (donation that
                 silently fails to alias is an invisible 2x KV HBM cost).
``host_sync``    no host callbacks (pure/io/debug callback custom-calls) and no
                 infeed/outfeed/send/recv in the lowered module.
``dtypes``       no f64 anywhere; with ``fp32_accum`` declared, at least one
                 bf16 x bf16 -> f32 contraction is present.
``upcast``       no bf16/f16 -> f32 convert producing a buffer at least as
                 large as the smallest cache leaf (a silently-upcast KV pool or
                 residual stream; small f32 islands — norms, softmax — pass).
``collectives``  the compiled module's collective-op multiset matches the
                 declared schedule ("forbid" = none at all; a dict = exact).
``hbm_bytes``    compiled cost-analysis bytes-accessed per step stays under the
                 declared ceiling.
``ici_bytes``    summed collective output bytes per dispatch stays under the
                 declared ceiling.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple, Union

CHECK_NAMES = ("aliasing", "host_sync", "dtypes", "upcast", "collectives",
               "hbm_bytes", "ici_bytes")


@dataclass
class DispatchContract:
    """Declared invariants for one registered serving dispatch.

    ``cache_args`` are PARAMETER NAMES (not indices): ``audited_jit`` resolves
    them against the function signature and derives ``donate_argnums`` from
    them, so a registered site cannot mis-index its donation by construction.
    """

    kind: str
    # cache-pytree parameters (donated + verified aliased + dtype-preserved)
    cache_args: Tuple[str, ...] = ()
    # small device-resident carry buffers (the in-graph telemetry block,
    # utils/device_telemetry.py): donated + verified aliased like a cache,
    # but EXCLUDED from the cache-sized upcast threshold — a 14-element
    # counter vector must not drag the "cache-leaf-sized" bar down to noise
    carry_args: Tuple[str, ...] = ()
    # additional donated parameters that are NOT caches (no aliasing required)
    donate_extra: Tuple[str, ...] = ()
    # static argname holding the per-dispatch iteration count; byte budgets
    # are normalized by its captured value (1 when None)
    steps_arg: Optional[str] = None
    host_sync_free: bool = True
    fp32_accum: bool = False
    # "auto": threshold = smallest cache-leaf element count from the captured
    # example; int: explicit element threshold; None: skip the check
    max_upcast_elems: Union[str, int, None] = "auto"
    # None: skip | "forbid": no collectives | dict op->count: exact multiset
    collectives: Union[None, str, Dict[str, int]] = None
    # absolute bytes-accessed ceiling per step (None: skip; cross-dispatch
    # RELATIVE budgets live in auditor.Rule, not here)
    hbm_bytes: Optional[float] = None
    # absolute collective-output-bytes ceiling per dispatch (None: skip)
    ici_bytes: Optional[float] = None
    # check name -> reason; a waived finding is reported, not enforced
    waivers: Dict[str, str] = field(default_factory=dict)

    def __post_init__(self) -> None:
        for name in self.waivers:
            if name not in CHECK_NAMES:
                raise ValueError(f"waiver for unknown check {name!r} "
                                 f"(known: {CHECK_NAMES})")
        if not self.kind:
            raise ValueError("contract needs a non-empty kind")


@dataclass
class Rule:
    """A cross-dispatch budget rule, evaluated AFTER all units are measured.

    ``fn(measurements)`` receives ``{unit_name: Measurement}`` and returns a
    list of violation strings (empty = pass). This is where the relational
    perf canaries live (table-width invariance, fused-vs-separate ratios,
    pinned collective schedules): one framework for ad-hoc thresholds that
    used to be scattered across tests/test_perf_regression.py.
    """

    name: str
    fn: Callable[[Dict[str, "Measurement"]], list]
    requires: Tuple[str, ...] = ()     # unit names the rule reads
    waiver: Optional[str] = None


@dataclass
class Measurement:
    """Per-unit numbers the auditor extracts from the compiled dispatch."""

    bytes_accessed: float = 0.0        # cost-analysis total for the dispatch
    steps: int = 1                     # captured steps_arg value (min 1)
    collective_counts: Dict[str, int] = field(default_factory=dict)
    collective_bytes: int = 0
    flops: float = 0.0

    @property
    def bytes_per_step(self) -> float:
        return self.bytes_accessed / max(1, self.steps)

    @property
    def collective_total(self) -> int:
        return sum(self.collective_counts.values())


def ratio_rule(name: str, a: str, b: str, max_ratio: float,
               waiver: Optional[str] = None) -> Rule:
    """bytes_per_step(a) <= max_ratio * bytes_per_step(b)."""

    def fn(m):
        xa, xb = m[a].bytes_per_step, m[b].bytes_per_step
        if xa > max_ratio * xb:
            return [f"{a} bytes/step {xa:.3g} exceeds "
                    f"{max_ratio} x {b} ({xb:.3g})"]
        return []

    return Rule(name, fn, requires=(a, b), waiver=waiver)


def min_growth_rule(name: str, a: str, b: str, min_ratio: float,
                    waiver: Optional[str] = None) -> Rule:
    """bytes_per_step(a) > min_ratio * bytes_per_step(b) — documents a cliff
    (e.g. the gather fallback really does scale with table width; if it stops
    growing, the kernel-vs-gather canaries are no longer measuring anything)."""

    def fn(m):
        xa, xb = m[a].bytes_per_step, m[b].bytes_per_step
        if xa <= min_ratio * xb:
            return [f"{a} bytes/step {xa:.3g} no longer grows past "
                    f"{min_ratio} x {b} ({xb:.3g}) — canary geometry is stale"]
        return []

    return Rule(name, fn, requires=(a, b), waiver=waiver)


def absolute_rule(name: str, a: str, ceiling: float,
                  waiver: Optional[str] = None) -> Rule:
    """bytes_per_step(a) <= ceiling."""

    def fn(m):
        xa = m[a].bytes_per_step
        if xa > ceiling:
            return [f"{a} bytes/step {xa:.3g} exceeds ceiling {ceiling:.3g}"]
        return []

    return Rule(name, fn, requires=(a,), waiver=waiver)


def collective_equal_rule(name: str, a: str, b: str, bytes_too: bool = True,
                          waiver: Optional[str] = None) -> Rule:
    """Collective-op multiset (and optionally ICI bytes) of a == b — the
    shape-invariance half of the pinned-schedule canary."""

    def fn(m):
        out = []
        if m[a].collective_counts != m[b].collective_counts:
            out.append(f"{a} collective schedule {m[a].collective_counts} != "
                       f"{b} {m[b].collective_counts}")
        if bytes_too and m[a].collective_bytes != m[b].collective_bytes:
            out.append(f"{a} collective bytes {m[a].collective_bytes} != "
                       f"{b} {m[b].collective_bytes}")
        return out

    return Rule(name, fn, requires=(a, b), waiver=waiver)


def collective_bound_rule(name: str, a: str, max_total: int,
                          require_ops: Tuple[str, ...] = (),
                          forbid_ops: Tuple[str, ...] = (),
                          waiver: Optional[str] = None) -> Rule:
    """Schedule size cap + required/forbidden op presence for one unit."""

    def fn(m):
        out = []
        counts = m[a].collective_counts
        total = sum(counts.values())
        if not 0 < total <= max_total:
            out.append(f"{a} collective count {total} outside (0, {max_total}]"
                       f" — a reintroduced (or vanished) per-layer collective")
        for op in require_ops:
            if counts.get(op, 0) <= 0:
                out.append(f"{a} missing required collective {op!r}: {counts}")
        for op in forbid_ops:
            if counts.get(op, 0) > 0:
                out.append(f"{a} carries forbidden collective {op!r}: {counts}")
        return out

    return Rule(name, fn, requires=(a,), waiver=waiver)
