"""Graph auditor: statically verify a registered dispatch's contract from its
lowered StableHLO and compiled HLO.

No execution happens here — every property is read off the compiled program,
which is the entire point: "the hot path has no host round trips" or "the KV
pool is read once" are properties of the GRAPH, and hoping the runtime behaves
is how round 1 shipped a 3x decode-traffic regression no test noticed.

What each check reads:

- ``aliasing``     ``lowered.args_info`` (donated flags) + the ``@main``
                   signature's ``tf.aliasing_output`` attributes, unioned with
                   the compiled module's ``input_output_alias={...}`` config
                   (multi-device lowerings defer alias placement to compile
                   time, so the StableHLO attribute alone under-reports on
                   tp>1 meshes). A donated buffer jax could not alias
                   (shape/dtype drift between the cache in and cache out)
                   appears in neither — that is the "donation silently
                   failed" disaster case, and it also subsumes the
                   dtype-preservation contract for caches (an int8 pool that
                   comes back bf16 cannot alias).
- ``host_sync``    callback custom-calls / infeed / outfeed / host send-recv
                   in the lowered module text.
- ``dtypes``       any ``f64`` tensor; declared fp32 accumulation present.
- ``upcast``       ``stablehlo.convert`` ops bf16/f16 -> f32 whose RESULT is
                   cache-leaf-sized or bigger.
- ``collectives``  op multiset from the optimized HLO
                   (parallel/overlap.collective_stats).
- ``hbm_bytes`` / ``ici_bytes``  XLA cost analysis / summed collective output
                   bytes against the declared ceilings.
"""

from __future__ import annotations

import contextlib
import math
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax

from ..parallel import overlap as overlap_lib
from .contracts import DispatchContract, Measurement, Rule
from .registry import AuditedDispatch

__all__ = ["AuditUnit", "Finding", "Report", "audit"]

_CALLBACK_RE = re.compile(
    r"xla_python_cpu_callback|xla_python_gpu_callback|xla_ffi_python"
    r"|stablehlo\.infeed|stablehlo\.outfeed"
    r"|stablehlo\.send|stablehlo\.recv")
_F64_RE = re.compile(r"tensor<(?:[0-9x]+x)?f64[>x]")
_FP32_ACCUM_RE = re.compile(
    r"dot_general[^\n]*\(tensor<[^>]*xbf16>,\s*tensor<[^>]*xbf16>\)"
    r"\s*->\s*tensor<[^>]*xf32>")
_UPCAST_RE = re.compile(
    r"stablehlo\.convert[^\n]*:\s*\(tensor<(?:[0-9x]+x)?(?:bf16|f16)>\)"
    r"\s*->\s*tensor<((?:\d+x)*)f32>")


@dataclass
class AuditUnit:
    """One auditable lowering: a registered dispatch, optionally re-specced.

    ``argmod`` transforms the captured example specs (e.g. widen the block
    table for an invariance variant); ``overrides`` replace keyword args
    (static chunk sizes); ``env`` pins trace-time environment toggles
    (TPUINF_PAGED_FUSED, TPUINF_TP_OVERLAP) for the duration of the lowering.
    """

    name: str
    dispatch: AuditedDispatch
    overrides: Dict[str, object] = field(default_factory=dict)
    argmod: Optional[Callable] = None
    env: Dict[str, str] = field(default_factory=dict)
    contract: Optional[DispatchContract] = None   # override (variants)

    def resolved_contract(self) -> DispatchContract:
        return self.contract or self.dispatch.contract


@dataclass
class Finding:
    unit: str
    check: str
    status: str          # "pass" | "fail" | "waived" | "skipped" | "error"
    detail: str = ""

    @property
    def violating(self) -> bool:
        return self.status in ("fail", "error")


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)
    measurements: Dict[str, Measurement] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not any(f.violating for f in self.findings)

    def violations(self) -> List[Finding]:
        return [f for f in self.findings if f.violating]

    def by_unit(self, unit: str) -> List[Finding]:
        return [f for f in self.findings if f.unit == unit]

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "findings": [vars(f).copy() for f in self.findings],
            "measurements": {
                k: {"bytes_accessed": m.bytes_accessed, "steps": m.steps,
                    "bytes_per_step": m.bytes_per_step,
                    "collective_counts": m.collective_counts,
                    "collective_bytes": m.collective_bytes}
                for k, m in self.measurements.items()},
        }


@contextlib.contextmanager
def _env_pinned(env: Dict[str, str]):
    prev = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        yield
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# --------------------------------------------------------------------------- lowering
def _lower_unit(unit: AuditUnit):
    d = unit.dispatch
    if d.example is None:
        raise RuntimeError(f"unit {unit.name!r}: dispatch "
                           f"{d.contract.kind!r} has no captured example")
    args, kwargs = d.example
    if unit.argmod is not None:
        args, kwargs = unit.argmod(args, dict(kwargs))
    kwargs = dict(kwargs, **unit.overrides)
    with _env_pinned(unit.env):
        return d._jit.lower(*args, **kwargs), (args, kwargs)


def _main_signature(text: str) -> str:
    for line in text.splitlines():
        if "func.func public @main(" in line:
            return line
    i = text.find("@main(")
    return text[i: text.find("\n", i)] if i >= 0 else ""


def _aliased_arg_indices(text: str) -> set:
    """Flat arg indices carrying ``tf.aliasing_output`` in the @main signature."""
    sig = _main_signature(text)
    out = set()
    chunks = re.split(r"%arg(\d+):", sig)
    # chunks: [pre, idx0, body0, idx1, body1, ...]
    for i in range(1, len(chunks) - 1, 2):
        if "tf.aliasing_output" in chunks[i + 1]:
            out.add(int(chunks[i]))
    return out


def _compiled_alias_param_indices(text: str) -> set:
    """Flat param indices aliased per the compiled HLO module's
    ``input_output_alias={ {out_idx}: (param_idx, {}, may-alias), ... }``
    header — where multi-device lowerings record the aliases the StableHLO
    ``tf.aliasing_output`` attribute carries on single-device ones."""
    start = text.find("input_output_alias={")
    if start < 0:
        return set()
    i = text.index("{", start)
    depth, j = 0, i
    for j in range(i, len(text)):
        if text[j] == "{":
            depth += 1
        elif text[j] == "}":
            depth -= 1
            if depth == 0:
                break
    span = text[i: j + 1]
    return {int(m.group(1)) for m in re.finditer(r"\(\s*(\d+)\s*,", span)}


def _flat_arg_layout(args: tuple, kwargs: dict, cache_names: Tuple[str, ...],
                     fn, static_names: Tuple[str, ...]) -> Tuple[dict, int]:
    """Map each declared cache arg name -> (start, stop) flat leaf range in
    jax's (args, kwargs) flatten order (statics excluded — they are not
    lowered args); returns (ranges, total_leaves)."""
    import inspect

    params = list(inspect.signature(fn).parameters)
    pos_names = params[: len(args)]
    ranges: Dict[str, Tuple[int, int]] = {}
    idx = 0
    for name, a in zip(pos_names, args):
        if name in static_names:
            continue
        n = len(jax.tree_util.tree_leaves(a))
        if name in cache_names:
            ranges[name] = (idx, idx + n)
        idx += n
    # keyword args flatten after positionals, in dict-key sorted order
    for name in sorted(kwargs):
        if name in static_names:
            continue
        n = len(jax.tree_util.tree_leaves(kwargs[name]))
        if name in cache_names:
            ranges[name] = (idx, idx + n)
        idx += n
    return ranges, idx


def _min_cache_leaf_elems(args: tuple, kwargs: dict,
                          cache_names: Tuple[str, ...], fn) -> Optional[int]:
    import inspect

    params = list(inspect.signature(fn).parameters)
    leaves = []
    for name, a in zip(params[: len(args)], args):
        if name in cache_names:
            leaves += jax.tree_util.tree_leaves(a)
    for name in cache_names:
        if name in kwargs:
            leaves += jax.tree_util.tree_leaves(kwargs[name])
    sizes = [math.prod(x.shape) for x in leaves if hasattr(x, "shape")]
    return min(sizes) if sizes else None


def _bytes_accessed(compiled) -> float:
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    # strict lookup: a missing key must surface as an audit ERROR, never as a
    # silent 0.0 that makes every byte ceiling vacuously pass
    return float(cost["bytes accessed"])


def _flops(compiled) -> float:
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    return float(cost.get("flops", 0.0))


# --------------------------------------------------------------------------- checks
def _emit(findings: List[Finding], contract: DispatchContract, unit: str,
          check: str, ok: bool, detail: str) -> None:
    if ok:
        findings.append(Finding(unit, check, "pass"))
    elif check in contract.waivers:
        findings.append(Finding(
            unit, check, "waived",
            f"{detail} [waived: {contract.waivers[check]}]"))
    else:
        findings.append(Finding(unit, check, "fail", detail))


def _audit_unit(unit: AuditUnit, findings: List[Finding],
                measurements: Dict[str, Measurement]) -> None:
    contract = unit.resolved_contract()
    lowered, (args, kwargs) = _lower_unit(unit)
    text = lowered.as_text()
    with _env_pinned(unit.env):
        compiled = lowered.compile()
    compiled_text = compiled.as_text()

    # ---- aliasing --------------------------------------------------------
    info_leaves = jax.tree_util.tree_leaves(
        lowered.args_info, is_leaf=lambda x: hasattr(x, "donated"))
    donated = {i for i, leaf in enumerate(info_leaves) if leaf.donated}
    aliased = (_aliased_arg_indices(text)
               | _compiled_alias_param_indices(compiled_text))
    ranges, total = _flat_arg_layout(
        args, kwargs,
        contract.cache_args + contract.carry_args + contract.donate_extra,
        unit.dispatch.fn, unit.dispatch.static_argnames)
    problems = []
    if total != len(info_leaves):
        problems.append(f"arg layout mismatch ({total} example leaves vs "
                        f"{len(info_leaves)} lowered args)")
    # carry buffers (the in-graph telemetry block) are held to the same
    # donated-AND-actually-aliased bar as caches — a carry that silently
    # fails to alias copies itself every dispatch
    for name in contract.cache_args + contract.carry_args:
        if name not in ranges:
            problems.append(f"cache arg {name!r} not found in example args")
            continue
        lo, hi = ranges[name]
        not_donated = [i for i in range(lo, hi) if i not in donated]
        if not_donated:
            problems.append(
                f"cache arg {name!r}: {len(not_donated)}/{hi - lo} leaves "
                f"NOT donated (flat args {not_donated[:6]}...) — the pool is "
                f"double-buffered")
        not_aliased = [i for i in range(lo, hi)
                       if i in donated and i not in aliased]
        if not_aliased:
            problems.append(
                f"cache arg {name!r}: donated leaves {not_aliased[:6]} carry "
                f"no input_output_alias — donation silently failed (shape/"
                f"dtype drift between cache in and cache out?)")
    # donate_extra args are donated to free memory, with NO aliasing promise
    # (contracts.py) — exclude them from the orphan catch-all
    extra_idx = set()
    for name in contract.donate_extra:
        if name in ranges:
            extra_idx |= set(range(*ranges[name]))
    orphans = donated - aliased - extra_idx
    if orphans and not problems:
        problems.append(f"donated args {sorted(orphans)[:6]} not aliased")
    if contract.cache_args or donated:
        _emit(findings, contract, unit.name, "aliasing", not problems,
              "; ".join(problems))
    else:
        findings.append(Finding(unit.name, "aliasing", "skipped",
                                "no cache args declared, nothing donated"))

    # ---- host_sync -------------------------------------------------------
    if contract.host_sync_free:
        hits = sorted(set(_CALLBACK_RE.findall(text)))
        _emit(findings, contract, unit.name, "host_sync", not hits,
              f"host-side ops in lowered graph: {hits}")
    else:
        findings.append(Finding(unit.name, "host_sync", "skipped",
                                "contract does not claim host-sync freedom"))

    # ---- dtypes ----------------------------------------------------------
    dt_problems = []
    if _F64_RE.search(text):
        dt_problems.append("f64 tensor present (silent x64 upcast)")
    if contract.fp32_accum and not _FP32_ACCUM_RE.search(text):
        dt_problems.append("declared fp32 accumulation, but no "
                           "bf16 x bf16 -> f32 contraction in the graph")
    _emit(findings, contract, unit.name, "dtypes", not dt_problems,
          "; ".join(dt_problems))

    # ---- upcast ----------------------------------------------------------
    threshold = contract.max_upcast_elems
    if threshold == "auto":
        threshold = _min_cache_leaf_elems(args, kwargs, contract.cache_args,
                                          unit.dispatch.fn)
    if threshold is None:
        findings.append(Finding(unit.name, "upcast", "skipped",
                                "no threshold (no cache args / disabled)"))
    else:
        big = []
        for m in _UPCAST_RE.finditer(text):
            dims = [int(d) for d in m.group(1).split("x") if d]
            elems = math.prod(dims) if dims else 1
            if elems >= threshold:
                big.append(elems)
        _emit(findings, contract, unit.name, "upcast", not big,
              f"bf16->f32 converts producing {big[:4]} elems "
              f"(>= cache-leaf threshold {threshold}) — a silently upcast "
              f"pool/residual stream")

    # ---- collectives + measurements --------------------------------------
    stats = overlap_lib.collective_stats(compiled_text)
    steps_arg = contract.steps_arg
    steps = 1
    if steps_arg is not None:
        v = unit.overrides.get(steps_arg, unit.dispatch.static_value(steps_arg))
        if v is None and steps_arg in kwargs:
            v = kwargs[steps_arg]
        steps = int(v) if v is not None else 1
    meas = Measurement(
        bytes_accessed=_bytes_accessed(compiled), steps=max(1, steps),
        collective_counts=dict(stats["counts"]),
        collective_bytes=int(stats["bytes"]), flops=_flops(compiled))
    measurements[unit.name] = meas

    decl = contract.collectives
    if decl is None:
        findings.append(Finding(unit.name, "collectives", "skipped",
                                "no schedule declared"))
    elif decl == "forbid":
        _emit(findings, contract, unit.name, "collectives",
              meas.collective_total == 0,
              f"collectives present in a declared-collective-free dispatch: "
              f"{meas.collective_counts}")
    else:
        _emit(findings, contract, unit.name, "collectives",
              meas.collective_counts == dict(decl),
              f"collective multiset {meas.collective_counts} != declared "
              f"{dict(decl)}")

    if contract.hbm_bytes is None:
        findings.append(Finding(unit.name, "hbm_bytes", "skipped", ""))
    else:
        _emit(findings, contract, unit.name, "hbm_bytes",
              meas.bytes_per_step <= contract.hbm_bytes,
              f"bytes/step {meas.bytes_per_step:.3g} exceeds declared ceiling "
              f"{contract.hbm_bytes:.3g}")
    if contract.ici_bytes is None:
        findings.append(Finding(unit.name, "ici_bytes", "skipped", ""))
    else:
        _emit(findings, contract, unit.name, "ici_bytes",
              meas.collective_bytes <= contract.ici_bytes,
              f"collective bytes {meas.collective_bytes} exceed declared "
              f"ceiling {contract.ici_bytes:.3g}")


def audit(units: Sequence[AuditUnit], rules: Sequence[Rule] = ()) -> Report:
    """Audit every unit, then evaluate cross-unit budget rules."""
    report = Report()
    for unit in units:
        try:
            _audit_unit(unit, report.findings, report.measurements)
        except Exception as e:  # an unauditable dispatch IS a violation
            report.findings.append(Finding(
                unit.name, "audit", "error",
                f"{type(e).__name__}: {e}"))
    for rule in rules:
        missing = [r for r in rule.requires if r not in report.measurements]
        if missing:
            report.findings.append(Finding(
                rule.name, "rule", "error",
                f"rule inputs never measured: {missing}"))
            continue
        try:
            violations = rule.fn(report.measurements)
        except Exception as e:
            report.findings.append(Finding(rule.name, "rule", "error",
                                           f"{type(e).__name__}: {e}"))
            continue
        if not violations:
            report.findings.append(Finding(rule.name, "rule", "pass"))
        elif rule.waiver:
            report.findings.append(Finding(
                rule.name, "rule", "waived",
                f"{'; '.join(violations)} [waived: {rule.waiver}]"))
        else:
            report.findings.append(Finding(rule.name, "rule", "fail",
                                           "; ".join(violations)))
    return report
