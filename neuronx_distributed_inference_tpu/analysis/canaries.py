"""Geometry-pinned budget canaries, migrated from tests/test_perf_regression.py
onto the registry/auditor framework.

Each canary is (AuditUnits at a pinned geometry) + (cross-unit Rules): the
auditor measures compiled bytes-accessed / collective schedules once per unit,
the rules encode the relations that used to live as scattered asserts —
table-width invariance, fused-vs-separate ratios, the one-KV-pass bound, the
pinned tp collective schedule. tests/test_perf_regression.py keeps its test
names as thin wrappers over these groups so history stays comparable.

The canary geometry (4-layer, 256-hidden, 66x128 block pool, bf16) is the
smallest shape where the paged-pool charges dominate params — at the tiny
2-layer harness scale the pool is noise and the ratios measure nothing.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .auditor import AuditUnit
from .contracts import (Rule, absolute_rule, collective_bound_rule,
                        collective_equal_rule, min_growth_rule, ratio_rule)
from .harness import generic_contract as _harness_contract
from .registry import audited_jit


def generic_contract(d, *, collectives="forbid"):
    """Canary-unit contract: the fleet checks minus the generic HBM ceiling —
    at the canary geometry XLA's conservative pallas-operand accounting can
    legitimately exceed it, and the RELATIONAL rules are the budget here."""
    return dataclasses.replace(_harness_contract(d, collectives=collectives),
                               hbm_bytes=None)

__all__ = ["CANARY_HF", "build_canary_units", "canary_group", "clear_caches",
           "GROUPS"]

CANARY_HF = {
    "model_type": "llama", "vocab_size": 256, "hidden_size": 256,
    "intermediate_size": 512, "num_hidden_layers": 4,
    "num_attention_heads": 2, "num_key_value_heads": 2,
    "max_position_embeddings": 1024, "rms_norm_eps": 1e-5,
    "rope_theta": 10000.0, "tie_word_embeddings": False,
}

_POOL_BYTES = 66 * 128 * 2 * 128 * 2       # blocks x BS x Hkv x D x bf16
_ONE_KV_PASS = CANARY_HF["num_hidden_layers"] * 2 * 2 * _POOL_BYTES


@functools.lru_cache(maxsize=None)
def _dense_app(kernel):
    from ..config import TpuConfig, load_pretrained_config
    from ..models.llama.modeling_llama import (LlamaForCausalLM,
                                               LlamaInferenceConfig)

    cfg = TpuConfig(batch_size=8, seq_len=512, max_context_length=128,
                    dtype="bfloat16", context_encoding_buckets=[128],
                    token_generation_buckets=[512],
                    decode_kernel_enabled=kernel)
    config = LlamaInferenceConfig(cfg,
                                  load_config=load_pretrained_config(CANARY_HF))
    app = LlamaForCausalLM(None, config)
    app.load_random(seed=0)
    return app


@functools.lru_cache(maxsize=None)
def _paged_runner(kernel, tp=1, sp=False, b=8, steps=4, tag="", mega=0):
    """``tag`` keys ENV-variant units (fused/separate, overlap/fallback) to
    their own runner: jax caches the traced jaxpr per jit object, so two
    lowerings of ONE dispatch under different trace-time env toggles would
    silently reuse the first trace — each variant needs its own jit.
    ``mega`` > 0 builds the runner with the device-resident megastep
    (megastep_k = megastep_ring = mega) so its while_loop dispatch exists."""
    from ..config import TpuConfig, load_pretrained_config
    from ..models.llama.modeling_llama import (LlamaForCausalLM,
                                               LlamaInferenceConfig)
    from ..runtime.continuous_batching import ContinuousBatchingRunner

    del tag
    cfg = TpuConfig(batch_size=b, seq_len=4096, max_context_length=128,
                    dtype="bfloat16", context_encoding_buckets=[128],
                    token_generation_buckets=[512],
                    is_continuous_batching=True, paged_attention_enabled=True,
                    pa_num_blocks=66, pa_block_size=128,
                    decode_kernel_enabled=kernel, tp_degree=tp,
                    sequence_parallel_enabled=sp)
    config = LlamaInferenceConfig(cfg,
                                  load_config=load_pretrained_config(CANARY_HF))
    app = LlamaForCausalLM(None, config)
    app.load_random(seed=0)
    kw = dict(megastep_k=mega, megastep_ring=mega) if mega else {}
    return app, ContinuousBatchingRunner(app, decode_chunk=steps, **kw)


def _set_paged_decode_example(app, runner, b=8, steps=4, mb=4):
    from ..ops import sampling as sampling_ops
    from ..utils import device_telemetry as dtel

    sp = sampling_ops.prepare_sampling_params(b)
    runner._decode_step.set_example(
        app.params, jnp.zeros((b,), jnp.int32), jnp.full((b,), 128, jnp.int32),
        jnp.ones((b,), bool), jnp.full((b,), 64, jnp.int32), runner.cache,
        dtel.init_carry(),
        jnp.zeros((b, mb), jnp.int32), jnp.zeros((b, steps), jnp.int32),
        sp, jax.random.PRNGKey(0), jnp.zeros((b,), jnp.int32),
        jnp.full((b,), -1, jnp.int32), num_steps=steps)


def _widen_table(arg_idx, mb):
    """argmod widening the block table (positional ``arg_idx``) to ``mb``."""

    def mod(args, kwargs):
        args = list(args)
        bt = args[arg_idx]
        args[arg_idx] = jax.ShapeDtypeStruct((bt.shape[0], mb), bt.dtype)
        return tuple(args), kwargs

    return mod


def _paged_decode_unit(name, kernel, mb, fused=True, tp=1, sp=False, b=8,
                       steps=4, env_extra=None, collectives="forbid"):
    env = {"TPUINF_PAGED_FUSED": "1" if fused else "0"}
    env.update(env_extra or {})
    app, runner = _paged_runner(kernel, tp=tp, sp=sp, b=b, steps=steps,
                                tag=",".join(f"{k}={v}" for k, v in
                                             sorted(env.items())))
    _set_paged_decode_example(app, runner, b=b, steps=steps, mb=4)
    return AuditUnit(
        name, runner._decode_step, argmod=_widen_table(7, mb), env=env,
        contract=generic_contract(runner._decode_step,
                                  collectives=collectives))


# --------------------------------------------------------------------- groups
def _group_dense_decode() -> Tuple[List[AuditUnit], List[Rule]]:
    """Dense decode per-step traffic: jnp path within 3x of the ideal working
    set; the Pallas stacked-cache path never pays MORE than the jnp path."""
    from ..ops import sampling as sampling_ops

    units = []
    for tag, kernel in (("jnp", False), ("kernel", True)):
        app = _dense_app(kernel)
        app.reset_cache()
        b = app.tpu_config.max_batch_size
        sp = sampling_ops.prepare_sampling_params(b)
        app._decode_step.set_example(
            app.params, jnp.zeros((b,), jnp.int32),
            np.full((b,), 128, np.int32), app.kv_cache, sp,
            jax.random.PRNGKey(0), decode_bucket=512, num_steps=4,
            with_logits=False, greedy=True)
        units.append(AuditUnit(f"dense_decode_{tag}", app._decode_step,
                               contract=generic_contract(app._decode_step)))
    app = _dense_app(False)
    ideal = (sum(x.nbytes for x in jax.tree.leaves(app.params))
             + sum(x.nbytes for x in jax.tree.leaves(app.kv_cache)))
    rules = [
        absolute_rule("dense_decode_bytes_bounded", "dense_decode_jnp",
                      3.0 * ideal),
        ratio_rule("kernel_decode_not_more_traffic", "dense_decode_kernel",
                   "dense_decode_jnp", 1.1),
    ]
    return units, rules


def _group_fused_paged() -> Tuple[List[AuditUnit], List[Rule]]:
    """Fused append+attend: table-width-invariant traffic, <=0.25x the
    separate write-then-attend charge, and within 2x of one aliased KV pass."""
    units = [
        _paged_decode_unit("fused_mb4", True, 4, fused=True),
        _paged_decode_unit("fused_mb32", True, 32, fused=True),
        _paged_decode_unit("separate_mb4", True, 4, fused=False),
    ]
    rules = [
        ratio_rule("fused_table_invariant", "fused_mb32", "fused_mb4", 1.02),
        ratio_rule("fused_vs_separate", "fused_mb4", "separate_mb4", 0.25),
        absolute_rule("fused_one_kv_pass", "fused_mb4", 2.0 * _ONE_KV_PASS),
    ]
    return units, rules


def _group_paged_table_width() -> Tuple[List[AuditUnit], List[Rule]]:
    """q_len=1 paged decode: kernel traffic invariant to table width; the
    gather fallback grows with it (documents the cliff the kernel avoids)."""
    units = [
        _paged_decode_unit("paged_kern_mb4", True, 4),
        _paged_decode_unit("paged_kern_mb32", True, 32),
        _paged_decode_unit("paged_gather_mb4", None, 4),
        _paged_decode_unit("paged_gather_mb32", None, 32),
    ]
    rules = [
        ratio_rule("paged_kernel_table_invariant", "paged_kern_mb32",
                   "paged_kern_mb4", 1.02),
        min_growth_rule("paged_gather_grows_with_table", "paged_gather_mb32",
                        "paged_gather_mb4", 1.15),
    ]
    return units, rules


def _mq_verify_dispatch(app, use_kernel):
    """Registered canary dispatch for the multi-query (spec verify) attend."""
    from ..models import base as model_base

    def _verify(params, ids, positions, cache, bt, sm):
        return model_base.decode_forward(
            params, app.arch_args, ids, positions, cache, None,
            mesh=app.mesh, rules=app.sharding_rules, block_table=bt,
            slot_mapping=sm, use_kernel=use_kernel)

    return audited_jit(_verify, kind="canary.mq_verify",
                       cache_args=("cache",))


def _group_multiquery() -> Tuple[List[AuditUnit], List[Rule]]:
    """q_len>1 (speculative verify) attend: same invariance/cliff pair."""
    units = []
    b, t = 8, 4
    for tag, kernel in (("kern", True), ("gather", None)):
        app, _ = _paged_runner(kernel)
        cache = app.make_paged_cache(66, 128)
        d = _mq_verify_dispatch(app, bool(kernel))
        d.set_example(app.params, jnp.zeros((b, t), jnp.int32),
                      jnp.full((b,), 128, jnp.int32), cache,
                      jnp.zeros((b, 4), jnp.int32),
                      jnp.zeros((b, t), jnp.int32))
        for mb in (4, 32):
            units.append(AuditUnit(
                f"mq_{tag}_mb{mb}", d, argmod=_widen_table(4, mb),
                contract=generic_contract(d)))
    rules = [
        ratio_rule("mq_kernel_table_invariant", "mq_kern_mb32", "mq_kern_mb4",
                   1.02),
        min_growth_rule("mq_gather_grows_with_table", "mq_gather_mb32",
                        "mq_gather_mb4", 1.15),
    ]
    return units, rules


def _mixed_chunk_dispatch(app, use_kernel):
    """Registered canary dispatch for the mixed-step variable-q_len attend."""
    from ..models import base as model_base

    def _chunk(params, ids, positions, q_lens, cache, bt, sm):
        return model_base.decode_forward(
            params, app.arch_args, ids, positions, cache, None,
            mesh=app.mesh, rules=app.sharding_rules, block_table=bt,
            slot_mapping=sm, use_kernel=use_kernel, q_lens=q_lens,
            logit_idx=q_lens - 1)

    return audited_jit(_chunk, kind="canary.mixed_chunk",
                       cache_args=("cache",))


def _group_mixed_chunk(chunk_lens=(64, 128, 256)
                       ) -> Tuple[List[AuditUnit], List[Rule]]:
    """Mixed-step chunk attend at q_len 64/128/256 must ride the variable-
    q_len kernel (table-invariant); the gather fallback grows with the table.

    Widths 16 vs 32 for the kernel: below 16 blocks the per-cell geometry is
    table-bound, so the invariance pair must sit where only the table grows.
    """
    units: List[AuditUnit] = []
    rules: List[Rule] = []
    b = 4
    app, _ = _paged_runner(True, b=b)
    cache = app.make_paged_cache(66, 128)
    for t in chunk_lens:
        # one dispatch per chunk length: examples are per-dispatch state
        d = _mixed_chunk_dispatch(app, True)
        d.set_example(app.params, jnp.zeros((b, t), jnp.int32),
                      jnp.full((b,), 64, jnp.int32),
                      jnp.full((b,), t, jnp.int32), cache,
                      jnp.zeros((b, 16), jnp.int32),
                      jnp.zeros((b, t), jnp.int32))
        for mb in (16, 32):
            units.append(AuditUnit(
                f"mixed_kern_t{t}_mb{mb}", d, argmod=_widen_table(5, mb),
                contract=generic_contract(d)))
        rules.append(ratio_rule(f"mixed_kernel_table_invariant_t{t}",
                                f"mixed_kern_t{t}_mb32",
                                f"mixed_kern_t{t}_mb16", 1.02))
    app_g, _ = _paged_runner(None, b=b)
    cache_g = app_g.make_paged_cache(66, 128)
    dg = _mixed_chunk_dispatch(app_g, False)
    t = 64
    dg.set_example(app_g.params, jnp.zeros((b, t), jnp.int32),
                   jnp.full((b,), 64, jnp.int32),
                   jnp.full((b,), t, jnp.int32), cache_g,
                   jnp.zeros((b, 4), jnp.int32),
                   jnp.zeros((b, t), jnp.int32))
    for mb in (4, 32):
        units.append(AuditUnit(
            f"mixed_gather_mb{mb}", dg, argmod=_widen_table(5, mb),
            contract=generic_contract(dg)))
    rules.append(min_growth_rule("mixed_gather_grows_with_table",
                                 "mixed_gather_mb32", "mixed_gather_mb4",
                                 1.15))
    return units, rules


def _set_megastep_example(app, runner, b=8, ring=4, mb=4):
    from ..ops import sampling as sampling_ops
    from ..utils import device_telemetry as dtel

    sp = sampling_ops.prepare_sampling_params(b)
    runner._megastep_step.set_example(
        app.params, jnp.zeros((b,), jnp.int32), jnp.full((b,), 128, jnp.int32),
        jnp.ones((b,), bool), jnp.full((b,), 64, jnp.int32), runner.cache,
        dtel.init_carry(), jnp.zeros((b, mb), jnp.int32),
        jnp.full((b,), 4096, jnp.int32), sp, jax.random.PRNGKey(0),
        jnp.zeros((b,), jnp.int32), jnp.full((b,), -1, jnp.int32),
        jnp.asarray(ring, jnp.int32), jnp.asarray(0, jnp.int32),
        ring_cap=ring, greedy=True)


def _group_megastep() -> Tuple[List[AuditUnit], List[Rule]]:
    """ISSUE-10 megastep canary: the device-resident while_loop serving step
    is ONE executable whose compiled HBM traffic is ~K-invariant — weights
    and caches are passed (and charged) ONCE however many inner steps the
    loop runs. The K sweep rides the only K-shaped static (the ring
    capacity); the in-loop iteration count itself is a dynamic operand, so a
    4x ring sweep bounding byte growth at 2% pins exactly the "dispatch floor
    amortizes K×, bytes don't" property the bs=1 bench phase banks on. The
    absolute rule bounds the whole megastep at 16x one weights+KV-pool pass
    (measured 11.6x at this geometry: XLA charges pallas custom-call
    operands whole-pool per operand and the while body's charges stack on
    the entry/exit copies — the rule is a regression tripwire against an
    extra O(pool) copy, not a sharp bound)."""
    b, ring = 8, 4
    app, runner = _paged_runner(True, b=b, mega=ring, tag="mega")
    _set_megastep_example(app, runner, b=b, ring=ring, mb=4)
    d = runner._megastep_step
    units = [
        AuditUnit("megastep_ring4", d, contract=generic_contract(d)),
        AuditUnit("megastep_ring16", d, overrides={"ring_cap": 16},
                  contract=generic_contract(d)),
    ]
    ideal = (sum(x.nbytes for x in jax.tree.leaves(app.params))
             + sum(x.nbytes for x in jax.tree.leaves(runner.cache)))
    rules = [
        ratio_rule("megastep_bytes_k_invariant", "megastep_ring16",
                   "megastep_ring4", 1.02),
        absolute_rule("megastep_one_weights_pass", "megastep_ring4",
                      16.0 * ideal),
    ]
    return units, rules


def _group_tp_collectives() -> Tuple[List[AuditUnit], List[Rule]]:
    """The PR-5 multichip canary: the tp>1 paged decode step's collective
    schedule is pinned per layer and table/batch-shape-invariant; the overlap
    path carries ring permutes, the GSPMD fallback carries none."""
    units = []
    for name, mb, b, overlap in (
            ("tp_mb4", 4, 8, True), ("tp_mb32", 32, 8, True),
            ("tp_b4", 4, 4, True), ("tp_fallback", 4, 8, False)):
        units.append(_paged_decode_unit(
            name, None, mb, tp=2, sp=True, b=b, steps=2,
            env_extra={"TPUINF_TP_OVERLAP": "1" if overlap else "0"},
            collectives=None))
    rules = [
        collective_equal_rule("tp_schedule_table_invariant", "tp_mb32",
                              "tp_mb4", bytes_too=True),
        collective_equal_rule("tp_schedule_batch_invariant", "tp_b4",
                              "tp_mb4", bytes_too=False),
        collective_bound_rule("tp_schedule_pinned", "tp_mb4", max_total=48,
                              require_ops=("collective-permute",)),
        collective_bound_rule("tp_fallback_no_ring", "tp_fallback",
                              max_total=64,
                              forbid_ops=("collective-permute",)),
    ]
    return units, rules


def _group_amla() -> Tuple[List[AuditUnit], List[Rule]]:
    """ISSUE-19 leg a canary: AMLA exponent-add rescaling is COMPUTE-only —
    it swaps the flash rescale multiplies for exponent-field adds inside the
    kernel and touches no new operands, so the compiled decode-step traffic
    must be byte-identical (both directions bounded at 0.1%) to the classic
    multiply path. An AMLA 'optimization' that materializes scratch in HBM
    would trip this immediately."""
    units = [
        _paged_decode_unit("amla_on", True, 4,
                           env_extra={"TPUINF_AMLA": "1"}),
        _paged_decode_unit("amla_off", True, 4,
                           env_extra={"TPUINF_AMLA": "0"}),
    ]
    rules = [
        ratio_rule("amla_zero_extra_hbm", "amla_on", "amla_off", 1.001),
        ratio_rule("amla_zero_hbm_savings", "amla_off", "amla_on", 1.001),
    ]
    return units, rules


def _group_lenpar() -> Tuple[List[AuditUnit], List[Rule]]:
    """ISSUE-19 leg b canary: the KV-length split re-shards the SAME block
    walk across grid rows — the pool is still streamed once (the only new
    traffic is the (splits, B, R) raw flash state the jnp merge reads back),
    so split-on vs split-off compiled bytes must agree within 2%, and the
    split step stays within the fused one-KV-pass absolute budget.

    Geometry: bs=1 with a 32-wide table — the long-context small-batch regime
    `_auto_kv_splits` targets (b*hkv = 2 row/head units, 4-way split at
    MB=32). The env pair keys separate runners (trace-time toggle)."""
    units = [
        _paged_decode_unit("lenpar_on_mb32", True, 32, b=1,
                           env_extra={"TPUINF_LENPAR": "1"}),
        _paged_decode_unit("lenpar_off_mb32", True, 32, b=1,
                           env_extra={"TPUINF_LENPAR": "0"}),
    ]
    rules = [
        ratio_rule("lenpar_split_byte_invariant", "lenpar_on_mb32",
                   "lenpar_off_mb32", 1.02),
        absolute_rule("lenpar_one_kv_pass", "lenpar_on_mb32",
                      2.0 * _ONE_KV_PASS),
    ]
    return units, rules


@functools.lru_cache(maxsize=None)
def _spec_canary_runner(tag=""):
    """Draft/target paged CB runner at canary geometry with the device-
    resident speculative megastep. The cb.spec.megastep example is captured
    from a REAL serving state (prompts run to completion) — its operand list
    (sampling matrix, eos table, coverage) is runner-internal and not worth
    hand-pinning."""
    from ..config import TpuConfig, load_pretrained_config
    from ..models.llama.modeling_llama import (LlamaForCausalLM,
                                               LlamaInferenceConfig)
    from ..runtime.continuous_batching import ContinuousBatchingRunner

    del tag

    def build(hf, seed):
        cfg = TpuConfig(batch_size=4, seq_len=4096, max_context_length=128,
                        dtype="bfloat16", context_encoding_buckets=[128],
                        token_generation_buckets=[512],
                        is_continuous_batching=True,
                        paged_attention_enabled=True,
                        pa_num_blocks=66, pa_block_size=128,
                        decode_kernel_enabled=True)
        config = LlamaInferenceConfig(
            cfg, load_config=load_pretrained_config(hf))
        app = LlamaForCausalLM(None, config)
        app.load_random(seed=seed)
        return app

    target = build(CANARY_HF, 0)
    draft_hf = dict(CANARY_HF, hidden_size=128, intermediate_size=256,
                    num_hidden_layers=1)
    draft = build(draft_hf, 1)
    runner = ContinuousBatchingRunner(target, draft=draft,
                                      speculation_length=4, spec_chunk=2,
                                      megastep_k=4, megastep_ring=4)
    rng = np.random.default_rng(0)
    for n in (12, 19):
        runner.submit(rng.integers(1, 256, size=(n,)).astype(np.int32),
                      max_new_tokens=6)
    runner.run_to_completion()
    if not runner._megastep_exit_counters:
        raise RuntimeError("spec megastep canary never dispatched")
    return target, runner


def _group_spec_megastep() -> Tuple[List[AuditUnit], List[Rule]]:
    """ISSUE-19 leg c canary: the SPECULATIVE serving megastep is ONE
    executable whose compiled traffic is ~K-invariant — both model's weights
    and both KV pools are passed (and charged) ONCE however many fused
    draft-verify-accept iterations the while_loop runs. As with the plain
    megastep canary, the only K-shaped static is the emitted-acceptance ring
    capacity; a 4x ring sweep must move compiled bytes by <2%. The absolute
    rule bounds the dispatch at 32x one (target+draft) weights+pools pass
    (measured 26x at this geometry: the K-deep draft chain and the verify
    each charge the pallas pool operands whole, per call) — the tripwire
    against an extra O(pool) copy in the loop body, not a sharp bound."""
    target, runner = _spec_canary_runner(tag="spec_mega")
    d = runner._spec_megastep_step
    units = [
        AuditUnit("spec_megastep_ring4", d, contract=generic_contract(d)),
        AuditUnit("spec_megastep_ring16", d, overrides={"ring_cap": 16},
                  contract=generic_contract(d)),
    ]
    ideal = (sum(x.nbytes for x in jax.tree.leaves(target.params))
             + sum(x.nbytes for x in jax.tree.leaves(runner.draft.params))
             + sum(x.nbytes for x in jax.tree.leaves(runner.cache))
             + sum(x.nbytes for x in jax.tree.leaves(runner.d_cache)))
    rules = [
        ratio_rule("spec_megastep_bytes_k_invariant", "spec_megastep_ring16",
                   "spec_megastep_ring4", 1.02),
        absolute_rule("spec_megastep_one_weights_pass", "spec_megastep_ring4",
                      32.0 * ideal),
    ]
    return units, rules


CANARY_MOE_HF = {
    "model_type": "mixtral", "vocab_size": 256, "hidden_size": 128,
    "intermediate_size": 256, "num_hidden_layers": 2,
    "num_attention_heads": 2, "num_key_value_heads": 2,
    "max_position_embeddings": 1024, "rms_norm_eps": 1e-5,
    "rope_theta": 10000.0, "tie_word_embeddings": False,
    "num_local_experts": 4, "num_experts_per_tok": 2,
    "sliding_window": None,
}


@functools.lru_cache(maxsize=None)
def _moe_paged_runner(ep=2, tp=1, b=8, steps=2, tag=""):
    """MoE (Mixtral-arch) paged CB runner at ep > 1 — the expert-dispatch
    collective canary's fleet. Same env-variant ``tag`` keying as
    _paged_runner; 2 layers suffice: the collective-schedule rules compare
    multisets, not pool-dominance byte ratios."""
    from ..config import TpuConfig, load_pretrained_config
    from ..models.mixtral import MixtralForCausalLM
    from ..runtime.continuous_batching import ContinuousBatchingRunner

    del tag
    cfg = TpuConfig(batch_size=b, seq_len=4096, max_context_length=128,
                    dtype="bfloat16", context_encoding_buckets=[128],
                    token_generation_buckets=[512],
                    is_continuous_batching=True, paged_attention_enabled=True,
                    pa_num_blocks=66, pa_block_size=128, tp_degree=tp,
                    ep_degree=ep)
    config = MixtralForCausalLM.get_config_cls()(
        cfg, load_config=load_pretrained_config(CANARY_MOE_HF))
    app = MixtralForCausalLM(None, config)
    app.load_random(seed=0)
    return app, ContinuousBatchingRunner(app, decode_chunk=steps)


def _moe_paged_decode_unit(name, mb, b=8, steps=2, ep=2, overlap=True):
    env = {"TPUINF_EP_OVERLAP": "1" if overlap else "0"}
    app, runner = _moe_paged_runner(ep=ep, b=b, steps=steps,
                                    tag=",".join(f"{k}={v}" for k, v in
                                                 sorted(env.items())))
    _set_paged_decode_example(app, runner, b=b, steps=steps, mb=4)
    return AuditUnit(
        name, runner._decode_step, argmod=_widen_table(7, mb), env=env,
        contract=generic_contract(runner._decode_step, collectives=None))


def _group_moe_ep_collectives() -> Tuple[List[AuditUnit], List[Rule]]:
    """ISSUE-16 expert-dispatch canary: the ep>1 MoE paged decode step's
    collective schedule is pinned and table/batch-shape-invariant; the
    overlap path carries the expert-ring permutes
    (parallel/overlap.expert_ring_moe), the TPUINF_EP_OVERLAP=0 fallback
    keeps the GSPMD combine all-reduce and no permutes."""
    units = [
        _moe_paged_decode_unit("moe_ep_mb4", 4, b=8, overlap=True),
        _moe_paged_decode_unit("moe_ep_mb32", 32, b=8, overlap=True),
        _moe_paged_decode_unit("moe_ep_b4", 4, b=4, overlap=True),
        _moe_paged_decode_unit("moe_ep_fallback", 4, b=8, overlap=False),
    ]
    rules = [
        collective_equal_rule("moe_ep_schedule_table_invariant", "moe_ep_mb32",
                              "moe_ep_mb4", bytes_too=True),
        collective_equal_rule("moe_ep_schedule_batch_invariant", "moe_ep_b4",
                              "moe_ep_mb4", bytes_too=False),
        collective_bound_rule("moe_ep_schedule_pinned", "moe_ep_mb4",
                              max_total=48,
                              require_ops=("collective-permute",)),
        collective_bound_rule("moe_ep_fallback_no_ring", "moe_ep_fallback",
                              max_total=64,
                              forbid_ops=("collective-permute",)),
    ]
    return units, rules


GROUPS: Dict[str, object] = {
    "dense_decode": _group_dense_decode,
    "fused_paged": _group_fused_paged,
    "paged_table_width": _group_paged_table_width,
    "multiquery": _group_multiquery,
    "mixed_chunk": _group_mixed_chunk,
    "megastep": _group_megastep,
    "amla": _group_amla,
    "lenpar": _group_lenpar,
    "spec_megastep": _group_spec_megastep,
    "tp_collectives": _group_tp_collectives,
    "moe_ep_collectives": _group_moe_ep_collectives,
}


def canary_group(name: str) -> Tuple[List[AuditUnit], List[Rule]]:
    return GROUPS[name]()


def clear_caches() -> None:
    """Drop the cached canary apps/runners (bf16 params + 66x128 block pools
    per variant — hundreds of MB across all groups). The caches exist so
    groups audited in one pass share builders; call this once the reports are
    in hand so a long pytest session / the audit driver doesn't retain the
    fleets until process exit."""
    _dense_app.cache_clear()
    _paged_runner.cache_clear()
    _spec_canary_runner.cache_clear()
    _moe_paged_runner.cache_clear()


def build_canary_units(names=None) -> Tuple[List[AuditUnit], List[Rule]]:
    units: List[AuditUnit] = []
    rules: List[Rule] = []
    for name in (names if names is not None else GROUPS):
        u, r = canary_group(name)
        units += u
        rules += r
    return units, rules
