"""Dispatch registry: ``audited_jit`` — the registered replacement for raw
``jax.jit`` at serving dispatch sites.

Why a wrapper instead of a convention: the two invariants that rot silently
are (a) ``donate_argnums`` drifting out of sync with the cache parameters as
signatures grow (donation that fails to alias doubles KV HBM with no error)
and (b) new dispatch sites never being audited at all. ``audited_jit`` kills
both by construction — donation is DERIVED from the declared cache parameter
NAMES, and registration is a side effect of building the step, so the auditor
(and the ``raw-jit`` lint rule) can see every site.

The wrapper captures the first real call's argument shapes/dtypes as
``jax.ShapeDtypeStruct`` specs (one ``is None`` check per call afterwards —
nothing on the hot path), which is exactly what the auditor needs to re-lower
the dispatch offline. Fixtures may also inject specs via ``set_example``.
"""

from __future__ import annotations

import inspect
import weakref
from typing import Any, Dict, Optional, Tuple

import jax

from .contracts import DispatchContract

__all__ = ["audited_jit", "register_external", "step_loop_body",
           "live_dispatches", "find", "clear"]

# weakrefs: dispatches die with their runner; the registry must not keep every
# runner a test session ever built alive
_REGISTRY: list = []


def _prune() -> None:
    _REGISTRY[:] = [r for r in _REGISTRY if r() is not None]


def _register(dispatch: "AuditedDispatch") -> None:
    if len(_REGISTRY) % 64 == 63:
        _prune()
    _REGISTRY.append(weakref.ref(dispatch))


def live_dispatches() -> Dict[str, "AuditedDispatch"]:
    """kind -> newest live dispatch of that kind."""
    out: Dict[str, AuditedDispatch] = {}
    for ref in _REGISTRY:          # registration order: later wins
        d = ref()
        if d is not None:
            out[d.contract.kind] = d
    return out


def find(kind: str) -> Optional["AuditedDispatch"]:
    return live_dispatches().get(kind)


def clear() -> None:
    _REGISTRY.clear()


def _spec_of(x):
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
    return x


class AuditedDispatch:
    """A jitted serving dispatch + its contract + a captured example."""

    def __init__(self, fn, contract: DispatchContract, jitted,
                 static_argnames: Tuple[str, ...] = ()) -> None:
        self.contract = contract
        self.fn = fn
        self._jit = jitted
        self.static_argnames = tuple(static_argnames)
        self.example: Optional[Tuple[tuple, dict]] = None
        self._example_cost: Optional[Dict[str, float]] = None
        _register(self)

    # ---- call path -------------------------------------------------------
    def __call__(self, *args, **kwargs):
        if self.example is None:
            self.set_example(*args, **kwargs)
        return self._jit(*args, **kwargs)

    def set_example(self, *args, **kwargs) -> None:
        """Record abstract arg specs for offline lowering (arrays become
        ShapeDtypeStructs; static python values pass through verbatim)."""
        self.example = (jax.tree_util.tree_map(_spec_of, args),
                        {k: jax.tree_util.tree_map(_spec_of, v)
                         for k, v in kwargs.items()})
        self._example_cost = None      # costs follow the example they came from

    # ---- audit surface ---------------------------------------------------
    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)

    def lower_example(self, **overrides):
        """Lower from the captured example; ``overrides`` replace keyword args
        (e.g. a different static ``num_steps``) before lowering."""
        if self.example is None:
            raise RuntimeError(
                f"dispatch {self.contract.kind!r} has no captured example — "
                f"run it once (or set_example) before auditing")
        args, kwargs = self.example
        kwargs = dict(kwargs, **overrides)
        return self._jit.lower(*args, **kwargs)

    def example_cost(self) -> Dict[str, float]:
        """Compiled-cost summary of the captured example — the roofline
        model's input (analysis/perf_model.py): HBM bytes accessed, FLOPs,
        collective (ICI) output bytes, and the captured ``steps_arg`` value
        the per-step normalization divides by.

        Cached after the first call: the AOT ``lower().compile()`` runs once
        per dispatch (and hits jax's persistent compile cache when enabled).
        This is an OFFLINE analysis hook — profiled-window attribution,
        bench phases and scripts call it; the serving hot path never does.
        Raises when no example was captured (run the dispatch once first)."""
        if self._example_cost is None:
            compiled = self.lower_example().compile()
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0]
            from ..parallel import overlap as overlap_lib

            stats = overlap_lib.collective_stats(compiled.as_text())
            steps = 1
            if self.contract.steps_arg is not None:
                v = self.static_value(self.contract.steps_arg)
                steps = int(v) if v is not None else 1
            # strict "bytes accessed" lookup, same rationale as the auditor:
            # a missing key must raise, never read as a silent 0.0
            self._example_cost = {
                "bytes_accessed": float(cost["bytes accessed"]),
                "flops": float(cost.get("flops", 0.0)),
                "collective_bytes": float(stats["bytes"]),
                "steps": max(1, steps),
            }
        return dict(self._example_cost)

    def static_value(self, name: str, default=None):
        """Captured value of a (static) argument, by name."""
        if self.example is None:
            return default
        args, kwargs = self.example
        if name in kwargs:
            return kwargs[name]
        try:
            bound = inspect.signature(self.fn).bind_partial(*args, **kwargs)
            return bound.arguments.get(name, default)
        except TypeError:
            return default

    def __getattr__(self, name: str) -> Any:
        # anything else (trace, eval_shape, ...) behaves like the raw jit
        return getattr(self._jit, name)


def _param_indices(fn, names: Tuple[str, ...], kind: str) -> Tuple[int, ...]:
    params = list(inspect.signature(fn).parameters)
    out = []
    for n in names:
        if n not in params:
            raise ValueError(f"audited_jit({kind!r}): declared arg {n!r} not "
                             f"in {fn.__name__} signature {params}")
        out.append(params.index(n))
    return tuple(out)


def audited_jit(fn, *, kind: str, cache_args: Tuple[str, ...] = (),
                carry_args: Tuple[str, ...] = (),
                donate_extra: Tuple[str, ...] = (),
                static_argnames: Tuple[str, ...] = (),
                steps_arg: Optional[str] = None,
                waivers: Optional[Dict[str, str]] = None,
                **contract_kw) -> AuditedDispatch:
    """``jax.jit`` + contract registration for a serving dispatch.

    ``cache_args``/``carry_args``/``donate_extra`` are parameter NAMES;
    donation indices are derived from the signature, so they cannot be
    mis-indexed. ``carry_args`` are small device-resident carry buffers (the
    in-graph telemetry block): donated + aliasing-verified like caches, but
    excluded from the cache-sized upcast threshold. Remaining ``contract_kw``
    forward to :class:`DispatchContract` (host_sync_free, fp32_accum,
    collectives, hbm_bytes, ...).
    """
    contract = DispatchContract(
        kind=kind, cache_args=tuple(cache_args),
        carry_args=tuple(carry_args),
        donate_extra=tuple(donate_extra), steps_arg=steps_arg,
        waivers=dict(waivers or {}), **contract_kw)
    donate = (_param_indices(fn, contract.cache_args, kind)
              + _param_indices(fn, contract.carry_args, kind)
              + _param_indices(fn, contract.donate_extra, kind))
    # keep_unused=True: jit drops unused args from the lowered module by
    # default, which would break the auditor's example-leaf -> lowered-arg
    # index mapping. Serving dispatches use every arg, so this is free.
    jit_kw: Dict[str, Any] = {"keep_unused": True}
    if donate:
        jit_kw["donate_argnums"] = donate
    if static_argnames:
        jit_kw["static_argnames"] = tuple(static_argnames)
    return AuditedDispatch(fn, contract, jax.jit(fn, **jit_kw),
                           static_argnames=tuple(static_argnames))


def register_external(jitted, fn, contract: DispatchContract,
                      static_argnames: Tuple[str, ...] = ()
                      ) -> AuditedDispatch:
    """Wrap an ALREADY-jitted callable (donation as the caller made it) —
    for fixtures that deliberately model a legacy/broken site, and for
    family-owned jits that cannot flow through ``audited_jit``."""
    return AuditedDispatch(fn, contract, jitted,
                           static_argnames=tuple(static_argnames))


def step_loop_body(fn):
    """No-op marker for host-side serving step-loop bodies.

    The lint pass (analysis/lint.py) resolves this decorator STATICALLY and
    holds the marked function to the step-loop discipline: no ``.item()`` /
    ``block_until_ready()`` host syncs, and no per-row ``asarray`` conversion
    loops (hoist them — PR 2 measured the per-window conversions at
    milliseconds per dispatch).
    """
    fn.__step_loop_body__ = True
    return fn
